"""The continuous telemetry pipeline (round 17): TSDB storage/query
semantics, the collector over in-process registries AND live fleet
replicas, SLO burn-rate alerting, the breach-triggered flight
recorder, the kill switch, and the witness invocations (race + lock
sanitizers) over the whole pipeline."""

import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from kubernetes_tpu.client.record import FakeRecorder
from kubernetes_tpu.metrics.metrics import registry
from kubernetes_tpu.telemetry import expo
from kubernetes_tpu.telemetry import scrape as tscrape
from kubernetes_tpu.telemetry.flight import FlightRecorder
from kubernetes_tpu.telemetry.slo import (
    BurnRateRule,
    Engine,
    ThresholdRule,
)
from kubernetes_tpu.telemetry.tsdb import (
    TSDB,
    QueryError,
    Ring,
    eval_query,
    sum_by,
)

_SANITIZED = bool(os.environ.get("KUBERNETES_TPU_RACE_SANITIZER")) or \
    bool(os.environ.get("KUBERNETES_TPU_LOCK_SANITIZER"))


@pytest.fixture
def no_default_collector():
    """Isolate the process-default collector slot: tests that register
    one must not leak it into (or inherit it from) other tests."""
    prev = tscrape.default()
    tscrape.set_default(None)
    yield
    tscrape.set_default(prev)


# -- the ring -----------------------------------------------------------------


def test_ring_round_trip_and_retention():
    r = Ring(interval=1.0, capacity=10)
    for i in range(25):
        r.append(100.0 + i, float(i * 3))
    assert len(r) == 10
    samples = r.samples()
    # newest sample exact, timestamps on the interval grid
    assert samples[-1] == (124.0, 72.0)
    assert samples[0] == (115.0, 45.0)
    assert [t for t, _ in samples] == [115.0 + i for i in range(10)]
    assert [v for _, v in samples] == [45.0 + 3 * i for i in range(10)]


def test_ring_counter_deltas_are_exact_ints():
    # large counters with small steps: int delta encoding must not
    # accumulate float error over eviction folding
    r = Ring(interval=1.0, capacity=4)
    base = 10**15
    for i in range(50):
        r.append(1.0 + i, float(base + i))
    assert [v for _, v in r.samples()] == [
        float(base + i) for i in range(46, 50)]


def test_ring_since_trims():
    r = Ring(interval=1.0, capacity=16)
    for i in range(8):
        r.append(100.0 + i, float(i))
    assert [t for t, _ in r.samples(since=105.0)] == [105.0, 106.0,
                                                      107.0]


# -- the store ----------------------------------------------------------------


def _fill(db, name, labels, values, t0=1000.0, step=1.0):
    for i, v in enumerate(values):
        db.append(name, labels, v, t=t0 + i * step)


def test_tsdb_range_rate_and_label_matchers():
    db = TSDB(interval=1.0, retention_samples=64)
    _fill(db, "reqs_total", {"verb": "GET"}, [0, 2, 4, 6, 8])
    _fill(db, "reqs_total", {"verb": "PUT"}, [0, 1, 2, 3, 4])
    now = 1004.0
    assert db.series_count() == 2
    assert db.metric_names() == ["reqs_total"]
    got = db.range("reqs_total", {"verb": "GET"}, window=10.0, now=now)
    assert len(got) == 1 and got[0][0] == {"verb": "GET"}
    rates = dict((lb["verb"], v) for lb, v in
                 db.rate("reqs_total", window=10.0, now=now))
    assert rates == {"GET": 2.0, "PUT": 1.0}


def test_tsdb_rate_survives_counter_reset():
    db = TSDB(interval=1.0)
    # process restart: 0,5,10, reset to 0, 5 -> increases 5+5+5 over 4s
    _fill(db, "c_total", {}, [0, 5, 10, 0, 5])
    [(_, rate)] = db.rate("c_total", window=10.0, now=1004.0)
    assert rate == pytest.approx(15.0 / 4.0)


def test_tsdb_quantile_interpolates():
    db = TSDB(interval=1.0)
    # 10 obs <= 0.1s, 10 more in (0.1, 1.0]
    _fill(db, "lat_seconds_bucket", {"le": "0.1"}, [0, 10])
    _fill(db, "lat_seconds_bucket", {"le": "1.0"}, [0, 20])
    _fill(db, "lat_seconds_bucket", {"le": "+Inf"}, [0, 20])
    now = 1001.0
    assert db.quantile(0.5, "lat_seconds", window=10.0, now=now) == \
        pytest.approx(0.1)
    assert db.quantile(0.75, "lat_seconds", window=10.0, now=now) == \
        pytest.approx(0.55)
    # bare name and explicit _bucket name agree
    assert db.quantile(0.75, "lat_seconds_bucket", window=10.0,
                       now=now) == pytest.approx(0.55)
    assert db.quantile(0.5, "no_such_seconds", window=10.0,
                       now=now) is None


def test_sum_by_aggregation():
    rows = [({"verb": "GET", "code": "200"}, 3.0),
            ({"verb": "GET", "code": "500"}, 1.0),
            ({"verb": "PUT", "code": "200"}, 2.0)]
    assert sum_by(rows, ()) == [({}, 6.0)]
    assert sum_by(rows, ("verb",)) == [
        ({"verb": "GET"}, 4.0), ({"verb": "PUT"}, 2.0)]


def test_cardinality_cap_drops_and_counts(no_default_collector):
    db = TSDB(interval=1.0, max_series_per_metric=64)
    db.set_metric_bound("capped_total", 2)
    from kubernetes_tpu.metrics import telemetry_series_dropped_total

    before = telemetry_series_dropped_total.get(metric="capped_total")
    stored = [db.append("capped_total", {"flow": f"f{i}"}, 1.0,
                        t=1000.0) for i in range(5)]
    assert stored == [True, True, False, False, False]
    assert db.series_count() == 2
    assert db.dropped() == {"capped_total": 3}
    assert telemetry_series_dropped_total.get(
        metric="capped_total") == before + 3
    # existing series keep appending under the cap
    assert db.append("capped_total", {"flow": "f0"}, 2.0, t=1001.0)


# -- the query language -------------------------------------------------------


def _query_db():
    db = TSDB(interval=1.0)
    _fill(db, "reqs_total", {"verb": "GET", "job": "a"}, [0, 2, 4])
    _fill(db, "reqs_total", {"verb": "GET", "job": "b"}, [0, 1, 2])
    _fill(db, "lat_seconds_bucket", {"le": "0.1"}, [0, 0, 10])
    _fill(db, "lat_seconds_bucket", {"le": "+Inf"}, [0, 0, 10])
    return db, 1002.0


def test_eval_query_matrix_vector_scalar():
    db, now = _query_db()
    m = eval_query(db, 'reqs_total{job="a"}[10s]', now=now)
    assert m["kind"] == "matrix"
    assert m["result"][0]["samples"][-1] == [1002.0, 4.0]

    v = eval_query(db, "rate(reqs_total[10s])", now=now)
    assert v["kind"] == "vector" and len(v["result"]) == 2

    # job a rate 2.0/s + job b rate 1.0/s
    s = eval_query(db, "sum(rate(reqs_total[10s]))", now=now)
    assert s["kind"] == "vector"
    assert s["result"] == [{"labels": {}, "value": pytest.approx(3.0)}]

    by = eval_query(db, "sum_by(verb, rate(reqs_total[10s]))", now=now)
    assert by["result"] == [
        {"labels": {"verb": "GET"}, "value": pytest.approx(3.0)}]

    # all 10 obs landed in (0, 0.1]; the median interpolates halfway
    q = eval_query(db, "quantile(0.5, lat_seconds[10s])", now=now)
    assert q["kind"] == "scalar"
    assert q["result"] == pytest.approx(0.05)


def test_eval_query_rejects_junk():
    db, now = _query_db()
    for bad in ("", "}{", "rate(", "sum(reqs_total[10s])",
                "quantile(zz, lat_seconds[10s])",
                'reqs_total{job}'):
        with pytest.raises(QueryError):
            eval_query(db, bad, now=now)


# -- the shared exposition parser (satellite: procs.py dedupe) ----------------


def test_procs_reexports_the_shared_parser():
    from kubernetes_tpu.harness import procs

    assert procs.series_sum is expo.series_sum
    assert procs.scrape_metrics is expo.scrape_metrics
    assert procs.scrape_raw is expo.scrape_raw
    assert procs.healthz is expo.healthz


def test_parse_text_round_trips_the_registry():
    from kubernetes_tpu.metrics import apiserver_request_latency

    apiserver_request_latency.labels("GET").observe(123.0)
    rows = expo.parse_text(registry.render())
    names = {name for name, _, _ in rows}
    # counters, gauges, and full histogram families all survive
    assert "apiserver_request_latencies_microseconds_bucket" in names
    assert "apiserver_request_latencies_microseconds_sum" in names
    assert "apiserver_request_latencies_microseconds_count" in names
    for name, labels, value in rows:
        assert isinstance(labels, dict)
        float(value)


# -- the collector ------------------------------------------------------------


def test_collector_scrapes_registry_with_job_label():
    db = TSDB(interval=0.1)
    coll = tscrape.Collector(db, interval=0.1)
    coll.add_registry("driver")
    registry.render()  # ensure lazily-registered metrics exist
    stored = coll.tick(now=2000.0)
    assert stored > 0
    assert coll.ticks() == 1
    assert coll.jobs() == ["driver"]
    got = db.range("apiserver_requests_total", {"job": "driver"})
    # every scraped series carries the stamped job label
    for labels, _samples in got:
        assert labels["job"] == "driver"


def test_collector_installs_declared_bounds():
    db = TSDB(interval=1.0)
    tscrape.Collector(db)
    # the lint-enforced label_bound declarations became ingest caps
    # (x8 jobs headroom; histograms fan out per bucket)
    assert db._bounds["workqueue_depth"] == 32 * 8
    assert db._bounds[
        "apiserver_request_latencies_microseconds_sum"] == 16 * 8
    assert db._bounds[
        "apiserver_request_latencies_microseconds_bucket"] >= 16 * 8


def test_collector_scrape_error_counts_not_raises(no_default_collector):
    from kubernetes_tpu.metrics import telemetry_scrape_errors_total

    coll = tscrape.Collector(TSDB(interval=0.1), interval=0.1)
    coll.add_url("ghost", "http://127.0.0.1:1/")  # nothing listens
    before = telemetry_scrape_errors_total.get(job="ghost")
    coll.tick()
    assert telemetry_scrape_errors_total.get(job="ghost") == before + 1


# -- SLO engine ---------------------------------------------------------------


def test_threshold_rule_fires_resolves_and_emits():
    db = TSDB(interval=1.0)
    level = {"v": 10.0}
    rec = FakeRecorder()
    fired = []
    eng = Engine(
        db,
        rules=[ThresholdRule("probe-alert",
                             lambda _db, _now: level["v"], 5.0,
                             description="probe threshold")],
        recorder=rec,
        on_fire=fired.append,
    )
    states = eng.evaluate(now=1000.0)
    assert states[0]["firing"] and states[0]["since"] == 1000.0
    assert [a["alert"] for a in eng.active()] == ["probe-alert"]
    assert len(fired) == 1 and fired[0]["alert"] == "probe-alert"
    assert any("TelemetrySLOBreach" in e for e in rec.events)

    from kubernetes_tpu.metrics import telemetry_alerts_firing

    assert telemetry_alerts_firing.values()["probe-alert"] == 1.0

    # refire while already firing: no duplicate event, no second hook
    eng.evaluate(now=1001.0)
    assert len(fired) == 1 and len(rec.events) == 1

    level["v"] = 1.0
    eng.evaluate(now=1002.0)
    assert eng.active() == []
    assert telemetry_alerts_firing.values()["probe-alert"] == 0.0
    timeline = eng.history()
    assert [e["state"] for e in timeline] == ["firing", "resolved"]


def _burn_db(bad_per_tick, ticks=130):
    """total grows 10/tick, bad grows bad_per_tick/tick."""
    db = TSDB(interval=1.0, retention_samples=200)
    for i in range(ticks):
        t = 1000.0 + i
        db.append("bad_total", {}, float(i * bad_per_tick), t=t)
        db.append("all_total", {}, float(i * 10), t=t)
    return db, 1000.0 + ticks - 1


def test_burn_rate_fires_only_on_both_windows():
    rule = BurnRateRule("burn", bad="bad_total", total="all_total",
                        budget=0.01, short_window=30.0,
                        long_window=120.0)
    # 50% error ratio -> burn 50x budget: over 14.4 AND 6 -> fires
    db, now = _burn_db(bad_per_tick=5)
    firing, value = rule.evaluate(db, now)
    assert firing and value == pytest.approx(50.0, rel=0.05)

    # 0.05% ratio -> burn 0.5x: under both factors -> quiet
    db2 = TSDB(interval=1.0, retention_samples=200)
    for i in range(130):
        t = 1000.0 + i
        db2.append("bad_total", {}, float(i) * 0.005, t=t)
        db2.append("all_total", {}, float(i * 10), t=t)
    firing, _ = rule.evaluate(db2, 1129.0)
    assert not firing

    # no data at all -> not firing, never raises
    firing, _ = rule.evaluate(TSDB(), 1000.0)
    assert not firing


# -- flight recorder ----------------------------------------------------------

BUNDLE_FILES = {"meta.json", "series.jsonl", "alerts.json",
                "traces.json", "audit.json", "procs.json"}


def test_flight_bundle_contents_and_debounce(tmp_path):
    db = TSDB(interval=1.0)
    # fill at real wall times: _write_series windows against now
    _fill(db, "reqs_total", {"verb": "GET"}, [0, 1, 2],
          t0=time.time() - 2.0)
    eng = Engine(db, rules=[])
    fl = FlightRecorder(db, str(tmp_path), engine=eng,
                        min_interval=60.0)
    fl.add_state_source("probe", lambda: {"ok": True})
    fl.add_state_source("broken", lambda: 1 / 0)

    bundle = fl.record("first breach!")
    assert bundle is not None
    assert set(os.listdir(bundle)) == BUNDLE_FILES
    meta = json.load(open(os.path.join(bundle, "meta.json")))
    assert meta["reason"] == "first breach!"
    lines = [json.loads(ln) for ln in
             open(os.path.join(bundle, "series.jsonl"))]
    assert any(ln["name"] == "reqs_total" and
               ln["samples"][-1][1] == 2.0 for ln in lines)
    procs = json.load(open(os.path.join(bundle, "procs.json")))
    assert procs["probe"] == {"ok": True}
    assert "error" in procs["broken"]

    # debounced within min_interval; force bypasses
    assert fl.record("storm") is None
    assert fl.record("gate breach", force=True) is not None
    idx = fl.index()
    assert idx["kind"] == "FlightRecorderIndex"
    assert len(idx["bundles"]) == 2
    assert idx["bundles"][0]["reason"] == "first breach!"


def test_flight_prunes_oldest_past_max_bundles(tmp_path):
    fl = FlightRecorder(TSDB(), str(tmp_path), max_bundles=2,
                        min_interval=0.0)
    dirs = [fl.record(f"r{i}", force=True) for i in range(4)]
    kept = [b["dir"] for b in fl.index()["bundles"]]
    assert kept == dirs[2:]
    assert not os.path.exists(dirs[0])
    assert not os.path.exists(dirs[1])


def test_alert_fire_triggers_flight_dump(tmp_path):
    db = TSDB(interval=1.0)
    eng = Engine(db, rules=[ThresholdRule(
        "hot", lambda _db, _now: 9.0, 1.0)])
    fl = FlightRecorder(db, str(tmp_path), engine=eng)
    eng.on_fire = lambda alert: fl.record("alert-" + alert["alert"])
    eng.evaluate(now=1000.0)
    [bundle] = [b["dir"] for b in fl.index()["bundles"]]
    assert "alert-hot" in bundle
    meta = json.load(open(os.path.join(bundle, "meta.json")))
    assert [a["alert"] for a in meta["firing"]] == ["hot"]


# -- process-default plumbing + HTTP endpoints --------------------------------


def test_kill_switch_disables_attach(monkeypatch, no_default_collector):
    from kubernetes_tpu import telemetry

    monkeypatch.setenv("KUBERNETES_TPU_TELEMETRY", "0")
    assert not telemetry.enabled()
    assert tscrape.ensure_default("probe") is None
    assert tscrape.default() is None
    code, body = telemetry.handle_query({})
    assert code == 503 and "message" in body
    assert telemetry.handle_alerts({})[0] == 503
    assert telemetry.handle_flight({})[0] == 503

    monkeypatch.setenv("KUBERNETES_TPU_TELEMETRY", "1")
    assert telemetry.enabled()


def test_ensure_default_is_idempotent_and_owned(tmp_path,
                                                no_default_collector):
    c1 = tscrape.ensure_default("probe", interval=5.0,
                                flight_dir=str(tmp_path))
    try:
        assert c1 is not None and tscrape.default() is c1
        assert c1.engine is not None and c1.flight is not None
        # second attach joins the first
        assert tscrape.ensure_default("other") is c1
        # a non-owner releasing someone else's collector is a no-op
        tscrape.release_default(None)
        assert tscrape.default() is c1
    finally:
        tscrape.release_default(c1)
    assert tscrape.default() is None


def test_component_mux_serves_telemetry(tmp_path, no_default_collector):
    from kubernetes_tpu.trace.httpd import start_component_server

    db = TSDB(interval=0.2)
    eng = Engine(db, rules=[])
    fl = FlightRecorder(db, str(tmp_path), engine=eng)
    coll = tscrape.Collector(db, interval=0.2, engine=eng, flight=fl)
    coll.add_registry("driver")
    coll.tick(now=3000.0)
    coll.tick(now=3001.0)
    tscrape.set_default(coll)
    server, port = start_component_server(port=0, name="probe")
    base = f"http://127.0.0.1:{port}"

    def get(path):
        with urllib.request.urlopen(base + path, timeout=5) as r:
            return r.status, json.loads(r.read())

    try:
        code, idx = get("/debug/telemetry/query")
        assert code == 200 and idx["kind"] == "TelemetryIndex"
        assert idx["ticks"] == 2 and idx["series"] > 0

        code, res = get("/debug/telemetry/query?q="
                        + urllib.parse.quote(
                            "sum(rate(apiserver_requests_total[30s]))"))
        assert code == 200
        assert res["kind"] == "TelemetryQueryResult"
        assert res["resultType"] == "vector"

        with pytest.raises(urllib.error.HTTPError) as ei:
            get("/debug/telemetry/query?q=%7Bjunk")
        assert ei.value.code == 400

        code, alerts = get("/debug/telemetry/alerts")
        assert code == 200 and alerts["kind"] == "TelemetryAlertList"

        code, fidx = get("/debug/flightrecorder")
        assert code == 200 and fidx["kind"] == "FlightRecorderIndex"

        code, dump = get("/debug/flightrecorder?dump=operator")
        assert code == 200 and dump["bundle"]
        assert os.path.isdir(dump["bundle"])
    finally:
        server.shutdown()


# -- fleet scraping (live replica processes) ----------------------------------


def test_collector_scrapes_live_fleet(tmp_path, no_default_collector):
    from kubernetes_tpu.harness.procs import ApiserverFleet

    fleet = ApiserverFleet(2, str(tmp_path / "procs"),
                           election_timeout=0.3).start()
    try:
        db = TSDB(interval=0.2)
        coll = tscrape.Collector(db, interval=0.2)
        coll.attach_fleet(fleet)
        assert coll.jobs() == [r.node_id for r in fleet.replicas]
        deadline = time.time() + 10.0
        stored = 0
        while time.time() < deadline:
            stored = coll.tick()
            if stored > 0 and len(coll.proc_state()) == 2:
                state = coll.proc_state()
                if all("healthz" in s for s in state.values()):
                    break
            time.sleep(0.2)
        assert stored > 0
        jobs_seen = set()
        for labels, _ in db.range("apiserver_requests_total"):
            jobs_seen.add(labels["job"])
        assert jobs_seen  # at least one replica answered /metrics
        assert jobs_seen <= {r.node_id for r in fleet.replicas}
        state = coll.proc_state()
        assert set(state) == {r.node_id for r in fleet.replicas}
        assert any("healthz" in s for s in state.values())
    finally:
        fleet.stop()


# -- soak integration: gate breach leaves a bundle ----------------------------


@pytest.mark.skipif(
    _SANITIZED,
    reason="perf-gated soak smokes are not valid under armed sanitizers",
)
def test_soak_gate_breach_writes_flight_bundle(tmp_path):
    from kubernetes_tpu.harness.soak import SoakConfig, run_wire_soak

    cfg = SoakConfig(
        seconds=8, num_nodes=16, rate=5.0,
        slo=1e-4,  # impossibly tight: the p99 gate must breach
        params={"churn_floor": 64, "flight_dir": str(tmp_path)},
    )
    rec = run_wire_soak(cfg)
    assert not rec["ok"]
    tel = rec["telemetry"]
    assert tel["ticks"] >= 1 and tel["series"] > 0
    bundle = rec["flight_bundle"]
    assert bundle and os.path.isdir(bundle)
    assert BUNDLE_FILES <= set(os.listdir(bundle))
    meta = json.load(open(os.path.join(bundle, "meta.json")))
    assert meta["reason"] == "soak-gate-breach"
    assert meta["extra"]["failed"]
    # the bundle's series really cover the run (queryable post-mortem)
    lines = [json.loads(ln) for ln in
             open(os.path.join(bundle, "series.jsonl"))]
    assert any(ln["labels"].get("job") == "driver" for ln in lines)


# -- witness invocations ------------------------------------------------------


def test_telemetry_race_witness(tmp_path):
    from kubernetes_tpu.analysis import races

    with races.instrumented(reset=True):
        db = TSDB(interval=0.05)
        eng = Engine(db, rules=[])
        fl = FlightRecorder(db, str(tmp_path), engine=eng,
                            min_interval=0.0)
        coll = tscrape.Collector(db, interval=0.05, engine=eng,
                                 flight=fl)
        coll.add_registry("driver")
        fl.add_state_source("fleet", coll.proc_state)
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                coll.tick()
                eng.evaluate()
                db.range("apiserver_requests_total", window=60.0)
                db.rate("apiserver_requests_total", window=60.0)

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.6)
        fl.record("probe", force=True)
        fl.index()
        stop.set()
        for t in threads:
            t.join()
        bad = [f for f in races.findings() if not f.suppressed]
        assert not bad, bad


def test_telemetry_lock_order_witness(tmp_path):
    from kubernetes_tpu.analysis import locks

    with locks.instrumented(reset=True):
        db = TSDB(interval=0.05)
        eng = Engine(db)
        fl = FlightRecorder(db, str(tmp_path), engine=eng,
                            min_interval=0.0)
        coll = tscrape.Collector(db, interval=0.05, engine=eng,
                                 flight=fl)
        coll.add_registry("driver")
        coll.tick()
        eng.evaluate()
        eval_query(db, "sum(rate(apiserver_requests_total[30s]))")
        fl.record("probe", force=True)
        locks.assert_no_cycles("(telemetry)")
