"""Optimizing profile & defragmentation (round 15).

Covers the ISSUE-15 contract:

* Solver safety: the auction and beam programs never propose a slot
  outside its fit mask or past per-node multi-resource capacity
  (randomized fuzz against a numpy re-check).
* Profile safety: every placement the optimizing profile commits
  passes the serial oracle's predicates (randomized fuzz, >=8 seeds);
  ineligible features (inter-pod terms, volumes, ports) route to the
  serial-equivalent scan and never crash the profile.
* Gang atomicity under the optimizer: an unfittable gang never
  partially binds; a fittable one binds whole.
* O(1) dispatches per wave regardless of template count.
* Strict improvement: the --pack smoke gates (schedulable count AND
  packed utilization vs greedy) pass at tier-1 size; the full ~1k-node
  forms are slow-marked.
* Defragmentation: proposal quality, the never-reduce-schedulability
  invariant (fuzz), the equal-or-higher-priority protection, busy
  backoff, and end-to-end evict+rebind through the batch door.
* PodGroup status reconciliation while the scheduler is down.
* Gang-level exponential backoff with the starvation cap.
"""

import os
import random

import numpy as np
import pytest

from kubernetes_tpu.api.types import (
    POD_GROUP_LABEL,
    Container,
    Node,
    NodeCondition,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodGroup,
    PodGroupSpec,
    PodSpec,
    shallow_copy,
)
from kubernetes_tpu.models.batch import SchedulerConfig as DevCfg
from kubernetes_tpu.oracle import ClusterState, GenericScheduler
from kubernetes_tpu.scheduler.optimizer import (
    PROFILE_GREEDY,
    PROFILE_OPTIMIZING,
    active_profile,
)
from kubernetes_tpu.scheduler.optimizer.controller import defrag as D
from kubernetes_tpu.scheduler.optimizer.ops.assign import (
    AssignSolver,
    auction_rounds,
)
from kubernetes_tpu.scheduler.tpu_algorithm import TPUScheduleAlgorithm

from tests.test_conformance import (
    ORACLE_PREDICATES,
    ORACLE_PRIORITIES,
    random_scenario,
)

_SANITIZED = bool(os.environ.get("KUBERNETES_TPU_RACE_SANITIZER"))


def node(name, cpu="4", mem="32Gi", pods="110", labels=None):
    return Node(
        metadata=ObjectMeta(name=name, labels=labels or {}),
        status=NodeStatus(
            allocatable={"cpu": cpu, "memory": mem, "pods": pods},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )


def pod(name, cpu, mem="1Gi", labels=None, node_name=None):
    p = Pod(
        metadata=ObjectMeta(name=name, labels=labels or {"app": "x"}),
        spec=PodSpec(containers=[Container(
            requests={"cpu": cpu, "memory": mem})]),
    )
    if node_name:
        p.spec.node_name = node_name
    return p


LRBA = DevCfg(
    predicates=("PodFitsResources",),
    priorities=(("LeastRequestedPriority", 1),
                ("BalancedResourceAllocation", 1)),
)


def interleaved_pack(n):
    """The stranding workload: complementary 1-CPU / 3-CPU templates
    arriving interleaved over n 4-CPU nodes (demand == capacity)."""
    pods = []
    for i in range(n):
        pods.append(pod(f"small-{i:04d}", "1000m"))
        pods.append(pod(f"big-{i:04d}", "3000m", "3Gi"))
    return pods


# -- profile flag -------------------------------------------------------------


class TestProfileFlag:
    def test_default_and_override(self, monkeypatch):
        monkeypatch.delenv("KUBERNETES_TPU_PROFILE", raising=False)
        assert active_profile() == PROFILE_GREEDY
        monkeypatch.setenv("KUBERNETES_TPU_PROFILE", "optimizing")
        assert active_profile() == PROFILE_OPTIMIZING
        assert active_profile("greedy") == PROFILE_GREEDY

    def test_unknown_falls_back_to_greedy(self, monkeypatch):
        monkeypatch.setenv("KUBERNETES_TPU_PROFILE", "simulated-annealing")
        assert active_profile() == PROFILE_GREEDY


# -- solver fuzz --------------------------------------------------------------


def _check_solution(owner, fit, req, check, cap):
    """numpy re-check: every assignment inside the fit mask, cumulative
    per-node usage inside capacity for every checked resource row."""
    used = np.zeros_like(cap)
    for s, n in enumerate(owner):
        n = int(n)
        if n < 0:
            continue
        assert fit[s, n], f"slot {s} assigned outside its fit mask"
        lhs = used[n] + req[s]
        ok = (lhs <= cap[n]) | ~check[s]
        assert ok.all(), f"slot {s} overflows node {n}"
        used[n] += req[s]


@pytest.mark.parametrize("seed", range(6))
def test_solver_fuzz_respects_fit_and_capacity(seed):
    rng = np.random.RandomState(seed)
    P = int(rng.choice([8, 24, 48]))
    N = int(rng.choice([8, 16]))
    fit = rng.rand(P, N) > 0.2
    score = rng.randint(0, 40, size=(P, N)).astype(np.int64)
    req = np.zeros((P, 4), np.int64)
    req[:, 0] = rng.choice([500, 1000, 2000, 3000], size=P)
    req[:, 1] = rng.choice([1, 2, 3], size=P) * (1 << 30)
    req[:, 3] = 1
    commit = req.copy()
    check = np.ones((P, 4), bool)
    zero = rng.rand(P) < 0.1
    check[zero, :3] = False
    # the encoder's invariant: zero_req means the request vector IS
    # zero (the flag only preserves the predicate's skip-order quirk)
    req[zero, :3] = 0
    commit = req.copy()
    cap = np.zeros((N, 4), np.int64)
    cap[:, 0] = rng.choice([2000, 4000, 8000], size=N)
    cap[:, 1] = rng.choice([4, 8, 32], size=N) * (1 << 30)
    cap[:, 3] = rng.choice([2, 5, 110], size=N)
    prio = np.zeros(P, np.int32)
    order = np.arange(P, dtype=np.int32)
    solver = AssignSolver()
    owner, name = solver.solve(fit, score, req, commit, check, cap,
                               prio, order, P)
    assert name in ("auction", "beam")
    _check_solution(owner, fit, req, check, cap)


def test_beam_packs_small_wave_optimally():
    # 2 nodes of 4 CPU; two 3-CPU and two 1-CPU slots: only the
    # big+small pairing seats all four
    fit = np.ones((4, 2), bool)
    req = np.zeros((4, 4), np.int64)
    req[:, 0] = [3000, 3000, 1000, 1000]
    req[:, 3] = 1
    cap = np.zeros((2, 4), np.int64)
    cap[:, 0] = 4000
    cap[:, 3] = 110
    score = np.zeros((4, 2), np.int64)
    solver = AssignSolver()
    owner, name = solver.solve(
        fit, score, req, req.copy(), np.ones((4, 4), bool), cap,
        np.zeros(4, np.int32), np.arange(4, dtype=np.int32), 4)
    assert name == "beam"
    assert (owner >= 0).all()
    _check_solution(owner, fit, req, np.ones((4, 4), bool), cap)


def test_auction_rounds_bounded():
    assert auction_rounds(16, 1024) == 16
    assert auction_rounds(2048, 64) == 2048 // 64 * 8
    assert auction_rounds(4096, 4096) >= 16


def test_auction_long_run_past_64_rounds_stays_sound():
    # P >> N drives auction_rounds past 64: the epsilon shift must
    # clamp (a >=64-bit int64 shift is implementation-defined and
    # would reinflate eps mid-run), and the whole wave still seats
    P, N = 256, 16
    assert auction_rounds(P, N) > 64
    fit = np.ones((P, N), bool)
    score = np.zeros((P, N), np.int64)
    req = np.zeros((P, 4), np.int64)
    req[:, 0] = 250
    req[:, 3] = 1
    check = np.ones((P, 4), bool)
    cap = np.zeros((N, 4), np.int64)
    cap[:, 0] = 4000  # exactly 16 slots per node
    cap[:, 3] = 110
    solver = AssignSolver()
    owner, name = solver.solve(fit, score, req, req.copy(), check, cap,
                               np.zeros(P, np.int32),
                               np.arange(P, dtype=np.int32), P)
    assert name == "auction"
    _check_solution(owner, fit, req, check, cap)
    assert (owner >= 0).all()


# -- profile: oracle validity fuzz -------------------------------------------


def _assert_oracle_valid(state, pods, hosts):
    """The packing must be SERIALLY feasible: some one-at-a-time order
    exists in which every placement passes the serial oracle's
    predicates at its own insertion (exactly the property the serial
    scheduler guarantees — a final-state re-check would be stricter
    than the oracle itself for init-container pods, whose fit request
    exceeds their committed usage)."""
    from kubernetes_tpu.api.types import pod_resource_request

    oracle = GenericScheduler(predicates=ORACLE_PREDICATES,
                              priorities=ORACLE_PRIORITIES)

    def gap(p):
        # fit request minus committed usage: init-container pods need
        # headroom at insertion they never consume, so they must come
        # first in any witness order (the exchange argument)
        req_c, req_m, _g = pod_resource_request(p)
        com_c = sum(int(str(c.requests.get("cpu", "0")).rstrip("m") or 0)
                    for c in p.spec.containers)
        return (req_c - com_c, req_c, req_m)

    remaining = sorted(
        ((p, h) for p, h in zip(pods, hosts) if h is not None),
        key=lambda ph: gap(ph[0]), reverse=True)
    while remaining:
        progress = None
        for idx, (p, h) in enumerate(remaining):
            fits, failed = oracle.find_nodes_that_fit(p, state)
            if h in fits:
                progress = idx
                q = shallow_copy(p)
                q.spec = shallow_copy(p.spec)
                q.spec.node_name = h
                state.assign(q)
                break
        assert progress is not None, (
            "no serial order admits the remaining placements: "
            + ", ".join(f"{p.metadata.name}->{h}"
                        for p, h in remaining[:5])
        )
        remaining.pop(progress)


@pytest.mark.parametrize("seed", range(8))
def test_optimizer_placements_pass_serial_oracle_fuzz(seed):
    rng = random.Random(1000 + seed)
    state, pending = random_scenario(
        rng, n_nodes=10, n_existing=8, n_pending=30)
    algo = TPUScheduleAlgorithm(profile="optimizing")
    hosts = algo.schedule_backlog(pending, state)
    _assert_oracle_valid(state, pending, hosts)


@pytest.mark.parametrize("seed", range(4))
def test_optimizer_mixed_features_route_and_stay_feasible(seed):
    # inter-pod terms and volumes are optimizer-ineligible: they must
    # route through the scan, and the combined packing must respect
    # per-node resource capacity
    rng = random.Random(2000 + seed)
    state, pending = random_scenario(
        rng, n_nodes=8, n_existing=6, n_pending=20,
        interpod_p=0.3, volumes_p=0.3)
    algo = TPUScheduleAlgorithm(profile="optimizing")
    hosts = algo.schedule_backlog(pending, state)
    for p, h in zip(pending, hosts):
        if h is None:
            continue
        q = shallow_copy(p)
        q.spec = shallow_copy(p.spec)
        q.spec.node_name = h
        state.assign(q)
    from kubernetes_tpu.api.types import (
        resource_list_cpu_milli,
        resource_list_memory,
    )

    for nm, info in state.node_infos.items():
        if info.node is None:
            continue
        alloc = info.node.status.allocatable or {}
        assert info.requested_milli_cpu <= resource_list_cpu_milli(alloc)
        assert info.requested_memory <= resource_list_memory(alloc)
        assert len(info.pods) <= int(str(alloc.get("pods", 0) or 0))


def test_optimizer_strictly_beats_greedy_on_stranding_mix():
    n = 16
    pods = interleaved_pack(n)
    g = TPUScheduleAlgorithm(config=LRBA, profile="greedy")
    hg = g.schedule_backlog(pods, ClusterState.build(
        [node(f"n{i:03d}") for i in range(n)]))
    o = TPUScheduleAlgorithm(config=LRBA, profile="optimizing")
    ho = o.schedule_backlog(pods, ClusterState.build(
        [node(f"n{i:03d}") for i in range(n)]))
    assert sum(1 for h in ho if h) > sum(1 for h in hg if h)
    assert sum(1 for h in ho if h) == len(pods)


def test_optimizer_o1_dispatches_per_wave():
    # 12 distinct templates interleaved: the greedy grouped path and
    # the optimizer must BOTH stay O(1) dispatches; the optimizer's
    # budget is probe_group + assign + apply + scan = 4
    n_nodes = 16
    nodes = [node(f"n{i:03d}") for i in range(n_nodes)]
    pods = []
    for i in range(48):
        t = i % 12
        pods.append(pod(f"p-{i:03d}-t{t}", f"{200 + 100 * t}m"))
    algo = TPUScheduleAlgorithm(config=LRBA, profile="optimizing")
    algo.schedule_backlog(pods, ClusterState.build(nodes))
    total = sum(algo._opt.dispatches.values())
    assert total <= 4, algo._opt.dispatches

    # template count doubles; dispatch count must not
    pods2 = []
    for i in range(96):
        t = i % 24
        pods2.append(pod(f"q-{i:03d}-t{t}", f"{200 + 50 * t}m"))
    algo2 = TPUScheduleAlgorithm(config=LRBA, profile="optimizing")
    algo2.schedule_backlog(pods2, ClusterState.build(nodes))
    assert sum(algo2._opt.dispatches.values()) <= 4


def test_greedy_profile_untouched_by_optimizer_import():
    # the default profile takes the wave driver path and stays
    # bit-identical to the serial oracle (the conformance suites gate
    # this too; here: same decisions with the optimizer imported)
    rng = random.Random(7)
    state, pending = random_scenario(rng, n_nodes=8, n_pending=20)
    oracle = GenericScheduler(predicates=ORACLE_PREDICATES,
                              priorities=ORACLE_PRIORITIES)
    import copy

    expected = oracle.schedule_backlog(pending, copy.deepcopy(state))
    algo = TPUScheduleAlgorithm(profile="greedy")
    got = algo.schedule_backlog(pending, state)
    assert got == expected


# -- gangs under the optimizer ------------------------------------------------


class TestOptimizerGangs:
    def test_unfittable_gang_never_partially_binds(self):
        nodes = [node(f"n{i}", cpu="4") for i in range(4)]
        # gang of 6 x 3cpu: at most 4 members could seat, so the gang
        # must come back entirely unplaced
        members = [pod(f"g-{i}", "3000m") for i in range(6)]
        singles = [pod(f"s-{i}", "1000m") for i in range(4)]
        backlog = singles + members
        algo = TPUScheduleAlgorithm(config=LRBA, profile="optimizing")
        hosts = algo.schedule_backlog(
            backlog, ClusterState.build(nodes),
            gangs=[{"start": 4, "length": 6, "score_by_name": None}])
        assert all(h is None for h in hosts[4:]), hosts
        assert all(h is not None for h in hosts[:4])

    def test_fittable_gang_binds_whole(self):
        nodes = [node(f"n{i}", cpu="4") for i in range(4)]
        members = [pod(f"g-{i}", "3000m") for i in range(4)]
        algo = TPUScheduleAlgorithm(config=LRBA, profile="optimizing")
        hosts = algo.schedule_backlog(
            members, ClusterState.build(nodes),
            gangs=[{"start": 0, "length": 4, "score_by_name": None}])
        assert all(h is not None for h in hosts)

    @pytest.mark.parametrize("seed", range(4))
    def test_gang_atomicity_fuzz(self, seed):
        rng = random.Random(3000 + seed)
        n = rng.choice([4, 6, 8])
        nodes = [node(f"n{i}", cpu="4") for i in range(n)]
        gang_len = rng.choice([2, 3, n + 2])
        members = [pod(f"g-{i}", f"{rng.choice([2000, 3000])}m")
                   for i in range(gang_len)]
        singles = [pod(f"s-{i}", "500m")
                   for i in range(rng.randint(0, 6))]
        backlog = singles + members
        algo = TPUScheduleAlgorithm(config=LRBA, profile="optimizing")
        hosts = algo.schedule_backlog(
            backlog, ClusterState.build(nodes),
            gangs=[{"start": len(singles), "length": gang_len,
                    "score_by_name": None}])
        span = hosts[len(singles):]
        placed = sum(1 for h in span if h is not None)
        assert placed in (0, gang_len), (
            f"partial gang bind: {placed}/{gang_len}")


# -- --pack gates -------------------------------------------------------------


@pytest.mark.skipif(_SANITIZED, reason="perf gates run unsanitized")
def test_pack_smoke_gates_strict_improvement():
    import bench

    record = bench.run_pack(smoke=True, write=False)
    assert record["all_gates_pass"]
    for key in ("pack_config2", "pack_config4"):
        gates = record[key]["gates"]
        assert gates["schedulable_count_strictly_improves"]
        assert gates["packed_utilization_strictly_improves"]
        assert gates["o1_dispatch_budget"]


@pytest.mark.slow
@pytest.mark.skipif(_SANITIZED, reason="perf gates run unsanitized")
def test_pack_full_gates():
    import bench

    record = bench.run_pack(smoke=False, write=False)
    assert record["all_gates_pass"]


# -- analysis registration ----------------------------------------------------


def test_assign_programs_registered():
    from kubernetes_tpu.analysis.programs import build_programs

    names = {s.name for s in build_programs(include_mesh=False)}
    assert {"assign_auction", "assign_beam"} <= names


# -- defragmentation ----------------------------------------------------------


def _strand_state(n=8, used_cpu="2000m"):
    nodes = [node(f"n{i}") for i in range(n)]
    assigned = [pod(f"p{i}", used_cpu, node_name=f"n{i}")
                for i in range(n)]
    return ClusterState.build(nodes, assigned_pods=assigned)


class TestDefrag:
    TARGET = np.array([3000, 3 << 30, 0, 1], np.int64)

    def test_fragmentation_measure(self):
        state = _strand_state()
        assert D.fragmentation(state, self.TARGET) == 1.0
        empty = ClusterState.build([node("e0"), node("e1")])
        assert D.fragmentation(empty, self.TARGET) == 0.0

    def test_proposal_pairs_and_unstrands(self):
        state = _strand_state(8)
        plan = D.propose_migrations(state, self.TARGET, budget=8)
        assert 0 < len(plan) <= 8
        D.apply_migrations_to_state(state, plan)
        assert D.fragmentation(state, self.TARGET) == 0.0

    def test_budget_caps_plan(self):
        state = _strand_state(8)
        plan = D.propose_migrations(state, self.TARGET, budget=2)
        assert len(plan) <= 2

    def test_priority_protection(self):
        state = _strand_state(8)
        # every pod belongs to a tier >= beneficiary: nothing may move
        plan = D.propose_migrations(
            state, self.TARGET, budget=8,
            beneficiary_priority=1, priority_of=lambda p: 5)
        assert plan == []

    @pytest.mark.parametrize("seed", range(8))
    def test_migrations_never_reduce_schedulability_fuzz(self, seed):
        rng = random.Random(4000 + seed)
        n = rng.choice([6, 8, 10])
        nodes = [node(f"n{i}") for i in range(n)]
        assigned = []
        k = 0
        for i in range(n):
            for _ in range(rng.randint(0, 3)):
                assigned.append(pod(
                    f"a{k}", f"{rng.choice([500, 1000, 2000])}m",
                    node_name=f"n{i}"))
                k += 1
        pending = [pod(f"w{i}", f"{rng.choice([1000, 3000])}m")
                   for i in range(6)]
        target = D.target_shape(
            ClusterState.build(nodes, assigned_pods=assigned), pending)

        def schedulable(state):
            algo = TPUScheduleAlgorithm(config=LRBA, profile="greedy")
            hosts = algo.schedule_backlog(list(pending), state)
            return sum(1 for h in hosts if h is not None)

        before_state = ClusterState.build(nodes,
                                          assigned_pods=list(assigned))
        before = schedulable(before_state)
        after_state = ClusterState.build(nodes,
                                         assigned_pods=list(assigned))
        frag_before = D.fragmentation(after_state, target)
        plan = D.propose_migrations(after_state, target, budget=6)
        D.apply_migrations_to_state(after_state, plan)
        assert D.fragmentation(after_state, target) <= frag_before
        after = schedulable(after_state)
        assert after >= before, (
            f"defrag reduced schedulable count {before} -> {after} "
            f"(plan: {[(p.metadata.name, s, d) for p, s, d in plan]})")

    def test_busy_backoff(self):
        clock = {"t": 0.0}
        ctrl = D.DefragController(
            lambda: _strand_state(), busy_fn=lambda: True,
            clock=lambda: clock["t"])
        assert ctrl.sync_once()["outcome"] == "busy"
        first = ctrl._backoff
        assert first > 0
        ctrl.sync_once()
        assert ctrl._backoff >= first  # doubling, capped
        assert ctrl._backoff <= ctrl.backoff_max

    def test_calm_below_threshold(self):
        state = ClusterState.build([node("n0"), node("n1")])
        ctrl = D.DefragController(lambda: state)
        res = ctrl.sync_once()
        assert res["outcome"] == "calm"
        assert res["migrations"] == 0

    def test_execute_evicts_and_rebinds_through_batch_door(self):
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.client import LocalTransport, RESTClient

        server = APIServer()
        client = RESTClient(LocalTransport(server))
        n = 4
        for i in range(n):
            client.nodes().create(node(f"n{i}"))
        for i in range(n):
            client.pods().create(pod(f"p{i}", "2000m",
                                     node_name=f"n{i}"))

        def state_fn():
            nodes_live, _ = client.nodes().list()
            pods_live, _ = client.pods().list()
            return ClusterState.build(
                list(nodes_live),
                assigned_pods=[p for p in pods_live
                               if p.spec.node_name])

        ctrl = D.DefragController(
            state_fn, client=client,
            pending_fn=lambda: [pod("want", "3000m")],
            frag_threshold=0.1)
        res = ctrl.sync_once()
        assert res["outcome"] == "migrated"
        assert res["migrations"] > 0
        pods_live, _ = client.pods().list()
        by_node = {}
        for p in pods_live:
            by_node.setdefault(p.spec.node_name, []).append(p)
        assert len(pods_live) == n  # every evicted pod was re-created
        # at least one node is now whole (empty), fragmentation fell
        empties = [f"n{i}" for i in range(n)
                   if f"n{i}" not in by_node]
        assert empties, by_node
        assert D.fragmentation(
            state_fn(), np.array([3000, 3 << 30, 0, 1], np.int64)) < 1.0


# -- PodGroup status reconciliation ------------------------------------------


class TestPodGroupStatusController:
    def _plane(self):
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.client import LocalTransport, RESTClient
        from kubernetes_tpu.controller.framework import (
            SharedInformerFactory,
        )
        from kubernetes_tpu.controller.podgroup import (
            PodGroupStatusController,
        )

        server = APIServer()
        client = RESTClient(LocalTransport(server))
        informers = SharedInformerFactory(client)
        ctrl = PodGroupStatusController(client, informers)
        informers.start()
        informers.wait_for_sync()
        return client, informers, ctrl

    def test_reconciles_terminal_drift(self):
        client, informers, ctrl = self._plane()
        try:
            pgr = client.resource("podgroups", "default")
            pgr.create(PodGroup(
                metadata=ObjectMeta(name="train"),
                spec=PodGroupSpec(min_member=2),
            ))
            for i in range(2):
                client.pods().create(pod(
                    f"m{i}", "100m",
                    labels={POD_GROUP_LABEL: "train", "app": "train"},
                    node_name=f"n{i}"))
            # the scheduler recorded a fully bound gang, then died
            pgr.patch("train", {"status": {
                "phase": "Scheduled", "members": 2, "scheduled": 2,
            }}, subresource="status")
            # one member finishes while the scheduler is away
            client.pods().patch("m0", {"status": {
                "phase": "Succeeded"}}, subresource="status")
            informers.wait_for_sync()
            import time as _t

            deadline = _t.time() + 5
            while _t.time() < deadline:
                if ctrl.sync_once():
                    break
                _t.sleep(0.1)
            got = pgr.get("train")
            assert got.status.members == 1
            assert got.status.scheduled == 1
            assert got.status.phase == "Pending"  # below minMember now
        finally:
            informers.stop()

    def test_no_patch_when_in_sync(self):
        client, informers, ctrl = self._plane()
        try:
            pgr = client.resource("podgroups", "default")
            pgr.create(PodGroup(
                metadata=ObjectMeta(name="idle"),
                spec=PodGroupSpec(min_member=1),
            ))
            import time as _t

            deadline = _t.time() + 5
            while _t.time() < deadline:
                ctrl.sync_once()
                got = pgr.get("idle")
                if got.status.phase == "Pending" \
                        and got.status.members == 0:
                    break
                _t.sleep(0.1)
            rv = pgr.get("idle").metadata.resource_version
            assert ctrl.sync_once() == 0  # steady state: zero PATCHes
            assert pgr.get("idle").metadata.resource_version == rv
        finally:
            informers.stop()


# -- gang backoff fairness ----------------------------------------------------


class TestGangBackoff:
    def _director(self, clock, pg):
        from kubernetes_tpu.scheduler.gang import GangDirector

        return GangDirector(
            pod_group_lister=lambda: [pg],
            backoff_initial=2.0, backoff_max=8.0, clock=clock,
        )

    def _wave(self, n_members):
        return [pod(f"g-{i}", "3000m",
                    labels={POD_GROUP_LABEL: "giant", "app": "giant"})
                for i in range(n_members)]

    def test_resource_park_backs_off_and_caps(self):
        clock = {"t": 0.0}
        pg = PodGroup(metadata=ObjectMeta(name="giant",
                                          namespace="default"),
                      spec=PodGroupSpec(min_member=2))
        d = self._director(lambda: clock["t"], pg)
        state = ClusterState.build([node("n0", cpu="1")])
        wave = self._wave(2)
        backlog, layout, parked = d.plan_wave(wave, state)
        assert layout and not parked  # members suffice: gang enters
        hosts, errors = d.after_wave(
            backlog, [None] * len(backlog), layout, state)
        assert errors  # resource park
        key = ("default", "giant")
        delay0, _ = d._backoff[key]
        assert delay0 == 2.0
        # inside the window: the gang sits the wave out (no re-probe)
        backlog2, layout2, parked2 = d.plan_wave(self._wave(2), state)
        assert not layout2 and len(parked2) == 2
        assert "backing off" in str(parked2[0][1])
        # repeated parks double the delay up to the starvation cap
        for _ in range(4):
            clock["t"] += d._backoff[key][0] + 0.1
            backlog3, layout3, _ = d.plan_wave(self._wave(2), state)
            assert layout3  # cap reached or window expired: re-probes
            d.after_wave(backlog3, [None] * len(backlog3), layout3,
                         state)
        assert d._backoff[key][0] == 8.0  # capped, never unbounded

    def test_success_clears_backoff(self):
        clock = {"t": 0.0}
        pg = PodGroup(metadata=ObjectMeta(name="giant",
                                          namespace="default"),
                      spec=PodGroupSpec(min_member=1))
        d = self._director(lambda: clock["t"], pg)
        state = ClusterState.build([node("n0")])
        wave = self._wave(1)
        backlog, layout, _ = d.plan_wave(wave, state)
        d.after_wave(backlog, [None], layout, state)
        assert ("default", "giant") in d._backoff
        clock["t"] += 100.0
        backlog2, layout2, _ = d.plan_wave(self._wave(1), state)
        d.after_wave(backlog2, ["n0"], layout2, state)
        assert ("default", "giant") not in d._backoff

    def test_backoff_pruned_when_podgroup_deleted(self):
        from kubernetes_tpu.scheduler.gang import GangDirector

        clock = {"t": 0.0}
        pgs = {
            n: PodGroup(metadata=ObjectMeta(name=n,
                                            namespace="default"),
                        spec=PodGroupSpec(min_member=1))
            for n in ("keep", "gone")
        }
        d = GangDirector(pod_group_lister=lambda: list(pgs.values()),
                         backoff_initial=2.0, backoff_max=8.0,
                         clock=lambda: clock["t"])
        state = ClusterState.build([node("n0", cpu="1")])
        for name in ("keep", "gone"):
            wave = [pod(f"{name}-0", "3000m",
                        labels={POD_GROUP_LABEL: name, "app": name})]
            backlog, layout, _ = d.plan_wave(wave, state)
            d.after_wave(backlog, [None], layout, state)
        assert set(d._backoff) == {("default", "keep"),
                                   ("default", "gone")}
        del pgs["gone"]  # PodGroup deleted: its backoff must not leak
        d.plan_wave([pod("keep-1", "3000m",
                         labels={POD_GROUP_LABEL: "keep",
                                 "app": "keep"})], state)
        assert ("default", "gone") not in d._backoff

    def test_singletons_unaffected_by_parked_gang_backoff(self):
        clock = {"t": 0.0}
        pg = PodGroup(metadata=ObjectMeta(name="giant",
                                          namespace="default"),
                      spec=PodGroupSpec(min_member=2))
        d = self._director(lambda: clock["t"], pg)
        state = ClusterState.build([node("n0", cpu="1")])
        wave = self._wave(2)
        backlog, layout, _ = d.plan_wave(wave, state)
        d.after_wave(backlog, [None] * len(backlog), layout, state)
        single = pod("lonely", "100m")
        backlog2, layout2, parked2 = d.plan_wave(
            [single] + self._wave(2), state)
        assert backlog2 == [single]  # the singleton still schedules
        assert len(parked2) == 2
