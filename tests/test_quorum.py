"""Quorum consensus store: elections, replication, linearizable reads,
snapshot install, restart recovery, and the storage.Interface contract
through consensus (tests/test_chaos.py and test_quorum_chaos.py carry
the fault-injection gates; this file is the sunny-day correctness
tier plus the Jepsen-lite checker's own unit tests)."""

import os
import time

import pytest

from conftest import wait_until  # noqa: E402

from kubernetes_tpu.analysis import locks as lock_sanitizer
from kubernetes_tpu.storage.quorum import (
    NodeConfig,
    QuorumStore,
    QuorumUnavailable,
    build_cluster,
)
from kubernetes_tpu.storage.quorum import linearize
from kubernetes_tpu.storage.quorum.log import Entry, RaftLog
from kubernetes_tpu.storage.store import (
    DELETE_OBJECT,
    Conflict,
    KeyExists,
    KeyNotFound,
)


@pytest.fixture(autouse=True)
def _lock_order_sanitizer():
    """Every quorum test doubles as a lock-order witness over the new
    node/store/rpc locks (the chaos-suite convention)."""
    with lock_sanitizer.instrumented():
        yield
    lock_sanitizer.assert_no_cycles("(quorum suite)")


def wait_leader(stores, exclude=(), timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for s in stores:
            if s not in exclude and s.node.is_leader():
                return s
        time.sleep(0.02)
    raise AssertionError("no leader elected within %ss" % timeout)


@pytest.fixture
def cluster3(tmp_path):
    stores = build_cluster(str(tmp_path), 3, election_timeout=0.15)
    try:
        yield stores
    finally:
        for s in stores:
            s.close()


def state_fingerprint(store):
    """Canonical bytes of a member's applied contents (the
    bit-identical comparison the snapshot-install gate uses)."""
    from kubernetes_tpu.runtime import tlv

    with store._lock:
        return tlv.dumps(sorted(
            (k, tlv.dumps([o, rv])) for k, (o, rv) in
            store._data.items()
        ))


# -- RaftLog persistence ------------------------------------------------------


class TestRaftLog:
    def test_hardstate_survives_restart(self, tmp_path):
        d = str(tmp_path)
        rl = RaftLog(d)
        rl.save_hardstate(7, "q1")
        rl.close()
        rl2 = RaftLog(d)
        assert (rl2.term, rl2.voted_for) == (7, "q1")
        rl2.close()

    def test_append_recover_truncate(self, tmp_path):
        d = str(tmp_path)
        rl = RaftLog(d)
        rl.append([Entry(1, 1, b"a"), Entry(1, 2, b"b"),
                   Entry(2, 3, b"c")])
        rl.close()
        rl2 = RaftLog(d)
        assert rl2.last_index == 3 and rl2.last_term == 2
        assert rl2.entry(2).payload == b"b"
        # conflict truncation drops the suffix durably
        rl2.truncate_from(2)
        assert rl2.last_index == 1
        rl2.append([Entry(3, 2, b"B")])
        rl2.close()
        rl3 = RaftLog(d)
        assert rl3.last_index == 2 and rl3.entry(2).payload == b"B"
        assert rl3.term_at(2) == 3
        rl3.close()

    def test_torn_tail_discarded(self, tmp_path):
        d = str(tmp_path)
        rl = RaftLog(d)
        rl.append([Entry(1, 1, b"keep")])
        rl.close()
        with open(os.path.join(d, "raft.log"), "ab") as f:
            f.write(b"\x40\x00\x00\x00TORN")  # mid-append crash
        rl2 = RaftLog(d)
        assert rl2.last_index == 1
        # appends after recovery land where the torn bytes were
        rl2.append([Entry(1, 2, b"next")])
        rl2.close()
        rl3 = RaftLog(d)
        assert [e.payload for e in rl3.entries_from(1)] == \
            [b"keep", b"next"]
        rl3.close()

    def test_compact_and_recover_from_snapshot(self, tmp_path):
        d = str(tmp_path)
        rl = RaftLog(d)
        rl.append([Entry(1, i, b"e%d" % i) for i in range(1, 6)])
        rl.compact(3, 1, b"STATE@3")
        assert rl.snap_index == 3
        assert rl.entries_from(1) == []  # compacted out of the window
        assert [e.index for e in rl.entries_from(4)] == [4, 5]
        rl.close()
        rl2 = RaftLog(d)
        assert rl2.snapshot() == (3, 1, b"STATE@3")
        assert rl2.last_index == 5
        rl2.close()

    def test_entry_kind_survives_restart(self, tmp_path):
        """Membership (KIND_CONFIG) entries keep their kind across a
        kill -9 + recovery — a replayed config change that came back
        as DATA would feed peer addresses into the state machine."""
        from kubernetes_tpu.storage.quorum.log import (
            KIND_CONFIG,
            KIND_DATA,
        )

        d = str(tmp_path)
        rl = RaftLog(d)
        rl.append([Entry(1, 1, b"data")])
        rl.append([Entry(1, 2, b"cfgchange", KIND_CONFIG)])
        rl.close()
        rl2 = RaftLog(d)
        assert rl2.entry(1).kind == KIND_DATA
        assert rl2.entry(2).kind == KIND_CONFIG
        assert rl2.entry(2).payload == b"cfgchange"
        rl2.close()

    def test_torn_tail_every_byte_offset(self):
        """Property fuzz: record a WAL through the sim disk, then
        truncate it at EVERY byte offset of the final record and
        recover. No cut may lose a committed (earlier-record) entry,
        and no cut short of the full record may resurrect any part of
        the torn suffix — byte-granular torn-write tolerance, not
        just the single mid-record cut the test above exercises."""
        from kubernetes_tpu.analysis.sim.disk import SimDisk

        recorder = SimDisk()
        d = "/wal"
        rl = RaftLog(d, fsync=True, disk=recorder)
        committed = [Entry(1, i, f"v{i}".encode() * i)
                     for i in (1, 2, 3)]
        rl.append(committed)
        log_path = os.path.join(d, "raft.log")
        prefix_len = recorder.getsize(log_path)
        rl.append([Entry(2, 4, b"tail-record-payload")])
        rl.close()
        full = bytes(recorder.read_bytes(log_path))
        assert len(full) > prefix_len

        for cut in range(prefix_len, len(full) + 1):
            disk = SimDisk()
            disk.makedirs(d)
            with disk.open(log_path, "wb") as h:
                h.write(full[:cut])
                disk.fsync(h)
            rec = RaftLog(d, fsync=True, disk=disk)
            # the committed prefix survives every cut, bit-identical
            for e in committed:
                got = rec.entry(e.index)
                assert got is not None and got.term == e.term \
                    and got.payload == e.payload, f"cut={cut}"
            if cut == len(full):
                assert rec.last_index == 4, "complete record kept"
            else:
                # partial tail: dropped whole, never half-parsed
                assert rec.last_index == 3, f"cut={cut}"
                # and recovery leaves a log that accepts new appends
                # where the torn bytes were
                rec.append([Entry(2, 4, b"replacement")])
                assert rec.entry(4).payload == b"replacement"
            rec.close()


# -- consensus basics ---------------------------------------------------------


class TestQuorumConsensus:
    def test_exactly_one_leader_and_terms_recorded(self, cluster3):
        lead = wait_leader(cluster3)
        time.sleep(0.3)  # heartbeats hold the others back
        leaders = [s for s in cluster3 if s.node.is_leader()]
        assert leaders == [lead]
        claimed = {}
        for s in cluster3:
            for t in s.node.terms_led:
                claimed.setdefault(t, []).append(s.node_id)
        assert all(len(v) == 1 for v in claimed.values()), (
            f"two leaders claimed one term: {claimed}")

    def test_write_replicates_to_majority_and_all(self, cluster3):
        lead = wait_leader(cluster3)
        for i in range(10):
            lead.create(f"/pods/p{i}", {"i": i})
        # acked == committed: every member converges (apply is async
        # on followers, so wait, but convergence must be fast)
        for s in cluster3:
            assert wait_until(
                lambda s=s: len(s.scan_refs("/pods/")) == 10,
                timeout=10), f"{s.node_id} never converged"

    def test_follower_forwards_writes_and_serves_reads(self, cluster3):
        lead = wait_leader(cluster3)
        follower = next(s for s in cluster3 if s is not lead)
        rv = follower.create("/pods/via-follower", {"x": 1})
        assert rv > 0
        # linearizable read from the OTHER follower sees it at once
        other = next(s for s in cluster3
                     if s is not lead and s is not follower)
        obj, _ = other.get("/pods/via-follower")
        assert obj == {"x": 1}

    def test_leader_kill_elects_new_and_loses_nothing(self, cluster3):
        lead = wait_leader(cluster3)
        for i in range(25):
            lead.create(f"/pods/p{i:02d}", {"i": i})
        lead.kill()
        lead2 = wait_leader(cluster3, exclude=(lead,))
        objs, _ = lead2.list("/pods/")
        assert len(objs) == 25, "acked writes lost across failover"
        # and the new leader takes writes with RV continuity
        rv_before = lead2.current_rv
        assert lead2.create("/pods/post", {"i": 99}) > rv_before

    def test_lease_reads_skip_readindex_rounds(self, cluster3):
        """Leader leases: once a majority of appends has acked, steady
        linearizable reads ride the lease — quorum_lease_reads_total
        grows while quorum_readindex_rounds_total stays flat (the
        structural gate the soak holds at scale)."""
        from kubernetes_tpu.metrics import (
            quorum_lease_reads_total,
            quorum_readindex_rounds_total,
        )

        lead = wait_leader(cluster3)
        lead.create("/pods/lease", {"x": 1})
        l0 = quorum_lease_reads_total.get()
        r0 = quorum_readindex_rounds_total.get()
        # each write's append round renews the lease milliseconds
        # before the read (the fixture's 0.15s election timeout makes
        # a purely heartbeat-renewed lease window too tight for a
        # loaded 1-core CI box)
        for i in range(20):
            lead.create(f"/pods/lease-{i}", {"x": i})
            lead.get("/pods/lease")
        assert quorum_lease_reads_total.get() - l0 >= 18
        assert quorum_readindex_rounds_total.get() - r0 <= 2

    def test_single_membership_change_in_flight(self, cluster3):
        """The single-server membership-change rule: a second config
        proposal while one is uncommitted is refused outright."""
        lead = wait_leader(cluster3)
        with lead.node._mu:
            lead.node._config_inflight = True
        try:
            with pytest.raises(QuorumUnavailable):
                lead.node.propose_config(
                    ["add", "q9", ["127.0.0.1", 1]], timeout=0.5)
        finally:
            with lead.node._mu:
                lead.node._config_inflight = False

    def test_stale_leader_cannot_serve_linearizable_reads(
            self, tmp_path):
        """The read-index regression: isolate the leader (its lease of
        silence), write through the majority side, and the deposed
        leader must REFUSE a linearizable read rather than serve its
        stale state."""
        from kubernetes_tpu.harness.nemesis import Nemesis

        stores = [QuorumStore(NodeConfig(
            node_id=f"q{i}",
            data_dir=str(tmp_path / f"n{i}"),
            election_timeout=0.15,
        )) for i in range(3)]
        nem = None
        try:
            nem = Nemesis({s.node_id: s.address for s in stores})
            for s in stores:
                s.set_peers(nem.peer_view(s.node_id))
                s.start()
            lead = wait_leader(stores)
            lead.create("/k/a", {"v": "old"})
            nem.isolate(lead.node_id)
            lead2 = wait_leader(stores, exclude=(lead,))
            lead2.update("/k/a", {"v": "new"})
            with pytest.raises(QuorumUnavailable):
                lead.get("/k/a")  # must NOT return {"v": "old"}
            nem.heal()
            # after healing, the old leader rejoins and serves the
            # committed value
            assert wait_until(
                lambda: not lead.node.is_leader(), timeout=10)
            obj, _ = lead.get("/k/a")
            assert obj == {"v": "new"}
        finally:
            for s in stores:
                s.close()
            if nem is not None:
                nem.close()

    def test_lagging_follower_catches_up_via_snapshot_install(
            self, tmp_path):
        """Partition one follower, write + compact past its position,
        heal: it must catch up through InstallSnapshot and end
        bit-identical to the leader."""
        from kubernetes_tpu.harness.nemesis import Nemesis
        from kubernetes_tpu.metrics import quorum_snapshot_installs_total

        stores = [QuorumStore(NodeConfig(
            node_id=f"q{i}", data_dir=str(tmp_path / f"n{i}"),
            election_timeout=0.15,
        )) for i in range(3)]
        nem = Nemesis({s.node_id: s.address for s in stores})
        for s in stores:
            s.set_peers(nem.peer_view(s.node_id))
            s.start()
        try:
            lead = wait_leader(stores)
            laggard = next(s for s in stores if s is not lead)
            lead.create("/k/pre", {"v": 0})
            assert wait_until(
                lambda: laggard.scan_refs("/k/"), timeout=10)
            nem.isolate(laggard.node_id)
            installs_before = quorum_snapshot_installs_total.get()
            for i in range(30):
                lead.create(f"/k/during-{i:02d}", {"v": i})
            # compact EVERY surviving member's log so the laggard's
            # next index is off the retained window no matter which
            # member leads after the heal (the isolated laggard's
            # term bumps can depose and re-elect — no pre-vote yet)
            survivors = [s for s in stores if s is not laggard]
            for s in survivors:
                s.node.compact_now()
            for s in survivors:
                assert wait_until(
                    lambda s=s: s.node.raft_log.snap_index
                    >= s.node.status()["applied_index"] - 1,
                    timeout=10)
            nem.heal()
            assert wait_until(
                lambda: len(laggard.scan_refs("/k/")) == 31,
                timeout=15), "laggard never caught up"
            assert wait_until(
                lambda: quorum_snapshot_installs_total.get()
                > installs_before, timeout=5), (
                "catch-up did not go through the snapshot path")
            assert wait_until(
                lambda: state_fingerprint(laggard)
                == state_fingerprint(lead), timeout=10), (
                "laggard state not bit-identical to the leader's")
        finally:
            for s in stores:
                s.close()
            nem.close()

    def test_restart_recovers_committed_state(self, tmp_path):
        stores = build_cluster(str(tmp_path), 3, election_timeout=0.15)
        try:
            lead = wait_leader(stores)
            for i in range(12):
                lead.create(f"/k/{i:02d}", {"v": i})
            victim = next(s for s in stores if s is not lead)
            vid = victim.node_id
            vdir = victim.node.config.data_dir
            vport = victim.address[1]
            peers = dict(victim.node.config.peers)
            assert wait_until(
                lambda: len(victim.scan_refs("/k/")) == 12, timeout=10)
            victim.kill()
            # more writes while it is down
            for i in range(12, 20):
                lead.create(f"/k/{i:02d}", {"v": i})
            # the restart rebinds the SAME peer port — that is how its
            # peers keep finding it (the deployment contract of
            # --quorum-listen)
            reborn = QuorumStore(NodeConfig(
                node_id=vid, data_dir=vdir, peers=peers,
                listen_port=vport, election_timeout=0.15))
            reborn.set_peers(peers)
            reborn.start()
            stores.append(reborn)
            assert wait_until(
                lambda: len(reborn.scan_refs("/k/")) == 20,
                timeout=15), "restarted member never converged"
            assert wait_until(
                lambda: state_fingerprint(reborn)
                == state_fingerprint(lead), timeout=10)
        finally:
            for s in stores:
                s.close()


# -- storage.Interface contract through consensus -----------------------------


class TestQuorumStoreContract:
    def test_contract_single_member(self, tmp_path):
        """A 1-member quorum is an instant leader: run the core
        MemoryStore contract through the full propose/apply path."""
        store = QuorumStore(NodeConfig(
            node_id="solo", data_dir=str(tmp_path),
            election_timeout=0.05))
        store.start()
        try:
            wait_leader([store])
            rv1 = store.create("/pods/a", {"v": 1})
            with pytest.raises(KeyExists):
                store.create("/pods/a", {"v": 1})
            with pytest.raises(Conflict):
                store.update("/pods/a", {"v": 2}, expect_rv=rv1 + 99)
            rv2 = store.update("/pods/a", {"v": 2}, expect_rv=rv1)
            assert rv2 > rv1
            assert store.get("/pods/a")[0] == {"v": 2}
            with pytest.raises(KeyNotFound):
                store.delete("/pods/missing")
            gone = store.delete("/pods/a", expect_rv=rv2)
            assert gone == {"v": 2}
            # guaranteed_update create-on-missing + mutate
            store.guaranteed_update(
                "/pods/b", lambda cur: {"n": 1} if cur is None
                else cur, ignore_not_found=True)
            store.guaranteed_update(
                "/pods/b", lambda cur: dict(cur, n=cur["n"] + 1))
            assert store.get("/pods/b")[0] == {"n": 2}
        finally:
            store.close()

    def test_watchers_see_only_committed_writes(self, cluster3):
        lead = wait_leader(cluster3)
        follower = next(s for s in cluster3 if s is not lead)
        stream = follower.watch("/pods/")
        lead.create("/pods/w1", {"v": 1})
        ev = stream.next_event(timeout=10)
        assert ev.type == "ADDED" and ev.object == {"v": 1}
        # events carry the rv the write acked with
        assert ev.resource_version == lead.get("/pods/w1")[1]
        stream.stop()

    def test_update_batch_and_delete_through_consensus(self, cluster3):
        lead = wait_leader(cluster3)
        follower = next(s for s in cluster3 if s is not lead)
        for i in range(6):
            lead.create(f"/pods/b{i}", {"v": i})
        # the batch door FROM A FOLLOWER: mutations + a batch delete
        # in one conditional batch (the forwarded-cas path)
        results = follower.update_batch(
            [(f"/pods/b{i}",
              (lambda o: DELETE_OBJECT) if i % 2
              else (lambda o: dict(o, bumped=True)))
             for i in range(6)]
            + [("/pods/missing", lambda o: o)]
        )
        assert results[:6] == [None] * 6
        assert isinstance(results[6], KeyNotFound)
        objs, _ = lead.list("/pods/")
        assert len(objs) == 3
        assert all(o.get("bumped") for o in objs)

    def test_create_batch_per_item_isolation(self, cluster3):
        lead = wait_leader(cluster3)
        lead.create("/pods/dup", {"v": 0})
        res = lead.create_batch([
            ("/pods/n1", {"v": 1}),
            ("/pods/dup", {"v": 2}),  # KeyExists, isolated
            ("/pods/n2", {"v": 3}),
        ])
        assert res[0] is None and res[2] is None
        assert isinstance(res[1], KeyExists)
        assert lead.get("/pods/dup")[0] == {"v": 0}

    def test_guaranteed_update_conflict_retry_across_members(
            self, cluster3):
        """Two members racing guaranteed_update on one key must both
        land (the CAS retry loop absorbs the Conflict)."""
        lead = wait_leader(cluster3)
        f1 = next(s for s in cluster3 if s is not lead)
        lead.create("/pods/ctr", {"n": 0})
        import threading

        def bump(store, times):
            for _ in range(times):
                store.guaranteed_update(
                    "/pods/ctr", lambda cur: dict(cur, n=cur["n"] + 1))

        ths = [threading.Thread(target=bump, args=(s, 10))
               for s in (lead, f1)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=60)
        assert lead.get("/pods/ctr")[0]["n"] == 20


# -- multi-apiserver smoke ----------------------------------------------------


class TestMultiAPIServer:
    def test_two_apiservers_one_quorum(self, tmp_path):
        """Two APIServer instances over the same 3-member quorum:
        writes through the FORWARDING FOLLOWER's server land for
        readers of the leader's server, watches fan out on both, and
        /healthz names the member identity (the horizontally-scaled
        apiserver shape the wire-soak protocol documents)."""
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import LocalTransport

        stores = build_cluster(str(tmp_path), 3, election_timeout=0.15)
        try:
            lead = wait_leader(stores)
            follower = next(s for s in stores if s is not lead)
            api_lead = APIServer(store=lead)
            api_follow = APIServer(store=follower)
            c_lead = RESTClient(LocalTransport(api_lead))
            c_follow = RESTClient(LocalTransport(api_follow))

            from kubernetes_tpu.api.types import (
                Container, ObjectMeta, Pod, PodSpec,
            )

            def pod(name):
                return Pod(metadata=ObjectMeta(name=name),
                           spec=PodSpec(containers=[Container(
                               requests={"cpu": "50m"})]))

            # watch on the leader's server, write through the follower
            stream = c_lead.pods().watch()
            for i in range(20):
                c_follow.pods().create(pod(f"fwd-{i:02d}"))
            objs, _ = c_lead.pods().list()
            assert len(objs) == 20
            seen = set()
            for ev_type, obj in stream:
                if ev_type == "ADDED":
                    seen.add(obj.metadata.name)
                if len(seen) >= 20:
                    break
            stream.stop()
            # healthz identity: the two servers answer as different
            # members of the same quorum, exactly one leading
            h1 = api_lead.handle("GET", "/healthz", {}, None)[1]
            h2 = api_follow.handle("GET", "/healthz", {}, None)[1]
            assert h1["quorum"]["node"] != h2["quorum"]["node"]
            assert h1["quorum"]["leader"] == h2["quorum"]["leader"]
            assert {h1["quorum"]["role"], h2["quorum"]["role"]} == \
                {"leader", "follower"}
        finally:
            for s in stores:
                s.close()


# -- Jepsen-lite checker unit tests -------------------------------------------


class TestLinearizeChecker:
    def _history(self, events):
        h = linearize.HistoryRecorder()
        ids = {}
        for ev in events:
            if ev[0] == "invoke":
                _, name, proc, kind, key, value = ev
                ids[name] = h.invoke(proc, kind, key, value)
            elif ev[0] == "ok":
                _, name, rv, value = ev
                h.ok(ids[name], rv=rv, value=value)
            elif ev[0] == "fail":
                h.fail(ids[ev[1]])
            else:
                h.info(ids[ev[1]])
        return h

    def test_accepts_clean_history(self):
        h = self._history([
            ("invoke", "w1", "p0", "write", "k", "a"),
            ("ok", "w1", 1, None),
            ("invoke", "r1", "p1", "read", "k", None),
            ("ok", "r1", 1, "a"),
            ("invoke", "w2", "p0", "write", "k", "b"),
            ("ok", "w2", 2, None),
            ("invoke", "r2", "p1", "read", "k", None),
            ("ok", "r2", 2, "b"),
        ])
        res = linearize.check(h, final_state={"k": ("b", 2)})
        assert res.ok, res.errors
        assert res.checked_writes == 2 and res.checked_reads == 2

    def test_rejects_lost_acknowledged_write(self):
        h = self._history([
            ("invoke", "w1", "p0", "write", "k", "a"),
            ("ok", "w1", 5, None),
        ])
        res = linearize.check(h, final_state={})
        assert not res.ok
        assert any("LOST" in e for e in res.errors)

    def test_rejects_stale_read(self):
        h = self._history([
            ("invoke", "w1", "p0", "write", "k", "a"),
            ("ok", "w1", 1, None),
            ("invoke", "w2", "p0", "write", "k", "b"),
            ("ok", "w2", 2, None),
            # read invoked AFTER w2 completed but observing rv 1
            ("invoke", "r1", "p1", "read", "k", None),
            ("ok", "r1", 1, "a"),
        ])
        res = linearize.check(h)
        assert not res.ok
        assert any("stale read" in e for e in res.errors)

    def test_rejects_value_model_mismatch(self):
        h = self._history([
            ("invoke", "w1", "p0", "write", "k", "a"),
            ("ok", "w1", 1, None),
            ("invoke", "r1", "p1", "read", "k", None),
            ("ok", "r1", 1, "WRONG"),
        ])
        res = linearize.check(h)
        assert not res.ok

    def test_indeterminate_write_may_or_may_not_land(self):
        h = self._history([
            ("invoke", "w1", "p0", "write", "k", "a"),
            ("ok", "w1", 1, None),
            ("invoke", "w2", "p0", "write", "k", "b"),
            ("info", "w2"),  # timeout: unknown outcome
        ])
        # either final state is linearizable
        assert linearize.check(h, final_state={"k": ("a", 1)}).ok
        assert linearize.check(h, final_state={"k": ("b", 2)}).ok
        # but a state OLDER than the acked write is still a loss
        assert not linearize.check(h, final_state={}).ok

    def test_rejects_real_time_inversion(self):
        h = linearize.HistoryRecorder()
        a = h.invoke("p0", "write", "k1", "a")
        h.ok(a, rv=9)  # completed, serialized at 9
        time.sleep(0.002)
        b = h.invoke("p1", "write", "k2", "b")  # invoked after a ok'd
        h.ok(b, rv=3)  # ...but claims an earlier point
        res = linearize.check(h)
        assert not res.ok
        assert any("inversion" in e for e in res.errors)
