"""Inbound extender service (scheduler/extender_server.py): the TPU
program served over the reference's extender wire protocol
(extender.go:96-173, api/types.go:135-151), so an external scheduler can
delegate Filter/Prioritize — plus bulk ScheduleBacklog — to the device."""

import json
import time
import urllib.request

import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.models.batch import (
    EQUAL,
    GENERAL_PREDICATES,
    LEAST_REQUESTED,
    POD_TOLERATES_NODE_TAINTS,
    SchedulerConfig,
)
from kubernetes_tpu.oracle import ClusterState, GenericScheduler
from kubernetes_tpu.oracle import predicates as opreds
from kubernetes_tpu.oracle import priorities as oprios
from kubernetes_tpu.oracle.scheduler import PriorityConfig
from kubernetes_tpu.runtime.scheme import scheme
from kubernetes_tpu.scheduler.extender import HTTPExtender
from kubernetes_tpu.scheduler.extender_server import TPUExtenderServer
from kubernetes_tpu.scheduler.policy import ExtenderConfig


def node(name, cpu="4", taints=None, labels=None):
    return t.Node(
        metadata=t.ObjectMeta(
            name=name,
            labels={"kubernetes.io/hostname": name, **(labels or {})},
        ),
        spec=t.NodeSpec(taints=taints),
        status=t.NodeStatus(
            allocatable={"cpu": cpu, "memory": "32Gi", "pods": "110"},
            conditions=[t.NodeCondition("Ready", "True")],
        ),
    )


def pod(name, cpu="100m", node_name=""):
    return t.Pod(
        metadata=t.ObjectMeta(name=name),
        spec=t.PodSpec(
            node_name=node_name,
            containers=[t.Container(requests={"cpu": cpu, "memory": "1Gi"})],
        ),
    )


@pytest.fixture()
def svc():
    server = TPUExtenderServer(
        SchedulerConfig(
            predicates=(GENERAL_PREDICATES, POD_TOLERATES_NODE_TAINTS),
            priorities=((LEAST_REQUESTED, 1),),
        )
    )
    host, port = server.serve_http()
    yield server, f"http://{host}:{port}"
    server.shutdown()


def test_filter_and_prioritize_wire_shapes(svc):
    """Drive the service with the framework's own outbound HTTPExtender —
    the same client the reference's Go scheduler shape implies — and check
    both verbs against the host oracle."""
    _, base = svc
    ext = HTTPExtender(ExtenderConfig(
        url_prefix=base, filter_verb="filter",
        prioritize_verb="prioritize", weight=1,
    ))
    tainted = node("n-taint", taints=[t.Taint(key="dedicated", value="x",
                                              effect="NoSchedule")])
    nodes = [node("n0"), node("n1", cpu="8"), tainted]
    p = pod("p0")

    filtered, failed = ext.filter(p, nodes)
    assert [n.metadata.name for n in filtered] == ["n0", "n1"]
    assert "n-taint" in failed

    scores = dict(ext.prioritize(p, nodes))
    # oracle agreement on the shared nodes
    state = ClusterState.build(nodes)
    expected = oprios.least_requested_priority(p, state)
    for name in ("n0", "n1", "n-taint"):
        assert scores[name] == expected[name]


def test_existing_pods_feed_commitments(svc):
    _, base = svc
    body = {
        "pod": scheme.encode(pod("p0", cpu="3")),
        "nodes": {"items": [scheme.encode(node("n0")),
                            scheme.encode(node("n1"))]},
        "existingPods": [scheme.encode(pod("busy", cpu="2", node_name="n0"))],
    }
    req = urllib.request.Request(
        f"{base}/v1beta1/filter", data=json.dumps(body).encode(),
        method="POST", headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        out = json.loads(r.read())
    names = [i["metadata"]["name"] for i in out["nodes"]["items"]]
    assert names == ["n1"]  # n0 has only 2 CPU headroom left
    assert out["failedNodes"] == {"n0": "TPUExtenderPredicates"}


def test_schedule_backlog_bulk_endpoint(svc):
    server, base = svc
    nodes = [node(f"n{i}") for i in range(4)]
    pending = [pod(f"p{i:02d}") for i in range(12)]
    body = {
        "nodes": {"items": [scheme.encode(n) for n in nodes]},
        "pending": {"items": [scheme.encode(p) for p in pending]},
        "lastNodeIndex": 0,
    }
    req = urllib.request.Request(
        f"{base}/v1beta1/scheduleBacklog", data=json.dumps(body).encode(),
        method="POST", headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        out = json.loads(r.read())
    # sequential-equivalent to the host oracle with the same config
    oracle = GenericScheduler(
        predicates=[
            ("GeneralPredicates", opreds.general_predicates),
            ("PodToleratesNodeTaints", opreds.pod_tolerates_node_taints),
        ],
        priorities=[PriorityConfig(oprios.least_requested_priority, 1,
                                   "LeastRequestedPriority")],
    )
    expected = oracle.schedule_backlog(pending, ClusterState.build(nodes))
    # assignments are keyed namespace/name (bare names collide)
    assert [out["assignments"][f"default/p{i:02d}"] for i in range(12)] == expected
    assert out["lastNodeIndex"] > 0


def test_oracle_scheduler_delegates_to_tpu_extender(svc):
    """VERDICT stage-6 done-criterion: an oracle-driven scheduler uses the
    TPU service as its extender and the device's filtering constrains its
    selections. The policy's own predicate set knows nothing about
    taints; only the extender (device) does."""
    import os
    import tempfile

    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.client.rest import RESTClient
    from kubernetes_tpu.client.transport import LocalTransport
    from kubernetes_tpu.scheduler.server import (
        SchedulerServer,
        SchedulerServerOptions,
    )
    from kubernetes_tpu.scheduler.tpu_algorithm import TPUScheduleAlgorithm

    _, base = svc
    policy = {
        "kind": "Policy",
        "predicates": [{"name": "GeneralPredicates"}],
        "priorities": [{"name": "EqualPriority", "weight": 1}],
        "extenders": [{
            "urlPrefix": base, "apiVersion": "v1beta1",
            "filterVerb": "filter", "prioritizeVerb": "prioritize",
            "weight": 1,
        }],
    }
    api = APIServer()
    client = RESTClient(LocalTransport(api))
    for i in range(3):
        client.nodes().create(node(f"ok{i}"))
    client.nodes().create(node("bad", taints=[
        t.Taint(key="dedicated", value="x", effect="NoSchedule")]))
    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump(policy, f)
        path = f.name
    try:
        srv = SchedulerServer(
            client, SchedulerServerOptions(policy_config_file=path)
        ).start()
        try:
            # extender-bearing policy: host path, not the device algorithm
            assert not isinstance(
                srv.scheduler.config.algorithm, TPUScheduleAlgorithm
            )
            for i in range(9):
                client.pods().create(pod(f"p{i}"))
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                objs, _ = client.pods().list()
                if all(o.spec.node_name for o in objs):
                    break
                time.sleep(0.05)
            objs, _ = client.pods().list()
            placed = {o.metadata.name: o.spec.node_name for o in objs}
            assert all(placed.values()), placed
            # the device's taint filtering constrained the oracle
            assert "bad" not in set(placed.values())
        finally:
            srv.stop()
    finally:
        os.unlink(path)
