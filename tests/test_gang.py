"""AI-cluster workload subsystem (round 14): gang scheduling,
priority preemption, and quota admission.

Covers the ISSUE-14 contract:

* PodGroup/PriorityClass API + admission: priority-class resolution,
  per-group pod/device budgets (403 on exceed, usage released on
  delete), readable denial messages, the quota-denial metric.
* Wave-driver gang semantics: all-or-nothing (a parked gang NEVER
  partially binds), no starvation of singletons behind a parked gang,
  O(1) device dispatches per wave regardless of gang count (the
  structural gate), and bit-identity to the serial oracle when the
  gang features are off.
* Preemption: the device victim scorer (lowest-priority-first,
  fewest-victims, newest-first) against a numpy reference, the
  never-evict-equal-or-higher invariant under randomized fuzz, and
  the no-pointless-evictions rule.
* End to end: a live control plane + TPU scheduler daemon binds a
  gang atomically, parks an oversized gang with a readable status,
  and preempts lower-priority pods for a high-priority gang.
"""

import random
import time

import numpy as np
import pytest

from kubernetes_tpu.api.types import (
    POD_GROUP_LABEL,
    Container,
    Node,
    NodeCondition,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodGroup,
    PodGroupSpec,
    PodSpec,
    PriorityClass,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client import LocalTransport, RESTClient
from kubernetes_tpu.oracle import ClusterState, GenericScheduler
from kubernetes_tpu.scheduler import algorithmprovider
from kubernetes_tpu.scheduler.gang import GangDirector, GangParked
from kubernetes_tpu.scheduler.tpu_algorithm import TPUScheduleAlgorithm
from kubernetes_tpu.ops.preempt import (
    INVALID_PRIO,
    VictimScorer,
    pack_candidates,
)

from conftest import wait_until
from tests.test_conformance import ORACLE_PREDICATES, ORACLE_PRIORITIES


def node(name, cpu="4", mem="32Gi", pods="110", labels=None):
    return Node(
        metadata=ObjectMeta(name=name, labels=labels or {}),
        status=NodeStatus(
            allocatable={"cpu": cpu, "memory": mem, "pods": pods},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )


def pod(name, cpu="500m", labels=None, group=None, ts=None):
    lbl = dict(labels or {"app": "x"})
    if group:
        lbl[POD_GROUP_LABEL] = group
        lbl.setdefault("app", group)
    p = Pod(
        metadata=ObjectMeta(name=name, labels=lbl),
        spec=PodSpec(containers=[
            Container(image="t", requests={"cpu": cpu})
        ]),
    )
    if ts:
        p.metadata.creation_timestamp = ts
    return p


def make_control_plane():
    server = APIServer()
    return server, RESTClient(LocalTransport(server))


# -- API + quota admission ----------------------------------------------------


class TestPodGroupAPI:
    def test_crud_and_validation(self):
        _, client = make_control_plane()
        rc = client.resource("podgroups", "default")
        rc.create(PodGroup(
            metadata=ObjectMeta(name="g1"),
            spec=PodGroupSpec(min_member=4, quota={"pods": "8"}),
        ))
        got = rc.get("g1")
        assert got.spec.min_member == 4
        assert got.status.phase == "Pending"
        from kubernetes_tpu.client.rest import APIStatusError

        with pytest.raises(APIStatusError) as ei:
            rc.create(PodGroup(metadata=ObjectMeta(name="bad"),
                               spec=PodGroupSpec(min_member=0)))
        assert ei.value.code == 422
        with pytest.raises(APIStatusError) as ei:
            rc.create(PodGroup(
                metadata=ObjectMeta(name="bad2"),
                spec=PodGroupSpec(quota={"gpus": "1"}),
            ))
        assert "unknown budget" in str(ei.value)

    def test_priority_class_resolved_at_admission(self):
        _, client = make_control_plane()
        client.resource("priorityclasses").create(PriorityClass(
            metadata=ObjectMeta(name="training-high"), value=1000,
        ))
        rc = client.resource("podgroups", "default")
        rc.create(PodGroup(
            metadata=ObjectMeta(name="g1"),
            spec=PodGroupSpec(priority_class_name="training-high"),
        ))
        assert rc.get("g1").spec.priority == 1000
        from kubernetes_tpu.client.rest import APIStatusError

        with pytest.raises(APIStatusError) as ei:
            rc.create(PodGroup(
                metadata=ObjectMeta(name="g2"),
                spec=PodGroupSpec(priority_class_name="nope"),
            ))
        assert ei.value.code == 403
        assert "unknown priority class" in str(ei.value)

    def test_pod_quota_denied_403_and_released_on_delete(self):
        from kubernetes_tpu.metrics import apiserver_quota_denials_total

        _, client = make_control_plane()
        client.resource("podgroups", "default").create(PodGroup(
            metadata=ObjectMeta(name="g1"),
            spec=PodGroupSpec(quota={"pods": "2"}),
        ))
        client.pods().create(pod("p0", group="g1"))
        client.pods().create(pod("p1", group="g1"))
        before = apiserver_quota_denials_total.get(budget="pods")
        from kubernetes_tpu.client.rest import APIStatusError

        with pytest.raises(APIStatusError) as ei:
            client.pods().create(pod("p2", group="g1"))
        assert ei.value.code == 403
        # the readable message kubectl surfaces
        assert "exceeded quota: pods=2" in str(ei.value)
        assert "in use: 2" in str(ei.value)
        assert apiserver_quota_denials_total.get(
            budget="pods") == before + 1
        # delete releases usage (computed from live store state)
        client.pods().delete("p0")
        client.pods().create(pod("p2", group="g1"))

    def test_device_quota(self):
        _, client = make_control_plane()
        client.resource("podgroups", "default").create(PodGroup(
            metadata=ObjectMeta(name="g1"),
            spec=PodGroupSpec(quota={"devices": "2"}),
        ))

        def gpu_pod(name, n):
            return Pod(
                metadata=ObjectMeta(
                    name=name, labels={POD_GROUP_LABEL: "g1"}),
                spec=PodSpec(containers=[Container(
                    image="t",
                    requests={"cpu": "100m",
                              "alpha.kubernetes.io/nvidia-gpu": str(n)},
                )]),
            )

        client.pods().create(gpu_pod("d0", 2))
        from kubernetes_tpu.client.rest import APIStatusError

        with pytest.raises(APIStatusError) as ei:
            client.pods().create(gpu_pod("d1", 1))
        assert ei.value.code == 403 and "devices=2" in str(ei.value)

    def test_pod_without_group_object_denied(self):
        _, client = make_control_plane()
        from kubernetes_tpu.client.rest import APIStatusError

        with pytest.raises(APIStatusError) as ei:
            client.pods().create(pod("orphan", group="ghost"))
        assert ei.value.code == 403
        assert "does not exist" in str(ei.value)

    def test_kubectl_get_and_describe_podgroups(self):
        from kubernetes_tpu.kubectl.cmd import Kubectl

        _, client = make_control_plane()
        client.resource("podgroups", "default").create(PodGroup(
            metadata=ObjectMeta(name="train"),
            spec=PodGroupSpec(min_member=8, priority=100,
                              quota={"pods": "16"}),
        ))
        client.resource("podgroups", "default").patch(
            "train",
            {"status": {"phase": "Parked", "scheduled": 0, "members": 8,
                        "unschedulable": ["train-3", "train-7"],
                        "message": "gang parked: 2 of 8 members "
                                   "unschedulable (insufficient "
                                   "resources); no partial binds"}},
            subresource="status",
        )
        k = Kubectl(client)
        table = k.get("podgroups")
        assert "MIN-MEMBER" in table and "Parked" in table
        assert "0/8" in table
        desc = k.describe("pg", "train")
        assert "Parked:" in desc and "insufficient resources" in desc
        assert "train-3" in desc and "train-7" in desc


# -- wave-driver gang semantics ----------------------------------------------


def oracle_backlog(state, pending):
    oracle = GenericScheduler(
        predicates=ORACLE_PREDICATES, priorities=ORACLE_PRIORITIES
    )
    return oracle.schedule_backlog(pending, state.clone())


class TestGangWaves:
    def test_parked_gang_never_partially_binds(self):
        # 4 nodes x 2cpu = 16 slots of 500m; a 20-pod gang cannot fit
        state = ClusterState.build([node(f"n{i:02d}", cpu="2")
                                    for i in range(4)])
        gang = [pod(f"g{i}", group="g1") for i in range(20)]
        singles = [pod(f"s{i}") for i in range(4)]
        algo = TPUScheduleAlgorithm(min_run=16)
        hosts = algo.schedule_backlog(
            gang + singles, state,
            gangs=[{"start": 0, "length": 20}],
        )
        assert set(hosts[:20]) == {None}
        # singletons behind the parked gang are NOT starved
        assert all(h is not None for h in hosts[20:])

    def test_fitting_gang_binds_every_member(self):
        state = ClusterState.build([node(f"n{i:02d}", cpu="2")
                                    for i in range(4)])
        gang = [pod(f"g{i}", group="g1") for i in range(8)]
        algo = TPUScheduleAlgorithm(min_run=16)
        hosts = algo.schedule_backlog(
            gang, state, gangs=[{"start": 0, "length": 8}])
        assert all(h is not None for h in hosts)

    def test_gang_probe_commit_o1_dispatches(self):
        """Structural gate (test_slo 24-template style): doubling the
        gang count must not grow the per-wave device dispatch count —
        gangs ride the grouped probe/replay machinery like any run."""
        state = ClusterState.build([node(f"n{i:02d}", cpu="64",
                                         pods="500")
                                    for i in range(8)])

        def wave_of(n_gangs):
            backlog, gangs = [], []
            for g in range(n_gangs):
                members = [
                    pod(f"w{g}-{i}", cpu=f"{100 + (g % 3) * 50}m",
                        group=f"grp{g}")
                    for i in range(8)
                ]
                gangs.append({"start": len(backlog), "length": 8})
                backlog += members
            return backlog, gangs

        counts = {}
        for n_gangs in (4, 8):
            algo = TPUScheduleAlgorithm(min_run=16)
            backlog, gangs = wave_of(n_gangs)
            hosts = algo.schedule_backlog(backlog, state, gangs=gangs)
            assert all(h is not None for h in hosts)
            d = algo._wave.dispatches
            counts[n_gangs] = sum(d.values())
            # every gang must have ridden the run machinery (grouped
            # probe or probe), never the serial scan
            assert d.get("scan", 0) == 0, d
        assert counts[8] <= counts[4] + 1, (
            f"dispatches grew with gang count: {counts}"
        )
        assert counts[8] <= 6, counts

    def test_no_gang_config_bit_identical_to_oracle(self):
        """Gang-labeled pods with the gang features OFF (no layout):
        decisions match the serial oracle exactly — the default
        profile is untouched by this subsystem (mixed-arrival
        regression)."""
        rng = random.Random(1414)
        for trial in range(4):
            nodes = [
                node(f"n{i:02d}", cpu=str(rng.choice([1, 2, 4])))
                for i in range(rng.randint(2, 6))
            ]
            state = ClusterState.build(nodes)
            backlog = []
            for t in range(rng.randint(1, 4)):
                kind = rng.random()
                n = rng.randint(1, 20)
                if kind < 0.5:
                    backlog += [
                        pod(f"t{trial}-g{t}-{i}", cpu="300m",
                            group=f"grp-{t}")
                        for i in range(n)
                    ]
                else:
                    backlog += [
                        pod(f"t{trial}-s{t}-{i}",
                            cpu=f"{200 + 100 * (t % 3)}m")
                        for i in range(n)
                    ]
            want = oracle_backlog(state, backlog)
            algo = TPUScheduleAlgorithm(min_run=8)
            got = algo.schedule_backlog(backlog, state)
            assert got == want, f"trial {trial} diverged"

    def test_randomized_gang_fuzz_no_partial_binds(self):
        """Property (c): under randomized gang mixes and capacities, a
        gang either binds EVERY member or none, and singleton
        placements never regress vs scheduling the singletons alone."""
        rng = random.Random(77)
        for trial in range(6):
            n_nodes = rng.randint(2, 6)
            cap = rng.choice([1, 2, 3])
            state = ClusterState.build(
                [node(f"n{i:02d}", cpu=str(cap))
                 for i in range(n_nodes)]
            )
            backlog, gangs = [], []
            for g in range(rng.randint(1, 4)):
                size = rng.randint(2, 12)
                gangs.append({"start": len(backlog), "length": size})
                backlog += [
                    pod(f"t{trial}-g{g}-{i}", cpu="600m",
                        group=f"grp-{g}")
                    for i in range(size)
                ]
            singles = [pod(f"t{trial}-s{i}", cpu="600m")
                       for i in range(rng.randint(0, 4))]
            # singletons first, like the director orders them
            offset = len(singles)
            for gd in gangs:
                gd["start"] += offset
            backlog = singles + backlog
            algo = TPUScheduleAlgorithm(min_run=16)
            hosts = algo.schedule_backlog(backlog, state, gangs=gangs)
            for gd in gangs:
                span = hosts[gd["start"]:gd["start"] + gd["length"]]
                assert (all(h is not None for h in span)
                        or all(h is None for h in span)), (
                    f"trial {trial} partial bind: {span}"
                )
            # singleton placements match scheduling them alone (a
            # parked gang consumed nothing)
            algo2 = TPUScheduleAlgorithm(min_run=16)
            alone = algo2.schedule_backlog(singles, state)
            assert hosts[:offset] == alone

    def test_gang_table_horizon_partial_continues_not_parks(self):
        """A gang whose replay stops at the TABLE HORIZON (n_done < K
        with every pick valid — reachable when one node absorbs a
        whole compiled table depth of members) is NOT unfit: the
        driver re-probes and continues the gang transactionally
        instead of parking it as 'insufficient resources'."""
        from kubernetes_tpu.models.wave import WaveScheduler
        from kubernetes_tpu.snapshot.encode import SnapshotEncoder

        # ONE huge node, gang of 200, max_j clamped to 128: the first
        # replay horizon-bails at 128 picks on the node with fit still
        # true, which before the horizon/unfit distinction parked the
        # (entirely schedulable) gang
        state = ClusterState.build(
            [node("n00", cpu="400", pods="300")])
        gang = [pod(f"h{i}", cpu="1000m", group="g1")
                for i in range(200)]
        enc = SnapshotEncoder(state, [gang[0]])
        snap = enc.encode_nodes()
        batch = enc.encode_pods()
        rep_idx = np.zeros(200, np.int64)
        w = WaveScheduler(min_run=16, max_j=128)
        out, _carry, _L = w.schedule_backlog(
            snap, batch, rep_idx,
            gangs=[{"start": 0, "length": 200, "score_add": None}],
        )
        assert (out >= 0).all(), (
            f"horizon-partial gang parked: "
            f"{int((out >= 0).sum())}/200 placed"
        )
        # and a genuinely oversized gang on the same shape still parks
        # wholesale (no partial binds through the horizon path)
        gang2 = [pod(f"u{i}", cpu="1000m", group="g2")
                 for i in range(500)]
        enc2 = SnapshotEncoder(state, [gang2[0]])
        snap2 = enc2.encode_nodes()
        batch2 = enc2.encode_pods()
        w2 = WaveScheduler(min_run=16, max_j=128)
        out2, _c, _l = w2.schedule_backlog(
            snap2, batch2, np.zeros(500, np.int64),
            gangs=[{"start": 0, "length": 500, "score_add": None}],
        )
        assert (out2 < 0).all(), "oversized gang partially bound"

    def test_het_score_steers_gang_to_fast_accelerator(self):
        state = ClusterState.build([
            node("slow-0", cpu="8"), node("slow-1", cpu="8"),
            node("fast-0", cpu="8"),
        ])
        gang = [pod(f"g{i}", group="g1") for i in range(4)]
        algo = TPUScheduleAlgorithm(min_run=16)
        hosts = algo.schedule_backlog(
            gang, state,
            gangs=[{"start": 0, "length": 4,
                    "score_by_name": {"fast-0": 1000}}],
        )
        assert set(hosts) == {"fast-0"}


# -- preemption ---------------------------------------------------------------


def _ref_victims_needed(prio, ordn, res, free, req, gang_prio):
    """Numpy reference of the device scorer (the differential spec)."""
    N, C = prio.shape
    needed = np.full(N, -1, np.int64)
    for n in range(N):
        cands = [
            (int(prio[n, c]), -int(ordn[n, c]), c)
            for c in range(C) if prio[n, c] < gang_prio
        ]
        cands.sort()
        f = free[n].astype(np.int64).copy()
        if np.all(f >= req):
            needed[n] = 0
            continue
        for k, (_p, _o, c) in enumerate(cands):
            f += res[n, c]
            if np.all(f >= req):
                needed[n] = k + 1
                break
    return needed


class TestVictimScorer:
    def test_device_matches_numpy_reference_fuzz(self):
        rng = np.random.RandomState(99)
        scorer = VictimScorer()
        for _ in range(5):
            N, C = 8, 8
            prio = rng.randint(0, 5, (N, C)).astype(np.int32)
            prio[rng.rand(N, C) < 0.3] = INVALID_PRIO
            ordn = rng.permutation(N * C).reshape(N, C).astype(np.int32)
            res = rng.randint(0, 4, (N, C, 4)).astype(np.int64) * 250
            free = rng.randint(0, 4, (N, 4)).astype(np.int64) * 250
            req = np.array([500, 250, 0, 1], np.int64)
            gang_prio = int(rng.randint(1, 6))
            needed, cost, order = scorer.score(
                prio, ordn, res, free, req, gang_prio)
            want = _ref_victims_needed(prio, ordn, res, free, req,
                                       gang_prio)
            assert np.array_equal(needed.astype(np.int64), want)

    def test_invariant_no_equal_or_higher_priority_victims_fuzz(self):
        """Property (b): randomized clusters and priority mixes — the
        planned victim set NEVER contains an equal-or-higher-priority
        pod, and evictions only happen when they seat the whole
        gang."""
        rng = random.Random(1337)
        for trial in range(6):
            n_nodes = rng.randint(2, 5)
            nodes = [node(f"n{i:02d}", cpu="4") for i in range(n_nodes)]
            prios = [0, 10, 50, 100, 200]
            pgs, bound = [], []
            for g, pr in enumerate(prios):
                pgs.append(PodGroup(
                    metadata=ObjectMeta(name=f"grp-{g}"),
                    spec=PodGroupSpec(min_member=1, priority=pr),
                ))
            for i in range(rng.randint(2, 10)):
                g = rng.randrange(len(prios))
                b = pod(f"b{trial}-{i}",
                        cpu=f"{rng.choice([500, 1000, 2000])}m",
                        group=f"grp-{g}",
                        ts=f"2026-08-04T00:00:{i:02d}Z")
                b.spec.node_name = f"n{rng.randrange(n_nodes):02d}"
                bound.append(b)
            state = ClusterState.build(nodes, assigned_pods=bound)
            gang_prio = rng.choice([10, 50, 100, 200])
            evicted = []
            d = GangDirector(
                pod_group_lister=lambda pgs=pgs: pgs,
                preemptor=lambda vs: evicted.extend(vs),
            )
            members = [
                pod(f"m{trial}-{i}", cpu="2000m", group="grp-hi")
                for i in range(rng.randint(1, 4))
            ]
            entry = {"start": 0, "length": len(members),
                     "key": ("default", "grp-hi"),
                     "group": PodGroup(
                         metadata=ObjectMeta(name="grp-hi"),
                         spec=PodGroupSpec(priority=gang_prio)),
                     "priority": gang_prio, "score_by_name": None}
            d.after_wave(members, [None] * len(members), [entry], state)
            pg_map = {("default", p.metadata.name): p for p in pgs}
            for v in evicted:
                assert d._priority_of(v, pg_map) < gang_prio, (
                    f"trial {trial}: evicted {v.metadata.name} at "
                    f"priority {d._priority_of(v, pg_map)} for a "
                    f"priority-{gang_prio} gang"
                )

    def test_newest_first_tiebreak(self):
        """Among equal-priority victims on one node, the newest pod
        evicts first."""
        nodes = [node("n00", cpu="2")]
        old = pod("old", cpu="900m", group="low",
                  ts="2026-08-04T00:00:01Z")
        new = pod("new", cpu="900m", group="low",
                  ts="2026-08-04T00:00:59Z")
        old.spec.node_name = new.spec.node_name = "n00"
        state = ClusterState.build(nodes, assigned_pods=[old, new])
        pgs = [PodGroup(metadata=ObjectMeta(name="low"),
                        spec=PodGroupSpec(priority=0))]
        evicted = []
        d = GangDirector(pod_group_lister=lambda: pgs,
                         preemptor=lambda vs: evicted.extend(vs))
        member = pod("m0", cpu="900m", group="hi")
        entry = {"start": 0, "length": 1, "key": ("default", "hi"),
                 "group": PodGroup(metadata=ObjectMeta(name="hi"),
                                   spec=PodGroupSpec(priority=100)),
                 "priority": 100, "score_by_name": None}
        d.after_wave([member], [None], [entry], state)
        assert [v.metadata.name for v in evicted] == ["new"]


# -- director planning --------------------------------------------------------


class TestDirectorPlanning:
    def _director(self, pgs, statuses=None, evicted=None):
        return GangDirector(
            pod_group_lister=lambda: pgs,
            status_updater=(
                None if statuses is None
                else lambda ns, n, s: statuses.append((n, s))
            ),
            preemptor=(
                None if evicted is None
                else lambda vs: evicted.extend(vs)
            ),
        )

    def test_min_member_short_gang_parks_before_the_wave(self):
        pgs = [PodGroup(metadata=ObjectMeta(name="g1"),
                        spec=PodGroupSpec(min_member=4))]
        statuses = []
        d = self._director(pgs, statuses)
        state = ClusterState.build([node("n00")])
        wave = [pod("s0"), pod("g-0", group="g1"), pod("g-1", group="g1")]
        backlog, layout, parked = d.plan_wave(wave, state)
        assert [p.metadata.name for p in backlog] == ["s0"]
        assert layout == [] and len(parked) == 2
        assert all(isinstance(e, GangParked) for _p, e in parked)
        assert "have 2 of minMember 4" in str(parked[0][1])
        assert statuses[-1][1]["phase"] == "Parked"

    def test_priority_orders_gangs_singletons_first(self):
        pgs = [
            PodGroup(metadata=ObjectMeta(name="lo"),
                     spec=PodGroupSpec(min_member=1, priority=10)),
            PodGroup(metadata=ObjectMeta(name="hi"),
                     spec=PodGroupSpec(min_member=1, priority=100)),
        ]
        d = self._director(pgs)
        state = ClusterState.build([node("n00")])
        wave = ([pod(f"lo-{i}", group="lo") for i in range(2)]
                + [pod("s0")]
                + [pod(f"hi-{i}", group="hi") for i in range(2)])
        backlog, layout, parked = d.plan_wave(wave, state)
        names = [p.metadata.name for p in backlog]
        assert names[0] == "s0"
        assert names[1:3] == ["hi-0", "hi-1"]  # priority desc
        assert names[3:] == ["lo-0", "lo-1"]
        assert [(g["start"], g["length"]) for g in layout] == [
            (1, 2), (3, 2)
        ]
        assert not parked

    def test_wave_without_gangs_is_untouched(self):
        d = self._director([])
        state = ClusterState.build([node("n00")])
        wave = [pod("a"), pod("b")]
        backlog, layout, parked = d.plan_wave(wave, state)
        assert backlog == wave and layout == [] and parked == []


# -- end to end ---------------------------------------------------------------


class TestGangEndToEnd:
    def test_gang_lifecycle_with_tpu_daemon(self):
        """One live session covers: atomic gang bind, minMember
        parking with a readable status, and priority preemption
        unparking a high-priority gang."""
        from kubernetes_tpu.scheduler.server import (
            SchedulerServer,
            SchedulerServerOptions,
        )

        server, client = make_control_plane()
        for i in range(2):
            client.nodes().create(node(f"n{i}", cpu="2", pods="8"))
        pgr = client.resource("podgroups", "default")
        pgr.create(PodGroup(metadata=ObjectMeta(name="fit"),
                            spec=PodGroupSpec(min_member=4)))
        pgr.create(PodGroup(metadata=ObjectMeta(name="waiting"),
                            spec=PodGroupSpec(min_member=3)))
        client.resource("priorityclasses").create(PriorityClass(
            metadata=ObjectMeta(name="urgent"), value=100))
        pgr.create(PodGroup(
            metadata=ObjectMeta(name="burst"),
            spec=PodGroupSpec(min_member=2,
                              priority_class_name="urgent")))
        options = SchedulerServerOptions(
            algorithm_provider=algorithmprovider.TPU_PROVIDER_NAME
        )
        srv = SchedulerServer(client, options).start()
        try:
            # 1) a fitting gang binds atomically
            for i in range(4):
                client.pods().create(pod(f"fit-{i}", cpu="400m",
                                         group="fit"))
            assert wait_until(
                lambda: all(p.spec.node_name for p in
                            client.pods().list(
                                label_selector="app=fit")[0]),
                timeout=40.0,
            )
            assert wait_until(
                lambda: pgr.get("fit").status.phase == "Scheduled",
                timeout=10.0,
            )
            # 2) a minMember-short gang parks with a readable status
            client.pods().create(pod("waiting-0", cpu="100m",
                                     group="waiting"))
            assert wait_until(
                lambda: pgr.get("waiting").status.phase == "Parked",
                timeout=20.0,
            )
            st = pgr.get("waiting").status
            assert "minMember 3" in st.message
            assert client.pods().get("waiting-0").spec.node_name == ""
            from kubernetes_tpu.kubectl.cmd import Kubectl

            desc = Kubectl(client).describe("podgroups", "waiting")
            assert "Parked:" in desc and "minMember 3" in desc
            # 3) fill the cluster with low-priority pods, then a
            # priority gang preempts its way in
            filler = []
            for i in range(2):
                f = pod(f"filler-{i}", cpu="1200m")
                client.pods().create(f)
                filler.append(f.metadata.name)
            assert wait_until(
                lambda: all(
                    client.pods().get(n).spec.node_name
                    for n in filler
                ),
                timeout=20.0,
            )
            for i in range(2):
                client.pods().create(pod(f"burst-{i}", cpu="1200m",
                                         group="burst"))
            # the fillers (priority 0) are evicted for the gang
            assert wait_until(
                lambda: all(
                    not any(p.metadata.name == n
                            for p in client.pods().list()[0])
                    for n in filler
                ),
                timeout=30.0,
            ), "low-priority fillers were not preempted"
            assert wait_until(
                lambda: all(p.spec.node_name for p in
                            client.pods().list(
                                label_selector="app=burst")[0]),
                timeout=30.0,
            ), "priority gang never bound after preemption"
            from kubernetes_tpu.metrics import (
                scheduler_preemption_victims_total,
            )

            assert scheduler_preemption_victims_total.total() >= 2
        finally:
            srv.stop()
