"""Versioned component configuration (pkg/apis/componentconfig):
daemon flags as a defaulted, validated API object loaded through the
versioned codec — not plain argv."""

import json

import pytest

from kubernetes_tpu.apis.componentconfig import (
    ComponentConfigError,
    GROUP_VERSION,
    KubeSchedulerConfiguration,
    KubeletConfiguration,
    load_component_config,
)
from kubernetes_tpu.apis.componentconfig import scheme as cc_scheme
from kubernetes_tpu.scheduler.server import SchedulerServerOptions


def write(tmp_path, body):
    p = tmp_path / "config.json"
    p.write_text(json.dumps(body))
    return str(p)


class TestLoadAndDefaulting:
    def test_sparse_file_fills_defaults(self, tmp_path):
        """The SetDefaults_* role: absent fields come back at their
        declared defaults."""
        path = write(tmp_path, {
            "apiVersion": GROUP_VERSION,
            "kind": "KubeSchedulerConfiguration",
            "algorithmProvider": "DefaultProvider",
        })
        cfg = load_component_config(path, "KubeSchedulerConfiguration")
        assert isinstance(cfg, KubeSchedulerConfiguration)
        assert cfg.algorithm_provider == "DefaultProvider"
        assert cfg.kube_api_qps == 50.0  # defaulted
        assert cfg.scheduler_name == "default-scheduler"
        assert cfg.leader_election.leader_elect is False
        assert "kubernetes.io/hostname" in cfg.failure_domains

    def test_yaml_form(self, tmp_path):
        p = tmp_path / "config.yaml"
        p.write_text(
            "apiVersion: componentconfig/v1alpha1\n"
            "kind: KubeletConfiguration\n"
            "nodeName: n1\n"
            "maxPods: 42\n"
        )
        cfg = load_component_config(str(p), "KubeletConfiguration")
        assert isinstance(cfg, KubeletConfiguration)
        assert (cfg.node_name, cfg.max_pods) == ("n1", 42)
        assert cfg.sync_frequency_seconds == 10.0  # defaulted

    def test_wrong_version_rejected(self, tmp_path):
        path = write(tmp_path, {
            "apiVersion": "componentconfig/v9",
            "kind": "KubeSchedulerConfiguration",
        })
        with pytest.raises(ComponentConfigError, match="apiVersion"):
            load_component_config(path, "KubeSchedulerConfiguration")

    def test_wrong_kind_rejected(self, tmp_path):
        path = write(tmp_path, {
            "apiVersion": GROUP_VERSION,
            "kind": "KubeletConfiguration",
        })
        with pytest.raises(ComponentConfigError, match="kind"):
            load_component_config(path, "KubeSchedulerConfiguration")

    def test_validation(self, tmp_path):
        path = write(tmp_path, {
            "apiVersion": GROUP_VERSION,
            "kind": "KubeSchedulerConfiguration",
            "kubeApiQps": -1,
        })
        with pytest.raises(ComponentConfigError, match="QPS"):
            load_component_config(path, "KubeSchedulerConfiguration")
        path = write(tmp_path, {
            "apiVersion": GROUP_VERSION,
            "kind": "KubeSchedulerConfiguration",
            "hardPodAffinitySymmetricWeight": 1000,
        })
        with pytest.raises(ComponentConfigError):
            load_component_config(path, "KubeSchedulerConfiguration")

    def test_wire_roundtrip(self):
        cfg = KubeSchedulerConfiguration(kube_api_qps=10.0)
        wire = cc_scheme.encode(cfg)
        assert wire["kind"] == "KubeSchedulerConfiguration"
        assert wire["apiVersion"] == GROUP_VERSION
        assert wire["kubeApiQps"] == 10.0
        back = cc_scheme.decode(wire)
        assert back == cfg

    def test_core_scheme_not_polluted(self):
        # componentconfig kinds ride their own codec; the apiserver's
        # v1 scheme must not learn them (a stray document with this
        # kind should be rejected by the core codec)
        from kubernetes_tpu.runtime.scheme import scheme as core

        assert core.type_for("KubeSchedulerConfiguration") is None


class TestDaemonEmbedding:
    def test_scheduler_options_from_config_file(self, tmp_path):
        """options.go:31: the daemon's options embed the versioned
        configuration object."""
        path = write(tmp_path, {
            "apiVersion": GROUP_VERSION,
            "kind": "KubeSchedulerConfiguration",
            "algorithmProvider": "DefaultProvider",
            "schedulerName": "alt-scheduler",
            "hardPodAffinitySymmetricWeight": 7,
            "leaderElection": {"leaderElect": True},
        })
        opts = SchedulerServerOptions.from_config_file(path)
        assert opts.algorithm_provider == "DefaultProvider"
        assert opts.scheduler_name == "alt-scheduler"
        assert opts.hard_pod_affinity_symmetric_weight == 7
        assert opts.leader_elect is True
        assert opts.kube_api_qps == 50.0  # defaulted through the object

    def test_config_drives_a_live_daemon(self, tmp_path):
        """End to end: a versioned config file configures a running
        scheduler daemon (scheduler_name selects which pods it owns)."""
        import time

        from kubernetes_tpu.api.types import (
            SCHEDULER_NAME_ANNOTATION,
            Container,
            Node,
            NodeCondition,
            NodeStatus,
            ObjectMeta,
            Pod,
            PodSpec,
        )
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import LocalTransport
        from kubernetes_tpu.scheduler.server import SchedulerServer

        path = write(tmp_path, {
            "apiVersion": GROUP_VERSION,
            "kind": "KubeSchedulerConfiguration",
            "algorithmProvider": "DefaultProvider",
            "schedulerName": "alt-scheduler",
        })
        server = APIServer()
        client = RESTClient(LocalTransport(server))
        client.nodes().create(Node(
            metadata=ObjectMeta(name="n1", namespace=""),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        ))
        sched = SchedulerServer(
            client, SchedulerServerOptions.from_config_file(path)
        ).start()
        try:
            client.pods().create(Pod(
                metadata=ObjectMeta(name="mine", annotations={
                    SCHEDULER_NAME_ANNOTATION: "alt-scheduler"}),
                spec=PodSpec(containers=[Container(
                    requests={"cpu": "100m"})]),
            ))
            client.pods().create(Pod(
                metadata=ObjectMeta(name="not-mine"),
                spec=PodSpec(containers=[Container(
                    requests={"cpu": "100m"})]),
            ))
            deadline = time.time() + 30
            while time.time() < deadline:
                if client.pods().get("mine").spec.node_name:
                    break
                time.sleep(0.1)
            assert client.pods().get("mine").spec.node_name == "n1"
            # the default-scheduler pod is NOT this daemon's
            # responsibility (factory.go:404 responsibleForPod)
            assert client.pods().get("not-mine").spec.node_name == ""
        finally:
            sched.stop()
