"""Chaos-scenario smokes over the wire-soak harness (harness/soak.py).

Each named ``--wire-soak`` scenario runs here as its tier-1 fast-smoke
variant — the SAME config and gates bench.py runs, at CI-sized
seconds/nodes/rates — so a scenario that rots fails the suite, not an
operator's overnight run. The production-realism (hours-long / A/B)
forms are ``slow``-marked below and excluded from ``-m 'not slow'``.
"""

import json
import os
import sys

import pytest

from kubernetes_tpu.harness.soak import (
    SCENARIOS,
    SoakConfig,
    run_wire_soak,
    scenario_config,
)


# the smokes gate on latency/recompile/RSS budgets; an armed sanitizer
# adds ~27% instrumentation overhead, so a failure there would indict
# the overhead, not a regression. The witness invocation covers the
# APF queue/dispatch machinery through tests/test_flowcontrol.py.
pytestmark = pytest.mark.skipif(
    bool(os.environ.get("KUBERNETES_TPU_RACE_SANITIZER"))
    or bool(os.environ.get("KUBERNETES_TPU_LOCK_SANITIZER")),
    reason="perf-gated soak smokes are not valid under armed sanitizers",
)


def _run(cfg):
    rec = run_wire_soak(cfg)
    if not rec["ok"]:
        breached = [k for k, v in rec["gates"].items() if not v]
        print(json.dumps(rec, indent=1), file=sys.stderr)
        pytest.fail(f"scenario gate breach: {breached}")
    return rec


def test_scenario_table_is_complete():
    assert set(SCENARIOS) == {
        "noisy-neighbor", "rack-failure", "rolling-update", "burst",
        "process-kill"}
    for name, forms in SCENARIOS.items():
        assert set(forms) == {"full", "smoke"}, name
    with pytest.raises(ValueError):
        scenario_config("no-such-scenario", 30)


def test_noisy_neighbor_smoke():
    """1 abusive flow + N well-behaved flows: the abuser eats 429s,
    the well-behaved flows shed nothing, the (exempt) scheduler's p99
    holds, and exempt traffic measurably never queued."""
    cfg = scenario_config("noisy-neighbor", 40, smoke=True,
                          num_nodes=50, rate=30.0)
    rec = _run(cfg)
    assert rec["scenario_accounting"]["throttled"] > 0
    assert rec["creator_sheds"] == 0
    assert rec["flowcontrol"]["exempt_wait_sum_seconds"] <= 1e-3
    assert rec["flowcontrol"]["rejected_requests_total"] > 0


@pytest.mark.slow
def test_rack_failure_smoke():
    """A rack of hollow nodes vanishes mid-soak: the node-lifecycle
    controller completes the eviction wave under the declared SLO, the
    pow2 node bucket holds (zero recompiles), and arrivals keep
    binding to the survivors.

    Slow-marked (round 14 tier-1 budget reclaim): the 45s soak rides
    the slow lane with the full forms; tier-1 keeps the
    noisy-neighbor + burst smokes for the APF/soak interplay."""
    cfg = scenario_config("rack-failure", 45, smoke=True)
    rec = _run(cfg)
    acct = rec["scenario_accounting"]
    assert acct["nodes_failed"] == 30
    assert acct["eviction_wave_seconds"] is not None
    assert acct["stranded_pods_at_stop"] == 0
    assert rec["steady_state_compiles"] == 0


@pytest.mark.slow
def test_rolling_update_smoke():
    """A multi-step RC roll v1->v2 through the real ReplicationManager
    completes under its SLO with every v2 replica bound, while soak
    traffic keeps meeting the p99 gate.

    Slow-marked (round 14 tier-1 budget reclaim): the 60s soak was the
    heaviest tier-1 smoke; it rides the slow lane with the full
    forms."""
    cfg = scenario_config("rolling-update", 60, smoke=True)
    rec = _run(cfg)
    acct = rec["scenario_accounting"]
    assert acct["v2_bound_at_finish"] == acct["replicas"]
    assert acct["rolling_update_seconds"] is not None


def test_burst_smoke():
    """A 10x Poisson spike: the queues absorb it (zero sheds, zero
    drops) and p99 recovers to the SLO after the burst drains."""
    cfg = scenario_config("burst", 38, smoke=True)
    rec = _run(cfg)
    acct = rec["scenario_accounting"]
    assert acct["burst_window_binds"] > 0
    assert acct["p99_recovered_seconds"] is not None
    assert rec["creator_sheds"] == 0
    assert rec["watch_events_dropped"] == 0


# -- production-realism forms (excluded from tier-1 via -m 'not slow') --------


@pytest.mark.slow
def test_noisy_neighbor_full_with_ab_protection_proof():
    """The full unpaced flood, twice: APF on must hold the SLO while
    the abuser eats 429s, and the APF-off control arm must demonstrably
    breach — the gate proves APF causes the protection."""
    cfg = scenario_config("noisy-neighbor", 300, ab_compare=True)
    rec = _run(cfg)
    assert rec["gates"]["apf_protection_demonstrated"]


@pytest.mark.slow
def test_rack_failure_full():
    """500 of 2000 hollow nodes vanish (same pow2 bucket by design)."""
    _run(scenario_config("rack-failure", 600))


@pytest.mark.slow
def test_rolling_update_full():
    """A 1k-replica RC rolls v1->v2 in 100-replica steps."""
    _run(scenario_config("rolling-update", 900))


@pytest.mark.slow
def test_burst_full():
    """10x of 300/s for 10s: ~30k extra pods absorbed, p99 recovers."""
    _run(scenario_config("burst", 300))
