"""Conformance tests for the sequential oracle.

Scenario tables re-derived from the reference's table-driven unit tests
(plugin/pkg/scheduler/algorithm/priorities/priorities_test.go,
predicates/predicates_test.go, generic_scheduler_test.go) — the tables are
the conformance corpus; the test code is new (SURVEY.md §4.1).
"""

import pytest

from kubernetes_tpu.api.types import (
    Container,
    ContainerPort,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    Service,
    ServiceSpec,
    Taint,
    Toleration,
)
from kubernetes_tpu.oracle import (
    ClusterState,
    FitError,
    GenericScheduler,
    select_host,
)
from kubernetes_tpu.oracle import predicates as preds
from kubernetes_tpu.oracle import priorities as prios
from kubernetes_tpu.oracle.scheduler import PriorityConfig, prioritize_nodes


def make_node(name, mcpu, mem, pods=110, labels=None, conditions=None, taints=None):
    return Node(
        metadata=ObjectMeta(name=name, labels=labels or {}),
        spec=NodeSpec(taints=taints),
        status=NodeStatus(
            capacity={"cpu": f"{mcpu}m", "memory": str(mem), "pods": str(pods)},
            allocatable={"cpu": f"{mcpu}m", "memory": str(mem), "pods": str(pods)},
            conditions=conditions or [NodeCondition("Ready", "True")],
        ),
    )


def make_pod(name, node_name="", containers=None, labels=None, ns="default", **kw):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns, labels=labels or {}),
        spec=PodSpec(node_name=node_name, containers=containers or [], **kw),
    )


def resource_pod(name, node_name, *reqs):
    return make_pod(
        name, node_name, containers=[Container(requests=dict(r)) for r in reqs]
    )


class TestSelectHost:
    def test_round_robin_over_ties(self):
        # generic_scheduler.go:119 — ties ordered host-name DESC after
        # sort.Reverse; index lastNodeIndex % numTies.
        plist = [("machine1", 5), ("machine2", 5), ("machine3", 3)]
        assert select_host(plist, 0) == "machine2"  # desc order: m2, m1
        assert select_host(plist, 1) == "machine1"
        assert select_host(plist, 2) == "machine2"

    def test_single_max(self):
        plist = [("a", 1), ("b", 7), ("c", 3)]
        for i in range(5):
            assert select_host(plist, i) == "b"

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            select_host([], 0)


class TestPodFitsResources:
    # table re-derived from predicates_test.go TestPodFitsResources
    def _state(self, existing_mcpu, existing_mem, cap_mcpu=10000, cap_mem=20):
        node = make_node("machine1", cap_mcpu, cap_mem)
        st = ClusterState.build([node])
        if existing_mcpu or existing_mem:
            st.assign(
                resource_pod(
                    "existing",
                    "machine1",
                    {"cpu": f"{existing_mcpu}m", "memory": str(existing_mem)},
                )
            )
        return st

    def test_no_resources_pod_fits_anywhere(self):
        st = self._state(10000, 20)
        pod = make_pod("p")  # zero-request -> early true (predicates.go:429)
        fit, _ = preds.pod_fits_resources(pod, st.node_infos["machine1"], st)
        assert fit

    def test_too_many_pods(self):
        node = make_node("machine1", 4000, 10**9, pods=1)
        st = ClusterState.build([node])
        st.assign(make_pod("e", "machine1"))
        fit, reason = preds.pod_fits_resources(
            make_pod("p"), st.node_infos["machine1"], st
        )
        assert not fit
        assert "PodCount" in reason

    @pytest.mark.parametrize(
        "pod_cpu,pod_mem,used_cpu,used_mem,fits,resource",
        [
            (1000, 1, 10000, 20, False, "CPU"),  # cpu overcommit
            (1000, 1, 9000, 19, True, None),
            (1000, 2, 9000, 19, False, "Memory"),  # mem overcommit
            (0, 0, 10000, 20, True, None),  # zero-request early exit
        ],
    )
    def test_fit_matrix(self, pod_cpu, pod_mem, used_cpu, used_mem, fits, resource):
        st = self._state(used_cpu, used_mem)
        pod = resource_pod("p", "", {"cpu": f"{pod_cpu}m", "memory": str(pod_mem)})
        fit, reason = preds.pod_fits_resources(pod, st.node_infos["machine1"], st)
        assert fit == fits
        if resource:
            assert resource in reason

    def test_init_container_max_rule(self):
        st = self._state(9000, 19)
        pod = make_pod(
            "p",
            containers=[Container(requests={"cpu": "500m", "memory": "1"})],
            init_containers=[Container(requests={"cpu": "2000m", "memory": "1"})],
        )
        fit, reason = preds.pod_fits_resources(pod, st.node_infos["machine1"], st)
        assert not fit  # init max 2000m > 1000m headroom
        assert "CPU" in reason


class TestHostPortsAndHostName:
    def test_host_port_conflict(self):
        node = make_node("m1", 4000, 10**10)
        st = ClusterState.build([node])
        st.assign(
            make_pod(
                "e",
                "m1",
                containers=[Container(ports=[ContainerPort(host_port=8080)])],
            )
        )
        pod = make_pod(
            "p", containers=[Container(ports=[ContainerPort(host_port=8080)])]
        )
        fit, reason = preds.pod_fits_host_ports(pod, st.node_infos["m1"], st)
        assert not fit and reason == preds.ERR_POD_NOT_FITS_HOST_PORTS
        pod2 = make_pod(
            "p2", containers=[Container(ports=[ContainerPort(host_port=8081)])]
        )
        fit, _ = preds.pod_fits_host_ports(pod2, st.node_infos["m1"], st)
        assert fit

    def test_port_zero_ignored(self):
        node = make_node("m1", 4000, 10**10)
        st = ClusterState.build([node])
        pod = make_pod("p", containers=[Container(ports=[ContainerPort(host_port=0)])])
        fit, _ = preds.pod_fits_host_ports(pod, st.node_infos["m1"], st)
        assert fit

    def test_pod_fits_host(self):
        node = make_node("m1", 4000, 10**10)
        st = ClusterState.build([node])
        assert preds.pod_fits_host(make_pod("p"), st.node_infos["m1"], st)[0]
        assert preds.pod_fits_host(
            make_pod("p", node_name="m1"), st.node_infos["m1"], st
        )[0]
        fit, reason = preds.pod_fits_host(
            make_pod("p", node_name="other"), st.node_infos["m1"], st
        )
        assert not fit and reason == preds.ERR_POD_NOT_MATCH_HOST_NAME


class TestNodeSelector:
    def test_node_selector_match(self):
        node = make_node("m1", 4000, 10**10, labels={"zone": "us-1", "disk": "ssd"})
        st = ClusterState.build([node])
        ok = make_pod("p", node_selector={"zone": "us-1"})
        fit, _ = preds.pod_selector_matches(ok, st.node_infos["m1"], st)
        assert fit
        bad = make_pod("p", node_selector={"zone": "eu-1"})
        fit, reason = preds.pod_selector_matches(bad, st.node_infos["m1"], st)
        assert not fit and reason == preds.ERR_NODE_SELECTOR_NOT_MATCH


class TestTaintsTolerations:
    def _st(self, taints):
        node = make_node("m1", 4000, 10**10, taints=taints)
        return ClusterState.build([node])

    def test_no_taints_tolerated_by_all(self):
        st = self._st([])
        fit, _ = preds.pod_tolerates_node_taints(
            make_pod("p"), st.node_infos["m1"], st
        )
        assert fit

    def test_untolerated_taint(self):
        st = self._st([Taint(key="dedicated", value="infra", effect="NoSchedule")])
        fit, reason = preds.pod_tolerates_node_taints(
            make_pod("p"), st.node_infos["m1"], st
        )
        assert not fit and reason == preds.ERR_TAINTS_TOLERATIONS_NOT_MATCH

    def test_equal_toleration(self):
        st = self._st([Taint(key="dedicated", value="infra", effect="NoSchedule")])
        pod = make_pod(
            "p",
            tolerations=[
                Toleration(key="dedicated", operator="Equal", value="infra", effect="NoSchedule")
            ],
        )
        assert preds.pod_tolerates_node_taints(pod, st.node_infos["m1"], st)[0]

    def test_exists_toleration_any_value(self):
        st = self._st([Taint(key="dedicated", value="x", effect="NoSchedule")])
        pod = make_pod("p", tolerations=[Toleration(key="dedicated", operator="Exists")])
        assert preds.pod_tolerates_node_taints(pod, st.node_infos["m1"], st)[0]

    def test_prefer_no_schedule_skipped_but_empty_tolerations_reject(self):
        # quirk (predicates.go:979-1002): non-empty taints + empty
        # tolerations -> reject even if all taints are PreferNoSchedule
        st = self._st([Taint(key="k", value="v", effect="PreferNoSchedule")])
        fit, _ = preds.pod_tolerates_node_taints(make_pod("p"), st.node_infos["m1"], st)
        assert not fit
        # but with ANY toleration present, PreferNoSchedule taints are skipped
        pod = make_pod("p", tolerations=[Toleration(key="other", operator="Exists")])
        assert preds.pod_tolerates_node_taints(pod, st.node_infos["m1"], st)[0]


class TestMemoryPressure:
    def test_best_effort_rejected_under_pressure(self):
        node = make_node(
            "m1",
            4000,
            10**10,
            conditions=[
                NodeCondition("Ready", "True"),
                NodeCondition("MemoryPressure", "True"),
            ],
        )
        st = ClusterState.build([node])
        best_effort = make_pod("p", containers=[Container()])
        fit, reason = preds.check_node_memory_pressure(
            best_effort, st.node_infos["m1"], st
        )
        assert not fit and reason == preds.ERR_NODE_UNDER_MEMORY_PRESSURE
        burstable = resource_pod("p2", "", {"cpu": "100m"})
        fit, _ = preds.check_node_memory_pressure(burstable, st.node_infos["m1"], st)
        assert fit


class TestLeastRequested:
    # priorities_test.go TestLeastRequested tables (comments give the math)
    def test_nothing_scheduled_nothing_requested(self):
        st = ClusterState.build(
            [make_node("machine1", 4000, 10000), make_node("machine2", 4000, 10000)]
        )
        pod = make_pod("p", containers=[])
        assert prios.least_requested_priority(pod, st) == {
            "machine1": 10,
            "machine2": 10,
        }

    def test_differently_sized_machines(self):
        st = ClusterState.build(
            [make_node("machine1", 4000, 10000), make_node("machine2", 6000, 10000)]
        )
        pod = make_pod(
            "p",
            containers=[
                Container(requests={"cpu": "1000m", "memory": "2000"}),
                Container(requests={"cpu": "2000m", "memory": "3000"}),
            ],
        )
        assert prios.least_requested_priority(pod, st) == {
            "machine1": 3,  # (2.5 + 5)/2 -> int
            "machine2": 5,
        }

    def test_pods_scheduled_with_resources(self):
        cpu_only = [
            Container(requests={"cpu": "1000m", "memory": "0"}),
            Container(requests={"cpu": "2000m", "memory": "0"}),
        ]
        cpu_mem = [
            Container(requests={"cpu": "1000m", "memory": "2000"}),
            Container(requests={"cpu": "2000m", "memory": "3000"}),
        ]
        st = ClusterState.build(
            [make_node("machine1", 10000, 20000), make_node("machine2", 10000, 20000)],
            assigned_pods=[
                make_pod("a", "machine1", containers=cpu_only),
                make_pod("b", "machine1", containers=cpu_only),
                make_pod("c", "machine2", containers=cpu_only),
                make_pod("d", "machine2", containers=cpu_mem),
            ],
        )
        # wait: machine1 has cpuOnly twice? reference has cpuOnly (m1) x2? no:
        # table "no resources requested, pods scheduled with resources":
        # machine1: cpuOnly, cpuOnly(labels1) -> but cpuOnly.NodeName=machine1
        # machine2: cpuOnly2, cpuAndMemory
        pod = make_pod("p", containers=[])
        scores = prios.least_requested_priority(pod, st)
        # m1: cpu (10000-6000)*10/10000=4, mem (20000-0)*10/20000=10 -> 7
        # m2: cpu 4, mem (20000-5000)*10/20000=7.5 -> int((4+7.5)/2)=5
        assert scores == {"machine1": 7, "machine2": 5}


class TestBalancedResourceAllocation:
    def test_balanced(self):
        st = ClusterState.build(
            [make_node("machine1", 4000, 10000), make_node("machine2", 4000, 10000)]
        )
        pod = make_pod(
            "p",
            containers=[
                Container(requests={"cpu": "1000m", "memory": "2000"}),
                Container(requests={"cpu": "2000m", "memory": "3000"}),
            ],
        )
        scores = prios.balanced_resource_allocation(pod, st)
        # cpuFrac=3000/4000=.75, memFrac=5000/10000=.5 -> 10-2.5 -> 7
        assert scores == {"machine1": 7, "machine2": 7}

    def test_overcommit_scores_zero(self):
        st = ClusterState.build([make_node("machine1", 1000, 10000)])
        pod = make_pod("p", containers=[Container(requests={"cpu": "2000m", "memory": "1"})])
        assert prios.balanced_resource_allocation(pod, st) == {"machine1": 0}


class TestSelectorSpread:
    def test_spread_across_nodes(self):
        # selector_spreading_test.go idiom: service pods spread
        labels1 = {"foo": "bar"}
        st = ClusterState.build(
            [make_node("machine1", 4000, 10**9), make_node("machine2", 4000, 10**9)],
            assigned_pods=[make_pod("e1", "machine1", labels=labels1)],
            services=[
                Service(
                    metadata=ObjectMeta(name="s"),
                    spec=ServiceSpec(selector={"foo": "bar"}),
                )
            ],
        )
        pod = make_pod("p", labels=labels1)
        scores = prios.selector_spread_priority(pod, st)
        # machine1 hosts 1 matching pod (max), machine2 hosts 0
        assert scores == {"machine1": 0, "machine2": 10}

    def test_no_selectors_all_max(self):
        st = ClusterState.build(
            [make_node("m1", 4000, 10**9), make_node("m2", 4000, 10**9)]
        )
        pod = make_pod("p", labels={"a": "b"})
        assert prios.selector_spread_priority(pod, st) == {"m1": 10, "m2": 10}


class TestGenericScheduler:
    def test_schedules_to_least_loaded(self):
        st = ClusterState.build(
            [make_node("m1", 4000, 10**10), make_node("m2", 4000, 10**10)],
            assigned_pods=[resource_pod("e", "m1", {"cpu": "3000m", "memory": "1000"})],
        )
        sched = GenericScheduler()
        pod = resource_pod("p", "", {"cpu": "500m", "memory": "500"})
        assert sched.schedule(pod, st) == "m2"

    def test_fit_error_when_nothing_fits(self):
        st = ClusterState.build([make_node("m1", 100, 10**10)])
        sched = GenericScheduler()
        pod = resource_pod("p", "", {"cpu": "4000m"})
        with pytest.raises(FitError) as ei:
            sched.schedule(pod, st)
        assert "failed to fit" in str(ei.value)

    def test_backlog_round_robin_on_identical_nodes(self):
        # the scheduler_perf shape: identical nodes, identical pods.
        # Everything ties; selection must walk nodes round-robin by
        # host-name-desc order, shifted by one each cycle.
        nodes = [make_node(f"node-{i}", 4000, 32 * 1024**3) for i in range(4)]
        st = ClusterState.build(nodes)
        sched = GenericScheduler()
        pods = [
            resource_pod(f"p{i}", "", {"cpu": "100m", "memory": "500Mi"})
            for i in range(8)
        ]
        got = sched.schedule_backlog(pods, st)
        assert None not in got
        # pods spread: no node should get more than 2 of the 8 pods
        from collections import Counter

        counts = Counter(got)
        assert all(v == 2 for v in counts.values())

    def test_backlog_commitment_affects_following_pods(self):
        # second pod must see first pod's assumed resources
        st = ClusterState.build(
            [make_node("m1", 1000, 10**10), make_node("m2", 900, 10**10)]
        )
        sched = GenericScheduler()
        pods = [
            resource_pod("p1", "", {"cpu": "800m"}),
            resource_pod("p2", "", {"cpu": "800m"}),
        ]
        got = sched.schedule_backlog(pods, st)
        assert got[0] == "m1"  # more free cpu
        assert got[1] == "m2"  # m1 now committed


class TestPrioritizeNodesCombined:
    # priorities_test.go:53-161 TestZeroRequest, exact table: nodes of
    # 1000m / DefaultMemoryRequest*10; machine1 holds large+zero-request,
    # machine2 holds large+small; default LR+BR+Spread stack.
    DMEM = 200 * 1024 * 1024

    def _state(self):
        large = {"cpu": "300m", "memory": str(3 * self.DMEM)}
        small = {"cpu": "100m", "memory": str(self.DMEM)}
        return ClusterState.build(
            [
                make_node("machine1", 1000, self.DMEM * 10),
                make_node("machine2", 1000, self.DMEM * 10),
            ],
            assigned_pods=[
                resource_pod("l1", "machine1", large),
                make_pod("z1", "machine1", containers=[Container()]),
                resource_pod("l2", "machine2", large),
                resource_pod("s2", "machine2", small),
            ],
        )

    def _configs(self):
        return [
            PriorityConfig(prios.least_requested_priority, 1, "LeastRequested"),
            PriorityConfig(prios.balanced_resource_allocation, 1, "Balanced"),
            PriorityConfig(prios.selector_spread_priority, 1, "Spread"),
        ]

    def test_zero_request_pod_scores_25(self):
        st = self._state()
        pod = make_pod("p", containers=[Container()])
        plist = dict(prioritize_nodes(pod, st, self._configs(), ["machine1", "machine2"]))
        assert plist == {"machine1": 25, "machine2": 25}

    def test_small_pod_scores_25(self):
        st = self._state()
        pod = resource_pod("p", "", {"cpu": "100m", "memory": str(self.DMEM)})
        plist = dict(prioritize_nodes(pod, st, self._configs(), ["machine1", "machine2"]))
        assert plist == {"machine1": 25, "machine2": 25}

    def test_large_pod_not_25(self):
        st = self._state()
        pod = resource_pod("p", "", {"cpu": "300m", "memory": str(3 * self.DMEM)})
        plist = dict(prioritize_nodes(pod, st, self._configs(), ["machine1", "machine2"]))
        assert plist["machine1"] != 25 and plist["machine2"] != 25
