"""Client layer: REST verbs, reflector/informer sync, FIFO semantics,
events, leader election (reference: pkg/client/* test idioms)."""

import threading
import time

import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client import LocalTransport, RESTClient
from kubernetes_tpu.client.cache import DeltaFIFO, FIFO, Reflector, Store
from kubernetes_tpu.client.cache.listers import (
    StoreToServiceLister,
    fake_service_lister,
)
from kubernetes_tpu.client.informer import Informer, ResourceEventHandler
from kubernetes_tpu.client.leaderelection import LeaderElector
from kubernetes_tpu.client.record import EventBroadcaster, EventSink, FakeRecorder


def make_client():
    server = APIServer()
    return server, RESTClient(LocalTransport(server))


def pod(name, ns="default", labels=None, node=""):
    return t.Pod(
        metadata=t.ObjectMeta(name=name, namespace=ns, labels=labels or {}),
        spec=t.PodSpec(node_name=node, containers=[t.Container(name="c")]),
    )


class TestRESTClient:
    def test_create_get_list_delete(self):
        _, c = make_client()
        c.pods().create(pod("a", labels={"app": "x"}))
        c.pods().create(pod("b"))
        got = c.pods().get("a")
        assert got.metadata.name == "a"
        items, rv = c.pods().list(label_selector="app=x")
        assert [p.metadata.name for p in items] == ["a"]
        assert int(rv) > 0
        c.pods().delete("b")
        items, _ = c.pods().list()
        assert [p.metadata.name for p in items] == ["a"]

    def test_field_selector_unassigned(self):
        _, c = make_client()
        c.pods().create(pod("u1"))
        c.pods().create(pod("a1", node="n1"))
        items, _ = c.pods().list(field_selector="spec.nodeName==")
        assert [p.metadata.name for p in items] == ["u1"]

    def test_bind(self):
        _, c = make_client()
        c.pods().create(pod("p"))
        c.pods().bind("p", "node-1")
        assert c.pods().get("p").spec.node_name == "node-1"

    def test_status_update_isolated(self):
        _, c = make_client()
        c.nodes().create(t.Node(metadata=t.ObjectMeta(name="n1")))
        n = c.nodes().get("n1")
        n.status.allocatable = {"cpu": "4"}
        c.nodes().update_status(n)
        assert c.nodes().get("n1").status.allocatable["cpu"] == "4"


class TestFIFO:
    def test_coalesce_and_order(self):
        q = FIFO()
        q.add(pod("a"))
        q.add(pod("b"))
        q.add(pod("a", labels={"v": "2"}))  # coalesces, keeps position
        first = q.pop()
        assert first.metadata.name == "a"
        assert first.metadata.labels == {"v": "2"}
        assert q.pop().metadata.name == "b"

    def test_delete_skips(self):
        q = FIFO()
        q.add(pod("a"))
        q.add(pod("b"))
        q.delete(pod("a"))
        assert q.pop().metadata.name == "b"

    def test_delta_fifo_synthesizes_deletes_on_replace(self):
        store = Store()
        store.add(pod("gone"))
        q = DeltaFIFO(known_objects=store)
        q.replace([pod("kept")])
        seen = {}
        for _ in range(2):
            key, deltas = q.pop(timeout=1)
            seen[key] = [d.type for d in deltas]
        assert seen["default/kept"] == ["Sync"]
        assert seen["default/gone"] == ["Deleted"]


class TestReflectorInformer:
    def test_reflector_mirrors_store(self):
        server, c = make_client()
        c.pods().create(pod("pre"))
        store = Store()
        r = Reflector(c.pods(), store).run()
        assert r.wait_for_sync()
        assert [p.metadata.name for p in store.list()] == ["pre"]
        c.pods().create(pod("live"))
        deadline = time.monotonic() + 5
        while len(store) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sorted(p.metadata.name for p in store.list()) == ["live", "pre"]
        c.pods().delete("pre")
        while len(store) > 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert [p.metadata.name for p in store.list()] == ["live"]
        r.stop()

    def test_informer_handlers(self):
        server, c = make_client()
        adds, updates, deletes = [], [], []
        inf = Informer(
            c.pods(),
            ResourceEventHandler(
                on_add=lambda o: adds.append(o.metadata.name),
                on_update=lambda o, n: updates.append(n.metadata.name),
                on_delete=lambda o: deletes.append(o.metadata.name),
            ),
        ).run()
        assert inf.wait_for_sync()
        c.pods().create(pod("x"))
        p = c.pods().get("x")
        p.metadata.labels = {"touched": "yes"}
        c.pods().update(p)
        c.pods().delete("x")
        deadline = time.monotonic() + 5
        while len(deletes) < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert adds == ["x"]
        assert updates == ["x"]
        assert deletes == ["x"]
        inf.stop()

    def test_informer_selector_transition_becomes_delete(self):
        # MODIFIED out of the label selector arrives as DELETED
        # (etcd_watcher.go sendModify translation).
        server, c = make_client()
        deletes = []
        inf = Informer(
            c.pods(),
            ResourceEventHandler(on_delete=lambda o: deletes.append(o.metadata.name)),
            label_selector="app=y",
        ).run()
        assert inf.wait_for_sync()
        c.pods().create(pod("p", labels={"app": "y"}))
        deadline = time.monotonic() + 5
        while len(inf.store) < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        p = c.pods().get("p")
        p.metadata.labels = {}
        c.pods().update(p)
        while not deletes and time.monotonic() < deadline:
            time.sleep(0.01)
        assert deletes == ["p"]
        assert len(inf.store) == 0
        inf.stop()


class TestListers:
    def test_get_pod_services(self):
        svc = t.Service(
            metadata=t.ObjectMeta(name="s", namespace="default"),
            spec=t.ServiceSpec(selector={"app": "web"}),
        )
        other_ns = t.Service(
            metadata=t.ObjectMeta(name="s2", namespace="other"),
            spec=t.ServiceSpec(selector={"app": "web"}),
        )
        empty_sel = t.Service(
            metadata=t.ObjectMeta(name="s3", namespace="default"),
            spec=t.ServiceSpec(selector={}),
        )
        lister = fake_service_lister([svc, other_ns, empty_sel])
        matches = lister.get_pod_services(pod("p", labels={"app": "web"}))
        assert [s.metadata.name for s in matches] == ["s"]


class TestEvents:
    def test_sink_aggregates_duplicates(self):
        server, c = make_client()
        bcast = EventBroadcaster()
        bcast.start_recording_to_sink(EventSink(c))
        rec = bcast.new_recorder("scheduler")
        target = pod("p")
        rec.event(target, "Normal", "Scheduled", "bound to node-1")
        rec.event(target, "Normal", "Scheduled", "bound to node-1")
        # publishing is async (bounded queue, like the reference's
        # watch.Broadcaster): poll for delivery
        import time as _time

        deadline = _time.time() + 5.0
        events = []
        while _time.time() < deadline:
            events, _ = c.events().list()
            if len(events) == 1 and events[0].count == 2:
                break
            _time.sleep(0.01)
        assert len(events) == 1
        assert events[0].count == 2
        assert events[0].reason == "Scheduled"

    def test_fake_recorder(self):
        rec = FakeRecorder()
        rec.eventf(pod("p"), "Warning", "FailedScheduling", "no fit: %s", "cpu")
        assert rec.events == ["Warning FailedScheduling no fit: cpu"]

    def test_broadcaster_shutdown_is_idempotent(self):
        bcast = EventBroadcaster()
        seen = []
        bcast._add(seen.append)
        rec = bcast.new_recorder("c")
        rec.event(pod("p"), "Normal", "R", "m")
        bcast.shutdown()
        # a second (and third) shutdown must return immediately instead
        # of enqueueing sentinels nobody drains
        t0 = time.time()
        bcast.shutdown()
        bcast.shutdown()
        assert time.time() - t0 < 1.0
        assert len(seen) == 1
        # post-shutdown records are dropped, not resurrected
        rec.event(pod("p"), "Normal", "R", "m2")
        assert len(seen) == 1

    def test_pending_queue_is_bounded_with_dead_sink(self):
        # a sink that never drains must not let the pending queue grow
        # without bound: the broadcaster drops (DropIfChannelFull), so
        # memory stays capped at QUEUE_LEN
        bcast = EventBroadcaster()
        blocker = threading.Event()

        def stuck_sink(ev):
            blocker.wait(30.0)

        bcast._add(stuck_sink)
        rec = bcast.new_recorder("c")
        for i in range(EventBroadcaster.QUEUE_LEN * 3):
            rec.event(pod(f"p{i}"), "Normal", "R", "m")
        assert bcast._queue.qsize() <= EventBroadcaster.QUEUE_LEN
        blocker.set()
        bcast.shutdown()

    def test_correlator_aggregates_identical_events(self):
        from kubernetes_tpu.client.record import EventCorrelator

        corr = EventCorrelator()
        rec_pod = pod("p")

        def ev(msg="same"):
            from kubernetes_tpu.client.record import (
                _now_iso,
                object_reference,
            )

            return t.Event(
                metadata=t.ObjectMeta(name="p.1", namespace="default"),
                involved_object=object_reference(rec_pod),
                reason="Scheduled",
                message=msg,
                source_component="scheduler",
                first_timestamp=_now_iso(),
                last_timestamp=_now_iso(),
                count=1,
                type="Normal",
            )

        first = corr.correlate(ev())
        assert first is not None and first.count == 1
        for i in range(2, 6):
            dup = corr.correlate(ev())
            assert dup is not None
            assert dup.count == i
            # every duplicate aggregates onto the FIRST event's name —
            # one store object, not one per occurrence
            assert dup.metadata.name == first.metadata.name
            assert dup.first_timestamp == first.first_timestamp
        # a different message is a different logical event
        other = corr.correlate(ev("different"))
        assert other.count == 1

    def test_spam_filter_token_refill(self):
        from kubernetes_tpu.client.record import EventSpamFilter

        clock = [0.0]
        f = EventSpamFilter(burst=3, qps=0.5, clock=lambda: clock[0])
        ev = t.Event(
            metadata=t.ObjectMeta(name="e", namespace="default"),
            involved_object=t.ObjectReference(
                kind="Pod", namespace="default", name="p"
            ),
            reason="R", message="m", source_component="watchdog",
            first_timestamp="t", last_timestamp="t", count=1,
            type="Warning",
        )
        assert all(f.allow(ev) for _ in range(3))  # burst
        assert not f.allow(ev)  # bucket dry
        clock[0] = 2.0  # 2s * 0.5 qps = 1 token back
        assert f.allow(ev)
        assert not f.allow(ev)
        # an unrelated source+object has its own bucket
        other = t.Event(
            metadata=t.ObjectMeta(name="e2", namespace="default"),
            involved_object=t.ObjectReference(
                kind="Pod", namespace="default", name="q"
            ),
            reason="R", message="m", source_component="watchdog",
            first_timestamp="t", last_timestamp="t", count=1,
            type="Warning",
        )
        assert f.allow(other)

    def test_correlated_sink_drops_storm_before_store(self):
        # an event storm on ONE object passes the first `burst` events
        # then sheds the rest client-side: the store sees one aggregated
        # object, and the API is not flooded
        from kubernetes_tpu.client.record import (
            EventCorrelator,
            EventSpamFilter,
        )

        server, c = make_client()
        bcast = EventBroadcaster()
        corr = EventCorrelator(
            spam_filter=EventSpamFilter(burst=5, qps=0.0)
        )
        bcast.start_recording_to_sink(EventSink(c), correlator=corr)
        rec = bcast.new_recorder("slo-watchdog")
        target = pod("hot")
        for _ in range(50):
            rec.event(target, "Warning", "SLOBreach", "p99 over budget")
        deadline = time.time() + 5.0
        events = []
        while time.time() < deadline:
            events, _ = c.events().list()
            if len(events) == 1 and events[0].count >= 5:
                break
            time.sleep(0.01)
        assert len(events) == 1  # single store object
        assert events[0].count == 5  # burst passed, storm shed
        bcast.shutdown()


class TestLeaderElection:
    def test_single_winner_and_failover(self):
        server, c = make_client()
        order = []
        stop_a = threading.Event()

        def make(identity, started):
            return LeaderElector(
                c,
                "kube-system",
                "kube-scheduler",
                identity,
                lease_duration=0.6,
                renew_deadline=0.4,
                retry_period=0.1,
                on_started_leading=lambda: started.set(),
            )

        started_a, started_b = threading.Event(), threading.Event()
        a = make("a", started_a)
        b = make("b", started_b)
        ta = threading.Thread(target=a.run, daemon=True)
        tb = threading.Thread(target=b.run, daemon=True)
        ta.start()
        assert started_a.wait(3)
        tb.start()
        # b cannot take a fresh lease
        assert not started_b.wait(0.5)
        assert a.is_leader() and not b.is_leader()
        # a dies; b takes over after the lease expires
        a.stop()
        ta.join(timeout=3)
        assert started_b.wait(5)
        assert b.is_leader()
        b.stop()
        tb.join(timeout=3)
