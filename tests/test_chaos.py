"""Fault injection / elastic recovery (the reference's chaosmonkey +
daemon_restart e2e tier, SURVEY.md section 5.3-5.4): every component is a
stateless cache of the API rebuilt via list+watch, so kill + restart must
resume exactly where the dead instance stopped."""

import time

import pytest

from kubernetes_tpu.api.types import (
    Container,
    Node,
    NodeCondition,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    PodTemplateSpec,
    ReplicationController,
    ReplicationControllerSpec,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.controller.framework import SharedInformerFactory
from kubernetes_tpu.controller.replication import ReplicationManager
from kubernetes_tpu.kubelet import FakeRuntime, Kubelet, KubeletConfig
from kubernetes_tpu.scheduler.server import SchedulerServer, SchedulerServerOptions


from conftest import wait_until  # noqa: E402

from kubernetes_tpu.analysis import locks as lock_sanitizer


@pytest.fixture(autouse=True)
def _lock_order_sanitizer():
    """The chaos scenarios double as lock-order witnesses: every
    Lock/RLock created by kubernetes_tpu code during the test is
    wrapped (analysis/locks) and the cross-thread acquisition-order
    graph must stay acyclic — a cycle is a latent deadlock even when
    this run's interleaving got lucky."""
    with lock_sanitizer.instrumented():
        yield
    lock_sanitizer.assert_no_cycles("(chaos suite)")


def ready_node(name):
    return Node(
        metadata=ObjectMeta(name=name),
        status=NodeStatus(
            allocatable={"cpu": "64", "memory": "256Gi", "pods": "500"},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )


def pending_pod(name):
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(containers=[Container(requests={"cpu": "50m"})]),
    )


def n_bound(client):
    return sum(1 for p in client.pods().list()[0] if p.spec.node_name)


def test_scheduler_restart_resumes_backlog():
    """daemon_restart.go for the scheduler: kill it mid-backlog; a FRESH
    instance (new process state, nothing carried over) must pick up the
    remaining pending pods from the watch and finish. This is the
    checkpoint/resume model: the API IS the checkpoint."""
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    for i in range(4):
        client.nodes().create(ready_node(f"n{i}"))
    for i in range(20):
        client.pods().create(pending_pod(f"p{i:03d}"))
    first = SchedulerServer(client, SchedulerServerOptions()).start()
    assert wait_until(lambda: n_bound(client) >= 1)
    first.stop()
    # the cluster keeps moving while NO scheduler runs: a backlog builds
    for i in range(20, 40):
        client.pods().create(pending_pod(f"p{i:03d}"))
    before = n_bound(client)
    assert before < 40
    # a FRESH instance must find the backlog via its initial LIST (no
    # watch event will ever replay the creations it missed)
    second = SchedulerServer(client, SchedulerServerOptions()).start()
    try:
        assert wait_until(lambda: n_bound(client) == 40)
        # every pod exactly once: no double-binding across instances
        nodes = [p.spec.node_name for p in client.pods().list()[0]]
        assert all(nodes)
    finally:
        second.stop()


def test_kubelet_restart_recovers_pods():
    """A kubelet restart (fresh runtime — the machine rebooted) must
    re-run its bound pods and report Running again."""
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    cfg = dict(pleg_relist_period=0.05, status_sync_period=0.05)
    kl = Kubelet(client, KubeletConfig(node_name="n1", **cfg), FakeRuntime()).run()
    client.pods().create(
        Pod(metadata=ObjectMeta(name="p1"),
            spec=PodSpec(node_name="n1", containers=[Container(name="c")]))
    )
    assert wait_until(lambda: client.pods().get("p1").status.phase == "Running")
    kl.stop()
    # fresh kubelet, empty runtime: the config watch replays the bound pod
    kl2 = Kubelet(client, KubeletConfig(node_name="n1", **cfg), FakeRuntime()).run()
    try:
        assert wait_until(
            lambda: any(rp.name == "p1" for rp in kl2.runtime.list_pods())
        )
        assert client.pods().get("p1").status.phase == "Running"
    finally:
        kl2.stop()


def test_controller_manager_restart_mid_scale():
    """Kill the replication manager mid-scale-up; a fresh one must
    complete the scale without duplicating pods (expectations are local
    state and die with the process — the API world is the truth)."""
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    informers = SharedInformerFactory(client)
    rcm = ReplicationManager(client, informers)
    informers.start()
    informers.wait_for_sync()
    rcm.run()
    client.resource("replicationcontrollers", "default").create(
        ReplicationController(
            metadata=ObjectMeta(name="web"),
            spec=ReplicationControllerSpec(
                replicas=30, selector={"app": "web"},
                template=PodTemplateSpec(
                    metadata=ObjectMeta(labels={"app": "web"}),
                    spec=PodSpec(containers=[Container(requests={"cpu": "10m"})]),
                ),
            ),
        )
    )
    assert wait_until(lambda: len(client.pods().list()[0]) >= 5)
    rcm.stop()
    informers.stop()
    informers2 = SharedInformerFactory(client)
    rcm2 = ReplicationManager(client, informers2)
    informers2.start()
    informers2.wait_for_sync()
    rcm2.run()
    try:
        assert wait_until(lambda: len(client.pods().list()[0]) == 30)
        time.sleep(0.5)  # stability: no over-creation afterwards
        assert len(client.pods().list()[0]) == 30
    finally:
        rcm2.stop()
        informers2.stop()


def test_assumed_pod_ttl_self_heals():
    """cache.go:278-299: a bind that never lands (assumed pod whose watch
    confirmation is lost) expires after the TTL, releasing the resources
    in the scheduler cache — verified through the SchedulerCache API."""
    from kubernetes_tpu.scheduler.cache import SchedulerCache
    from kubernetes_tpu.utils.clock import FakeClock

    clock = FakeClock(1000.0)
    cache = SchedulerCache(ttl=30.0, clock=clock)
    cache.add_node(ready_node("n1"))
    pod = Pod(metadata=ObjectMeta(name="ghost", uid="u1"),
              spec=PodSpec(node_name="n1",
                           containers=[Container(requests={"cpu": "1"})]))
    cache.assume_pod(pod)
    state = cache.snapshot()
    assert state.node_infos["n1"].requested_milli_cpu == 1000
    # TTL passes with no Add confirmation: cleanup drops the assumption
    clock.step(31.0)
    cache.cleanup_expired(clock.now())
    state = cache.snapshot()
    assert state.node_infos["n1"].requested_milli_cpu == 0


def test_apiserver_restart_mid_backlog(tmp_path):
    """Kill and restart the apiserver (the one component whose death was
    previously unrecoverable) mid-backlog: the durable store recovers
    every object with RV continuity, reflectors relist through the
    Compacted horizon, and the scheduler drains the rest of the backlog.
    Matches the role of etcd-as-only-checkpoint (SURVEY 5.4)."""
    from kubernetes_tpu.client.transport import HTTPTransport

    data_dir = str(tmp_path / "etcd")
    api1 = APIServer(data_dir=data_dir)
    host, port = api1.serve_http()
    client = RESTClient(HTTPTransport(f"http://{host}:{port}", timeout=5.0))
    for i in range(4):
        client.nodes().create(ready_node(f"n{i}"))
    sched = SchedulerServer(
        client, SchedulerServerOptions(algorithm_provider="TPUProvider")
    ).start()
    try:
        for i in range(30):
            client.pods().create(pending_pod(f"pre-{i:03d}"))
        assert wait_until(lambda: n_bound(client) >= 10)

        # --- kill the apiserver process (HTTP down, store dropped) ---
        api1.shutdown_http()
        api1.store.close()
        del api1
        time.sleep(0.3)

        # --- restart on the same port from the same data_dir ---
        api2 = APIServer(data_dir=data_dir)
        api2.serve_http(host=host, port=port)
        try:
            objs, _ = client.pods().list()
            assert len(objs) == 30, "recovered store lost pods"
            bound_before = sum(1 for p in objs if p.spec.node_name)
            assert bound_before >= 10, "recovered store lost bindings"
            # new work + the unfinished backlog drain through the same
            # scheduler: its reflectors must recover on their own
            for i in range(10):
                client.pods().create(pending_pod(f"post-{i:02d}"))
            assert wait_until(lambda: n_bound(client) == 40, timeout=40), (
                f"stuck at {n_bound(client)}/40 bound"
            )
        finally:
            api2.shutdown_http()
            api2.store.close()
    finally:
        sched.stop()


def test_replicated_store_failover_zero_lost_bindings(tmp_path):
    """Kill the PRIMARY apiserver mid-density (no graceful close — the
    store object is abandoned, like kill -9 severing its sockets) and
    assert: the standby's WAL-shipped state holds EVERY acknowledged
    write, the promotion monitor promotes it, clients fail over through
    the multi-server transport, and the scheduler drains the remaining
    backlog against the promoted standby. The etcd-cluster property
    (VERDICT r4 missing #1) at primary/standby scale."""
    from kubernetes_tpu.client.transport import HTTPTransport
    from kubernetes_tpu.storage.replicated import (
        FollowerStore,
        PromotionMonitor,
        ReplicatedStore,
    )

    primary_store = ReplicatedStore(str(tmp_path / "primary"))
    api1 = APIServer(store=primary_store)
    host, port1 = api1.serve_http()
    url1 = f"http://{host}:{port1}"

    follower = FollowerStore(
        str(tmp_path / "standby"), primary_store.repl_address
    )
    assert follower.synced(10), "standby never completed initial sync"
    api2 = APIServer(store=follower)
    # the standby SERVES already (reads + 503 writes); promotion makes
    # it writable — clients reach it via transport failover
    _h2, port2 = api2.serve_http()
    url2 = f"http://{host}:{port2}"

    probe_client = RESTClient(HTTPTransport(url1, timeout=2.0))
    monitor = PromotionMonitor(
        follower, probe=probe_client.healthz, interval=0.1, failures=3
    )

    client = RESTClient(HTTPTransport(f"{url1},{url2}", timeout=5.0))
    for i in range(4):
        client.nodes().create(ready_node(f"n{i}"))
    sched = SchedulerServer(
        client, SchedulerServerOptions(algorithm_provider="TPUProvider")
    ).start()
    try:
        for i in range(30):
            client.pods().create(pending_pod(f"pre-{i:03d}"))
        assert wait_until(lambda: n_bound(client) >= 10)
        monitor.run()

        # --- kill -9 the primary: HTTP torn down, store abandoned
        # without close() (no final snapshot, no WAL truncation) ---
        bound_acked = n_bound(client)
        api1.shutdown_http()
        del api1, primary_store

        # promotion fires on probe silence; writes drain to the standby
        assert wait_until(lambda: follower.promoted, timeout=15), (
            "standby was never promoted"
        )
        objs, _ = client.pods().list()
        assert len(objs) == 30, (
            f"standby lost pods: {len(objs)}/30"
        )
        bound_after = sum(1 for p in objs if p.spec.node_name)
        assert bound_after >= bound_acked, (
            f"standby lost acknowledged bindings: {bound_after} < "
            f"{bound_acked}"
        )
        # the scheduler finishes the density against the promoted
        # standby (its reflectors relist through transport failover)
        for i in range(10):
            client.pods().create(pending_pod(f"post-{i:02d}"))
        assert wait_until(lambda: n_bound(client) == 40, timeout=50), (
            f"stuck at {n_bound(client)}/40 bound after failover"
        )
    finally:
        monitor.stop()
        sched.stop()
        api2.shutdown_http()
        follower.close()


def test_replicated_store_sync_semantics(tmp_path):
    """Every write acked by the primary is on the follower BEFORE any
    watcher sees it: commit N objects, sever the replication socket
    abruptly, and the follower's recovered state must hold exactly the
    committed prefix (nothing torn, nothing phantom)."""
    from kubernetes_tpu.storage.replicated import (
        FollowerStore,
        ReplicatedStore,
    )

    primary = ReplicatedStore(str(tmp_path / "p"))
    follower = FollowerStore(str(tmp_path / "f"), primary.repl_address)
    assert follower.synced(10)
    api = APIServer(store=primary)
    client = RESTClient(LocalTransport(api))
    for i in range(50):
        client.pods().create(pending_pod(f"w-{i:03d}"))
    # the follower holds all 50 the moment the creates returned
    with follower._lock:
        n = sum(1 for k in follower._data if k.startswith("/pods/"))
    assert n == 50, f"follower behind acked writes: {n}/50"
    primary.close()
    follower.promote()
    api2 = APIServer(store=follower)
    c2 = RESTClient(LocalTransport(api2))
    objs, _ = c2.pods().list()
    assert len(objs) == 50
    # and the promoted store accepts writes with RV continuity
    rv_before = follower.current_rv
    c2.pods().create(pending_pod("post-promote"))
    assert follower.current_rv > rv_before
    follower.close()
