"""Fault injection / elastic recovery (the reference's chaosmonkey +
daemon_restart e2e tier, SURVEY.md section 5.3-5.4): every component is a
stateless cache of the API rebuilt via list+watch, so kill + restart must
resume exactly where the dead instance stopped."""

import time

import pytest

from kubernetes_tpu.api.types import (
    Container,
    Node,
    NodeCondition,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    PodTemplateSpec,
    ReplicationController,
    ReplicationControllerSpec,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.controller.framework import SharedInformerFactory
from kubernetes_tpu.controller.replication import ReplicationManager
from kubernetes_tpu.kubelet import FakeRuntime, Kubelet, KubeletConfig
from kubernetes_tpu.scheduler.server import SchedulerServer, SchedulerServerOptions


from conftest import wait_until  # noqa: E402

from kubernetes_tpu.analysis import locks as lock_sanitizer


@pytest.fixture(autouse=True)
def _lock_order_sanitizer():
    """The chaos scenarios double as lock-order witnesses: every
    Lock/RLock created by kubernetes_tpu code during the test is
    wrapped (analysis/locks) and the cross-thread acquisition-order
    graph must stay acyclic — a cycle is a latent deadlock even when
    this run's interleaving got lucky."""
    with lock_sanitizer.instrumented():
        yield
    lock_sanitizer.assert_no_cycles("(chaos suite)")


def ready_node(name):
    return Node(
        metadata=ObjectMeta(name=name),
        status=NodeStatus(
            allocatable={"cpu": "64", "memory": "256Gi", "pods": "500"},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )


def pending_pod(name):
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(containers=[Container(requests={"cpu": "50m"})]),
    )


def n_bound(client):
    return sum(1 for p in client.pods().list()[0] if p.spec.node_name)


def test_scheduler_restart_resumes_backlog():
    """daemon_restart.go for the scheduler: kill it mid-backlog; a FRESH
    instance (new process state, nothing carried over) must pick up the
    remaining pending pods from the watch and finish. This is the
    checkpoint/resume model: the API IS the checkpoint."""
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    for i in range(4):
        client.nodes().create(ready_node(f"n{i}"))
    for i in range(20):
        client.pods().create(pending_pod(f"p{i:03d}"))
    first = SchedulerServer(client, SchedulerServerOptions()).start()
    assert wait_until(lambda: n_bound(client) >= 1)
    first.stop()
    # the cluster keeps moving while NO scheduler runs: a backlog builds
    for i in range(20, 40):
        client.pods().create(pending_pod(f"p{i:03d}"))
    before = n_bound(client)
    assert before < 40
    # a FRESH instance must find the backlog via its initial LIST (no
    # watch event will ever replay the creations it missed)
    second = SchedulerServer(client, SchedulerServerOptions()).start()
    try:
        assert wait_until(lambda: n_bound(client) == 40)
        # every pod exactly once: no double-binding across instances
        nodes = [p.spec.node_name for p in client.pods().list()[0]]
        assert all(nodes)
    finally:
        second.stop()


def test_kubelet_restart_recovers_pods():
    """A kubelet restart (fresh runtime — the machine rebooted) must
    re-run its bound pods and report Running again."""
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    cfg = dict(pleg_relist_period=0.05, status_sync_period=0.05)
    kl = Kubelet(client, KubeletConfig(node_name="n1", **cfg), FakeRuntime()).run()
    client.pods().create(
        Pod(metadata=ObjectMeta(name="p1"),
            spec=PodSpec(node_name="n1", containers=[Container(name="c")]))
    )
    assert wait_until(lambda: client.pods().get("p1").status.phase == "Running")
    kl.stop()
    # fresh kubelet, empty runtime: the config watch replays the bound pod
    kl2 = Kubelet(client, KubeletConfig(node_name="n1", **cfg), FakeRuntime()).run()
    try:
        assert wait_until(
            lambda: any(rp.name == "p1" for rp in kl2.runtime.list_pods())
        )
        assert client.pods().get("p1").status.phase == "Running"
    finally:
        kl2.stop()


def test_controller_manager_restart_mid_scale():
    """Kill the replication manager mid-scale-up; a fresh one must
    complete the scale without duplicating pods (expectations are local
    state and die with the process — the API world is the truth)."""
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    informers = SharedInformerFactory(client)
    rcm = ReplicationManager(client, informers)
    informers.start()
    informers.wait_for_sync()
    rcm.run()
    client.resource("replicationcontrollers", "default").create(
        ReplicationController(
            metadata=ObjectMeta(name="web"),
            spec=ReplicationControllerSpec(
                replicas=30, selector={"app": "web"},
                template=PodTemplateSpec(
                    metadata=ObjectMeta(labels={"app": "web"}),
                    spec=PodSpec(containers=[Container(requests={"cpu": "10m"})]),
                ),
            ),
        )
    )
    assert wait_until(lambda: len(client.pods().list()[0]) >= 5)
    rcm.stop()
    informers.stop()
    informers2 = SharedInformerFactory(client)
    rcm2 = ReplicationManager(client, informers2)
    informers2.start()
    informers2.wait_for_sync()
    rcm2.run()
    try:
        assert wait_until(lambda: len(client.pods().list()[0]) == 30)
        time.sleep(0.5)  # stability: no over-creation afterwards
        assert len(client.pods().list()[0]) == 30
    finally:
        rcm2.stop()
        informers2.stop()


def test_assumed_pod_ttl_self_heals():
    """cache.go:278-299: a bind that never lands (assumed pod whose watch
    confirmation is lost) expires after the TTL, releasing the resources
    in the scheduler cache — verified through the SchedulerCache API."""
    from kubernetes_tpu.scheduler.cache import SchedulerCache
    from kubernetes_tpu.utils.clock import FakeClock

    clock = FakeClock(1000.0)
    cache = SchedulerCache(ttl=30.0, clock=clock)
    cache.add_node(ready_node("n1"))
    pod = Pod(metadata=ObjectMeta(name="ghost", uid="u1"),
              spec=PodSpec(node_name="n1",
                           containers=[Container(requests={"cpu": "1"})]))
    cache.assume_pod(pod)
    state = cache.snapshot()
    assert state.node_infos["n1"].requested_milli_cpu == 1000
    # TTL passes with no Add confirmation: cleanup drops the assumption
    clock.step(31.0)
    cache.cleanup_expired(clock.now())
    state = cache.snapshot()
    assert state.node_infos["n1"].requested_milli_cpu == 0


def test_apiserver_restart_mid_backlog(tmp_path):
    """Kill and restart the apiserver (the one component whose death was
    previously unrecoverable) mid-backlog: the durable store recovers
    every object with RV continuity, reflectors relist through the
    Compacted horizon, and the scheduler drains the rest of the backlog.
    Matches the role of etcd-as-only-checkpoint (SURVEY 5.4)."""
    from kubernetes_tpu.client.transport import HTTPTransport

    data_dir = str(tmp_path / "etcd")
    api1 = APIServer(data_dir=data_dir)
    host, port = api1.serve_http()
    client = RESTClient(HTTPTransport(f"http://{host}:{port}", timeout=5.0))
    for i in range(4):
        client.nodes().create(ready_node(f"n{i}"))
    sched = SchedulerServer(
        client, SchedulerServerOptions(algorithm_provider="TPUProvider")
    ).start()
    try:
        for i in range(30):
            client.pods().create(pending_pod(f"pre-{i:03d}"))
        assert wait_until(lambda: n_bound(client) >= 10)

        # --- kill the apiserver process (HTTP down, store dropped) ---
        api1.shutdown_http()
        api1.store.close()
        del api1
        time.sleep(0.3)

        # --- restart on the same port from the same data_dir ---
        api2 = APIServer(data_dir=data_dir)
        api2.serve_http(host=host, port=port)
        try:
            objs, _ = client.pods().list()
            assert len(objs) == 30, "recovered store lost pods"
            bound_before = sum(1 for p in objs if p.spec.node_name)
            assert bound_before >= 10, "recovered store lost bindings"
            # new work + the unfinished backlog drain through the same
            # scheduler: its reflectors must recover on their own
            for i in range(10):
                client.pods().create(pending_pod(f"post-{i:02d}"))
            assert wait_until(lambda: n_bound(client) == 40, timeout=40), (
                f"stuck at {n_bound(client)}/40 bound"
            )
        finally:
            api2.shutdown_http()
            api2.store.close()
    finally:
        sched.stop()


class _ReplicatedHA:
    """The 2-node WAL-shipping profile: primary + WAL-shipped standby
    with an external PromotionMonitor (storage/replicated.py)."""

    name = "replicated"

    def start(self, tmp_path):
        from kubernetes_tpu.client.transport import HTTPTransport
        from kubernetes_tpu.storage.replicated import (
            FollowerStore,
            PromotionMonitor,
            ReplicatedStore,
        )

        self.primary = ReplicatedStore(str(tmp_path / "primary"))
        self.api1 = APIServer(store=self.primary)
        host, port1 = self.api1.serve_http()
        self.follower = FollowerStore(
            str(tmp_path / "standby"), self.primary.repl_address
        )
        assert self.follower.synced(10), (
            "standby never completed initial sync")
        self.api2 = APIServer(store=self.follower)
        # the standby SERVES already (reads + 503 writes); promotion
        # makes it writable — clients reach it via transport failover
        _h2, port2 = self.api2.serve_http()
        url1 = f"http://{host}:{port1}"
        probe = RESTClient(HTTPTransport(url1, timeout=2.0))
        self.monitor = PromotionMonitor(
            self.follower, probe=probe.healthz, interval=0.1,
            failures=3)
        return f"{url1},http://{host}:{port2}"

    def arm(self):
        self.monitor.run()

    def kill_primary(self):
        # kill -9: HTTP torn down, store abandoned without close()
        # (no final snapshot, no WAL truncation)
        self.api1.shutdown_http()
        self.api1 = None
        self.primary = None

    def wait_failover(self):
        assert wait_until(lambda: self.follower.promoted, timeout=15), (
            "standby was never promoted")

    def survivor_store(self):
        return self.follower

    def assert_acked_replicated(self, prefix, n):
        """acked == already durably on the standby, synchronously."""
        with self.follower._lock:
            have = sum(1 for k in self.follower._data
                       if k.startswith(prefix))
        assert have == n, f"follower behind acked writes: {have}/{n}"

    def promote_now(self):
        if self.primary is not None:
            self.primary.close()
            self.primary = None
        self.follower.promote()
        return self.api2

    def close(self):
        self.monitor.stop()
        if self.api1 is not None:
            self.api1.shutdown_http()
        self.api2.shutdown_http()
        if self.primary is not None:
            self.primary.close()
        self.follower.close()


class _QuorumHA:
    """The 3-member majority-ack consensus profile: every member
    serves an apiserver; election is INSIDE the store (storage/
    quorum), so there is no promotion monitor to arm."""

    name = "quorum"

    def start(self, tmp_path):
        from kubernetes_tpu.storage.quorum import build_cluster

        # 0.5s base: fast failover for the test, but wide enough that
        # a GIL stall under the armed sanitizers (~3x slowdown) never
        # reads as leader death mid-propose (a spurious deposition
        # 503s the bare test client, which has no retry loop)
        self.stores = build_cluster(
            str(tmp_path), 3, election_timeout=0.5)
        self.killed = []
        self.apis = [APIServer(store=s) for s in self.stores]
        urls = []
        for api in self.apis:
            host, port = api.serve_http()
            urls.append(f"http://{host}:{port}")
        return ",".join(urls)

    def arm(self):
        pass  # the quorum elects from INSIDE the store

    # generous: during a mid-density failover the scheduler's retry
    # storm shares the GIL with the election itself
    def _leader(self, timeout=60.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            for s in self.stores:
                if s not in self.killed and s.node.is_leader():
                    return s
            time.sleep(0.02)
        raise AssertionError("no quorum leader")

    def kill_primary(self):
        lead = self._leader()
        self.apis[self.stores.index(lead)].shutdown_http()
        lead.kill()
        self.killed.append(lead)

    def wait_failover(self):
        self._leader()  # a new leader IS the failover

    def survivor_store(self):
        return self._leader()

    def assert_acked_replicated(self, prefix, n):
        """acked == durably in a MAJORITY's raft log (applied state
        follows at the next commit notification)."""
        lead = self._leader()
        need = lead.node.status()["applied_index"]
        followers = [s for s in self.stores
                     if s is not lead and s not in self.killed]
        logged = [f for f in followers
                  if f.node.raft_log.last_index >= need]
        assert logged, (
            f"no follower's log reached index {need} at ack time: "
            f"{[(f.node_id, f.node.raft_log.last_index) for f in followers]}")

    def promote_now(self):
        """kill the leader; the surviving majority elects — return an
        apiserver over the new leader."""
        self.kill_primary()
        lead = self._leader()
        return self.apis[self.stores.index(lead)]

    def close(self):
        for api in self.apis:
            api.shutdown_http()
        for s in self.stores:
            s.close()


@pytest.fixture(params=["replicated", "quorum"])
def ha_profile(request):
    return {"replicated": _ReplicatedHA, "quorum": _QuorumHA}[
        request.param]()


def test_replicated_store_failover_zero_lost_bindings(tmp_path,
                                                      ha_profile):
    """Kill the PRIMARY mid-density (no graceful close — the store is
    abandoned, like kill -9 severing its sockets) and assert: the
    surviving replica(s) hold EVERY acknowledged write, failover
    happens (external promotion for the 2-node profile, internal
    election for the quorum), clients fail over through the
    multi-server transport, and the scheduler drains the remaining
    backlog. The etcd-cluster property, at both HA scales."""
    from kubernetes_tpu.client.transport import HTTPTransport

    profile = ha_profile
    urls = profile.start(tmp_path)
    client = RESTClient(HTTPTransport(urls, timeout=5.0))
    for i in range(4):
        client.nodes().create(ready_node(f"n{i}"))
    sched = SchedulerServer(
        client, SchedulerServerOptions(algorithm_provider="TPUProvider")
    ).start()
    try:
        for i in range(30):
            client.pods().create(pending_pod(f"pre-{i:03d}"))
        assert wait_until(lambda: n_bound(client) >= 10)
        profile.arm()

        bound_acked = n_bound(client)
        profile.kill_primary()
        profile.wait_failover()

        objs, _ = client.pods().list()
        assert len(objs) == 30, (
            f"survivors lost pods: {len(objs)}/30"
        )
        bound_after = sum(1 for p in objs if p.spec.node_name)
        assert bound_after >= bound_acked, (
            f"survivors lost acknowledged bindings: {bound_after} < "
            f"{bound_acked}"
        )
        # the scheduler finishes the density against the survivors
        # (its reflectors relist through transport failover)
        # the sanitizer witnesses run this suite instrumented (~3x
        # slower), so the drain deadline is generous
        for i in range(10):
            client.pods().create(pending_pod(f"post-{i:02d}"))
        assert wait_until(lambda: n_bound(client) == 40, timeout=120), (
            f"stuck at {n_bound(client)}/40 bound after failover"
        )
    finally:
        sched.stop()
        profile.close()


def test_replicated_store_sync_semantics(tmp_path, ha_profile):
    """Every write acked by the primary is durably replicated BEFORE
    the ack: commit N objects, kill the primary abruptly, and the
    survivors must hold exactly the committed prefix (nothing torn,
    nothing phantom), then accept writes with RV continuity."""
    profile = ha_profile
    urls = profile.start(tmp_path)
    from kubernetes_tpu.client.transport import HTTPTransport

    client = RESTClient(HTTPTransport(urls, timeout=5.0))
    for i in range(50):
        client.pods().create(pending_pod(f"w-{i:03d}"))
    # replication is synchronous with the ack
    profile.assert_acked_replicated("/pods/", 50)
    api2 = profile.promote_now()
    profile.wait_failover()
    c2 = RESTClient(LocalTransport(api2))
    objs, _ = c2.pods().list()
    assert len(objs) == 50
    # and the surviving store accepts writes with RV continuity
    survivor = profile.survivor_store()
    rv_before = survivor.current_rv
    c2.pods().create(pending_pod("post-promote"))
    assert survivor.current_rv > rv_before
    profile.close()


def test_replicated_store_promotion_fences_stale_primary(tmp_path):
    """The fencing regression (quorum terms subsume this; the 2-node
    profile needs it explicitly): a follower promoted while the
    primary is still ALIVE — deemed dead by the monitor, e.g. just
    slow — must fence the old term's writes. A client holding pooled
    connections to the stale primary gets NotPrimary/503 instead of a
    silently-diverging ack, and fails over to the promoted store."""
    from kubernetes_tpu.storage.replicated import (
        FollowerStore,
        NotPrimary,
        ReplicatedStore,
    )

    primary = ReplicatedStore(str(tmp_path / "p"))
    follower = FollowerStore(str(tmp_path / "f"), primary.repl_address)
    assert follower.synced(10)
    api1 = APIServer(store=primary)
    c_stale = RESTClient(LocalTransport(api1))  # the pooled client
    c_stale.pods().create(pending_pod("pre-fence"))

    # promotion fires while the primary is alive and connected
    follower.promote()
    assert wait_until(lambda: primary.fenced, timeout=10), (
        "fence never reached the stale primary")

    # the stale primary rejects every verb of the old term
    with pytest.raises(Exception) as exc:
        primary.create("/pods/default/stale", pending_pod("stale"))
    assert isinstance(exc.value, NotPrimary)
    # ...and the pooled client's write surfaces as a 503, the signal
    # transports use to fail over
    from kubernetes_tpu.client.rest import APIStatusError

    with pytest.raises(APIStatusError) as aerr:
        c_stale.pods().create(pending_pod("stale-via-client"))
    assert aerr.value.code == 503
    # the promoted store is the live half
    api2 = APIServer(store=follower)
    c_new = RESTClient(LocalTransport(api2))
    c_new.pods().create(pending_pod("post-fence"))
    assert len(c_new.pods().list()[0]) == 2  # pre-fence + post-fence
    primary.close()
    follower.close()
