"""Util layer tests (workqueue/flowcontrol/wait/trace idioms from
pkg/util/*_test.go)."""

import threading

import pytest

from kubernetes_tpu.utils import (
    Backoff,
    DelayingQueue,
    FakeClock,
    RateLimitingQueue,
    TokenBucketRateLimiter,
    Trace,
    WorkQueue,
    parallelize,
)
from kubernetes_tpu.utils.wait import poll_until, until
from kubernetes_tpu.utils.workqueue import ShutDown


class TestWorkQueue:
    def test_fifo_order(self):
        q = WorkQueue()
        for i in range(5):
            q.add(i)
        assert [q.get() for _ in range(5)] == [0, 1, 2, 3, 4]

    def test_dedup_while_queued(self):
        q = WorkQueue()
        q.add("a")
        q.add("a")
        assert len(q) == 1

    def test_readd_while_processing_requeues_on_done(self):
        q = WorkQueue()
        q.add("a")
        item = q.get()
        q.add("a")  # while processing: goes dirty, not queued
        assert len(q) == 0
        q.done(item)
        assert len(q) == 1
        assert q.get() == "a"

    def test_shutdown_raises(self):
        q = WorkQueue()
        q.shut_down()
        with pytest.raises(ShutDown):
            q.get()

    def test_concurrent_producers_consumers(self):
        q = WorkQueue()
        seen = set()
        lock = threading.Lock()

        def consume():
            while True:
                try:
                    item = q.get(timeout=2)
                except (ShutDown, TimeoutError):
                    return
                with lock:
                    seen.add(item)
                q.done(item)

        threads = [threading.Thread(target=consume) for _ in range(4)]
        for t in threads:
            t.start()
        for i in range(200):
            q.add(i)
        for t in threads:
            t.join(timeout=5)
        q.shut_down()
        assert seen == set(range(200))


class TestDelayingQueue:
    def test_add_after_zero_is_immediate(self):
        q = DelayingQueue()
        q.add_after("x", 0)
        assert q.get(timeout=1) == "x"

    def test_add_after_delivers(self):
        q = DelayingQueue()
        q.add_after("x", 0.05)
        assert q.get(timeout=2) == "x"

    def test_readd_keeps_earliest_ready_time(self):
        # delaying_queue.go insert: a re-add may only move the deadline
        # EARLIER. The long re-add must not push out the imminent retry,
        # and the item must be delivered exactly once.
        clock = FakeClock()
        q = DelayingQueue(clock=clock)
        q.add_after("x", 10.0)
        q.add_after("x", 0.05)  # earlier: supersedes
        q.add_after("x", 60.0)  # later: ignored
        assert q.waiting() == 1
        clock.step(0.2)
        assert q.get(timeout=2) == "x"
        q.done("x")
        assert q.waiting() == 0
        assert len(q) == 0  # exactly once: no second delivery pending
        clock.step(120.0)
        with pytest.raises(TimeoutError):
            q.get(timeout=0.3)

    def test_delayed_items_keep_ready_order(self):
        # two items with different deadlines come out in deadline order,
        # even when added in reverse
        clock = FakeClock()
        q = DelayingQueue(clock=clock)
        q.add_after("late", 5.0)
        q.add_after("early", 1.0)
        clock.step(10.0)
        first = q.get(timeout=2)
        second = q.get(timeout=2)
        assert (first, second) == ("early", "late")

    def test_immediate_add_supersedes_delayed(self):
        # Add() bypasses the delay; when the stale deadline fires the
        # dirty-set dedup keeps the item single
        clock = FakeClock()
        q = DelayingQueue(clock=clock)
        q.add_after("x", 30.0)
        q.add_after("x", 0)  # immediate
        assert q.get(timeout=1) == "x"
        assert q.waiting() == 0


class TestRateLimitingQueue:
    def test_backoff_growth_and_forget(self):
        clock = FakeClock()
        q = RateLimitingQueue(base_delay=1.0, max_delay=8.0, clock=clock)
        b = q._backoff
        assert b.next_("k") == 1.0
        assert b.next_("k") == 2.0
        assert b.next_("k") == 4.0
        assert b.next_("k") == 8.0
        assert b.next_("k") == 8.0  # capped
        q.forget("k")
        assert b.next_("k") == 1.0


class TestFlowControl:
    def test_token_bucket_burst(self):
        clock = FakeClock()
        rl = TokenBucketRateLimiter(qps=1, burst=3, clock=clock)
        assert rl.try_accept()
        assert rl.try_accept()
        assert rl.try_accept()
        assert not rl.try_accept()
        clock.step(1.0)
        assert rl.try_accept()

    def test_backoff_period_check(self):
        clock = FakeClock()
        b = Backoff(1.0, 60.0, clock=clock)
        b.next_("pod")
        assert b.is_in_backoff_period("pod")
        clock.step(1.5)
        assert not b.is_in_backoff_period("pod")

    def test_backoff_gc(self):
        clock = FakeClock()
        b = Backoff(1.0, 2.0, clock=clock)
        b.next_("pod")
        clock.step(10.0)
        b.gc()
        assert b.get("pod") == 0.0

    def test_backoff_resets_after_idle(self):
        # backoff.go: an entry idle for > 2*max restarts at initial
        clock = FakeClock()
        b = Backoff(1.0, 4.0, clock=clock)
        b.next_("pod")
        b.next_("pod")
        clock.step(100.0)
        assert b.next_("pod") == 1.0


class TestWait:
    def test_until_runs_and_stops(self):
        stop = threading.Event()
        count = []

        def body():
            count.append(1)
            if len(count) >= 3:
                stop.set()

        until(body, 0.001, stop)
        assert len(count) >= 3

    def test_until_contains_crash(self):
        stop = threading.Event()
        count = []

        def body():
            count.append(1)
            if len(count) >= 2:
                stop.set()
            raise RuntimeError("boom")

        until(body, 0.001, stop)  # must not raise
        assert len(count) >= 2

    def test_poll_until(self):
        clock = FakeClock()
        state = {"n": 0}

        def cond():
            state["n"] += 1
            return state["n"] >= 3

        assert poll_until(cond, 1.0, 10.0, clock=clock)
        assert not poll_until(lambda: False, 1.0, 3.0, clock=clock)


class TestParallelize:
    def test_all_pieces_run(self):
        seen = []
        lock = threading.Lock()

        def work(i):
            with lock:
                seen.append(i)

        parallelize(16, 100, work)
        assert sorted(seen) == list(range(100))

    def test_contains_panics(self):
        seen = []
        lock = threading.Lock()

        def work(i):
            if i % 2:
                raise RuntimeError("boom")
            with lock:
                seen.append(i)

        parallelize(4, 10, work)
        assert sorted(seen) == [0, 2, 4, 6, 8]


class TestTrace:
    def test_steps_recorded(self):
        clock = FakeClock()
        tr = Trace("scheduling pod", clock=clock)
        clock.step(0.01)
        tr.step("computing predicates")
        clock.step(0.02)
        assert tr.total_time() == pytest.approx(0.03)
        tr.log_if_long(0.02)  # must not raise


class TestPprofEndpoints:
    """net/http/pprof analogue on the shared mux (server.go:96-99)."""

    def test_thread_dump_and_profile(self):
        from kubernetes_tpu.apiserver.server import APIServer

        api = APIServer()
        code, out = api.handle("GET", "/debug/pprof/goroutine")
        assert code == 200
        text = out["_raw"].decode()
        assert "MainThread" in text and "thread " in text
        code, out = api.handle(
            "GET", "/debug/pprof/profile", {"seconds": "0.2"}
        )
        assert code == 200
        assert b"sampling rounds" in out["_raw"]
        code, out = api.handle("GET", "/debug/pprof")
        assert b"pprof endpoints" in out["_raw"]

    def test_profile_rejects_garbage_seconds(self):
        from kubernetes_tpu.apiserver.server import APIServer

        api = APIServer()
        code, _ = api.handle(
            "GET", "/debug/pprof/profile", {"seconds": "bananas"}
        )
        assert code == 400
