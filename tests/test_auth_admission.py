"""Authn/authz over HTTP + the admission plugin chain (pkg/auth,
plugin/pkg/admission)."""

import pytest

from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    LabelSelector,
    LimitRange,
    LimitRangeItem,
    LimitRangeSpec,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    ResourceQuota,
    ResourceQuotaSpec,
)
from kubernetes_tpu.apiserver import admission as adm
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.auth import (
    ABACAuthorizer,
    ABACPolicy,
    BasicAuthAuthenticator,
    TokenAuthenticator,
    UnionAuthenticator,
    UserInfo,
)
from kubernetes_tpu.client.rest import APIStatusError, RESTClient
from kubernetes_tpu.client.transport import HTTPTransport, LocalTransport


def pod(name, cpu=None, affinity=None):
    reqs = {"cpu": cpu} if cpu else {}
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(containers=[Container(name="c", requests=reqs)],
                     affinity=affinity),
    )


# --- authn / authz over HTTP -------------------------------------------------


class _AuthedTransport(HTTPTransport):
    def __init__(self, base_url, headers):
        super().__init__(base_url)
        self._headers = headers

    def _request(self, req):  # inject headers on every request
        for k, v in self._headers.items():
            req.add_header(k, v)
        return req


def _send(base, method, path, headers, body=None):
    import json as _json
    from urllib import error, request

    req = request.Request(
        base + path,
        data=_json.dumps(body).encode() if body is not None else None,
        method=method,
        headers={"Content-Type": "application/json", **headers},
    )
    try:
        with request.urlopen(req, timeout=10) as resp:
            return resp.status, _json.loads(resp.read() or b"{}")
    except error.HTTPError as e:
        return e.code, _json.loads(e.read() or b"{}")


def test_token_and_abac_over_http():
    authn = UnionAuthenticator([
        TokenAuthenticator.from_csv(
            "secret-admin,admin,1\nsecret-bob,bob,2\n"
        ),
        BasicAuthAuthenticator({"carol": ("pw", UserInfo("carol"))}),
    ])
    authz = ABACAuthorizer([
        ABACPolicy(user="admin", resource="*", namespace="*"),
        ABACPolicy(user="bob", resource="pods", namespace="default",
                   readonly=True),
        ABACPolicy(user="carol", resource="nodes", readonly=True),
    ])
    server = APIServer(authenticator=authn, authorizer=authz)
    host, port = server.serve_http()
    base = f"http://{host}:{port}"

    # no credentials -> 401
    code, _ = _send(base, "GET", "/api/v1/pods", {})
    assert code == 401
    # bad token -> 401
    code, _ = _send(base, "GET", "/api/v1/pods",
                    {"Authorization": "Bearer nope"})
    assert code == 401
    # admin can write
    code, _ = _send(
        base, "POST", "/api/v1/namespaces/default/pods",
        {"Authorization": "Bearer secret-admin"},
        {"kind": "Pod", "metadata": {"name": "p1"},
         "spec": {"containers": [{"name": "c"}]}},
    )
    assert code == 201
    # bob can read pods...
    code, _ = _send(base, "GET", "/api/v1/namespaces/default/pods",
                    {"Authorization": "Bearer secret-bob"})
    assert code == 200
    # ...but not write them (readonly policy)
    code, _ = _send(
        base, "POST", "/api/v1/namespaces/default/pods",
        {"Authorization": "Bearer secret-bob"},
        {"kind": "Pod", "metadata": {"name": "p2"},
         "spec": {"containers": [{"name": "c"}]}},
    )
    assert code == 403
    # basic auth + resource restriction
    import base64

    basic = {"Authorization": "Basic " + base64.b64encode(b"carol:pw").decode()}
    code, _ = _send(base, "GET", "/api/v1/nodes", basic)
    assert code == 200
    code, _ = _send(base, "GET", "/api/v1/namespaces/default/pods", basic)
    assert code == 403
    server.shutdown_http()


# --- admission plugins -------------------------------------------------------


@pytest.fixture()
def plane():
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    return server, client


def test_limit_ranger_defaults_and_bounds(plane):
    server, client = plane
    server.admission.plugins.append(adm.LimitRanger(server))
    client.resource("limitranges", "default").create(
        LimitRange(
            metadata=ObjectMeta(name="limits"),
            spec=LimitRangeSpec(limits=[
                LimitRangeItem(
                    type="Container",
                    default_request={"cpu": "200m"},
                    max={"cpu": "1"},
                )
            ]),
        )
    )
    client.pods().create(pod("defaulted"))
    assert client.pods().get("defaulted").spec.containers[0].requests["cpu"] == "200m"
    with pytest.raises(APIStatusError) as exc:
        client.pods().create(pod("hog", cpu="2"))
    assert "maximum cpu" in str(exc.value)


def test_resource_quota_admission(plane):
    server, client = plane
    server.admission.plugins.append(adm.ResourceQuotaAdmission(server))
    client.resource("resourcequotas", "default").create(
        ResourceQuota(
            metadata=ObjectMeta(name="quota"),
            spec=ResourceQuotaSpec(hard={"pods": "2", "requests.cpu": "500m"}),
        )
    )
    client.pods().create(pod("a", cpu="200m"))
    client.pods().create(pod("b", cpu="200m"))
    # third pod violates pods=2
    with pytest.raises(APIStatusError) as exc:
        client.pods().create(pod("c"))
    assert "exceeded quota" in str(exc.value)
    client.pods().delete("b")
    # cpu quota: 200m used + 400m requested > 500m
    with pytest.raises(APIStatusError):
        client.pods().create(pod("d", cpu="400m"))


def test_service_account_and_antiaffinity_admission(plane):
    server, client = plane
    server.admission.plugins.append(adm.ServiceAccountAdmission())
    server.admission.plugins.append(adm.LimitPodHardAntiAffinityTopology())
    client.pods().create(pod("sa-pod"))
    assert client.pods().get("sa-pod").spec.service_account_name == "default"
    bad = Affinity(pod_anti_affinity=PodAntiAffinity(
        required_during_scheduling_ignored_during_execution=(
            PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"a": "b"}),
                topology_key="failure-domain.beta.kubernetes.io/zone",
            ),
        )
    ))
    with pytest.raises(APIStatusError) as exc:
        client.pods().create(pod("zonal-anti", affinity=bad))
    assert "hostname" in str(exc.value).lower()
    ok = Affinity(pod_anti_affinity=PodAntiAffinity(
        required_during_scheduling_ignored_during_execution=(
            PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"a": "b"}),
                topology_key="kubernetes.io/hostname",
            ),
        )
    ))
    client.pods().create(pod("host-anti", affinity=ok))  # allowed


def test_always_pull_images(plane):
    server, client = plane
    server.admission.plugins.append(adm.AlwaysPullImages())
    client.pods().create(Pod(
        metadata=ObjectMeta(name="pull"),
        spec=PodSpec(containers=[
            Container(name="a", image="private/app:v1"),
            Container(name="b", image="private/side:v1",
                      image_pull_policy="IfNotPresent"),
        ]),
    ))
    got = client.pods().get("pull")
    assert all(c.image_pull_policy == "Always"
               for c in got.spec.containers)


def test_security_context_deny(plane):
    from kubernetes_tpu.api.types import (
        PodSecurityContext, SecurityContext, SELinuxOptions)
    from kubernetes_tpu.client.rest import APIStatusError

    server, client = plane
    server.admission.plugins.append(adm.SecurityContextDeny())
    for name, spec in (
        ("run-as-user", PodSpec(containers=[Container(
            name="c", security_context=SecurityContext(run_as_user=0))])),
        ("selinux", PodSpec(containers=[Container(
            name="c", security_context=SecurityContext(
                se_linux_options=SELinuxOptions(level="s0")))])),
        ("pod-groups", PodSpec(
            containers=[Container(name="c")],
            security_context=PodSecurityContext(
                supplemental_groups=[1000]))),
        ("pod-run-as", PodSpec(
            containers=[Container(name="c")],
            security_context=PodSecurityContext(run_as_user=1))),
    ):
        with pytest.raises(APIStatusError) as e:
            client.pods().create(Pod(
                metadata=ObjectMeta(name=name), spec=spec))
        assert e.value.code == 403, name
    # a plain pod still admits
    client.pods().create(Pod(
        metadata=ObjectMeta(name="plain"),
        spec=PodSpec(containers=[Container(name="c")])))


def test_initial_resources_estimates_from_history(plane):
    server, client = plane
    server.admission.plugins.append(adm.InitialResources(server))
    # history: three running pods with the same image at varying requests
    for i, cpu in enumerate(("100m", "200m", "400m")):
        client.pods().create(Pod(
            metadata=ObjectMeta(name=f"hist-{i}"),
            spec=PodSpec(containers=[Container(
                name="c", image="app:v2",
                requests={"cpu": cpu, "memory": "64Mi"})]),
        ))
    # a request-less pod of the same image gets the 60th-percentile
    # estimate (sorted [100,200,400] -> index 1 -> 200m) + the audit
    # annotation
    client.pods().create(Pod(
        metadata=ObjectMeta(name="fresh"),
        spec=PodSpec(containers=[Container(name="c", image="app:v2")]),
    ))
    got = client.pods().get("fresh")
    assert str(got.spec.containers[0].requests["cpu"]) == "200m"
    assert adm.InitialResources.ANNOTATION in got.metadata.annotations
    # unknown image without a table entry: left untouched
    client.pods().create(Pod(
        metadata=ObjectMeta(name="unknown"),
        spec=PodSpec(containers=[Container(name="c", image="mystery")]),
    ))
    assert not client.pods().get("unknown").spec.containers[0].requests


def test_admission_control_flag_builds_chain():
    from kubernetes_tpu.apiserver.server import APIServer

    api = APIServer(admission_control=(
        "NamespaceLifecycle,AlwaysPullImages,SecurityContextDeny"
    ))
    kinds = [type(p).__name__ for p in api.admission.plugins]
    assert kinds == ["NamespaceLifecycle", "AlwaysPullImages",
                     "SecurityContextDeny"]
    with pytest.raises(ValueError):
        APIServer(admission_control="NoSuchPlugin")
