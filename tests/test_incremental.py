"""Incremental snapshot maintenance: snapshot-after-deltas must equal
snapshot-from-scratch (VERDICT round-1 item #2; reference analogue:
schedulercache/node_info.go:118-156 O(1) deltas + cache.go:77 clone).

Two layers of proof:
  1. semantic: after a random cache event stream, every decoded per-node
     quantity in the incremental arrays equals what a from-scratch
     SnapshotEncoder derives from the same cluster state;
  2. end-to-end: scheduling decisions through the cache-wired
     TPUScheduleAlgorithm (incremental wave path, with fallback gates)
     are identical to the sequential oracle on the equivalently
     restricted state, across interleaved event batches.
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.api.types import (
    Container,
    ContainerPort,
    Node,
    NodeCondition,
    NodeStatus,
    NodeSpec,
    ObjectMeta,
    Pod,
    PodSpec,
    Service,
    ServiceSpec,
    Taint,
)
from kubernetes_tpu.oracle import ClusterState, GenericScheduler
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.factory import node_schedulable
from kubernetes_tpu.scheduler.tpu_algorithm import TPUScheduleAlgorithm
from kubernetes_tpu.snapshot.incremental import IncrementalEncoder
from kubernetes_tpu.utils.clock import FakeClock

from tests.test_conformance import ORACLE_PREDICATES, ORACLE_PRIORITIES

ZONE = "failure-domain.beta.kubernetes.io/zone"


class _Lister:
    def __init__(self):
        self.items = []

    def list(self):
        return list(self.items)


def rand_node(rng, name):
    labels = {"kubernetes.io/hostname": name}
    if rng.random() < 0.4:
        labels[ZONE] = rng.choice(["a", "b"])
    if rng.random() < 0.5:
        labels["disktype"] = rng.choice(["ssd", "hdd"])
    taints = None
    if rng.random() < 0.25:
        taints = [Taint(key="dedicated", value=rng.choice(["a", "b"]),
                        effect=rng.choice(["NoSchedule", "PreferNoSchedule"]))]
    conds = [NodeCondition("Ready", rng.choice(["True", "True", "True", "False"]))]
    if rng.random() < 0.2:
        conds.append(NodeCondition("MemoryPressure", "True"))
    return Node(
        metadata=ObjectMeta(name=name, labels=labels),
        spec=NodeSpec(taints=taints),
        status=NodeStatus(
            allocatable={
                "cpu": f"{rng.choice([1000, 2000, 4000])}m",
                "memory": str(rng.choice([2, 4, 8]) * 1024**3),
                "pods": str(rng.choice([5, 20, 110])),
            },
            conditions=conds,
        ),
    )


def rand_assigned(rng, i, node_name):
    reqs = {}
    if rng.random() < 0.8:
        reqs["cpu"] = f"{rng.choice([0, 100, 300])}m"
    if rng.random() < 0.8:
        reqs["memory"] = str(rng.choice([0, 256, 512]) * 1024**2)
    ports = []
    if rng.random() < 0.3:
        ports.append(ContainerPort(host_port=rng.choice([8080, 9090])))
    return Pod(
        metadata=ObjectMeta(
            name=f"assigned-{i}",
            labels=rng.choice([{"app": "web"}, {"app": "db"}, {}]),
        ),
        spec=PodSpec(
            node_name=node_name,
            containers=[Container(requests=reqs, ports=ports)],
        ),
    )


def rand_pending(rng, i):
    kw = {}
    if rng.random() < 0.3:
        kw["node_selector"] = rng.choice([{"disktype": "ssd"}, {ZONE: "a"}])
    return Pod(
        metadata=ObjectMeta(
            name=f"pending-{i}",
            labels=rng.choice([{"app": "web"}, {"app": "db"}]),
        ),
        spec=PodSpec(
            containers=[
                Container(requests={"cpu": "100m", "memory": "100Mi"})
            ],
            **kw,
        ),
    )


def drive_events(rng, cache, steps, live_nodes, live_pods, pod_seq):
    """Apply `steps` random mutations to the cache, mirroring them in
    live_nodes / live_pods dicts (name -> object)."""
    for _ in range(steps):
        op = rng.random()
        if op < 0.25 or not live_nodes:
            name = f"node-{rng.randrange(200):03d}"
            node = rand_node(rng, name)
            if name in live_nodes:
                cache.update_node(live_nodes[name], node)
            else:
                cache.add_node(node)
            live_nodes[name] = node
        elif op < 0.35 and live_nodes:
            name = rng.choice(list(live_nodes))
            cache.remove_node(live_nodes.pop(name))
        elif op < 0.75:
            pod_seq[0] += 1
            pod = rand_assigned(rng, pod_seq[0], rng.choice(list(live_nodes)))
            cache.add_pod(pod)
            live_pods[pod.metadata.name] = pod
        elif live_pods:
            name = rng.choice(list(live_pods))
            cache.remove_pod(live_pods.pop(name))


def restricted_state(cache, services=(), controllers=()):
    """core.py Scheduler._snapshot semantics: schedulable nodes only."""
    state = cache.snapshot(services=list(services), controllers=list(controllers))
    sub = ClusterState(services=list(services), controllers=list(controllers))
    sub.node_infos = {
        n: info
        for n, info in state.node_infos.items()
        if info.node is not None and node_schedulable(info.node)
    }
    sub.full = state
    return sub


@pytest.mark.parametrize("seed", range(4))
def test_incremental_semantic_equality(seed):
    rng = random.Random(7000 + seed)
    cache = SchedulerCache(clock=FakeClock(0.0))
    inc = IncrementalEncoder()
    cache.add_listener(inc.on_cache_event)
    live_nodes, live_pods, seq = {}, {}, [0]
    for _round in range(4):
        drive_events(rng, cache, 40, live_nodes, live_pods, seq)
        snap, _batch, _keep = inc.wave_view([rand_pending(rng, 0)])
        assert snap is not None
        v = inc.vocabs
        state = cache.snapshot()
        for name, info in state.node_infos.items():
            if info.node is None:
                slot = inc.slot_of[name]
                assert inc._node_gone[slot]
                continue
            slot = inc.slot_of[name]
            node = info.node
            # resources: cache aggregates vs incremental arrays
            assert snap.req_mcpu[slot] == info.requested_milli_cpu
            assert snap.req_mem[slot] == info.requested_memory
            assert snap.nz_mcpu[slot] == info.nonzero_milli_cpu
            assert snap.nz_mem[slot] == info.nonzero_memory
            assert snap.pod_count[slot] == len(info.pods)
            # labels: decode the kv bitset back to pairs
            got_kv = {
                kv
                for kv, kid in v.kv.ids.items()
                if snap.label_kv[slot, kid // 32] >> np.uint32(kid % 32) & 1
            }
            assert got_kv == set(node.metadata.labels.items())
            # taints (multiset via taint_count)
            from kubernetes_tpu.api.types import get_taints

            want_taints = {}
            for t in get_taints(node):
                k = (t.key, t.value, t.effect)
                want_taints[k] = want_taints.get(k, 0) + 1
            got_taints = {
                k: int(snap.taint_count[slot, tid])
                for k, tid in v.taints.ids.items()
                if snap.taint_count[slot, tid]
            }
            assert got_taints == want_taints
            # ports union
            want_ports = set()
            for p in info.pods:
                for c in p.spec.containers:
                    for pp in c.ports:
                        if pp.host_port:
                            want_ports.add(pp.host_port)
            got_ports = {
                port
                for port, pid in v.ports.ids.items()
                if snap.port_mask[slot, pid // 32] >> np.uint32(pid % 32) & 1
            }
            assert got_ports == want_ports
            # spread classes
            for ckey, cid in v.classes.ids.items():
                ns, labels_fs, deleted = ckey
                want = sum(
                    1
                    for p in info.pods
                    if p.namespace == ns
                    and frozenset(p.metadata.labels.items()) == labels_fs
                    and (p.metadata.deletion_timestamp is not None) == deleted
                )
                assert snap.class_count[slot, cid] == want
            # schedulability masking
            if node_schedulable(node):
                assert snap.alloc_mcpu[slot] > 0
            else:
                assert snap.alloc_pods[slot] == 0
        # every live slot maps to a live node or a gone-with-pods slot
        for name, slot in inc.slot_of.items():
            assert name in state.node_infos


@pytest.mark.parametrize("seed", range(4))
def test_incremental_decisions_match_oracle(seed):
    rng = random.Random(8000 + seed)
    cache = SchedulerCache(clock=FakeClock(0.0))
    svc_lister, rc_lister, rs_lister = _Lister(), _Lister(), _Lister()
    svc_lister.items = [
        Service(metadata=ObjectMeta(name="web"),
                spec=ServiceSpec(selector={"app": "web"}))
    ]
    algo = TPUScheduleAlgorithm(
        min_run=1, cache=cache, service_lister=svc_lister,
        controller_lister=rc_lister, replica_set_lister=rs_lister,
    )
    oracle = GenericScheduler(
        predicates=ORACLE_PREDICATES, priorities=ORACLE_PRIORITIES
    )
    live_nodes, live_pods, seq = {}, {}, [0]
    pend_seq = 0
    for _round in range(5):
        drive_events(rng, cache, 30, live_nodes, live_pods, seq)
        pending = []
        for _ in range(rng.randint(1, 12)):
            pend_seq += 1
            p = rand_pending(rng, pend_seq)
            pending += [p] * rng.randint(1, 4)  # runs of identical pods
        state = restricted_state(cache, services=svc_lister.items)
        want = oracle.schedule_backlog(pending, state.clone())
        got = algo.schedule_backlog(pending, state)
        assert got == want, f"seed {seed} round {_round}"
        # decisions consumed: mirror what binding would do, so later
        # rounds schedule against the updated cluster
        for p, host in zip(pending, want):
            if host is None:
                continue
            import copy

            bound = copy.deepcopy(p)
            bound.metadata.name = f"{p.metadata.name}-b{len(live_pods)}"
            bound.spec.node_name = host
            cache.add_pod(bound)
            live_pods[bound.metadata.name] = bound


def test_pod_on_unsynced_node_invalidates_name_order():
    """A pod_add for a node the cache hasn't seen materializes a new slot
    and changes name_desc_order; wave_view must not report it in `keep`
    (a stale device copy would desync selectHost's tie-breaking)."""
    cache = SchedulerCache(clock=FakeClock())
    inc = IncrementalEncoder()
    cache.add_listener(inc.on_cache_event)
    rng = random.Random(0)
    for i in range(4):
        cache.add_node(rand_node(rng, f"node-{i:03d}"))

    def plain_pod(name, node):
        # identical class (namespace/labels) and no ports: introduces no
        # new vocab entries, so no width growth masks the slot's dirt
        return Pod(
            metadata=ObjectMeta(name=name, labels={"app": "web"}),
            spec=PodSpec(node_name=node,
                         containers=[Container(requests={"cpu": "100m"})]),
        )

    cache.add_pod(plain_pod("seed", "node-000"))
    snap1, _, _ = inc.wave_view([plain_pod("pend-0", "")])
    assert snap1 is not None
    # informer races: the pod lands before its node object syncs
    cache.add_pod(plain_pod("racer", "zz-unsynced-node"))
    # the wave-2 pending pod is shape-identical so no vocab growth
    # re-dirties the node side by accident
    snap2, _, keep = inc.wave_view([plain_pod("pend-1", "")])
    assert snap2 is not None
    changed = not np.array_equal(snap1.name_desc_order, snap2.name_desc_order)
    assert changed
    assert "name_desc_order" not in keep


def test_daemon_warmup_compiles_incremental_shapes():
    """warmup() in daemon mode must compile the programs the incremental
    wave path will actually run — the full encoder's static shapes differ
    (padded vocab widths), so warming via it leaves the cold compile on
    the first real wave."""
    cache = SchedulerCache(clock=FakeClock())
    algo = TPUScheduleAlgorithm(cache=cache, service_lister=_Lister(),
                                controller_lister=_Lister(),
                                replica_set_lister=_Lister())
    algo.warmup(6)
    assert algo._wave.scan._jitted and algo._wave.probe._jitted
    # now drive a real wave of the same shape through the daemon path
    rng = random.Random(1)
    for i in range(6):
        cache.add_node(Node(
            metadata=ObjectMeta(name=f"node-{i:03d}",
                                labels={"app": "warm"}),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        ))
    pods = [Pod(metadata=ObjectMeta(name=f"p-{i}", labels={"app": "warm"}),
                spec=PodSpec(containers=[
                    Container(image="warm", requests={"cpu": "100m"})]))
            for i in range(max(algo._wave.min_run, 2))]
    state = restricted_state(cache)
    import logging

    import jax

    compiles = []

    class _H(logging.Handler):
        def emit(self, r):
            msg = r.getMessage()
            if "Finished XLA compilation" in msg:
                compiles.append(msg)

    h = _H()
    lg = logging.getLogger("jax._src.dispatch")
    prev_level = lg.level
    lg.addHandler(h)
    lg.setLevel(logging.DEBUG)
    jax.config.update("jax_log_compiles", True)
    try:
        got = algo.schedule_backlog(pods, state)
    finally:
        jax.config.update("jax_log_compiles", False)
        lg.removeHandler(h)
        lg.setLevel(prev_level)
    assert all(g is not None for g in got)
    # the wave must hit only programs warmup already compiled
    assert not compiles, compiles
