"""Audit subsystem tests: policy levels, the ring buffer, apiserver
integration (exactly-once per REST request, both doors), /debug/audit
on the muxes, and the kubectl surfaces (audit tail, top, get -w)."""

import json
import threading
import urllib.request

import pytest

from kubernetes_tpu import audit
from kubernetes_tpu.api import types as t
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client import LocalTransport, RESTClient


def make_api():
    audit.LOG.clear()
    return APIServer()


def pod_body(name, ns="default"):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"containers": [{"requests": {"cpu": "100m"}}]},
    }


class TestAuditPolicy:
    def test_levels_validate(self):
        assert audit.AuditPolicy("Metadata").level == "Metadata"
        with pytest.raises(ValueError):
            audit.AuditPolicy("Verbose")

    def test_none_drops_everything(self):
        p = audit.AuditPolicy("None")
        assert p.level_for("/api/v1/namespaces/default/pods") == "None"

    def test_observability_paths_exempt(self):
        p = audit.AuditPolicy("Metadata")
        for path in ("/healthz", "/metrics", "/debug/audit",
                     "/debug/traces", "/configz", "/ui", "/api",
                     "/apis/extensions/v1beta1", "/swaggerapi/foo"):
            assert p.level_for(path) == "None", path

    def test_resource_paths_audited(self):
        p = audit.AuditPolicy("Request")
        assert p.level_for("/api/v1/namespaces/default/pods") == "Request"
        assert p.level_for("/apis/extensions/v1beta1/jobs") == "Request"

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("KUBERNETES_TPU_AUDIT", "off")
        assert audit.AuditPolicy.from_env().level == "None"
        monkeypatch.setenv("KUBERNETES_TPU_AUDIT", "Request")
        assert audit.AuditPolicy.from_env().level == "Request"
        monkeypatch.delenv("KUBERNETES_TPU_AUDIT")
        assert audit.AuditPolicy.from_env().level == "Metadata"


class TestAuditLog:
    def test_ring_is_bounded_and_newest_first(self):
        log = audit.AuditLog(capacity=4)
        for i in range(10):
            log.record({"requestID": f"r{i}", "verb": "get"})
        items = log.snapshot(limit=10)
        assert [e["requestID"] for e in items] == ["r9", "r8", "r7", "r6"]
        assert log.total_recorded == 10

    def test_snapshot_filters(self):
        log = audit.AuditLog(capacity=16)
        log.record({"user": "alice", "verb": "create", "resource": "pods"})
        log.record({"user": "bob", "verb": "delete", "resource": "nodes"})
        assert len(log.snapshot(user="alice")) == 1
        assert log.snapshot(verb="delete")[0]["user"] == "bob"
        assert log.snapshot(resource="pods")[0]["verb"] == "create"

    def test_jsonl_sink(self, tmp_path):
        path = tmp_path / "audit.jsonl"
        log = audit.AuditLog(capacity=8, sink_path=str(path))
        log.record({"requestID": "r1", "verb": "create", "code": 201})
        log.record({"requestID": "r2", "verb": "delete", "code": 200})
        log.close()
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        assert [e["requestID"] for e in lines] == ["r1", "r2"]


class TestAPIServerAudit:
    def test_mutating_request_audited_exactly_once(self):
        api = make_api()
        code, _ = api.handle(
            "POST", "/api/v1/namespaces/default/pods", None,
            pod_body("audit-p1"),
        )
        assert code == 201
        code, out = api.handle("GET", "/debug/audit", {}, None)
        assert code == 200
        evs = [e for e in out["items"] if e.get("name") == "audit-p1"]
        assert len(evs) == 1
        ev = evs[0]
        assert ev["verb"] == "create"
        assert ev["resource"] == "pods"
        assert ev["namespace"] == "default"
        assert ev["code"] == 201
        assert ev["latencySeconds"] >= 0
        assert ev["requestID"]

    def test_verbs_mapped_from_method_and_path(self):
        api = make_api()
        api.handle("POST", "/api/v1/namespaces/default/pods", None,
                   pod_body("vm1"))
        api.handle("GET", "/api/v1/namespaces/default/pods", {}, None)
        api.handle("GET", "/api/v1/namespaces/default/pods/vm1", {}, None)
        api.handle("DELETE", "/api/v1/namespaces/default/pods/vm1", {}, None)
        verbs = [e["verb"] for e in audit.LOG.snapshot(limit=10)]
        assert verbs[:4] == ["delete", "get", "list", "create"]

    def test_error_responses_audited_with_code(self):
        api = make_api()
        api.handle("GET", "/api/v1/namespaces/default/pods/ghost", {}, None)
        ev = audit.LOG.snapshot(limit=1)[0]
        assert ev["code"] == 404 and ev["verb"] == "get"

    def test_request_level_includes_body_summary(self):
        api = make_api()
        api.audit_policy = audit.AuditPolicy("Request")
        api.handle("POST", "/api/v1/namespaces/default/pods", None,
                   pod_body("req-lvl"))
        ev = audit.LOG.snapshot(limit=1)[0]
        assert ev["level"] == "Request"
        assert ev["requestObject"]["metadata"]["name"] == "req-lvl"

    def test_level_none_disables(self):
        api = make_api()
        api.audit_policy = audit.AuditPolicy("None")
        api.handle("POST", "/api/v1/namespaces/default/pods", None,
                   pod_body("quiet"))
        assert not any(
            e.get("name") == "quiet" for e in audit.LOG.snapshot(limit=50)
        )

    def test_observability_reads_not_audited(self):
        api = make_api()
        api.handle("GET", "/metrics", {}, None)
        api.handle("GET", "/debug/audit", {}, None)
        api.handle("GET", "/healthz", {}, None)
        assert audit.LOG.total_recorded == 0

    def test_audit_counter_increments(self):
        from kubernetes_tpu.metrics import apiserver_audit_event_total

        api = make_api()
        before = apiserver_audit_event_total.get(
            level="Metadata", verb="create"
        )
        api.handle("POST", "/api/v1/namespaces/default/pods", None,
                   pod_body("ctr"))
        after = apiserver_audit_event_total.get(
            level="Metadata", verb="create"
        )
        assert after == before + 1


class TestAuditOverHTTP:
    def test_http_request_audited_once_with_user(self):
        from kubernetes_tpu.apiserver.http_frontend import start_http_server
        from kubernetes_tpu.auth.authn import TokenAuthenticator, UserInfo

        api = make_api()
        api.authenticator = TokenAuthenticator(
            {"tok1": UserInfo("alice", "u1", ())}
        )
        server, port = start_http_server(api, "127.0.0.1", 0)
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/namespaces/default/pods",
                data=json.dumps(pod_body("http-p")).encode(),
                method="POST",
                headers={
                    "Content-Type": "application/json",
                    "Authorization": "Bearer tok1",
                    "X-Request-Id": "trail-42",
                },
            )
            with urllib.request.urlopen(req) as r:
                assert r.status == 201
            audit_req = urllib.request.Request(
                f"http://127.0.0.1:{port}/debug/audit",
                headers={"Authorization": "Bearer tok1"},
            )
            with urllib.request.urlopen(audit_req) as r:
                out = json.loads(r.read())
        finally:
            server.shutdown()
        evs = [e for e in out["items"] if e.get("name") == "http-p"]
        assert len(evs) == 1  # exactly once through the HTTP door
        assert evs[0]["user"] == "alice"
        assert evs[0]["requestID"] == "trail-42"

    def test_denied_requests_are_audited(self):
        from kubernetes_tpu.apiserver.http_frontend import start_http_server
        from kubernetes_tpu.auth.authn import TokenAuthenticator, UserInfo

        api = make_api()
        api.authenticator = TokenAuthenticator(
            {"good": UserInfo("alice", "u1", ())}
        )
        server, port = start_http_server(api, "127.0.0.1", 0)
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/namespaces/default/pods",
                headers={"Authorization": "Bearer wrong"},
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req)
            assert exc.value.code == 401
        finally:
            server.shutdown()
        denied = [e for e in audit.LOG.snapshot(limit=10)
                  if e["code"] == 401]
        assert len(denied) == 1
        assert denied[0]["user"] == "system:anonymous"

    def test_component_mux_serves_audit(self):
        from kubernetes_tpu.trace.httpd import start_component_server

        audit.LOG.clear()
        audit.record("Metadata", "carol", "delete", "nodes", "", "n1",
                     200, 0.002)
        server, port = start_component_server(name="test-mux")
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/audit?user=carol"
            ) as r:
                out = json.loads(r.read())
        finally:
            server.shutdown()
        assert out["kind"] == "AuditEventList"
        assert out["items"][0]["verb"] == "delete"


class TestKubectlSurfaces:
    def test_audit_tail_renders_trail(self):
        from kubernetes_tpu.kubectl.cmd import Kubectl

        api = make_api()
        client = RESTClient(LocalTransport(api))
        k = Kubectl(client)
        client.resource("pods", "default")  # no-op, path sanity
        api.handle("POST", "/api/v1/namespaces/default/pods", None,
                   pod_body("tail-p"))
        out = k.audit_tail(limit=5)
        assert "VERB" in out and "create" in out and "tail-p" in out
        as_json = json.loads(k.audit_tail(limit=5, output="json"))
        assert any(e.get("name") == "tail-p" for e in as_json)

    def test_audit_tail_filters_by_verb(self):
        from kubernetes_tpu.kubectl.cmd import Kubectl

        api = make_api()
        client = RESTClient(LocalTransport(api))
        k = Kubectl(client)
        api.handle("POST", "/api/v1/namespaces/default/pods", None,
                   pod_body("f1"))
        api.handle("GET", "/api/v1/namespaces/default/pods", {}, None)
        filtered = json.loads(
            k.audit_tail(limit=10, output="json", verb="create")
        )
        assert filtered and all(e["verb"] == "create" for e in filtered)

    def test_get_events_watch_streams_rows(self):
        from kubernetes_tpu.kubectl.cmd import Kubectl

        api = make_api()
        client = RESTClient(LocalTransport(api))
        k = Kubectl(client)

        def emit_later():
            ev = t.Event(
                metadata=t.ObjectMeta(name="we.1", namespace="default"),
                involved_object=t.ObjectReference(
                    kind="Pod", namespace="default", name="watched-pod"
                ),
                reason="Scheduled", message="bound", type="Normal",
                source_component="scheduler", count=1,
                first_timestamp="t", last_timestamp="t",
            )
            client.resource("events", "default").create(ev)

        timer = threading.Timer(0.2, emit_later)
        timer.start()
        lines = []
        out = k.get_watch("events", max_events=1, out=lines.append)
        timer.join()
        assert "LASTSEEN" in lines[0]  # header row
        assert any("watched-pod" in l and "Scheduled" in l for l in lines)
        assert out == "\n".join(lines)


class TestKubeletSummary:
    def _kubelet_stub(self):
        class Cfg:
            node_name = "node-a"

        class Runtime:
            def pod_stats(self, uid):
                return {
                    "main": {
                        "memory_rss_bytes": 1 << 20,
                        "cpu_jiffies": 250,
                    },
                }

        class KL:
            config = Cfg()
            runtime = Runtime()
            eviction_manager = None
            _lock = threading.Lock()
            _pods = {}

        kl = KL()
        p = t.Pod(
            metadata=t.ObjectMeta(
                name="sp", namespace="default", uid="u1"
            ),
            spec=t.PodSpec(containers=[t.Container(
                name="main",
                requests={"alpha.kubernetes.io/nvidia-gpu": 2},
            )]),
        )
        kl._pods = {"u1": p}
        return kl

    def test_summary_reports_cpu_memory_devices(self):
        from kubernetes_tpu.kubelet.server import build_summary

        s = build_summary(self._kubelet_stub())
        assert s["node"]["nodeName"] == "node-a"
        pod = s["pods"][0]
        assert pod["podRef"]["name"] == "sp"
        assert pod["memory"]["rssBytes"] == 1 << 20
        assert pod["cpu"]["usageCoreSeconds"] > 0
        assert pod["devices"]["requested"] == 2
        assert pod["containers"][0]["name"] == "main"
        # node aggregates roll up the pods
        assert s["node"]["memory"]["workingSetBytes"] == 1 << 20
        assert s["node"]["devices"]["requested"] == 2

    def test_summary_tolerates_statless_runtime(self):
        from kubernetes_tpu.kubelet.server import build_summary

        kl = self._kubelet_stub()
        kl.runtime = object()  # no pod_stats attr (FakeRuntime-like)
        s = build_summary(kl)
        assert s["pods"][0]["containers"] == []
        assert s["pods"][0]["devices"]["requested"] == 2


class TestControlLoopMetrics:
    def test_named_workqueue_exports_families(self):
        from kubernetes_tpu.metrics import (
            workqueue_adds_total,
            workqueue_depth,
            workqueue_queue_duration_seconds,
            workqueue_work_duration_seconds,
        )
        from kubernetes_tpu.utils.workqueue import RateLimitingQueue

        q = RateLimitingQueue(name="metrics-probe")
        before = workqueue_adds_total.get(name="metrics-probe")
        q.add("k1")
        assert workqueue_depth.values()["metrics-probe"] == 1
        item = q.get(timeout=1)
        assert workqueue_depth.values()["metrics-probe"] == 0
        q.done(item)
        q.shut_down()
        assert workqueue_adds_total.get(name="metrics-probe") == before + 1
        assert (
            workqueue_queue_duration_seconds.labels("metrics-probe").count
            >= 1
        )
        assert (
            workqueue_work_duration_seconds.labels("metrics-probe").count
            >= 1
        )

    def test_retries_counted(self):
        from kubernetes_tpu.metrics import workqueue_retries_total
        from kubernetes_tpu.utils.workqueue import RateLimitingQueue

        q = RateLimitingQueue(name="retry-probe", base_delay=0.001)
        before = workqueue_retries_total.get(name="retry-probe")
        q.add_rate_limited("k")
        assert workqueue_retries_total.get(name="retry-probe") == before + 1
        q.shut_down()

    def test_named_fifo_reports_depth(self):
        from kubernetes_tpu.client.cache.fifo import FIFO
        from kubernetes_tpu.metrics import workqueue_depth

        q = FIFO(name="fifo-probe")
        q.add(t.Pod(metadata=t.ObjectMeta(name="p", namespace="d")))
        assert workqueue_depth.values()["fifo-probe"] == 1
        q.pop(timeout=1)
        assert workqueue_depth.values()["fifo-probe"] == 0

    def test_named_fifo_delete_drops_enqueue_timestamp(self):
        from kubernetes_tpu.client.cache.fifo import FIFO
        from kubernetes_tpu.metrics import workqueue_depth

        q = FIFO(name="fifo-del-probe")
        p = t.Pod(metadata=t.ObjectMeta(name="p", namespace="d"))
        q.add(p)
        q.delete(p)
        # delete must clean the timestamp map (no leak, no phantom
        # queue-wait on a later re-add of the same key) and fix depth
        assert q._added_at == {}
        assert workqueue_depth.values()["fifo-del-probe"] == 0
        q.add(p)
        assert len(q._added_at) == 1
        q.pop(timeout=1)
        assert q._added_at == {}

    def test_reflector_and_watch_metrics(self):
        from kubernetes_tpu.client.cache import Store
        from kubernetes_tpu.client.cache.reflector import Reflector
        from kubernetes_tpu.client.cache.store import (
            meta_namespace_key_func,
        )
        from kubernetes_tpu.metrics import (
            reflector_lists_total,
            watch_events_total,
        )

        api = make_api()
        client = RESTClient(LocalTransport(api))
        store = Store(meta_namespace_key_func)
        refl = Reflector(
            client.resource("pods", "default"), store,
            name="probe-pods",
        ).run()
        try:
            assert refl.wait_for_sync(5)
            assert reflector_lists_total.get(name="probe-pods") >= 1
            api.handle("POST", "/api/v1/namespaces/default/pods", None,
                       pod_body("refl-p"))
            from tests.conftest import wait_until

            assert wait_until(
                lambda: watch_events_total.get(
                    name="probe-pods", type="ADDED"
                ) >= 1,
                timeout=5,
            )
        finally:
            refl.stop()


class TestMetricsEndpointIntegration:
    def test_controller_queue_renders_on_metrics(self):
        # a named controller-style queue that has seen work shows up in
        # the text exposition with depth + duration families
        from kubernetes_tpu.controller.framework import QueueWorker
        from kubernetes_tpu.metrics import registry

        done = threading.Event()

        def sync(key):
            done.set()

        w = QueueWorker("probe-controller", sync).run()
        w.enqueue("k")
        assert done.wait(5)
        w.stop()
        text = registry.render()
        assert 'workqueue_depth{name="probe-controller"}' in text
        assert 'workqueue_work_duration_seconds_count{name="probe-controller"}' in text
