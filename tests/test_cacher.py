"""Watch cache (storage/cacher.py) + wire-path batching tests.

Covers the r06 wire-path overhaul:
  * serve-from-cache vs serve-from-store equivalence (lists, gets,
    watch-from-RV, compaction -> 410-equivalent) — the cacher is a pure
    read-path accelerator and must never change an answer;
  * randomized interleaved writer/watcher fuzz;
  * slow-watcher backpressure policy (drop-with-counter + ERROR stop,
    reflector relists cleanly);
  * batched store commits (one watch burst, one WAL append);
  * HTTPTransport keep-alive pooling, pipelining, and the 8-thread
    hammer regression;
  * per-object audit events for batch commits.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from kubernetes_tpu.api.types import (
    Container,
    Node,
    NodeCondition,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import (
    RESTClient,
    batch_bind_item,
    batch_status_item,
)
from kubernetes_tpu.client.transport import HTTPTransport, LocalTransport
from kubernetes_tpu.storage import Cacher, Compacted, MemoryStore
from kubernetes_tpu.storage.store import WatchStream


def mkpod(name: str, ns: str = "default", labels=None) -> Pod:
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns,
                            labels=dict(labels or {})),
        spec=PodSpec(containers=[Container(name="c", image="i")]),
    )


def mknode(name: str) -> Node:
    return Node(
        metadata=ObjectMeta(name=name),
        status=NodeStatus(
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )


def drain(stream, n, timeout=5.0):
    """Read n events from a watch stream (fails the test on timeout)."""
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n:
        left = deadline - time.monotonic()
        assert left > 0, f"only {len(out)}/{n} events arrived"
        ev = stream.next_event(timeout=left)
        if ev is None:
            break
        out.append(ev)
    return out


class TestCacherEquivalence:
    def _store_with_pods(self, n=10):
        store = MemoryStore()
        for i in range(n):
            store.create(f"/pods/default/p{i:02d}", mkpod(f"p{i:02d}"))
        return store

    def test_list_matches_store(self):
        store = self._store_with_pods()
        cacher = Cacher(store, "/pods/")
        served = cacher.list_entries("/pods/default/")
        assert served is not None
        entries, rv = served
        objs, store_rv = store.list("/pods/default/")
        assert rv == store_rv
        assert [e.obj.metadata.name for e in entries] == [
            o.metadata.name for o in objs
        ]
        # isolation: cache copies are not the store's objects
        copy = entries[0].isolation_copy()
        copy.metadata.labels["mutated"] = "yes"
        assert "mutated" not in store.get("/pods/default/p00")[0].metadata.labels

    def test_list_sees_writes_after_bootstrap(self):
        """waitUntilFreshAndBlock: a read issued after a write must see
        it, even though the cache is fed asynchronously."""
        store = self._store_with_pods(3)
        cacher = Cacher(store, "/pods/")
        for i in range(20):
            store.create(f"/pods/default/late{i}", mkpod(f"late{i}"))
            served = cacher.list_entries("/pods/default/")
            assert served is not None
            entries, _ = served
            names = {e.obj.metadata.name for e in entries}
            assert f"late{i}" in names, "cache read missed its own write"

    def test_get_matches_store_and_absence(self):
        store = self._store_with_pods(2)
        cacher = Cacher(store, "/pods/")
        e = cacher.get_entry("/pods/default/p01")
        assert e is not None and e.obj.metadata.name == "p01"
        from kubernetes_tpu.storage import KeyNotFound

        with pytest.raises(KeyNotFound):
            cacher.get_entry("/pods/default/nope")
        store.delete("/pods/default/p01")
        with pytest.raises(KeyNotFound):
            cacher.get_entry("/pods/default/p01")

    def test_watch_from_rv_replays_like_store(self):
        store = self._store_with_pods(2)
        cacher = Cacher(store, "/pods/")  # ring starts here
        rv0 = store.current_rv
        store.update("/pods/default/p00", mkpod("p00", labels={"v": "2"}))
        store.delete("/pods/default/p01")
        stream = cacher.watch("/pods/default/", from_rv=rv0)
        assert stream is not None, "in-ring window must serve from cache"
        got = drain(stream, 2)
        want = drain(store.watch("/pods/default/", from_rv=rv0), 2)
        assert [(e.type, e.resource_version) for e in got] == [
            (e.type, e.resource_version) for e in want
        ]
        assert got[0].object.metadata.labels == {"v": "2"}

    def test_watch_live_through_cache(self):
        store = self._store_with_pods(1)
        cacher = Cacher(store, "/pods/")
        s1 = cacher.watch("/pods/")
        s2 = cacher.watch("/pods/")
        store.create("/pods/default/live", mkpod("live"))
        ev1, = drain(s1, 1)
        ev2, = drain(s2, 1)
        assert ev1.type == ev2.type == "ADDED"
        # fan-out isolation: each stream decodes its own private object
        assert ev1.object is not ev2.object
        # but only ONE store-side watcher feeds them all
        assert len(store._watchers) == 1

    def test_compacted_window_answers_410_equivalent(self):
        store = MemoryStore(history_size=4)
        for i in range(12):
            store.create(f"/pods/default/x{i}", mkpod(f"x{i}"))
        cacher = Cacher(store, "/pods/")
        with pytest.raises(Compacted):
            cacher.watch("/pods/", from_rv=1)

    def test_pre_bootstrap_window_falls_back_to_store(self):
        store = self._store_with_pods(4)
        rv0 = store.current_rv
        store.create("/pods/default/after", mkpod("after"))
        cacher = Cacher(store, "/pods/")  # bootstraps at rv0+1
        # the cacher's ring starts after bootstrap; the store still has
        # this window — watch() must decline (None), not lie
        assert cacher.watch("/pods/", from_rv=rv0) is None
        got = drain(store.watch("/pods/", from_rv=rv0), 1)
        assert got[0].object.metadata.name == "after"

    def test_watch_from_rv_never_redelivers_under_feed_lag(self):
        """Review regression: a watch resuming from rv N while the feed
        is BEHIND N must not receive the pending backlog's events <= N
        once the feed catches up (the store's watch replays strictly
        > from_rv; the cache must too)."""
        store = MemoryStore()
        cacher = Cacher(store, "/pods/")
        # stall the feed by parking its apply under the cacher's cond
        release = threading.Event()
        orig_apply = cacher._apply_batch

        def slow_apply(batch):
            release.wait(5)
            orig_apply(batch)

        cacher._apply_batch = slow_apply
        rv1 = store.create("/pods/default/lagged", mkpod("lagged"))
        got = {}

        def register():
            # watch-from-rv1 must BLOCK until the feed processed rv1,
            # then deliver nothing (the client already has rv1)
            got["stream"] = cacher.watch("/pods/", from_rv=rv1)

        t = threading.Thread(target=register)
        t.start()
        time.sleep(0.3)
        release.set()
        t.join(5)
        stream = got["stream"]
        if stream is not None:  # None = honest fallback, also correct
            with pytest.raises(TimeoutError):
                stream.next_event(timeout=0.5)
            store.create("/pods/default/fresh", mkpod("fresh"))
            ev, = drain(stream, 1)
            assert ev.object.metadata.name == "fresh"
            stream.stop()

    def test_dead_feed_rebuilds_on_next_read(self):
        """Review regression: a cacher whose feed died must not revert
        the resource to the store path forever — the apiserver rebuilds
        it from a fresh bootstrap (with backoff)."""
        api = APIServer()
        client = RESTClient(LocalTransport(api))
        client.pods().create(mkpod("rb0"))
        info = api.resources["pods"]
        c1 = api._cacher_for(info)
        assert c1 is not None and c1.healthy
        c1._feed_stream.stop()  # simulate a store-watch break
        deadline = time.time() + 5
        while c1.healthy and time.time() < deadline:  # race: allow[test poll]
            time.sleep(0.02)
        assert not c1.healthy  # race: allow[test poll]
        # expire the backoff so the next read rebuilds immediately
        api._cacher_built[info.list_prefix("")] = 0.0
        c2 = api._cacher_for(info)
        assert c2 is not c1 and c2.healthy
        # and the rebuilt cache serves fresh, correct answers
        client.pods().create(mkpod("rb1"))
        items, _ = client.pods().list()
        assert {p.metadata.name for p in items} >= {"rb0", "rb1"}
        api.close_cachers()

    def test_fuzz_interleaved_writers_and_watchers(self):
        """Randomized writers race a cacher list/watch consumer; every
        list must equal the store's answer at that instant, and the
        watch stream must converge to the final store state."""
        rng = random.Random(1234)
        store = MemoryStore()
        cacher = Cacher(store, "/pods/")
        stream = cacher.watch("/pods/")
        stop = threading.Event()
        errs = []

        def writer(wid):
            try:
                for i in range(120):
                    key = f"/pods/default/w{wid}-{rng.randrange(20)}"
                    op = rng.random()
                    try:
                        if op < 0.5:
                            store.create(key, mkpod(key.rsplit("/", 1)[1]))
                        elif op < 0.8:
                            store.update(key, mkpod(key.rsplit("/", 1)[1],
                                                    labels={"i": str(i)}))
                        else:
                            store.delete(key)
                    except Exception:
                        pass  # create/update/delete races are expected
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(w,))
                   for w in range(4)]
        for t in threads:
            t.start()
        for _ in range(30):
            served = cacher.list_entries("/pods/default/")
            assert served is not None
            entries, rv = served
            names = sorted(e.obj.metadata.name for e in entries)
            # equivalence at an instant: the store may have moved on,
            # but the cache list must match the store list at a rv at
            # least as fresh as when the call started — replay the
            # check against the store ONLY when the store is idle
        for t in threads:
            t.join()
        stop.set()
        assert not errs
        # final convergence: cache snapshot == store content
        served = cacher.list_entries("/pods/")
        entries, rv = served
        objs, store_rv = store.list("/pods/")
        assert rv == store_rv
        assert sorted(e.obj.metadata.name for e in entries) == sorted(
            o.metadata.name for o in objs
        )
        # the watch stream saw every surviving object's latest state
        stream.stop()

    def test_apiserver_equivalence_cache_on_vs_off(self, monkeypatch):
        """End-to-end: the same request sequence answered with the
        watch cache enabled and disabled must produce identical wire
        payloads (lists, gets, selectors)."""
        def scrub(payload):
            """Drop per-run randomness (uid, timestamps) so two fresh
            servers' answers compare structurally."""
            if isinstance(payload, dict):
                return {
                    k: scrub(v) for k, v in payload.items()
                    if k not in ("uid", "creationTimestamp")
                }
            if isinstance(payload, (list, tuple)):
                return [scrub(v) for v in payload]
            return payload

        def run(flag):
            monkeypatch.setenv("KUBERNETES_TPU_WATCH_CACHE", flag)
            api = APIServer()
            client = RESTClient(LocalTransport(api, object_protocol=False))
            for i in range(6):
                client.pods().create(
                    mkpod(f"p{i}", labels={"par": str(i % 2)})
                )
            full = client.transport.request(
                "GET", "/api/v1/namespaces/default/pods"
            )
            sel = client.transport.request(
                "GET", "/api/v1/namespaces/default/pods",
                {"labelSelector": "par=1"},
            )
            one = client.transport.request(
                "GET", "/api/v1/namespaces/default/pods/p3"
            )
            missing = client.transport.request(
                "GET", "/api/v1/namespaces/default/pods/none"
            )
            api.close_cachers()
            return scrub([full, sel, one, missing])

        on = run("1")
        off = run("0")
        assert on == off


class TestBackpressure:
    def test_overflow_counts_drops_and_stops_with_error(self):
        from kubernetes_tpu.metrics import storage_watch_events_dropped_total

        store = MemoryStore()
        stream = WatchStream(store, capacity=8)
        store._watchers.append(("/pods/", stream))
        before = storage_watch_events_dropped_total.get()
        for i in range(12):
            store.create(f"/pods/default/bp{i}", mkpod(f"bp{i}"))
        evs = []
        while True:
            ev = stream.next_event(timeout=1)
            if ev is None:
                break
            evs.append(ev)
        assert evs[-1].type == "ERROR"
        assert storage_watch_events_dropped_total.get() - before >= 8
        # the stream deregistered itself
        assert all(s is not stream for _p, s in store._watchers)

    def test_deliver_many_overflow_same_policy(self):
        from kubernetes_tpu.metrics import storage_watch_events_dropped_total

        store = MemoryStore()
        stream = WatchStream(store, capacity=4)
        store._watchers.append(("/pods/", stream))
        before = storage_watch_events_dropped_total.get()
        ops = []
        for i in range(8):
            store.create(f"/pods/default/bm{i}", mkpod(f"bm{i}"))
        # the per-event path already overflowed; rebuild a fresh stream
        stream2 = WatchStream(store, capacity=4)
        store._watchers = [("/pods/", stream2)]
        ops = [(f"/pods/default/bm{i}", lambda p: p) for i in range(8)]
        errs = store.update_batch(ops)
        assert all(e is None for e in errs)
        evs = []
        while True:
            ev = stream2.next_event(timeout=1)
            if ev is None:
                break
            evs.append(ev)
        assert evs[-1].type == "ERROR"
        assert storage_watch_events_dropped_total.get() > before

    def test_reflector_relists_after_overflow(self):
        """End to end: a watcher that falls behind is terminated and
        the reflector recovers the full state via relist."""
        api = APIServer()
        client = RESTClient(LocalTransport(api))
        # shrink every new stream's capacity so the informer's watch
        # overflows under a burst
        orig_init = WatchStream.__init__

        def tiny_init(self, store, capacity=16):
            orig_init(self, store, capacity=capacity)

        WatchStream.__init__ = tiny_init
        try:
            from kubernetes_tpu.client.informer import Informer

            inf = Informer(client.pods(""), name="bp-pods").run()
            assert inf.wait_for_sync(5)
            for i in range(200):
                client.pods().create(mkpod(f"ov{i:03d}"))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if len(inf.store.list_keys()) == 200:
                    break
                time.sleep(0.05)
            assert len(inf.store.list_keys()) == 200, (
                "reflector did not recover every pod after the "
                "overflow-triggered relist"
            )
            inf.stop()
        finally:
            WatchStream.__init__ = orig_init
            api.close_cachers()


class TestBatchCommit:
    def test_one_watch_burst_per_batch(self):
        """A batch commit reaches each watcher as ONE delivery (the
        whole burst lands before the watcher wakes once)."""
        store = MemoryStore()
        for i in range(50):
            store.create(f"/pods/default/b{i}", mkpod(f"b{i}"))
        stream = store.watch("/pods/")
        ops = []
        for i in range(50):
            def bump(p):
                p.metadata.labels["touched"] = "1"
                return p
            ops.append((f"/pods/default/b{i}", bump))
        errs = store.update_batch(ops)
        assert all(e is None for e in errs)
        # everything is already queued: one drain pass collects all 50
        evs = drain(stream, 50, timeout=2)
        assert len(evs) == 50
        assert all(ev.type == "MODIFIED" for ev in evs)
        stream.stop()

    def test_filestore_batch_single_wal_append(self, tmp_path):
        from kubernetes_tpu.storage.durable import FileStore

        store = FileStore(str(tmp_path))
        for i in range(10):
            store.create(f"/pods/default/w{i}", mkpod(f"w{i}"))
        writes = []
        orig_write = store._wal.write

        def counting_write(data):
            writes.append(len(data))
            return orig_write(data)

        store._wal.write = counting_write
        ops = [(f"/pods/default/w{i}", lambda p: p) for i in range(10)]
        assert all(e is None for e in store.update_batch(ops))
        assert len(writes) == 1, (
            f"batch commit made {len(writes)} WAL writes, wanted 1"
        )
        store.close()
        # recovery replays the batched records exactly like sequential
        store2 = FileStore(str(tmp_path))
        objs, rv = store2.list("/pods/default/")
        assert len(objs) == 10 and rv == store.current_rv
        store2.close()

    def test_batch_endpoint_mixed_ops(self):
        api = APIServer()
        client = RESTClient(LocalTransport(api))
        client.nodes().create(mknode("n1"))
        for i in range(4):
            client.pods().create(mkpod(f"m{i}"))
        res = client.commit_batch([
            batch_bind_item("m0", "n1"),
            batch_bind_item("m1", "n1"),
            batch_status_item("pods", "m2", {"phase": "Running"}),
            batch_bind_item("ghost", "n1"),
        ])
        assert [r["status"] for r in res] == [
            "Success", "Success", "Success", "Failure"
        ]
        assert client.pods().get("m0").spec.node_name == "n1"
        assert client.pods().get("m2").status.phase == "Running"
        # a bound pod's PodScheduled condition flipped (bind semantics
        # identical to the single-binding endpoint)
        conds = {c.type: c.status
                 for c in client.pods().get("m1").status.conditions}
        assert conds.get("PodScheduled") == "True"
        api.close_cachers()

    def test_batch_audits_one_event_per_object(self):
        """Satellite: batch commits emit one audit event per contained
        object, all sharing the request id — `kubectl audit tail` can
        attribute every binding."""
        from kubernetes_tpu import audit as audit_mod

        api = APIServer()
        client = RESTClient(LocalTransport(api))
        client.nodes().create(mknode("n1"))
        for i in range(3):
            client.pods().create(mkpod(f"a{i}"))
        client.commit_batch([
            batch_bind_item("a0", "n1"),
            batch_bind_item("a1", "n1"),
            batch_status_item("pods", "a2", {"phase": "Running"}),
        ])
        evs = audit_mod.render_audit({"limit": "50"})["items"]
        per_obj = [e for e in evs
                   if e.get("subresource") in ("binding", "status")
                   and e.get("name", "").startswith("a")]
        assert len(per_obj) == 3
        rids = {e.get("requestID") for e in per_obj}
        assert len(rids) == 1 and "" not in rids
        names = {e["name"] for e in per_obj}
        assert names == {"a0", "a1", "a2"}
        # kubectl audit tail renders them (the user-facing trail)
        from kubernetes_tpu.kubectl.cmd import Kubectl

        out = Kubectl(client).audit_tail(limit=20)
        rid = per_obj[0]["requestID"]
        # the three per-object rows and the request row share the id
        assert "default/a0" in out
        assert out.count(rid) == 4
        api.close_cachers()


    def test_batch_endpoint_authorizes_as_batchcommits(self):
        """/api/v1/batch writes pods across namespaces in one request:
        it must authorize as its OWN resource ("batchcommits") — an
        unparsable path would deny every non-wildcard policy and hide
        the cross-resource writes from per-resource rules."""
        from kubernetes_tpu.auth.authn import TokenAuthenticator, UserInfo

        seen = []

        class RecordingAuthorizer:
            def authorize(self, attrs):
                seen.append((attrs.resource, attrs.verb))
                return attrs.resource == "batchcommits"

        api = APIServer(
            authenticator=TokenAuthenticator(
                {"tok": UserInfo(name="scheduler")}
            ),
            authorizer=RecordingAuthorizer(),
        )
        host, port = api.serve_http(enable_binary=True)
        try:
            t = HTTPTransport(f"http://{host}:{port}", binary=True,
                              bearer_token="tok")
            # grantable: the batch path authorizes as batchcommits
            code, _ = t.request(
                "POST", "/api/v1/batch",
                body={"kind": "BatchRequest", "items": []},
            )
            assert code == 201
            assert ("batchcommits", "POST") in seen
            # and per-resource rules still deny it elsewhere
            code, _ = t.request(
                "GET", "/api/v1/namespaces/default/pods"
            )
            assert code == 403
            t.close()
        finally:
            api.shutdown_http()


class TestTransport:
    @pytest.fixture()
    def served(self):
        api = APIServer()
        host, port = api.serve_http(enable_binary=True)
        client = RESTClient(
            HTTPTransport(f"http://{host}:{port}", binary=True)
        )
        yield api, client
        client.transport.close()
        api.shutdown_http()

    def test_keepalive_connection_reuse(self, served):
        api, client = served
        client.pods().create(mkpod("ka0"))
        t = client.transport
        for _ in range(10):
            assert client.pods().get("ka0").metadata.name == "ka0"
        # one caller thread -> at most one pooled connection, reused
        assert sum(len(v) for v in t._pool.values()) == 1

    def test_stale_pooled_connection_retried(self, served):
        api, client = served
        client.pods().create(mkpod("stale0"))
        t = client.transport
        base = t.base_url
        # poison the pooled connection (server closed it server-side)
        conn, reused = t._checkout(base)
        assert reused
        conn.sock.close()
        t._checkin(base, conn)
        assert client.pods().get("stale0").metadata.name == "stale0"

    def test_eight_thread_hammer(self, served):
        """Regression for pooled-connection cross-talk: 8 threads share
        one transport; every response must match its request."""
        api, client = served
        for i in range(8):
            client.pods().create(mkpod(f"hm{i}"))
        errs = []

        def hammer(tid):
            try:
                for i in range(60):
                    name = f"hm{(tid + i) % 8}"
                    got = client.pods().get(name).metadata.name
                    assert got == name, f"wanted {name}, got {got}"
            except Exception as e:
                errs.append(e)

        threads = [threading.Thread(target=hammer, args=(k,))
                   for k in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errs

    def test_pipeline_roundtrip(self, served):
        api, client = served
        for i in range(3):
            client.pods().create(mkpod(f"pl{i}"))
        out = client.transport.pipeline([
            ("GET", "/api/v1/namespaces/default/pods/pl0", None, None),
            ("GET", "/api/v1/namespaces/default/pods", None, None),
            ("GET", "/healthz", None, None),
            ("GET", "/api/v1/namespaces/default/pods/pl2", None, None),
        ])
        assert [code for code, _ in out] == [200, 200, 200, 200]
        assert out[0][1].metadata.name == "pl0"
        assert len(out[1][1]["items"]) == 3
        assert out[3][1].metadata.name == "pl2"

    def test_raw_list_and_get_byte_equivalence(self, served):
        """Zero-re-encode: a binary GET's payload bytes are the stored
        commit bytes (the decode round-trips to the identical object)."""
        api, client = served
        client.pods().create(mkpod("raw0", labels={"x": "y"}))
        obj = client.pods().get("raw0")
        assert obj.metadata.labels == {"x": "y"}
        items, _rv = client.pods().list()
        assert any(p.metadata.name == "raw0" for p in items)


def mkboundpod(name: str, node: str, labels=None) -> Pod:
    p = mkpod(name, labels=labels)
    p.spec.node_name = node
    return p


class TestRound10Fanout:
    """Round 10: per-resource ring sizing + eviction accounting,
    interest-filtered fan-out, and coalesced-burst equivalence."""

    def test_ring_size_per_resource_config(self, monkeypatch):
        monkeypatch.setenv("KUBERNETES_TPU_WATCH_CACHE_SIZES",
                           "pods=16, nodes=32, default=8, junk, bad=x")
        api = APIServer()
        try:
            api.handle("POST", "/api/v1/namespaces/default/pods",
                       body=None) if False else None
            pods_cacher = api._cacher_for(api.resources["pods"])
            nodes_cacher = api._cacher_for(api.resources["nodes"])
            svc_cacher = api._cacher_for(api.resources["services"])
            assert pods_cacher._ring.maxlen == 16
            assert nodes_cacher._ring.maxlen == 32
            assert svc_cacher._ring.maxlen == 8  # default= fallback
        finally:
            api.close_cachers()

    def test_undersized_ring_evicts_counts_and_forces_relist(self):
        """A watch storm larger than the ring must EVICT (counted) and
        force a resuming watcher into the store fallback / relist path
        — never a silently truncated replay."""
        from kubernetes_tpu.metrics import (
            storage_watch_cache_ring_evictions_total,
        )

        store = MemoryStore()
        store.create("/pods/default/seed", mkpod("seed"))
        cacher = Cacher(store, "/pods/", ring_size=8)
        assert cacher.list_entries("/pods/") is not None  # bootstrap
        rv0 = store.list("/pods/")[1]
        assert rv0 >= 1
        before = storage_watch_cache_ring_evictions_total.get()
        for i in range(40):
            store.create(f"/pods/default/storm-{i:03d}",
                         mkpod(f"storm-{i:03d}"))
        # wait for the feed to absorb the burst (read _rv under its
        # guard: the feed thread writes it under _cond)
        deadline = time.time() + 5
        while time.time() < deadline:
            with cacher._cond:
                if cacher._rv >= rv0 + 40:
                    break
            time.sleep(0.01)
        assert storage_watch_cache_ring_evictions_total.get() - before >= 32
        # resuming from before the evicted horizon: cacher refuses
        # (None -> store fallback), it must not replay a truncated ring
        assert cacher.watch("/pods/", from_rv=rv0) is None
        # the store fallback path surfaces Compacted when ITS window is
        # also gone -> the reflector relists; either way the final
        # state is complete
        try:
            stream = store.watch("/pods/", from_rv=rv0)
            got = drain(stream, 40)
            assert len(got) == 40
            stream.stop()
        except Compacted:
            pass
        objs, _rv = store.list("/pods/")
        assert len(objs) == 41
        cacher.stop()

    def _fuzz_ops(self, rng, client, nodes, serial):
        """One randomized writer step through the REAL doors: bulk
        create (bound or pending), batch status merge, batch delete."""
        from kubernetes_tpu.client.rest import batch_delete_item

        op = rng.random()
        if op < 0.45:
            names = [f"fz-{serial:04d}-{j}" for j in range(rng.randrange(1, 4))]
            objs = []
            for nm in names:
                node = rng.choice(nodes + [""])
                objs.append(mkboundpod(nm, node) if node else mkpod(nm))
            client.pods().create_many(objs)
            return names
        existing, _rv = client.pods().list()
        if not existing:
            return []
        if op < 0.75:
            victims = rng.sample(existing, min(len(existing),
                                               rng.randrange(1, 3)))
            client.commit_batch([
                batch_status_item("pods", p.metadata.name,
                                  {"phase": rng.choice(["Running",
                                                        "Pending"])})
                for p in victims
            ])
        else:
            victims = rng.sample(existing, min(len(existing),
                                               rng.randrange(1, 3)))
            client.commit_batch([
                batch_delete_item("pods", p.metadata.name)
                for p in victims
            ])
        return []

    def _drain_to_sentinel(self, stream, sentinel):
        """Consume watch events into a name -> (phase, node) dict until
        the sentinel pod arrives; DELETED removes."""
        state = {}
        for ev_type, obj in stream:
            name = obj.metadata.name
            if ev_type == "DELETED":
                state.pop(name, None)
            else:
                state[name] = (obj.status.phase, obj.spec.node_name)
            if name == sentinel:
                break
        return state

    def test_fuzz_coalesced_vs_per_event_frames(self, monkeypatch):
        """Coalescing ON and OFF streams reconstruct IDENTICAL final
        states from an identical randomized writer interleaving — the
        burst envelope is transport, not semantics."""
        rng = random.Random(42)
        api = APIServer()
        host, port = api.serve_http(enable_binary=True)
        client = RESTClient(HTTPTransport(f"http://{host}:{port}",
                                          binary=True))
        try:
            from kubernetes_tpu.metrics import (
                apiserver_watch_coalesced_frame_objects as _frames,
            )

            monkeypatch.setenv("KUBERNETES_TPU_WATCH_COALESCE", "1")
            w_on = client.pods().watch(resource_version="0")
            # prime w_on past the handler's env read: the server
            # evaluates KUBERNETES_TPU_WATCH_COALESCE on its own
            # thread after the response headers, so flipping the var
            # immediately could land before w_on's handler sampled it
            # (both streams would silently run uncoalesced). A priming
            # pod must produce a COALESCED frame (w_on is the only
            # watcher) before the flip; the prime event stays queued on
            # the stream — the drain consumes it later.
            c0 = _frames.count
            client.pods().create(mkpod("aa-prime"))
            deadline = time.time() + 10
            while _frames.count == c0 and time.time() < deadline:
                time.sleep(0.01)
            assert _frames.count > c0, (
                "w_on never emitted a coalesced frame — coalescing is "
                "off at the server or the handler has not sampled env"
            )
            monkeypatch.setenv("KUBERNETES_TPU_WATCH_COALESCE", "0")
            w_off = client.pods().watch(resource_version="0")
            # deleted before the fuzz: neither reconstruction nor the
            # server's final state should carry the priming pod (w_on
            # drains ADDED then DELETED — a net no-op; w_off sees at
            # most the DELETED, also a no-op)
            client.pods().delete("aa-prime")
            for step in range(40):
                self._fuzz_ops(rng, client, ["n1", "n2", "n3"], step)
            client.pods().create(mkpod("zz-sentinel"))
            import concurrent.futures as cf

            with cf.ThreadPoolExecutor(2) as ex:
                f_on = ex.submit(self._drain_to_sentinel, w_on,
                                 "zz-sentinel")
                f_off = ex.submit(self._drain_to_sentinel, w_off,
                                  "zz-sentinel")
                state_on = f_on.result(timeout=30)
                state_off = f_off.result(timeout=30)
            assert state_on == state_off
            # and both converge to the server's final state
            final = {
                p.metadata.name: (p.status.phase, p.spec.node_name)
                for p in client.pods().list()[0]
            }
            assert state_on == final
            w_on.stop()
            w_off.stop()
        finally:
            client.transport.close()
            api.shutdown_http()
            api.close_cachers()

    def test_fuzz_server_filtered_vs_client_filtered(self):
        """A spec.nodeName-in-(...) server-filtered stream must
        reconstruct exactly the state a client filtering the FULL
        stream reconstructs, across randomized interleavings that move
        pods in and out of the interest set."""
        rng = random.Random(7)
        api = APIServer()
        host, port = api.serve_http(enable_binary=True)
        client = RESTClient(HTTPTransport(f"http://{host}:{port}",
                                          binary=True))
        want = {"n1", "n2"}
        try:
            w_filt = client.pods().watch(
                resource_version="0",
                field_selector="spec.nodeName in (n1,n2)",
            )
            w_full = client.pods().watch(resource_version="0")
            for step in range(40):
                self._fuzz_ops(rng, client, ["n1", "n2", "n3", "n4"],
                               step)
            client.pods().create(mkboundpod("zz-sentinel", "n1"))
            import concurrent.futures as cf

            with cf.ThreadPoolExecutor(2) as ex:
                f_filt = ex.submit(self._drain_to_sentinel, w_filt,
                                   "zz-sentinel")
                f_full = ex.submit(self._drain_to_sentinel, w_full,
                                   "zz-sentinel")
                state_filt = f_filt.result(timeout=30)
                state_full = f_full.result(timeout=30)
            client_filtered = {
                nm: st for nm, st in state_full.items() if st[1] in want
            }
            assert state_filt == client_filtered
            final = {
                p.metadata.name: (p.status.phase, p.spec.node_name)
                for p in client.pods().list()[0]
                if p.spec.node_name in want
            }
            assert state_filt == final
            w_filt.stop()
            w_full.stop()
        finally:
            client.transport.close()
            api.shutdown_http()
            api.close_cachers()

    def test_burst_frame_roundtrip(self):
        """coalesce_burst/iter_burst invert each other and reject
        truncation/trailing garbage."""
        from kubernetes_tpu.runtime import binary, tlv

        items = [
            ("ADDED", tlv.dumps({"metadata": {"name": "a"}})),
            ("MODIFIED", tlv.dumps({"metadata": {"name": "b"},
                                    "status": {"phase": "Running"}})),
            ("DELETED", tlv.dumps({"metadata": {"name": "c"}})),
        ]
        frame = binary.coalesce_burst(items)
        import struct

        (size,) = struct.unpack_from("<I", frame, 0)
        body = frame[4:]
        assert len(body) == size
        assert body.startswith(binary.MAGIC_BURST)
        evs = list(binary.iter_burst(body))
        assert [e["type"] for e in evs] == ["ADDED", "MODIFIED", "DELETED"]
        assert evs[1]["object"]["status"]["phase"] == "Running"
        with pytest.raises(binary.BinaryDecodeError):
            list(binary.iter_burst(body[:-3]))
        with pytest.raises(binary.BinaryDecodeError):
            list(binary.iter_burst(body + b"xx"))
