"""Bit-identical conformance: BatchScheduler (TPU tensor path) vs the
sequential oracle on randomized scenarios.

This is the core guarantee of the framework (BASELINE.json north star):
node selection must match the serial reference loop exactly, including
round-robin tie-breaks, integer score truncations, and commitment
threading across the backlog.
"""

import random

import pytest

from kubernetes_tpu.api.types import (
    Container,
    ContainerPort,
    Node,
    NodeAffinity,
    NodeCondition,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    PreferredSchedulingTerm,
    Affinity,
    ReplicationController,
    ReplicationControllerSpec,
    Service,
    ServiceSpec,
    Taint,
    Toleration,
)
from kubernetes_tpu.models.batch import BatchScheduler, SchedulerConfig
from kubernetes_tpu.oracle import ClusterState, GenericScheduler
from kubernetes_tpu.oracle import predicates as opreds
from kubernetes_tpu.oracle import priorities as oprios
from kubernetes_tpu.oracle.scheduler import PriorityConfig
from kubernetes_tpu.snapshot.encode import SnapshotEncoder

ZONE = "failure-domain.beta.kubernetes.io/zone"
REGION = "failure-domain.beta.kubernetes.io/region"

# the full default provider (defaults.go) — the device SchedulerConfig
# default mirrors this exactly
from kubernetes_tpu.oracle.scheduler import (  # noqa: E402
    DEFAULT_PREDICATE_ORDER as ORACLE_PREDICATES,
    DEFAULT_PRIORITIES as ORACLE_PRIORITIES,
)


def random_pod_affinity(rng: random.Random, interpod_p: float):
    """Random PodAffinity/PodAntiAffinity over the scenario's app labels."""
    from kubernetes_tpu.api.types import (
        LabelSelector,
        LabelSelectorRequirement,
        PodAffinity,
        PodAffinityTerm,
        PodAntiAffinity,
        WeightedPodAffinityTerm,
    )

    if rng.random() >= interpod_p:
        return None

    def rand_selector():
        r = rng.random()
        if r < 0.4:
            return LabelSelector(match_labels={"app": rng.choice(["web", "db", "cache"])})
        if r < 0.7:
            return LabelSelector(
                match_expressions=(
                    LabelSelectorRequirement(
                        key=rng.choice(["app", "tier"]),
                        operator=rng.choice(["In", "NotIn", "Exists", "DoesNotExist"]),
                        values=(rng.choice(["web", "db", "be"]),),
                    ),
                )
            )
        if r < 0.85:
            return LabelSelector()  # empty == Everything
        return None  # nil == Nothing

    def rand_term():
        return PodAffinityTerm(
            label_selector=rand_selector(),
            namespaces=rng.choice([None, (), ("default",), ("other",)]),
            topology_key=rng.choice(
                ["kubernetes.io/hostname", ZONE, REGION, "", "disktype"]
            ),
        )

    def rand_side(cls):
        req = tuple(rand_term() for _ in range(rng.randint(0, 2)))
        pref = tuple(
            WeightedPodAffinityTerm(
                weight=rng.choice([0, 1, 3, 7]), pod_affinity_term=rand_term()
            )
            for _ in range(rng.randint(0, 2))
        )
        if not req and not pref and rng.random() < 0.5:
            return None
        return cls(
            required_during_scheduling_ignored_during_execution=req,
            preferred_during_scheduling_ignored_during_execution=pref,
        )

    aff = rng.random()
    return Affinity(
        pod_affinity=rand_side(PodAffinity) if aff < 0.7 else None,
        pod_anti_affinity=rand_side(PodAntiAffinity) if aff > 0.3 else None,
    )


def random_volumes(rng: random.Random, volumes_p: float):
    """Random EBS/GCE/RBD/PVC volumes over a small shared universe."""
    from kubernetes_tpu.api.types import (
        AWSElasticBlockStore,
        GCEPersistentDisk,
        PersistentVolumeClaimSource,
        RBDVolume,
        Volume,
    )

    vols = []
    if rng.random() >= volumes_p:
        return vols
    for _ in range(rng.randint(1, 2)):
        kind = rng.random()
        if kind < 0.3:
            vols.append(
                Volume(
                    name="v",
                    gce_persistent_disk=GCEPersistentDisk(
                        pd_name=rng.choice(["pd-a", "pd-b", "pd-c"]),
                        read_only=rng.random() < 0.5,
                    ),
                )
            )
        elif kind < 0.55:
            vols.append(
                Volume(
                    name="v",
                    aws_elastic_block_store=AWSElasticBlockStore(
                        volume_id=rng.choice(["vol-1", "vol-2", "vol-3"])
                    ),
                )
            )
        elif kind < 0.7:
            vols.append(
                Volume(
                    name="v",
                    rbd=RBDVolume(
                        monitors=tuple(
                            rng.sample(["m1", "m2", "m3"], rng.randint(1, 2))
                        ),
                        pool=rng.choice(["p1", "p2"]),
                        image=rng.choice(["img1", "img2"]),
                    ),
                )
            )
        else:
            vols.append(
                Volume(
                    name="v",
                    persistent_volume_claim=PersistentVolumeClaimSource(
                        claim_name=rng.choice(
                            ["claim-ebs", "claim-gce", "claim-zoned",
                             "claim-unbound", "claim-missing"]
                        )
                    ),
                )
            )
    return vols


def scenario_pvs_pvcs():
    """A fixed PV/PVC universe: bound EBS + GCE + zone-labeled PVs, an
    unbound PVC, and a claim with no PV."""
    from kubernetes_tpu.api.types import (
        AWSElasticBlockStore,
        GCEPersistentDisk,
        PersistentVolume,
        PersistentVolumeClaim,
    )

    pvs = [
        PersistentVolume(
            metadata=ObjectMeta(name="pv-ebs"),
            aws_elastic_block_store=AWSElasticBlockStore(volume_id="vol-9"),
        ),
        PersistentVolume(
            metadata=ObjectMeta(name="pv-gce"),
            gce_persistent_disk=GCEPersistentDisk(pd_name="pd-z"),
        ),
        PersistentVolume(
            metadata=ObjectMeta(name="pv-zoned", labels={ZONE: "a", REGION: "r1"}),
        ),
    ]
    pvcs = [
        PersistentVolumeClaim(
            metadata=ObjectMeta(name="claim-ebs"), volume_name="pv-ebs"
        ),
        PersistentVolumeClaim(
            metadata=ObjectMeta(name="claim-gce"), volume_name="pv-gce"
        ),
        PersistentVolumeClaim(
            metadata=ObjectMeta(name="claim-zoned"), volume_name="pv-zoned"
        ),
        PersistentVolumeClaim(metadata=ObjectMeta(name="claim-unbound")),
        PersistentVolumeClaim(
            metadata=ObjectMeta(name="claim-missing"), volume_name="pv-gone"
        ),
    ]
    return pvs, pvcs


def random_scenario(
    rng: random.Random,
    n_nodes=12,
    n_existing=15,
    n_pending=25,
    interpod_p=0.0,
    volumes_p=0.0,
):
    zones = ["a", "b", "c"]
    nodes = []
    for i in range(n_nodes):
        labels = {"kubernetes.io/hostname": f"node-{i:03d}"}
        if rng.random() < 0.7:
            labels[ZONE] = rng.choice(zones)
            labels[REGION] = "r1"
        if rng.random() < 0.5:
            labels["disktype"] = rng.choice(["ssd", "hdd"])
        if rng.random() < 0.3:
            labels["gen"] = str(rng.randint(1, 5))
        taints = None
        if rng.random() < 0.25:
            taints = [
                Taint(
                    key=rng.choice(["dedicated", "special"]),
                    value=rng.choice(["a", "b"]),
                    effect=rng.choice(["NoSchedule", "PreferNoSchedule"]),
                )
            ]
        conds = [NodeCondition("Ready", "True")]
        if rng.random() < 0.15:
            conds.append(NodeCondition("MemoryPressure", "True"))
        nodes.append(
            Node(
                metadata=ObjectMeta(name=f"node-{i:03d}", labels=labels),
                spec=NodeSpec(taints=taints),
                status=NodeStatus(
                    allocatable={
                        "cpu": f"{rng.choice([1000, 2000, 4000])}m",
                        "memory": str(rng.choice([2, 4, 8]) * 1024**3),
                        "pods": str(rng.choice([3, 5, 110])),
                    },
                    conditions=conds,
                ),
            )
        )

    def rand_containers(allow_zero=True):
        cs = []
        for _ in range(rng.randint(1, 2)):
            reqs = {}
            if not allow_zero or rng.random() < 0.8:
                reqs["cpu"] = f"{rng.choice([0, 100, 250, 500])}m"
            if not allow_zero or rng.random() < 0.8:
                reqs["memory"] = str(rng.choice([0, 128, 512, 1024]) * 1024**2)
            ports = []
            if rng.random() < 0.25:
                ports.append(ContainerPort(host_port=rng.choice([8080, 9090, 9091])))
            cs.append(Container(requests=reqs, ports=ports))
        return cs

    app_labels = [{"app": "web"}, {"app": "db"}, {"app": "cache", "tier": "be"}]

    existing = []
    for i in range(n_existing):
        existing.append(
            Pod(
                metadata=ObjectMeta(
                    name=f"existing-{i}",
                    labels=rng.choice(app_labels),
                    deletion_timestamp="2026-01-01T00:00:00Z" if rng.random() < 0.1 else None,
                ),
                spec=PodSpec(
                    node_name=f"node-{rng.randrange(n_nodes):03d}",
                    containers=rand_containers(),
                    affinity=random_pod_affinity(rng, interpod_p),
                    volumes=random_volumes(rng, volumes_p),
                ),
            )
        )

    services = [
        Service(metadata=ObjectMeta(name="web"), spec=ServiceSpec(selector={"app": "web"})),
        Service(metadata=ObjectMeta(name="db"), spec=ServiceSpec(selector={"app": "db"})),
    ]
    controllers = [
        ReplicationController(
            metadata=ObjectMeta(name="cache-rc"),
            spec=ReplicationControllerSpec(selector={"app": "cache"}),
        )
    ]

    pending = []
    for i in range(n_pending):
        spec_kw = {}
        if rng.random() < 0.3:
            spec_kw["node_selector"] = rng.choice(
                [{"disktype": "ssd"}, {ZONE: "a"}, {"disktype": "hdd"}]
            )
        if rng.random() < 0.2:
            spec_kw["tolerations"] = [
                Toleration(
                    key=rng.choice(["dedicated", "special"]),
                    operator=rng.choice(["Exists", "Equal"]),
                    value="a",
                    effect=rng.choice(["", "NoSchedule"]),
                )
            ]
        affinity = None
        if rng.random() < 0.3:
            terms = []
            for _ in range(rng.randint(1, 2)):
                reqs = [
                    NodeSelectorRequirement(
                        key=rng.choice(["disktype", "gen", ZONE]),
                        operator=rng.choice(["In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"]),
                        values=(rng.choice(["ssd", "2", "a", "x"]),),
                    )
                ]
                terms.append(NodeSelectorTerm(match_expressions=tuple(reqs)))
            required = NodeSelector(node_selector_terms=tuple(terms)) if rng.random() < 0.6 else None
            preferred = ()
            if rng.random() < 0.5:
                preferred = tuple(
                    PreferredSchedulingTerm(
                        weight=rng.randint(1, 5),
                        preference=NodeSelectorTerm(
                            match_expressions=(
                                NodeSelectorRequirement(
                                    key=rng.choice(["disktype", "gen"]),
                                    operator=rng.choice(["In", "Exists"]),
                                    values=("ssd",),
                                ),
                            )
                        ),
                    )
                    for _ in range(rng.randint(1, 2))
                )
            affinity = Affinity(
                node_affinity=NodeAffinity(
                    required_during_scheduling_ignored_during_execution=required,
                    preferred_during_scheduling_ignored_during_execution=preferred,
                )
            )
        ip_aff = random_pod_affinity(rng, interpod_p)
        if ip_aff is not None:
            if affinity is None:
                affinity = ip_aff
            else:
                affinity.pod_affinity = ip_aff.pod_affinity
                affinity.pod_anti_affinity = ip_aff.pod_anti_affinity
        pod = Pod(
            metadata=ObjectMeta(name=f"pending-{i:04d}", labels=rng.choice(app_labels)),
            spec=PodSpec(
                containers=rand_containers(),
                affinity=affinity,
                volumes=random_volumes(rng, volumes_p),
                **spec_kw,
            ),
        )
        if rng.random() < 0.1:
            pod.spec.init_containers = [
                Container(requests={"cpu": "600m", "memory": str(512 * 1024**2)})
            ]
        pending.append(pod)

    pvs, pvcs = scenario_pvs_pvcs() if volumes_p > 0 else ((), ())
    state = ClusterState.build(
        nodes,
        assigned_pods=existing,
        services=services,
        controllers=controllers,
        pvs=pvs,
        pvcs=pvcs,
    )
    return state, pending


def run_both(state, pending):
    oracle = GenericScheduler(predicates=ORACLE_PREDICATES, priorities=ORACLE_PRIORITIES)
    oracle_result = oracle.schedule_backlog(pending, state.clone())

    enc = SnapshotEncoder(state, pending)
    snap, batch = enc.encode()
    tpu = BatchScheduler(SchedulerConfig())
    tpu_result = tpu.schedule_names(snap, batch)
    return oracle_result, tpu_result


@pytest.mark.parametrize("seed", range(8))
def test_random_scenarios_bit_identical(seed):
    rng = random.Random(seed)
    state, pending = random_scenario(rng)
    oracle_result, tpu_result = run_both(state, pending)
    assert tpu_result == oracle_result, (
        f"seed {seed}: first divergence at "
        f"{next(i for i, (a, b) in enumerate(zip(oracle_result, tpu_result)) if a != b)}"
    )


def test_scheduler_perf_shape_identical():
    # 50 identical nodes, 300 identical pause pods — the density-test shape
    nodes = [
        Node(
            metadata=ObjectMeta(name=f"node-{i:04d}"),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        )
        for i in range(50)
    ]
    pods = [
        Pod(
            metadata=ObjectMeta(name=f"pod-{i:05d}", labels={"app": "pause"}),
            spec=PodSpec(
                containers=[Container(requests={"cpu": "100m", "memory": "500Mi"})]
            ),
        )
        for i in range(300)
    ]
    state = ClusterState.build(nodes)
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    assert None not in tpu_result


def test_duplicate_taints_count_per_list():
    # a node carrying duplicate PreferNoSchedule taints counts each
    # occurrence in the taint-toleration priority (review regression)
    n0 = Node(
        metadata=ObjectMeta(name="node-0"),
        spec=NodeSpec(
            taints=[Taint("k", "v", "PreferNoSchedule"), Taint("k", "v", "PreferNoSchedule")]
        ),
        status=NodeStatus(
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )
    n1 = Node(
        metadata=ObjectMeta(name="node-1"),
        spec=NodeSpec(taints=[Taint("other", "x", "PreferNoSchedule")]),
        status=NodeStatus(
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )
    pods = [
        Pod(
            metadata=ObjectMeta(name=f"p{i}"),
            spec=PodSpec(
                containers=[Container(requests={"cpu": "100m"})],
                tolerations=[Toleration(key="zzz", operator="Exists")],
            ),
        )
        for i in range(2)
    ]
    state = ClusterState.build([n0, n1])
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result


def test_bogus_operator_in_required_term(recwarn):
    # term order matters: a match BEFORE the bogus term wins; a bogus term
    # reached first rejects the whole list (review regression)
    def mk_pod(name, terms):
        return Pod(
            metadata=ObjectMeta(name=name),
            spec=PodSpec(
                containers=[Container(requests={"cpu": "100m"})],
                affinity=Affinity(
                    node_affinity=NodeAffinity(
                        required_during_scheduling_ignored_during_execution=NodeSelector(
                            node_selector_terms=tuple(terms)
                        )
                    )
                ),
            ),
        )

    good = NodeSelectorTerm(
        match_expressions=(
            NodeSelectorRequirement(key="disktype", operator="In", values=("ssd",)),
        )
    )
    bogus = NodeSelectorTerm(
        match_expressions=(
            NodeSelectorRequirement(key="x", operator="Bogus", values=("y",)),
        )
    )
    nodes = [
        Node(
            metadata=ObjectMeta(name=f"node-{i}", labels={"disktype": "ssd"}),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        )
        for i in range(2)
    ]
    state = ClusterState.build(nodes)
    pods = [mk_pod("a", [bogus, good]), mk_pod("b", [good, bogus])]
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    assert oracle_result[0] is None  # bogus reached first -> unschedulable
    assert oracle_result[1] is not None  # good term matched first -> fits


def test_empty_cluster_all_unscheduled():
    # review regression: zero-node snapshot must return all -1, not crash
    state = ClusterState.build([])
    pods = [
        Pod(metadata=ObjectMeta(name="p"), spec=PodSpec(containers=[Container()]))
    ]
    oracle_result, tpu_result = run_both(state, pods)
    assert oracle_result == [None]
    assert tpu_result == [None]


# --- inter-pod affinity conformance -----------------------------------------


@pytest.mark.parametrize("seed", range(12))
def test_interpod_affinity_random_bit_identical(seed):
    """Randomized pod (anti-)affinity on existing AND pending pods, all
    topology keys incl. empty (= any default failure domain), namespaces
    modes, weight-0 terms, commitment threading mid-backlog."""
    rng = random.Random(1000 + seed)
    state, pending = random_scenario(
        rng, n_nodes=8, n_existing=10, n_pending=15, interpod_p=0.6
    )
    oracle_result, tpu_result = run_both(state, pending)
    assert tpu_result == oracle_result, (
        f"seed {seed}: first divergence at "
        f"{next(i for i, (a, b) in enumerate(zip(oracle_result, tpu_result)) if a != b)}"
    )


def _affinity_nodes(n=4):
    zones = ["a", "a", "b", "b"]
    return [
        Node(
            metadata=ObjectMeta(
                name=f"node-{i}",
                labels={
                    "kubernetes.io/hostname": f"node-{i}",
                    ZONE: zones[i % len(zones)],
                    REGION: "r1",
                },
            ),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        )
        for i in range(n)
    ]


def _aff_pod(name, labels, affinity=None, node=None):
    from kubernetes_tpu.api.types import PodSpec

    return Pod(
        metadata=ObjectMeta(name=name, labels=labels),
        spec=PodSpec(
            containers=[Container(requests={"cpu": "100m"})],
            affinity=affinity,
            node_name=node,
        ),
    )


def test_interpod_first_pod_of_collection_escape():
    """predicates.go:819-843: a hard-affinity term matching no pod anywhere
    is waived iff the pod matches its own term."""
    from kubernetes_tpu.api.types import (
        LabelSelector,
        PodAffinity,
        PodAffinityTerm,
    )

    term = PodAffinityTerm(
        label_selector=LabelSelector(match_labels={"app": "solo"}),
        topology_key=ZONE,
    )
    aff = Affinity(
        pod_affinity=PodAffinity(
            required_during_scheduling_ignored_during_execution=(term,)
        )
    )
    state = ClusterState.build(_affinity_nodes())
    # first pod self-matches -> escape applies -> schedules; second pod
    # then finds the first co-located; a non-self-matching pod with the
    # same term must follow the collection, and a pod whose term matches
    # nothing and not itself is unschedulable.
    pods = [
        _aff_pod("first", {"app": "solo"}, aff),
        _aff_pod("second", {"app": "solo"}, aff),
        _aff_pod("follower", {"app": "other"}, aff),
        _aff_pod(
            "lost",
            {"app": "other"},
            Affinity(
                pod_affinity=PodAffinity(
                    required_during_scheduling_ignored_during_execution=(
                        PodAffinityTerm(
                            label_selector=LabelSelector(
                                match_labels={"app": "nonexistent"}
                            ),
                            topology_key=ZONE,
                        ),
                    )
                )
            ),
        ),
    ]
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    assert oracle_result[0] is not None
    assert oracle_result[3] is None
    # followers landed in the first pod's zone
    zone_of = {f"node-{i}": ["a", "a", "b", "b"][i] for i in range(4)}
    assert zone_of[oracle_result[1]] == zone_of[oracle_result[0]]
    assert zone_of[oracle_result[2]] == zone_of[oracle_result[0]]


def test_interpod_symmetric_anti_affinity():
    """predicates.go:858-921: an ASSIGNED pod's hard anti-affinity term
    keeps matching pods out of its topology domain (symmetry) — both for
    preexisting pods and for pods committed mid-backlog."""
    from kubernetes_tpu.api.types import (
        LabelSelector,
        PodAntiAffinity,
        PodAffinityTerm,
    )

    anti = Affinity(
        pod_anti_affinity=PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=(
                PodAffinityTerm(
                    label_selector=LabelSelector(match_labels={"app": "web"}),
                    topology_key=ZONE,
                ),
            )
        )
    )
    state = ClusterState.build(
        _affinity_nodes(),
        assigned_pods=[_aff_pod("guard", {"app": "db"}, anti, node="node-0")],
    )
    pods = [
        _aff_pod("web-2", {"app": "web"}, anti),  # must avoid zone a (guard)
        _aff_pod("web-1", {"app": "web"}),  # no own anti: symmetric check is
        # gated on the pod having anti-affinity => schedules anywhere
    ]
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    zone_of = {f"node-{i}": ["a", "a", "b", "b"][i] for i in range(4)}
    assert zone_of[oracle_result[0]] == "b"
    assert oracle_result[1] is not None


def test_interpod_empty_topology_key_any_default_domain():
    """util/non_zero.go:97-113: empty topologyKey in anti-affinity means
    co-location under ANY default failure-domain key."""
    from kubernetes_tpu.api.types import (
        LabelSelector,
        PodAntiAffinity,
        PodAffinityTerm,
    )

    anti = Affinity(
        pod_anti_affinity=PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=(
                PodAffinityTerm(
                    label_selector=LabelSelector(match_labels={"app": "web"}),
                    topology_key="",
                ),
            )
        )
    )
    # node-0/1 share zone a + region; node-2/3 share zone b + region — all
    # four share the region, so an existing web pod anywhere blocks every
    # node for an anti(web, "") pod.
    state = ClusterState.build(
        _affinity_nodes(),
        assigned_pods=[_aff_pod("w", {"app": "web"}, node="node-3")],
    )
    pods = [_aff_pod("p", {"app": "cache"}, anti)]
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    assert oracle_result[0] is None  # region co-location blocks everywhere


def test_interpod_priority_reverse_direction():
    """interpod_affinity.go:128-191: assigned pods' preferred terms pull
    (or push) the pending pod toward/away from their domains."""
    from kubernetes_tpu.api.types import (
        LabelSelector,
        PodAffinity,
        PodAffinityTerm,
        WeightedPodAffinityTerm,
    )

    want_web_near = Affinity(
        pod_affinity=PodAffinity(
            preferred_during_scheduling_ignored_during_execution=(
                WeightedPodAffinityTerm(
                    weight=7,
                    pod_affinity_term=PodAffinityTerm(
                        label_selector=LabelSelector(match_labels={"app": "web"}),
                        topology_key=ZONE,
                    ),
                ),
            )
        )
    )
    state = ClusterState.build(
        _affinity_nodes(),
        assigned_pods=[
            _aff_pod("attractor", {"app": "db"}, want_web_near, node="node-2")
        ],
    )
    pods = [_aff_pod("web-1", {"app": "web"})]
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    zone_of = {f"node-{i}": ["a", "a", "b", "b"][i] for i in range(4)}
    assert zone_of[oracle_result[0]] == "b"  # pulled toward the attractor


# --- volume predicate conformance -------------------------------------------


@pytest.mark.parametrize("seed", range(10))
def test_volume_predicates_random_bit_identical(seed):
    """Randomized EBS/GCE/RBD/PVC volumes on existing and pending pods:
    NoDiskConflict, NoVolumeZoneConflict, Max{EBS,GCEPD}VolumeCount all
    active, committed volumes threaded through the backlog."""
    rng = random.Random(2000 + seed)
    state, pending = random_scenario(
        rng, n_nodes=8, n_existing=12, n_pending=16, volumes_p=0.6
    )
    oracle_result, tpu_result = run_both(state, pending)
    assert tpu_result == oracle_result, (
        f"seed {seed}: first divergence at "
        f"{next(i for i, (a, b) in enumerate(zip(oracle_result, tpu_result)) if a != b)}"
    )


def test_max_pd_count_commit_threading():
    """A node fills to the EBS max via COMMITTED pods mid-backlog; later
    pods with new EBS volumes must go elsewhere (or nowhere)."""
    from kubernetes_tpu.api.types import AWSElasticBlockStore, Volume
    from kubernetes_tpu.models.batch import SchedulerConfig
    from kubernetes_tpu.oracle import GenericScheduler
    from kubernetes_tpu.oracle import predicates as op
    from kubernetes_tpu.oracle.scheduler import PriorityConfig
    from kubernetes_tpu.oracle import priorities as opr

    # one node, max 2 EBS volumes
    nodes = [
        Node(
            metadata=ObjectMeta(name="only"),
            status=NodeStatus(
                allocatable={"cpu": "16", "memory": "64Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        )
    ]
    state = ClusterState.build(nodes)

    def ebs_pod(name, vol_id):
        return Pod(
            metadata=ObjectMeta(name=name),
            spec=PodSpec(
                containers=[Container(requests={"cpu": "10m"})],
                volumes=[
                    Volume(
                        name="v",
                        aws_elastic_block_store=AWSElasticBlockStore(volume_id=vol_id),
                    )
                ],
            ),
        )

    pods = [
        ebs_pod("a", "vol-1"),
        ebs_pod("b", "vol-2"),
        ebs_pod("c", "vol-1"),  # duplicate id: already on node, still fits
        ebs_pod("d", "vol-3"),  # third distinct id: over max, unschedulable
    ]
    oracle = GenericScheduler(
        predicates=(("MaxEBSVolumeCount", op.max_pd_volume_count("ebs", 2)),),
        priorities=(PriorityConfig(opr.equal_priority, 1, "EqualPriority"),),
    )
    oracle_result = oracle.schedule_backlog(pods, state.clone())

    enc = SnapshotEncoder(state, pods)
    snap, batch = enc.encode()
    cfg = SchedulerConfig(
        predicates=("MaxEBSVolumeCount",),
        priorities=(("EqualPriority", 1),),
        max_ebs_volumes=2,
    )
    tpu_result = BatchScheduler(cfg).schedule_names(snap, batch)
    assert tpu_result == oracle_result
    assert oracle_result == ["only", "only", "only", None]


def test_disk_conflict_ro_gce_shared():
    """GCE PDs are shareable read-only but conflict on any writable use;
    conflicts must also arise from pods committed mid-backlog."""
    from kubernetes_tpu.api.types import GCEPersistentDisk, Volume

    nodes = [
        Node(
            metadata=ObjectMeta(name=f"n{i}"),
            status=NodeStatus(
                allocatable={"cpu": "16", "memory": "64Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        )
        for i in range(2)
    ]
    state = ClusterState.build(nodes)

    def gce_pod(name, ro):
        return Pod(
            metadata=ObjectMeta(name=name),
            spec=PodSpec(
                containers=[Container(requests={"cpu": "10m"})],
                volumes=[
                    Volume(
                        name="v",
                        gce_persistent_disk=GCEPersistentDisk(
                            pd_name="pd-x", read_only=ro
                        ),
                    )
                ],
            ),
        )

    # two RO users may share; a writer conflicts with both nodes' users
    pods = [gce_pod("ro1", True), gce_pod("ro2", True), gce_pod("rw", False)]
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    # spreading puts ro1/ro2 on different nodes; the writer then conflicts
    # with a RO user everywhere
    assert oracle_result[2] is None


def test_volume_zone_conflict():
    """A pod bound to a zone-labeled PV only fits nodes in that zone (or
    nodes with no zone labels at all)."""
    from kubernetes_tpu.api.types import PersistentVolumeClaimSource, Volume

    nodes = [
        Node(
            metadata=ObjectMeta(name="in-zone", labels={ZONE: "a", REGION: "r1"}),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        ),
        Node(
            metadata=ObjectMeta(name="off-zone", labels={ZONE: "b", REGION: "r1"}),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        ),
        Node(
            metadata=ObjectMeta(name="unlabeled"),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        ),
    ]
    pvs, pvcs = scenario_pvs_pvcs()
    state = ClusterState.build(nodes, pvs=pvs, pvcs=pvcs)
    mk = lambda name, claim: Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(
            containers=[Container(requests={"cpu": "100m"})],
            volumes=[
                Volume(
                    name="v",
                    persistent_volume_claim=PersistentVolumeClaimSource(
                        claim_name=claim
                    ),
                )
            ],
        ),
    )
    pods = [mk("zoned-1", "claim-zoned"), mk("zoned-2", "claim-zoned"),
            mk("zoned-3", "claim-zoned"), mk("broken", "claim-missing")]
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    assert set(oracle_result[:3]) <= {"in-zone", "unlabeled"}
    # broken claim: VolumeZone alone would pass on the unlabeled node, but
    # the Max*VolumeCount predicates error on the unresolvable PVC for
    # EVERY node (predicates.go:312-317) => unschedulable
    assert oracle_result[3] is None


def test_image_locality_and_node_label():
    """ImageLocalityPriority (legacy alias) and the Policy-configurable
    CheckNodeLabelPresence / NodeLabelPriority on the device path."""
    from kubernetes_tpu.api.types import ContainerImage
    from kubernetes_tpu.oracle import GenericScheduler
    from kubernetes_tpu.oracle import predicates as op
    from kubernetes_tpu.oracle import priorities as opr
    from kubernetes_tpu.oracle.scheduler import PriorityConfig

    GB = 1024**3
    nodes = []
    for i in range(4):
        labels = {"region": "r1"} if i < 3 else {}
        images = []
        if i == 1:
            images = [ContainerImage(names=("app:v1",), size_bytes=GB)]
        if i == 2:
            images = [ContainerImage(names=("app:v1",), size_bytes=200 * 1024**2)]
        nodes.append(
            Node(
                metadata=ObjectMeta(name=f"n{i}", labels=labels),
                status=NodeStatus(
                    allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                    conditions=[NodeCondition("Ready", "True")],
                    images=images,
                ),
            )
        )
    state = ClusterState.build(nodes)
    pods = [
        Pod(
            metadata=ObjectMeta(name=f"p{i}"),
            spec=PodSpec(
                containers=[
                    Container(image="app:v1", requests={"cpu": "100m"})
                ]
            ),
        )
        for i in range(3)
    ]
    oracle = GenericScheduler(
        predicates=(
            ("GeneralPredicates", op.general_predicates),
            ("NodeLabel", op.node_label_predicate(["region"], True)),
        ),
        priorities=(
            PriorityConfig(opr.image_locality_priority, 2, "ImageLocalityPriority"),
            PriorityConfig(opr.node_label_priority("region", True), 1, "NodeLabelPriority"),
        ),
    )
    oracle_result = oracle.schedule_backlog(pods, state.clone())

    snap, batch = SnapshotEncoder(state, pods).encode()
    cfg = SchedulerConfig(
        predicates=(
            "GeneralPredicates",
            ("CheckNodeLabelPresence", ("region",), True),
        ),
        priorities=(
            ("ImageLocalityPriority", 2),
            (("NodeLabelPriority", "region", True), 1),
        ),
    )
    tpu_result = BatchScheduler(cfg).schedule_names(snap, batch)
    assert tpu_result == oracle_result
    # n1 has the full 1GB image -> max image score; n3 is excluded by the
    # label predicate
    assert oracle_result[0] == "n1"
    assert "n3" not in oracle_result


def test_interpod_escape_denied_for_all_namespaces_term():
    """predicates.go:826-832: the first-pod escape checks names.Has(ns)
    LITERALLY — an explicit empty namespaces list ("all namespaces")
    contains nothing, so the escape never applies to such terms."""
    from kubernetes_tpu.api.types import (
        LabelSelector,
        PodAffinity,
        PodAffinityTerm,
    )

    aff = Affinity(
        pod_affinity=PodAffinity(
            required_during_scheduling_ignored_during_execution=(
                PodAffinityTerm(
                    label_selector=LabelSelector(match_labels={"app": "solo"}),
                    namespaces=(),  # explicit empty == ALL namespaces
                    topology_key=ZONE,
                ),
            )
        )
    )
    state = ClusterState.build(_affinity_nodes())
    pods = [_aff_pod("self-matching", {"app": "solo"}, aff)]
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    assert oracle_result == [None]


def test_bucket_padding_bit_identical():
    """snapshot/pad.py: power-of-two bucketing (the daemon's compile-reuse
    path) must not change any decision — padded pods yield -1 and commit
    nothing; padded nodes never fit."""
    from kubernetes_tpu.snapshot.pad import pad_to_buckets

    rng = random.Random(77)
    state, pending = random_scenario(
        rng, n_nodes=11, n_existing=10, n_pending=13, interpod_p=0.5, volumes_p=0.5
    )
    snap, batch = SnapshotEncoder(state, pending).encode()
    plain = BatchScheduler().schedule_names(snap, batch)
    ps, pb, n_real, p_real = pad_to_buckets(snap, batch)
    assert ps.num_nodes == 16 and pb.num_pods == 16
    chosen, _ = BatchScheduler().schedule(ps, pb)
    padded = [ps.node_names[i] if 0 <= i < n_real else None for i in chosen[:p_real]]
    assert padded == plain


# --- ServiceAffinity / ServiceAntiAffinity (Policy args) ---------------------


def _svc_affinity_cluster(rng=None):
    nodes = []
    for i in range(9):
        labels = {"kubernetes.io/hostname": f"node-{i}"}
        if i % 3 != 2:  # one node per triple lacks the labels entirely
            labels["region"] = ["r1", "r2"][i % 2]
            labels["rack"] = f"rack-{i % 3}"
        nodes.append(
            Node(
                metadata=ObjectMeta(name=f"node-{i}", labels=labels),
                status=NodeStatus(
                    allocatable={"cpu": "8", "memory": "32Gi", "pods": "110"},
                    conditions=[NodeCondition("Ready", "True")],
                ),
            )
        )
    services = [
        Service(metadata=ObjectMeta(name="web"),
                spec=ServiceSpec(selector={"app": "web"})),
        Service(metadata=ObjectMeta(name="db"),
                spec=ServiceSpec(selector={"app": "db"})),
    ]
    return nodes, services


def _svc_pod(name, labels, node=None, node_selector=None):
    return Pod(
        metadata=ObjectMeta(name=name, labels=dict(labels)),
        spec=PodSpec(
            containers=[Container(requests={"cpu": "100m"})],
            node_name=node,
            node_selector=dict(node_selector or {}),
        ),
    )


def _run_both_svc(state, pending, labels=("region",), anti_label=None):
    from kubernetes_tpu.oracle.scheduler import PriorityConfig

    preds = (
        ("GeneralPredicates", opreds.general_predicates),
        ("ServiceAffinity", opreds.service_affinity_predicate(list(labels))),
    )
    prios = [
        PriorityConfig(oprios.least_requested_priority, 1, "LeastRequestedPriority"),
    ]
    cfg_prios = [("LeastRequestedPriority", 1)]
    if anti_label:
        prios.append(
            PriorityConfig(
                oprios.service_anti_affinity_priority(anti_label), 2,
                "ServiceAntiAffinityPriority",
            )
        )
        cfg_prios.append((("ServiceAntiAffinity", anti_label), 2))
    oracle = GenericScheduler(predicates=preds, priorities=tuple(prios))
    oracle_result = oracle.schedule_backlog(pending, state.clone())

    cfg = SchedulerConfig(
        predicates=("GeneralPredicates", ("ServiceAffinity", tuple(labels))),
        priorities=tuple(cfg_prios),
    )
    snap, batch = SnapshotEncoder(state, pending, config=cfg).encode()
    tpu_result = BatchScheduler(cfg).schedule_names(snap, batch)
    return oracle_result, tpu_result


def test_service_affinity_follows_first_peer():
    """predicates.go:596: the first peer's node pins the affinity labels
    for every later pod of the service — including peers committed
    mid-backlog."""
    nodes, services = _svc_affinity_cluster()
    # a peer already sits on node-0 (region r1): every later web pod must
    # stay in r1 (and off the unlabeled nodes)
    state = ClusterState.build(
        nodes,
        services=services,
        assigned_pods=[_svc_pod("web-0", {"app": "web"}, node="node-0")],
    )
    pending = [
        _svc_pod("web-1", {"app": "web"}),
        _svc_pod("web-2", {"app": "web"}),
        _svc_pod("lone", {"app": "none"}),  # no service: unconstrained
    ]
    oracle_result, tpu_result = _run_both_svc(state, pending)
    assert tpu_result == oracle_result
    region_of = {
        n.metadata.name: n.metadata.labels.get("region") for n in nodes
    }
    assert {region_of[h] for h in oracle_result[:2]} == {"r1"}


def test_service_affinity_node_selector_pins():
    """A label value pinned by the pod's own nodeSelector wins over the
    peer's node."""
    nodes, services = _svc_affinity_cluster()
    state = ClusterState.build(
        nodes,
        services=services,
        assigned_pods=[_svc_pod("web-0", {"app": "web"}, node="node-0")],
    )
    # peer sits in r1 (node-0); the pinned pod demands r2 -> conflict with
    # the implicit selector is impossible since nodeSelector wins, so it
    # lands in r2 per the oracle
    pending = [
        _svc_pod("web-pinned", {"app": "web"}, node_selector={"region": "r2"})
    ]
    oracle_result, tpu_result = _run_both_svc(state, pending)
    assert tpu_result == oracle_result


def test_service_anti_affinity_spreads_across_label_values():
    """selector_spreading.go:244: peers spread across values of the config
    label; unlabeled nodes score 0."""
    nodes, services = _svc_affinity_cluster()
    state = ClusterState.build(nodes, services=services)
    pending = [_svc_pod(f"db-{i}", {"app": "db"}) for i in range(4)]
    oracle_result, tpu_result = _run_both_svc(
        state, pending, labels=(), anti_label="region"
    )
    assert tpu_result == oracle_result
    region_of = {
        n.metadata.name: n.metadata.labels.get("region") for n in nodes
    }
    placed = [region_of[h] for h in oracle_result]
    # spread: both regions used
    assert set(placed) >= {"r1", "r2"}


@pytest.mark.parametrize("seed", range(6))
def test_service_affinity_random_bit_identical(seed):
    rng = random.Random(3000 + seed)
    nodes, services = _svc_affinity_cluster()
    existing = []
    for i in range(rng.randint(0, 6)):
        existing.append(
            _svc_pod(
                f"e{i}",
                rng.choice([{"app": "web"}, {"app": "db"}, {"app": "x"}]),
                node=f"node-{rng.randrange(9)}",
            )
        )
    state = ClusterState.build(nodes, services=services, assigned_pods=existing)
    pending = [
        _svc_pod(
            f"p{i}",
            rng.choice([{"app": "web"}, {"app": "db"}, {"app": "x"}]),
            node_selector=rng.choice([{}, {}, {"region": rng.choice(["r1", "r2"])}]),
        )
        for i in range(10)
    ]
    oracle_result, tpu_result = _run_both_svc(
        state, pending, labels=("region", "rack"), anti_label="rack"
    )
    assert tpu_result == oracle_result, (
        f"seed {seed}: divergence at "
        f"{next(i for i, (a, b) in enumerate(zip(oracle_result, tpu_result)) if a != b)}"
    )


def test_service_affinity_all_labels_pinned_ignores_bad_peer():
    """Review regression (predicates.py 'if unresolved:' gate): when every
    affinity label is pinned by the pod's nodeSelector, the first peer is
    never consulted — even a peer on a deleted/None node must not reject
    candidates."""
    nodes, services = _svc_affinity_cluster()
    state = ClusterState.build(nodes, services=services)
    # a peer assigned to a node that does not exist in the cluster
    ghost = _svc_pod("ghost", {"app": "web"}, node="gone-node")
    state.assign(ghost)
    pending = [
        _svc_pod("unpinned", {"app": "web"}),  # consults the bad peer: unfit
        _svc_pod("pinned", {"app": "web"}, node_selector={"region": "r2"}),
    ]
    oracle_result, tpu_result = _run_both_svc(state, pending, labels=("region",))
    assert tpu_result == oracle_result
    assert oracle_result[0] is None  # unresolved label + bad peer -> unfit
    assert oracle_result[1] is not None  # all labels pinned: peer ignored
