"""Bit-identical conformance: BatchScheduler (TPU tensor path) vs the
sequential oracle on randomized scenarios.

This is the core guarantee of the framework (BASELINE.json north star):
node selection must match the serial reference loop exactly, including
round-robin tie-breaks, integer score truncations, and commitment
threading across the backlog.
"""

import random

import pytest

from kubernetes_tpu.api.types import (
    Container,
    ContainerPort,
    Node,
    NodeAffinity,
    NodeCondition,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    PreferredSchedulingTerm,
    Affinity,
    ReplicationController,
    ReplicationControllerSpec,
    Service,
    ServiceSpec,
    Taint,
    Toleration,
)
from kubernetes_tpu.models.batch import BatchScheduler, SchedulerConfig
from kubernetes_tpu.oracle import ClusterState, GenericScheduler
from kubernetes_tpu.oracle import predicates as opreds
from kubernetes_tpu.oracle import priorities as oprios
from kubernetes_tpu.oracle.scheduler import PriorityConfig
from kubernetes_tpu.snapshot.encode import SnapshotEncoder

ZONE = "failure-domain.beta.kubernetes.io/zone"
REGION = "failure-domain.beta.kubernetes.io/region"

ORACLE_PREDICATES = (
    ("GeneralPredicates", opreds.general_predicates),
    ("PodToleratesNodeTaints", opreds.pod_tolerates_node_taints),
    ("CheckNodeMemoryPressure", opreds.check_node_memory_pressure),
    ("MatchInterPodAffinity", opreds.inter_pod_affinity_matches),
)
ORACLE_PRIORITIES = (
    PriorityConfig(oprios.least_requested_priority, 1, "LeastRequestedPriority"),
    PriorityConfig(oprios.balanced_resource_allocation, 1, "BalancedResourceAllocation"),
    PriorityConfig(oprios.selector_spread_priority, 1, "SelectorSpreadPriority"),
    PriorityConfig(oprios.node_affinity_priority, 1, "NodeAffinityPriority"),
    PriorityConfig(oprios.taint_toleration_priority, 1, "TaintTolerationPriority"),
    PriorityConfig(oprios.inter_pod_affinity_priority, 1, "InterPodAffinityPriority"),
)


def random_pod_affinity(rng: random.Random, interpod_p: float):
    """Random PodAffinity/PodAntiAffinity over the scenario's app labels."""
    from kubernetes_tpu.api.types import (
        LabelSelector,
        LabelSelectorRequirement,
        PodAffinity,
        PodAffinityTerm,
        PodAntiAffinity,
        WeightedPodAffinityTerm,
    )

    if rng.random() >= interpod_p:
        return None

    def rand_selector():
        r = rng.random()
        if r < 0.4:
            return LabelSelector(match_labels={"app": rng.choice(["web", "db", "cache"])})
        if r < 0.7:
            return LabelSelector(
                match_expressions=(
                    LabelSelectorRequirement(
                        key=rng.choice(["app", "tier"]),
                        operator=rng.choice(["In", "NotIn", "Exists", "DoesNotExist"]),
                        values=(rng.choice(["web", "db", "be"]),),
                    ),
                )
            )
        if r < 0.85:
            return LabelSelector()  # empty == Everything
        return None  # nil == Nothing

    def rand_term():
        return PodAffinityTerm(
            label_selector=rand_selector(),
            namespaces=rng.choice([None, (), ("default",), ("other",)]),
            topology_key=rng.choice(
                ["kubernetes.io/hostname", ZONE, REGION, "", "disktype"]
            ),
        )

    def rand_side(cls):
        req = tuple(rand_term() for _ in range(rng.randint(0, 2)))
        pref = tuple(
            WeightedPodAffinityTerm(
                weight=rng.choice([0, 1, 3, 7]), pod_affinity_term=rand_term()
            )
            for _ in range(rng.randint(0, 2))
        )
        if not req and not pref and rng.random() < 0.5:
            return None
        return cls(
            required_during_scheduling_ignored_during_execution=req,
            preferred_during_scheduling_ignored_during_execution=pref,
        )

    aff = rng.random()
    return Affinity(
        pod_affinity=rand_side(PodAffinity) if aff < 0.7 else None,
        pod_anti_affinity=rand_side(PodAntiAffinity) if aff > 0.3 else None,
    )


def random_scenario(
    rng: random.Random, n_nodes=12, n_existing=15, n_pending=25, interpod_p=0.0
):
    zones = ["a", "b", "c"]
    nodes = []
    for i in range(n_nodes):
        labels = {"kubernetes.io/hostname": f"node-{i:03d}"}
        if rng.random() < 0.7:
            labels[ZONE] = rng.choice(zones)
            labels[REGION] = "r1"
        if rng.random() < 0.5:
            labels["disktype"] = rng.choice(["ssd", "hdd"])
        if rng.random() < 0.3:
            labels["gen"] = str(rng.randint(1, 5))
        taints = None
        if rng.random() < 0.25:
            taints = [
                Taint(
                    key=rng.choice(["dedicated", "special"]),
                    value=rng.choice(["a", "b"]),
                    effect=rng.choice(["NoSchedule", "PreferNoSchedule"]),
                )
            ]
        conds = [NodeCondition("Ready", "True")]
        if rng.random() < 0.15:
            conds.append(NodeCondition("MemoryPressure", "True"))
        nodes.append(
            Node(
                metadata=ObjectMeta(name=f"node-{i:03d}", labels=labels),
                spec=NodeSpec(taints=taints),
                status=NodeStatus(
                    allocatable={
                        "cpu": f"{rng.choice([1000, 2000, 4000])}m",
                        "memory": str(rng.choice([2, 4, 8]) * 1024**3),
                        "pods": str(rng.choice([3, 5, 110])),
                    },
                    conditions=conds,
                ),
            )
        )

    def rand_containers(allow_zero=True):
        cs = []
        for _ in range(rng.randint(1, 2)):
            reqs = {}
            if not allow_zero or rng.random() < 0.8:
                reqs["cpu"] = f"{rng.choice([0, 100, 250, 500])}m"
            if not allow_zero or rng.random() < 0.8:
                reqs["memory"] = str(rng.choice([0, 128, 512, 1024]) * 1024**2)
            ports = []
            if rng.random() < 0.25:
                ports.append(ContainerPort(host_port=rng.choice([8080, 9090, 9091])))
            cs.append(Container(requests=reqs, ports=ports))
        return cs

    app_labels = [{"app": "web"}, {"app": "db"}, {"app": "cache", "tier": "be"}]

    existing = []
    for i in range(n_existing):
        existing.append(
            Pod(
                metadata=ObjectMeta(
                    name=f"existing-{i}",
                    labels=rng.choice(app_labels),
                    deletion_timestamp="2026-01-01T00:00:00Z" if rng.random() < 0.1 else None,
                ),
                spec=PodSpec(
                    node_name=f"node-{rng.randrange(n_nodes):03d}",
                    containers=rand_containers(),
                    affinity=random_pod_affinity(rng, interpod_p),
                ),
            )
        )

    services = [
        Service(metadata=ObjectMeta(name="web"), spec=ServiceSpec(selector={"app": "web"})),
        Service(metadata=ObjectMeta(name="db"), spec=ServiceSpec(selector={"app": "db"})),
    ]
    controllers = [
        ReplicationController(
            metadata=ObjectMeta(name="cache-rc"),
            spec=ReplicationControllerSpec(selector={"app": "cache"}),
        )
    ]

    pending = []
    for i in range(n_pending):
        spec_kw = {}
        if rng.random() < 0.3:
            spec_kw["node_selector"] = rng.choice(
                [{"disktype": "ssd"}, {ZONE: "a"}, {"disktype": "hdd"}]
            )
        if rng.random() < 0.2:
            spec_kw["tolerations"] = [
                Toleration(
                    key=rng.choice(["dedicated", "special"]),
                    operator=rng.choice(["Exists", "Equal"]),
                    value="a",
                    effect=rng.choice(["", "NoSchedule"]),
                )
            ]
        affinity = None
        if rng.random() < 0.3:
            terms = []
            for _ in range(rng.randint(1, 2)):
                reqs = [
                    NodeSelectorRequirement(
                        key=rng.choice(["disktype", "gen", ZONE]),
                        operator=rng.choice(["In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"]),
                        values=(rng.choice(["ssd", "2", "a", "x"]),),
                    )
                ]
                terms.append(NodeSelectorTerm(match_expressions=tuple(reqs)))
            required = NodeSelector(node_selector_terms=tuple(terms)) if rng.random() < 0.6 else None
            preferred = ()
            if rng.random() < 0.5:
                preferred = tuple(
                    PreferredSchedulingTerm(
                        weight=rng.randint(1, 5),
                        preference=NodeSelectorTerm(
                            match_expressions=(
                                NodeSelectorRequirement(
                                    key=rng.choice(["disktype", "gen"]),
                                    operator=rng.choice(["In", "Exists"]),
                                    values=("ssd",),
                                ),
                            )
                        ),
                    )
                    for _ in range(rng.randint(1, 2))
                )
            affinity = Affinity(
                node_affinity=NodeAffinity(
                    required_during_scheduling_ignored_during_execution=required,
                    preferred_during_scheduling_ignored_during_execution=preferred,
                )
            )
        ip_aff = random_pod_affinity(rng, interpod_p)
        if ip_aff is not None:
            if affinity is None:
                affinity = ip_aff
            else:
                affinity.pod_affinity = ip_aff.pod_affinity
                affinity.pod_anti_affinity = ip_aff.pod_anti_affinity
        pod = Pod(
            metadata=ObjectMeta(name=f"pending-{i:04d}", labels=rng.choice(app_labels)),
            spec=PodSpec(
                containers=rand_containers(),
                affinity=affinity,
                **spec_kw,
            ),
        )
        if rng.random() < 0.1:
            pod.spec.init_containers = [
                Container(requests={"cpu": "600m", "memory": str(512 * 1024**2)})
            ]
        pending.append(pod)

    state = ClusterState.build(
        nodes, assigned_pods=existing, services=services, controllers=controllers
    )
    return state, pending


def run_both(state, pending):
    oracle = GenericScheduler(predicates=ORACLE_PREDICATES, priorities=ORACLE_PRIORITIES)
    oracle_result = oracle.schedule_backlog(pending, state.clone())

    enc = SnapshotEncoder(state, pending)
    snap, batch = enc.encode()
    tpu = BatchScheduler(SchedulerConfig())
    tpu_result = tpu.schedule_names(snap, batch)
    return oracle_result, tpu_result


@pytest.mark.parametrize("seed", range(8))
def test_random_scenarios_bit_identical(seed):
    rng = random.Random(seed)
    state, pending = random_scenario(rng)
    oracle_result, tpu_result = run_both(state, pending)
    assert tpu_result == oracle_result, (
        f"seed {seed}: first divergence at "
        f"{next(i for i, (a, b) in enumerate(zip(oracle_result, tpu_result)) if a != b)}"
    )


def test_scheduler_perf_shape_identical():
    # 50 identical nodes, 300 identical pause pods — the density-test shape
    nodes = [
        Node(
            metadata=ObjectMeta(name=f"node-{i:04d}"),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        )
        for i in range(50)
    ]
    pods = [
        Pod(
            metadata=ObjectMeta(name=f"pod-{i:05d}", labels={"app": "pause"}),
            spec=PodSpec(
                containers=[Container(requests={"cpu": "100m", "memory": "500Mi"})]
            ),
        )
        for i in range(300)
    ]
    state = ClusterState.build(nodes)
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    assert None not in tpu_result


def test_duplicate_taints_count_per_list():
    # a node carrying duplicate PreferNoSchedule taints counts each
    # occurrence in the taint-toleration priority (review regression)
    n0 = Node(
        metadata=ObjectMeta(name="node-0"),
        spec=NodeSpec(
            taints=[Taint("k", "v", "PreferNoSchedule"), Taint("k", "v", "PreferNoSchedule")]
        ),
        status=NodeStatus(
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )
    n1 = Node(
        metadata=ObjectMeta(name="node-1"),
        spec=NodeSpec(taints=[Taint("other", "x", "PreferNoSchedule")]),
        status=NodeStatus(
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )
    pods = [
        Pod(
            metadata=ObjectMeta(name=f"p{i}"),
            spec=PodSpec(
                containers=[Container(requests={"cpu": "100m"})],
                tolerations=[Toleration(key="zzz", operator="Exists")],
            ),
        )
        for i in range(2)
    ]
    state = ClusterState.build([n0, n1])
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result


def test_bogus_operator_in_required_term(recwarn):
    # term order matters: a match BEFORE the bogus term wins; a bogus term
    # reached first rejects the whole list (review regression)
    def mk_pod(name, terms):
        return Pod(
            metadata=ObjectMeta(name=name),
            spec=PodSpec(
                containers=[Container(requests={"cpu": "100m"})],
                affinity=Affinity(
                    node_affinity=NodeAffinity(
                        required_during_scheduling_ignored_during_execution=NodeSelector(
                            node_selector_terms=tuple(terms)
                        )
                    )
                ),
            ),
        )

    good = NodeSelectorTerm(
        match_expressions=(
            NodeSelectorRequirement(key="disktype", operator="In", values=("ssd",)),
        )
    )
    bogus = NodeSelectorTerm(
        match_expressions=(
            NodeSelectorRequirement(key="x", operator="Bogus", values=("y",)),
        )
    )
    nodes = [
        Node(
            metadata=ObjectMeta(name=f"node-{i}", labels={"disktype": "ssd"}),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        )
        for i in range(2)
    ]
    state = ClusterState.build(nodes)
    pods = [mk_pod("a", [bogus, good]), mk_pod("b", [good, bogus])]
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    assert oracle_result[0] is None  # bogus reached first -> unschedulable
    assert oracle_result[1] is not None  # good term matched first -> fits


def test_empty_cluster_all_unscheduled():
    # review regression: zero-node snapshot must return all -1, not crash
    state = ClusterState.build([])
    pods = [
        Pod(metadata=ObjectMeta(name="p"), spec=PodSpec(containers=[Container()]))
    ]
    oracle_result, tpu_result = run_both(state, pods)
    assert oracle_result == [None]
    assert tpu_result == [None]


# --- inter-pod affinity conformance -----------------------------------------


@pytest.mark.parametrize("seed", range(12))
def test_interpod_affinity_random_bit_identical(seed):
    """Randomized pod (anti-)affinity on existing AND pending pods, all
    topology keys incl. empty (= any default failure domain), namespaces
    modes, weight-0 terms, commitment threading mid-backlog."""
    rng = random.Random(1000 + seed)
    state, pending = random_scenario(
        rng, n_nodes=8, n_existing=10, n_pending=15, interpod_p=0.6
    )
    oracle_result, tpu_result = run_both(state, pending)
    assert tpu_result == oracle_result, (
        f"seed {seed}: first divergence at "
        f"{next(i for i, (a, b) in enumerate(zip(oracle_result, tpu_result)) if a != b)}"
    )


def _affinity_nodes(n=4):
    zones = ["a", "a", "b", "b"]
    return [
        Node(
            metadata=ObjectMeta(
                name=f"node-{i}",
                labels={
                    "kubernetes.io/hostname": f"node-{i}",
                    ZONE: zones[i % len(zones)],
                    REGION: "r1",
                },
            ),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        )
        for i in range(n)
    ]


def _aff_pod(name, labels, affinity=None, node=None):
    from kubernetes_tpu.api.types import PodSpec

    return Pod(
        metadata=ObjectMeta(name=name, labels=labels),
        spec=PodSpec(
            containers=[Container(requests={"cpu": "100m"})],
            affinity=affinity,
            node_name=node,
        ),
    )


def test_interpod_first_pod_of_collection_escape():
    """predicates.go:819-843: a hard-affinity term matching no pod anywhere
    is waived iff the pod matches its own term."""
    from kubernetes_tpu.api.types import (
        LabelSelector,
        PodAffinity,
        PodAffinityTerm,
    )

    term = PodAffinityTerm(
        label_selector=LabelSelector(match_labels={"app": "solo"}),
        topology_key=ZONE,
    )
    aff = Affinity(
        pod_affinity=PodAffinity(
            required_during_scheduling_ignored_during_execution=(term,)
        )
    )
    state = ClusterState.build(_affinity_nodes())
    # first pod self-matches -> escape applies -> schedules; second pod
    # then finds the first co-located; a non-self-matching pod with the
    # same term must follow the collection, and a pod whose term matches
    # nothing and not itself is unschedulable.
    pods = [
        _aff_pod("first", {"app": "solo"}, aff),
        _aff_pod("second", {"app": "solo"}, aff),
        _aff_pod("follower", {"app": "other"}, aff),
        _aff_pod(
            "lost",
            {"app": "other"},
            Affinity(
                pod_affinity=PodAffinity(
                    required_during_scheduling_ignored_during_execution=(
                        PodAffinityTerm(
                            label_selector=LabelSelector(
                                match_labels={"app": "nonexistent"}
                            ),
                            topology_key=ZONE,
                        ),
                    )
                )
            ),
        ),
    ]
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    assert oracle_result[0] is not None
    assert oracle_result[3] is None
    # followers landed in the first pod's zone
    zone_of = {f"node-{i}": ["a", "a", "b", "b"][i] for i in range(4)}
    assert zone_of[oracle_result[1]] == zone_of[oracle_result[0]]
    assert zone_of[oracle_result[2]] == zone_of[oracle_result[0]]


def test_interpod_symmetric_anti_affinity():
    """predicates.go:858-921: an ASSIGNED pod's hard anti-affinity term
    keeps matching pods out of its topology domain (symmetry) — both for
    preexisting pods and for pods committed mid-backlog."""
    from kubernetes_tpu.api.types import (
        LabelSelector,
        PodAntiAffinity,
        PodAffinityTerm,
    )

    anti = Affinity(
        pod_anti_affinity=PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=(
                PodAffinityTerm(
                    label_selector=LabelSelector(match_labels={"app": "web"}),
                    topology_key=ZONE,
                ),
            )
        )
    )
    state = ClusterState.build(
        _affinity_nodes(),
        assigned_pods=[_aff_pod("guard", {"app": "db"}, anti, node="node-0")],
    )
    pods = [
        _aff_pod("web-2", {"app": "web"}, anti),  # must avoid zone a (guard)
        _aff_pod("web-1", {"app": "web"}),  # no own anti: symmetric check is
        # gated on the pod having anti-affinity => schedules anywhere
    ]
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    zone_of = {f"node-{i}": ["a", "a", "b", "b"][i] for i in range(4)}
    assert zone_of[oracle_result[0]] == "b"
    assert oracle_result[1] is not None


def test_interpod_empty_topology_key_any_default_domain():
    """util/non_zero.go:97-113: empty topologyKey in anti-affinity means
    co-location under ANY default failure-domain key."""
    from kubernetes_tpu.api.types import (
        LabelSelector,
        PodAntiAffinity,
        PodAffinityTerm,
    )

    anti = Affinity(
        pod_anti_affinity=PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=(
                PodAffinityTerm(
                    label_selector=LabelSelector(match_labels={"app": "web"}),
                    topology_key="",
                ),
            )
        )
    )
    # node-0/1 share zone a + region; node-2/3 share zone b + region — all
    # four share the region, so an existing web pod anywhere blocks every
    # node for an anti(web, "") pod.
    state = ClusterState.build(
        _affinity_nodes(),
        assigned_pods=[_aff_pod("w", {"app": "web"}, node="node-3")],
    )
    pods = [_aff_pod("p", {"app": "cache"}, anti)]
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    assert oracle_result[0] is None  # region co-location blocks everywhere


def test_interpod_priority_reverse_direction():
    """interpod_affinity.go:128-191: assigned pods' preferred terms pull
    (or push) the pending pod toward/away from their domains."""
    from kubernetes_tpu.api.types import (
        LabelSelector,
        PodAffinity,
        PodAffinityTerm,
        WeightedPodAffinityTerm,
    )

    want_web_near = Affinity(
        pod_affinity=PodAffinity(
            preferred_during_scheduling_ignored_during_execution=(
                WeightedPodAffinityTerm(
                    weight=7,
                    pod_affinity_term=PodAffinityTerm(
                        label_selector=LabelSelector(match_labels={"app": "web"}),
                        topology_key=ZONE,
                    ),
                ),
            )
        )
    )
    state = ClusterState.build(
        _affinity_nodes(),
        assigned_pods=[
            _aff_pod("attractor", {"app": "db"}, want_web_near, node="node-2")
        ],
    )
    pods = [_aff_pod("web-1", {"app": "web"})]
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    zone_of = {f"node-{i}": ["a", "a", "b", "b"][i] for i in range(4)}
    assert zone_of[oracle_result[0]] == "b"  # pulled toward the attractor
