"""Bit-identical conformance: BatchScheduler (TPU tensor path) vs the
sequential oracle on randomized scenarios.

This is the core guarantee of the framework (BASELINE.json north star):
node selection must match the serial reference loop exactly, including
round-robin tie-breaks, integer score truncations, and commitment
threading across the backlog.
"""

import random

import pytest

from kubernetes_tpu.api.types import (
    Container,
    ContainerPort,
    Node,
    NodeAffinity,
    NodeCondition,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    PreferredSchedulingTerm,
    Affinity,
    ReplicationController,
    ReplicationControllerSpec,
    Service,
    ServiceSpec,
    Taint,
    Toleration,
)
from kubernetes_tpu.models.batch import BatchScheduler, SchedulerConfig
from kubernetes_tpu.oracle import ClusterState, GenericScheduler
from kubernetes_tpu.oracle import predicates as opreds
from kubernetes_tpu.oracle import priorities as oprios
from kubernetes_tpu.oracle.scheduler import PriorityConfig
from kubernetes_tpu.snapshot.encode import SnapshotEncoder

ZONE = "failure-domain.beta.kubernetes.io/zone"
REGION = "failure-domain.beta.kubernetes.io/region"

ORACLE_PREDICATES = (
    ("GeneralPredicates", opreds.general_predicates),
    ("PodToleratesNodeTaints", opreds.pod_tolerates_node_taints),
    ("CheckNodeMemoryPressure", opreds.check_node_memory_pressure),
)
ORACLE_PRIORITIES = (
    PriorityConfig(oprios.least_requested_priority, 1, "LeastRequestedPriority"),
    PriorityConfig(oprios.balanced_resource_allocation, 1, "BalancedResourceAllocation"),
    PriorityConfig(oprios.selector_spread_priority, 1, "SelectorSpreadPriority"),
    PriorityConfig(oprios.node_affinity_priority, 1, "NodeAffinityPriority"),
    PriorityConfig(oprios.taint_toleration_priority, 1, "TaintTolerationPriority"),
)


def random_scenario(rng: random.Random, n_nodes=12, n_existing=15, n_pending=25):
    zones = ["a", "b", "c"]
    nodes = []
    for i in range(n_nodes):
        labels = {"kubernetes.io/hostname": f"node-{i:03d}"}
        if rng.random() < 0.7:
            labels[ZONE] = rng.choice(zones)
            labels[REGION] = "r1"
        if rng.random() < 0.5:
            labels["disktype"] = rng.choice(["ssd", "hdd"])
        if rng.random() < 0.3:
            labels["gen"] = str(rng.randint(1, 5))
        taints = None
        if rng.random() < 0.25:
            taints = [
                Taint(
                    key=rng.choice(["dedicated", "special"]),
                    value=rng.choice(["a", "b"]),
                    effect=rng.choice(["NoSchedule", "PreferNoSchedule"]),
                )
            ]
        conds = [NodeCondition("Ready", "True")]
        if rng.random() < 0.15:
            conds.append(NodeCondition("MemoryPressure", "True"))
        nodes.append(
            Node(
                metadata=ObjectMeta(name=f"node-{i:03d}", labels=labels),
                spec=NodeSpec(taints=taints),
                status=NodeStatus(
                    allocatable={
                        "cpu": f"{rng.choice([1000, 2000, 4000])}m",
                        "memory": str(rng.choice([2, 4, 8]) * 1024**3),
                        "pods": str(rng.choice([3, 5, 110])),
                    },
                    conditions=conds,
                ),
            )
        )

    def rand_containers(allow_zero=True):
        cs = []
        for _ in range(rng.randint(1, 2)):
            reqs = {}
            if not allow_zero or rng.random() < 0.8:
                reqs["cpu"] = f"{rng.choice([0, 100, 250, 500])}m"
            if not allow_zero or rng.random() < 0.8:
                reqs["memory"] = str(rng.choice([0, 128, 512, 1024]) * 1024**2)
            ports = []
            if rng.random() < 0.25:
                ports.append(ContainerPort(host_port=rng.choice([8080, 9090, 9091])))
            cs.append(Container(requests=reqs, ports=ports))
        return cs

    app_labels = [{"app": "web"}, {"app": "db"}, {"app": "cache", "tier": "be"}]

    existing = []
    for i in range(n_existing):
        existing.append(
            Pod(
                metadata=ObjectMeta(
                    name=f"existing-{i}",
                    labels=rng.choice(app_labels),
                    deletion_timestamp="2026-01-01T00:00:00Z" if rng.random() < 0.1 else None,
                ),
                spec=PodSpec(
                    node_name=f"node-{rng.randrange(n_nodes):03d}",
                    containers=rand_containers(),
                ),
            )
        )

    services = [
        Service(metadata=ObjectMeta(name="web"), spec=ServiceSpec(selector={"app": "web"})),
        Service(metadata=ObjectMeta(name="db"), spec=ServiceSpec(selector={"app": "db"})),
    ]
    controllers = [
        ReplicationController(
            metadata=ObjectMeta(name="cache-rc"),
            spec=ReplicationControllerSpec(selector={"app": "cache"}),
        )
    ]

    pending = []
    for i in range(n_pending):
        spec_kw = {}
        if rng.random() < 0.3:
            spec_kw["node_selector"] = rng.choice(
                [{"disktype": "ssd"}, {ZONE: "a"}, {"disktype": "hdd"}]
            )
        if rng.random() < 0.2:
            spec_kw["tolerations"] = [
                Toleration(
                    key=rng.choice(["dedicated", "special"]),
                    operator=rng.choice(["Exists", "Equal"]),
                    value="a",
                    effect=rng.choice(["", "NoSchedule"]),
                )
            ]
        affinity = None
        if rng.random() < 0.3:
            terms = []
            for _ in range(rng.randint(1, 2)):
                reqs = [
                    NodeSelectorRequirement(
                        key=rng.choice(["disktype", "gen", ZONE]),
                        operator=rng.choice(["In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"]),
                        values=(rng.choice(["ssd", "2", "a", "x"]),),
                    )
                ]
                terms.append(NodeSelectorTerm(match_expressions=tuple(reqs)))
            required = NodeSelector(node_selector_terms=tuple(terms)) if rng.random() < 0.6 else None
            preferred = ()
            if rng.random() < 0.5:
                preferred = tuple(
                    PreferredSchedulingTerm(
                        weight=rng.randint(1, 5),
                        preference=NodeSelectorTerm(
                            match_expressions=(
                                NodeSelectorRequirement(
                                    key=rng.choice(["disktype", "gen"]),
                                    operator=rng.choice(["In", "Exists"]),
                                    values=("ssd",),
                                ),
                            )
                        ),
                    )
                    for _ in range(rng.randint(1, 2))
                )
            affinity = Affinity(
                node_affinity=NodeAffinity(
                    required_during_scheduling_ignored_during_execution=required,
                    preferred_during_scheduling_ignored_during_execution=preferred,
                )
            )
        pod = Pod(
            metadata=ObjectMeta(name=f"pending-{i:04d}", labels=rng.choice(app_labels)),
            spec=PodSpec(
                containers=rand_containers(),
                affinity=affinity,
                **spec_kw,
            ),
        )
        if rng.random() < 0.1:
            pod.spec.init_containers = [
                Container(requests={"cpu": "600m", "memory": str(512 * 1024**2)})
            ]
        pending.append(pod)

    state = ClusterState.build(
        nodes, assigned_pods=existing, services=services, controllers=controllers
    )
    return state, pending


def run_both(state, pending):
    oracle = GenericScheduler(predicates=ORACLE_PREDICATES, priorities=ORACLE_PRIORITIES)
    oracle_result = oracle.schedule_backlog(pending, state.clone())

    enc = SnapshotEncoder(state, pending)
    snap, batch = enc.encode()
    tpu = BatchScheduler(SchedulerConfig())
    tpu_result = tpu.schedule_names(snap, batch)
    return oracle_result, tpu_result


@pytest.mark.parametrize("seed", range(8))
def test_random_scenarios_bit_identical(seed):
    rng = random.Random(seed)
    state, pending = random_scenario(rng)
    oracle_result, tpu_result = run_both(state, pending)
    assert tpu_result == oracle_result, (
        f"seed {seed}: first divergence at "
        f"{next(i for i, (a, b) in enumerate(zip(oracle_result, tpu_result)) if a != b)}"
    )


def test_scheduler_perf_shape_identical():
    # 50 identical nodes, 300 identical pause pods — the density-test shape
    nodes = [
        Node(
            metadata=ObjectMeta(name=f"node-{i:04d}"),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        )
        for i in range(50)
    ]
    pods = [
        Pod(
            metadata=ObjectMeta(name=f"pod-{i:05d}", labels={"app": "pause"}),
            spec=PodSpec(
                containers=[Container(requests={"cpu": "100m", "memory": "500Mi"})]
            ),
        )
        for i in range(300)
    ]
    state = ClusterState.build(nodes)
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    assert None not in tpu_result


def test_duplicate_taints_count_per_list():
    # a node carrying duplicate PreferNoSchedule taints counts each
    # occurrence in the taint-toleration priority (review regression)
    n0 = Node(
        metadata=ObjectMeta(name="node-0"),
        spec=NodeSpec(
            taints=[Taint("k", "v", "PreferNoSchedule"), Taint("k", "v", "PreferNoSchedule")]
        ),
        status=NodeStatus(
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )
    n1 = Node(
        metadata=ObjectMeta(name="node-1"),
        spec=NodeSpec(taints=[Taint("other", "x", "PreferNoSchedule")]),
        status=NodeStatus(
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )
    pods = [
        Pod(
            metadata=ObjectMeta(name=f"p{i}"),
            spec=PodSpec(
                containers=[Container(requests={"cpu": "100m"})],
                tolerations=[Toleration(key="zzz", operator="Exists")],
            ),
        )
        for i in range(2)
    ]
    state = ClusterState.build([n0, n1])
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result


def test_bogus_operator_in_required_term(recwarn):
    # term order matters: a match BEFORE the bogus term wins; a bogus term
    # reached first rejects the whole list (review regression)
    def mk_pod(name, terms):
        return Pod(
            metadata=ObjectMeta(name=name),
            spec=PodSpec(
                containers=[Container(requests={"cpu": "100m"})],
                affinity=Affinity(
                    node_affinity=NodeAffinity(
                        required_during_scheduling_ignored_during_execution=NodeSelector(
                            node_selector_terms=tuple(terms)
                        )
                    )
                ),
            ),
        )

    good = NodeSelectorTerm(
        match_expressions=(
            NodeSelectorRequirement(key="disktype", operator="In", values=("ssd",)),
        )
    )
    bogus = NodeSelectorTerm(
        match_expressions=(
            NodeSelectorRequirement(key="x", operator="Bogus", values=("y",)),
        )
    )
    nodes = [
        Node(
            metadata=ObjectMeta(name=f"node-{i}", labels={"disktype": "ssd"}),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        )
        for i in range(2)
    ]
    state = ClusterState.build(nodes)
    pods = [mk_pod("a", [bogus, good]), mk_pod("b", [good, bogus])]
    oracle_result, tpu_result = run_both(state, pods)
    assert tpu_result == oracle_result
    assert oracle_result[0] is None  # bogus reached first -> unschedulable
    assert oracle_result[1] is not None  # good term matched first -> fits


def test_empty_cluster_all_unscheduled():
    # review regression: zero-node snapshot must return all -1, not crash
    state = ClusterState.build([])
    pods = [
        Pod(metadata=ObjectMeta(name="p"), spec=PodSpec(containers=[Container()]))
    ]
    oracle_result, tpu_result = run_both(state, pods)
    assert oracle_result == [None]
    assert tpu_result == [None]
