"""Federation (multi-cluster) + DNS + hyperkube local-up pieces."""

import time

import pytest

from kubernetes_tpu.api.types import (
    Container,
    EndpointAddress,
    EndpointPort,
    Endpoints,
    EndpointSubset,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
    ReplicationController,
    ReplicationControllerSpec,
    Service,
    ServicePort,
    ServiceSpec,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import HTTPTransport, LocalTransport
from kubernetes_tpu.dns import DNSRecords
from kubernetes_tpu.federation import (
    Cluster,
    ClusterController,
    ClusterSpec,
    FederatedAPIServer,
    FederatedReplicationManager,
)
from kubernetes_tpu.federation.federation import spread_replicas


from conftest import wait_until  # noqa: E402


def test_federation_health_and_spread():
    fed = FederatedAPIServer()
    fed_client = RESTClient(LocalTransport(fed))
    members = {f"c{i}": APIServer() for i in range(3)}
    clients = {n: RESTClient(LocalTransport(s)) for n, s in members.items()}

    def member_client(cluster):
        return clients.get(cluster.metadata.name)

    for name in members:
        fed_client.resource("clusters").create(
            Cluster(metadata=ObjectMeta(name=name),
                    spec=ClusterSpec(server_address=f"local://{name}"))
        )
    # an unreachable member
    fed_client.resource("clusters").create(
        Cluster(metadata=ObjectMeta(name="gone"),
                spec=ClusterSpec(server_address="local://gone"))
    )
    cc = ClusterController(fed_client, member_client)
    cc.sync_once()
    ready = {
        c.metadata.name: c.status.conditions[0].status
        for c in fed_client.resource("clusters").list()[0]
    }
    assert ready == {"c0": "True", "c1": "True", "c2": "True", "gone": "False"}

    # federated RC of 8 replicas spread 3/3/2 across ready clusters
    fed_client.resource("replicationcontrollers", "default").create(
        ReplicationController(
            metadata=ObjectMeta(name="web"),
            spec=ReplicationControllerSpec(
                replicas=8, selector={"app": "web"},
                template=PodTemplateSpec(
                    metadata=ObjectMeta(labels={"app": "web"}),
                    spec=PodSpec(containers=[Container(name="c")]),
                ),
            ),
        )
    )
    frm = FederatedReplicationManager(fed_client, member_client)
    frm.sync_once()
    shares = [
        clients[n].resource("replicationcontrollers", "default").get("web").spec.replicas
        for n in ("c0", "c1", "c2")
    ]
    assert shares == [3, 3, 2]
    # scaling the federated object rebalances members
    rc = fed_client.resource("replicationcontrollers", "default").get("web")
    rc.spec.replicas = 4
    fed_client.resource("replicationcontrollers", "default").update(rc)
    frm.sync_once()
    shares = [
        clients[n].resource("replicationcontrollers", "default").get("web").spec.replicas
        for n in ("c0", "c1", "c2")
    ]
    assert shares == [2, 1, 1]


def test_spread_replicas():
    assert spread_replicas(10, 3) == [4, 3, 3]
    assert spread_replicas(2, 3) == [1, 1, 0]
    assert spread_replicas(0, 2) == [0, 0]
    assert spread_replicas(5, 0) == []


def test_dns_records():
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    dns = DNSRecords(client).run()
    try:
        client.resource("services", "default").create(
            Service(
                metadata=ObjectMeta(name="web"),
                spec=ServiceSpec(
                    selector={"app": "web"},
                    cluster_ip="10.0.0.10",
                    ports=[ServicePort(name="http", port=80)],
                ),
            )
        )
        client.resource("services", "default").create(
            Service(
                metadata=ObjectMeta(name="db"),
                spec=ServiceSpec(selector={"app": "db"}, cluster_ip="None"),
            )
        )
        client.resource("endpoints", "default").create(
            Endpoints(
                metadata=ObjectMeta(name="db"),
                subsets=[EndpointSubset(
                    addresses=[
                        EndpointAddress(ip="10.1.0.5", target_ref="default/db-0"),
                        EndpointAddress(ip="10.1.0.6", target_ref="default/db-1"),
                    ],
                    ports=[EndpointPort(port=5432)],
                )],
            )
        )
        assert wait_until(
            lambda: dns.resolve("web.default.svc.cluster.local") == ["10.0.0.10"]
        )
        # headless -> endpoint IPs; pet hostname -> its own IP
        assert wait_until(
            lambda: dns.resolve("db.default.svc.cluster.local")
            == ["10.1.0.5", "10.1.0.6"]
        )
        assert dns.resolve("db-1.db.default.svc.cluster.local") == ["10.1.0.6"]
        assert dns.resolve("nope.default.svc.cluster.local") == []
        srv = dns.resolve_srv("_http._tcp.web.default.svc.cluster.local")
        assert len(srv) == 1 and srv[0].port == 80
        assert srv[0].target == "web.default.svc.cluster.local"
    finally:
        dns.stop()


def _dns_query(name: str, qtype: int, txn: int = 0x1234) -> bytes:
    """A dig-equivalent raw query packet (RFC1035, RD set)."""
    import struct

    out = bytearray(struct.pack("!HHHHHH", txn, 0x0100, 1, 0, 0, 0))
    for label in name.rstrip(".").split("."):
        out.append(len(label))
        out += label.encode()
    out.append(0)
    out += struct.pack("!HH", qtype, 1)
    return bytes(out)


def _parse_answers(data: bytes, txn: int = 0x1234):
    """-> (rcode, [(type, rdata-bytes)]). Minimal independent parser."""
    import struct

    tid, flags, qd, an, _, _ = struct.unpack_from("!HHHHHH", data, 0)
    assert tid == txn and flags & 0x8000  # a response to our txn
    pos = 12
    while data[pos]:  # skip question name
        pos += 1 + data[pos]
    pos += 1 + 4
    out = []
    for _ in range(an):
        assert data[pos:pos + 2] == b"\xc0\x0c"  # name -> question
        rtype, _cls, _ttl, rdlen = struct.unpack_from("!HHIH", data, pos + 2)
        rdata = data[pos + 12:pos + 12 + rdlen]
        out.append((rtype, rdata))
        pos += 12 + rdlen
    return flags & 0xF, out


def test_dns_wire_protocol():
    """dig-style A and SRV queries over real UDP and TCP sockets resolve
    a service, a headless service, and a pet hostname (cmd/kube-dns)."""
    import socket
    import struct

    from kubernetes_tpu.dns import DNSServer

    server = APIServer()
    client = RESTClient(LocalTransport(server))
    dns = DNSRecords(client).run()
    wire = DNSServer(dns)
    host, port = wire.serve()
    try:
        client.resource("services", "default").create(
            Service(
                metadata=ObjectMeta(name="web"),
                spec=ServiceSpec(
                    selector={"app": "web"},
                    cluster_ip="10.0.0.10",
                    ports=[ServicePort(name="http", port=80)],
                ),
            )
        )
        client.resource("services", "default").create(
            Service(
                metadata=ObjectMeta(name="db"),
                spec=ServiceSpec(selector={"app": "db"}, cluster_ip="None"),
            )
        )
        client.resource("endpoints", "default").create(
            Endpoints(
                metadata=ObjectMeta(name="db"),
                subsets=[EndpointSubset(
                    addresses=[
                        EndpointAddress(ip="10.1.0.5", target_ref="default/db-0"),
                        EndpointAddress(ip="10.1.0.6", target_ref="default/db-1"),
                    ],
                    ports=[EndpointPort(port=5432)],
                )],
            )
        )
        assert wait_until(
            lambda: dns.resolve("web.default.svc.cluster.local") == ["10.0.0.10"]
        )
        assert wait_until(
            lambda: len(dns.resolve("db.default.svc.cluster.local")) == 2
        )

        def udp_ask(name, qtype):
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.settimeout(5)
            s.sendto(_dns_query(name, qtype), (host, port))
            data, _ = s.recvfrom(4096)
            s.close()
            return _parse_answers(data)

        # A: cluster IP
        rcode, ans = udp_ask("web.default.svc.cluster.local", 1)
        assert rcode == 0
        assert [socket.inet_ntoa(r) for t, r in ans if t == 1] == ["10.0.0.10"]
        # A: headless -> both endpoint IPs
        rcode, ans = udp_ask("db.default.svc.cluster.local", 1)
        assert sorted(socket.inet_ntoa(r) for _t, r in ans) == [
            "10.1.0.5", "10.1.0.6"]
        # A: pet hostname
        rcode, ans = udp_ask("db-1.db.default.svc.cluster.local", 1)
        assert [socket.inet_ntoa(r) for _t, r in ans] == ["10.1.0.6"]
        # SRV: named port
        rcode, ans = udp_ask("_http._tcp.web.default.svc.cluster.local", 33)
        assert rcode == 0 and len(ans) == 1
        prio, weight, sport = struct.unpack_from("!HHH", ans[0][1], 0)
        assert sport == 80
        # NXDOMAIN
        rcode, ans = udp_ask("nope.default.svc.cluster.local", 1)
        assert rcode == 3 and ans == []

        # TCP path (2-byte length prefix)
        c = socket.create_connection((host, port), timeout=5)
        q = _dns_query("web.default.svc.cluster.local", 1)
        c.sendall(struct.pack("!H", len(q)) + q)
        hdr = c.recv(2)
        (n,) = struct.unpack("!H", hdr)
        data = b""
        while len(data) < n:
            data += c.recv(n - len(data))
        c.close()
        rcode, ans = _parse_answers(data)
        assert [socket.inet_ntoa(r) for _t, r in ans] == ["10.0.0.10"]

        # hostile input: garbage and a compression-pointer loop are
        # dropped without killing the server
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.settimeout(0.3)
        s.sendto(b"\x00" * 5, (host, port))
        loop = bytearray(_dns_query("a.b", 1))
        loop[12] = 0xC0
        loop[13] = 0x0C  # name points at itself
        s.sendto(bytes(loop), (host, port))
        import pytest as _pytest

        with _pytest.raises(socket.timeout):
            s.recvfrom(4096)
        s.close()
        rcode, ans = udp_ask("web.default.svc.cluster.local", 1)
        assert rcode == 0  # still serving
    finally:
        wire.shutdown()
        dns.stop()


def test_federation_controller_manager_join_flow():
    """The kubefed-join flow through the federation-controller-manager
    process: join two member clusters by endpoint, watch health flip
    Ready, services and replicas propagate; unjoin stops propagation."""
    from kubernetes_tpu.federation import (
        FederatedAPIServer,
        FederationControllerManager,
        join_cluster,
        unjoin_cluster,
    )

    fed_server = FederatedAPIServer()
    fed = RESTClient(LocalTransport(fed_server))
    members = {}
    for name in ("east", "west"):
        srv = APIServer()
        host, port = srv.serve_http(port=0)
        members[name] = (srv, f"http://{host}:{port}")
    try:
        for name, (_srv, url) in members.items():
            join_cluster(fed, name, url)
        mgr = FederationControllerManager(
            fed, cluster_sync_period=0.1, workload_sync_period=0.1
        ).start()
        try:
            def ready_count():
                clusters, _ = fed.resource("clusters").list()
                return sum(
                    1 for c in clusters
                    if any(cond.type == "Ready" and cond.status == "True"
                           for cond in c.status.conditions)
                )

            assert wait_until(lambda: ready_count() == 2)
            # a federated service propagates to every member
            fed.resource("services", "default").create(Service(
                metadata=ObjectMeta(name="web"),
                spec=ServiceSpec(selector={"app": "web"},
                                 ports=[ServicePort(port=80)]),
            ))
            east = RESTClient(HTTPTransport(members["east"][1]))
            west = RESTClient(HTTPTransport(members["west"][1]))
            assert wait_until(lambda: all(
                _has_service(c, "web") for c in (east, west)
            ))
            # a federated RC spreads 5 replicas 3/2 across members
            from kubernetes_tpu.api.types import (
                Container,
                Pod,
                PodSpec,
                PodTemplateSpec,
                ReplicationController,
                ReplicationControllerSpec,
            )

            fed.resource("replicationcontrollers", "default").create(
                ReplicationController(
                    metadata=ObjectMeta(name="app"),
                    spec=ReplicationControllerSpec(
                        replicas=5, selector={"run": "app"},
                        template=PodTemplateSpec(
                            metadata=ObjectMeta(labels={"run": "app"}),
                            spec=PodSpec(containers=[Container(name="c")]),
                        ),
                    ),
                )
            )

            def shares():
                out = []
                for c in (east, west):
                    try:
                        rc = c.resource(
                            "replicationcontrollers", "default").get("app")
                        out.append(rc.spec.replicas)
                    except Exception:
                        out.append(None)
                return out

            assert wait_until(lambda: shares() == [3, 2])
            # unjoin west: its propagated workloads are DELETED (the
            # kubefed cleanup) and reconcile concentrates on east
            unjoin_cluster(fed, "west")
            assert wait_until(lambda: shares() == [5, None])
            assert not _has_service(west, "web")
            assert _has_service(east, "web")
        finally:
            mgr.stop()
    finally:
        for srv, _url in members.values():
            srv.shutdown_http()


def _has_service(client, name):
    try:
        client.resource("services", "default").get(name)
        return True
    except Exception:
        return False
