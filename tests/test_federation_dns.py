"""Federation (multi-cluster) + DNS + hyperkube local-up pieces."""

import time

import pytest

from kubernetes_tpu.api.types import (
    Container,
    EndpointAddress,
    EndpointPort,
    Endpoints,
    EndpointSubset,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
    ReplicationController,
    ReplicationControllerSpec,
    Service,
    ServicePort,
    ServiceSpec,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.dns import DNSRecords
from kubernetes_tpu.federation import (
    Cluster,
    ClusterController,
    ClusterSpec,
    FederatedAPIServer,
    FederatedReplicationManager,
)
from kubernetes_tpu.federation.federation import spread_replicas


def wait_until(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def test_federation_health_and_spread():
    fed = FederatedAPIServer()
    fed_client = RESTClient(LocalTransport(fed))
    members = {f"c{i}": APIServer() for i in range(3)}
    clients = {n: RESTClient(LocalTransport(s)) for n, s in members.items()}

    def member_client(cluster):
        return clients.get(cluster.metadata.name)

    for name in members:
        fed_client.resource("clusters").create(
            Cluster(metadata=ObjectMeta(name=name),
                    spec=ClusterSpec(server_address=f"local://{name}"))
        )
    # an unreachable member
    fed_client.resource("clusters").create(
        Cluster(metadata=ObjectMeta(name="gone"),
                spec=ClusterSpec(server_address="local://gone"))
    )
    cc = ClusterController(fed_client, member_client)
    cc.sync_once()
    ready = {
        c.metadata.name: c.status.conditions[0].status
        for c in fed_client.resource("clusters").list()[0]
    }
    assert ready == {"c0": "True", "c1": "True", "c2": "True", "gone": "False"}

    # federated RC of 8 replicas spread 3/3/2 across ready clusters
    fed_client.resource("replicationcontrollers", "default").create(
        ReplicationController(
            metadata=ObjectMeta(name="web"),
            spec=ReplicationControllerSpec(
                replicas=8, selector={"app": "web"},
                template=PodTemplateSpec(
                    metadata=ObjectMeta(labels={"app": "web"}),
                    spec=PodSpec(containers=[Container(name="c")]),
                ),
            ),
        )
    )
    frm = FederatedReplicationManager(fed_client, member_client)
    frm.sync_once()
    shares = [
        clients[n].resource("replicationcontrollers", "default").get("web").spec.replicas
        for n in ("c0", "c1", "c2")
    ]
    assert shares == [3, 3, 2]
    # scaling the federated object rebalances members
    rc = fed_client.resource("replicationcontrollers", "default").get("web")
    rc.spec.replicas = 4
    fed_client.resource("replicationcontrollers", "default").update(rc)
    frm.sync_once()
    shares = [
        clients[n].resource("replicationcontrollers", "default").get("web").spec.replicas
        for n in ("c0", "c1", "c2")
    ]
    assert shares == [2, 1, 1]


def test_spread_replicas():
    assert spread_replicas(10, 3) == [4, 3, 3]
    assert spread_replicas(2, 3) == [1, 1, 0]
    assert spread_replicas(0, 2) == [0, 0]
    assert spread_replicas(5, 0) == []


def test_dns_records():
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    dns = DNSRecords(client).run()
    try:
        client.resource("services", "default").create(
            Service(
                metadata=ObjectMeta(name="web"),
                spec=ServiceSpec(
                    selector={"app": "web"},
                    cluster_ip="10.0.0.10",
                    ports=[ServicePort(name="http", port=80)],
                ),
            )
        )
        client.resource("services", "default").create(
            Service(
                metadata=ObjectMeta(name="db"),
                spec=ServiceSpec(selector={"app": "db"}, cluster_ip="None"),
            )
        )
        client.resource("endpoints", "default").create(
            Endpoints(
                metadata=ObjectMeta(name="db"),
                subsets=[EndpointSubset(
                    addresses=[
                        EndpointAddress(ip="10.1.0.5", target_ref="default/db-0"),
                        EndpointAddress(ip="10.1.0.6", target_ref="default/db-1"),
                    ],
                    ports=[EndpointPort(port=5432)],
                )],
            )
        )
        assert wait_until(
            lambda: dns.resolve("web.default.svc.cluster.local") == ["10.0.0.10"]
        )
        # headless -> endpoint IPs; pet hostname -> its own IP
        assert wait_until(
            lambda: dns.resolve("db.default.svc.cluster.local")
            == ["10.1.0.5", "10.1.0.6"]
        )
        assert dns.resolve("db-1.db.default.svc.cluster.local") == ["10.1.0.6"]
        assert dns.resolve("nope.default.svc.cluster.local") == []
        srv = dns.resolve_srv("_http._tcp.web.default.svc.cluster.local")
        assert len(srv) == 1 and srv[0].port == 80
        assert srv[0].target == "web.default.svc.cluster.local"
    finally:
        dns.stop()
