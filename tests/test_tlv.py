"""TLV binary wire codec (runtime/tlv.py + runtime/binary.py).

The wire must round-trip every payload shape the apiserver serves
(objects, List dicts, Status dicts, watch frames), reject malformed and
hostile input without executing anything, and hold its own against the
retired pickle envelope on throughput (the VERDICT r2 #7 bar).

Reference analogue: pkg/runtime/serializer/protobuf/protobuf.go — a
schema'd, data-only, magic-prefixed binary codec.
"""

import dataclasses
import io
import pickle
import time

import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.runtime import binary, tlv


def sample_pod(i: int = 0) -> t.Pod:
    return t.Pod(
        metadata=t.ObjectMeta(
            name=f"pod-{i}",
            namespace="default",
            labels={"app": "web", "tier": "frontend"},
            annotations={"scheduler.alpha.kubernetes.io/name": "tpu"},
        ),
        spec=t.PodSpec(
            node_name="",
            node_selector={"disktype": "ssd"},
            containers=[
                t.Container(
                    name="c1",
                    image="nginx:1.9",
                    requests={"cpu": "100m", "memory": "500Mi"},
                    limits={"cpu": "200m"},
                    ports=[t.ContainerPort(host_port=0, container_port=80)],
                )
            ],
            tolerations=[
                t.Toleration(key="dedicated", operator="Equal",
                             value="infra", effect="NoSchedule")
            ],
        ),
        status=t.PodStatus(phase="Pending"),
    )


class TestRoundTrip:
    def test_pod(self):
        p = sample_pod()
        q = tlv.loads(tlv.dumps(p))
        assert q == p
        assert type(q) is t.Pod
        assert q.spec.containers[0].requests["cpu"] == "100m"

    def test_node(self):
        n = t.Node(
            metadata=t.ObjectMeta(name="n1", namespace=""),
            status=t.NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[t.NodeCondition("Ready", "True")],
            ),
        )
        assert tlv.loads(tlv.dumps(n)) == n

    def test_wire_payload_shapes(self):
        # the apiserver's three payload shapes: object, List, Status
        pods = [sample_pod(i) for i in range(5)]
        lst = {"kind": "PodList", "items": pods,
               "metadata": {"resourceVersion": "17"}}
        out = tlv.loads(tlv.dumps(lst))
        assert out["items"] == pods
        status = {"kind": "Status", "status": "Failure", "code": 404,
                  "message": "not found"}
        assert tlv.loads(tlv.dumps(status)) == status

    def test_scalars_and_collections(self):
        vals = [None, True, False, 0, -1, 1, 2**62, -(2**62), 3.25, "",
                "héllo", b"\x00\xff", [], {}, [1, [2, [3]]],
                {"a": {"b": [None, False]}}]
        for v in vals:
            assert tlv.loads(tlv.dumps(v)) == v

    def test_class_table_reuse(self):
        # 100 pods: the class table defines each class once, so the
        # per-item cost is field values only
        pods = [sample_pod(i) for i in range(100)]
        one = len(tlv.dumps(pods[:1]))
        hundred = len(tlv.dumps(pods))
        assert hundred < one * 100  # sublinear envelope growth

    def test_envelope(self):
        p = sample_pod()
        data = binary.encode(p)
        assert data.startswith(binary.MAGIC)
        assert binary.decode(data) == p

    def test_watch_frames(self):
        frames = [
            {"type": "ADDED", "object": sample_pod(1)},
            {"type": "MODIFIED", "object": sample_pod(2)},
        ]
        buf = b"".join(binary.encode_frame(f) for f in frames)
        got = list(binary.read_frames(io.BytesIO(buf)))
        assert got == frames


class TestHostileInput:
    def test_rejects_pickle(self):
        # the retired pickle envelope (magic v0) must not decode
        evil = b"k8s-tpu\x00" + pickle.dumps({"boom": 1})
        with pytest.raises(binary.BinaryDecodeError):
            binary.decode(evil)

    def test_unknown_class(self):
        data = tlv.dumps(sample_pod()).replace(b"Pod", b"Pwn", 1)
        with pytest.raises(tlv.TLVError):
            tlv.loads(data)

    def test_unregistered_class_rejected(self):
        @dataclasses.dataclass
        class Sneaky:
            x: int = 0

        # encode-side late registration exists, but a fresh decode-side
        # registry must refuse names it never registered
        blob = tlv.dumps(Sneaky(x=1))
        saved_by_name = dict(tlv._BY_NAME)
        saved_fields = dict(tlv._FIELDS)
        try:
            del tlv._BY_NAME["Sneaky"]
            del tlv._FIELDS[Sneaky]
            with pytest.raises(tlv.TLVError):
                tlv.loads(blob)
        finally:
            tlv._BY_NAME.clear()
            tlv._BY_NAME.update(saved_by_name)
            tlv._FIELDS.clear()
            tlv._FIELDS.update(saved_fields)

    def test_truncation_everywhere(self):
        data = tlv.dumps([sample_pod(i) for i in range(3)])
        for cut in range(len(data) - 1):
            with pytest.raises(tlv.TLVError):
                tlv.loads(data[:cut])

    def test_invalid_utf8_is_tlv_error(self):
        # bad utf-8 in STR must surface as TLVError, not
        # UnicodeDecodeError, so the HTTP 400 mapping holds
        with pytest.raises(tlv.TLVError):
            tlv.loads(bytes([tlv.STR, 2]) + b"\xff\xfe")

    def test_unhashable_dict_key_is_tlv_error(self):
        evil = bytes([tlv.DICT, 1, tlv.LIST, 0, tlv.NONE])
        with pytest.raises(tlv.TLVError):
            tlv.loads(evil)

    def test_hostile_bytes_never_escape_binary_error(self):
        import os
        import random

        rng = random.Random(7)
        good = binary.encode(sample_pod())
        for _ in range(300):
            data = bytearray(good)
            for _ in range(rng.randrange(1, 4)):
                data[rng.randrange(len(binary.MAGIC), len(data))] = (
                    rng.randrange(256)
                )
            try:
                binary.decode(bytes(data))
            except binary.BinaryDecodeError:
                pass  # the ONLY acceptable failure mode
        for _ in range(200):
            blob = binary.MAGIC + os.urandom(rng.randrange(0, 60))
            try:
                binary.decode(blob)
            except binary.BinaryDecodeError:
                pass

    def test_trailing_garbage(self):
        with pytest.raises(tlv.TLVError):
            tlv.loads(tlv.dumps({"a": 1}) + b"\x00")

    def test_huge_length_does_not_allocate(self):
        # LIST claiming 2^40 elements with a 3-byte body
        evil = bytes([tlv.LIST]) + b"\x80\x80\x80\x80\x80\x20" + b"\x00"
        with pytest.raises(tlv.TLVError):
            tlv.loads(evil)

    def test_depth_bomb(self):
        evil = bytes([tlv.LIST, 1]) * 500 + bytes([tlv.NONE])
        with pytest.raises(tlv.TLVError):
            tlv.loads(evil)

    def test_no_init_side_effects(self):
        # decode builds objects without running __init__/__post_init__
        calls = []
        orig = t.Pod.__init__

        def spy(self, *a, **k):
            calls.append(1)
            return orig(self, *a, **k)

        t.Pod.__init__ = spy
        try:
            blob = tlv.dumps(sample_pod())  # one __init__ in sample_pod
            calls.clear()
            tlv.loads(blob)
            assert calls == []
        finally:
            t.Pod.__init__ = orig


class TestNativeParity:
    """The C fast path (native/_ktlv.c) must be indistinguishable from
    the Python codec: byte-identical wire, identical decode results,
    and a Fallback (not a wrong answer) for everything it punts on."""

    def setup_method(self):
        if tlv._ktlv is None:
            pytest.skip("native _ktlv not built")

    def test_wire_identity(self):
        payloads = [
            sample_pod(),
            [sample_pod(i) for i in range(20)],
            {"kind": "Status", "code": 404, "message": "héllo"},
            [None, True, False, 0, -1, 2**62, -(2**62), 3.25, -0.0,
             float("inf"), "", "héllo", b"\xff\x00", [1, [2]], {"a": 1}],
        ]
        for p in payloads:
            cb = tlv._ktlv.dumps(p)
            pb = tlv._py_dumps(p)
            assert cb == pb, p
            assert tlv._ktlv.loads(pb) == tlv._py_loads(cb)

    def test_tuple_encodes_as_list(self):
        assert tlv._ktlv.dumps((1, 2)) == tlv._py_dumps((1, 2))
        assert tlv._ktlv.loads(tlv._ktlv.dumps((1, 2))) == [1, 2]

    def test_int64_boundaries(self):
        for v in (2**63 - 1, -(2**63), 2**62, -(2**62)):
            assert tlv._ktlv.dumps(v) == tlv._py_dumps(v)
            assert tlv._ktlv.loads(tlv._py_dumps(v)) == v

    def test_big_int_falls_back(self):
        # >64-bit ints: C path punts, dispatcher serves the python wire
        for v in (2**64, -(2**100), 2**125):
            with pytest.raises(tlv._ktlv.Fallback):
                tlv._ktlv.dumps(v)
            assert tlv.loads(tlv.dumps(v)) == v

    def test_numeric_subclass_falls_back(self):
        import enum

        class E(enum.IntEnum):
            A = 3

        with pytest.raises(tlv._ktlv.Fallback):
            tlv._ktlv.dumps(E.A)
        assert tlv.loads(tlv.dumps(E.A)) == 3

    def test_malformed_is_tlverror_on_both_paths(self):
        bad = [
            b"",  # truncated value
            bytes([tlv.LIST, 0xFF]),  # truncated varint
            bytes([tlv.STR, 5, 65]),  # truncated payload
            bytes([tlv.STR, 2, 0xC3, 0x28]),  # bad utf-8
            bytes([tlv.LIST, 200]) + b"\x00",  # length exceeds input
            bytes([tlv.OBJ, 0]),  # undefined class id
            bytes([99]),  # unknown tag
            tlv.dumps(1) + b"\x00",  # trailing bytes
            bytes([tlv.LIST, 1] * 100),  # too deep
        ]
        for blob in bad:
            with pytest.raises(tlv.TLVError):
                tlv._ktlv.loads(blob)
            with pytest.raises(tlv.TLVError):
                tlv._py_loads(blob)

    def test_fuzz_wire_identity(self):
        # randomized nested payloads: both encoders agree byte-for-byte
        import random

        rng = random.Random(7)

        def gen(depth):
            kinds = ["int", "str", "none", "bool", "float", "bytes"]
            if depth < 4:
                kinds += ["list", "dict", "pod"]
            k = rng.choice(kinds)
            if k == "int":
                return rng.randint(-(2**63), 2**63 - 1)
            if k == "str":
                return "".join(chr(rng.randint(32, 1000))
                               for _ in range(rng.randint(0, 12)))
            if k == "none":
                return None
            if k == "bool":
                return rng.random() < 0.5
            if k == "float":
                return rng.uniform(-1e18, 1e18)
            if k == "bytes":
                return bytes(rng.getrandbits(8)
                             for _ in range(rng.randint(0, 8)))
            if k == "list":
                return [gen(depth + 1) for _ in range(rng.randint(0, 5))]
            if k == "dict":
                return {str(i): gen(depth + 1)
                        for i in range(rng.randint(0, 5))}
            return sample_pod(rng.randint(0, 99))

        for _ in range(200):
            p = gen(0)
            cb = tlv._ktlv.dumps(p)
            assert cb == tlv._py_dumps(p)
            assert tlv._ktlv.loads(cb) == tlv._py_loads(cb)


class TestPerf:
    def test_throughput_vs_pickle(self):
        """The schema'd codec must stay within a small factor of the
        C pickle it replaced on the dominant wire shape (a pod list);
        the hard 'safe for untrusted callers' property is what pickle
        could never offer at any speed.  With the native fast path the
        codec beats pickle outright; the assertion keeps the old 8x
        bar so a lost .so (pure-python fallback) still passes on a
        quiet box, measured best-of-3 to shrug off suite-load noise."""
        pods = [sample_pod(i) for i in range(200)]
        payload = {"kind": "PodList", "items": pods,
                   "metadata": {"resourceVersion": "1"}}

        def rate(enc, dec):
            blob = enc(payload)
            best = 0.0
            for _ in range(3):
                t0 = time.perf_counter()
                n = 0
                while time.perf_counter() - t0 < 0.2:
                    dec(enc(payload))
                    n += 1
                best = max(best, n / (time.perf_counter() - t0))
            return best, len(blob)

        tlv_rate, tlv_size = rate(tlv.dumps, tlv.loads)
        pk_rate, pk_size = rate(
            lambda p: pickle.dumps(p, pickle.HIGHEST_PROTOCOL), pickle.loads
        )
        # wire size must be competitive (TLV drops field names entirely)
        assert tlv_size < pk_size * 1.2, (tlv_size, pk_size)
        # throughput within 8x of C pickle keeps the codec off the
        # daemon's critical path (HTTP+dispatch dominate per request)
        assert tlv_rate * 8 > pk_rate, (tlv_rate, pk_rate)
        if tlv._ktlv is not None:
            # the native path must actually beat pickle (VERDICT r3 #7)
            assert tlv_rate > pk_rate * 0.8, (tlv_rate, pk_rate)
