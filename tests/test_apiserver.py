"""APIServer tests (pkg/apiserver resthandler + registry semantics)."""

import json
import threading
import urllib.request

import pytest

from kubernetes_tpu.api.types import (
    Container,
    Node,
    ObjectMeta,
    Pod,
    PodSpec,
)
from kubernetes_tpu.apiserver import APIServer, WatchResponse
from kubernetes_tpu.runtime import scheme


def pod_body(name, ns="default", node="", labels=None):
    return scheme.encode(
        Pod(
            metadata=ObjectMeta(name=name, namespace=ns, labels=labels or {}),
            spec=PodSpec(
                containers=[Container(requests={"cpu": "100m"})], node_name=node
            ),
        )
    )


def node_body(name):
    return scheme.encode(Node(metadata=ObjectMeta(name=name)))


@pytest.fixture()
def api():
    return APIServer()


class TestRESTVerbs:
    def test_create_get_pod(self, api):
        code, out = api.handle(
            "POST", "/api/v1/namespaces/default/pods", body=pod_body("p1")
        )
        assert code == 201
        assert out["metadata"]["uid"]
        assert out["metadata"]["resourceVersion"]
        assert out["status"]["phase"] == "Pending"
        code, out = api.handle("GET", "/api/v1/namespaces/default/pods/p1")
        assert code == 200
        assert out["metadata"]["name"] == "p1"

    def test_create_validates(self, api):
        bad = pod_body("p1")
        bad["spec"].pop("containers")
        code, out = api.handle(
            "POST", "/api/v1/namespaces/default/pods", body=bad
        )
        assert code == 422

    def test_create_duplicate_conflict(self, api):
        api.handle("POST", "/api/v1/namespaces/default/pods", body=pod_body("p1"))
        code, _ = api.handle(
            "POST", "/api/v1/namespaces/default/pods", body=pod_body("p1")
        )
        assert code == 409

    def test_namespace_mismatch(self, api):
        code, _ = api.handle(
            "POST", "/api/v1/namespaces/other/pods", body=pod_body("p1", ns="default")
        )
        assert code == 400

    def test_list_with_selectors(self, api):
        api.handle(
            "POST",
            "/api/v1/namespaces/default/pods",
            body=pod_body("a", labels={"app": "web"}),
        )
        api.handle(
            "POST",
            "/api/v1/namespaces/default/pods",
            body=pod_body("b", labels={"app": "db"}, node="n1"),
        )
        code, out = api.handle(
            "GET",
            "/api/v1/namespaces/default/pods",
            {"labelSelector": "app=web"},
        )
        assert [i["metadata"]["name"] for i in out["items"]] == ["a"]
        # unassigned pods: the scheduler's field selector (factory.go:431)
        code, out = api.handle(
            "GET", "/api/v1/pods", {"fieldSelector": "spec.nodeName="}
        )
        assert [i["metadata"]["name"] for i in out["items"]] == ["a"]
        code, out = api.handle(
            "GET", "/api/v1/pods", {"fieldSelector": "spec.nodeName!="}
        )
        assert [i["metadata"]["name"] for i in out["items"]] == ["b"]

    def test_nodes_not_namespaced(self, api):
        code, out = api.handle("POST", "/api/v1/nodes", body=node_body("n1"))
        assert code == 201
        code, out = api.handle("GET", "/api/v1/nodes/n1")
        assert code == 200
        code, out = api.handle("GET", "/api/v1/nodes")
        assert len(out["items"]) == 1

    def test_update_conflict_on_stale_rv(self, api):
        _, created = api.handle(
            "POST", "/api/v1/namespaces/default/pods", body=pod_body("p1")
        )
        stale = dict(created)
        # successful no-op update bumps rv
        code, _ = api.handle(
            "PUT", "/api/v1/namespaces/default/pods/p1", body=created
        )
        assert code == 200
        code, _ = api.handle(
            "PUT", "/api/v1/namespaces/default/pods/p1", body=stale
        )
        assert code == 409

    def test_patch_merges(self, api):
        api.handle("POST", "/api/v1/namespaces/default/pods", body=pod_body("p1"))
        code, out = api.handle(
            "PATCH",
            "/api/v1/namespaces/default/pods/p1",
            body={"metadata": {"labels": {"extra": "yes"}}},
        )
        assert code == 200
        assert out["metadata"]["labels"]["extra"] == "yes"

    def test_status_subresource_only_moves_status(self, api):
        _, created = api.handle(
            "POST", "/api/v1/namespaces/default/pods", body=pod_body("p1")
        )
        update = dict(created)
        update["status"] = {"phase": "Running"}
        update["metadata"] = dict(created["metadata"], labels={"hacked": "yes"})
        code, out = api.handle(
            "PUT", "/api/v1/namespaces/default/pods/p1/status", body=update
        )
        assert code == 200
        assert out["status"]["phase"] == "Running"
        assert "hacked" not in out["metadata"].get("labels", {})

    def test_delete(self, api):
        api.handle("POST", "/api/v1/namespaces/default/pods", body=pod_body("p1"))
        code, _ = api.handle("DELETE", "/api/v1/namespaces/default/pods/p1")
        assert code == 200
        code, _ = api.handle("GET", "/api/v1/namespaces/default/pods/p1")
        assert code == 404

    def test_extensions_group_path(self, api):
        from kubernetes_tpu.api.types import ReplicaSet

        rs = scheme.encode(ReplicaSet(metadata=ObjectMeta(name="rs1")))
        code, _ = api.handle(
            "POST",
            "/apis/extensions/v1beta1/namespaces/default/replicasets",
            body=rs,
        )
        assert code == 201
        code, out = api.handle(
            "GET", "/apis/extensions/v1beta1/namespaces/default/replicasets/rs1"
        )
        assert code == 200


class TestBinding:
    def test_bind_sets_node_name(self, api):
        api.handle("POST", "/api/v1/namespaces/default/pods", body=pod_body("p1"))
        code, _ = api.handle(
            "POST",
            "/api/v1/namespaces/default/pods/p1/binding",
            body={"metadata": {"name": "p1"}, "target": {"name": "n1"}},
        )
        assert code == 201
        _, out = api.handle("GET", "/api/v1/namespaces/default/pods/p1")
        assert out["spec"]["nodeName"] == "n1"
        conds = {c["type"]: c["status"] for c in out["status"]["conditions"]}
        assert conds["PodScheduled"] == "True"

    def test_double_bind_conflict(self, api):
        api.handle("POST", "/api/v1/namespaces/default/pods", body=pod_body("p1"))
        body = {"metadata": {"name": "p1"}, "target": {"name": "n1"}}
        api.handle("POST", "/api/v1/namespaces/default/pods/p1/binding", body=body)
        code, _ = api.handle(
            "POST", "/api/v1/namespaces/default/pods/p1/binding", body=body
        )
        assert code == 409

    def test_bindings_collection_form(self, api):
        api.handle("POST", "/api/v1/namespaces/default/pods", body=pod_body("p1"))
        code, _ = api.handle(
            "POST",
            "/api/v1/namespaces/default/bindings",
            body={"metadata": {"name": "p1"}, "target": {"name": "n2"}},
        )
        assert code == 201
        _, out = api.handle("GET", "/api/v1/namespaces/default/pods/p1")
        assert out["spec"]["nodeName"] == "n2"


class TestNamespaces:
    def test_auto_provision(self, api):
        api.handle("POST", "/api/v1/namespaces/myns/pods", body=pod_body("p", ns="myns"))
        code, out = api.handle("GET", "/api/v1/namespaces/myns")
        assert code == 200
        assert out["status"]["phase"] == "Active"

    def test_terminating_namespace_rejects_creates(self, api):
        api.handle("POST", "/api/v1/namespaces/doomed/pods", body=pod_body("p", ns="doomed"))
        _, ns = api.handle("GET", "/api/v1/namespaces/doomed")
        ns["status"]["phase"] = "Terminating"
        api.handle("PUT", "/api/v1/namespaces/doomed/status", body=ns)
        code, _ = api.handle(
            "POST", "/api/v1/namespaces/doomed/pods", body=pod_body("q", ns="doomed")
        )
        assert code == 403


class TestWatch:
    def test_watch_stream_basic(self, api):
        code, watch = api.handle(
            "GET", "/api/v1/pods", {"watch": "true"}
        )
        assert code == 200
        assert isinstance(watch, WatchResponse)
        api.handle("POST", "/api/v1/namespaces/default/pods", body=pod_body("p1"))
        gen = watch.events()
        ev = next(gen)
        assert ev["type"] == "ADDED"
        assert ev["object"]["metadata"]["name"] == "p1"
        watch.stop()

    def test_watch_field_transition_translates(self, api):
        """A pod leaving the unassigned-pod filter must surface as
        DELETED (etcd_watcher.go sendModify) — the scheduler's FIFO
        depends on this to drop bound pods."""
        api.handle("POST", "/api/v1/namespaces/default/pods", body=pod_body("p1"))
        code, watch = api.handle(
            "GET",
            "/api/v1/pods",
            {"watch": "true", "fieldSelector": "spec.nodeName="},
        )
        api.handle(
            "POST",
            "/api/v1/namespaces/default/pods/p1/binding",
            body={"metadata": {"name": "p1"}, "target": {"name": "n1"}},
        )
        gen = watch.events()
        ev = next(gen)
        assert ev["type"] == "DELETED"
        assert ev["object"]["metadata"]["name"] == "p1"
        watch.stop()

    def test_watch_from_resource_version(self, api):
        _, out = api.handle(
            "POST", "/api/v1/namespaces/default/pods", body=pod_body("p1")
        )
        rv = out["metadata"]["resourceVersion"]
        api.handle("POST", "/api/v1/namespaces/default/pods", body=pod_body("p2"))
        code, watch = api.handle(
            "GET", "/api/v1/pods", {"watch": "true", "resourceVersion": rv}
        )
        ev = next(watch.events())
        assert ev["object"]["metadata"]["name"] == "p2"
        watch.stop()

    def test_watch_gone_after_compaction(self, api):
        for i in range(5):
            api.handle(
                "POST", "/api/v1/namespaces/default/pods", body=pod_body(f"p{i}")
            )
        api.store.compact()
        code, out = api.handle(
            "GET", "/api/v1/pods", {"watch": "true", "resourceVersion": "1"}
        )
        assert code == 410


class TestHTTPFrontend:
    def test_end_to_end(self, api):
        host, port = api.serve_http()
        base = f"http://{host}:{port}"
        try:
            req = urllib.request.Request(
                f"{base}/api/v1/namespaces/default/pods",
                data=json.dumps(pod_body("web")).encode(),
                method="POST",
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 201
            with urllib.request.urlopen(f"{base}/api/v1/pods") as resp:
                out = json.loads(resp.read())
            assert out["kind"] == "PodList"
            assert len(out["items"]) == 1
            with urllib.request.urlopen(f"{base}/healthz") as resp:
                assert resp.status == 200
            with urllib.request.urlopen(f"{base}/metrics") as resp:
                assert b"scheduler_e2e_scheduling_latency" in resp.read()
        finally:
            api.shutdown_http()

    def test_http_watch_streams(self, api):
        host, port = api.serve_http()
        base = f"http://{host}:{port}"
        events = []
        ready = threading.Event()

        def watch():
            resp = urllib.request.urlopen(f"{base}/api/v1/pods?watch=true")
            ready.set()
            while len(events) < 2:
                line = resp.readline()
                if not line.strip():
                    continue
                events.append(json.loads(line))

        thr = threading.Thread(target=watch, daemon=True)
        thr.start()
        ready.wait(2)
        try:
            for name in ("a", "b"):
                req = urllib.request.Request(
                    f"{base}/api/v1/namespaces/default/pods",
                    data=json.dumps(pod_body(name)).encode(),
                    method="POST",
                )
                urllib.request.urlopen(req)
            thr.join(timeout=5)
            assert [e["type"] for e in events] == ["ADDED", "ADDED"]
            assert [e["object"]["metadata"]["name"] for e in events] == ["a", "b"]
        finally:
            api.shutdown_http()


def test_configz_endpoint():
    """pkg/util/configz: components install live config; /configz serves
    the merged JSON view."""
    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.scheduler.server import SchedulerServerOptions
    from kubernetes_tpu.utils import configz

    configz.install("componentconfig", SchedulerServerOptions())
    try:
        server = APIServer()
        code, payload = server.handle("GET", "/configz", {}, None)
        assert code == 200
        assert payload["componentconfig"]["scheduler_name"] == "default-scheduler"
    finally:
        configz.delete("componentconfig")


# --- genericapiserver hardening (handlers.go + TLS) -------------------------


class TestHardening:
    def test_max_in_flight_sheds_load(self, api):
        """handlers.go MaxInFlightLimit: when the in-flight budget is
        saturated by slow requests, the next one gets 429 instead of
        queueing unboundedly."""
        import threading

        gate = threading.Event()
        entered = threading.Barrier(3)
        orig = api.handle

        def slow_handle(method, path, query=None, body=None, obj_mode=False,
                        body_owned=False):
            if path == "/api/v1/nodes" and method == "GET":
                entered.wait(timeout=5)
                gate.wait(timeout=10)
            return orig(method, path, query, body, obj_mode)

        api.handle = slow_handle
        host, port = api.serve_http(max_in_flight=2)
        base = f"http://{host}:{port}"
        try:
            def fire(results):
                try:
                    with urllib.request.urlopen(f"{base}/api/v1/nodes") as r:
                        results.append(r.status)
                except urllib.error.HTTPError as e:
                    results.append(e.code)

            results = []
            threads = [
                threading.Thread(target=fire, args=(results,), daemon=True)
                for _ in range(2)
            ]
            for t in threads:
                t.start()
            entered.wait(timeout=5)  # both slow requests hold the budget
            overflow = []
            fire(overflow)
            assert overflow == [429]
            gate.set()
            for t in threads:
                t.join(timeout=5)
            assert results == [200, 200]
        finally:
            gate.set()
            api.shutdown_http()
            api.handle = orig

    def test_watches_exempt_from_max_in_flight(self, api):
        """Long-running requests (watches) must not consume the budget
        (handlers.go longRunningRE)."""
        host, port = api.serve_http(max_in_flight=1)
        base = f"http://{host}:{port}"
        try:
            streams = [
                urllib.request.urlopen(
                    f"{base}/api/v1/pods?watch=true", timeout=5
                )
                for _ in range(3)
            ]
            # the full budget is still available for a normal request
            with urllib.request.urlopen(f"{base}/api/v1/nodes") as r:
                assert r.status == 200
            for s in streams:
                s.close()
        finally:
            api.shutdown_http()

    def test_tls_end_to_end(self, api, tmp_path):
        """genericapiserver serves TLS; the client pins the self-signed
        cert like a kubeconfig certificate-authority."""
        import subprocess

        from kubernetes_tpu.api.types import ObjectMeta, Node
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import HTTPTransport

        cert = tmp_path / "tls.crt"
        key = tmp_path / "tls.key"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(cert), "-days", "1",
             "-subj", "/CN=127.0.0.1",
             "-addext", "subjectAltName=IP:127.0.0.1"],
            check=True, capture_output=True,
        )
        host, port = api.serve_http(tls_cert=str(cert), tls_key=str(key))
        try:
            client = RESTClient(HTTPTransport(
                f"https://{host}:{port}", tls_ca=str(cert)
            ))
            client.nodes().create(Node(metadata=ObjectMeta(name="tls-node")))
            nodes, _ = client.nodes().list()
            assert [n.metadata.name for n in nodes] == ["tls-node"]
            # plaintext client against the TLS port must fail
            import urllib.error

            try:
                urllib.request.urlopen(f"http://{host}:{port}/api/v1/nodes",
                                       timeout=3)
                raised = False
            except Exception:
                raised = True
            assert raised
        finally:
            api.shutdown_http()


class TestBinaryWireFormat:
    def test_binary_disabled_by_default(self, api):
        """The code-bearing content type is strictly opt-in: a listener
        without enable_binary refuses binary bodies with 415."""
        from kubernetes_tpu.runtime import binary

        host, port = api.serve_http()
        try:
            req = urllib.request.Request(
                f"http://{host}:{port}/api/v1/namespaces/default/pods",
                data=binary.encode({"kind": "Pod"}),
                method="POST",
                headers={"Content-Type": binary.CONTENT_TYPE},
            )
            try:
                urllib.request.urlopen(req)
                code = 200
            except urllib.error.HTTPError as e:
                code = e.code
            assert code == 415
        finally:
            api.shutdown_http()


    """runtime/binary.py: the protobuf-content-type analogue over HTTP —
    object payloads in a magic-prefixed envelope, length-prefixed watch
    frames, negotiated per request while JSON stays the default."""

    def test_binary_round_trip_and_watch(self, api):
        import threading

        from kubernetes_tpu.api.types import (
            Container,
            Node,
            NodeStatus,
            ObjectMeta,
            Pod,
            PodSpec,
        )
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import HTTPTransport

        host, port = api.serve_http(enable_binary=True)
        base = f"http://{host}:{port}"
        try:
            bclient = RESTClient(HTTPTransport(base, binary=True))
            jclient = RESTClient(HTTPTransport(base))
            bclient.nodes().create(Node(
                metadata=ObjectMeta(name="bin-node"),
                status=NodeStatus(allocatable={"cpu": "4", "pods": "110"}),
            ))
            # JSON client sees what the binary client wrote (and back)
            node = jclient.nodes().get("bin-node")
            assert node.status.allocatable["cpu"] == "4"
            got = bclient.nodes().get("bin-node")
            assert got.metadata.name == "bin-node"
            assert type(got).__name__ == "Node"

            # binary watch with field selector translation
            events = []
            ready = threading.Event()

            def watch():
                stream = bclient.pods().watch(resource_version="0")
                ready.set()
                for et, obj in stream:
                    events.append((et, obj.metadata.name,
                                   obj.spec.node_name))
                    if len(events) >= 2:
                        stream.stop()
                        return

            t = threading.Thread(target=watch, daemon=True)
            t.start()
            ready.wait(timeout=5)
            bclient.pods().create(Pod(
                metadata=ObjectMeta(name="bp"),
                spec=PodSpec(containers=[Container(name="c")]),
            ))
            bclient.pods().bind("bp", "bin-node")
            t.join(timeout=10)
            assert events[0][:2] == ("ADDED", "bp")
            assert events[1] == ("MODIFIED", "bp", "bin-node")
        finally:
            api.shutdown_http()

    def test_binary_rejects_bad_envelope(self, api):
        from kubernetes_tpu.runtime import binary

        host, port = api.serve_http(enable_binary=True)
        try:
            req = urllib.request.Request(
                f"http://{host}:{port}/api/v1/namespaces/default/pods",
                data=b"not-an-envelope",
                method="POST",
                headers={"Content-Type": binary.CONTENT_TYPE},
            )
            try:
                urllib.request.urlopen(req)
                code = 200
            except urllib.error.HTTPError as e:
                code = e.code
            assert code == 400
        finally:
            api.shutdown_http()

    def test_scheduler_daemon_over_binary_http(self, api):
        """A daemon on the binary transport schedules end-to-end — the
        kubemark-defaults-to-protobuf configuration (hollow-node.go:65)."""
        import time

        from kubernetes_tpu.api.types import (
            Container,
            Node,
            NodeCondition,
            NodeStatus,
            ObjectMeta,
            Pod,
            PodSpec,
        )
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import HTTPTransport
        from kubernetes_tpu.scheduler.server import (
            SchedulerServer,
            SchedulerServerOptions,
        )

        host, port = api.serve_http(enable_binary=True)
        client = RESTClient(HTTPTransport(f"http://{host}:{port}",
                                          binary=True))
        try:
            for i in range(3):
                client.nodes().create(Node(
                    metadata=ObjectMeta(name=f"bn{i}"),
                    status=NodeStatus(
                        allocatable={"cpu": "4", "memory": "32Gi",
                                     "pods": "110"},
                        conditions=[NodeCondition("Ready", "True")],
                    ),
                ))
            srv = SchedulerServer(client, SchedulerServerOptions(
                algorithm_provider="TPUProvider")).start()
            try:
                for i in range(6):
                    client.pods().create(Pod(
                        metadata=ObjectMeta(name=f"bp{i}"),
                        spec=PodSpec(containers=[
                            Container(requests={"cpu": "100m"})]),
                    ))
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    objs, _ = client.pods().list()
                    if all(o.spec.node_name for o in objs):
                        break
                    time.sleep(0.1)
                objs, _ = client.pods().list()
                assert all(o.spec.node_name for o in objs)
                assert len({o.spec.node_name for o in objs}) == 3
            finally:
                srv.stop()
        finally:
            api.shutdown_http()


def test_ui_dashboard_served(api):
    """The www/ dashboard analogue: /ui serves the static cluster view,
    whose data calls ride the ordinary JSON list endpoints."""
    host, port = api.serve_http()
    try:
        with urllib.request.urlopen(f"http://{host}:{port}/ui") as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/html")
            body = r.read().decode()
        assert "kubernetes-tpu" in body
        for resource in ("nodes", "pods", "services", "events"):
            assert resource in body
    finally:
        api.shutdown_http()


class TestScaleSubresource:
    """GET/PUT {resource}/{name}/scale (registry ScaleREST): the
    uniform Scale shape any scaler drives."""

    def test_get_and_put_scale(self):
        from kubernetes_tpu.api.types import (
            LabelSelector,
            ObjectMeta,
            ReplicaSet,
            ReplicaSetSpec,
        )
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import HTTPTransport

        api = APIServer()
        host, port = api.serve_http()
        client = RESTClient(HTTPTransport(f"http://{host}:{port}"))
        client.resource("replicasets", "default").create(ReplicaSet(
            metadata=ObjectMeta(name="web"),
            spec=ReplicaSetSpec(
                replicas=3,
                selector=LabelSelector(match_labels={"app": "web"}),
            ),
        ))
        scale = client.do_raw(
            "GET",
            "/apis/extensions/v1beta1/namespaces/default/"
            "replicasets/web/scale",
        )
        assert scale["kind"] == "Scale"
        assert scale["spec"]["replicas"] == 3
        assert scale["status"]["selector"] == {"app": "web"}
        out = client.do_raw(
            "PUT",
            "/apis/extensions/v1beta1/namespaces/default/"
            "replicasets/web/scale",
            body={"kind": "Scale", "spec": {"replicas": 7}},
        )
        assert out["spec"]["replicas"] == 7
        assert client.resource(
            "replicasets", "default").get("web").spec.replicas == 7
        # stale resourceVersion conflicts (optimistic concurrency)
        import pytest as _pytest

        from kubernetes_tpu.client.rest import APIStatusError

        with _pytest.raises(APIStatusError) as ei:
            client.do_raw(
                "PUT",
                "/apis/extensions/v1beta1/namespaces/default/"
                "replicasets/web/scale",
                body={"kind": "Scale",
                      "metadata": {"resourceVersion": "1"},
                      "spec": {"replicas": 1}},
            )
        assert ei.value.code == 409

    def test_scale_on_unscalable_404s_as_subresource(self):
        from kubernetes_tpu.api.types import ObjectMeta, ConfigMap

        api = APIServer()
        code, _ = api.handle(
            "POST", "/api/v1/namespaces/default/configmaps",
            body={"kind": "ConfigMap", "metadata": {"name": "c"}},
        )
        assert code == 201
        # a PUT to an unknown subresource must not write the object
        code, out = api.handle(
            "PUT", "/api/v1/namespaces/default/configmaps/c/scale",
            body={"kind": "Scale", "spec": {"replicas": 3}},
        )
        assert code == 404
        code, got = api.handle(
            "GET", "/api/v1/namespaces/default/configmaps/c"
        )
        assert code == 200 and "spec" not in got

    def test_job_scale_maps_to_parallelism(self):
        from kubernetes_tpu.api.types import Job, JobSpec, ObjectMeta

        api = APIServer()
        code, _ = api.handle(
            "POST", "/apis/batch/v1/namespaces/default/jobs",
            body={"kind": "Job", "metadata": {"name": "j"},
                  "spec": {"parallelism": 2}},
        )
        assert code == 201
        code, out = api.handle(
            "GET", "/apis/batch/v1/namespaces/default/jobs/j/scale")
        assert code == 200 and out["spec"]["replicas"] == 2
        code, out = api.handle(
            "PUT", "/apis/batch/v1/namespaces/default/jobs/j/scale",
            body={"kind": "Scale", "spec": {"replicas": 5}})
        assert code == 200
        code, got = api.handle(
            "GET", "/apis/batch/v1/namespaces/default/jobs/j")
        assert got["spec"]["parallelism"] == 5

    def test_scale_bumps_generation_and_patch_subresource_guard(self):
        from kubernetes_tpu.api.types import (
            ObjectMeta,
            ReplicaSet,
            ReplicaSetSpec,
        )
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import LocalTransport

        api = APIServer()
        client = RESTClient(LocalTransport(api))
        client.resource("replicasets", "default").create(ReplicaSet(
            metadata=ObjectMeta(name="g"),
            spec=ReplicaSetSpec(replicas=1),
        ))
        before = client.resource(
            "replicasets", "default").get("g").metadata.generation
        code, _ = api.handle(
            "PUT",
            "/apis/extensions/v1beta1/namespaces/default/"
            "replicasets/g/scale",
            body={"kind": "Scale", "spec": {"replicas": 4}})
        assert code == 200
        after = client.resource(
            "replicasets", "default").get("g").metadata.generation
        assert after == before + 1  # spec change moves the sequence
        # PATCH to an unknown subresource must not write either
        code, _ = api.handle(
            "PATCH",
            "/apis/extensions/v1beta1/namespaces/default/"
            "replicasets/g/bogus",
            body={"spec": {"replicas": 9}})
        assert code == 404
        assert client.resource(
            "replicasets", "default").get("g").spec.replicas == 4


class TestInterestSelectors:
    """The `in` field-selector extension + the fan-out interest index
    plumbing (round 10: one hollow-fleet shard watches its whole node
    group on ONE stream)."""

    def test_in_clause_parse_and_match(self):
        from kubernetes_tpu.apiserver.fields import (
            format_in_clause,
            interest_values,
            matches_fields,
            parse_field_selector,
        )

        text = format_in_clause("spec.nodeName", ["n1", "n2"])
        clauses = parse_field_selector(text + ",metadata.namespace=default")
        assert ("spec.nodeName", "in", "(n1,n2)") in clauses
        p = Pod(
            metadata=ObjectMeta(name="p"),
            spec=PodSpec(containers=[Container()], node_name="n2"),
        )
        assert matches_fields(p, clauses)
        p.spec.node_name = "n9"
        assert not matches_fields(p, clauses)
        # interest extraction: equality and `in` pin; '!=' does not
        assert interest_values(clauses, "spec.nodeName") == {"n1", "n2"}
        assert interest_values(
            parse_field_selector("spec.nodeName!=n1"), "spec.nodeName"
        ) is None
        # intersecting pins narrow the set
        both = parse_field_selector(
            "spec.nodeName in (n1,n2),spec.nodeName=n2")
        assert interest_values(both, "spec.nodeName") == {"n2"}

    def test_in_selector_list_and_watch(self, api):
        for name, node in (("a", "n1"), ("b", "n2"), ("c", "n3")):
            api.handle(
                "POST", "/api/v1/namespaces/default/pods",
                body=pod_body(name, node=node),
            )
        code, out = api.handle(
            "GET", "/api/v1/pods",
            {"fieldSelector": "spec.nodeName in (n1,n3)"},
        )
        assert sorted(i["metadata"]["name"] for i in out["items"]) == [
            "a", "c"]
        # watch: only events for the pinned node set flow
        code, watch = api.handle(
            "GET", "/api/v1/pods",
            {"watch": "true", "fieldSelector": "spec.nodeName in (n1,n3)"},
        )
        assert code == 200
        api.handle(
            "POST", "/api/v1/namespaces/default/pods",
            body=pod_body("d", node="n3"),
        )
        api.handle(
            "POST", "/api/v1/namespaces/default/pods",
            body=pod_body("e", node="n2"),
        )
        api.handle(
            "POST", "/api/v1/namespaces/default/pods",
            body=pod_body("f", node="n1"),
        )
        seen = []
        for ev in watch.events():
            seen.append(ev["object"]["metadata"]["name"])
            if len(seen) == 2:
                break
        assert seen == ["d", "f"]
        watch.stop()

    def test_interest_indexed_watch_registration(self, api):
        """A spec.nodeName-pinned watch registers in the cacher's
        interest index, not the broadcast list."""
        api.handle(
            "POST", "/api/v1/namespaces/default/pods",
            body=pod_body("seed", node="n1"),
        )
        cacher = api._cacher_for(api.resources["pods"])
        assert cacher is not None
        code, watch = api.handle(
            "GET", "/api/v1/pods",
            {"watch": "true", "fieldSelector": "spec.nodeName=n1"},
        )
        assert code == 200
        with cacher._cond:
            assert len(cacher._watchers) == 0
            assert set(cacher._interest) == {"n1"}
        watch.stop()
        # removal cleans the index bucket
        import time as _t
        deadline = _t.time() + 5
        while _t.time() < deadline:
            with cacher._cond:
                if not cacher._interest:
                    break
            _t.sleep(0.05)
        with cacher._cond:
            assert not cacher._interest


class TestBatchDelete:
    def test_batch_delete_op(self, api):
        from kubernetes_tpu.client.rest import (
            RESTClient,
            batch_delete_item,
        )
        from kubernetes_tpu.client.transport import LocalTransport

        client = RESTClient(LocalTransport(api))
        for name in ("a", "b", "c"):
            api.handle(
                "POST", "/api/v1/namespaces/default/pods",
                body=pod_body(name),
            )
        res = client.commit_batch([
            batch_delete_item("pods", "a"),
            batch_delete_item("pods", "b"),
            batch_delete_item("pods", "nope"),
        ])
        assert [r["status"] for r in res] == [
            "Success", "Success", "Failure"]
        code, out = api.handle("GET", "/api/v1/pods")
        assert [i["metadata"]["name"] for i in out["items"]] == ["c"]

    def test_batch_delete_emits_deleted_events(self, api):
        api.handle(
            "POST", "/api/v1/namespaces/default/pods", body=pod_body("a")
        )
        code, watch = api.handle(
            "GET", "/api/v1/pods", {"watch": "true"}
        )
        from kubernetes_tpu.client.rest import (
            RESTClient,
            batch_delete_item,
        )
        from kubernetes_tpu.client.transport import LocalTransport

        client = RESTClient(LocalTransport(api))
        client.commit_batch([batch_delete_item("pods", "a")])
        for ev in watch.events():
            assert ev["type"] == "DELETED"
            assert ev["object"]["metadata"]["name"] == "a"
            break
        watch.stop()


class TestEventTTL:
    """kube-apiserver --event-ttl analogue: per-bind Events expire, so
    a sustained-traffic store can't grow without bound on Events."""

    def _event_body(self, name):
        return {
            "kind": "Event",
            "metadata": {"name": name},
            "involvedObject": {"kind": "Pod", "name": "p"},
            "reason": "Scheduled",
            "message": "test",
        }

    def test_expired_events_swept_on_write(self, api):
        assert api._event_ttl == 3600.0  # default 1h, like the flag
        for nm in ("old-ev", "fresh-ev"):
            code, _ = api.handle(
                "POST", "/api/v1/namespaces/default/events",
                body=self._event_body(nm),
            )
            assert code == 201
        # age one event past the TTL (admission stamps now, so expiry
        # is injected at the store) and force the sweep deadline due
        with api.store._lock:
            obj = api.store._data["/events/default/old-ev"][0]
            obj.metadata.creation_timestamp = "2000-01-01T00:00:00Z"
            # reads serve the commit-time TLV bytes; drop them so the
            # sweep sees the aged timestamp
            api.store._tlv_blobs.pop("/events/default/old-ev", None)
        api._event_gc_next = 0.0
        code, _ = api.handle(
            "POST", "/api/v1/namespaces/default/events",
            body=self._event_body("trigger-ev"),
        )
        assert code == 201
        names = {e["metadata"]["name"] for e in api.handle(
            "GET", "/api/v1/namespaces/default/events")[1]["items"]}
        assert names == {"fresh-ev", "trigger-ev"}

    def test_sweep_rides_bulk_create(self, api):
        """The broadcaster's storm path is record_many -> create_many
        (one bulk POST, not N singles): the sweep must fire there too,
        or sustained traffic never expires anything."""
        api.handle(
            "POST", "/api/v1/namespaces/default/events",
            body=self._event_body("old-ev"),
        )
        with api.store._lock:
            obj = api.store._data["/events/default/old-ev"][0]
            obj.metadata.creation_timestamp = "2000-01-01T00:00:00Z"
            api.store._tlv_blobs.pop("/events/default/old-ev", None)
        api._event_gc_next = 0.0
        code, out = api.handle(
            "POST", "/api/v1/namespaces/default/events",
            body={"kind": "List", "items": [
                self._event_body("bulk-0"), self._event_body("bulk-1"),
            ]},
        )
        assert code == 201
        assert all(r["status"] == "Success" for r in out["items"])
        names = {e["metadata"]["name"] for e in api.handle(
            "GET", "/api/v1/namespaces/default/events")[1]["items"]}
        assert names == {"bulk-0", "bulk-1"}

    def test_ttl_zero_disables(self, monkeypatch):
        monkeypatch.setenv("KUBERNETES_TPU_EVENT_TTL", "0")
        api = APIServer()
        try:
            api.handle(
                "POST", "/api/v1/namespaces/default/events",
                body=self._event_body("ancient-ev"),
            )
            with api.store._lock:
                obj = api.store._data["/events/default/ancient-ev"][0]
                obj.metadata.creation_timestamp = "2000-01-01T00:00:00Z"
                api.store._tlv_blobs.pop(
                    "/events/default/ancient-ev", None)
            api._event_gc_next = 0.0
            api.handle(
                "POST", "/api/v1/namespaces/default/events",
                body=self._event_body("trigger-ev"),
            )
            items = api.handle(
                "GET", "/api/v1/namespaces/default/events")[1]["items"]
            assert {e["metadata"]["name"] for e in items} == {
                "ancient-ev", "trigger-ev"}
        finally:
            api.close_cachers()

    def test_rfc3339_epoch_rejects_garbage(self):
        assert APIServer._rfc3339_epoch("") is None
        assert APIServer._rfc3339_epoch("not-a-time") is None
        assert APIServer._rfc3339_epoch(
            "2026-08-03T10:00:00Z") == 1785751200


def test_rand_hex_fork_reseeds():
    """The buffered-urandom pool is fork-unsafe without the pid check:
    a forked child inherits the parent's unconsumed buffer and would
    mint the parent's EXACT uid/generateName stream."""
    import os

    from kubernetes_tpu.apiserver import registry as reg

    # prime this thread's buffer so the child inherits unconsumed bytes
    reg.rand_hex(8)
    r, w = os.pipe()
    pid = os.fork()
    if pid == 0:  # child: emit what it mints, then hard-exit
        try:
            os.close(r)
            os.write(w, reg.rand_hex(16).encode())
            os.close(w)
        finally:
            os._exit(0)
    os.close(w)
    child = b""
    while True:
        chunk = os.read(r, 64)
        if not chunk:
            break
        child += chunk
    os.close(r)
    os.waitpid(pid, 0)
    parent = reg.rand_hex(16)
    assert len(child) == 32
    assert child.decode() != parent
