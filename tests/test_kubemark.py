"""Kubemark-tier scale: hollow nodes + the real scheduler + controllers in
one process (test/kubemark; SURVEY.md section 4 'multi-node without a
cluster')."""

import time

import pytest

from kubernetes_tpu.api.types import (
    Container,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
    ReplicationController,
    ReplicationControllerSpec,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.controller.framework import SharedInformerFactory
from kubernetes_tpu.controller.replication import ReplicationManager
from kubernetes_tpu.kubemark import HollowCluster
from kubernetes_tpu.scheduler.server import SchedulerServer, SchedulerServerOptions


from conftest import wait_until  # noqa: E402


def test_hollow_cluster_runs_workload():
    """20 hollow nodes, an RC of 60 pods: everything must reach Running
    via real scheduler bindings and real kubelet status updates."""
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    cluster = HollowCluster(client, 20).run()
    informers = SharedInformerFactory(client)
    rcm = ReplicationManager(client, informers)
    informers.start()
    informers.wait_for_sync()
    rcm.run()
    sched = SchedulerServer(client, SchedulerServerOptions()).start()
    try:
        assert wait_until(lambda: len(client.nodes().list()[0]) == 20, 30)
        client.resource("replicationcontrollers", "default").create(
            ReplicationController(
                metadata=ObjectMeta(name="load"),
                spec=ReplicationControllerSpec(
                    replicas=60,
                    selector={"app": "load"},
                    template=PodTemplateSpec(
                        metadata=ObjectMeta(labels={"app": "load"}),
                        spec=PodSpec(
                            containers=[
                                Container(name="pause", requests={"cpu": "100m"})
                            ]
                        ),
                    ),
                ),
            )
        )
        assert wait_until(
            lambda: sum(
                1
                for p in client.pods().list()[0]
                if p.status.phase == "Running"
            )
            == 60,
            60,
        ), [
            (p.metadata.name, p.status.phase, p.spec.node_name)
            for p in client.pods().list()[0]
        ][:10]
        nodes_used = {p.spec.node_name for p in client.pods().list()[0]}
        assert len(nodes_used) == 20  # spreading across every hollow node
    finally:
        sched.stop()
        rcm.stop()
        informers.stop()
        cluster.stop()


def test_perf_harness_small():
    """The density harness runs end-to-end (tiny config in CI; the real
    configs are 100n/3kp and 1000n/30kp per the reference README)."""
    import io

    from kubernetes_tpu.harness.perf import schedule_pods

    out = io.StringIO()
    throughput = schedule_pods(10, 50, provider="DefaultProvider", out=out)
    assert throughput > 0
    assert "scheduled 50 pods on 10 nodes" in out.getvalue()
