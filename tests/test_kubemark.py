"""Kubemark-tier scale: hollow nodes + the real scheduler + controllers in
one process (test/kubemark; SURVEY.md section 4 'multi-node without a
cluster')."""

import time

import pytest

from kubernetes_tpu.api.types import (
    Container,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
    ReplicationController,
    ReplicationControllerSpec,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.controller.framework import SharedInformerFactory
from kubernetes_tpu.controller.replication import ReplicationManager
from kubernetes_tpu.kubemark import HollowCluster
from kubernetes_tpu.scheduler.server import SchedulerServer, SchedulerServerOptions


from conftest import wait_until  # noqa: E402


def test_hollow_cluster_runs_workload():
    """20 hollow nodes, an RC of 60 pods: everything must reach Running
    via real scheduler bindings and real kubelet status updates."""
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    cluster = HollowCluster(client, 20).run()
    informers = SharedInformerFactory(client)
    rcm = ReplicationManager(client, informers)
    informers.start()
    informers.wait_for_sync()
    rcm.run()
    sched = SchedulerServer(client, SchedulerServerOptions()).start()
    try:
        assert wait_until(lambda: len(client.nodes().list()[0]) == 20, 30)
        client.resource("replicationcontrollers", "default").create(
            ReplicationController(
                metadata=ObjectMeta(name="load"),
                spec=ReplicationControllerSpec(
                    replicas=60,
                    selector={"app": "load"},
                    template=PodTemplateSpec(
                        metadata=ObjectMeta(labels={"app": "load"}),
                        spec=PodSpec(
                            containers=[
                                Container(name="pause", requests={"cpu": "100m"})
                            ]
                        ),
                    ),
                ),
            )
        )
        assert wait_until(
            lambda: sum(
                1
                for p in client.pods().list()[0]
                if p.status.phase == "Running"
            )
            == 60,
            60,
        ), [
            (p.metadata.name, p.status.phase, p.spec.node_name)
            for p in client.pods().list()[0]
        ][:10]
        nodes_used = {p.spec.node_name for p in client.pods().list()[0]}
        assert len(nodes_used) == 20  # spreading across every hollow node
    finally:
        sched.stop()
        rcm.stop()
        informers.stop()
        cluster.stop()


def test_perf_harness_small():
    """The density harness runs end-to-end (tiny config in CI; the real
    configs are 100n/3kp and 1000n/30kp per the reference README)."""
    import io

    from kubernetes_tpu.harness.perf import schedule_pods

    out = io.StringIO()
    throughput = schedule_pods(10, 50, provider="DefaultProvider", out=out)
    assert throughput > 0
    assert "scheduled 50 pods on 10 nodes" in out.getvalue()


class _CountingTransport:
    """LocalTransport wrapper counting requests (the O(1)-requests
    structural assertions below count wire ops, not wall time)."""

    def __init__(self, inner):
        self._inner = inner
        self.object_protocol = getattr(inner, "object_protocol", False)
        self.requests = 0

    def request(self, method, path, query=None, body=None):
        self.requests += 1
        return self._inner.request(method, path, query, body)

    def watch(self, path, query=None):
        self.requests += 1
        return self._inner.watch(path, query)


def _fleet_env(num_nodes, **cfg_kw):
    from kubernetes_tpu.client.transport import LocalTransport
    from kubernetes_tpu.kubemark.fleet import FleetConfig, HollowFleet

    server = APIServer()
    transport = _CountingTransport(LocalTransport(server))
    client = RESTClient(transport)
    fleet = HollowFleet(client, FleetConfig(num_nodes=num_nodes, **cfg_kw))
    return server, transport, client, fleet


def test_hollow_fleet_acks_lifecycle():
    """Pending->Running acks through the batch door + local
    deletion observation, driven by interest-indexed shard watches."""
    from kubernetes_tpu.api.types import Pod
    from kubernetes_tpu.client.rest import batch_delete_item

    server, transport, client, fleet = _fleet_env(
        40, shard_size=16, heartbeat_interval=30.0, tick=0.05)
    fleet.run()
    try:
        assert len(client.nodes().list()[0]) == 40
        pods = client.pods()
        for i in range(30):
            pods.create(Pod(
                metadata=ObjectMeta(name=f"p-{i:03d}"),
                spec=PodSpec(containers=[Container(requests={"cpu": "100m"})]),
            ))
            pods.bind(f"p-{i:03d}", fleet.node_names[i % 40])
        assert wait_until(lambda: fleet.running_pods() == 30, 30)
        assert wait_until(
            lambda: sum(1 for p in pods.list()[0]
                        if p.status.phase == "Running") == 30, 30)
        # the fleet's shard watches registered in the interest index,
        # not the broadcast list (O(own pods) fan-out)
        cacher = server._cacher_for(server.resources["pods"])
        with cacher._cond:
            assert len(cacher._watchers) == 0
            assert len(cacher._interest) == 40
        # churn's delete half: one batch request, acks observed
        client.commit_batch(
            [batch_delete_item("pods", f"p-{i:03d}") for i in range(10)])
        assert wait_until(lambda: fleet.running_pods() == 20, 30)
        assert fleet.snapshot_stats()["deletions_observed"] >= 10
    finally:
        fleet.stop()
        server.close_cachers()


def test_hollow_fleet_heartbeats_are_batched():
    """N nodes' heartbeats per interval ride O(ticks) batch requests,
    not N PUTs: 120 nodes / 0.6s interval for ~1.5s must commit >=120
    heartbeats in a handful of requests."""
    import time as _t

    server, transport, client, fleet = _fleet_env(
        120, shard_size=64, heartbeat_interval=0.6, tick=0.1)
    fleet.run()
    try:
        t0 = transport.requests
        _t.sleep(1.5)
        stats = fleet.snapshot_stats()
        spent = transport.requests - t0
        assert stats["heartbeats"] >= 120
        # ~15 ticks elapsed; every tick flushes at most
        # ceil(pending/batch_max) = 1 batch here. Generous 3x headroom
        # against scheduler jitter — the per-node shape would be 120+.
        assert spent <= 45, (spent, stats)
        # heartbeats actually landed server-side
        node = client.nodes().get(fleet.node_names[0])
        assert node.status.conditions[0].last_heartbeat_time
    finally:
        fleet.stop()
        server.close_cachers()


def test_start_kubemark_mode_selection():
    from kubernetes_tpu.client.transport import LocalTransport
    from kubernetes_tpu.kubemark import (
        HollowCluster,
        HollowFleet,
        start_kubemark,
    )

    server = APIServer()
    client = RESTClient(LocalTransport(server))
    small = start_kubemark(client, 2)
    try:
        assert isinstance(small, HollowCluster) and len(small) == 2
    finally:
        small.stop()
    big = start_kubemark(client, 80, shard_size=40,
                         heartbeat_interval=30.0)
    try:
        assert isinstance(big, HollowFleet) and len(big) == 80
    finally:
        big.stop()
        server.close_cachers()
