"""Static-analysis suite (kubernetes_tpu/analysis): the tree must be
clean under every pass, AND each pass must catch its seeded violation —
a gate that can't fail is not a gate.

Seeded violations per the issues: an s64 dot_general (the PR 3 TPU
lowering incident), a ``.item()`` host sync in a hot module, a
lock-order inversion, a two-thread data race (lockset path and
missing-happens-before path separately), a ``# guarded-by`` write
without the lock, a drifted PartitionSpec, and a non-commutative
scatter smuggled into a commit fold."""

import dataclasses
import json
import sys
import threading
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_tpu.analysis import Finding, render_report
from kubernetes_tpu.analysis import lint
from kubernetes_tpu.analysis import jaxpr_audit
from kubernetes_tpu.analysis import locks
from kubernetes_tpu.analysis import races
from kubernetes_tpu.analysis.compile_guard import CompileSentinel
from kubernetes_tpu.analysis.jaxpr_audit import (
    audit_jaxpr,
    registered_programs,
)
from kubernetes_tpu.analysis.programs import ProgramSpec


# -- pass 1: jaxpr auditor ----------------------------------------------------


def _active(findings):
    return [f for f in findings if not f.suppressed]


def test_tree_jaxpr_audit_clean():
    """Every registered device program honors the lowering/transfer
    contracts (this is the `python -m kubernetes_tpu.analysis` body)."""
    findings = jaxpr_audit.audit_all()
    assert not _active(findings), render_report(findings)


def test_registry_covers_the_wave_programs():
    names = {s.name for s in registered_programs()}
    for expect in ("scan", "probe", "probe_fused_same", "apply",
                   "apply_group", "zreplay", "zreplay_group"):
        assert expect in names, f"{expect} missing from the registry"
    assert any(n.startswith("group_probe_G") for n in names)
    # mesh variants ride when the host can form a mesh (conftest
    # forces 8 CPU devices, so here they must be present)
    if len(jax.devices()) >= 2:
        assert {"mesh_scan", "mesh_probe", "mesh_group_probe",
                "mesh_apply", "mesh_apply_group",
                "resident_scatter"} <= names


def test_donation_contract_is_audited():
    """Every registered resident-state program declares donation and
    passes the aliasing audit; the donated folds cover the carry."""
    specs = {s.name: s for s in registered_programs()}
    if "mesh_apply" not in specs:
        import pytest

        pytest.skip("no mesh on this host")
    donated = [n for n, s in specs.items() if s.donate_argnums]
    assert {"mesh_apply", "mesh_apply_group",
            "resident_scatter"} <= set(donated)
    for n in donated:
        assert not jaxpr_audit._donation_findings(specs[n]), n


def test_seeded_broken_donation_is_flagged():
    """A donated input the program cannot alias (shape/dtype drift —
    XLA would silently copy it) must trip the donation audit."""
    def drops_donated(a, b):
        return b[:2] * 2  # output shape matches neither donated leaf

    fn = jax.jit(drops_donated, donate_argnums=(0,))
    spec = ProgramSpec(
        name="seeded_drop", fn=fn,
        args=(jnp.zeros(7, jnp.float32), jnp.zeros(5, jnp.float32)),
        carry_out_leaves=1, expected_host_leaves=None,
        donate_argnums=(0,),
    )
    found = jaxpr_audit._donation_findings(spec)
    assert any(f.rule in ("donation-contract", "donation-unusable")
               for f in found), found

    def keeps_donated(a, b):
        return a + b.sum()

    good = ProgramSpec(
        name="seeded_keep", fn=jax.jit(keeps_donated, donate_argnums=(0,)),
        args=(jnp.zeros(7, jnp.float32), jnp.zeros(5, jnp.float32)),
        carry_out_leaves=1, expected_host_leaves=None,
        donate_argnums=(0,),
    )
    assert not jaxpr_audit._donation_findings(good)


def test_grouped_wave_transfer_contract_is_static():
    """The O(1)-dispatch property as a STRUCTURAL invariant: the
    grouped probe ships exactly ONE host-bound array at every
    registered G (probe=1 transfer per wave regardless of template
    count) and the folds ship zero (apply=1 dispatch, 0 transfers)."""
    specs = {s.name: s for s in registered_programs()}
    gp = [s for n, s in specs.items() if n.startswith("group_probe_G")]
    assert len(gp) >= 2, "need two G values to pin G-independence"
    for s in gp:
        assert s.expected_host_leaves == 1
        assert not jaxpr_audit._transfer_findings(s), s.name
    for n in ("apply", "apply_group"):
        assert specs[n].expected_host_leaves == 0
        assert not jaxpr_audit._transfer_findings(specs[n]), n


def test_seeded_transfer_contract_violation_is_flagged():
    """An extra device->host output must trip the transfer audit."""
    carry = (jnp.zeros(3), jnp.zeros(3))

    def leaky(c, x):
        return c, x * 2, x + 1  # 2 host-bound outputs

    spec = ProgramSpec(
        name="seeded_leak", fn=jax.jit(leaky),
        args=(carry, jnp.zeros(3)),
        carry_out_leaves=2, expected_host_leaves=1,
    )
    found = jaxpr_audit._transfer_findings(spec)
    assert len(found) == 1 and found[0].rule == "transfer-contract"


def test_seeded_s64_dot_general_is_flagged():
    """Reintroduce the PR 3 incident: an s64 matmul must be denylisted."""
    bad = jax.jit(lambda a, b: a @ b)
    jaxpr = jax.make_jaxpr(bad)(
        jnp.ones((4, 4), jnp.int64), jnp.ones((4, 4), jnp.int64)
    )
    found = audit_jaxpr("seeded_s64", jaxpr)
    assert any(f.rule == "denylisted-primitive" for f in found), found
    # and the f32 spelling of the same program is fine
    ok = jax.make_jaxpr(bad)(
        jnp.ones((4, 4), jnp.float32), jnp.ones((4, 4), jnp.float32)
    )
    assert not audit_jaxpr("ok_f32", ok)


def test_seeded_callback_and_f64_upcast_are_flagged():
    def with_cb(x):
        import numpy as np

        return jax.pure_callback(
            lambda v: np.asarray(v), jax.ShapeDtypeStruct((4,), x.dtype), x
        )

    jaxpr = jax.make_jaxpr(with_cb)(jnp.ones(4))
    assert any(f.rule == "host-callback"
               for f in audit_jaxpr("seeded_cb", jaxpr))

    # a weak-type float division promotes int64 -> float64: the classic
    # silent upcast the probe/apply contract forbids
    jaxpr2 = jax.make_jaxpr(jax.jit(lambda x: x / 3.0))(
        jnp.ones(4, jnp.int64))
    found = audit_jaxpr("seeded_f64", jaxpr2)
    assert any(f.rule == "f64-upcast" for f in found), found
    # ...and the same jaxpr passes when the program is registered f64
    assert not audit_jaxpr("allowed_f64", jaxpr2, allow_f64=True)


# -- pass 2: AST lint ---------------------------------------------------------


def test_tree_lint_clean():
    findings = lint.lint_tree()
    assert not _active(findings), render_report(findings)


_HOT_FIXTURE = '''\
import jax
import jax.numpy as jnp


def _traced_body(x):
    k = x.sum(){item}  # seeded host sync
    return x * k


def run(x):
    return jax.jit(_traced_body)(x)
'''


def test_seeded_item_in_hot_module_is_flagged():
    src = _HOT_FIXTURE.format(item=".item()")
    found = lint.lint_sources(
        {"kubernetes_tpu/models/_seeded_fixture.py": src})
    hs = [f for f in found if f.rule == "host-sync"]
    assert len(hs) == 1 and not hs[0].suppressed, found
    assert "_seeded_fixture.py:6" in hs[0].where


def test_lint_suppression_syntax():
    src = _HOT_FIXTURE.format(
        item=".item()  # lint: allow[host-sync]")
    found = lint.lint_sources(
        {"kubernetes_tpu/models/_seeded_fixture.py": src})
    hs = [f for f in found if f.rule == "host-sync"]
    assert len(hs) == 1 and hs[0].suppressed, found


_WALL_CLOCK_FIXTURE = '''\
import time
from time import time as walltime


class Lease:
    def renew(self, window):
        self.expiry = time.time() + window        # arithmetic
        return self.expiry

    def valid(self):
        return time.time() < self.expiry          # comparison

    def wait_for(self, cond):
        cond.wait(timeout=time.time())            # deadline keyword
        self.deadline = walltime()                # deadline-ish bind

    def stamp_event(self):
        return time.time()                        # bare read: legal

    def monotonic_path(self, window):
        return time.monotonic() + window          # the correct form
'''


def test_seeded_wall_clock_deadline_is_flagged():
    found = lint.lint_sources(
        {"kubernetes_tpu/storage/quorum/_seeded_lease.py":
         _WALL_CLOCK_FIXTURE})
    wc = [f for f in found if f.rule == "wall-clock-deadline"]
    assert len(wc) == 4 and not any(f.suppressed for f in wc), found
    lines = sorted(int(f.where.rsplit(":", 1)[1]) for f in wc)
    assert lines == [7, 11, 14, 15], wc


def test_wall_clock_rule_covers_all_named_modules_and_no_others():
    src = "import time\ndeadline = time.time() + 5.0\n"
    for rel in ("kubernetes_tpu/storage/quorum/_seeded.py",
                "kubernetes_tpu/client/transport.py",
                "kubernetes_tpu/apiserver/flowcontrol.py"):
        found = lint.lint_sources({rel: src})
        assert any(f.rule == "wall-clock-deadline" for f in found), rel
    # identical source outside the consensus-critical scope is exempt
    found = lint.lint_sources(
        {"kubernetes_tpu/scheduler/_seeded.py": src})
    assert not any(f.rule == "wall-clock-deadline" for f in found)


def test_wall_clock_suppression_syntax():
    src = ("import time\n"
           "t = time.time() + 5  # lint: allow[wall-clock-deadline]\n")
    found = lint.lint_sources(
        {"kubernetes_tpu/storage/quorum/_seeded.py": src})
    wc = [f for f in found if f.rule == "wall-clock-deadline"]
    assert len(wc) == 1 and wc[0].suppressed, found


def test_lint_traced_scope_is_transitive_and_cold_code_is_exempt():
    src = '''\
import jax
import jax.numpy as jnp


def helper(x):
    return x.sum().item()  # reached from a traced body


def _traced_body(x):
    return helper(x)


def run(x):
    return jax.jit(_traced_body)(x)


def host_driver(arr):
    return arr.sum().item()  # NOT traced: no finding here
'''
    found = lint.lint_sources(
        {"kubernetes_tpu/models/_seeded_fixture2.py": src})
    hs = [f for f in found if f.rule == "host-sync"]
    assert len(hs) == 1, found
    assert ":6" in hs[0].where  # helper's .item(), not host_driver's


def test_lint_package_wide_rules_fire():
    src = '''\
import threading
from kubernetes_tpu.metrics import Counter


def f(x=[]):
    try:
        pass
    except:
        pass
    threading.Thread(target=f).start()
    return Counter("loose_total", "constructed outside the registry")
'''
    found = lint.lint_sources({"kubernetes_tpu/client/_seeded3.py": src})
    rules = {f.rule for f in found}
    assert {"mutable-default", "bare-except", "nondaemon-thread",
            "metric-outside-registry"} <= rules, found


def test_lint_syntax_error_is_a_finding_not_a_crash():
    found = lint.lint_sources({
        "kubernetes_tpu/models/_broken.py": "def f(:\n",
        "kubernetes_tpu/models/_fine.py": "x = 1\n",
    })
    se = [f for f in found if f.rule == "syntax-error"]
    assert len(se) == 1 and "_broken.py" in se[0].where, found


def test_lint_impure_traced_rules_fire():
    src = '''\
import time

import jax


def _traced_body(x):
    t = time.time()  # seeded impurity
    print("trace me")
    return x


def run(x):
    return jax.jit(_traced_body)(x)
'''
    found = lint.lint_sources(
        {"kubernetes_tpu/ops/_seeded4.py": src})
    impure = [f for f in found if f.rule == "traced-impure"]
    assert len(impure) == 2, found


# -- pass 3: runtime sanitizers ----------------------------------------------


def _fake_component():
    """Locks created from a module whose __name__ is inside the
    package, so the instrumented factories track them."""
    mod = types.ModuleType("kubernetes_tpu._seeded_locks")
    sys.modules["kubernetes_tpu._seeded_locks"] = mod
    src = ("import threading\n"
           "def make_a():\n    return threading.Lock()\n"
           "def make_b():\n    return threading.Lock()\n")
    exec(compile(src, "_seeded_locks.py", "exec"), mod.__dict__)
    return mod


def test_seeded_lock_order_inversion_is_flagged():
    mod = _fake_component()
    locks.GRAPH.reset()
    with locks.instrumented():
        a, b = mod.make_a(), mod.make_b()

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        for fn in (t1, t2):
            th = threading.Thread(target=fn)
            th.start()
            th.join()
    try:
        cycles = locks.GRAPH.cycles()
        assert cycles, "inversion not detected"
        with pytest.raises(AssertionError, match="lock-order"):
            locks.assert_no_cycles("(seeded)")
    finally:
        locks.GRAPH.reset()  # never leak the seeded cycle into chaos


def test_consistent_lock_order_stays_clean():
    mod = _fake_component()
    locks.GRAPH.reset()
    with locks.instrumented():
        a, b = mod.make_a(), mod.make_b()
        for _ in range(3):
            with a:
                with b:
                    pass
        with a:
            pass
        with b:
            pass
    assert not locks.GRAPH.cycles()
    locks.assert_no_cycles("(ordered)")


def test_reentrant_rlock_is_not_a_cycle():
    mod = _fake_component()
    src = ("import threading\n"
           "def make_r():\n    return threading.RLock()\n")
    exec(compile(src, "_seeded_locks.py", "exec"), mod.__dict__)
    locks.GRAPH.reset()
    with locks.instrumented():
        r = mod.make_r()
        with r:
            with r:  # re-entrant: no self-edge
                pass
    assert not locks.GRAPH.cycles()


def test_untracked_modules_get_raw_locks():
    with locks.instrumented():
        lk = threading.Lock()  # caller: tests/, not kubernetes_tpu
    assert not isinstance(lk, locks.TrackedLock)


def test_compile_sentinel_catches_steady_state_compiles():
    sentinel = CompileSentinel()
    f = jax.jit(lambda x: x * 2 + 1)
    f(jnp.ones(7))  # compile happens OUTSIDE the guarded window
    with sentinel.expect_no_compiles("warm replay"):
        f(jnp.ones(7))
    with pytest.raises(AssertionError, match="recompilation"):
        with sentinel.expect_no_compiles("cold"):
            jax.jit(lambda x: x * 3 - 1)(jnp.ones(7))


# -- pass 4: data-race detector (lockset + happens-before) --------------------


class _Shared:
    """A plain shared object the seeded-race tests track."""

    def __init__(self):
        self.val = 0


def _run_pair(t1, t2):
    a = threading.Thread(target=t1)
    b = threading.Thread(target=t2)
    a.start()
    b.start()
    a.join()
    b.join()


def test_seeded_race_disjoint_locksets_is_flagged():
    """The Eraser path: both threads DO hold locks — just never a
    common one — so only lockset intersection (not mere lock use)
    may clear an access pair."""
    mod = _fake_component()
    try:
        with races.instrumented(reset=True):
            la, lb = mod.make_a(), mod.make_b()
            obj = races.track(_Shared(), "seeded.Shared")

            def t1():
                with la:
                    obj.val = 1

            def t2():
                with lb:
                    obj.val = 2

            _run_pair(t1, t2)
            found = [f for f in races.findings() if not f.suppressed]
            assert any(f.rule == "data-race"
                       and "seeded.Shared.val" in f.where
                       for f in found), races.findings()
            # the finding carries BOTH sample stacks (this file twice)
            msg = found[0].message
            assert msg.count("test_analysis.py") >= 2, msg
            assert "write/write" in msg
            with pytest.raises(AssertionError, match="data race"):
                races.assert_no_races("(seeded)")
    finally:
        races.reset()  # never leak the seeded race into later tests


def test_seeded_race_missing_hb_is_flagged():
    """The happens-before path: no locks anywhere, two sibling threads
    with no ordering edge between them."""
    try:
        with races.instrumented(reset=True):
            obj = races.track(_Shared(), "seeded.NoHB")

            def t1():
                obj.val = 1

            def t2():
                obj.val = 2

            _run_pair(t1, t2)
            found = [f for f in races.findings() if not f.suppressed]
            assert any("seeded.NoHB.val" in f.where for f in found), \
                races.findings()
            assert "no common lock, no happens-before" in found[0].message
    finally:
        races.reset()


def test_common_lock_keeps_the_pair_clean():
    mod = _fake_component()
    with races.instrumented(reset=True):
        lk = mod.make_a()
        obj = races.track(_Shared(), "seeded.Locked")

        def t1():
            with lk:
                obj.val = 1

        def t2():
            with lk:
                obj.val = 2

        _run_pair(t1, t2)
        races.assert_no_races("(common lock)")


def test_thread_start_join_edges_order_accesses():
    """Parent-before-start and join-before-parent are HB edges: the
    classic create/join lifecycle never reports."""
    with races.instrumented(reset=True):
        obj = races.track(_Shared(), "seeded.Lifecycle")
        obj.val = 5  # parent write BEFORE start

        def child():
            obj.val = obj.val + 1

        th = threading.Thread(target=child)
        th.start()
        th.join()
        obj.val = 7  # parent write AFTER join
        races.assert_no_races("(start/join)")


def test_queue_put_get_handoff_is_ordered():
    """The workqueue put→get hook: producer-side mutations are ordered
    before the draining consumer's accesses — the highest-traffic
    cross-thread handoff must not false-positive."""
    from kubernetes_tpu.utils.workqueue import WorkQueue

    with races.instrumented(reset=True):
        q = WorkQueue(name="hb-witness")
        obj = races.track(_Shared(), "seeded.Handoff")

        def producer():
            obj.val = 41  # unlocked write, ordered only by the queue
            q.add("item")

        def consumer():
            item = q.get()
            obj.val = obj.val + 1
            q.done(item)

        _run_pair(consumer, producer)
        assert obj.val == 42
        races.assert_no_races("(queue handoff)")


def test_fifo_pop_handoff_is_ordered():
    from kubernetes_tpu.client.cache.fifo import FIFO

    with races.instrumented(reset=True):
        fifo = FIFO(key_func=lambda o: o["name"])
        obj = races.track(_Shared(), "seeded.FifoHandoff")

        def producer():
            obj.val = 10
            fifo.add({"name": "x"})

        def consumer():
            fifo.pop()
            obj.val = obj.val + 1

        _run_pair(consumer, producer)
        assert obj.val == 11
        races.assert_no_races("(fifo handoff)")


def test_race_suppression_syntax_is_honored():
    """`# race: allow[reason]` at EITHER access site suppresses the
    pair; the finding stays counted (reported, marked), like lint."""
    try:
        with races.instrumented(reset=True):
            obj = races.track(_Shared(), "seeded.Benign")

            def t1():
                obj.val = 1  # race: allow[seeded benign fixture]

            def t2():
                obj.val = 2

            _run_pair(t1, t2)
            found = races.findings()
            assert found and all(f.suppressed for f in found), found
            assert "allow[seeded benign fixture]" in found[0].message
            races.assert_no_races("(suppressed only)")  # does not raise
    finally:
        races.reset()


def test_shared_decorator_registers_instances():
    """@shared instances self-register at construction: the decorator
    path must catch the same race track() does (and stay a no-op while
    disarmed)."""
    from kubernetes_tpu.analysis.races import shared

    @shared("seeded.Decorated")
    class _Deco:
        def __init__(self):
            self.val = 0

    cold = _Deco()  # constructed disarmed: stays raw
    assert type(cold).__name__ == "_Deco"
    try:
        with races.instrumented(reset=True):
            obj = _Deco()

            def t1():
                obj.val = 1

            def t2():
                obj.val = 2

            _run_pair(t1, t2)
            found = [f for f in races.findings() if not f.suppressed]
            assert any("seeded.Decorated.val" in f.where
                       for f in found), races.findings()
    finally:
        races.reset()


def test_track_registration_is_weakref_safe():
    """Tracking must never extend an object's lifetime (the cacher feed
    holds its cacher only weakly; a pinning registry would leak every
    discarded apiserver's caches)."""
    import gc
    import weakref

    with races.instrumented(reset=True):
        obj = races.track(_Shared(), "seeded.Collectable")
        obj.val = 3
        ref = weakref.ref(obj)
        del obj
        gc.collect()
        assert ref() is None, "track() pinned the object alive"


def test_disarmed_track_is_a_no_op(monkeypatch):
    # force-disarm even under the suite-wide sanitizer
    monkeypatch.setattr(races, "_armed", False)
    obj = _Shared()
    assert races.track(obj) is obj
    assert type(obj) is _Shared  # no retyping while disarmed
    races.note_put(obj)  # all hooks are flag-check no-ops
    races.note_get(obj)


# -- true-positive sweep regressions ------------------------------------------
#
# Each race the armed sweep confirmed got a fix; these pin the fixes so
# a refactor can't silently reintroduce them.


def test_delaying_queue_waiter_shutdown_is_race_clean():
    """The waiter used to read the base queue's _shutting_down (guarded
    by self._cond) under self._heap_cond — two different guards on one
    field. The fix gives the waiter its own _heap_cond-guarded flag;
    the armed detector must stay silent across a threaded shutdown."""
    from kubernetes_tpu.utils.workqueue import DelayingQueue

    with races.instrumented(reset=True):
        q = DelayingQueue(name="race-regress")
        q.add_after("a", 0.01)

        t = threading.Thread(target=q.shut_down)
        t.start()
        t.join()
        q._waiter.join(timeout=5)
        assert not q._waiter.is_alive(), "waiter missed the stop flag"
        races.assert_no_races("(delaying-queue shutdown)")


def test_replicated_store_stop_flag_is_guarded(tmp_path):
    """close() used to flip _stopped lock-free while repl-accept polled
    it lock-free; both sides now hold _repl_lock."""
    import time

    from kubernetes_tpu.storage.replicated import ReplicatedStore

    with races.instrumented(reset=True):
        st = ReplicatedStore(str(tmp_path))
        time.sleep(0.2)  # let repl-accept reach its guarded poll
        t = threading.Thread(target=st.close)
        t.start()
        t.join()
        races.assert_no_races("(replicated close)")


def test_leaderelection_observation_cache_fix_is_pinned():
    """The armed lint sweep found try_acquire_or_renew writing
    observed_record/observed_time bare while stop()'s release path
    reads them under _write_lock. The file must lint clean now, AND
    un-fixing it must still be caught — the gate can't go blind."""
    import kubernetes_tpu.client.leaderelection as le

    with open(le.__file__, "r", encoding="utf-8") as f:
        src = f.read()
    rel = "kubernetes_tpu/client/leaderelection.py"
    conc = [f for f in lint.lint_sources({rel: src})
            if f.rule in ("guarded-by", "unguarded-shared-write")
            and not f.suppressed]
    assert not conc, conc
    reverted = src.replace(
        "                with self._write_lock:\n"
        "                    self.observed_record = existing\n"
        "                    self.observed_time = now\n",
        "                self.observed_record = existing\n"
        "                self.observed_time = now\n",
    )
    assert reverted != src, "fix site moved; update this regression"
    found = lint.lint_sources({rel: reverted})
    assert any(f.rule == "unguarded-shared-write" and not f.suppressed
               for f in found), found


def test_kubelet_pod_ips_fix_is_pinned():
    """_kill_pod popped _pod_ips outside self._lock while per-pod
    workers mutate it under the lock; same clean-now / caught-if-
    reverted pin as the leaderelection fix."""
    import kubernetes_tpu.kubelet.kubelet as kl

    with open(kl.__file__, "r", encoding="utf-8") as f:
        src = f.read()
    rel = "kubernetes_tpu/kubelet/kubelet.py"
    conc = [f for f in lint.lint_sources({rel: src})
            if f.rule in ("guarded-by", "unguarded-shared-write")
            and not f.suppressed]
    assert not conc, conc
    fixed_block = (
        "        with self._lock:\n"
        "            # _pod_ips is mutated under the lock by every per-pod\n"
        "            # worker's _pod_ip(); the delete must hold it too\n"
        "            self._pod_ips.pop(pod.metadata.uid, None)\n"
    )
    assert fixed_block in src, "fix site moved; update this regression"
    reverted = src.replace(
        fixed_block,
        "        self._pod_ips.pop(pod.metadata.uid, None)\n"
        "        with self._lock:\n",
    )
    found = lint.lint_sources({rel: reverted})
    assert any(f.rule == "unguarded-shared-write" and not f.suppressed
               for f in found), found


# -- guarded-by / thread-escape lint ------------------------------------------


_GUARDED_FIXTURE = '''\
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._data = {{}}  # guarded-by: self._lock

    def good(self, k, v):
        with self._lock:
            self._data[k] = v

    def also_good(self, k, v):
        with self._cond:  # Condition aliases its lock
            self._data[k] = v

    def _helper(self, k):  # guarded-by: self._lock
        self._data.pop(k, None)

    def _drop_locked(self, k):
        self._data.pop(k, None)

    def bad(self, k, v):
        {bad_write}
'''


def test_seeded_guarded_by_violation_is_flagged():
    src = _GUARDED_FIXTURE.format(bad_write="self._data[k] = v")
    found = lint.lint_sources({"kubernetes_tpu/client/_seeded_gb.py": src})
    gb = [f for f in found if f.rule == "guarded-by"]
    assert len(gb) == 1 and not gb[0].suppressed, found
    assert "Box._data" in gb[0].message
    assert "self._lock" in gb[0].message
    # only the bare write fires: with-lock, with-Condition-alias,
    # def-line held-on-entry annotation, and *_locked naming all pass


def test_guarded_by_clean_class_is_clean():
    src = _GUARDED_FIXTURE.format(
        bad_write="with self._lock:\n            self._data[k] = v")
    found = lint.lint_sources({"kubernetes_tpu/client/_seeded_gb.py": src})
    assert not [f for f in found if f.rule == "guarded-by"], found


def test_guarded_by_suppression_is_honored():
    src = _GUARDED_FIXTURE.format(
        bad_write="self._data[k] = v  # lint: allow[guarded-by]")
    found = lint.lint_sources({"kubernetes_tpu/client/_seeded_gb.py": src})
    gb = [f for f in found if f.rule == "guarded-by"]
    assert len(gb) == 1 and gb[0].suppressed, found


def test_unguarded_shared_write_in_escaping_class_is_flagged():
    src = '''\
import threading


class Esc:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def start(self):
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        with self._lock:
            self._items.append(1)

    def nudge(self):
        self._items.append(2)
'''
    found = lint.lint_sources({"kubernetes_tpu/client/_seeded_esc.py": src})
    uw = [f for f in found if f.rule == "unguarded-shared-write"]
    assert len(uw) == 1, found
    assert "Esc._items" in uw[0].message
    # the same class WITHOUT the thread escape is not a finding (the
    # inconsistent guarding may be phase discipline; only escape makes
    # it a shared-state signal)
    solo = src.replace(
        "        threading.Thread(target=self._run, daemon=True)"
        ".start()\n", "        pass\n")
    found2 = lint.lint_sources(
        {"kubernetes_tpu/client/_seeded_esc.py": solo})
    assert not [f for f in found2
                if f.rule == "unguarded-shared-write"], found2


# -- sharding-drift + scatter-contract audits ---------------------------------


def _mesh_and_shardings():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = np.array(jax.devices())
    if devs.size < 2:
        pytest.skip("needs a multi-device host platform")
    mesh = Mesh(devs, ("nodes",))
    return (mesh,
            NamedSharding(mesh, PartitionSpec("nodes")),
            NamedSharding(mesh, PartitionSpec()))


def test_seeded_sharding_drift_is_flagged():
    from jax.sharding import PartitionSpec as P

    mesh, sharded, repl = _mesh_and_shardings()
    n = len(jax.devices()) * 4
    fn = jax.jit(lambda a, b: a * 2 + b.sum(),
                 in_shardings=(sharded, repl), out_shardings=sharded)
    args = (jnp.zeros(n), jnp.zeros(3))
    jaxpr = jax.make_jaxpr(fn)(*args)
    spec = ProgramSpec(
        name="seeded_drift", fn=fn, args=args, carry_out_leaves=0,
        arg_shardings=(P("nodes"), P()), out_shardings_decl=P("nodes"),
    )
    # the agreeing declaration is clean...
    assert not jaxpr_audit._sharding_findings(spec, jaxpr)
    # ...a drifted input PartitionSpec is a finding...
    bad_in = dataclasses.replace(spec, arg_shardings=(P(), P()))
    found = jaxpr_audit._sharding_findings(bad_in, jaxpr)
    assert found and all(f.rule == "sharding-drift" for f in found)
    assert "PartitionSpec" in found[0].message
    # ...and so is a drifted output
    bad_out = dataclasses.replace(spec, out_shardings_decl=P())
    assert jaxpr_audit._sharding_findings(bad_out, jaxpr)
    # trailing-None canonicalization: P('nodes') == P('nodes', None)
    two_d = jax.jit(lambda a: a * 2, in_shardings=(sharded,),
                    out_shardings=sharded)
    args2 = (jnp.zeros((n, 3)),)
    spec2 = ProgramSpec(
        name="seeded_trailing", fn=two_d, args=args2, carry_out_leaves=0,
        arg_shardings=(P("nodes", None),),
        out_shardings_decl=P("nodes", None),
    )
    assert not jaxpr_audit._sharding_findings(
        spec2, jax.make_jaxpr(two_d)(*args2))


def test_mesh_programs_declare_and_pass_the_sharding_audit():
    """The registry's mesh programs all carry declarations built from
    resident.carry_specs()/static_specs() and the audit passes — the
    acceptance-criteria clean run, scoped to the drift pass."""
    specs = {s.name: s for s in registered_programs()}
    if "mesh_apply" not in specs:
        pytest.skip("no mesh on this host")
    for name in ("mesh_scan", "mesh_probe", "mesh_group_probe",
                 "mesh_apply", "mesh_apply_group", "resident_scatter"):
        s = specs[name]
        assert s.arg_shardings is not None, f"{name} undeclared"
        jaxpr = jax.make_jaxpr(s.fn)(*s.args)
        assert not jaxpr_audit._sharding_findings(s, jaxpr), name
    # and a seeded drift against the REAL mesh_apply program fires
    ma = specs["mesh_apply"]
    from jax.sharding import PartitionSpec as P

    drifted_carry = (P(),) + ma.arg_shardings[1][1:]
    bad = dataclasses.replace(
        ma, arg_shardings=(ma.arg_shardings[0], drifted_carry)
        + ma.arg_shardings[2:])
    found = jaxpr_audit._sharding_findings(
        bad, jax.make_jaxpr(ma.fn)(*ma.args))
    assert found and found[0].rule == "sharding-drift", found


def test_seeded_scatter_contract_violation_is_flagged():
    _mesh_and_shardings()  # skip on 1-device hosts for parity

    def overwrite(tbl, idx, vals):
        return tbl.at[idx].set(vals)  # plain scatter, no unique claim

    def accumulate(tbl, idx, vals):
        return tbl.at[idx].add(vals)

    args = (jnp.zeros(16), jnp.arange(4), jnp.ones(4))
    ow = jax.make_jaxpr(overwrite)(*args)
    acc = jax.make_jaxpr(accumulate)(*args)

    def spec_for(fn, jx, allowed):
        return ProgramSpec(name="seeded_scatter", fn=fn, args=args,
                           carry_out_leaves=0,
                           scatter_allowed=allowed), jx

    # a commutative scatter-add matching the declaration: clean
    s, jx = spec_for(accumulate, acc, (("scatter-add", (0,)),))
    assert not jaxpr_audit._scatter_findings(s, jx)
    # an UNDECLARED form is a finding even when commutative
    s, jx = spec_for(accumulate, acc, (("scatter-add", (1,)),))
    found = jaxpr_audit._scatter_findings(s, jx)
    assert found and found[0].rule == "scatter-contract", found
    # a declared OVERWRITE scatter without unique_indices is order-
    # dependent under collisions: finding
    s, jx = spec_for(overwrite, ow, (("scatter", (0,)),))
    found = jaxpr_audit._scatter_findings(s, jx)
    assert found and "unique_indices" in found[0].message, found
    # the unique-indices spelling of the same overwrite passes
    def overwrite_unique(tbl, idx, vals):
        return tbl.at[idx].set(vals, unique_indices=True)

    ju = jax.make_jaxpr(overwrite_unique)(*args)
    s, jx = spec_for(overwrite_unique, ju, (("scatter", (0,)),))
    assert not jaxpr_audit._scatter_findings(s, jx)


# -- the CLI gate -------------------------------------------------------------


def test_cli_lint_gate_exits_zero():
    from kubernetes_tpu.analysis.__main__ import main

    assert main(["--lint-only"]) == 0


def test_cli_json_mode_emits_machine_readable_rows(capsys, tmp_path):
    """--json: one JSON object per finding, uniform across lint, jaxpr
    audit, and merged race-witness artifacts (the CI upload format)."""
    from kubernetes_tpu.analysis.__main__ import main

    # seed a race artifact the CLI must merge and fail on
    report = tmp_path / "races.jsonl"
    try:
        with races.instrumented(reset=True):
            obj = races.track(_Shared(), "seeded.CLI")

            def t1():
                obj.val = 1

            def t2():
                obj.val = 2

            _run_pair(t1, t2)
            assert races.dump_jsonl(str(report)) >= 1
    finally:
        races.reset()

    rc = main(["--lint-only", "--json", "--race-report", str(report)])
    out = capsys.readouterr().out
    rows = [json.loads(line) for line in out.splitlines() if line]
    assert rc == 1  # the merged unsuppressed race fails the gate
    assert all({"pass", "rule", "where", "message", "suppressed"}
               <= set(r) for r in rows)
    assert any(r["pass"] == "races" and r["rule"] == "data-race"
               for r in rows)
    # an empty artifact gates clean
    empty = tmp_path / "none.jsonl"
    empty.write_text("")
    assert main(["--lint-only", "--json", "--race-report",
                 str(empty)]) == 0
    capsys.readouterr()


def test_bench_refuses_armed_sanitizers(monkeypatch):
    """Perf runs must hard-fail with a sanitizer armed — an
    instrumented headline number is worse than no number."""
    import importlib.util as u
    import os

    monkeypatch.setenv("KUBERNETES_TPU_RACE_SANITIZER", "1")
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    spec = u.spec_from_file_location("_bench_under_test", path)
    mod = u.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # SystemExit, not AssertionError: the guard must survive python -O
    with pytest.raises(SystemExit, match="RACE_SANITIZER"):
        mod.main()  # the guard is the first statement: no heavy work


def test_cli_malformed_race_report_fails_the_gate(tmp_path):
    from kubernetes_tpu.analysis.__main__ import main

    bad = tmp_path / "corrupt.jsonl"
    bad.write_text("this is not json\n")
    assert main(["--lint-only", "--race-report", str(bad)]) == 1


def test_findings_report_shape():
    rep = render_report([
        Finding("lint", "host-sync", "a.py:1", "x", suppressed=False),
        Finding("lint", "host-sync", "b.py:2", "y", suppressed=True),
    ], "t:")
    assert "1 finding(s), 1 suppressed" in rep
    assert "a.py:1" in rep
    # suppressed rows stay visible, marked — allowance drift is
    # auditable from the report itself
    assert "[suppressed lint/host-sync] b.py:2" in rep


def test_nondaemon_thread_rule_ignores_path_and_str_joins():
    """os.path.join / ', '.join must NOT satisfy the thread-join
    heuristic — only a plausible Thread.join() does."""
    base = '''\
import os
import threading


def f():
    p = os.path.join("a", "b")
    s = ", ".join(["x", "y"])
    threading.Thread(target=print).start()
    return p, s
'''
    found = lint.lint_sources({"kubernetes_tpu/client/_seeded5.py": base})
    assert any(f.rule == "nondaemon-thread" for f in found), found
    joined = base.replace(
        "    return p, s",
        "    t = threading.Thread(target=print)\n"
        "    t.start()\n"
        "    t.join()\n"
        "    return p, s",
    )
    found2 = lint.lint_sources(
        {"kubernetes_tpu/client/_seeded5.py": joined})
    assert not any(f.rule == "nondaemon-thread" for f in found2), found2
