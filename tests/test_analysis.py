"""Static-analysis suite (kubernetes_tpu/analysis): the tree must be
clean under every pass, AND each pass must catch its seeded violation —
a gate that can't fail is not a gate.

Seeded violations per the issue: an s64 dot_general (the PR 3 TPU
lowering incident), a ``.item()`` host sync in a hot module, and a
lock-order inversion."""

import sys
import threading
import types

import jax
import jax.numpy as jnp
import pytest

from kubernetes_tpu.analysis import Finding, render_report
from kubernetes_tpu.analysis import lint
from kubernetes_tpu.analysis import jaxpr_audit
from kubernetes_tpu.analysis import locks
from kubernetes_tpu.analysis.compile_guard import CompileSentinel
from kubernetes_tpu.analysis.jaxpr_audit import (
    audit_jaxpr,
    registered_programs,
)
from kubernetes_tpu.analysis.programs import ProgramSpec


# -- pass 1: jaxpr auditor ----------------------------------------------------


def _active(findings):
    return [f for f in findings if not f.suppressed]


def test_tree_jaxpr_audit_clean():
    """Every registered device program honors the lowering/transfer
    contracts (this is the `python -m kubernetes_tpu.analysis` body)."""
    findings = jaxpr_audit.audit_all()
    assert not _active(findings), render_report(findings)


def test_registry_covers_the_wave_programs():
    names = {s.name for s in registered_programs()}
    for expect in ("scan", "probe", "probe_fused_same", "apply",
                   "apply_group", "zreplay", "zreplay_group"):
        assert expect in names, f"{expect} missing from the registry"
    assert any(n.startswith("group_probe_G") for n in names)
    # mesh variants ride when the host can form a mesh (conftest
    # forces 8 CPU devices, so here they must be present)
    if len(jax.devices()) >= 2:
        assert {"mesh_scan", "mesh_probe", "mesh_group_probe",
                "mesh_apply", "mesh_apply_group",
                "resident_scatter"} <= names


def test_donation_contract_is_audited():
    """Every registered resident-state program declares donation and
    passes the aliasing audit; the donated folds cover the carry."""
    specs = {s.name: s for s in registered_programs()}
    if "mesh_apply" not in specs:
        import pytest

        pytest.skip("no mesh on this host")
    donated = [n for n, s in specs.items() if s.donate_argnums]
    assert {"mesh_apply", "mesh_apply_group",
            "resident_scatter"} <= set(donated)
    for n in donated:
        assert not jaxpr_audit._donation_findings(specs[n]), n


def test_seeded_broken_donation_is_flagged():
    """A donated input the program cannot alias (shape/dtype drift —
    XLA would silently copy it) must trip the donation audit."""
    def drops_donated(a, b):
        return b[:2] * 2  # output shape matches neither donated leaf

    fn = jax.jit(drops_donated, donate_argnums=(0,))
    spec = ProgramSpec(
        name="seeded_drop", fn=fn,
        args=(jnp.zeros(7, jnp.float32), jnp.zeros(5, jnp.float32)),
        carry_out_leaves=1, expected_host_leaves=None,
        donate_argnums=(0,),
    )
    found = jaxpr_audit._donation_findings(spec)
    assert any(f.rule in ("donation-contract", "donation-unusable")
               for f in found), found

    def keeps_donated(a, b):
        return a + b.sum()

    good = ProgramSpec(
        name="seeded_keep", fn=jax.jit(keeps_donated, donate_argnums=(0,)),
        args=(jnp.zeros(7, jnp.float32), jnp.zeros(5, jnp.float32)),
        carry_out_leaves=1, expected_host_leaves=None,
        donate_argnums=(0,),
    )
    assert not jaxpr_audit._donation_findings(good)


def test_grouped_wave_transfer_contract_is_static():
    """The O(1)-dispatch property as a STRUCTURAL invariant: the
    grouped probe ships exactly ONE host-bound array at every
    registered G (probe=1 transfer per wave regardless of template
    count) and the folds ship zero (apply=1 dispatch, 0 transfers)."""
    specs = {s.name: s for s in registered_programs()}
    gp = [s for n, s in specs.items() if n.startswith("group_probe_G")]
    assert len(gp) >= 2, "need two G values to pin G-independence"
    for s in gp:
        assert s.expected_host_leaves == 1
        assert not jaxpr_audit._transfer_findings(s), s.name
    for n in ("apply", "apply_group"):
        assert specs[n].expected_host_leaves == 0
        assert not jaxpr_audit._transfer_findings(specs[n]), n


def test_seeded_transfer_contract_violation_is_flagged():
    """An extra device->host output must trip the transfer audit."""
    carry = (jnp.zeros(3), jnp.zeros(3))

    def leaky(c, x):
        return c, x * 2, x + 1  # 2 host-bound outputs

    spec = ProgramSpec(
        name="seeded_leak", fn=jax.jit(leaky),
        args=(carry, jnp.zeros(3)),
        carry_out_leaves=2, expected_host_leaves=1,
    )
    found = jaxpr_audit._transfer_findings(spec)
    assert len(found) == 1 and found[0].rule == "transfer-contract"


def test_seeded_s64_dot_general_is_flagged():
    """Reintroduce the PR 3 incident: an s64 matmul must be denylisted."""
    bad = jax.jit(lambda a, b: a @ b)
    jaxpr = jax.make_jaxpr(bad)(
        jnp.ones((4, 4), jnp.int64), jnp.ones((4, 4), jnp.int64)
    )
    found = audit_jaxpr("seeded_s64", jaxpr)
    assert any(f.rule == "denylisted-primitive" for f in found), found
    # and the f32 spelling of the same program is fine
    ok = jax.make_jaxpr(bad)(
        jnp.ones((4, 4), jnp.float32), jnp.ones((4, 4), jnp.float32)
    )
    assert not audit_jaxpr("ok_f32", ok)


def test_seeded_callback_and_f64_upcast_are_flagged():
    def with_cb(x):
        import numpy as np

        return jax.pure_callback(
            lambda v: np.asarray(v), jax.ShapeDtypeStruct((4,), x.dtype), x
        )

    jaxpr = jax.make_jaxpr(with_cb)(jnp.ones(4))
    assert any(f.rule == "host-callback"
               for f in audit_jaxpr("seeded_cb", jaxpr))

    # a weak-type float division promotes int64 -> float64: the classic
    # silent upcast the probe/apply contract forbids
    jaxpr2 = jax.make_jaxpr(jax.jit(lambda x: x / 3.0))(
        jnp.ones(4, jnp.int64))
    found = audit_jaxpr("seeded_f64", jaxpr2)
    assert any(f.rule == "f64-upcast" for f in found), found
    # ...and the same jaxpr passes when the program is registered f64
    assert not audit_jaxpr("allowed_f64", jaxpr2, allow_f64=True)


# -- pass 2: AST lint ---------------------------------------------------------


def test_tree_lint_clean():
    findings = lint.lint_tree()
    assert not _active(findings), render_report(findings)


_HOT_FIXTURE = '''\
import jax
import jax.numpy as jnp


def _traced_body(x):
    k = x.sum(){item}  # seeded host sync
    return x * k


def run(x):
    return jax.jit(_traced_body)(x)
'''


def test_seeded_item_in_hot_module_is_flagged():
    src = _HOT_FIXTURE.format(item=".item()")
    found = lint.lint_sources(
        {"kubernetes_tpu/models/_seeded_fixture.py": src})
    hs = [f for f in found if f.rule == "host-sync"]
    assert len(hs) == 1 and not hs[0].suppressed, found
    assert "_seeded_fixture.py:6" in hs[0].where


def test_lint_suppression_syntax():
    src = _HOT_FIXTURE.format(
        item=".item()  # lint: allow[host-sync]")
    found = lint.lint_sources(
        {"kubernetes_tpu/models/_seeded_fixture.py": src})
    hs = [f for f in found if f.rule == "host-sync"]
    assert len(hs) == 1 and hs[0].suppressed, found


def test_lint_traced_scope_is_transitive_and_cold_code_is_exempt():
    src = '''\
import jax
import jax.numpy as jnp


def helper(x):
    return x.sum().item()  # reached from a traced body


def _traced_body(x):
    return helper(x)


def run(x):
    return jax.jit(_traced_body)(x)


def host_driver(arr):
    return arr.sum().item()  # NOT traced: no finding here
'''
    found = lint.lint_sources(
        {"kubernetes_tpu/models/_seeded_fixture2.py": src})
    hs = [f for f in found if f.rule == "host-sync"]
    assert len(hs) == 1, found
    assert ":6" in hs[0].where  # helper's .item(), not host_driver's


def test_lint_package_wide_rules_fire():
    src = '''\
import threading
from kubernetes_tpu.metrics import Counter


def f(x=[]):
    try:
        pass
    except:
        pass
    threading.Thread(target=f).start()
    return Counter("loose_total", "constructed outside the registry")
'''
    found = lint.lint_sources({"kubernetes_tpu/client/_seeded3.py": src})
    rules = {f.rule for f in found}
    assert {"mutable-default", "bare-except", "nondaemon-thread",
            "metric-outside-registry"} <= rules, found


def test_lint_syntax_error_is_a_finding_not_a_crash():
    found = lint.lint_sources({
        "kubernetes_tpu/models/_broken.py": "def f(:\n",
        "kubernetes_tpu/models/_fine.py": "x = 1\n",
    })
    se = [f for f in found if f.rule == "syntax-error"]
    assert len(se) == 1 and "_broken.py" in se[0].where, found


def test_lint_impure_traced_rules_fire():
    src = '''\
import time

import jax


def _traced_body(x):
    t = time.time()  # seeded impurity
    print("trace me")
    return x


def run(x):
    return jax.jit(_traced_body)(x)
'''
    found = lint.lint_sources(
        {"kubernetes_tpu/ops/_seeded4.py": src})
    impure = [f for f in found if f.rule == "traced-impure"]
    assert len(impure) == 2, found


# -- pass 3: runtime sanitizers ----------------------------------------------


def _fake_component():
    """Locks created from a module whose __name__ is inside the
    package, so the instrumented factories track them."""
    mod = types.ModuleType("kubernetes_tpu._seeded_locks")
    sys.modules["kubernetes_tpu._seeded_locks"] = mod
    src = ("import threading\n"
           "def make_a():\n    return threading.Lock()\n"
           "def make_b():\n    return threading.Lock()\n")
    exec(compile(src, "_seeded_locks.py", "exec"), mod.__dict__)
    return mod


def test_seeded_lock_order_inversion_is_flagged():
    mod = _fake_component()
    locks.GRAPH.reset()
    with locks.instrumented():
        a, b = mod.make_a(), mod.make_b()

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        for fn in (t1, t2):
            th = threading.Thread(target=fn)
            th.start()
            th.join()
    try:
        cycles = locks.GRAPH.cycles()
        assert cycles, "inversion not detected"
        with pytest.raises(AssertionError, match="lock-order"):
            locks.assert_no_cycles("(seeded)")
    finally:
        locks.GRAPH.reset()  # never leak the seeded cycle into chaos


def test_consistent_lock_order_stays_clean():
    mod = _fake_component()
    locks.GRAPH.reset()
    with locks.instrumented():
        a, b = mod.make_a(), mod.make_b()
        for _ in range(3):
            with a:
                with b:
                    pass
        with a:
            pass
        with b:
            pass
    assert not locks.GRAPH.cycles()
    locks.assert_no_cycles("(ordered)")


def test_reentrant_rlock_is_not_a_cycle():
    mod = _fake_component()
    src = ("import threading\n"
           "def make_r():\n    return threading.RLock()\n")
    exec(compile(src, "_seeded_locks.py", "exec"), mod.__dict__)
    locks.GRAPH.reset()
    with locks.instrumented():
        r = mod.make_r()
        with r:
            with r:  # re-entrant: no self-edge
                pass
    assert not locks.GRAPH.cycles()


def test_untracked_modules_get_raw_locks():
    with locks.instrumented():
        lk = threading.Lock()  # caller: tests/, not kubernetes_tpu
    assert not isinstance(lk, locks.TrackedLock)


def test_compile_sentinel_catches_steady_state_compiles():
    sentinel = CompileSentinel()
    f = jax.jit(lambda x: x * 2 + 1)
    f(jnp.ones(7))  # compile happens OUTSIDE the guarded window
    with sentinel.expect_no_compiles("warm replay"):
        f(jnp.ones(7))
    with pytest.raises(AssertionError, match="recompilation"):
        with sentinel.expect_no_compiles("cold"):
            jax.jit(lambda x: x * 3 - 1)(jnp.ones(7))


# -- the CLI gate -------------------------------------------------------------


def test_cli_lint_gate_exits_zero():
    from kubernetes_tpu.analysis.__main__ import main

    assert main(["--lint-only"]) == 0


def test_findings_report_shape():
    rep = render_report([
        Finding("lint", "host-sync", "a.py:1", "x", suppressed=False),
        Finding("lint", "host-sync", "b.py:2", "y", suppressed=True),
    ], "t:")
    assert "1 finding(s), 1 suppressed" in rep
    assert "a.py:1" in rep
    # suppressed rows stay visible, marked — allowance drift is
    # auditable from the report itself
    assert "[suppressed lint/host-sync] b.py:2" in rep


def test_nondaemon_thread_rule_ignores_path_and_str_joins():
    """os.path.join / ', '.join must NOT satisfy the thread-join
    heuristic — only a plausible Thread.join() does."""
    base = '''\
import os
import threading


def f():
    p = os.path.join("a", "b")
    s = ", ".join(["x", "y"])
    threading.Thread(target=print).start()
    return p, s
'''
    found = lint.lint_sources({"kubernetes_tpu/client/_seeded5.py": base})
    assert any(f.rule == "nondaemon-thread" for f in found), found
    joined = base.replace(
        "    return p, s",
        "    t = threading.Thread(target=print)\n"
        "    t.start()\n"
        "    t.join()\n"
        "    return p, s",
    )
    found2 = lint.lint_sources(
        {"kubernetes_tpu/client/_seeded5.py": joined})
    assert not any(f.rule == "nondaemon-thread" for f in found2), found2
