"""Controller-manager loops against an in-process control plane — the
reference's integration-test idiom (test/integration + controller unit
suites): real apiserver + watch plumbing, controllers converging
actual -> desired, no kubelets."""

import time

import pytest

from kubernetes_tpu.api.types import (
    Container,
    ContainerPort,
    DaemonSet,
    DaemonSetSpec,
    Deployment,
    DeploymentSpec,
    HorizontalPodAutoscaler,
    HorizontalPodAutoscalerSpec,
    Job,
    JobSpec,
    LabelSelector,
    Namespace,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    PetSet,
    PetSetSpec,
    Pod,
    PodCondition,
    PodSpec,
    PodTemplateSpec,
    ReplicationController,
    ReplicationControllerSpec,
    ReplicaSet,
    ReplicaSetSpec,
    ResourceQuota,
    ResourceQuotaSpec,
    Service,
    ServicePort,
    ServiceSpec,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.controller.autoscale import (
    HorizontalController,
    ResourceQuotaController,
)
from kubernetes_tpu.controller.daemonset import DaemonSetsController
from kubernetes_tpu.controller.deployment import DeploymentController
from kubernetes_tpu.controller.endpoints import EndpointsController
from kubernetes_tpu.controller.framework import SharedInformerFactory
from kubernetes_tpu.controller.gc import NamespaceController, PodGCController
from kubernetes_tpu.controller.job import JobController
from kubernetes_tpu.controller.manager import (
    ControllerManager,
    ControllerManagerOptions,
)
from kubernetes_tpu.controller.node_lifecycle import NodeLifecycleController
from kubernetes_tpu.controller.petset import PetSetController
from kubernetes_tpu.controller.replication import (
    ReplicationManager,
    new_replicaset_manager,
)


from conftest import wait_until  # noqa: E402


@pytest.fixture()
def plane():
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    informers = SharedInformerFactory(client)
    started = []

    def start(*controllers):
        informers.start()
        informers.wait_for_sync()
        for c in controllers:
            c.run()
            started.append(c)
        return controllers

    yield server, client, informers, start
    for c in started:
        try:
            c.stop()
        except Exception:
            pass
    informers.stop()


def template(labels, cpu="100m"):
    return PodTemplateSpec(
        metadata=ObjectMeta(labels=dict(labels)),
        spec=PodSpec(containers=[Container(requests={"cpu": cpu})]),
    )


def pods_of(client, ns="default"):
    return client.pods(ns).list()[0]


def update_spec(client, resource, name, mutate, ns="default", attempts=20):
    """GET-mutate-PUT with conflict retry (controllers race status writes
    onto the same object; real clients retry exactly like this)."""
    from kubernetes_tpu.client.rest import APIStatusError

    rc = client.resource(resource, ns)
    for _ in range(attempts):
        obj = rc.get(name)
        mutate(obj)
        try:
            return rc.update(obj)
        except APIStatusError as e:
            if e.code != 409:
                raise
            time.sleep(0.02)
    raise AssertionError(f"update of {resource}/{name} kept conflicting")


# --- ReplicationController / ReplicaSet -------------------------------------


def test_rc_scales_up_and_down(plane):
    server, client, informers, start = plane
    rcm = ReplicationManager(client, informers)
    start(rcm)
    rc = ReplicationController(
        metadata=ObjectMeta(name="web"),
        spec=ReplicationControllerSpec(
            replicas=3, selector={"app": "web"}, template=template({"app": "web"})
        ),
    )
    client.resource("replicationcontrollers", "default").create(rc)
    assert wait_until(lambda: len(pods_of(client)) == 3)
    # status converges
    assert wait_until(
        lambda: client.resource("replicationcontrollers", "default")
        .get("web")
        .status.replicas
        == 3
    )
    # scale down to 1: the two newest/pending pods are the victims
    update_spec(client, "replicationcontrollers", "web",
                lambda rc: setattr(rc.spec, "replicas", 1))
    assert wait_until(lambda: len(pods_of(client)) == 1)
    # deleted pods are replaced (reconciliation, not one-shot)
    client.pods().delete(pods_of(client)[0].metadata.name)
    assert wait_until(lambda: len(pods_of(client)) == 1)


def test_replicaset_label_selector(plane):
    server, client, informers, start = plane
    rsm = new_replicaset_manager(client, informers)
    start(rsm)
    rs = ReplicaSet(
        metadata=ObjectMeta(name="web-rs"),
        spec=ReplicaSetSpec(
            replicas=2,
            selector=LabelSelector(match_labels={"app": "web"}),
            template=template({"app": "web"}),
        ),
    )
    client.resource("replicasets", "default").create(rs)
    assert wait_until(lambda: len(pods_of(client)) == 2)


# --- Endpoints ---------------------------------------------------------------


def _make_running(client, pod, ip, ready=True):
    pod.status.phase = "Running"
    pod.status.pod_ip = ip
    if ready:
        pod.status.conditions = [PodCondition(type="Ready", status="True")]
    client.pods(pod.metadata.namespace).update_status(pod)


def test_endpoints_controller(plane):
    server, client, informers, start = plane
    epc = EndpointsController(client, informers)
    start(epc)
    client.resource("services", "default").create(
        Service(
            metadata=ObjectMeta(name="web"),
            spec=ServiceSpec(
                selector={"app": "web"},
                ports=[ServicePort(name="http", port=80, target_port=8080)],
            ),
        )
    )
    pod = Pod(
        metadata=ObjectMeta(name="w1", labels={"app": "web"}),
        spec=PodSpec(
            node_name="n1",
            containers=[Container(ports=[ContainerPort(container_port=8080)])],
        ),
    )
    client.pods().create(pod)
    _make_running(client, client.pods().get("w1"), "10.0.0.1")

    def eps_ips():
        try:
            eps = client.resource("endpoints", "default").get("web")
        except Exception:
            return []
        return [a.ip for s in eps.subsets for a in s.addresses]

    assert wait_until(lambda: eps_ips() == ["10.0.0.1"])
    eps = client.resource("endpoints", "default").get("web")
    assert eps.subsets[0].ports[0].port == 8080
    # pod deleted -> endpoints drain
    client.pods().delete("w1")
    assert wait_until(lambda: eps_ips() == [])


# --- Job ---------------------------------------------------------------------


def test_job_runs_to_completion(plane):
    server, client, informers, start = plane
    jc = JobController(client, informers)
    start(jc)
    job = Job(
        metadata=ObjectMeta(name="batch1"),
        spec=JobSpec(
            parallelism=2,
            completions=3,
            selector=LabelSelector(match_labels={"job": "batch1"}),
            template=template({"job": "batch1"}),
        ),
    )
    client.resource("jobs", "default").create(job)
    assert wait_until(
        lambda: len(
            [p for p in pods_of(client) if p.status.phase == "Pending"]
        )
        == 2
    )
    # complete pods one by one; the controller backfills until 3 succeeded
    for _ in range(3):
        assert wait_until(
            lambda: any(p.status.phase == "Pending" for p in pods_of(client))
        )
        p = next(p for p in pods_of(client) if p.status.phase == "Pending")
        p.status.phase = "Succeeded"
        client.pods().update_status(p)
    assert wait_until(
        lambda: "Complete"
        in client.resource("jobs", "default").get("batch1").status.conditions
    )
    assert client.resource("jobs", "default").get("batch1").status.succeeded == 3


# --- Deployment --------------------------------------------------------------


def test_deployment_rolling_update(plane):
    server, client, informers, start = plane
    dc = DeploymentController(client, informers)
    rsm = new_replicaset_manager(client, informers)
    start(dc, rsm)
    d = Deployment(
        metadata=ObjectMeta(name="web"),
        spec=DeploymentSpec(
            replicas=3,
            selector=LabelSelector(match_labels={"app": "web"}),
            template=template({"app": "web"}),
        ),
    )
    client.resource("deployments", "default").create(d)
    assert wait_until(lambda: len(pods_of(client)) == 3, 15)
    first_rs = [
        rs for rs in client.resource("replicasets", "default").list()[0]
    ]
    assert len(first_rs) == 1

    # roll the template: a second RS appears, the old one drains to zero
    update_spec(client, "deployments", "web",
                lambda d: setattr(d.spec, "template", template({"app": "web"}, cpu="200m")))
    assert wait_until(
        lambda: len(client.resource("replicasets", "default").list()[0]) == 2, 15
    )
    assert wait_until(
        lambda: any(
            rs.spec.replicas == 0
            for rs in client.resource("replicasets", "default").list()[0]
        )
        and sum(
            rs.spec.replicas for rs in client.resource("replicasets", "default").list()[0]
        )
        == 3,
        20,
    )
    assert wait_until(
        lambda: sorted(
            p.spec.containers[0].requests.get("cpu", "")
            for p in pods_of(client)
        )
        == ["200m", "200m", "200m"],
        20,
    )


# --- DaemonSet ---------------------------------------------------------------


def ready_node(name, unschedulable=False):
    return Node(
        metadata=ObjectMeta(name=name),
        spec=NodeSpec(unschedulable=unschedulable),
        status=NodeStatus(
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )


def test_daemonset_one_pod_per_node(plane):
    server, client, informers, start = plane
    dsc = DaemonSetsController(client, informers)
    start(dsc)
    client.nodes().create(ready_node("n1"))
    client.nodes().create(ready_node("n2"))
    client.nodes().create(ready_node("cordoned", unschedulable=True))
    ds = DaemonSet(
        metadata=ObjectMeta(name="agent"),
        spec=DaemonSetSpec(
            selector=LabelSelector(match_labels={"ds": "agent"}),
            template=template({"ds": "agent"}),
        ),
    )
    client.resource("daemonsets", "default").create(ds)
    assert wait_until(
        lambda: sorted(p.spec.node_name for p in pods_of(client)) == ["n1", "n2"]
    )
    # a new node gets its daemon
    client.nodes().create(ready_node("n3"))
    assert wait_until(
        lambda: sorted(p.spec.node_name for p in pods_of(client))
        == ["n1", "n2", "n3"]
    )
    # status lands in a follow-up sync after the n3 pod create: poll
    assert wait_until(
        lambda: client.resource("daemonsets", "default")
        .get("agent").status.desired_number_scheduled == 3
    )


# --- GC + namespace ----------------------------------------------------------


def test_podgc_orphans_and_threshold(plane):
    server, client, informers, start = plane
    gc = PodGCController(client, informers, terminated_pod_threshold=1)
    informers.start()
    informers.wait_for_sync()
    client.nodes().create(ready_node("n1"))
    # orphan: bound to a node that does not exist
    orphan = Pod(metadata=ObjectMeta(name="orphan"),
                 spec=PodSpec(node_name="ghost", containers=[Container()]))
    client.pods().create(orphan)
    # two terminated pods; threshold 1 -> oldest collected
    for i, name in enumerate(["dead-old", "dead-new"]):
        p = Pod(metadata=ObjectMeta(name=name),
                spec=PodSpec(node_name="n1", containers=[Container()]))
        client.pods().create(p)
        p = client.pods().get(name)
        p.status.phase = "Failed"
        client.pods().update_status(p)
    # the GC reads the INFORMER view; wait until it has seen the phases
    assert wait_until(
        lambda: sum(
            1
            for p in informers.pods().store.list()
            if p.status.phase == "Failed"
        )
        == 2
        and len(informers.pods().store.list()) == 3
    )
    gc.gc_once()
    names = {p.metadata.name for p in pods_of(client)}
    assert "orphan" not in names
    assert len(names & {"dead-old", "dead-new"}) == 1


def test_namespace_lifecycle(plane):
    server, client, informers, start = plane
    nc = NamespaceController(client, informers)
    start(nc)
    client.resource("namespaces").create(Namespace(metadata=ObjectMeta(name="doomed")))
    client.pods("doomed").create(
        Pod(metadata=ObjectMeta(name="p1", namespace="doomed"),
            spec=PodSpec(containers=[Container()]))
    )
    client.resource("namespaces").delete("doomed")

    def gone():
        try:
            client.resource("namespaces").get("doomed")
            return False
        except Exception:
            return True

    assert wait_until(gone)
    assert pods_of(client, "doomed") == []


# --- node lifecycle ----------------------------------------------------------


def test_node_lifecycle_eviction(plane):
    server, client, informers, start = plane
    fake_now = [time.time()]
    nlc = NodeLifecycleController(
        client, informers,
        node_monitor_grace_period=40.0,
        pod_eviction_timeout=300.0,
        eviction_qps=1000.0,
        now=lambda: fake_now[0],
    )
    informers.start()
    informers.wait_for_sync()
    n = ready_node("flaky")
    n.status.conditions[0].last_heartbeat_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(fake_now[0])
    )
    client.nodes().create(n)
    client.pods().create(
        Pod(metadata=ObjectMeta(name="victim"),
            spec=PodSpec(node_name="flaky", containers=[Container()]))
    )
    assert wait_until(lambda: len(informers.nodes().store.list()) == 1)
    assert wait_until(lambda: len(informers.pods().store.list()) == 1)
    # within grace: nothing happens
    nlc.monitor_once()
    assert client.nodes().get("flaky").status.conditions[0].status == "True"
    # past grace: Ready -> Unknown
    fake_now[0] += 60
    nlc.monitor_once()
    assert wait_until(
        lambda: client.nodes().get("flaky").status.conditions[0].status
        == "Unknown"
    )
    # past eviction timeout: pods deleted
    fake_now[0] += 301
    assert wait_until(lambda: len(informers.nodes().store.list()) == 1)
    nlc.monitor_once()
    assert wait_until(lambda: pods_of(client) == [])


# --- HPA + quota -------------------------------------------------------------


def test_hpa_scales_rc(plane):
    server, client, informers, start = plane
    rcm = ReplicationManager(client, informers)
    utilization = [160.0]
    hpa_ctl = HorizontalController(
        client, informers, lambda ns, pods: utilization[0]
    )
    start(rcm)
    client.resource("replicationcontrollers", "default").create(
        ReplicationController(
            metadata=ObjectMeta(name="web"),
            spec=ReplicationControllerSpec(
                replicas=2, selector={"app": "web"},
                template=template({"app": "web"}),
            ),
        )
    )
    client.resource("horizontalpodautoscalers", "default").create(
        HorizontalPodAutoscaler(
            metadata=ObjectMeta(name="web-hpa"),
            spec=HorizontalPodAutoscalerSpec(
                scale_target_kind="ReplicationController",
                scale_target_name="web",
                min_replicas=1,
                max_replicas=10,
                target_cpu_utilization_percentage=80,
            ),
        )
    )
    assert wait_until(lambda: len(pods_of(client)) == 2)
    # reconcile_once syncs from the informer view; wait for the watch to
    # deliver the HPA first (the reference's loop just retries in 30s)
    assert wait_until(lambda: len(hpa_ctl.hpa_informer.store.list()) == 1)
    hpa_ctl.reconcile_once()
    # 160% of an 80% target -> double the replicas
    assert client.resource("replicationcontrollers", "default").get("web").spec.replicas == 4
    assert wait_until(lambda: len(pods_of(client)) == 4)
    # back within tolerance: no change
    utilization[0] = 82.0
    hpa_ctl.reconcile_once()
    assert client.resource("replicationcontrollers", "default").get("web").spec.replicas == 4


def test_resource_quota_usage(plane):
    server, client, informers, start = plane
    qc = ResourceQuotaController(client, informers)
    informers.start()
    informers.wait_for_sync()
    client.resource("resourcequotas", "default").create(
        ResourceQuota(
            metadata=ObjectMeta(name="quota"),
            spec=ResourceQuotaSpec(hard={"pods": "10", "requests.cpu": "2"}),
        )
    )
    for i in range(3):
        client.pods().create(
            Pod(metadata=ObjectMeta(name=f"q{i}"),
                spec=PodSpec(containers=[Container(requests={"cpu": "250m"})]))
        )
    assert wait_until(lambda: len(informers.pods().store.list()) == 3)
    qc.sync_once()
    status = client.resource("resourcequotas", "default").get("quota").status
    assert status.used["pods"] == "3"
    assert status.used["requests.cpu"] == "750m"


# --- PetSet ------------------------------------------------------------------


def test_petset_ordered_stable_identity(plane):
    server, client, informers, start = plane
    psc = PetSetController(client, informers)
    start(psc)
    client.resource("petsets", "default").create(
        PetSet(
            metadata=ObjectMeta(name="db"),
            spec=PetSetSpec(
                replicas=3,
                selector=LabelSelector(match_labels={"ps": "db"}),
                template=template({"ps": "db"}),
                service_name="db",
            ),
        )
    )
    assert wait_until(
        lambda: sorted(p.metadata.name for p in pods_of(client))
        == ["db-0", "db-1", "db-2"]
    )
    # scale down deletes the highest ordinal
    update_spec(client, "petsets", "db",
                lambda ps: setattr(ps.spec, "replicas", 2))
    assert wait_until(
        lambda: sorted(p.metadata.name for p in pods_of(client))
        == ["db-0", "db-1"]
    )


# --- the manager -------------------------------------------------------------


def test_controller_manager_starts_all(plane):
    server, client, informers, start = plane
    mgr = ControllerManager(client)
    mgr.start()
    try:
        client.nodes().create(ready_node("n1"))
        client.resource("replicationcontrollers", "default").create(
            ReplicationController(
                metadata=ObjectMeta(name="web"),
                spec=ReplicationControllerSpec(
                    replicas=2, selector={"app": "web"},
                    template=template({"app": "web"}),
                ),
            )
        )
        assert wait_until(lambda: len(pods_of(client)) == 2)
    finally:
        mgr.stop()


def test_controller_manager_leader_election():
    """controllermanager.go:142-170: two managers, one lease — only the
    leader runs loops; the standby takes over when the leader dies."""
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    opts = ControllerManagerOptions(
        leader_elect=True, enable=("replication",),
    )
    m1 = ControllerManager(client, opts).start()
    assert wait_until(lambda: m1.is_leader() and m1.informers._started)
    m2 = ControllerManager(client, ControllerManagerOptions(
        leader_elect=True, enable=("replication",))).start()
    time.sleep(0.5)
    assert not m2.informers._started  # standby idles without the lease
    client.resource("replicationcontrollers", "default").create(
        ReplicationController(
            metadata=ObjectMeta(name="web"),
            spec=ReplicationControllerSpec(
                replicas=2, selector={"app": "web"},
                template=template({"app": "web"}),
            ),
        )
    )
    assert wait_until(lambda: len(pods_of(client)) == 2)
    m1.stop()  # releases the lease: the standby acquires without
    # waiting out the 15s lease_duration
    assert not m1.lost_lease  # voluntary stop is not a lost lease
    assert wait_until(lambda: m2.informers._started)
    update_spec(client, "replicationcontrollers", "web",
                lambda rc: setattr(rc.spec, "replicas", 4))
    assert wait_until(lambda: len(pods_of(client)) == 4, timeout=30.0)
    m2.stop()


def test_service_and_route_controllers(plane):
    """servicecontroller.go + routecontroller.go against the fake cloud:
    LoadBalancer services get balancers spanning the nodes; nodes get pod
    CIDR routes; deletions tear both down."""
    from kubernetes_tpu.cloudprovider import FakeCloud
    from kubernetes_tpu.controller.cloud import RouteController, ServiceController

    server, client, informers, start = plane
    cloud = FakeCloud()
    sc = ServiceController(client, informers, cloud)
    rc = RouteController(client, informers, cloud)
    client.nodes().create(ready_node("n1"))
    client.nodes().create(ready_node("n2"))
    client.resource("services", "default").create(
        Service(
            metadata=ObjectMeta(name="lb"),
            spec=ServiceSpec(
                selector={"app": "web"},
                type="LoadBalancer",
                ports=[ServicePort(port=443)],
            ),
        )
    )
    informers.start()
    informers.wait_for_sync()
    assert wait_until(lambda: len(informers.nodes().store.list()) == 2)
    sc.sync_once()
    rc.sync_once()
    lbs = list(cloud.balancers.values())
    assert len(lbs) == 1
    assert lbs[0].ports == (443,) and lbs[0].hosts == ("n1", "n2")
    assert lbs[0].region == cloud.get_zone().region
    routes = cloud.list_routes("kubernetes")
    assert sorted(r.target_instance for r in routes) == ["n1", "n2"]
    assert all(r.destination_cidr.endswith("/24") for r in routes)
    # service deleted -> balancer torn down; node gone -> route removed
    client.resource("services", "default").delete("lb")
    client.nodes().delete("n2")
    assert wait_until(lambda: len(informers.nodes().store.list()) == 1)
    assert wait_until(
        lambda: len(informers.informer("services").store.list()) == 0
    )
    sc.sync_once()
    rc.sync_once()
    assert cloud.balancers == {}
    assert [r.target_instance for r in cloud.list_routes("kubernetes")] == ["n1"]
