"""kube-proxy: rule compilation from service/endpoints watches and the
round-robin/session-affinity dataplane (pkg/proxy)."""

import time

import pytest

from kubernetes_tpu.api.types import (
    EndpointAddress,
    EndpointPort,
    Endpoints,
    EndpointSubset,
    ObjectMeta,
    Service,
    ServicePort,
    ServiceSpec,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.proxy import Proxier


def wait_until(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture()
def plane():
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    proxier = Proxier(client, node_name="n1").run()
    yield server, client, proxier
    proxier.stop()


def _mk_service(client, affinity="None"):
    client.resource("services", "default").create(
        Service(
            metadata=ObjectMeta(name="web"),
            spec=ServiceSpec(
                selector={"app": "web"},
                cluster_ip="10.0.0.10",
                session_affinity=affinity,
                ports=[ServicePort(name="http", port=80, target_port=8080)],
            ),
        )
    )


def _mk_endpoints(client, ips):
    eps = Endpoints(
        metadata=ObjectMeta(name="web"),
        subsets=[
            EndpointSubset(
                addresses=[EndpointAddress(ip=ip) for ip in ips],
                ports=[EndpointPort(name="http", port=8080)],
            )
        ],
    )
    rc = client.resource("endpoints", "default")
    try:
        cur = rc.get("web")
        cur.subsets = eps.subsets
        rc.update(cur)
    except Exception:
        rc.create(eps)


def test_rules_follow_endpoints(plane):
    server, client, proxier = plane
    _mk_service(client)
    _mk_endpoints(client, ["10.1.0.1", "10.1.0.2"])

    def rule():
        for spn, r in proxier.rules.items():
            if spn.name == "web" and spn.port == "http":
                return r
        return None

    assert wait_until(lambda: rule() is not None and len(rule().endpoints) == 2)
    r = rule()
    assert r.cluster_ip == "10.0.0.10" and r.port == 80
    assert r.endpoints == (("10.1.0.1", 8080), ("10.1.0.2", 8080))
    # endpoint removal propagates
    _mk_endpoints(client, ["10.1.0.2"])
    assert wait_until(lambda: rule().endpoints == (("10.1.0.2", 8080),))


def test_round_robin_and_session_affinity(plane):
    server, client, proxier = plane
    _mk_service(client)
    _mk_endpoints(client, ["10.1.0.1", "10.1.0.2"])
    assert wait_until(
        lambda: any(
            len(r.endpoints) == 2 for r in proxier.rules.values()
        )
    )
    picks = {proxier.route("default", "web", "http")[0] for _ in range(4)}
    assert picks == {"10.1.0.1", "10.1.0.2"}  # round-robin alternates

    # ClientIP affinity pins a client to one endpoint
    svc = client.resource("services", "default").get("web")
    svc.spec.session_affinity = "ClientIP"
    client.resource("services", "default").update(svc)
    assert wait_until(
        lambda: any(
            r.session_affinity == "ClientIP" for r in proxier.rules.values()
        )
    )
    first = proxier.route("default", "web", "http", client_ip="1.2.3.4")
    for _ in range(5):
        assert proxier.route("default", "web", "http", client_ip="1.2.3.4") == first


def test_service_delete_drops_rules(plane):
    server, client, proxier = plane
    _mk_service(client)
    _mk_endpoints(client, ["10.1.0.1"])
    assert wait_until(lambda: len(proxier.rules) == 1)
    client.resource("services", "default").delete("web")
    assert wait_until(lambda: len(proxier.rules) == 0)
    with pytest.raises(LookupError):
        proxier.route("default", "web", "http")
