"""kube-proxy: rule compilation from service/endpoints watches and the
round-robin/session-affinity dataplane (pkg/proxy)."""

import time

import pytest

from kubernetes_tpu.api.types import (
    EndpointAddress,
    EndpointPort,
    Endpoints,
    EndpointSubset,
    ObjectMeta,
    Service,
    ServicePort,
    ServiceSpec,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.proxy import Proxier


from conftest import wait_until  # noqa: E402


@pytest.fixture()
def plane():
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    proxier = Proxier(client, node_name="n1").run()
    yield server, client, proxier
    proxier.stop()


def _mk_service(client, affinity="None", port=80):
    client.resource("services", "default").create(
        Service(
            metadata=ObjectMeta(name="web"),
            spec=ServiceSpec(
                selector={"app": "web"},
                cluster_ip="10.0.0.10",
                session_affinity=affinity,
                ports=[ServicePort(name="http", port=port, target_port=8080)],
            ),
        )
    )


def _free_port():
    """A fresh port per dataplane test: sequential tests reusing one
    service port trip over TIME_WAIT leftovers from the previous
    test's connections."""
    import socket as _socket

    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _mk_endpoints(client, ips):
    eps = Endpoints(
        metadata=ObjectMeta(name="web"),
        subsets=[
            EndpointSubset(
                addresses=[EndpointAddress(ip=ip) for ip in ips],
                ports=[EndpointPort(name="http", port=8080)],
            )
        ],
    )
    rc = client.resource("endpoints", "default")
    try:
        cur = rc.get("web")
        cur.subsets = eps.subsets
        rc.update(cur)
    except Exception:
        rc.create(eps)


def test_rules_follow_endpoints(plane):
    server, client, proxier = plane
    _mk_service(client)
    _mk_endpoints(client, ["10.1.0.1", "10.1.0.2"])

    def rule():
        for spn, r in proxier.rules.items():
            if spn.name == "web" and spn.port == "http":
                return r
        return None

    assert wait_until(lambda: rule() is not None and len(rule().endpoints) == 2)
    r = rule()
    assert r.cluster_ip == "10.0.0.10" and r.port == 80
    assert r.endpoints == (("10.1.0.1", 8080), ("10.1.0.2", 8080))
    # endpoint removal propagates
    _mk_endpoints(client, ["10.1.0.2"])
    assert wait_until(lambda: rule().endpoints == (("10.1.0.2", 8080),))


def test_round_robin_and_session_affinity(plane):
    server, client, proxier = plane
    _mk_service(client)
    _mk_endpoints(client, ["10.1.0.1", "10.1.0.2"])
    assert wait_until(
        lambda: any(
            len(r.endpoints) == 2 for r in proxier.rules.values()
        )
    )
    picks = {proxier.route("default", "web", "http")[0] for _ in range(4)}
    assert picks == {"10.1.0.1", "10.1.0.2"}  # round-robin alternates

    # ClientIP affinity pins a client to one endpoint
    svc = client.resource("services", "default").get("web")
    svc.spec.session_affinity = "ClientIP"
    client.resource("services", "default").update(svc)
    assert wait_until(
        lambda: any(
            r.session_affinity == "ClientIP" for r in proxier.rules.values()
        )
    )
    first = proxier.route("default", "web", "http", client_ip="1.2.3.4")
    for _ in range(5):
        assert proxier.route("default", "web", "http", client_ip="1.2.3.4") == first


def test_service_delete_drops_rules(plane):
    server, client, proxier = plane
    _mk_service(client)
    _mk_endpoints(client, ["10.1.0.1"])
    assert wait_until(lambda: len(proxier.rules) == 1)
    client.resource("services", "default").delete("web")
    assert wait_until(lambda: len(proxier.rules) == 0)
    with pytest.raises(LookupError):
        proxier.route("default", "web", "http")


# -- the userspace dataplane (pkg/proxy/userspace/proxier.go) ----------------


import socket
import socketserver
import threading

from kubernetes_tpu.proxy.userspace import UserspaceProxier


class _Echo(socketserver.BaseRequestHandler):
    def handle(self):
        while True:
            data = self.request.recv(4096)
            if not data:
                return
            self.request.sendall(b"%s:%s" % (
                self.server.tag.encode(), data))


def _backend(tag):
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _Echo)
    srv.daemon_threads = True
    srv.tag = tag
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _mk_endpoints_ports(client, backends):
    """One subset per backend so each address can carry its own port
    (real local listeners sit on distinct ephemeral ports)."""
    eps = Endpoints(
        metadata=ObjectMeta(name="web"),
        subsets=[
            EndpointSubset(
                addresses=[EndpointAddress(ip=ip)],
                ports=[EndpointPort(name="http", port=port)],
            )
            for ip, port in backends
        ],
    )
    rc = client.resource("endpoints", "default")
    try:
        cur = rc.get("web")
        cur.subsets = eps.subsets
        rc.update(cur)
    except Exception:
        rc.create(eps)




def _ready(proxier, n_eps=1):
    """Listener exists AND its rule has endpoints (the service event can
    land a beat before the endpoints event)."""
    addr = proxier.proxy_addr("default", "web", "http")
    if addr is None:
        return False
    return any(
        spn.name == "web" and len(r.endpoints) >= n_eps
        for spn, r in proxier.rules.items()
    )


@pytest.fixture()
def dataplane():
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    proxier = UserspaceProxier(client, node_name="n1").run()
    backends = [_backend("a"), _backend("b")]
    yield server, client, proxier, backends
    proxier.stop()
    for b in backends:
        b.shutdown()
        b.server_close()


def _call(addr, payload=b"ping"):
    with socket.create_connection(addr, timeout=5) as s:
        s.sendall(payload)
        return s.recv(4096)


def test_bytes_flow_client_vip_backend(dataplane):
    server, client, proxier, backends = dataplane
    _mk_service(client, port=_free_port())
    _mk_endpoints_ports(
        client, [("127.0.0.1", b.server_address[1]) for b in backends]
    )
    assert wait_until(lambda: _ready(proxier, 2))
    addr = proxier.proxy_addr("default", "web", "http")
    # the proxy claims the service's own port when free
    # (no NAT layer to translate), else an ephemeral one
    got = {_call(addr), _call(addr), _call(addr), _call(addr)}
    # real bytes flowed and round-robin hit both backends
    assert got == {b"a:ping", b"b:ping"}


def test_session_affinity_pins_backend(dataplane):
    server, client, proxier, backends = dataplane
    _mk_service(client, affinity="ClientIP", port=_free_port())
    _mk_endpoints_ports(
        client, [("127.0.0.1", b.server_address[1]) for b in backends]
    )
    assert wait_until(lambda: _ready(proxier, 2))
    addr = proxier.proxy_addr("default", "web", "http")
    got = {_call(addr) for _ in range(4)}
    assert len(got) == 1  # same client ip -> same endpoint every time


def test_endpoint_update_reroutes_live(dataplane):
    server, client, proxier, backends = dataplane
    _mk_service(client, port=_free_port())
    _mk_endpoints_ports(
        client, [("127.0.0.1", backends[0].server_address[1])]
    )
    assert wait_until(lambda: _ready(proxier))
    addr = proxier.proxy_addr("default", "web", "http")
    assert _call(addr) == b"a:ping"
    # endpoints change from watch: new connections reach the new backend
    _mk_endpoints_ports(
        client, [("127.0.0.1", backends[1].server_address[1])]
    )
    assert wait_until(lambda: any(
        r.endpoints == (("127.0.0.1", backends[1].server_address[1]),)
        for r in proxier.rules.values()
    ))
    assert _call(addr) == b"b:ping"


def test_no_endpoints_refuses_cleanly(dataplane):
    server, client, proxier, backends = dataplane
    _mk_service(client, port=_free_port())
    _mk_endpoints_ports(client, [])
    assert wait_until(
        lambda: proxier.proxy_addr("default", "web", "http") is not None
    )
    addr = proxier.proxy_addr("default", "web", "http")
    with socket.create_connection(addr, timeout=5) as s:
        s.sendall(b"ping")
        try:
            assert s.recv(4096) == b""  # dropped like a REJECT
        except ConnectionResetError:
            pass  # RST is the other honest REJECT shape


def _refused(addr, deadline=10.0):
    """True once a fresh connect to addr fails. Listener teardown is
    asynchronous in the kernel (gVisor's netstack especially): a connect
    racing close() can still complete the handshake and then see a FIN
    or RST, so a single immediate probe flakes — poll with a deadline
    until the refusal is observable."""
    end = time.time() + deadline
    while time.time() < end:
        try:
            with socket.create_connection(addr, timeout=5) as s:
                s.sendall(b"ping")
                s.recv(4096)  # half-open leftover: drain and re-probe
        except (socket.timeout, TimeoutError):
            pass  # accepting-but-silent is NOT refusal: keep probing
        except OSError:
            return True
        time.sleep(0.05)
    return False


def test_service_delete_closes_listener(dataplane):
    server, client, proxier, backends = dataplane
    _mk_service(client, port=_free_port())
    _mk_endpoints_ports(
        client, [("127.0.0.1", backends[0].server_address[1])]
    )
    assert wait_until(lambda: _ready(proxier))
    addr = proxier.proxy_addr("default", "web", "http")
    client.resource("services", "default").delete("web")
    assert wait_until(
        lambda: proxier.proxy_addr("default", "web", "http") is None
    )
    # the listener must become unreachable (not merely be unreachable on
    # the first probe — that races the kernel's asynchronous close)
    assert _refused(addr), "deleted service's listener still accepting"


def test_udp_echo_through_proxy():
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    proxier = UserspaceProxier(client, udp_idle_timeout=0.25).run()
    try:
        usock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        usock.bind(("127.0.0.1", 0))

        def udp_echo():
            while True:
                try:
                    data, addr = usock.recvfrom(4096)
                except OSError:
                    return
                usock.sendto(b"u:" + data, addr)

        threading.Thread(target=udp_echo, daemon=True).start()
        client.resource("services", "default").create(
            Service(
                metadata=ObjectMeta(name="dns"),
                spec=ServiceSpec(
                    cluster_ip="10.0.0.53",
                    ports=[ServicePort(name="dns", port=10053,
                                       protocol="UDP")],
                ),
            )
        )
        eps = Endpoints(
            metadata=ObjectMeta(name="dns"),
            subsets=[EndpointSubset(
                addresses=[EndpointAddress(ip="127.0.0.1")],
                ports=[EndpointPort(name="dns", port=usock.getsockname()[1],
                                    protocol="UDP")],
            )],
        )
        client.resource("endpoints", "default").create(eps)
        assert wait_until(
            lambda: proxier.proxy_addr("default", "dns", "dns") is not None
        )
        addr = proxier.proxy_addr("default", "dns", "dns")
        c = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        # UDP: no delivery guarantee even on loopback — under full-suite
        # load a 1-core box can starve the relay thread past a single
        # receive window, so retry the datagram a few times
        c.settimeout(5)
        data = None
        for _ in range(4):
            c.sendto(b"hello", addr)
            try:
                data, _ = c.recvfrom(4096)
                break
            except socket.timeout:
                continue
        assert data == b"u:hello"
        c.close()
        usock.close()
    finally:
        proxier.stop()
