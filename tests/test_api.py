"""Unit tests for the core API layer (quantities, selectors, helpers).

Scenario tables here are re-derived from the reference's test intent
(pkg/api/resource/quantity_test.go, pkg/labels/selector_test.go idioms).
The scheduler's own tables are ported verbatim as the independent
conformance ground truth — see tests/corpus/ + tests/test_corpus.py.
"""

import pytest

from kubernetes_tpu.api import labels as lab
from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.api.types import (
    Container,
    Pod,
    PodSpec,
    ObjectMeta,
    pod_nonzero_request,
    pod_resource_request,
)


@pytest.mark.parametrize(
    "s,value,milli",
    [
        ("100m", 1, 100),
        ("1", 1, 1000),
        ("0", 0, 0),
        ("500Mi", 500 * 1024 * 1024, 500 * 1024 * 1024 * 1000),
        ("1Gi", 1024**3, 1024**3 * 1000),
        ("4", 4, 4000),
        ("2.5", 3, 2500),  # Value() rounds up
        ("1e3", 1000, 10**6),
        ("5G", 5 * 10**9, 5 * 10**12),
        ("110", 110, 110000),
        ("250m", 1, 250),
        ("32Gi", 32 * 1024**3, 32 * 1024**3 * 1000),
    ],
)
def test_quantity_parse(s, value, milli):
    q = parse_quantity(s)
    assert q.value() == value
    assert q.milli_value() == milli


def test_quantity_negative_rounds_away_from_zero():
    assert parse_quantity("-2.5").value() == -3
    assert parse_quantity("-100m").milli_value() == -100


def test_selector_ops():
    labels = {"env": "prod", "tier": "web", "num": "3"}
    assert lab.new_requirement("env", lab.IN, ["prod", "dev"]).matches(labels)
    assert not lab.new_requirement("env", lab.IN, ["dev"]).matches(labels)
    assert lab.new_requirement("missing", lab.NOT_IN, ["x"]).matches(labels)
    assert lab.new_requirement("env", lab.NOT_IN, ["dev"]).matches(labels)
    assert not lab.new_requirement("env", lab.NOT_IN, ["prod"]).matches(labels)
    assert lab.new_requirement("tier", lab.EXISTS, []).matches(labels)
    assert not lab.new_requirement("zzz", lab.EXISTS, []).matches(labels)
    assert lab.new_requirement("zzz", lab.DOES_NOT_EXIST, []).matches(labels)
    assert lab.new_requirement("num", lab.GT, ["2"]).matches(labels)
    assert not lab.new_requirement("num", lab.GT, ["3"]).matches(labels)
    assert lab.new_requirement("num", lab.LT, ["4"]).matches(labels)
    # Gt with non-numeric label value -> no match
    assert not lab.new_requirement("env", lab.GT, ["2"]).matches(labels)
    # Gt with |values| != 1 -> no match
    assert not lab.Requirement("num", lab.GT, frozenset(["1", "2"])).matches(labels)


def test_selector_from_set_and_everything():
    assert lab.selector_from_set({}).matches({"a": "b"})
    assert lab.selector_from_set(None).matches({})
    s = lab.selector_from_set({"a": "b", "c": "d"})
    assert s.matches({"a": "b", "c": "d", "e": "f"})
    assert not s.matches({"a": "b"})
    assert not lab.nothing().matches({})


def _pod(requests_list, init_requests=()):
    return Pod(
        metadata=ObjectMeta(name="p"),
        spec=PodSpec(
            containers=[Container(requests=r) for r in requests_list],
            init_containers=[Container(requests=r) for r in init_requests],
        ),
    )


def test_pod_resource_request_sums_and_init_max():
    # predicates.go:355-374: sum of containers, max with init containers
    pod = _pod([{"cpu": "100m", "memory": "500Mi"}, {"cpu": "200m"}])
    assert pod_resource_request(pod) == (300, 500 * 1024**2, 0)
    pod = _pod(
        [{"cpu": "100m", "memory": "100Mi"}],
        init_requests=[{"cpu": "1", "memory": "50Mi"}, {"cpu": "50m", "memory": "900Mi"}],
    )
    mcpu, mem, gpu = pod_resource_request(pod)
    assert mcpu == 1000  # init container max beats sum
    assert mem == 900 * 1024**2
    assert gpu == 0


def test_pod_nonzero_request_defaults():
    # non_zero.go: absent key -> 100m/200Mi; explicit zero stays zero
    pod = _pod([{}])
    assert pod_nonzero_request(pod) == (100, 200 * 1024**2)
    pod = _pod([{"cpu": "0", "memory": "0"}])
    assert pod_nonzero_request(pod) == (0, 0)
    pod = _pod([{"cpu": "250m"}])
    assert pod_nonzero_request(pod) == (250, 200 * 1024**2)
    # init containers do not contribute (node_info.go calculateResource)
    pod = _pod([{}], init_requests=[{"cpu": "4"}])
    assert pod_nonzero_request(pod) == (100, 200 * 1024**2)
