"""Deterministic-simulation model checker: checker-internals tests.

The sim itself gets trusted only as far as these tests push it:
the seeded-bug corpus proves the invariants are not vacuous, the
fabrication tests prove each invariant actually fires on the state it
claims to guard, the fingerprint tests prove exhaustive-search pruning
is sound, and the replay tests prove an emitted counterexample is a
durable artifact, not a one-off.
"""

import pytest

from kubernetes_tpu.analysis.sim import corpus
from kubernetes_tpu.analysis.sim.disk import SimDisk
from kubernetes_tpu.analysis.sim.explore import (explore_bfs,
                                                 explore_random)
from kubernetes_tpu.analysis.sim.harness import SimCluster, _PendingOp
from kubernetes_tpu.analysis.sim.invariants import (STEP_CHECKS,
                                                    acked_durability,
                                                    check_step,
                                                    config_serialization,
                                                    election_safety,
                                                    leader_completeness,
                                                    log_matching,
                                                    state_machine_safety)
from kubernetes_tpu.analysis.sim.net import SimNet
from kubernetes_tpu.analysis.sim.schedule import Schedule, replay, run
from kubernetes_tpu.harness.faults import FaultKind, FaultSpec
from kubernetes_tpu.storage.quorum import linearize
from kubernetes_tpu.storage.quorum.log import (KIND_CONFIG, KIND_DATA,
                                               Entry)

ELECT_A = corpus.ELECT_A


def _healthy_cluster():
    """Elected leader a, one committed+applied write on every node."""
    c = SimCluster(n=3, seed=0)
    for ev in ELECT_A + [
        ["propose", "a", "x", "v1"],
        ["replicate", "a", "b"], ["deliver", 5],
        ["replicate", "a", "c"], ["deliver", 6],
        ["replicate", "a", "b"], ["deliver", 7],
        ["replicate", "a", "c"], ["deliver", 8],
        ["apply", "a"], ["apply", "a"],
        ["apply", "b"], ["apply", "b"],
        ["apply", "c"], ["apply", "c"],
    ]:
        c.step(ev)
    assert c.nodes["a"].role == "leader"
    assert c.committed, "healthy prelude must commit"
    return c


# -- seeded-bug corpus (the checker's own regression gate) -------------------


class TestSeededBugCorpus:
    def test_quick_budget_finds_every_historical_bug(self):
        found = corpus.find_seeded_bugs()
        assert set(found) == {corpus.COMMIT_PAST_MATCH,
                              corpus.ACK_WITHOUT_ENTRY_CHECK,
                              corpus.BARRIER_BYPASS}
        missed = [n for n, s in found.items() if s is None]
        assert not missed, f"checker went blind to: {missed}"
        for name, sched in found.items():
            assert sched.violation, name

    def test_counterexamples_replay_deterministically(self):
        for name, sched in corpus.find_seeded_bugs().items():
            with corpus.mutate(name):
                first = replay(sched)
                second = replay(sched)
            assert first == second, name
            # every violation the finder recorded is re-found
            assert set(sched.violation) <= set(first), name

    def test_triggers_are_quiet_without_their_mutations(self):
        for name, events in corpus._TARGETED.items():
            assert run(Schedule(events=events)) == [], name

    def test_clean_tree_model_checks_quiet(self):
        assert corpus.check_clean() == []

    def test_mutation_restores_original_method(self):
        from kubernetes_tpu.storage.quorum.node import QuorumNode
        orig = QuorumNode._barrier_ready_locked
        with corpus.mutate(corpus.BARRIER_BYPASS):
            assert QuorumNode._barrier_ready_locked is not orig
        assert QuorumNode._barrier_ready_locked is orig

    @pytest.mark.slow
    def test_deep_budget_model_checks_quiet(self):
        # CI invocation (see build/ci.sh): the widened explorer pass
        assert corpus.check_clean(deep=True) == []
        assert explore_random(schedules=60, steps=100, seed=7) is None


# -- schedule files ----------------------------------------------------------


class TestScheduleFiles:
    def test_round_trip_preserves_everything(self, tmp_path):
        sched = Schedule(events=corpus.COMMIT_PAST_MATCH_EVENTS,
                         n=3, seed=4, replication_batch=2,
                         violation=["witness text"])
        path = sched.dump(str(tmp_path / "counterexample.json"))
        loaded = Schedule.load(path)
        assert loaded == sched

    def test_unknown_version_is_rejected(self):
        with pytest.raises(ValueError):
            Schedule.from_json('{"version": 99, "events": []}')

    def test_replay_is_bit_deterministic(self):
        sched = Schedule(events=corpus.ACK_WITHOUT_ENTRY_CHECK_EVENTS)
        assert run(sched) == run(sched) == []
        with corpus.mutate(corpus.ACK_WITHOUT_ENTRY_CHECK):
            a, b = run(sched), run(sched)
        assert a == b and a


# -- fingerprint soundness ---------------------------------------------------


class TestFingerprints:
    def test_convergent_paths_fingerprint_identically(self):
        # path B detours through duplicate-then-drop-the-duplicate,
        # which burns different message ids: the fingerprint must see
        # through schedule-local identifiers to the logical state
        a = SimCluster(n=3, seed=0)
        for ev in ELECT_A:
            a.step(ev)
        b = SimCluster(n=3, seed=0)
        for ev in [["tick", "a"], ["dup", 2], ["drop", 3],
                   ["deliver", 1], ["deliver", 4]]:
            b.step(ev)
        assert a.fingerprint() == b.fingerprint()
        a.close(), b.close()

    def test_distinct_states_fingerprint_differently(self):
        a = SimCluster(n=3, seed=0)
        b = SimCluster(n=3, seed=0)
        for ev in ELECT_A:
            a.step(ev)
        for ev in ELECT_A[:-1]:  # b's vote never delivered
            b.step(ev)
        assert a.fingerprint() != b.fingerprint()
        a.close(), b.close()

    def test_virtual_time_is_excluded(self):
        a = SimCluster(n=3, seed=0)
        fp = a.fingerprint()
        a.clock.advance(1000.0)
        assert a.fingerprint() == fp
        a.close()


# -- explorer bounding -------------------------------------------------------


class TestExplorer:
    def test_bfs_respects_depth_and_state_budget(self, monkeypatch):
        import kubernetes_tpu.analysis.sim.explore as ex
        seen = {"n": 0, "deepest": 0}
        orig = ex._run_prefix

        def spy(sched, events):
            seen["n"] += 1
            seen["deepest"] = max(seen["deepest"], len(events))
            return orig(sched, events)

        monkeypatch.setattr(ex, "_run_prefix", spy)
        assert ex.explore_bfs(max_depth=2, max_states=30) is None
        assert seen["deepest"] <= 2
        # every execution past the budget is one frontier drain, so
        # the count stays within budget * max branching, far from
        # unbounded
        assert seen["n"] < 30 * 20

    def test_bfs_counterexample_is_minimal(self):
        with corpus.mutate(corpus.BARRIER_BYPASS):
            found = explore_bfs(
                base=Schedule(events=[list(e) for e in ELECT_A]),
                max_depth=3, max_states=500)
        assert found is not None
        # depth 1 past the prelude: the barrier probe itself
        assert len(found.events) == len(ELECT_A) + 1

    def test_random_explorer_reaches_committed_writes(self):
        # a random explorer that never commits anything would check
        # nothing; the progress bias must keep walks productive
        sched = Schedule()
        cluster = sched.build_cluster()
        cluster.close()
        assert explore_random(schedules=6, steps=60, seed=3) is None


# -- fabricated violations: every invariant must actually fire ---------------


class TestInvariantSensitivity:
    def test_healthy_cluster_passes_every_check(self):
        c = _healthy_cluster()
        for chk in STEP_CHECKS:
            assert chk(c) == [], chk.__name__
        c.close()

    def test_election_safety_fires(self):
        c = _healthy_cluster()
        c.leaders_by_term.setdefault(1, set()).update({"a", "b"})
        assert election_safety(c)
        c.close()

    def test_log_matching_fires(self):
        c = _healthy_cluster()
        rl = c.nodes["b"].raft_log
        e = rl._entries[-1]
        rl._entries[-1] = Entry(e.term, e.index, b"tampered", e.kind)
        assert log_matching(c)
        c.close()

    def test_leader_completeness_fires(self):
        c = _healthy_cluster()
        idx = max(c.committed)
        c.committed[idx] = (c.committed[idx][0], b"ghost-write",
                            KIND_DATA)
        assert leader_completeness(c)
        c.close()

    def test_state_machine_safety_fires(self):
        c = _healthy_cluster()
        idx, payload = c.machines["b"].applied[-1]
        c.machines["b"].applied[-1] = (idx, payload + b"-forked")
        assert state_machine_safety(c)
        c.close()

    def test_acked_durability_fires(self):
        c = _healthy_cluster()
        op = linearize.Op(op_id=99, process="client-a", kind="write",
                          key="x", value="never-committed",
                          status=linearize.OK)
        fake = _PendingOp(op, "a", max(c.committed), 1)
        fake.done = True
        c.pending.append(fake)
        assert acked_durability(c)
        c.close()

    def test_config_serialization_fires(self):
        c = _healthy_cluster()
        rl = c.nodes["a"].raft_log
        nxt = rl.last_index
        rl._entries.extend([
            Entry(1, nxt + 1, b"cfg1", KIND_CONFIG),
            Entry(1, nxt + 2, b"cfg2", KIND_CONFIG),
        ])
        assert config_serialization(c)
        c.close()

    def test_commit_bound_witness_drains_once(self):
        c = _healthy_cluster()
        c.witnesses.append("fabricated: witness")
        found = check_step(c)
        assert "fabricated: witness" in found
        assert check_step(c) == []  # drained, not re-reported
        c.close()


# -- shared fault vocabulary -------------------------------------------------


class TestFaultVocabulary:
    def test_simnet_applies_standing_faults(self):
        net = SimNet()
        net.apply(FaultSpec(FaultKind.PARTITION, ("a",), ("b", "c")),
                  ["a", "b", "c"])
        assert ("a", "b") in net.blocked and ("c", "a") in net.blocked
        net.apply(FaultSpec(FaultKind.HEAL, (), ()), ["a", "b", "c"])
        assert not net.blocked

    def test_simnet_rejects_non_network_faults(self):
        with pytest.raises(ValueError):
            SimNet().apply(FaultSpec(FaultKind.CRASH, ("a",), ()),
                           ["a", "b", "c"])

    def test_schedule_fault_events_use_the_shared_enum(self):
        # every fault verb a schedule may carry parses as a FaultKind
        for ev in (corpus.ACK_WITHOUT_ENTRY_CHECK_EVENTS):
            if ev[0] == "fault":
                assert FaultKind(ev[1]) in FaultKind

    def test_crash_and_recover_round_trip(self):
        c = _healthy_cluster()
        committed_before = dict(c.committed)
        c.step(["fault", "crash", ["b"], [], 0.0])
        assert "b" in c.crashed and "b" not in c.nodes
        c.step(["fault", "recover", ["b"], [], 0.0])
        assert "b" in c.nodes
        assert check_step(c) == []
        # b recovered from its fsync'd disk: no committed entry lost
        rl = c.nodes["b"].raft_log
        for idx, (term, payload, kind) in committed_before.items():
            e = rl.entry(idx)
            assert e is not None and e.term == term \
                and bytes(e.payload) == payload
        c.close()


# -- sim disk crash model ----------------------------------------------------


class TestSimDiskCrash:
    def test_buffered_flushed_synced_layers(self):
        disk = SimDisk()
        disk.makedirs("/d")
        h = disk.open("/d/f", "wb")
        h.write(b"AAAA")
        h.flush()
        disk.fsync(h)      # synced: 4
        h.write(b"BBBB")
        h.flush()          # flushed but unsynced: torn region
        h.write(b"CC")     # buffered: always lost
        disk.crash("/d/", torn=0.5)
        data = disk.read_bytes("/d/f")
        assert data == b"AAAABB"  # synced + half the torn region
        assert disk._synced["/d/f"] == 4

    def test_replace_is_atomic_and_durable(self):
        disk = SimDisk()
        disk.makedirs("/d")
        with disk.open("/d/tmp", "wb") as h:
            h.write(b"NEW")
            disk.fsync(h)
        disk.replace("/d/tmp", "/d/f")
        disk.crash("/d/", torn=0.0)
        assert disk.read_bytes("/d/f") == b"NEW"
        assert not disk.exists("/d/tmp")
