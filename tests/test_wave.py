"""Wave fast-path conformance: the run-splitting driver (models/wave.py)
must be bit-identical to the serial scan — and therefore to the oracle —
on any backlog, fast-pathing eligible runs and falling back for the
rest with exact carry handoff.

The replay's float formulas and the selectHost round-robin are the risky
parts; fixtures here are tie-heavy (identical nodes), fill nodes to
capacity mid-run (fit-set changes → normalizer rebuilds), and mix
eligible runs with ineligible pods (volumes, inter-pod terms)."""

import copy
import random

import numpy as np
import pytest

from kubernetes_tpu.api.types import (
    Container,
    ContainerPort,
    Node,
    NodeCondition,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    Service,
    ServiceSpec,
    Taint,
    NodeSpec,
)
from kubernetes_tpu.models.batch import BatchScheduler, SchedulerConfig
from kubernetes_tpu.oracle import ClusterState, GenericScheduler
from kubernetes_tpu.scheduler.tpu_algorithm import TPUScheduleAlgorithm
from kubernetes_tpu.snapshot.encode import SnapshotEncoder, pod_feature_key

from tests.test_conformance import (
    ORACLE_PREDICATES,
    ORACLE_PRIORITIES,
    random_scenario,
)


def oracle_backlog(state, pending):
    oracle = GenericScheduler(
        predicates=ORACLE_PREDICATES, priorities=ORACLE_PRIORITIES
    )
    return oracle.schedule_backlog(pending, state.clone())


def wave_backlog(state, pending, min_run=1):
    algo = TPUScheduleAlgorithm(min_run=min_run)
    return algo.schedule_backlog(pending, state)


def clone_named(pod: Pod, name: str) -> Pod:
    out = copy.deepcopy(pod)
    out.metadata.name = name
    return out


def density_nodes(n, pods_cap="110", cpu="4", mem="32Gi", taint_every=0):
    nodes = []
    for i in range(n):
        spec = NodeSpec()
        if taint_every and i % taint_every == 0:
            spec = NodeSpec(
                taints=[Taint(key="dedicated", value="a",
                              effect="PreferNoSchedule")]
            )
        nodes.append(
            Node(
                metadata=ObjectMeta(name=f"node-{i:04d}"),
                spec=spec,
                status=NodeStatus(
                    allocatable={"cpu": cpu, "memory": mem, "pods": pods_cap},
                    conditions=[NodeCondition("Ready", "True")],
                ),
            )
        )
    return nodes


def pause_pods(k, labels=None, requests=None):
    labels = labels or {"name": "sched-perf"}
    requests = requests or {"cpu": "100m", "memory": "500Mi"}
    return [
        Pod(
            metadata=ObjectMeta(name=f"pod-{i:06d}", labels=dict(labels)),
            spec=PodSpec(containers=[Container(requests=dict(requests))]),
        )
        for i in range(k)
    ]


def test_feature_key_implies_identical_rows():
    rng = random.Random(1234)
    state, pending = random_scenario(
        rng, n_nodes=6, n_existing=8, n_pending=20,
        interpod_p=0.3, volumes_p=0.3,
    )
    # clones share the feature key with their template by construction;
    # the property under test is key-equality => row-equality
    pending = pending + [
        clone_named(p, f"{p.metadata.name}-x") for p in pending[::2]
    ]
    enc = SnapshotEncoder(state, pending)
    batch = enc.encode_pods()
    by_key = {}
    for i, p in enumerate(pending):
        by_key.setdefault(pod_feature_key(p), []).append(i)
    import dataclasses

    checked_groups = 0
    for rows in by_key.values():
        if len(rows) < 2:
            continue
        checked_groups += 1
        a = rows[0]
        for b in rows[1:]:
            for f in dataclasses.fields(batch):
                v = getattr(batch, f.name)
                if f.name == "pod_keys" or not isinstance(v, np.ndarray):
                    continue
                if v.ndim >= 1 and v.shape[0] == batch.num_pods:
                    assert np.array_equal(v[a], v[b]), (
                        f"rows {a},{b} differ in {f.name}"
                    )
    assert checked_groups >= 1  # the fixture produced at least one run


def test_wave_homogeneous_tie_heavy_matches_oracle():
    # 20 identical nodes (every pick is a 20-way tie at first), service
    # selecting all pods => dynamic SelectorSpread with maxCount changes
    nodes = density_nodes(20)
    pods = pause_pods(150)
    state = ClusterState.build(
        nodes,
        services=[Service(metadata=ObjectMeta(name="svc"),
                          spec=ServiceSpec(selector={"name": "sched-perf"}))],
    )
    assert wave_backlog(state, pods) == oracle_backlog(state, pods)


def test_wave_capacity_exhaustion_tail():
    # 5 nodes x 4 pods cap = 20 slots for 40 pods: nodes leave the fit
    # set mid-run and the tail must be unschedulable (None), with the
    # round-robin counter frozen once scheduling stops
    nodes = density_nodes(5, pods_cap="4")
    pods = pause_pods(40)
    state = ClusterState.build(
        nodes,
        services=[Service(metadata=ObjectMeta(name="svc"),
                          spec=ServiceSpec(selector={"name": "sched-perf"}))],
    )
    got = wave_backlog(state, pods)
    want = oracle_backlog(state, pods)
    assert got == want
    assert want[-1] is None and got.count(None) == 20


def test_wave_taints_and_fill_rebuilds():
    # PreferNoSchedule taints on every 3rd node make TaintToleration
    # normalize over a nonuniform count vector; tiny capacity forces
    # fit-set changes => per-event renormalization in the replay
    nodes = density_nodes(9, pods_cap="3", taint_every=3)
    pods = pause_pods(30)
    state = ClusterState.build(nodes)
    assert wave_backlog(state, pods) == oracle_backlog(state, pods)


def test_wave_cpu_bound_fill():
    # cpu exhausts before the pod-count cap: res_fit flips from the
    # resource side of the table
    nodes = density_nodes(4, pods_cap="110", cpu="1", mem="32Gi")
    pods = pause_pods(50, requests={"cpu": "250m", "memory": "100Mi"})
    state = ClusterState.build(nodes)
    got = wave_backlog(state, pods)
    want = oracle_backlog(state, pods)
    assert got == want
    assert got.count(None) == 50 - 4 * 4


def test_wave_host_port_self_conflict():
    # a host port means each node takes exactly one copy of the run
    nodes = density_nodes(6)
    pods = [
        Pod(
            metadata=ObjectMeta(name=f"pod-{i}", labels={"app": "p"}),
            spec=PodSpec(containers=[
                Container(requests={"cpu": "100m"},
                          ports=[ContainerPort(host_port=8080)])
            ]),
        )
        for i in range(10)
    ]
    state = ClusterState.build(nodes)
    got = wave_backlog(state, pods)
    want = oracle_backlog(state, pods)
    assert got == want
    assert got.count(None) == 4 and len(set(x for x in got if x)) == 6


def test_wave_reprobe_on_table_horizon():
    # max_j=16 forces the replay to bail at the table horizon and
    # re-probe with a fresh carry; output must still be identical
    from kubernetes_tpu.models.wave import WaveScheduler
    from kubernetes_tpu.snapshot.pad import next_pow2
    from kubernetes_tpu.parallel.mesh import _pad_snapshot

    nodes = density_nodes(3)
    pods = pause_pods(100, requests={"cpu": "10m", "memory": "10Mi"})
    state = ClusterState.build(nodes)
    want = oracle_backlog(state, pods)

    enc = SnapshotEncoder(state, [pods[0]])
    snap = enc.encode_nodes()
    batch = enc.encode_pods()
    snap_p = _pad_snapshot(snap, next_pow2(snap.num_nodes, 4))
    ws = WaveScheduler(min_run=1, max_j=16)
    chosen, _, _ = ws.schedule_backlog(
        snap_p, batch, np.zeros(len(pods), np.int64)
    )
    got = [snap.node_names[c] if 0 <= c < snap.num_nodes else None
           for c in chosen]
    assert got == want


@pytest.mark.parametrize("seed", range(6))
def test_wave_mixed_backlog_random(seed):
    # random heterogeneous scenario, then pending expanded into runs:
    # every pod is cloned 0-6 times in place — runs of identical pods
    # interleaved with singles, some ineligible (volumes/interpod)
    rng = random.Random(1000 + seed)
    state, pending = random_scenario(
        rng,
        n_nodes=8,
        n_existing=10,
        n_pending=10,
        interpod_p=0.25 if seed % 2 else 0.0,
        volumes_p=0.25 if seed >= 3 else 0.0,
    )
    backlog = []
    for i, p in enumerate(pending):
        for c in range(rng.randint(1, 7)):
            backlog.append(clone_named(p, f"{p.metadata.name}-c{c}"))
    want = oracle_backlog(state, backlog)
    got = wave_backlog(state, backlog)
    assert got == want, (
        f"seed {seed}: first divergence at "
        f"{next(i for i, (a, b) in enumerate(zip(want, got)) if a != b)}"
        f" of {len(backlog)}"
    )


@pytest.mark.parametrize("seed", range(20))
def test_replay_c_matches_spec_fuzz(seed):
    # synthetic RunTables stress the C engine's bucket/Fenwick/rebuild
    # machinery far beyond what end-to-end fixtures reach: plateaus,
    # score raises (Balanced can go up), deep ties, horizon bails
    from kubernetes_tpu.models.probe import RunTables
    from kubernetes_tpu.models.replay import (
        _load_lib,
        replay_fast,
        replay_spec,
    )

    if _load_lib() is None:
        pytest.skip("native/_replay.so not built")
    rng = np.random.default_rng(seed)
    N = int(rng.integers(1, 40))
    J = int(rng.integers(2, 20))
    K = int(rng.integers(1, 120))
    # mostly-flat tables maximize ties; occasional jumps exercise
    # bucket moves in both directions
    tab = rng.integers(0, 4, (J, N)).astype(np.int64)
    if rng.random() < 0.5:
        # blend a reversed copy in: more plateaus and non-monotone rows
        tab = np.maximum(tab, tab[::-1])
    tab = np.sort(tab, axis=0)[::-1].copy()  # mostly decreasing in j
    if rng.random() < 0.4:  # inject raises
        r0 = int(rng.integers(0, J))
        tab[r0] = tab[r0] + rng.integers(0, 3, N)
    t = RunTables(
        fit_static=rng.random(N) < 0.9,
        res_fit=(rng.random((J, N)) < 0.97).cumprod(axis=0).astype(bool),
        tab=tab,
        static_add=rng.integers(0, 3, N).astype(np.int64),
        w_spread=int(rng.integers(0, 3)),
        spread_base=(rng.integers(0, 4, N).astype(np.int64)
                     if rng.random() < 0.7 else None),
        spread_selfmatch=bool(rng.random() < 0.7),
        has_selectors=bool(rng.random() < 0.8),
        w_na=int(rng.integers(0, 3)),
        na_counts=(rng.integers(0, 6, N).astype(np.int64)
                   if rng.random() < 0.5 else None),
        w_tt=int(rng.integers(0, 3)),
        tt_counts=(rng.integers(0, 4, N).astype(np.int64)
                   if rng.random() < 0.5 else None),
        w_ip=int(rng.integers(0, 3)),
        ip_totals=(rng.integers(-5, 6, N).astype(np.int64)
                   if rng.random() < 0.4 else None),
    )
    L0 = int(rng.integers(0, 1000))
    spec = replay_spec(t, K, L0)
    fast = replay_fast(t, K, L0)
    assert fast.n_done == spec.n_done
    assert np.array_equal(fast.chosen, spec.chosen)
    assert np.array_equal(fast.counts, spec.counts)
    assert fast.last_node_index == spec.last_node_index
    assert fast.scheduled == spec.scheduled


def test_wave_min_run_fallback_matches():
    # with min_run above every run length, everything goes through the
    # scan fallback — the driver must still match (pure handoff test)
    nodes = density_nodes(5)
    pods = pause_pods(20)
    state = ClusterState.build(nodes)
    assert wave_backlog(state, pods, min_run=64) == oracle_backlog(state, pods)


ZONE = "failure-domain.beta.kubernetes.io/zone"


def zoned_density_nodes(n, zones=("a", "b", "c"), unzoned_every=0,
                        pods_cap="110"):
    nodes = density_nodes(n, pods_cap=pods_cap)
    for i, node in enumerate(nodes):
        if unzoned_every and i % unzoned_every == 0:
            continue  # leave some nodes without a zone (zone 0 path)
        node.metadata.labels[ZONE] = zones[i % len(zones)]
    return nodes


def spread_state(nodes):
    return ClusterState.build(
        nodes,
        services=[Service(metadata=ObjectMeta(name="svc"),
                          spec=ServiceSpec(selector={"name": "sched-perf"}))],
    )


def test_wave_zoned_spread_matches_oracle():
    # selector pods on a ZONED cluster stay on the fast path now: the
    # replay recomputes the 2/3 zone blend per pick
    # (selector_spreading.go:221-228)
    state = spread_state(zoned_density_nodes(18))
    pods = pause_pods(120)
    assert wave_backlog(state, pods) == oracle_backlog(state, pods)


def test_wave_zoned_spread_mixed_unzoned_nodes():
    # zone 0 (no label) never joins the blend; zoned and unzoned nodes
    # coexist in the same fit set
    state = spread_state(zoned_density_nodes(15, unzoned_every=3))
    pods = pause_pods(90)
    assert wave_backlog(state, pods) == oracle_backlog(state, pods)


def test_wave_zoned_capacity_exhaustion():
    # zones drain mid-run: nodes leave the fit set, per-zone counts
    # re-aggregate over the survivors, tail goes unschedulable
    state = spread_state(zoned_density_nodes(6, pods_cap="5"))
    pods = pause_pods(45)
    got = wave_backlog(state, pods)
    want = oracle_backlog(state, pods)
    assert got == want
    assert want[-1] is None


def test_wave_zoned_uneven_zone_sizes():
    # one big zone + one single-node zone: the blend must steer picks
    # toward the small zone exactly as the oracle does
    nodes = zoned_density_nodes(9, zones=("a",))
    nodes[-1].metadata.labels[ZONE] = "b"
    state = spread_state(nodes)
    pods = pause_pods(70)
    assert wave_backlog(state, pods) == oracle_backlog(state, pods)


@pytest.mark.parametrize("seed", range(12))
def test_wave_zoned_random_backlogs(seed):
    rng = random.Random(1000 + seed)
    zones = ["a", "b", "c", "d"][: rng.randint(1, 4)]
    nodes = zoned_density_nodes(
        rng.randint(4, 24), zones=tuple(zones),
        unzoned_every=rng.choice([0, 2, 3]),
        pods_cap=str(rng.randint(3, 30)),
    )
    state = spread_state(nodes)
    pods = pause_pods(rng.randint(20, 160))
    # a second distinct template exercises run switching on the
    # zoned path (separate probes, shared carry)
    pods += pause_pods(rng.randint(10, 40),
                       requests={"cpu": "200m", "memory": "1Gi"})
    for i, p in enumerate(pods):
        p.metadata.name = f"pod-{i:06d}"
    assert wave_backlog(state, pods) == oracle_backlog(state, pods)


def _anti_pods(k, labels, topo="kubernetes.io/hostname", name0=0,
               requests=None, sel_labels=None):
    from kubernetes_tpu.api.types import (
        Affinity, PodAffinityTerm, PodAntiAffinity, LabelSelector)
    import json
    out = []
    for i in range(k):
        p = Pod(
            metadata=ObjectMeta(name=f"anti-{name0 + i:05d}",
                                labels=dict(labels)),
            spec=PodSpec(containers=[Container(
                requests=dict(requests or {"cpu": "100m"}))]),
        )
        p.metadata.annotations = {
            "scheduler.alpha.kubernetes.io/affinity": json.dumps({
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [{
                        "labelSelector": {"matchLabels": sel_labels or dict(labels)},
                        "topologyKey": topo,
                        "namespaces": [],
                    }],
                },
            })
        }
        out.append(p)
    return out


def hostname_nodes(n, **kw):
    nodes = density_nodes(n, **kw)
    for node in nodes:
        node.metadata.labels["kubernetes.io/hostname"] = node.metadata.name
    return nodes


def test_wave_self_anti_one_per_node():
    # the config-3 pattern: a run of identical pods, each with hard
    # anti-affinity to its own labels on hostname topology — exactly one
    # lands per node, surplus goes unschedulable; the run must stay on
    # the fast path via the res_fit self-veto
    nodes = hostname_nodes(12)
    pods = _anti_pods(20, {"app": "exclusive"})
    state = ClusterState.build(nodes)
    got = wave_backlog(state, pods)
    want = oracle_backlog(state, pods)
    assert got == want
    placed = [h for h in got if h]
    assert len(placed) == len(set(placed)) == 12 and got.count(None) == 8


def test_wave_self_anti_carry_feeds_later_pods():
    # an eligible self-anti run FOLLOWED by pods of a different template
    # that match the run's anti selector: the committed copies' own
    # terms must veto them via the carry fold (the symmetric check)
    nodes = hostname_nodes(8)
    first = _anti_pods(6, {"tier": "a"})
    # same labels (so the first run's anti terms match them) but a
    # different resource shape => different run
    second = _anti_pods(6, {"tier": "a"}, name0=100,
                        requests={"cpu": "200m"})
    state = ClusterState.build(nodes)
    pods = first + second
    got = wave_backlog(state, pods)
    want = oracle_backlog(state, pods)
    assert got == want
    placed = [h for h in got if h]
    assert len(placed) == len(set(placed)) == 8  # 12 pods, 8 nodes, 1 each


def test_wave_nonself_anti_term_fold():
    # a run whose anti term matches OTHER labels only: no self-feedback
    # (fast-path eligible), but later pods carrying those labels must
    # see the committed copies' terms through the carry fold. The v1.3
    # quirk applies: the symmetric check only runs for candidates that
    # THEMSELVES have anti-affinity (predicates.go:884-921 is inside
    # the pod's own PodAntiAffinity branch), so the victims carry a
    # harmless anti term of their own to arm it.
    nodes = hostname_nodes(10)
    guards = _anti_pods(10, {"role": "guard"}, sel_labels={"role": "victim"})
    victims = _anti_pods(10, {"role": "victim"}, name0=200,
                         sel_labels={"role": "nobody"},
                         requests={"cpu": "50m"})
    state = ClusterState.build(nodes)
    pods = guards + victims
    got = wave_backlog(state, pods)
    want = oracle_backlog(state, pods)
    assert got == want
    # every node hosts a guard whose term matches the victims, and the
    # victims' own anti-affinity arms the symmetric check: none land
    assert got[:10].count(None) == 0 and got[10:].count(None) == 10


def test_wave_plain_pod_ignores_existing_anti_owner():
    # ...and the quirk itself: a pod with NO anti-affinity of its own
    # sails past an existing anti-owner whose term matches it
    nodes = hostname_nodes(3)
    guards = _anti_pods(3, {"role": "guard"}, sel_labels={"role": "plain"})
    plain = pause_pods(3, labels={"role": "plain"})
    for i, p in enumerate(plain):
        p.metadata.name = f"plain-{i:05d}"
    state = ClusterState.build(nodes)
    pods = guards + plain
    got = wave_backlog(state, pods)
    assert got == oracle_backlog(state, pods)
    assert got.count(None) == 0


def test_wave_self_anti_zone_topology_falls_back():
    # zone-topology self anti-affinity couples nodes: must NOT take the
    # fast path, and the scan fallback must still match the oracle
    nodes = zoned_density_nodes(9, zones=("a", "b", "c"))
    pods = _anti_pods(9, {"app": "zonal"}, topo=ZONE)
    state = ClusterState.build(nodes)
    got = wave_backlog(state, pods)
    want = oracle_backlog(state, pods)
    assert got == want
    assert got.count(None) == 6  # one per zone


@pytest.mark.parametrize("seed", range(8))
def test_wave_self_anti_mixed_random(seed):
    rng = random.Random(2000 + seed)
    nodes = hostname_nodes(rng.randint(5, 16),
                           pods_cap=str(rng.randint(2, 8)))
    pods = []
    pods += _anti_pods(rng.randint(16, 40), {"g": "x"})
    pods += pause_pods(rng.randint(10, 50))
    pods += _anti_pods(rng.randint(16, 30), {"g": "y"},
                       name0=500, requests={"cpu": "150m"})
    rng.shuffle(pods)
    # keep runs contiguous enough to fast-path: stable-sort by template
    pods.sort(key=lambda p: pod_feature_key(p))
    for i, p in enumerate(pods):
        p.metadata.name = f"pod-{i:06d}"
    state = ClusterState.build(nodes)
    assert wave_backlog(state, pods) == oracle_backlog(state, pods)


# -- service-member runs on the wave path (SA pin + SAA renormalization) -----


def _svc_policy(sa=True, saa=True, saa_weight=2):
    import json as _json

    from kubernetes_tpu.scheduler.policy import (
        load_policy, resolve_policy_tpu)

    preds = [{"name": "GeneralPredicates"}]
    if sa:
        preds.append({"name": "ZoneAffinity", "argument": {
            "serviceAffinity": {"labels": ["zone"]}}})
    prios = [{"name": "LeastRequestedPriority", "weight": 1}]
    if saa:
        prios.append({"name": "ZoneSpread", "weight": saa_weight,
                      "argument": {"serviceAntiAffinity": {
                          "label": "zone"}}})
    cfg = resolve_policy_tpu(load_policy(_json.dumps({
        "kind": "Policy", "predicates": preds, "priorities": prios,
    })), 1)
    assert cfg is not None
    return cfg


def _svc_oracle(state, pending, sa=True, saa=True, saa_weight=2):
    from kubernetes_tpu.oracle import predicates as opreds
    from kubernetes_tpu.oracle import priorities as oprios
    from kubernetes_tpu.oracle.scheduler import PriorityConfig

    preds = [("GeneralPredicates", opreds.general_predicates)]
    if sa:
        preds.append(
            ("ZoneAffinity", opreds.service_affinity_predicate(["zone"])))
    prios = [PriorityConfig(oprios.least_requested_priority, 1,
                            "LeastRequestedPriority")]
    if saa:
        prios.append(PriorityConfig(
            oprios.service_anti_affinity_priority("zone"), saa_weight,
            "ZoneSpread"))
    oracle = GenericScheduler(predicates=preds, priorities=prios)
    return oracle.schedule_backlog(pending, state.clone())


def _zone_nodes(n, zones=("za", "zb", "zc"), cap="110", unlabeled=0):
    nodes = []
    for i in range(n):
        labels = {"kubernetes.io/hostname": f"node-{i:04d}"}
        if i >= unlabeled:
            labels["zone"] = zones[i % len(zones)]
        nodes.append(Node(
            metadata=ObjectMeta(name=f"node-{i:04d}", labels=labels),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": cap},
                conditions=[NodeCondition("Ready", "True")],
            ),
        ))
    return nodes


def _member_state(nodes, existing=()):
    return ClusterState.build(
        nodes,
        assigned_pods=list(existing),
        services=[Service(metadata=ObjectMeta(name="app"),
                          spec=ServiceSpec(selector={"app": "x"}))],
    )


def _members(k, name0=0, cpu="100m"):
    out = pause_pods(k, labels={"app": "x"}, requests={"cpu": cpu})
    for i, p in enumerate(out):
        p.metadata.name = f"mem-{name0 + i:05d}"
    return out


def test_wave_service_affinity_first_pick_pins():
    """An unpinned member run: the FIRST commit pins the zone and the
    rest of the run (and later runs) must follow — the replay's
    sa_refine path, bit-identical to the oracle."""
    cfg = _svc_policy(sa=True, saa=False)
    nodes = _zone_nodes(9)
    state = _member_state(nodes)
    pods = _members(40)
    algo = TPUScheduleAlgorithm(config=cfg)
    got = algo.schedule_backlog(pods, state)
    want = _svc_oracle(state, pods, sa=True, saa=False)
    assert got == want
    zones = {n.metadata.name: n.metadata.labels["zone"] for n in nodes}
    assert len({zones[h] for h in got if h}) == 1  # all in the pin zone


def test_wave_service_affinity_existing_peer_pins():
    """A member already assigned pins BEFORE the run: fit is static and
    the run must stay on the fast path landing in the peer's zone."""
    cfg = _svc_policy(sa=True, saa=False)
    nodes = _zone_nodes(9)
    peer = _members(1, name0=900)[0]
    peer.spec.node_name = "node-0004"  # zone zb
    state = _member_state(nodes, existing=[peer])
    pods = _members(30)
    got = TPUScheduleAlgorithm(config=cfg).schedule_backlog(pods, state)
    want = _svc_oracle(state, pods, sa=True, saa=False)
    assert got == want
    zones = {n.metadata.name: n.metadata.labels["zone"] for n in nodes}
    assert {zones[h] for h in got if h} == {"zb"}


def test_wave_service_anti_affinity_spreads_values():
    """SAA only: member commits renormalize the per-value spread every
    pick (the replay's w_saa path)."""
    cfg = _svc_policy(sa=False, saa=True)
    nodes = _zone_nodes(9)
    state = _member_state(nodes)
    pods = _members(60)
    got = TPUScheduleAlgorithm(config=cfg).schedule_backlog(pods, state)
    want = _svc_oracle(state, pods, sa=False, saa=True)
    assert got == want
    zones = {n.metadata.name: n.metadata.labels["zone"] for n in nodes}
    per_zone = {}
    for h in got:
        per_zone[zones[h]] = per_zone.get(zones[h], 0) + 1
    assert max(per_zone.values()) - min(per_zone.values()) <= 1


def test_wave_service_member_and_plain_runs_interleave():
    """Member runs + non-member runs share the carry: the fold must
    record member commits exactly for the later runs' static fits."""
    cfg = _svc_policy(sa=True, saa=True)
    nodes = _zone_nodes(12, unlabeled=2)
    state = _member_state(nodes)
    pods = _members(30) + pause_pods(30, labels={"app": "y"},
                                     requests={"cpu": "50m"})
    for i, p in enumerate(pods[30:]):
        p.metadata.name = f"plain-{i:05d}"
    got = TPUScheduleAlgorithm(config=cfg).schedule_backlog(pods, state)
    want = _svc_oracle(state, pods, sa=True, saa=True)
    assert got == want


@pytest.mark.parametrize("seed", range(10))
def test_wave_service_runs_random(seed):
    rng = random.Random(3000 + seed)
    sa = rng.random() < 0.7
    saa = (not sa) or rng.random() < 0.7
    cfg = _svc_policy(sa=sa, saa=saa, saa_weight=rng.choice([1, 2]))
    nodes = _zone_nodes(rng.randint(4, 15),
                        zones=("za", "zb", "zc")[: rng.randint(1, 3)],
                        cap=str(rng.randint(3, 20)),
                        unlabeled=rng.choice([0, 0, 2]))
    existing = []
    if rng.random() < 0.5:
        peer = _members(1, name0=900)[0]
        peer.spec.node_name = nodes[rng.randrange(len(nodes))].metadata.name
        existing.append(peer)
    state = _member_state(nodes, existing=existing)
    pods = _members(rng.randint(20, 70))
    if rng.random() < 0.6:
        pods += _members(rng.randint(16, 30), name0=500, cpu="200m")
    got = TPUScheduleAlgorithm(config=cfg).schedule_backlog(pods, state)
    want = _svc_oracle(state, pods, sa=sa, saa=saa,
                       saa_weight=cfg.priorities[-1][1] if saa else 2)
    assert got == want


def test_wave_sa_unlabeled_peer_repins_falls_back():
    """The re-pin hazard (review repro): the group IS pinned but the
    peer sits on an UNLABELED node, so the zone stays unresolved and a
    mid-run commit to a lower-ord labeled node re-pins. The tables
    can't express that — the run must fall back to the scan and still
    match the oracle bit-for-bit."""
    cfg = _svc_policy(sa=True, saa=False)
    nodes = _zone_nodes(9, unlabeled=9)  # start all-unlabeled
    for i, n in enumerate(nodes[:8]):
        n.metadata.labels["zone"] = ("za", "zb", "zc")[i % 3]
    # node-0008 stays unlabeled; the existing peer lives there
    peer = _members(1, name0=900)[0]
    peer.spec.node_name = "node-0008"
    state = _member_state(nodes, existing=[peer])
    pods = _members(30)
    cold = TPUScheduleAlgorithm(config=cfg).schedule_backlog(pods, state)
    want = _svc_oracle(state, pods, sa=True, saa=False)
    assert cold == want


def test_wave_sa_unlabeled_nodes_unpinned_falls_back():
    """Unpinned group + partially-labeled cluster: the first pick might
    land on an unlabeled node and leave the label unresolved, so the
    first-pick refinement is not exact — fall back, match the oracle."""
    cfg = _svc_policy(sa=True, saa=False)
    nodes = _zone_nodes(9, unlabeled=3)
    state = _member_state(nodes)
    pods = _members(25)
    got = TPUScheduleAlgorithm(config=cfg).schedule_backlog(pods, state)
    want = _svc_oracle(state, pods, sa=True, saa=False)
    assert got == want


def test_wave_zoned_device_replay_equals_host_spec():
    """The device replay (models/zreplay, one lax.scan dispatch) and the
    host spec replay must produce identical decisions on zoned
    backlogs — both are compared to the oracle elsewhere; this pins
    them against each other directly, including a capacity-exhausted
    tail and an unzoned-node mix."""
    from kubernetes_tpu.models.replay import replay_spec

    nodes = zoned_density_nodes(14, zones=("a", "b"), unzoned_every=4,
                                pods_cap="7")
    state = spread_state(nodes)
    pods = pause_pods(120)  # 98 slots -> unschedulable tail
    dev = TPUScheduleAlgorithm()  # device replay for zoned runs
    host = TPUScheduleAlgorithm(replay=replay_spec)  # host opt-out
    got_dev = dev.schedule_backlog(pods, state.clone())
    got_host = host.schedule_backlog(pods, state.clone())
    assert got_dev == got_host
    assert got_dev == oracle_backlog(state, pods)
    assert got_dev.count(None) == 120 - 98


def test_wave_zoned_tainted_device_replay_matches_host():
    """The review's adversarial case: zoned cluster + PreferNoSchedule
    taints in play, where an integer rewrite of TaintToleration's
    (1.0 - c/mx)*10.0 double-rounding would diverge (mx=20, c=18 ->
    host 0, integer form 1). Pins device replay == host spec == oracle
    with live taint normalizers."""
    import json as _json

    from kubernetes_tpu.api.types import TAINTS_ANNOTATION, Toleration
    from kubernetes_tpu.models.replay import replay_spec

    nodes = zoned_density_nodes(8, zones=("a", "b"), pods_cap="40")
    # escalating intolerable PreferNoSchedule taint counts per node
    for i, node in enumerate(nodes):
        taints = [
            {"key": f"t{k}", "value": "v", "effect": "PreferNoSchedule"}
            for k in range(13 + i)
        ]
        node.metadata.annotations = {
            TAINTS_ANNOTATION: _json.dumps(taints)
        }
    state = spread_state(nodes)
    pods = pause_pods(90)
    for p in pods:
        p.spec.tolerations = [Toleration(key="t0", operator="Equal",
                                         value="v",
                                         effect="PreferNoSchedule")]
    got_dev = TPUScheduleAlgorithm().schedule_backlog(pods, state.clone())
    got_host = TPUScheduleAlgorithm(replay=replay_spec).schedule_backlog(
        pods, state.clone())
    want = oracle_backlog(state, pods)
    assert got_host == want
    assert got_dev == want


# -- grouped multi-run dispatch (fused wave groups) ---------------------------
#
# The grouped driver amortizes device round trips across DISTINCT
# templates: one header probe for K runs, host-rebuilt resource j-axes
# against the accumulating usage, one grouped fold. These fixtures hit
# every cross-run coupling channel the host adjustments must model
# exactly — resources, spread class counts, host ports — plus the
# channels that must BREAK grouping (own inter-pod terms), asserting
# bit-identity to the serial oracle throughout.


def template_pods(num_templates, per, labels=None, cpu0=50, mem_step=50,
                  name0=""):
    pods = []
    for t in range(num_templates):
        for i in range(per):
            pods.append(Pod(
                metadata=ObjectMeta(
                    name=f"{name0}tpl{t:03d}-{i:03d}",
                    labels=dict(labels or {"name": "sched-perf"}),
                ),
                spec=PodSpec(containers=[Container(requests={
                    "cpu": f"{cpu0 + t * 5}m",
                    "memory": f"{100 + (t % 7) * mem_step}Mi",
                })]),
            ))
    return pods


def test_wave_grouped_heterogeneous_spread_coupling():
    # 12 templates all selected by ONE service: every run's commits move
    # every later run's spread counts — the host class-count adjustment
    # path, live under the default provider config
    state = spread_state(density_nodes(15))
    pods = template_pods(12, 10)
    assert wave_backlog(state, pods) == oracle_backlog(state, pods)


def test_wave_grouped_resource_coupling_fills_nodes():
    # tight capacity: earlier runs' commits exhaust nodes mid-group, so
    # later runs' host-rebuilt res_fit/LR/BA tables must reflect the
    # accumulated usage exactly; tail goes unschedulable
    nodes = density_nodes(4, pods_cap="110", cpu="2", mem="4Gi")
    state = ClusterState.build(nodes)
    pods = template_pods(8, 15, cpu0=200, mem_step=100)
    got = wave_backlog(state, pods)
    want = oracle_backlog(state, pods)
    assert got == want
    assert None in want  # the fixture really does exhaust capacity


def test_wave_grouped_port_conflicts_across_runs():
    # three templates sharing a host port (distinct resources => distinct
    # runs): a node taken by run A's copy must reject runs B/C — the
    # cross-run port veto; a fourth portless template is unaffected
    nodes = density_nodes(6)
    pods = []
    for t in range(3):
        for i in range(4):
            pods.append(Pod(
                metadata=ObjectMeta(name=f"pp{t}-{i}",
                                    labels={"app": "p"}),
                spec=PodSpec(containers=[
                    Container(requests={"cpu": f"{100 + t * 50}m"},
                              ports=[ContainerPort(host_port=8080)])
                ]),
            ))
    pods += template_pods(1, 5, labels={"app": "free"}, cpu0=75,
                          name0="free-")
    state = ClusterState.build(nodes)
    got = wave_backlog(state, pods)
    want = oracle_backlog(state, pods)
    assert got == want
    port_hosts = [h for h in got[:12] if h]
    assert len(port_hosts) == len(set(port_hosts)) == 6  # one per node


def test_wave_grouped_zoned_multi_template():
    # many selector templates on a ZONED cluster ride the grouped DEVICE
    # dispatch (zreplay.run_group): one outer scan, carry threaded run
    # to run — the config-4 shape
    state = spread_state(zoned_density_nodes(12))
    pods = template_pods(6, 15)
    assert wave_backlog(state, pods) == oracle_backlog(state, pods)


def test_wave_grouped_zoned_capacity_tail():
    # zoned device group + capacity exhaustion inside the group
    state = spread_state(zoned_density_nodes(6, pods_cap="8"))
    pods = template_pods(5, 14)
    got = wave_backlog(state, pods)
    want = oracle_backlog(state, pods)
    assert got == want
    assert want[-1] is None


def test_wave_grouped_impure_run_breaks_group():
    # pure templates around an anti-affinity template (own terms =>
    # impure): the impure run must take the per-run path and its carry
    # fold must be visible to the later pure runs
    nodes = hostname_nodes(10)
    pods = template_pods(3, 8, labels={"g": "a"})
    pods += _anti_pods(8, {"g": "a"}, name0=500,
                       requests={"cpu": "300m"})
    pods += template_pods(3, 8, labels={"g": "a"}, cpu0=400,
                          name0="post-")
    state = ClusterState.build(nodes)
    assert wave_backlog(state, pods) == oracle_backlog(state, pods)


@pytest.mark.parametrize("seed", range(8))
def test_wave_grouped_random_templates(seed):
    # randomized multi-template backlogs: varying template counts, run
    # lengths, capacities, zones, services, host ports — grouped (host
    # AND device), single, and scan paths interleave; bit-identity to
    # the oracle throughout
    rng = random.Random(4000 + seed)
    zones = ["a", "b", "c"][: rng.randint(1, 3)]
    if rng.random() < 0.5:
        nodes = zoned_density_nodes(
            rng.randint(5, 20), zones=tuple(zones),
            unzoned_every=rng.choice([0, 3]),
            pods_cap=str(rng.randint(4, 30)),
        )
    else:
        nodes = density_nodes(rng.randint(5, 20),
                              pods_cap=str(rng.randint(4, 30)))
    state = (spread_state(nodes) if rng.random() < 0.6
             else ClusterState.build(nodes))
    pods = []
    for t in range(rng.randint(3, 14)):
        k = rng.randint(1, 18)
        lbl = ({"name": "sched-perf"} if rng.random() < 0.7
               else {"app": f"x{t % 3}"})
        tpl = template_pods(1, k, labels=lbl, cpu0=40 + t * 7,
                            mem_step=30 + t, name0=f"s{t:02d}-")
        if rng.random() < 0.15:
            for p in tpl:
                p.spec.containers[0].ports = [
                    ContainerPort(host_port=7000 + t % 2)]
        pods.extend(tpl)
    assert wave_backlog(state, pods) == oracle_backlog(state, pods), (
        f"seed {seed}"
    )


def test_wave_grouped_probe_count_is_o1():
    # the regression the tentpole exists for: 100 distinct templates
    # must NOT issue 100 probes. One grouped header probe (plus its
    # deferred fold) covers the whole backlog.
    from kubernetes_tpu.models.batch import SchedulerConfig
    from kubernetes_tpu.scheduler.tpu_algorithm import (
        TPUScheduleAlgorithm,
    )

    nodes = density_nodes(50)
    state = ClusterState.build(nodes)
    pods = template_pods(100, 8, cpu0=20, mem_step=13)
    cfg = SchedulerConfig(
        predicates=("PodFitsResources",),
        priorities=(("LeastRequestedPriority", 1),
                    ("BalancedResourceAllocation", 1)),
    )
    algo = TPUScheduleAlgorithm(min_run=1, config=cfg)
    got = algo.schedule_backlog(pods, state)
    d = dict(algo._wave.dispatches)
    assert d.get("probe", 0) == 0, f"per-template probes: {d}"
    assert d.get("group_probe", 0) <= 1, f"grouped probes scaled: {d}"
    assert sum(d.values()) <= 3, (
        f"dispatches must be O(1) in templates, got {d}"
    )
    # and the decisions still match the oracle
    from kubernetes_tpu.oracle import GenericScheduler
    from kubernetes_tpu.oracle import predicates as opreds
    from kubernetes_tpu.oracle import priorities as oprios
    from kubernetes_tpu.oracle.scheduler import PriorityConfig

    oracle = GenericScheduler(
        predicates=[("PodFitsResources", opreds.pod_fits_resources)],
        priorities=[
            PriorityConfig(oprios.least_requested_priority, 1, "LR"),
            PriorityConfig(oprios.balanced_resource_allocation, 1,
                           "BA"),
        ],
    )
    assert got == oracle.schedule_backlog(pods, state.clone())


def test_wave_grouped_mesh_matches_oracle():
    # the grouped path through the MESH driver (sharded header probe +
    # shared host replay + sharded grouped fold) on the 8-virtual-device
    # CPU mesh; skipped automatically where jax.shard_map is absent
    import jax
    from jax.sharding import Mesh
    from kubernetes_tpu.parallel.mesh import MeshWaveScheduler
    from kubernetes_tpu.snapshot.encode import SnapshotEncoder

    devices = jax.devices()
    assert len(devices) >= 8
    mesh = Mesh(np.array(devices[:8]), ("nodes",))
    nodes = density_nodes(13)  # not divisible by 8: padding live
    state = spread_state(nodes)
    pods = template_pods(7, 9)
    want = oracle_backlog(state, pods)

    # dedup positions -> unique rows (the driver contract)
    reps, rep_idx = {}, []
    uniq = []
    for i, p in enumerate(pods):
        k = pod_feature_key(p)
        if k not in reps:
            reps[k] = len(uniq)
            uniq.append(i)
        rep_idx.append(reps[k])
    enc2 = SnapshotEncoder(state, [pods[i] for i in uniq])
    snap = enc2.encode_nodes()
    batch = enc2.encode_pods()
    ws = MeshWaveScheduler(mesh, min_run=1)
    chosen, _, _ = ws.schedule_backlog(
        snap, batch, np.asarray(rep_idx, np.int64)
    )
    got = [snap.node_names[c]
           if 0 <= c < len(state.node_infos) else None for c in chosen]
    assert got == want
    d = ws.dispatches
    assert d.get("group_probe", 0) >= 1, f"mesh grouping idle: {d}"


def _wave_direct(state, pods, max_j):
    """Drive WaveScheduler directly (dedup + pad like the algorithm
    shell) with a clamped table horizon."""
    from kubernetes_tpu.models.wave import WaveScheduler
    from kubernetes_tpu.parallel.mesh import _pad_snapshot
    from kubernetes_tpu.snapshot.pad import next_pow2

    uniq, rep_of, rep_idx = [], {}, []
    for p in pods:
        k = pod_feature_key(p)
        if k not in rep_of:
            rep_of[k] = len(uniq)
            uniq.append(p)
        rep_idx.append(rep_of[k])
    enc = SnapshotEncoder(state, uniq)
    snap = enc.encode_nodes()
    batch = enc.encode_pods()
    snap_p = _pad_snapshot(snap, next_pow2(snap.num_nodes, 4))
    ws = WaveScheduler(min_run=1, max_j=max_j)
    chosen, _, _ = ws.schedule_backlog(
        snap_p, batch, np.asarray(rep_idx, np.int64)
    )
    got = [snap.node_names[c] if 0 <= c < snap.num_nodes else None
           for c in chosen]
    return got, ws.dispatches


def test_wave_grouped_host_horizon_resume():
    # huge per-node capacity + a clamped 128-row table horizon: runs
    # inside a HOST group trip the horizon mid-run, the group aborts,
    # the partial run resumes on the single path, and the remaining
    # runs regroup — decisions stay bit-identical to the oracle
    nodes = density_nodes(2, pods_cap="1000")
    state = ClusterState.build(nodes)
    pods = template_pods(3, 300, cpu0=1, mem_step=0)
    got, d = _wave_direct(state, pods, max_j=128)
    assert got == oracle_backlog(state, pods)
    assert d.get("probe", 0) >= 1, f"no single-path resume happened: {d}"


def test_wave_grouped_device_horizon_resume():
    # the same horizon abort through the grouped DEVICE dispatch: the
    # outer scan aborts at the bail, later runs schedule nothing, the
    # host resumes from the bail point
    state = spread_state(zoned_density_nodes(2, pods_cap="1000"))
    pods = template_pods(3, 300, cpu0=1, mem_step=0)
    got, d = _wave_direct(state, pods, max_j=128)
    assert got == oracle_backlog(state, pods)
    assert d.get("zreplay", 0) >= 1, f"no single-path resume: {d}"
