"""The 1.3-window API resources absent until round 4: Ingress,
NetworkPolicy, PodDisruptionBudget, PodSecurityPolicy, ScheduledJob,
PodTemplate (stored; CRUD + watch round-trip over the real HTTP wire)
and ComponentStatus (virtual; live health probes).

Reference: pkg/registry/{ingress,networkpolicy,poddisruptionbudget,
podsecuritypolicy,scheduledjob,podtemplate,componentstatus}/."""

import threading
import time

import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import APIStatusError, RESTClient
from kubernetes_tpu.client.transport import HTTPTransport, LocalTransport
from kubernetes_tpu.kubectl.cmd import Kubectl


@pytest.fixture()
def plane():
    server = APIServer()
    host, port = server.serve_http(enable_binary=True)
    client = RESTClient(HTTPTransport(f"http://{host}:{port}", binary=True))
    yield server, client


def mk_objects():
    """One instance of each new stored resource."""
    return [
        ("ingresses", t.Ingress(
            metadata=t.ObjectMeta(name="web"),
            spec=t.IngressSpec(rules=[t.IngressRule(
                host="foo.bar.com",
                http_paths=[t.HTTPIngressPath(
                    path="/app",
                    backend=t.IngressBackend(
                        service_name="app", service_port=80
                    ),
                )],
            )]),
        )),
        ("networkpolicies", t.NetworkPolicy(
            metadata=t.ObjectMeta(name="allow-frontend"),
            spec=t.NetworkPolicySpec(
                pod_selector={"tier": "backend"},
                ingress=[t.NetworkPolicyIngressRule(
                    ports=[t.NetworkPolicyPort(port=6379)],
                    from_peers=[t.NetworkPolicyPeer(
                        pod_selector={"tier": "frontend"}
                    )],
                )],
            ),
        )),
        ("poddisruptionbudgets", t.PodDisruptionBudget(
            metadata=t.ObjectMeta(name="zk-budget"),
            spec=t.PodDisruptionBudgetSpec(
                min_available=2, selector={"app": "zk"}
            ),
        )),
        ("podsecuritypolicies", t.PodSecurityPolicy(
            metadata=t.ObjectMeta(name="restricted", namespace=""),
            spec=t.PodSecurityPolicySpec(
                privileged=False, host_network=False,
                volumes=["emptyDir", "secret"],
                host_ports=[t.HostPortRange(min=8000, max=9000)],
                run_as_user_rule="MustRunAsNonRoot",
            ),
        )),
        ("scheduledjobs", t.ScheduledJob(
            metadata=t.ObjectMeta(name="nightly"),
            spec=t.ScheduledJobSpec(
                schedule="0 2 * * *",
                concurrency_policy="Forbid",
                job_template=t.JobTemplateSpec(
                    spec=t.JobSpec(template=t.PodTemplateSpec(
                        spec=t.PodSpec(containers=[
                            t.Container(name="c", image="backup")
                        ]),
                    )),
                ),
            ),
        )),
        ("podtemplates", t.PodTemplate(
            metadata=t.ObjectMeta(name="base"),
            template=t.PodTemplateSpec(
                metadata=t.ObjectMeta(labels={"app": "base"}),
                spec=t.PodSpec(containers=[
                    t.Container(name="c", image="nginx")
                ]),
            ),
        )),
    ]


class TestCRUDAndWatch:
    @pytest.mark.parametrize("resource,obj", mk_objects(),
                             ids=[r for r, _ in mk_objects()])
    def test_crud_watch_roundtrip(self, plane, resource, obj):
        server, client = plane
        rc = client.resource(resource, obj.metadata.namespace)
        events = []
        done = threading.Event()

        def watcher():
            w = rc.watch()
            for typ, o in w:
                events.append((typ, o.metadata.name))
                if typ == "DELETED":
                    done.set()
                    return

        th = threading.Thread(target=watcher, daemon=True)
        th.start()
        time.sleep(0.2)
        rc.create(obj)
        got = rc.get(obj.metadata.name)
        assert type(got) is type(obj)
        assert got.metadata.uid and got.metadata.resource_version
        # spec round-trips the wire exactly
        from kubernetes_tpu.runtime.scheme import scheme
        stripped = scheme.encode(got)
        stripped.get("metadata", {}).pop("uid", None)
        want = scheme.encode(obj)
        for k in ("uid", "resourceVersion", "creationTimestamp"):
            stripped.get("metadata", {}).pop(k, None)
            want.get("metadata", {}).pop(k, None)
        assert stripped == want
        # update round-trips
        got.metadata.labels["touched"] = "yes"
        rc.update(got)
        assert rc.get(obj.metadata.name).metadata.labels["touched"] == "yes"
        # list sees it
        items, _rv = rc.list()
        assert [o.metadata.name for o in items] == [obj.metadata.name]
        rc.delete(obj.metadata.name)
        assert done.wait(5), f"watch never saw DELETED; got {events}"
        assert events[0] == ("ADDED", obj.metadata.name)
        assert ("DELETED", obj.metadata.name) in events

    def test_ingress_requires_backend_or_rules(self, plane):
        server, client = plane
        with pytest.raises(APIStatusError) as ei:
            client.resource("ingresses", "default").create(
                t.Ingress(metadata=t.ObjectMeta(name="empty")))
        assert ei.value.code == 422

    def test_scheduledjob_requires_valid_cron(self, plane):
        server, client = plane
        with pytest.raises(APIStatusError) as ei:
            client.resource("scheduledjobs", "default").create(
                t.ScheduledJob(metadata=t.ObjectMeta(name="bad"),
                               spec=t.ScheduledJobSpec(schedule="whenever")))
        assert ei.value.code == 422


class TestComponentStatus:
    def test_virtual_health_listing(self, plane):
        server, client = plane
        healthy = [True]
        server.register_component(
            "scheduler", lambda: (healthy[0], "ok")
        )
        server.register_component(
            "controller-manager", lambda: (True, "ok")
        )
        items, _ = client.resource("componentstatuses").list()
        names = {c.metadata.name for c in items}
        assert names == {"etcd-0", "scheduler", "controller-manager"}
        cs = client.resource("componentstatuses").get("scheduler")
        assert cs.conditions[0].status == "True"
        # component goes down: the NEXT get reflects it (live probe,
        # nothing cached or stored)
        healthy[0] = False
        cs = client.resource("componentstatuses").get("scheduler")
        assert cs.conditions[0].status == "False"
        assert cs.conditions[0].error

    def test_read_only(self, plane):
        server, client = plane
        with pytest.raises(APIStatusError) as ei:
            client.resource("componentstatuses").create(
                t.ComponentStatus(metadata=t.ObjectMeta(name="x")))
        assert ei.value.code == 405


class TestKubectl:
    def test_get_new_resources(self):
        server = APIServer()
        client = RESTClient(LocalTransport(server))
        for resource, obj in mk_objects():
            client.resource(resource, obj.metadata.namespace).create(obj)
        kc = Kubectl(client)
        out = kc.get("ing")
        assert "foo.bar.com" in out and "web" in out
        out = kc.get("pdb")
        assert "zk-budget" in out and "2" in out
        out = kc.get("scheduledjobs")
        assert "nightly" in out and "0 2 * * *" in out
        out = kc.get("netpol")
        assert "allow-frontend" in out
        out = kc.get("psp")
        assert "restricted" in out
        out = kc.get("podtemplates")
        assert "base" in out
        out = kc.get("cs")
        assert "etcd-0" in out and "Healthy" in out
        # describe works for each
        assert "foo.bar.com" not in kc.describe("pdb", "zk-budget")
        assert "zk-budget" in kc.describe("pdb", "zk-budget")


class TestDiscovery:
    """/apis group/version discovery (apiserver.go APIGroupVersion
    install; genericapiserver.go:332 swagger wiring)."""

    def test_apigrouplist(self, plane):
        server, client = plane
        body = client.do_raw("GET", "/apis")
        assert body["kind"] == "APIGroupList"
        names = {g["name"] for g in body["groups"]}
        assert {"extensions", "batch", "policy", "autoscaling"} <= names
        ext = next(g for g in body["groups"] if g["name"] == "extensions")
        assert ext["preferredVersion"]["groupVersion"].startswith(
            "extensions/"
        )

    def test_core_versions_and_resource_list(self, plane):
        server, client = plane
        assert client.do_raw("GET", "/api")["versions"] == ["v1"]
        rl = client.do_raw("GET", "/api/v1")
        assert rl["kind"] == "APIResourceList"
        byname = {r["name"]: r for r in rl["resources"]}
        assert byname["pods"]["namespaced"] is True
        assert byname["nodes"]["namespaced"] is False
        assert "pods/binding" in byname and "pods/status" in byname
        assert "componentstatuses" in byname

    def test_group_resource_list(self, plane):
        server, client = plane
        rl = client.do_raw("GET", "/apis/extensions/v1beta1")
        byname = {r["name"] for r in rl["resources"]}
        assert {"ingresses", "networkpolicies", "podsecuritypolicies",
                "replicasets", "deployments"} <= byname
        rl = client.do_raw("GET", "/apis/policy/v1alpha1")
        assert {r["name"] for r in rl["resources"]} >= {
            "poddisruptionbudgets", "poddisruptionbudgets/status"}
        # unknown version 404s like the reference's discovery-gated mux
        with pytest.raises(APIStatusError) as ei:
            client.do_raw("GET", "/apis/extensions/v9")
        assert ei.value.code == 404

    def test_swagger_index(self, plane):
        server, client = plane
        sw = client.do_raw("GET", "/swaggerapi")
        paths = {a["path"] for a in sw["apis"]}
        assert "/api/v1" in paths and "/apis/extensions/v1beta1" in paths

    def test_generic_client_can_enumerate_everything(self, plane):
        """The VERDICT bar: group list -> per-group resource lists."""
        server, client = plane
        groups = client.do_raw("GET", "/apis")["groups"]
        total = {
            r["name"] for r in client.do_raw("GET", "/api/v1")["resources"]
        }
        for g in groups:
            for v in g["versions"]:
                rl = client.do_raw("GET", f"/apis/{v['groupVersion']}")
                total |= {r["name"] for r in rl["resources"]}
        # every registered resource is discoverable somewhere
        for r in server.resources:
            assert r in total, f"{r} not discoverable"


class TestNewKubectlVerbs:
    def _plane(self):
        server = APIServer()
        client = RESTClient(LocalTransport(server))
        return server, client, Kubectl(client)

    def test_api_versions_and_cluster_info(self):
        server, client, kc = self._plane()
        out = kc.api_versions()
        assert "v1" in out.splitlines()
        assert "extensions/v1beta1" in out
        assert "policy/v1alpha1" in out
        info = kc.cluster_info()
        assert "Kubernetes master is running at" in info

    def test_replace(self, tmp_path):
        import json as jsonlib

        server, client, kc = self._plane()
        client.resource("configmaps", "default").create(
            t.ConfigMap(metadata=t.ObjectMeta(name="cfg"),
                        data={"a": "1"}))
        mf = tmp_path / "cm.json"
        mf.write_text(jsonlib.dumps({
            "kind": "ConfigMap",
            "metadata": {"name": "cfg", "namespace": "default"},
            "data": {"a": "2"},
        }))
        out = kc.replace(str(mf))
        assert "replaced" in out
        assert client.resource("configmaps", "default").get(
            "cfg").data["a"] == "2"
        # replace (unlike apply) demands existence
        mf2 = tmp_path / "cm2.json"
        mf2.write_text(jsonlib.dumps({
            "kind": "ConfigMap",
            "metadata": {"name": "absent", "namespace": "default"},
            "data": {},
        }))
        with pytest.raises(APIStatusError):
            kc.replace(str(mf2))
        # --force re-creates
        out = kc.replace(str(mf2), force=True)
        assert "replaced" in out

    def test_taint_add_and_remove(self):
        from kubernetes_tpu.api.types import get_taints

        server, client, kc = self._plane()
        client.resource("nodes").create(
            t.Node(metadata=t.ObjectMeta(name="n1", namespace="")))
        kc.taint("n1", "dedicated=infra:NoSchedule")
        node = client.resource("nodes").get("n1")
        taints = get_taints(node)
        assert [(x.key, x.value, x.effect) for x in taints] == [
            ("dedicated", "infra", "NoSchedule")]
        # re-tainting the same key:effect overwrites, not duplicates
        kc.taint("n1", "dedicated=batch:NoSchedule")
        taints = get_taints(client.resource("nodes").get("n1"))
        assert [(x.key, x.value) for x in taints] == [("dedicated", "batch")]
        # removal via trailing dash
        kc.taint("n1", "dedicated:NoSchedule-")
        assert get_taints(client.resource("nodes").get("n1")) == []
        with pytest.raises(ValueError):
            kc.taint("n1", "keyonly")
        # a malformed add must not masquerade as a removal
        kc.taint("n1", "foo=x:NoSchedule")
        with pytest.raises(ValueError):
            kc.taint("n1", "foo=bar-")
        assert len(get_taints(client.resource("nodes").get("n1"))) == 1

    def test_taint_spec_field_form(self):
        """Nodes carrying spec.taints (the direct form get_taints
        prefers) get mutated IN that form."""
        from kubernetes_tpu.api.types import get_taints

        server, client, kc = self._plane()
        client.resource("nodes").create(t.Node(
            metadata=t.ObjectMeta(name="n2", namespace=""),
            spec=t.NodeSpec(taints=[t.Taint(
                key="old", value="", effect="NoSchedule")]),
        ))
        kc.taint("n2", "extra=1:PreferNoSchedule")
        node = client.resource("nodes").get("n2")
        assert node.spec.taints is not None  # stayed in spec form
        assert {(x.key, x.effect) for x in get_taints(node)} == {
            ("old", "NoSchedule"), ("extra", "PreferNoSchedule")}
        kc.taint("n2", "old:NoSchedule-")
        assert {x.key for x in get_taints(
            client.resource("nodes").get("n2"))} == {"extra"}


class TestSwaggerModels:
    def test_model_schemas_served_per_group_version(self, plane):
        server, client = plane
        doc = client.do_raw("GET", "/swaggerapi/api/v1")
        assert doc["swaggerVersion"] == "1.2"
        models = doc["models"]
        pod = models["Pod"]
        assert pod["properties"]["metadata"] == {"$ref": "ObjectMeta"}
        spec = models["PodSpec"]["properties"]
        assert spec["containers"]["type"] == "array"
        assert spec["containers"]["items"] == {"$ref": "Container"}
        assert spec["nodeName"] == {"type": "string"}
        # transitively referenced models are present
        assert "Container" in models and "ObjectMeta" in models
        # extension group serves its own kinds
        ext = client.do_raw("GET", "/apis/extensions/v1beta1")
        assert ext["kind"] == "APIResourceList"
        doc2 = client.do_raw("GET", "/swaggerapi/apis/extensions/v1beta1")
        assert "Deployment" in doc2["models"]

    def test_unknown_swagger_path_404s(self, plane):
        server, client = plane
        from kubernetes_tpu.client.rest import APIStatusError

        with pytest.raises(APIStatusError) as e:
            client.do_raw("GET", "/swaggerapi/apis/nope/v9")
        assert e.value.code == 404
