"""Shared helpers for the conformance-corpus builders.

The builders transcribe the reference's unit-test scenario tables
(plugin/pkg/scheduler/algorithm/predicates/predicates_test.go,
priorities/*_test.go, generic_scheduler_test.go) into JSON fixtures under
tests/corpus/. The helper names mirror the Go test helpers so the
transcription can be checked side by side against the Go source.

Fixture objects use this framework's wire format (runtime/scheme.py), not
the upstream wire format — the corpus is scenario DATA, re-encoded.
"""

import json
import os

from kubernetes_tpu.api.types import (
    AFFINITY_ANNOTATION,
    TAINTS_ANNOTATION,
    TOLERATIONS_ANNOTATION,
    Container,
    ContainerPort,
    Node,
    NodeCondition,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from kubernetes_tpu.runtime.scheme import scheme

CORPUS_DIR = os.path.join(os.path.dirname(__file__), os.pardir)


def enc(obj):
    return scheme.encode(obj)


def enc_list(objs):
    return [scheme.encode(o) for o in objs]


def write_fixture(name, doc):
    path = os.path.abspath(os.path.join(CORPUS_DIR, name + ".json"))
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {path}")


# --- Go test helper equivalents --------------------------------------------


def resource_request(milli_cpu=0, memory=0, nvidia_gpu=0):
    """resourceRequest struct → a container requests dict."""
    req = {}
    if milli_cpu:
        req["cpu"] = f"{milli_cpu}m"
    if memory:
        req["memory"] = memory
    if nvidia_gpu:
        req["alpha.kubernetes.io/nvidia-gpu"] = nvidia_gpu
    return req


def new_resource_pod(*usage, **meta):
    """predicates_test.go:94 newResourcePod — one container per request."""
    return Pod(
        metadata=ObjectMeta(**meta),
        spec=PodSpec(
            containers=[Container(requests=resource_request(*u)) for u in usage]
        ),
    )


def new_resource_init_pod(pod, *usage):
    """predicates_test.go:114 newResourceInitPod."""
    pod.spec.init_containers = [
        Container(requests=resource_request(*u)) for u in usage
    ]
    return pod


def make_resources(milli_cpu, memory, nvidia_gpus, pods):
    """predicates_test.go:74 makeResources (capacity == allocatable here)."""
    return {
        "cpu": f"{milli_cpu}m",
        "memory": memory,
        "pods": pods,
        "alpha.kubernetes.io/nvidia-gpu": nvidia_gpus,
    }


def new_port_pod(host, *host_ports):
    """predicates_test.go:351 newPod(host, hostPorts...)."""
    return Pod(
        spec=PodSpec(
            node_name=host,
            containers=[
                Container(ports=[ContainerPort(host_port=p) for p in host_ports])
            ],
        )
    )


def node_with(name="", labels=None, annotations=None, allocatable=None,
              capacity=None, conditions=None):
    st = NodeStatus(
        capacity=capacity or {},
        allocatable=allocatable or {},
        conditions=[NodeCondition(**c) for c in (conditions or [])],
    )
    return Node(
        metadata=ObjectMeta(name=name, labels=labels or {},
                            annotations=annotations or {}),
        status=st,
    )


def affinity_pod(annotation_json, labels=None, node_selector=None, name="",
                 namespace="default", node_name=""):
    """A pod carrying the alpha affinity annotation verbatim from the Go
    table (api.AffinityAnnotationKey)."""
    meta = ObjectMeta(name=name, namespace=namespace, labels=labels or {})
    if annotation_json is not None:
        meta.annotations = {AFFINITY_ANNOTATION: annotation_json}
    return Pod(
        metadata=meta,
        spec=PodSpec(node_selector=node_selector or {}, node_name=node_name),
    )


__all__ = [
    "AFFINITY_ANNOTATION",
    "TAINTS_ANNOTATION",
    "TOLERATIONS_ANNOTATION",
    "enc",
    "enc_list",
    "write_fixture",
    "resource_request",
    "new_resource_pod",
    "new_resource_init_pod",
    "make_resources",
    "new_port_pod",
    "node_with",
    "affinity_pod",
]
