"""Transcription of generic_scheduler_test.go tables into JSON fixtures.

The fake predicates/priorities ("true"/"false"/"matches"/"nopods",
"numeric"/"reverseNumeric"/"equal") are named here and implemented by the
runner (tests/test_corpus.py) exactly as generic_scheduler_test.go:37-104
defines them. Run `python tests/corpus/builders/build_scheduler.py`.
"""

from kubernetes_tpu.api.types import ObjectMeta, Pod, PodSpec, PodStatus

from common import enc, write_fixture


def build_select_host():
    # generic_scheduler_test.go:116 TestSelectHost
    cases = [
        {"list": [["machine1.1", 1], ["machine2.1", 2]],
         "possible": ["machine2.1"], "expects_err": False},
        {"list": [["machine1.1", 1], ["machine1.2", 2], ["machine1.3", 2],
                  ["machine2.1", 2]],
         "possible": ["machine1.2", "machine1.3", "machine2.1"],
         "expects_err": False},
        {"list": [["machine1.1", 3], ["machine1.2", 3], ["machine2.1", 2],
                  ["machine3.1", 1], ["machine1.3", 3]],
         "possible": ["machine1.1", "machine1.2", "machine1.3"],
         "expects_err": False},
        {"list": [], "possible": [], "expects_err": True},
    ]
    write_fixture("select_host", {
        "source": "generic_scheduler_test.go:116 TestSelectHost",
        "cases": cases,
    })


def build_generic_scheduler():
    # generic_scheduler_test.go:182 TestGenericScheduler
    pod2 = Pod(metadata=ObjectMeta(name="2", namespace=""))
    running2 = Pod(metadata=ObjectMeta(name="2", namespace=""),
                   spec=PodSpec(node_name="2"),
                   status=PodStatus(phase="Running"))
    cases = [
        {"name": "test 1", "predicates": ["false"], "priorities": [["equal", 1]],
         "nodes": ["machine1", "machine2"], "pod": enc(Pod()), "pods": [],
         "expects_err": True, "expected": []},
        {"name": "test 2", "predicates": ["true"], "priorities": [["equal", 1]],
         "nodes": ["machine1", "machine2"], "pod": enc(Pod()), "pods": [],
         "expects_err": False, "expected": ["machine1", "machine2"]},
        {"name": "test 3", "predicates": ["matches"],
         "priorities": [["equal", 1]], "nodes": ["machine1", "machine2"],
         "pod": enc(Pod(metadata=ObjectMeta(name="machine2", namespace=""))),
         "pods": [], "expects_err": False, "expected": ["machine2"]},
        {"name": "test 4", "predicates": ["true"],
         "priorities": [["numeric", 1]], "nodes": ["3", "2", "1"],
         "pod": enc(Pod()), "pods": [], "expects_err": False,
         "expected": ["3"]},
        {"name": "test 5", "predicates": ["matches"],
         "priorities": [["numeric", 1]], "nodes": ["3", "2", "1"],
         "pod": enc(pod2), "pods": [], "expects_err": False,
         "expected": ["2"]},
        {"name": "test 6", "predicates": ["true"],
         "priorities": [["numeric", 1], ["reverseNumeric", 2]],
         "nodes": ["3", "2", "1"], "pod": enc(pod2), "pods": [],
         "expects_err": False, "expected": ["1"]},
        {"name": "test 7", "predicates": ["true", "false"],
         "priorities": [["numeric", 1]], "nodes": ["3", "2", "1"],
         "pod": enc(Pod()), "pods": [], "expects_err": True, "expected": []},
        {"name": "test 8", "predicates": ["nopods", "matches"],
         "priorities": [["numeric", 1]], "nodes": ["1", "2"],
         "pod": enc(pod2), "pods": [enc(running2)], "expects_err": True,
         "expected": []},
    ]
    # TestFindFitAllError / TestFindFitSomeError (:305, :334)
    find_fit = [
        {"name": "all error", "predicates": ["true", "false"],
         "nodes": ["3", "2", "1"], "pod": enc(Pod()), "pods": [],
         "expect_failed": {"3": "FakePredicateError",
                           "2": "FakePredicateError",
                           "1": "FakePredicateError"}},
        {"name": "some error", "predicates": ["true", "matches"],
         "nodes": ["3", "2", "1"],
         "pod": enc(Pod(metadata=ObjectMeta(name="1", namespace=""))),
         "pods": [enc(Pod(metadata=ObjectMeta(name="1", namespace=""),
                          spec=PodSpec(node_name="1")))],
         "expect_failed": {"3": "FakePredicateError",
                           "2": "FakePredicateError"}},
    ]
    write_fixture("generic_scheduler", {
        "source": "generic_scheduler_test.go:182 TestGenericScheduler + :305 TestFindFit*",
        "cases": cases,
        "find_fit": find_fit,
    })


if __name__ == "__main__":
    build_select_host()
    build_generic_scheduler()
