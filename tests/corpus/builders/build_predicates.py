"""Transcription of the reference predicate test tables into JSON fixtures.

Source: plugin/pkg/scheduler/algorithm/predicates/predicates_test.go
(table data only — scenarios, expected fits, expected failure reasons).
Run `python tests/corpus/builders/build_predicates.py` to regenerate.
"""

from kubernetes_tpu.api.types import (
    AWSElasticBlockStore,
    Container,
    GCEPersistentDisk,
    HostPathVolumeSource,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    PersistentVolumeClaimSource,
    Pod,
    PodSpec,
    RBDVolume,
    Service,
    ServiceSpec,
    Volume,
)

from common import (
    AFFINITY_ANNOTATION,
    TAINTS_ANNOTATION,
    TOLERATIONS_ANNOTATION,
    affinity_pod,
    enc,
    enc_list,
    make_resources,
    new_port_pod,
    new_resource_init_pod,
    new_resource_pod,
    node_with,
    write_fixture,
)


def insufficient(resource, requested, used, capacity):
    return {
        "kind": "insufficient",
        "resource": resource,
        "requested": requested,
        "used": used,
        "capacity": capacity,
    }


def perr(name):
    return {"kind": "predicate", "name": name}


# --- TestPodFitsResources (predicates_test.go:119) --------------------------


def build_pod_fits_resources():
    rp = new_resource_pod
    ip = new_resource_init_pod
    enough = [
        # (pod, existing-on-node, fits, reason, test)
        (Pod(), [rp((10, 20))], True, None, "no resources requested always fits"),
        (rp((1, 1)), [rp((10, 20))], False, insufficient("CPU", 1, 10, 10),
         "too many resources fails"),
        (ip(rp((1, 1)), (3, 1)), [rp((8, 19))], False, insufficient("CPU", 3, 8, 10),
         "too many resources fails due to init container cpu"),
        (ip(rp((1, 1)), (3, 1), (2, 1)), [rp((8, 19))], False,
         insufficient("CPU", 3, 8, 10),
         "too many resources fails due to highest init container cpu"),
        (ip(rp((1, 1)), (1, 3)), [rp((9, 19))], False,
         insufficient("Memory", 3, 19, 20),
         "too many resources fails due to init container memory"),
        (ip(rp((1, 1)), (1, 3), (1, 2)), [rp((9, 19))], False,
         insufficient("Memory", 3, 19, 20),
         "too many resources fails due to highest init container memory"),
        (ip(rp((1, 1)), (1, 1)), [rp((9, 19))], True, None,
         "init container fits because it's the max, not sum, of containers and init containers"),
        (ip(rp((1, 1)), (1, 1), (1, 1)), [rp((9, 19))], True, None,
         "multiple init containers fit because it's the max, not sum, of containers and init containers"),
        (rp((1, 1)), [rp((5, 5))], True, None, "both resources fit"),
        (rp((1, 2)), [rp((5, 19))], False, insufficient("Memory", 2, 19, 20),
         "one resources fits"),
        (rp((5, 1)), [rp((5, 19))], True, None, "equal edge case"),
        (ip(rp((4, 1)), (5, 1)), [rp((5, 19))], True, None,
         "equal edge case for init container"),
    ]
    not_enough = [
        (Pod(), [rp((10, 20))], False, insufficient("PodCount", 1, 1, 1),
         "even without specified resources predicate fails when there's no space for additional pod"),
        (rp((1, 1)), [rp((5, 5))], False, insufficient("PodCount", 1, 1, 1),
         "even if both resources fit predicate fails when there's no space for additional pod"),
        (rp((5, 1)), [rp((5, 19))], False, insufficient("PodCount", 1, 1, 1),
         "even for equal edge case predicate fails when there's no space for additional pod"),
        (ip(rp((5, 1)), (5, 1)), [rp((5, 19))], False,
         insufficient("PodCount", 1, 1, 1),
         "even for equal edge case predicate fails when there's no space for additional pod due to init container"),
    ]
    cases = []
    for pod, existing, fits, reason, test in enough:
        cases.append({
            "test": test,
            "pod": enc(pod),
            "existing": enc_list(existing),
            "node": enc(node_with(name="machine1",
                                  capacity=make_resources(10, 20, 0, 32),
                                  allocatable=make_resources(10, 20, 0, 32))),
            "fits": fits,
            "reason": reason,
        })
    for pod, existing, fits, reason, test in not_enough:
        cases.append({
            "test": test,
            "pod": enc(pod),
            "existing": enc_list(existing),
            "node": enc(node_with(name="machine1",
                                  allocatable=make_resources(10, 20, 0, 1))),
            "fits": fits,
            "reason": reason,
        })
    write_fixture("pod_fits_resources", {
        "source": "predicates_test.go:119 TestPodFitsResources",
        "predicate": "PodFitsResources",
        "cases": cases,
    })


# --- TestPodFitsHost (predicates_test.go:292) -------------------------------


def build_pod_fits_host():
    cases = [
        {"test": "no host specified", "pod": enc(Pod()),
         "node": enc(node_with(name="")), "fits": True, "reason": None},
        {"test": "host matches",
         "pod": enc(Pod(spec=PodSpec(node_name="foo"))),
         "node": enc(node_with(name="foo")), "fits": True, "reason": None},
        {"test": "host doesn't match",
         "pod": enc(Pod(spec=PodSpec(node_name="bar"))),
         "node": enc(node_with(name="foo")), "fits": False,
         "reason": perr("HostName")},
    ]
    write_fixture("pod_fits_host", {
        "source": "predicates_test.go:292 TestPodFitsHost",
        "predicate": "PodFitsHost",
        "cases": cases,
    })


# --- TestPodFitsHostPorts (predicates_test.go:368) --------------------------


def build_pod_fits_host_ports():
    np = new_port_pod
    table = [
        (Pod(), [], True, "nothing running"),
        (np("m1", 8080), [np("m1", 9090)], True, "other port"),
        (np("m1", 8080), [np("m1", 8080)], False, "same port"),
        (np("m1", 8000, 8080), [np("m1", 8080)], False, "second port"),
        (np("m1", 8000, 8080), [np("m1", 8001, 8080)], False, "second port conflict"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "existing": enc_list(existing),
        "node": enc(node_with(name="m1")),
        "fits": fits,
        "reason": None if fits else perr("PodFitsHostPorts"),
    } for pod, existing, fits, test in table]
    write_fixture("pod_fits_host_ports", {
        "source": "predicates_test.go:368 TestPodFitsHostPorts",
        "predicate": "PodFitsHostPorts",
        "cases": cases,
    })


# --- TestDiskConflicts / TestAWSDiskConflicts / TestRBDDiskConflicts --------


def build_no_disk_conflict():
    def vol_pod(vol):
        return Pod(spec=PodSpec(volumes=[vol]))

    gce1 = Volume(gce_persistent_disk=GCEPersistentDisk(pd_name="foo"))
    gce2 = Volume(gce_persistent_disk=GCEPersistentDisk(pd_name="bar"))
    aws1 = Volume(aws_elastic_block_store=AWSElasticBlockStore(volume_id="foo"))
    aws2 = Volume(aws_elastic_block_store=AWSElasticBlockStore(volume_id="bar"))
    rbd1 = Volume(rbd=RBDVolume(monitors=("a", "b"), pool="foo", image="bar"))
    rbd2 = Volume(rbd=RBDVolume(monitors=("c", "d"), pool="foo", image="bar"))

    cases = []
    for flavor, v1, v2 in [("gce", gce1, gce2), ("aws", aws1, aws2),
                           ("rbd", rbd1, rbd2)]:
        table = [
            (Pod(), [], True, f"{flavor}: nothing"),
            (Pod(), [vol_pod(v1)], True, f"{flavor}: one state"),
            (vol_pod(v1), [vol_pod(v1)], False, f"{flavor}: same state"),
            (vol_pod(v2), [vol_pod(v1)], True, f"{flavor}: different state"),
        ]
        for pod, existing, fits, test in table:
            cases.append({
                "test": test,
                "pod": enc(pod),
                "existing": enc_list(existing),
                "node": enc(node_with(name="m1")),
                "fits": fits,
                "reason": None if fits else perr("NoDiskConflict"),
            })
    write_fixture("no_disk_conflict", {
        "source": "predicates_test.go:460,512,564 Test{GCE,AWS,RBD}DiskConflicts",
        "predicate": "NoDiskConflict",
        "cases": cases,
    })


# --- TestPodFitsSelector (predicates_test.go:622) ---------------------------


def build_pod_fits_selector():
    a = affinity_pod
    table = [
        (Pod(), None, True, "no selector"),
        (a(None, node_selector={"foo": "bar"}), None, False, "missing labels"),
        (a(None, node_selector={"foo": "bar"}), {"foo": "bar"}, True,
         "same labels"),
        (a(None, node_selector={"foo": "bar"}), {"foo": "bar", "baz": "blah"},
         True, "node labels are superset"),
        (a(None, node_selector={"foo": "bar", "baz": "blah"}), {"foo": "bar"},
         False, "node labels are subset"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": {'
           '"nodeSelectorTerms": [{"matchExpressions": [{"key": "foo", "operator": "In",'
           ' "values": ["bar", "value2"]}]}]}}}'),
         {"foo": "bar"}, True,
         "Pod with matchExpressions using In operator that matches the existing node"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": {'
           '"nodeSelectorTerms": [{"matchExpressions": [{"key": "kernel-version",'
           ' "operator": "Gt", "values": ["2.4"]}]}]}}}'),
         {"kernel-version": "2.6"}, True,
         "Pod with matchExpressions using Gt operator that matches the existing node"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": {'
           '"nodeSelectorTerms": [{"matchExpressions": [{"key": "mem-type",'
           ' "operator": "NotIn", "values": ["DDR", "DDR2"]}]}]}}}'),
         {"mem-type": "DDR3"}, True,
         "Pod with matchExpressions using NotIn operator that matches the existing node"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": {'
           '"nodeSelectorTerms": [{"matchExpressions": [{"key": "GPU",'
           ' "operator": "Exists"}]}]}}}'),
         {"GPU": "NVIDIA-GRID-K1"}, True,
         "Pod with matchExpressions using Exists operator that matches the existing node"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": {'
           '"nodeSelectorTerms": [{"matchExpressions": [{"key": "foo", "operator": "In",'
           ' "values": ["value1", "value2"]}]}]}}}'),
         {"foo": "bar"}, False,
         "Pod with affinity that don't match node's labels won't schedule onto the node"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": {'
           '"nodeSelectorTerms": null}}}'),
         {"foo": "bar"}, False,
         "Pod with a nil []NodeSelectorTerm in affinity, can't match the node's labels and won't schedule onto the node"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": {'
           '"nodeSelectorTerms": []}}}'),
         {"foo": "bar"}, False,
         "Pod with an empty []NodeSelectorTerm in affinity, can't match the node's labels and won't schedule onto the node"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": {'
           '"nodeSelectorTerms": [{}, {}]}}}'),
         {"foo": "bar"}, False,
         "Pod with invalid NodeSelectTerms in affinity will match no objects and won't schedule onto the node"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": {'
           '"nodeSelectorTerms": [{"matchExpressions": [{}]}]}}}'),
         {"foo": "bar"}, False,
         "Pod with empty MatchExpressions is not a valid value will match no objects and won't schedule onto the node"),
        (Pod(metadata=ObjectMeta(annotations={"some-key": "some-value"})),
         {"foo": "bar"}, True, "Pod with no Affinity will schedule onto a node"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": null}}'),
         {"foo": "bar"}, True,
         "Pod with Affinity but nil NodeSelector will schedule onto a node"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": {'
           '"nodeSelectorTerms": [{"matchExpressions": [{"key": "GPU", "operator":'
           ' "Exists"}, {"key": "GPU", "operator": "NotIn", "values": ["AMD",'
           ' "INTER"]}]}]}}}'),
         {"GPU": "NVIDIA-GRID-K1"}, True,
         "Pod with multiple matchExpressions ANDed that matches the existing node"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": {'
           '"nodeSelectorTerms": [{"matchExpressions": [{"key": "GPU", "operator":'
           ' "Exists"}, {"key": "GPU", "operator": "In", "values": ["AMD",'
           ' "INTER"]}]}]}}}'),
         {"GPU": "NVIDIA-GRID-K1"}, False,
         "Pod with multiple matchExpressions ANDed that doesn't match the existing node"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": {'
           '"nodeSelectorTerms": [{"matchExpressions": [{"key": "foo", "operator":'
           ' "In", "values": ["bar", "value2"]}]}, {"matchExpressions": [{"key":'
           ' "diffkey", "operator": "In", "values": ["wrong", "value2"]}]}]}}}'),
         {"foo": "bar"}, True,
         "Pod with multiple NodeSelectorTerms ORed in affinity, matches the node's labels and will schedule onto the node"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": {'
           '"nodeSelectorTerms": [{"matchExpressions": [{"key": "foo", "operator":'
           ' "Exists"}]}]}}}', node_selector={"foo": "bar"}),
         {"foo": "bar"}, True,
         "Pod with an Affinity and a PodSpec.NodeSelector both are satisfied, will schedule onto the node"),
        (a('{"nodeAffinity": { "requiredDuringSchedulingIgnoredDuringExecution": {'
           '"nodeSelectorTerms": [{"matchExpressions": [{"key": "foo", "operator":'
           ' "Exists"}]}]}}}', node_selector={"foo": "bar"}),
         {"foo": "barrrrrr"}, False,
         "Pod with an Affinity matches node's labels but the PodSpec.NodeSelector is not satisfied, won't schedule onto the node"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "node": enc(node_with(name="m1", labels=labels or {})),
        "fits": fits,
        "reason": None if fits else perr("MatchNodeSelector"),
    } for pod, labels, fits, test in table]
    write_fixture("pod_fits_selector", {
        "source": "predicates_test.go:622 TestPodFitsSelector",
        "predicate": "PodSelectorMatches",
        "cases": cases,
    })


# --- TestNodeLabelPresence (predicates_test.go:1097) ------------------------


def build_node_label_presence():
    table = [
        (["baz"], True, False, "label does not match, presence true"),
        (["baz"], False, True, "label does not match, presence false"),
        (["foo", "baz"], True, False, "one label matches, presence true"),
        (["foo", "baz"], False, False, "one label matches, presence false"),
        (["foo", "bar"], True, True, "all labels match, presence true"),
        (["foo", "bar"], False, False, "all labels match, presence false"),
    ]
    cases = [{
        "test": test,
        "pod": enc(Pod()),
        "node": enc(node_with(name="m1", labels={"foo": "bar", "bar": "foo"})),
        "labels": labels,
        "presence": presence,
        "fits": fits,
        "reason": None if fits else perr("CheckNodeLabelPresence"),
    } for labels, presence, fits, test in table]
    write_fixture("node_label_presence", {
        "source": "predicates_test.go:1097 TestNodeLabelPresence",
        "predicate": "CheckNodeLabelPresence",
        "cases": cases,
    })


# --- TestServiceAffinity (predicates_test.go:1162) --------------------------


def build_service_affinity():
    selector = {"foo": "bar"}
    labels1 = {"region": "r1", "zone": "z11"}
    labels2 = {"region": "r1", "zone": "z12"}
    labels3 = {"region": "r2", "zone": "z21"}
    labels4 = {"region": "r2", "zone": "z22"}
    nodes = [
        node_with(name="machine1", labels=labels1),
        node_with(name="machine2", labels=labels2),
        node_with(name="machine3", labels=labels3),
        node_with(name="machine4", labels=labels4),
        node_with(name="machine5", labels=labels4),
    ]

    def lp(node_name, labels_=None, namespace="default"):
        return Pod(metadata=ObjectMeta(labels=labels_ or {}, namespace=namespace),
                   spec=PodSpec(node_name=node_name))

    def svc(sel, namespace="default"):
        return Service(metadata=ObjectMeta(namespace=namespace),
                       spec=ServiceSpec(selector=sel))

    table = [
        # (pod, lister-pods, services, node-under-test, labels, fits, test)
        (Pod(), [], [], "machine1", ["region"], True, "nothing scheduled"),
        (Pod(spec=PodSpec(node_selector={"region": "r1"})), [], [], "machine1",
         ["region"], True, "pod with region label match"),
        (Pod(spec=PodSpec(node_selector={"region": "r2"})), [], [], "machine1",
         ["region"], False, "pod with region label mismatch"),
        (Pod(metadata=ObjectMeta(labels=selector)), [lp("machine1", selector)],
         [svc(selector)], "machine1", ["region"], True, "service pod on same node"),
        (Pod(metadata=ObjectMeta(labels=selector)), [lp("machine2", selector)],
         [svc(selector)], "machine1", ["region"], True,
         "service pod on different node, region match"),
        (Pod(metadata=ObjectMeta(labels=selector)), [lp("machine3", selector)],
         [svc(selector)], "machine1", ["region"], False,
         "service pod on different node, region mismatch"),
        (Pod(metadata=ObjectMeta(labels=selector, namespace="ns1")),
         [lp("machine3", selector, namespace="ns1")], [svc(selector, namespace="ns2")],
         "machine1", ["region"], True, "service in different namespace, region mismatch"),
        (Pod(metadata=ObjectMeta(labels=selector, namespace="ns1")),
         [lp("machine3", selector, namespace="ns2")], [svc(selector, namespace="ns1")],
         "machine1", ["region"], True, "pod in different namespace, region mismatch"),
        (Pod(metadata=ObjectMeta(labels=selector, namespace="ns1")),
         [lp("machine3", selector, namespace="ns1")], [svc(selector, namespace="ns1")],
         "machine1", ["region"], False,
         "service and pod in same namespace, region mismatch"),
        (Pod(metadata=ObjectMeta(labels=selector)), [lp("machine2", selector)],
         [svc(selector)], "machine1", ["region", "zone"], False,
         "service pod on different node, multiple labels, not all match"),
        (Pod(metadata=ObjectMeta(labels=selector)), [lp("machine5", selector)],
         [svc(selector)], "machine4", ["region", "zone"], True,
         "service pod on different node, multiple labels, all match"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "pods": enc_list(pods),
        "services": enc_list(services),
        "nodes": enc_list(nodes),
        "node": node,
        "labels": labels,
        "fits": fits,
        "reason": None if fits else perr("CheckServiceAffinity"),
    } for pod, pods, services, node, labels, fits, test in table]
    write_fixture("service_affinity", {
        "source": "predicates_test.go:1162 TestServiceAffinity",
        "predicate": "CheckServiceAffinity",
        "cases": cases,
    })


# --- TestEBSVolumeCountConflicts (predicates_test.go:1307) ------------------


def build_max_pd_volume_count():
    def vols_pod(*vols):
        return Pod(spec=PodSpec(volumes=list(vols)))

    ebs = lambda vid: Volume(aws_elastic_block_store=AWSElasticBlockStore(volume_id=vid))
    pvc = lambda name: Volume(persistent_volume_claim=PersistentVolumeClaimSource(claim_name=name))
    host_path = Volume(host_path=HostPathVolumeSource())

    one_vol_pod = vols_pod(ebs("ovp"))
    ebs_pvc_pod = vols_pod(pvc("someEBSVol"))
    split_pvc_pod = vols_pod(pvc("someNonEBSVol"), pvc("someEBSVol"))
    two_vol_pod = vols_pod(ebs("tvp1"), ebs("tvp2"))
    split_vols_pod = vols_pod(host_path, ebs("svp"))
    non_applicable_pod = vols_pod(host_path)
    empty_pod = Pod(spec=PodSpec())

    pvs = [
        PersistentVolume(metadata=ObjectMeta(name="someEBSVol"),
                         aws_elastic_block_store=AWSElasticBlockStore()),
        PersistentVolume(metadata=ObjectMeta(name="someNonEBSVol")),
    ]
    pvcs = [
        PersistentVolumeClaim(metadata=ObjectMeta(name="someEBSVol"),
                              volume_name="someEBSVol"),
        PersistentVolumeClaim(metadata=ObjectMeta(name="someNonEBSVol"),
                              volume_name="someNonEBSVol"),
    ]

    table = [
        (one_vol_pod, [two_vol_pod, one_vol_pod], 4, True,
         "fits when node capacity >= new pod's EBS volumes"),
        (two_vol_pod, [one_vol_pod], 2, False,
         "doesn't fit when node capacity < new pod's EBS volumes"),
        (split_vols_pod, [two_vol_pod], 3, True,
         "new pod's count ignores non-EBS volumes"),
        (two_vol_pod, [split_vols_pod, non_applicable_pod, empty_pod], 3, True,
         "existing pods' counts ignore non-EBS volumes"),
        (ebs_pvc_pod, [split_vols_pod, non_applicable_pod, empty_pod], 3, True,
         "new pod's count considers PVCs backed by EBS volumes"),
        (split_pvc_pod, [split_vols_pod, one_vol_pod], 3, True,
         "new pod's count ignores PVCs not backed by EBS volumes"),
        (two_vol_pod, [one_vol_pod, ebs_pvc_pod], 3, False,
         "existing pods' counts considers PVCs backed by EBS volumes"),
        (two_vol_pod, [one_vol_pod, two_vol_pod, ebs_pvc_pod], 4, True,
         "already-mounted EBS volumes are always ok to allow"),
        (split_vols_pod, [one_vol_pod, one_vol_pod, ebs_pvc_pod], 3, True,
         "the same EBS volumes are not counted multiple times"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "existing": enc_list(existing),
        "node": enc(node_with(name="m1")),
        "max_vols": max_vols,
        "filter": "ebs",
        "pvs": enc_list(pvs),
        "pvcs": enc_list(pvcs),
        "fits": fits,
        "reason": None if fits else perr("MaxVolumeCount"),
    } for pod, existing, max_vols, fits, test in table]
    write_fixture("max_pd_volume_count", {
        "source": "predicates_test.go:1307 TestEBSVolumeCountConflicts",
        "predicate": "MaxPDVolumeCountPredicate",
        "cases": cases,
    })


# --- TestRunGeneralPredicates (predicates_test.go:1589) ---------------------


def build_general_predicates():
    rp = new_resource_pod

    from kubernetes_tpu.api.types import ContainerPort

    def pp(*ports):
        return Pod(spec=PodSpec(containers=[
            Container(ports=[ContainerPort(host_port=p) for p in ports])]))

    node_10_20_0 = node_with(name="machine1",
                             capacity=make_resources(10, 20, 0, 32),
                             allocatable=make_resources(10, 20, 0, 32))
    node_10_20_1 = node_with(name="machine1",
                             capacity=make_resources(10, 20, 1, 32),
                             allocatable=make_resources(10, 20, 1, 32))
    table = [
        (Pod(), [rp((9, 19))], node_10_20_0, True, None,
         "no resources/port/host requested always fits"),
        (rp((8, 10)), [rp((5, 19))], node_10_20_0, False,
         insufficient("CPU", 8, 5, 10), "not enough cpu resource"),
        (Pod(), [rp((9, 19))], node_10_20_1, True, None,
         "no resources/port/host requested always fits on GPU machine"),
        (rp((3, 1, 1)), [rp((5, 10, 1))], node_10_20_1, False,
         insufficient("NvidiaGpu", 1, 1, 1), "not enough GPU resource"),
        (rp((3, 1, 1)), [rp((5, 10, 0))], node_10_20_1, True, None,
         "enough GPU resource"),
        (Pod(spec=PodSpec(node_name="machine2")), [], node_10_20_0, False,
         perr("HostName"), "host not match"),
        (pp(123), [pp(123)], node_10_20_0, False, perr("PodFitsHostPorts"),
         "hostport conflict"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "existing": enc_list(existing),
        "node": enc(node),
        "fits": fits,
        "reason": reason,
    } for pod, existing, node, fits, reason, test in table]
    write_fixture("general_predicates", {
        "source": "predicates_test.go:1589 TestRunGeneralPredicates",
        "predicate": "GeneralPredicates",
        "cases": cases,
    })


# --- TestInterPodAffinity (predicates_test.go:1688) -------------------------


def build_interpod_affinity():
    pod_label = {"service": "securityscan"}
    pod_label2 = {"security": "S1"}
    node1 = node_with(name="machine1", labels={"region": "r1", "zone": "z11"})

    def ap(annot, labels):
        return affinity_pod(annot, labels=labels)

    def existing(labels, annot=None, node_name="machine1", namespace="default"):
        meta = ObjectMeta(labels=labels, namespace=namespace)
        if annot:
            meta.annotations = {AFFINITY_ANNOTATION: annot}
        return Pod(metadata=meta, spec=PodSpec(node_name=node_name))

    table = [
        (Pod(), [], True,
         "A pod that has no required pod affinity scheduling rules can schedule onto a node with no existing pods"),
        (ap('{"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{'
            '"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "In", "values": ["securityscan", "value2"]}]}, "topologyKey": "region"}]}}',
            pod_label2),
         [existing(pod_label)], True,
         "satisfies with requiredDuringSchedulingIgnoredDuringExecution in PodAffinity using In operator that matches the existing pod"),
        (ap('{"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{'
            '"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "NotIn", "values": ["securityscan3", "value3"]}]}, "topologyKey": "region"}]}}',
            pod_label2),
         [existing(pod_label)], True,
         "satisfies the pod with requiredDuringSchedulingIgnoredDuringExecution in PodAffinity using not in operator in labelSelector that matches the existing pod"),
        (ap('{"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{'
            '"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "In", "values": ["securityscan", "value2"]}]}, "namespaces":["DiffNameSpace"]}]}}',
            pod_label2),
         [existing(pod_label, namespace="ns")], False,
         "Does not satisfy the PodAffinity with labelSelector because of diff Namespace"),
        (ap('{"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{'
            '"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "In", "values": ["antivirusscan", "value2"]}]}}]}}',
            pod_label),
         [existing(pod_label)], False,
         "Doesn't satisfy the PodAffinity because of unmatching labelSelector with the existing pod"),
        (ap('{"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": ['
            '{"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "Exists"}, {"key": "wrongkey", "operator": "DoesNotExist"}]},'
            ' "topologyKey": "region"}, {"labelSelector": {"matchExpressions": [{'
            '"key": "service", "operator": "In", "values": ["securityscan"]},'
            ' {"key": "service", "operator": "NotIn", "values": ["WrongValue"]}]},'
            ' "topologyKey": "region"}]}}',
            pod_label2),
         [existing(pod_label)], True,
         "satisfies the PodAffinity with different label Operators in multiple RequiredDuringSchedulingIgnoredDuringExecution"),
        (ap('{"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": ['
            '{"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "Exists"}, {"key": "wrongkey", "operator": "DoesNotExist"}]},'
            ' "topologyKey": "region"}, {"labelSelector": {"matchExpressions": [{'
            '"key": "service", "operator": "In", "values": ["securityscan2"]},'
            ' {"key": "service", "operator": "NotIn", "values": ["WrongValue"]}]},'
            ' "topologyKey": "region"}]}}',
            pod_label2),
         [existing(pod_label)], False,
         "The labelSelector requirements(items of matchExpressions) are ANDed, the pod cannot schedule onto the node because one of the matchExpression items doesn't match"),
        (ap('{"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{'
            '"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "In", "values": ["securityscan", "value2"]}]}, "topologyKey": "region"}]},'
            ' "podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{'
            '"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "In", "values": ["antivirusscan", "value2"]}]}, "topologyKey": "node"}]}}',
            pod_label2),
         [existing(pod_label)], True,
         "satisfies the PodAffinity and PodAntiAffinity with the existing pod"),
        (ap('{"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{'
            '"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "In", "values": ["securityscan", "value2"]}]}, "topologyKey": "region"}]},'
            ' "podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{'
            '"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "In", "values": ["antivirusscan", "value2"]}]}, "topologyKey": "node"}]}}',
            pod_label2),
         [existing(pod_label,
                   '{"PodAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution":'
                   ' [{"labelSelector": {"matchExpressions": [{"key": "service",'
                   ' "operator": "In", "values": ["antivirusscan", "value2"]}]},'
                   ' "topologyKey": "node"}]}}')], True,
         "satisfies the PodAffinity and PodAntiAffinity and PodAntiAffinity symmetry with the existing pod"),
        (ap('{"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{'
            '"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "In", "values": ["securityscan", "value2"]}]}, "topologyKey": "region"}]},'
            ' "podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{'
            '"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "In", "values": ["securityscan", "value2"]}]}, "topologyKey": "zone"}]}}',
            pod_label2),
         [existing(pod_label)], False,
         "satisfies the PodAffinity but doesn't satisfy the PodAntiAffinity with the existing pod"),
        (ap('{"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{'
            '"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "In", "values": ["securityscan", "value2"]}]}, "topologyKey": "region"}]},'
            ' "podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{'
            '"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "In", "values": ["antivirusscan", "value2"]}]}, "topologyKey": "node"}]}}',
            pod_label),
         [existing(pod_label,
                   '{"PodAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution":'
                   ' [{"labelSelector": {"matchExpressions": [{"key": "service",'
                   ' "operator": "In", "values": ["securityscan", "value2"]}]},'
                   ' "topologyKey": "zone"}]}}')], False,
         "satisfies the PodAffinity and PodAntiAffinity but doesn't satisfy PodAntiAffinity symmetry with the existing pod"),
        (ap('{"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{'
            '"labelSelector": {"matchExpressions": [{"key": "service", "operator":'
            ' "NotIn", "values": ["securityscan", "value2"]}]}, "topologyKey": "region"}]}}',
            pod_label),
         [existing(pod_label, node_name="machine2")], False,
         "pod matches its own Label in PodAffinity and that matches the existing pod Labels"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "pods": enc_list(pods),
        "nodes": [enc(node1)],
        "expect": {"machine1": {"fits": fits,
                                "reason": None if fits else perr("MatchInterPodAffinity")}},
    } for pod, pods, fits, test in table]
    write_fixture("interpod_affinity", {
        "source": "predicates_test.go:1688 TestInterPodAffinity",
        "predicate": "InterPodAffinityMatches",
        "cases": cases,
    })


# --- TestInterPodAffinityWithMultipleNodes (predicates_test.go:2181) --------


def build_interpod_affinity_multi():
    def lpod(node_name, labels):
        return Pod(metadata=ObjectMeta(labels=labels),
                   spec=PodSpec(node_name=node_name))

    cases = [
        {
            "test": "A pod can be scheduled onto all the nodes that have the same topology key & label value with one of them has an existing pod that match the affinity rules",
            "pod": enc(affinity_pod(
                '{"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution":'
                ' [{"labelSelector": {"matchExpressions": [{"key": "foo", "operator":'
                ' "In", "values": ["bar"]}]}, "topologyKey": "region"}]}}')),
            "pods": enc_list([lpod("machine1", {"foo": "bar"})]),
            "nodes": enc_list([
                node_with(name="machine1", labels={"region": "China"}),
                node_with(name="machine2", labels={"region": "China", "az": "az1"}),
                node_with(name="machine3", labels={"region": "India"}),
            ]),
            "expect": {
                "machine1": {"fits": True, "reason": None},
                "machine2": {"fits": True, "reason": None},
                "machine3": {"fits": False, "reason": perr("MatchInterPodAffinity")},
            },
        },
        {
            "test": "NodeA and nodeB have same topologyKey and label value. NodeA does not satisfy node affinity rule, but has an existing pod that matches the inter pod affinity rule. The pod can be scheduled onto nodeB.",
            "also_node_selector": True,
            "pod": enc(affinity_pod(
                '{"nodeAffinity": {"requiredDuringSchedulingIgnoredDuringExecution":'
                ' {"nodeSelectorTerms": [{"matchExpressions": [{"key": "hostname",'
                ' "operator": "NotIn", "values": ["h1"]}]}]}}, "podAffinity": {'
                '"requiredDuringSchedulingIgnoredDuringExecution": [{"labelSelector":'
                ' {"matchExpressions": [{"key": "foo", "operator": "In", "values":'
                ' ["abc"]}]}, "topologyKey": "region"}]}}')),
            "pods": enc_list([lpod("nodeA", {"foo": "abc"}),
                              lpod("nodeB", {"foo": "def"})]),
            "nodes": enc_list([
                node_with(name="nodeA", labels={"region": "r1", "hostname": "h1"}),
                node_with(name="nodeB", labels={"region": "r1", "hostname": "h2"}),
            ]),
            "expect": {
                "nodeA": {"fits": False, "reason": None},
                "nodeB": {"fits": True, "reason": None},
            },
        },
        {
            "test": "The affinity rule is to schedule all of the pods of this collection to the same zone. The first pod of the collection should not be blocked from being scheduled onto any node, even there's no existing pod that matches the rule anywhere.",
            "pod": enc(affinity_pod(
                '{"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution":'
                ' [{"labelSelector": {"matchExpressions": [{"key": "foo", "operator":'
                ' "In", "values": ["bar"]}]}, "topologyKey": "zone"}]}}',
                labels={"foo": "bar"})),
            "pods": [],
            "nodes": enc_list([
                node_with(name="nodeA", labels={"zone": "az1", "hostname": "h1"}),
                node_with(name="nodeB", labels={"zone": "az2", "hostname": "h2"}),
            ]),
            "expect": {
                "nodeA": {"fits": True, "reason": None},
                "nodeB": {"fits": True, "reason": None},
            },
        },
    ]
    write_fixture("interpod_affinity_multi", {
        "source": "predicates_test.go:2181 TestInterPodAffinityWithMultipleNodes",
        "predicate": "InterPodAffinityMatches",
        "cases": cases,
    })


# --- TestPodToleratesTaints (predicates_test.go:2362) -----------------------


def build_pod_tolerates_taints():
    def tpod(name, tolerations_json=None):
        annotations = {}
        if tolerations_json:
            annotations[TOLERATIONS_ANNOTATION] = tolerations_json
        return Pod(metadata=ObjectMeta(name=name, annotations=annotations),
                   spec=PodSpec(containers=[Container(image=f"{name}:V1")]))

    def tnode(taints_json):
        return node_with(name="m1", annotations={TAINTS_ANNOTATION: taints_json})

    table = [
        (tpod("pod0"),
         tnode('[{"key": "dedicated", "value": "user1", "effect": "NoSchedule"}]'),
         False,
         "a pod having no tolerations can't be scheduled onto a node with nonempty taints"),
        (tpod("pod1", '[{"key": "dedicated", "value": "user1", "effect": "NoSchedule"}]'),
         tnode('[{"key": "dedicated", "value": "user1", "effect": "NoSchedule"}]'),
         True,
         "a pod which can be scheduled on a dedicated node assigned to user1 with effect NoSchedule"),
        (tpod("pod2", '[{"key": "dedicated", "operator": "Equal", "value": "user2", "effect": "NoSchedule"}]'),
         tnode('[{"key": "dedicated", "value": "user1", "effect": "NoSchedule"}]'),
         False,
         "a pod which can't be scheduled on a dedicated node assigned to user2 with effect NoSchedule"),
        (tpod("pod2", '[{"key": "foo", "operator": "Exists", "effect": "NoSchedule"}]'),
         tnode('[{"key": "foo", "value": "bar", "effect": "NoSchedule"}]'),
         True,
         "a pod can be scheduled onto the node, with a toleration uses operator Exists that tolerates the taints on the node"),
        (tpod("pod2", '[{"key": "dedicated", "operator": "Equal", "value": "user2",'
                      ' "effect": "NoSchedule"}, {"key": "foo", "operator": "Exists",'
                      ' "effect": "NoSchedule"}]'),
         tnode('[{"key": "dedicated", "value": "user2", "effect": "NoSchedule"},'
               ' {"key": "foo", "value": "bar", "effect": "NoSchedule"}]'),
         True,
         "a pod has multiple tolerations, node has multiple taints, all the taints are tolerated, pod can be scheduled onto the node"),
        (tpod("pod2", '[{"key": "foo", "operator": "Equal", "value": "bar", "effect":'
                      ' "PreferNoSchedule"}]'),
         tnode('[{"key": "foo", "value": "bar", "effect": "NoSchedule"}]'),
         False,
         "a pod has a toleration that keys and values match the taint on the node, but (non-empty) effect doesn't match, can't be scheduled onto the node"),
        (tpod("pod2", '[{"key": "foo", "operator": "Equal", "value": "bar"}]'),
         tnode('[{"key": "foo", "value": "bar", "effect": "NoSchedule"}]'),
         True,
         "The pod has a toleration that keys and values match the taint on the node, the effect of toleration is empty, and the effect of taint is NoSchedule. Pod can be scheduled onto the node"),
        (tpod("pod2", '[{"key": "dedicated", "operator": "Equal", "value": "user2",'
                      ' "effect": "NoSchedule"}]'),
         tnode('[{"key": "dedicated", "value": "user1", "effect": "PreferNoSchedule"}]'),
         True,
         "The pod has a toleration that key and value don't match the taint on the node, but the effect of taint on node is PreferNoSchedule. Pod can be scheduled onto the node"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "node": enc(node),
        "fits": fits,
        "reason": None if fits else perr("PodToleratesNodeTaints"),
    } for pod, node, fits, test in table]
    write_fixture("pod_tolerates_taints", {
        "source": "predicates_test.go:2362 TestPodToleratesTaints",
        "predicate": "PodToleratesNodeTaints",
        "cases": cases,
    })


# --- TestPodSchedulesOnNodeWithMemoryPressureCondition (:2651) --------------


def build_memory_pressure():
    best_effort = Pod(spec=PodSpec(containers=[
        Container(name="container", image="image")]))
    non_best_effort = Pod(spec=PodSpec(containers=[
        Container(name="container", image="image",
                  requests=make_resources(100, 100, 100, 100))]))
    no_pressure = node_with(name="m1", conditions=[
        {"type": "Ready", "status": "True"}])
    pressure = node_with(name="m1", conditions=[
        {"type": "MemoryPressure", "status": "True"}])
    table = [
        (best_effort, no_pressure, True,
         "best-effort pod schedulable on node without memory pressure condition on"),
        (best_effort, pressure, False,
         "best-effort pod not schedulable on node with memory pressure condition on"),
        (non_best_effort, pressure, True,
         "non best-effort pod schedulable on node with memory pressure condition on"),
        (non_best_effort, no_pressure, True,
         "non best-effort pod schedulable on node without memory pressure condition on"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "node": enc(node),
        "fits": fits,
        "reason": None if fits else perr("NodeUnderMemoryPressure"),
    } for pod, node, fits, test in table]
    write_fixture("memory_pressure", {
        "source": "predicates_test.go:2651 TestPodSchedulesOnNodeWithMemoryPressureCondition",
        "predicate": "CheckNodeMemoryPressure",
        "cases": cases,
    })


if __name__ == "__main__":
    build_pod_fits_resources()
    build_pod_fits_host()
    build_pod_fits_host_ports()
    build_no_disk_conflict()
    build_pod_fits_selector()
    build_node_label_presence()
    build_service_affinity()
    build_max_pd_volume_count()
    build_general_predicates()
    build_interpod_affinity()
    build_interpod_affinity_multi()
    build_pod_tolerates_taints()
    build_memory_pressure()
