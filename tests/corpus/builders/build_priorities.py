"""Transcription of the reference priority test tables into JSON fixtures.

Sources: plugin/pkg/scheduler/algorithm/priorities/priorities_test.go,
selector_spreading_test.go, node_affinity_test.go, taint_toleration_test.go,
interpod_affinity_test.go (table data only).
Run `python tests/corpus/builders/build_priorities.py` to regenerate.
"""

import json

from kubernetes_tpu.api.types import (
    AFFINITY_ANNOTATION,
    TAINTS_ANNOTATION,
    TOLERATIONS_ANNOTATION,
    Container,
    ContainerImage,
    LabelSelector,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ReplicaSet,
    ReplicaSetSpec,
    ReplicationController,
    ReplicationControllerSpec,
    Service,
    ServiceSpec,
)

from common import enc, enc_list, write_fixture

MB = 1024 * 1024
# priorities/util/non_zero.go DefaultMilliCpuRequest / DefaultMemoryRequest
DEFAULT_MILLI_CPU = 100
DEFAULT_MEMORY = 200 * MB
ZONE = "failure-domain.beta.kubernetes.io/zone"


def make_node(name, milli_cpu, memory):
    """priorities_test.go:37 makeNode."""
    rl = {"cpu": f"{milli_cpu}m", "memory": memory}
    return Node(metadata=ObjectMeta(name=name),
                status=NodeStatus(capacity=dict(rl), allocatable=dict(rl)))


def plain_node(name, labels=None):
    return Node(metadata=ObjectMeta(name=name, labels=labels or {}))


def req_pod(node_name="", reqs=(), labels=None, namespace="default",
            annotations=None):
    """A pod with per-container (milli_cpu, memory) requests."""
    containers = []
    for mc, mem in reqs:
        r = {}
        if mc is not None:
            r["cpu"] = f"{mc}m"
        if mem is not None:
            r["memory"] = mem
        containers.append(Container(requests=r))
    return Pod(
        metadata=ObjectMeta(labels=labels or {}, namespace=namespace,
                            annotations=annotations or {}),
        spec=PodSpec(node_name=node_name, containers=containers),
    )


def expected_map(pairs):
    return {host: score for host, score in pairs}


# --- TestZeroRequest (priorities_test.go:53) --------------------------------


def build_zero_request():
    no_resources = [(None, None)]  # one container, no requests
    small = [(DEFAULT_MILLI_CPU, DEFAULT_MEMORY)]
    large = [(DEFAULT_MILLI_CPU * 3, DEFAULT_MEMORY * 3)]
    nodes = [make_node("machine1", 1000, DEFAULT_MEMORY * 10),
             make_node("machine2", 1000, DEFAULT_MEMORY * 10)]
    backdrop = [
        req_pod("machine1", large), req_pod("machine1", no_resources),
        req_pod("machine2", large), req_pod("machine2", small),
    ]
    cases = [
        {"test": "test priority of zero-request pod with machine with zero-request pod",
         "pod": enc(req_pod("", no_resources)), "expect_all": 25},
        {"test": "test priority of nonzero-request pod with machine with zero-request pod",
         "pod": enc(req_pod("", small)), "expect_all": 25},
        {"test": "test priority of larger pod with machine with zero-request pod",
         "pod": enc(req_pod("", large)), "expect_all_not": 25},
    ]
    for c in cases:
        c["pods"] = enc_list(backdrop)
        c["nodes"] = enc_list(nodes)
    write_fixture("zero_request", {
        "source": "priorities_test.go:53 TestZeroRequest",
        "priorities": ["LeastRequestedPriority", "BalancedResourceAllocation",
                       "SelectorSpreadPriority"],
        "cases": cases,
    })


# --- TestLeastRequested (priorities_test.go:165) ----------------------------

LABELS1 = {"foo": "bar", "baz": "blah"}
LABELS2 = {"bar": "foo", "baz": "blah"}


def _cpu_only(node):
    return req_pod(node, [(1000, 0), (2000, 0)])


def _cpu_mem(node="machine2"):
    return req_pod(node, [(1000, 2000), (2000, 3000)])


def build_least_requested():
    m1 = req_pod("machine1")
    m2 = req_pod("machine2")
    table = [
        (req_pod(), [], [make_node("machine1", 4000, 10000),
                         make_node("machine2", 4000, 10000)],
         [("machine1", 10), ("machine2", 10)],
         "nothing scheduled, nothing requested"),
        (_cpu_mem(""), [], [make_node("machine1", 4000, 10000),
                            make_node("machine2", 6000, 10000)],
         [("machine1", 3), ("machine2", 5)],
         "nothing scheduled, resources requested, differently sized machines"),
        (req_pod(), [req_pod("machine1", labels=LABELS2),
                     req_pod("machine1", labels=LABELS1),
                     req_pod("machine2", labels=LABELS1),
                     req_pod("machine2", labels=LABELS1)],
         [make_node("machine1", 4000, 10000), make_node("machine2", 4000, 10000)],
         [("machine1", 10), ("machine2", 10)],
         "no resources requested, pods scheduled"),
        (req_pod(), [_cpu_only("machine1"), _cpu_only("machine1"),
                     _cpu_only("machine2"), _cpu_mem("machine2")],
         [make_node("machine1", 10000, 20000), make_node("machine2", 10000, 20000)],
         [("machine1", 7), ("machine2", 5)],
         "no resources requested, pods scheduled with resources"),
        (_cpu_mem(""), [_cpu_only("machine1"), _cpu_mem("machine2")],
         [make_node("machine1", 10000, 20000), make_node("machine2", 10000, 20000)],
         [("machine1", 5), ("machine2", 4)],
         "resources requested, pods scheduled with resources"),
        (_cpu_mem(""), [_cpu_only("machine1"), _cpu_mem("machine2")],
         [make_node("machine1", 10000, 20000), make_node("machine2", 10000, 50000)],
         [("machine1", 5), ("machine2", 6)],
         "resources requested, pods scheduled with resources, differently sized machines"),
        (_cpu_only(""), [_cpu_only("machine1"), _cpu_mem("machine2")],
         [make_node("machine1", 4000, 10000), make_node("machine2", 4000, 10000)],
         [("machine1", 5), ("machine2", 2)],
         "requested resources exceed node capacity"),
        (req_pod(), [_cpu_only("machine1"), _cpu_mem("machine2")],
         [make_node("machine1", 0, 0), make_node("machine2", 0, 0)],
         [("machine1", 0), ("machine2", 0)],
         "zero node resources, pods scheduled with resources"),
    ]
    # the labels on backdrop pods in cases 3/4 are irrelevant to this
    # priority; retained for fidelity
    _ = m1, m2
    cases = [{
        "test": test,
        "pod": enc(pod),
        "pods": enc_list(pods),
        "nodes": enc_list(nodes),
        "expected": expected_map(exp),
    } for pod, pods, nodes, exp, test in table]
    write_fixture("least_requested", {
        "source": "priorities_test.go:165 TestLeastRequested",
        "priority": "LeastRequestedPriority",
        "cases": cases,
    })


# --- TestBalancedResourceAllocation (priorities_test.go:498) ----------------


def build_balanced_allocation():
    table = [
        (req_pod(), [], [make_node("machine1", 4000, 10000),
                         make_node("machine2", 4000, 10000)],
         [("machine1", 10), ("machine2", 10)],
         "nothing scheduled, nothing requested"),
        (_cpu_mem(""), [], [make_node("machine1", 4000, 10000),
                            make_node("machine2", 6000, 10000)],
         [("machine1", 7), ("machine2", 10)],
         "nothing scheduled, resources requested, differently sized machines"),
        (req_pod(), [req_pod("machine1", labels=LABELS2),
                     req_pod("machine1", labels=LABELS1),
                     req_pod("machine2", labels=LABELS1),
                     req_pod("machine2", labels=LABELS1)],
         [make_node("machine1", 4000, 10000), make_node("machine2", 4000, 10000)],
         [("machine1", 10), ("machine2", 10)],
         "no resources requested, pods scheduled"),
        (req_pod(), [_cpu_only("machine1"), _cpu_only("machine1"),
                     _cpu_only("machine2"), _cpu_mem("machine2")],
         [make_node("machine1", 10000, 20000), make_node("machine2", 10000, 20000)],
         [("machine1", 4), ("machine2", 6)],
         "no resources requested, pods scheduled with resources"),
        (_cpu_mem(""), [_cpu_only("machine1"), _cpu_mem("machine2")],
         [make_node("machine1", 10000, 20000), make_node("machine2", 10000, 20000)],
         [("machine1", 6), ("machine2", 9)],
         "resources requested, pods scheduled with resources"),
        (_cpu_mem(""), [_cpu_only("machine1"), _cpu_mem("machine2")],
         [make_node("machine1", 10000, 20000), make_node("machine2", 10000, 50000)],
         [("machine1", 6), ("machine2", 6)],
         "resources requested, pods scheduled with resources, differently sized machines"),
        (_cpu_only(""), [_cpu_only("machine1"), _cpu_mem("machine2")],
         [make_node("machine1", 4000, 10000), make_node("machine2", 4000, 10000)],
         [("machine1", 0), ("machine2", 0)],
         "requested resources exceed node capacity"),
        (req_pod(), [_cpu_only("machine1"), _cpu_mem("machine2")],
         [make_node("machine1", 0, 0), make_node("machine2", 0, 0)],
         [("machine1", 0), ("machine2", 0)],
         "zero node resources, pods scheduled with resources"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "pods": enc_list(pods),
        "nodes": enc_list(nodes),
        "expected": expected_map(exp),
    } for pod, pods, nodes, exp, test in table]
    write_fixture("balanced_allocation", {
        "source": "priorities_test.go:498 TestBalancedResourceAllocation",
        "priority": "BalancedResourceAllocation",
        "cases": cases,
    })


# --- TestNewNodeLabelPriority (priorities_test.go:401) ----------------------


def build_node_label_priority():
    nodes = [plain_node("machine1", {"foo": "bar"}),
             plain_node("machine2", {"bar": "foo"}),
             plain_node("machine3", {"bar": "baz"})]
    table = [
        ("baz", True, [("machine1", 0), ("machine2", 0), ("machine3", 0)],
         "no match found, presence true"),
        ("baz", False, [("machine1", 10), ("machine2", 10), ("machine3", 10)],
         "no match found, presence false"),
        ("foo", True, [("machine1", 10), ("machine2", 0), ("machine3", 0)],
         "one match found, presence true"),
        ("foo", False, [("machine1", 0), ("machine2", 10), ("machine3", 10)],
         "one match found, presence false"),
        ("bar", True, [("machine1", 0), ("machine2", 10), ("machine3", 10)],
         "two matches found, presence true"),
        ("bar", False, [("machine1", 10), ("machine2", 0), ("machine3", 0)],
         "two matches found, presence false"),
    ]
    cases = [{
        "test": test,
        "pod": enc(Pod()),
        "pods": [],
        "nodes": enc_list(nodes),
        "label": label,
        "presence": presence,
        "expected": expected_map(exp),
    } for label, presence, exp, test in table]
    write_fixture("node_label_priority", {
        "source": "priorities_test.go:401 TestNewNodeLabelPriority",
        "priority": "NodeLabelPriority",
        "cases": cases,
    })


# --- TestImageLocalityPriority (priorities_test.go:734) ---------------------


def build_image_locality():
    def image_pod(*images):
        return Pod(spec=PodSpec(containers=[Container(image=i) for i in images]))

    node_40_140_2000 = Node(
        metadata=ObjectMeta(name="machine1"),
        status=NodeStatus(images=[
            ContainerImage(names=("gcr.io/40", "gcr.io/40:v1", "gcr.io/40:v1"),
                           size_bytes=40 * MB),
            ContainerImage(names=("gcr.io/140", "gcr.io/140:v1"),
                           size_bytes=140 * MB),
            ContainerImage(names=("gcr.io/2000",), size_bytes=2000 * MB),
        ]))
    node_250_10 = Node(
        metadata=ObjectMeta(name="machine2"),
        status=NodeStatus(images=[
            ContainerImage(names=("gcr.io/250",), size_bytes=250 * MB),
            ContainerImage(names=("gcr.io/10", "gcr.io/10:v1"),
                           size_bytes=10 * MB),
        ]))
    nodes = [node_40_140_2000, node_250_10]
    table = [
        (image_pod("gcr.io/40", "gcr.io/250"),
         [("machine1", 1), ("machine2", 3)],
         "two images spread on two nodes, prefer the larger image one"),
        (image_pod("gcr.io/40", "gcr.io/140"),
         [("machine1", 2), ("machine2", 0)],
         "two images on one node, prefer this node"),
        (image_pod("gcr.io/10", "gcr.io/2000"),
         [("machine1", 10), ("machine2", 0)],
         "if exceed limit, use limit"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "pods": [],
        "nodes": enc_list(nodes),
        "expected": expected_map(exp),
    } for pod, exp, test in table]
    write_fixture("image_locality", {
        "source": "priorities_test.go:734 TestImageLocalityPriority",
        "priority": "ImageLocalityPriority",
        "cases": cases,
    })


# --- TestSelectorSpreadPriority (selector_spreading_test.go:33) -------------


def lpod(node, labels=None, namespace=""):
    # Go's zero-value Namespace is "" and the spreading tables rely on ""
    # differing from NamespaceDefault — preserve it exactly.
    return Pod(metadata=ObjectMeta(labels=labels or {}, namespace=namespace),
               spec=PodSpec(node_name=node))


def svc(selector, namespace=""):
    return Service(metadata=ObjectMeta(namespace=namespace),
                   spec=ServiceSpec(selector=selector))


def rc(selector):
    return ReplicationController(
        metadata=ObjectMeta(namespace=""),
        spec=ReplicationControllerSpec(selector=selector))


def rs(match_labels):
    return ReplicaSet(
        metadata=ObjectMeta(namespace=""),
        spec=ReplicaSetSpec(selector=LabelSelector(match_labels=match_labels)))


def build_selector_spread():
    z1 = "machine1"
    z2 = "machine2"
    nodes = [plain_node("machine1"), plain_node("machine2")]
    table = [
        (Pod(), [], [], [], [], [("machine1", 10), ("machine2", 10)],
         "nothing scheduled"),
        (lpod("", LABELS1), [lpod(z1)], [], [], [],
         [("machine1", 10), ("machine2", 10)], "no services"),
        (lpod("", LABELS1), [lpod(z1, LABELS2)], [svc({"key": "value"})], [], [],
         [("machine1", 10), ("machine2", 10)], "different services"),
        (lpod("", LABELS1), [lpod(z1, LABELS2), lpod(z2, LABELS1)],
         [svc(LABELS1)], [], [],
         [("machine1", 10), ("machine2", 0)], "two pods, one service pod"),
        (lpod("", LABELS1),
         [lpod(z1, LABELS2), lpod(z1, LABELS1, "default"),
          lpod(z1, LABELS1, "ns1"), lpod(z2, LABELS1), lpod(z2, LABELS2)],
         [svc(LABELS1)], [], [],
         [("machine1", 10), ("machine2", 0)],
         "five pods, one service pod in no namespace"),
        (lpod("", LABELS1, "default"),
         [lpod(z1, LABELS1), lpod(z1, LABELS1, "ns1"),
          lpod(z2, LABELS1, "default"), lpod(z2, LABELS2)],
         [svc(LABELS1, "default")], [], [],
         [("machine1", 10), ("machine2", 0)],
         "four pods, one service pod in default namespace"),
        (lpod("", LABELS1, "ns1"),
         [lpod(z1, LABELS1), lpod(z1, LABELS1, "default"),
          lpod(z1, LABELS1, "ns2"), lpod(z2, LABELS1, "ns1"),
          lpod(z2, LABELS2)],
         [svc(LABELS1, "ns1")], [], [],
         [("machine1", 10), ("machine2", 0)],
         "five pods, one service pod in specific namespace"),
        (lpod("", LABELS1),
         [lpod(z1, LABELS2), lpod(z1, LABELS1), lpod(z2, LABELS1)],
         [svc(LABELS1)], [], [],
         [("machine1", 0), ("machine2", 0)],
         "three pods, two service pods on different machines"),
        (lpod("", LABELS1),
         [lpod(z1, LABELS2), lpod(z1, LABELS1), lpod(z2, LABELS1),
          lpod(z2, LABELS1)],
         [svc(LABELS1)], [], [],
         [("machine1", 5), ("machine2", 0)],
         "four pods, three service pods"),
        (lpod("", LABELS1),
         [lpod(z1, LABELS2), lpod(z1, LABELS1), lpod(z2, LABELS1)],
         [svc({"baz": "blah"})], [], [],
         [("machine1", 0), ("machine2", 5)],
         "service with partial pod label matches"),
        (lpod("", LABELS1),
         [lpod(z1, LABELS2), lpod(z1, LABELS1), lpod(z2, LABELS1)],
         [svc({"baz": "blah"})], [rc({"foo": "bar"})], [],
         [("machine1", 0), ("machine2", 5)],
         "service with partial pod label matches with service and replication controller"),
        (lpod("", LABELS1),
         [lpod(z1, LABELS2), lpod(z1, LABELS1), lpod(z2, LABELS1)],
         [svc({"baz": "blah"})], [], [rs({"foo": "bar"})],
         [("machine1", 0), ("machine2", 5)],
         "service with partial pod label matches with service and replica set"),
        (lpod("", {"foo": "bar", "bar": "foo"}),
         [lpod(z1, LABELS2), lpod(z1, LABELS1), lpod(z2, LABELS1)],
         [svc({"bar": "foo"})], [rc({"foo": "bar"})], [],
         [("machine1", 0), ("machine2", 5)],
         "disjoined service and replication controller should be treated equally"),
        (lpod("", {"foo": "bar", "bar": "foo"}),
         [lpod(z1, LABELS2), lpod(z1, LABELS1), lpod(z2, LABELS1)],
         [svc({"bar": "foo"})], [], [rs({"foo": "bar"})],
         [("machine1", 0), ("machine2", 5)],
         "disjoined service and replica set should be treated equally"),
        (lpod("", LABELS1),
         [lpod(z1, LABELS2), lpod(z1, LABELS1), lpod(z2, LABELS1)],
         [], [rc({"foo": "bar"})], [],
         [("machine1", 0), ("machine2", 0)],
         "Replication controller with partial pod label matches"),
        (lpod("", LABELS1),
         [lpod(z1, LABELS2), lpod(z1, LABELS1), lpod(z2, LABELS1)],
         [], [], [rs({"foo": "bar"})],
         [("machine1", 0), ("machine2", 0)],
         "Replica set with partial pod label matches"),
        (lpod("", LABELS1),
         [lpod(z1, LABELS2), lpod(z1, LABELS1), lpod(z2, LABELS1)],
         [], [rc({"baz": "blah"})], [],
         [("machine1", 0), ("machine2", 5)],
         "Replication controller with full pod label matches"),
        (lpod("", LABELS1),
         [lpod(z1, LABELS2), lpod(z1, LABELS1), lpod(z2, LABELS1)],
         [], [], [rs({"baz": "blah"})],
         [("machine1", 0), ("machine2", 5)],
         "Replica set with full pod label matches"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "pods": enc_list(pods),
        "nodes": enc_list(nodes),
        "services": enc_list(services),
        "rcs": enc_list(rcs),
        "rss": enc_list(rss),
        "expected": expected_map(exp),
    } for pod, pods, services, rcs, rss, exp, test in table]
    write_fixture("selector_spread", {
        "source": "selector_spreading_test.go:33 TestSelectorSpreadPriority",
        "priority": "SelectorSpreadPriority",
        "cases": cases,
    })


# --- TestZoneSelectorSpreadPriority (selector_spreading_test.go:291) --------


def build_zone_selector_spread():
    zlabels1 = {"label1": "l1", "baz": "blah"}
    zlabels2 = {"label2": "l2", "baz": "blah"}
    n11, n12, n22 = "machine1.zone1", "machine1.zone2", "machine2.zone2"
    n13, n23, n33 = "machine1.zone3", "machine2.zone3", "machine3.zone3"
    nodes = [plain_node(n11, {ZONE: "zone1"}),
             plain_node(n12, {ZONE: "zone2"}),
             plain_node(n22, {ZONE: "zone2"}),
             plain_node(n13, {ZONE: "zone3"}),
             plain_node(n23, {ZONE: "zone3"}),
             plain_node(n33, {ZONE: "zone3"})]
    all10 = [(n11, 10), (n12, 10), (n22, 10), (n13, 10), (n23, 10), (n33, 10)]
    table = [
        (Pod(), [], [], [], all10, "nothing scheduled"),
        (lpod("", zlabels1), [lpod(n11)], [], [], all10, "no services"),
        (lpod("", zlabels1), [lpod(n11, zlabels2)],
         [svc({"key": "value"})], [], all10, "different services"),
        (lpod("", zlabels1), [lpod(n11, zlabels2), lpod(n12, zlabels1)],
         [svc(zlabels1)], [],
         [(n11, 10), (n12, 0), (n22, 3), (n13, 10), (n23, 10), (n33, 10)],
         "two pods, 1 matching (in z2)"),
        (lpod("", zlabels1),
         [lpod(n11, zlabels2), lpod(n12, zlabels1), lpod(n22, zlabels1),
          lpod(n13, zlabels2), lpod(n23, zlabels1)],
         [svc(zlabels1)], [],
         [(n11, 10), (n12, 0), (n22, 0), (n13, 6), (n23, 3), (n33, 6)],
         "five pods, 3 matching (z2=2, z3=1)"),
        (lpod("", zlabels1),
         [lpod(n11, zlabels1), lpod(n12, zlabels1), lpod(n22, zlabels2),
          lpod(n13, zlabels1)],
         [svc(zlabels1)], [],
         [(n11, 0), (n12, 0), (n22, 3), (n13, 0), (n23, 3), (n33, 3)],
         "four pods, 3 matching (z1=1, z2=1, z3=1)"),
        (lpod("", zlabels1),
         [lpod(n11, zlabels1), lpod(n12, zlabels1), lpod(n13, zlabels1),
          lpod(n22, zlabels2)],
         [svc(zlabels1)], [],
         [(n11, 0), (n12, 0), (n22, 3), (n13, 0), (n23, 3), (n33, 3)],
         "four pods, 3 matching (z1=1, z2=1, z3=1) (2)"),
        (lpod("", zlabels1),
         [lpod(n13, zlabels1), lpod(n12, zlabels1), lpod(n13, zlabels1)],
         [], [rc(zlabels1)],
         [(n11, 10), (n12, 5), (n22, 6), (n13, 0), (n23, 3), (n33, 3)],
         "Replication controller spreading (z1=0, z2=1, z3=2)"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "pods": enc_list(pods),
        "nodes": enc_list(nodes),
        "services": enc_list(services),
        "rcs": enc_list(rcs),
        "rss": [],
        "expected": expected_map(exp),
    } for pod, pods, services, rcs, exp, test in table]
    write_fixture("zone_selector_spread", {
        "source": "selector_spreading_test.go:291 TestZoneSelectorSpreadPriority",
        "priority": "SelectorSpreadPriority",
        "cases": cases,
    })


# --- TestZoneSpreadPriority (selector_spreading_test.go:495) ----------------


def build_zone_spread():
    zone1 = {"zone": "zone1"}
    zone2 = {"zone": "zone2"}
    nozone = {"name": "value"}
    nodes = [plain_node("machine01", nozone), plain_node("machine02", nozone),
             plain_node("machine11", zone1), plain_node("machine12", zone1),
             plain_node("machine21", zone2), plain_node("machine22", zone2)]
    z0, z1s, z2s = "machine01", "machine11", "machine21"
    table = [
        (Pod(), [], [],
         [("machine11", 10), ("machine12", 10), ("machine21", 10),
          ("machine22", 10), ("machine01", 0), ("machine02", 0)],
         "nothing scheduled"),
        (lpod("", LABELS1), [lpod(z1s)], [],
         [("machine11", 10), ("machine12", 10), ("machine21", 10),
          ("machine22", 10), ("machine01", 0), ("machine02", 0)],
         "no services"),
        (lpod("", LABELS1), [lpod(z1s, LABELS2)], [svc({"key": "value"})],
         [("machine11", 10), ("machine12", 10), ("machine21", 10),
          ("machine22", 10), ("machine01", 0), ("machine02", 0)],
         "different services"),
        (lpod("", LABELS1),
         [lpod(z0, LABELS2), lpod(z1s, LABELS2), lpod(z2s, LABELS1)],
         [svc(LABELS1)],
         [("machine11", 10), ("machine12", 10), ("machine21", 0),
          ("machine22", 0), ("machine01", 0), ("machine02", 0)],
         "three pods, one service pod"),
        (lpod("", LABELS1),
         [lpod(z1s, LABELS2), lpod(z1s, LABELS1), lpod(z2s, LABELS1)],
         [svc(LABELS1)],
         [("machine11", 5), ("machine12", 5), ("machine21", 5),
          ("machine22", 5), ("machine01", 0), ("machine02", 0)],
         "three pods, two service pods on different machines"),
        (lpod("", LABELS1, "default"),
         [lpod(z1s, LABELS1), lpod(z1s, LABELS1, "default"),
          lpod(z2s, LABELS1), lpod(z2s, LABELS1, "ns1")],
         [svc(LABELS1, "default")],
         [("machine11", 0), ("machine12", 0), ("machine21", 10),
          ("machine22", 10), ("machine01", 0), ("machine02", 0)],
         "three service label match pods in different namespaces"),
        (lpod("", LABELS1),
         [lpod(z1s, LABELS2), lpod(z1s, LABELS1), lpod(z2s, LABELS1),
          lpod(z2s, LABELS1)],
         [svc(LABELS1)],
         [("machine11", 6), ("machine12", 6), ("machine21", 3),
          ("machine22", 3), ("machine01", 0), ("machine02", 0)],
         "four pods, three service pods"),
        (lpod("", LABELS1),
         [lpod(z1s, LABELS2), lpod(z1s, LABELS1), lpod(z2s, LABELS1)],
         [svc({"baz": "blah"})],
         [("machine11", 3), ("machine12", 3), ("machine21", 6),
          ("machine22", 6), ("machine01", 0), ("machine02", 0)],
         "service with partial pod label matches"),
        (lpod("", LABELS1),
         [lpod(z0, LABELS1), lpod(z1s, LABELS1), lpod(z2s, LABELS1),
          lpod(z2s, LABELS1)],
         [svc(LABELS1)],
         [("machine11", 7), ("machine12", 7), ("machine21", 5),
          ("machine22", 5), ("machine01", 0), ("machine02", 0)],
         "service pod on non-zoned node"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "pods": enc_list(pods),
        "nodes": enc_list(nodes),
        "services": enc_list(services),
        "label": "zone",
        "expected": expected_map(exp),
    } for pod, pods, services, exp, test in table]
    write_fixture("zone_spread", {
        "source": "selector_spreading_test.go:495 TestZoneSpreadPriority",
        "priority": "ServiceAntiAffinityPriority",
        "cases": cases,
    })


# --- TestNodeAffinityPriority (node_affinity_test.go:29) --------------------


def build_node_affinity_priority():
    affinity1 = {AFFINITY_ANNOTATION: json.dumps({
        "nodeAffinity": {"preferredDuringSchedulingIgnoredDuringExecution": [
            {"weight": 2, "preference": {"matchExpressions": [
                {"key": "foo", "operator": "In", "values": ["bar"]}]}},
        ]}})}
    affinity2 = {AFFINITY_ANNOTATION: json.dumps({
        "nodeAffinity": {"preferredDuringSchedulingIgnoredDuringExecution": [
            {"weight": 2, "preference": {"matchExpressions": [
                {"key": "foo", "operator": "In", "values": ["bar"]}]}},
            {"weight": 4, "preference": {"matchExpressions": [
                {"key": "key", "operator": "In", "values": ["value"]}]}},
            {"weight": 5, "preference": {"matchExpressions": [
                {"key": "foo", "operator": "In", "values": ["bar"]},
                {"key": "key", "operator": "In", "values": ["value"]},
                {"key": "az", "operator": "In", "values": ["az1"]}]}},
        ]}})}
    label1 = {"foo": "bar"}
    label2 = {"key": "value"}
    label3 = {"az": "az1"}
    label4 = {"abc": "az11", "def": "az22"}
    label5 = {"foo": "bar", "key": "value", "az": "az1"}
    table = [
        (Pod(metadata=ObjectMeta(annotations={})),
         [plain_node("machine1", label1), plain_node("machine2", label2),
          plain_node("machine3", label3)],
         [("machine1", 0), ("machine2", 0), ("machine3", 0)],
         "all machines are same priority as NodeAffinity is nil"),
        (Pod(metadata=ObjectMeta(annotations=affinity1)),
         [plain_node("machine1", label4), plain_node("machine2", label2),
          plain_node("machine3", label3)],
         [("machine1", 0), ("machine2", 0), ("machine3", 0)],
         "no machine matches preferred scheduling requirements in NodeAffinity of pod so all machines' priority is zero"),
        (Pod(metadata=ObjectMeta(annotations=affinity1)),
         [plain_node("machine1", label1), plain_node("machine2", label2),
          plain_node("machine3", label3)],
         [("machine1", 10), ("machine2", 0), ("machine3", 0)],
         "only machine1 matches the preferred scheduling requirements of pod"),
        (Pod(metadata=ObjectMeta(annotations=affinity2)),
         [plain_node("machine1", label1), plain_node("machine5", label5),
          plain_node("machine2", label2)],
         [("machine1", 1), ("machine5", 10), ("machine2", 3)],
         "all machines matches the preferred scheduling requirements of pod but with different priorities"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "pods": [],
        "nodes": enc_list(nodes),
        "expected": expected_map(exp),
    } for pod, nodes, exp, test in table]
    write_fixture("node_affinity_priority", {
        "source": "node_affinity_test.go:29 TestNodeAffinityPriority",
        "priority": "NodeAffinityPriority",
        "cases": cases,
    })


# --- TestTaintAndToleration (taint_toleration_test.go:57) -------------------


def build_taint_toleration_priority():
    def tnode(name, taints):
        return Node(metadata=ObjectMeta(
            name=name, annotations={TAINTS_ANNOTATION: json.dumps(taints)}))

    def tpod(tolerations):
        return Pod(metadata=ObjectMeta(
            annotations={TOLERATIONS_ANNOTATION: json.dumps(tolerations)}))

    table = [
        (tpod([{"key": "foo", "operator": "Equal", "value": "bar",
                "effect": "PreferNoSchedule"}]),
         [tnode("nodeA", [{"key": "foo", "value": "bar",
                           "effect": "PreferNoSchedule"}]),
          tnode("nodeB", [{"key": "foo", "value": "blah",
                           "effect": "PreferNoSchedule"}])],
         [("nodeA", 10), ("nodeB", 0)],
         "node with taints tolerated by the pod, gets a higher score than those node with intolerable taints"),
        (tpod([{"key": "cpu-type", "operator": "Equal", "value": "arm64",
                "effect": "PreferNoSchedule"},
               {"key": "disk-type", "operator": "Equal", "value": "ssd",
                "effect": "PreferNoSchedule"}]),
         [tnode("nodeA", []),
          tnode("nodeB", [{"key": "cpu-type", "value": "arm64",
                           "effect": "PreferNoSchedule"}]),
          tnode("nodeC", [{"key": "cpu-type", "value": "arm64",
                           "effect": "PreferNoSchedule"},
                          {"key": "disk-type", "value": "ssd",
                           "effect": "PreferNoSchedule"}])],
         [("nodeA", 10), ("nodeB", 10), ("nodeC", 10)],
         "the nodes that all of their taints are tolerated by the pod, get the same score, no matter how many tolerable taints a node has"),
        (tpod([{"key": "foo", "operator": "Equal", "value": "bar",
                "effect": "PreferNoSchedule"}]),
         [tnode("nodeA", []),
          tnode("nodeB", [{"key": "cpu-type", "value": "arm64",
                           "effect": "PreferNoSchedule"}]),
          tnode("nodeC", [{"key": "cpu-type", "value": "arm64",
                           "effect": "PreferNoSchedule"},
                          {"key": "disk-type", "value": "ssd",
                           "effect": "PreferNoSchedule"}])],
         [("nodeA", 10), ("nodeB", 5), ("nodeC", 0)],
         "the more intolerable taints a node has, the lower score it gets."),
        (tpod([{"key": "cpu-type", "operator": "Equal", "value": "arm64",
                "effect": "NoSchedule"},
               {"key": "disk-type", "operator": "Equal", "value": "ssd",
                "effect": "NoSchedule"}]),
         [tnode("nodeA", []),
          tnode("nodeB", [{"key": "cpu-type", "value": "arm64",
                           "effect": "NoSchedule"}]),
          tnode("nodeC", [{"key": "cpu-type", "value": "arm64",
                           "effect": "PreferNoSchedule"},
                          {"key": "disk-type", "value": "ssd",
                           "effect": "PreferNoSchedule"}])],
         [("nodeA", 10), ("nodeB", 10), ("nodeC", 0)],
         "only taints and tolerations that have effect PreferNoSchedule are checked by taints-tolerations priority function"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "pods": [],
        "nodes": enc_list(nodes),
        "expected": expected_map(exp),
    } for pod, nodes, exp, test in table]
    write_fixture("taint_toleration_priority", {
        "source": "taint_toleration_test.go:57 TestTaintAndToleration",
        "priority": "TaintTolerationPriority",
        "cases": cases,
    })


# --- TestInterPodAffinityPriority (interpod_affinity_test.go:44) ------------


def build_interpod_priority():
    rg_china = {"region": "China"}
    rg_india = {"region": "India"}
    az1 = {"az": "az1"}
    az2 = {"az": "az2"}
    rg_china_az1 = {"region": "China", "az": "az1"}
    s1 = {"security": "S1"}
    s2 = {"security": "S2"}

    def ann(d):
        return {AFFINITY_ANNOTATION: json.dumps(d)}

    stay_s1_region = ann({"podAffinity": {
        "preferredDuringSchedulingIgnoredDuringExecution": [{
            "weight": 5, "podAffinityTerm": {
                "labelSelector": {"matchExpressions": [
                    {"key": "security", "operator": "In", "values": ["S1"]}]},
                "namespaces": [], "topologyKey": "region"}}]}})
    stay_s2_region = ann({"podAffinity": {
        "preferredDuringSchedulingIgnoredDuringExecution": [{
            "weight": 6, "podAffinityTerm": {
                "labelSelector": {"matchExpressions": [
                    {"key": "security", "operator": "In", "values": ["S2"]}]},
                "namespaces": [], "topologyKey": "region"}}]}})
    affinity3 = ann({"podAffinity": {
        "preferredDuringSchedulingIgnoredDuringExecution": [
            {"weight": 8, "podAffinityTerm": {
                "labelSelector": {"matchExpressions": [
                    {"key": "security", "operator": "NotIn", "values": ["S1"]},
                    {"key": "security", "operator": "In", "values": ["S2"]}]},
                "namespaces": [], "topologyKey": "region"}},
            {"weight": 2, "podAffinityTerm": {
                "labelSelector": {"matchExpressions": [
                    {"key": "security", "operator": "Exists"},
                    {"key": "wrongkey", "operator": "DoesNotExist"}]},
                "namespaces": [], "topologyKey": "region"}},
        ]}})
    hard_affinity = ann({"podAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": [
            {"labelSelector": {"matchExpressions": [
                {"key": "security", "operator": "In", "values": ["S1", "value2"]}]},
             "namespaces": [], "topologyKey": "region"},
            {"labelSelector": {"matchExpressions": [
                {"key": "security", "operator": "Exists"},
                {"key": "wrongkey", "operator": "DoesNotExist"}]},
             "namespaces": [], "topologyKey": "region"},
        ]}})
    away_s1_az = ann({"podAntiAffinity": {
        "preferredDuringSchedulingIgnoredDuringExecution": [{
            "weight": 5, "podAffinityTerm": {
                "labelSelector": {"matchExpressions": [
                    {"key": "security", "operator": "In", "values": ["S1"]}]},
                "namespaces": [], "topologyKey": "az"}}]}})
    away_s2_az = ann({"podAntiAffinity": {
        "preferredDuringSchedulingIgnoredDuringExecution": [{
            "weight": 5, "podAffinityTerm": {
                "labelSelector": {"matchExpressions": [
                    {"key": "security", "operator": "In", "values": ["S2"]}]},
                "namespaces": [], "topologyKey": "az"}}]}})
    stay_s1_away_s2 = ann({
        "podAffinity": {"preferredDuringSchedulingIgnoredDuringExecution": [{
            "weight": 8, "podAffinityTerm": {
                "labelSelector": {"matchExpressions": [
                    {"key": "security", "operator": "In", "values": ["S1"]}]},
                "namespaces": [], "topologyKey": "region"}}]},
        "podAntiAffinity": {"preferredDuringSchedulingIgnoredDuringExecution": [{
            "weight": 5, "podAffinityTerm": {
                "labelSelector": {"matchExpressions": [
                    {"key": "security", "operator": "In", "values": ["S2"]}]},
                "namespaces": [], "topologyKey": "az"}}]}})

    def apod(labels=None, annotations=None, node=""):
        return Pod(metadata=ObjectMeta(labels=labels or {},
                                       annotations=annotations or {}),
                   spec=PodSpec(node_name=node))

    table = [
        (apod(s1, {}), [],
         [plain_node("machine1", rg_china), plain_node("machine2", rg_india),
          plain_node("machine3", az1)],
         [("machine1", 0), ("machine2", 0), ("machine3", 0)],
         "all machines are same priority as Affinity is nil"),
        (apod(s1, stay_s1_region),
         [apod(s1, node="machine1"), apod(s2, node="machine2"),
          apod(s1, node="machine3")],
         [plain_node("machine1", rg_china), plain_node("machine2", rg_india),
          plain_node("machine3", az1)],
         [("machine1", 10), ("machine2", 0), ("machine3", 0)],
         "Affinity: pod that matches topology key & pods in nodes will get high score comparing to others which doesn't match either pods in nodes or in topology key"),
        (apod(None, stay_s1_region),
         [apod(s1, node="machine1")],
         [plain_node("machine1", rg_china),
          plain_node("machine2", rg_china_az1),
          plain_node("machine3", rg_india)],
         [("machine1", 10), ("machine2", 10), ("machine3", 0)],
         "All the nodes that have the same topology key & label value with one of them has an existing pod that match the affinity rules, have the same score"),
        (apod(s1, stay_s2_region),
         [apod(s2, node="machine1"), apod(s2, node="machine1"),
          apod(s2, node="machine2"), apod(s2, node="machine3"),
          apod(s2, node="machine4"), apod(s2, node="machine5")],
         [plain_node("machine1", rg_china), plain_node("machine2", rg_india),
          plain_node("machine3", rg_china), plain_node("machine4", rg_china),
          plain_node("machine5", rg_india)],
         [("machine1", 10), ("machine2", 5), ("machine3", 10),
          ("machine4", 10), ("machine5", 5)],
         "Affinity: nodes in one region has more matching pods comparing to other region, so the region which has more matches will get high score"),
        (apod(s1, affinity3),
         [apod(s1, node="machine1"), apod(s2, node="machine2"),
          apod(s1, node="machine3")],
         [plain_node("machine1", rg_china), plain_node("machine2", rg_india),
          plain_node("machine3", az1)],
         [("machine1", 2), ("machine2", 10), ("machine3", 0)],
         "Affinity: different Label operators and values for pod affinity scheduling preference, including some match failures"),
        (apod(s2),
         [apod(s1, stay_s1_region, "machine1"),
          apod(s2, stay_s2_region, "machine2")],
         [plain_node("machine1", rg_china), plain_node("machine2", rg_india),
          plain_node("machine3", az1)],
         [("machine1", 0), ("machine2", 10), ("machine3", 0)],
         "Affinity symmetry: considered only the preferredDuringSchedulingIgnoredDuringExecution in pod affinity symmetry"),
        (apod(s1),
         [apod(s1, hard_affinity, "machine1"),
          apod(s2, hard_affinity, "machine2")],
         [plain_node("machine1", rg_china), plain_node("machine2", rg_india),
          plain_node("machine3", az1)],
         [("machine1", 10), ("machine2", 10), ("machine3", 0)],
         "Affinity symmetry: considered RequiredDuringSchedulingIgnoredDuringExecution in pod affinity symmetry"),
        (apod(s1, away_s1_az),
         [apod(s1, node="machine1"), apod(s2, node="machine2")],
         [plain_node("machine1", az1), plain_node("machine2", rg_china)],
         [("machine1", 0), ("machine2", 10)],
         "Anti Affinity: pod that does not match existing pods in node will get high score"),
        (apod(s1, away_s1_az),
         [apod(s1, node="machine1"), apod(s1, node="machine2")],
         [plain_node("machine1", az1), plain_node("machine2", rg_china)],
         [("machine1", 0), ("machine2", 10)],
         "Anti Affinity: pod that does not match topology key & matches the pods in nodes will get higher score comparing to others"),
        (apod(s1, away_s1_az),
         [apod(s1, node="machine1"), apod(s1, node="machine1"),
          apod(s2, node="machine2")],
         [plain_node("machine1", az1), plain_node("machine2", rg_india)],
         [("machine1", 0), ("machine2", 10)],
         "Anti Affinity: one node has more matching pods comparing to other node, so the node which has more unmatches will get high score"),
        (apod(s2),
         [apod(s1, away_s2_az, "machine1"), apod(s2, away_s1_az, "machine2")],
         [plain_node("machine1", az1), plain_node("machine2", az2)],
         [("machine1", 0), ("machine2", 10)],
         "Anti Affinity symmetry: the existing pods in node which has anti affinity match will get high score"),
        (apod(s1, stay_s1_away_s2),
         [apod(s1, node="machine1"), apod(s1, node="machine2")],
         [plain_node("machine1", rg_china), plain_node("machine2", az1)],
         [("machine1", 10), ("machine2", 0)],
         "Affinity and Anti Affinity: considered only preferredDuringSchedulingIgnoredDuringExecution in both pod affinity & anti affinity"),
        (apod(s1, stay_s1_away_s2),
         [apod(s1, node="machine1"), apod(s1, node="machine1"),
          apod(s1, node="machine2"), apod(s1, node="machine3"),
          apod(s1, node="machine3"), apod(s1, node="machine4"),
          apod(s1, node="machine5")],
         [plain_node("machine1", rg_china_az1), plain_node("machine2", rg_india),
          plain_node("machine3", rg_china), plain_node("machine4", rg_china),
          plain_node("machine5", rg_india)],
         [("machine1", 10), ("machine2", 4), ("machine3", 10),
          ("machine4", 10), ("machine5", 4)],
         "Affinity and Anti Affinity: considering both affinity and anti-affinity, the pod to schedule and existing pods have the same labels"),
        (apod(s1, stay_s1_away_s2),
         [apod(s1, node="machine1"), apod(s2, node="machine2"),
          apod(None, stay_s1_away_s2, "machine3"),
          apod(None, away_s1_az, "machine4")],
         [plain_node("machine1", rg_china), plain_node("machine2", az1),
          plain_node("machine3", rg_india), plain_node("machine4", az2)],
         [("machine1", 10), ("machine2", 0), ("machine3", 10), ("machine4", 0)],
         "Affinity and Anti Affinity and symmetry: considered only preferredDuringSchedulingIgnoredDuringExecution in both pod affinity & anti affinity & symmetry"),
    ]
    cases = [{
        "test": test,
        "pod": enc(pod),
        "pods": enc_list(pods),
        "nodes": enc_list(nodes),
        "hard_pod_affinity_weight": 1,
        "expected": expected_map(exp),
    } for pod, pods, nodes, exp, test in table]
    write_fixture("interpod_priority", {
        "source": "interpod_affinity_test.go:44 TestInterPodAffinityPriority",
        "priority": "InterPodAffinityPriority",
        "cases": cases,
    })

    # TestHardPodAffinitySymmetricWeight (interpod_affinity_test.go:517)
    hard_pod_affinity = ann({"podAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": [
            {"labelSelector": {"matchExpressions": [
                {"key": "service", "operator": "In", "values": ["S1"]}]},
             "namespaces": [], "topologyKey": "region"}]}})
    service_s1 = {"service": "S1"}
    hw_cases = []
    for weight, exp, test in [
        (1, [("machine1", 10), ("machine2", 10), ("machine3", 0)],
         "Hard Pod Affinity symmetry: hard pod affinity symmetry weights 1 by default, then nodes that match the hard pod affinity symmetry rules, get a high score"),
        (0, [("machine1", 0), ("machine2", 0), ("machine3", 0)],
         "Hard Pod Affinity symmetry: hard pod affinity symmetry is closed(weights 0), then nodes that match the hard pod affinity symmetry rules, get same score with those not match"),
    ]:
        hw_cases.append({
            "test": test,
            "pod": enc(apod(service_s1)),
            "pods": enc_list([apod(None, hard_pod_affinity, "machine1"),
                              apod(None, hard_pod_affinity, "machine2")]),
            "nodes": enc_list([plain_node("machine1", rg_china),
                               plain_node("machine2", rg_india),
                               plain_node("machine3", az1)]),
            "hard_pod_affinity_weight": weight,
            "expected": expected_map(exp),
        })
    write_fixture("hard_pod_affinity_weight", {
        "source": "interpod_affinity_test.go:517 TestHardPodAffinitySymmetricWeight",
        "priority": "InterPodAffinityPriority",
        "cases": hw_cases,
    })

    # TestSoftPodAntiAffinityWithFailureDomains (interpod_affinity_test.go:605)
    anti_empty_topo = ann({"podAntiAffinity": {
        "preferredDuringSchedulingIgnoredDuringExecution": [{
            "weight": 5, "podAffinityTerm": {
                "labelSelector": {"matchExpressions": [
                    {"key": "security", "operator": "In", "values": ["S1"]}]},
                "namespaces": [], "topologyKey": ""}}]}})
    fd_cases = [
        {
            "test": "Soft Pod Anti Affinity: when the topologyKey is empty, match among topologyKeys indicated by failure domains.",
            "pod": enc(apod(s1, anti_empty_topo)),
            "pods": enc_list([apod(s1, node="machine1"),
                              apod(s1, node="machine2")]),
            "nodes": enc_list([plain_node("machine1", {ZONE: "az1"}),
                               plain_node("machine2", az1)]),
            "failure_domains": "default",
            "hard_pod_affinity_weight": 1,
            "expected": expected_map([("machine1", 0), ("machine2", 10)]),
        },
        {
            "test": "Soft Pod Anti Affinity: when the topologyKey is empty, and no failure domains indicated, regard as topologyKey not match.",
            "pod": enc(apod(s1, anti_empty_topo)),
            "pods": enc_list([apod(s1, node="machine1"),
                              apod(s1, node="machine2")]),
            "nodes": enc_list([plain_node("machine1", {ZONE: "az1"}),
                               plain_node("machine2", az1)]),
            "failure_domains": "none",
            "hard_pod_affinity_weight": 1,
            "oracle_only": True,
            "expected": expected_map([("machine1", 0), ("machine2", 0)]),
        },
    ]
    write_fixture("soft_anti_affinity_failure_domains", {
        "source": "interpod_affinity_test.go:605 TestSoftPodAntiAffinityWithFailureDomains",
        "priority": "InterPodAffinityPriority",
        "cases": fd_cases,
    })


if __name__ == "__main__":
    build_zero_request()
    build_least_requested()
    build_balanced_allocation()
    build_node_label_priority()
    build_image_locality()
    build_selector_spread()
    build_zone_selector_spread()
    build_zone_spread()
    build_node_affinity_priority()
    build_taint_toleration_priority()
    build_interpod_priority()
