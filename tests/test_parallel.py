"""Mesh-sharded scheduler vs single-device scheduler: identical results
on an 8-virtual-device CPU mesh (the kubemark idea: real program, fake
chips — SURVEY.md §4)."""

import random

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from kubernetes_tpu.models.batch import BatchScheduler, SchedulerConfig
from kubernetes_tpu.parallel.mesh import MeshBatchScheduler
from kubernetes_tpu.snapshot.encode import SnapshotEncoder
from tests.test_conformance import random_scenario, run_both


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    assert len(devices) == 8, "conftest must force 8 CPU devices"
    return Mesh(np.array(devices), ("nodes",))


@pytest.mark.parametrize("seed", [1, 4])
def test_mesh_matches_single_device(mesh, seed):
    rng = random.Random(seed)
    # 13 nodes: NOT divisible by 8 -> exercises dummy-node padding
    state, pending = random_scenario(rng, n_nodes=13, n_existing=10, n_pending=18)
    snap, batch = SnapshotEncoder(state, pending).encode()

    single = BatchScheduler(SchedulerConfig()).schedule_names(snap, batch)
    sharded = MeshBatchScheduler(mesh).schedule_names(snap, batch)
    assert sharded == single


def test_mesh_matches_oracle(mesh):
    rng = random.Random(7)
    state, pending = random_scenario(rng, n_nodes=16, n_existing=8, n_pending=12)
    oracle_result, _ = run_both(state, pending)
    snap, batch = SnapshotEncoder(state, pending).encode()
    sharded = MeshBatchScheduler(mesh).schedule_names(snap, batch)
    assert sharded == oracle_result


@pytest.mark.parametrize("seed", [2, 9])
def test_mesh_interpod_affinity_matches_oracle(mesh, seed):
    """The mesh interpod path (dynamic_slice domain queries, all_gather
    min/max normalization, replicated table commits, ip_topo_dom padding
    on a non-divisible node count) must match the serial oracle."""
    rng = random.Random(seed)
    state, pending = random_scenario(
        rng, n_nodes=13, n_existing=10, n_pending=14, interpod_p=0.7
    )
    oracle_result, single = run_both(state, pending)
    assert single == oracle_result  # precondition: single-chip conformance
    snap, batch = SnapshotEncoder(state, pending).encode()
    sharded = MeshBatchScheduler(mesh).schedule_names(snap, batch)
    assert sharded == oracle_result


@pytest.mark.parametrize("seed", [3])
def test_mesh_volumes_match_oracle(mesh, seed):
    """Mesh path with volume predicates active: the sharded volume-mask
    commit (shard-local indexing) must thread identically to the serial
    oracle."""
    rng = random.Random(seed)
    state, pending = random_scenario(
        rng, n_nodes=13, n_existing=12, n_pending=14, volumes_p=0.7
    )
    oracle_result, single = run_both(state, pending)
    assert single == oracle_result
    snap, batch = SnapshotEncoder(state, pending).encode()
    sharded = MeshBatchScheduler(mesh).schedule_names(snap, batch)
    assert sharded == oracle_result


def test_mesh_service_affinity_matches_oracle(mesh):
    """ServiceAffinity on the mesh: replicated svc tables, global-axis
    evaluation sliced per shard, identical commits on every shard —
    bit-identical to the serial oracle (incl. 9->16 node padding)."""
    from kubernetes_tpu.oracle import ClusterState
    from tests.test_conformance import (
        _run_both_svc,
        _svc_affinity_cluster,
        _svc_pod,
    )

    nodes, services = _svc_affinity_cluster()
    state = ClusterState.build(
        nodes,
        services=services,
        assigned_pods=[_svc_pod("web-0", {"app": "web"}, node="node-0")],
    )
    pending = [
        _svc_pod("web-1", {"app": "web"}),
        _svc_pod("db-1", {"app": "db"}),
        _svc_pod("web-2", {"app": "web"}),
        _svc_pod("lone", {"app": "none"}),
        _svc_pod("db-2", {"app": "db"}),
    ]
    oracle_result, single = _run_both_svc(state, pending)
    assert single == oracle_result  # precondition: single-chip conformance

    cfg = SchedulerConfig(
        predicates=("GeneralPredicates", ("ServiceAffinity", ("region",))),
        priorities=(("LeastRequestedPriority", 1),),
    )
    snap, batch = SnapshotEncoder(state, pending, config=cfg).encode()
    sharded = MeshBatchScheduler(mesh, config=cfg).schedule_names(snap, batch)
    assert sharded == oracle_result


def test_mesh_service_anti_affinity_matches_oracle(mesh):
    """ServiceAntiAffinity spreading on the mesh: the per-value peer
    normalizer counts over the globally gathered fit mask."""
    from kubernetes_tpu.oracle import ClusterState
    from tests.test_conformance import (
        _run_both_svc,
        _svc_affinity_cluster,
        _svc_pod,
    )

    nodes, services = _svc_affinity_cluster()
    state = ClusterState.build(
        nodes,
        services=services,
        assigned_pods=[
            _svc_pod("web-0", {"app": "web"}, node="node-0"),
            _svc_pod("web-1", {"app": "web"}, node="node-1"),
        ],
    )
    pending = [
        _svc_pod(f"web-{i}", {"app": "web"}) for i in range(2, 8)
    ] + [_svc_pod("db-1", {"app": "db"})]
    oracle_result, single = _run_both_svc(
        state, pending, labels=("region",), anti_label="rack"
    )
    assert single == oracle_result

    cfg = SchedulerConfig(
        predicates=("GeneralPredicates", ("ServiceAffinity", ("region",))),
        priorities=(("LeastRequestedPriority", 1),
                    (("ServiceAntiAffinity", "rack"), 2)),
    )
    snap, batch = SnapshotEncoder(state, pending, config=cfg).encode()
    sharded = MeshBatchScheduler(mesh, config=cfg).schedule_names(snap, batch)
    assert sharded == oracle_result


def test_mesh_image_locality_and_node_label_match_oracle(mesh):
    """Mesh coverage for the two config-parameterized scorers the round-1
    suite never ran sharded: ImageLocality (per-node static, unnormalized)
    and NodeLabel predicate+priority (config-resolved static masks)."""
    from kubernetes_tpu.api.types import (
        Container,
        ContainerImage,
        Node,
        NodeCondition,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from kubernetes_tpu.oracle import ClusterState, GenericScheduler
    from kubernetes_tpu.oracle import predicates as opreds
    from kubernetes_tpu.oracle import priorities as oprios
    from kubernetes_tpu.oracle.scheduler import PriorityConfig

    rng = random.Random(11)
    mb = 1024 * 1024
    nodes = []
    for i in range(13):  # non-divisible: pads to 16
        labels = {"kubernetes.io/hostname": f"node-{i:02d}"}
        if i % 3 != 0:
            labels["disktype"] = "ssd"
        images = []
        if i % 2:
            images.append(ContainerImage(names=("registry/app:v1",),
                                         size_bytes=(40 + i * 13) * mb))
        if i % 5 == 0:
            images.append(ContainerImage(names=("registry/db:v2",),
                                         size_bytes=300 * mb))
        nodes.append(Node(
            metadata=ObjectMeta(name=f"node-{i:02d}", labels=labels),
            status=NodeStatus(
                allocatable={"cpu": "8", "memory": "32Gi", "pods": "110"},
                images=images,
                conditions=[NodeCondition("Ready", "True")],
            ),
        ))
    pending = [
        Pod(metadata=ObjectMeta(name=f"p{i:02d}"),
            spec=PodSpec(containers=[Container(
                image=rng.choice(["registry/app:v1", "registry/db:v2",
                                  "registry/other:v9"]),
                requests={"cpu": "200m"},
            )]))
        for i in range(10)
    ]
    state = ClusterState.build(nodes)
    oracle = GenericScheduler(
        predicates=[
            ("GeneralPredicates", opreds.general_predicates),
            ("RequireSSD", opreds.node_label_predicate(["disktype"], True)),
        ],
        priorities=[
            PriorityConfig(oprios.image_locality_priority, 2,
                           "ImageLocalityPriority"),
            PriorityConfig(oprios.node_label_priority("disktype", True), 1,
                           "NodeLabelPriority"),
            PriorityConfig(oprios.least_requested_priority, 1,
                           "LeastRequestedPriority"),
        ],
    )
    expected = oracle.schedule_backlog(pending, state.clone())
    cfg = SchedulerConfig(
        predicates=("GeneralPredicates",
                    ("CheckNodeLabelPresence", ("disktype",), True)),
        priorities=(("ImageLocalityPriority", 2),
                    (("NodeLabelPriority", "disktype", True), 1),
                    ("LeastRequestedPriority", 1)),
    )
    snap, batch = SnapshotEncoder(state, pending, config=cfg).encode()
    single = BatchScheduler(cfg).schedule_names(snap, batch)
    assert single == expected
    sharded = MeshBatchScheduler(mesh, config=cfg).schedule_names(snap, batch)
    assert sharded == expected


def test_mesh_scale_1k_nodes_matches_single_chip(mesh):
    """Kubemark-scale mesh check: ~1k nodes (1000 -> 1024 padded, 128 per
    shard on the 8-device CPU mesh) with the full default provider; the
    sharded program must agree with the single-chip scan exactly."""
    from kubernetes_tpu.api.types import (
        Container,
        Node,
        NodeCondition,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from kubernetes_tpu.oracle import ClusterState

    rng = random.Random(12)
    zones = ["a", "b", "c"]
    nodes = []
    for i in range(1000):
        labels = {
            "kubernetes.io/hostname": f"node-{i:04d}",
            "failure-domain.beta.kubernetes.io/zone": zones[i % 3],
        }
        nodes.append(Node(
            metadata=ObjectMeta(name=f"node-{i:04d}", labels=labels),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        ))
    existing = [
        Pod(metadata=ObjectMeta(name=f"run-{i:04d}",
                                labels={"app": rng.choice(["web", "db"])}),
            spec=PodSpec(node_name=f"node-{rng.randrange(1000):04d}",
                         containers=[Container(requests={
                             "cpu": f"{rng.choice([100, 500])}m",
                             "memory": "500Mi"})]))
        for i in range(300)
    ]
    pending = [
        Pod(metadata=ObjectMeta(name=f"p-{i:03d}",
                                labels={"app": "web"}),
            spec=PodSpec(containers=[Container(requests={
                "cpu": "100m", "memory": "500Mi"})]))
        for i in range(48)
    ]
    state = ClusterState.build(nodes, assigned_pods=existing)
    snap, batch = SnapshotEncoder(state, pending).encode()
    single = BatchScheduler(SchedulerConfig()).schedule_names(snap, batch)
    sharded = MeshBatchScheduler(mesh).schedule_names(snap, batch)
    assert sharded == single
    assert all(s is not None for s in sharded)


def test_daemon_selects_mesh_when_multichip(monkeypatch):
    """VERDICT r3 #5: the TPUProvider daemon must be deployable sharded —
    with >1 visible device (the 8-device CPU mesh here) and
    KUBERNETES_TPU_MESH=force, the provider builds a MeshBatchScheduler
    and the daemon schedules through it end to end."""
    import time

    from kubernetes_tpu.api.types import (
        Container,
        Node,
        NodeCondition,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.client.rest import RESTClient
    from kubernetes_tpu.client.transport import LocalTransport
    from kubernetes_tpu.scheduler.server import (
        SchedulerServer,
        SchedulerServerOptions,
    )

    monkeypatch.setenv("KUBERNETES_TPU_MESH", "force")
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    for i in range(5):
        client.nodes().create(Node(
            metadata=ObjectMeta(name=f"m{i}", namespace=""),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        ))
    sched = SchedulerServer(
        client, SchedulerServerOptions(algorithm_provider="TPUProvider")
    ).start()
    try:
        algo = sched.scheduler.config.algorithm
        assert algo._mesh_sched is not None, (
            "TPUProvider did not select the mesh path"
        )
        assert algo._mesh_sched.mesh.devices.size > 1
        for i in range(10):
            client.pods().create(Pod(
                metadata=ObjectMeta(name=f"mp{i}"),
                spec=PodSpec(containers=[Container(
                    requests={"cpu": "100m", "memory": "200Mi"}
                )]),
            ))
        deadline = time.time() + 60
        while time.time() < deadline:
            pods, _ = client.pods().list()
            if all(p.spec.node_name for p in pods):
                break
            time.sleep(0.2)
        pods, _ = client.pods().list()
        assert all(p.spec.node_name for p in pods), [
            (p.metadata.name, p.spec.node_name) for p in pods
        ]
        # identical pods spread across nodes (round-robin tie-break)
        assert len({p.spec.node_name for p in pods}) == 5
    finally:
        sched.stop()


def test_mesh_wave_matches_single_chip_and_oracle(mesh):
    """The mesh WAVE path (sharded probe + host replay + sharded commit
    fold): a template-heavy backlog must match the single-chip wave AND
    the oracle bit-for-bit, with the fallback scan sharing the carry."""
    from kubernetes_tpu.scheduler.tpu_algorithm import TPUScheduleAlgorithm
    from tests.test_wave import (
        density_nodes, pause_pods, oracle_backlog, spread_state)

    nodes = density_nodes(40, pods_cap="12")
    state = spread_state(nodes)
    # 3 template runs (wave) + heterogeneous stragglers (scan fallback)
    pods = pause_pods(120)
    pods += pause_pods(40, requests={"cpu": "200m", "memory": "1Gi"})
    for k in range(10):  # distinct requests => never a run
        pods += pause_pods(1, requests={"cpu": f"{50 + k}m"})
    for i, p in enumerate(pods):
        p.metadata.name = f"pod-{i:06d}"
    mesh_algo = TPUScheduleAlgorithm(mesh=mesh)
    single = TPUScheduleAlgorithm()
    got_mesh = mesh_algo.schedule_backlog(pods, state.clone())
    got_single = single.schedule_backlog(pods, state.clone())
    want = oracle_backlog(state, pods)
    assert got_mesh == want
    assert got_single == want


def test_mesh_wave_zoned_and_self_anti(mesh):
    """The round-5 wave extensions ride the mesh too: zoned selector
    spread and hostname self-anti-affinity runs."""
    from kubernetes_tpu.scheduler.tpu_algorithm import TPUScheduleAlgorithm
    from tests.test_wave import (
        zoned_density_nodes, hostname_nodes, pause_pods, _anti_pods,
        spread_state, oracle_backlog)
    from kubernetes_tpu.oracle import ClusterState

    state = spread_state(zoned_density_nodes(18))
    pods = pause_pods(90)
    got = TPUScheduleAlgorithm(mesh=mesh).schedule_backlog(pods, state)
    assert got == oracle_backlog(state, pods)

    nodes = hostname_nodes(12)
    pods2 = _anti_pods(20, {"app": "excl"})
    state2 = ClusterState.build(nodes)
    got2 = TPUScheduleAlgorithm(mesh=mesh).schedule_backlog(pods2, state2)
    want2 = oracle_backlog(state2, pods2)
    assert got2 == want2
    placed = [h for h in got2 if h]
    assert len(placed) == len(set(placed)) == 12


def test_mesh_wave_scale_2k_nodes(mesh):
    """2k nodes / 6k template pods through the mesh wave: deep fill with
    capacity exhaustion, bit-identical to the single-chip wave."""
    from kubernetes_tpu.scheduler.tpu_algorithm import TPUScheduleAlgorithm
    from tests.test_wave import density_nodes, pause_pods, spread_state

    nodes = density_nodes(2000, pods_cap="3")
    state = spread_state(nodes)
    pods = pause_pods(6500)  # 6000 slots: a 500-pod unschedulable tail
    for i, p in enumerate(pods):
        p.metadata.name = f"pod-{i:06d}"
    got_mesh = TPUScheduleAlgorithm(mesh=mesh).schedule_backlog(
        pods, state.clone())
    got_single = TPUScheduleAlgorithm().schedule_backlog(
        pods, state.clone())
    assert got_mesh == got_single
    assert got_mesh.count(None) == 500


def test_mesh_wave_service_member_runs(mesh):
    """Service-member runs (SA pin + SAA renormalization) on the MESH
    wave path: svc rows ride the sharded probe, the fold is replicated."""
    from kubernetes_tpu.scheduler.tpu_algorithm import TPUScheduleAlgorithm
    from tests.test_wave import (
        _svc_policy, _svc_oracle, _zone_nodes, _member_state, _members)

    cfg = _svc_policy(sa=True, saa=True)
    state = _member_state(_zone_nodes(9))
    pods = _members(40)
    got = TPUScheduleAlgorithm(mesh=mesh, config=cfg).schedule_backlog(
        pods, state.clone())
    want = _svc_oracle(state, pods, sa=True, saa=True)
    assert got == want
