"""Mesh-sharded scheduler vs single-device scheduler: identical results
on an 8-virtual-device CPU mesh (the kubemark idea: real program, fake
chips — SURVEY.md §4)."""

import random

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from kubernetes_tpu.models.batch import BatchScheduler, SchedulerConfig
from kubernetes_tpu.parallel.mesh import MeshBatchScheduler
from kubernetes_tpu.snapshot.encode import SnapshotEncoder
from tests.test_conformance import random_scenario, run_both


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    assert len(devices) == 8, "conftest must force 8 CPU devices"
    return Mesh(np.array(devices), ("nodes",))


@pytest.mark.parametrize("seed", [1, 4])
def test_mesh_matches_single_device(mesh, seed):
    rng = random.Random(seed)
    # 13 nodes: NOT divisible by 8 -> exercises dummy-node padding
    state, pending = random_scenario(rng, n_nodes=13, n_existing=10, n_pending=18)
    snap, batch = SnapshotEncoder(state, pending).encode()

    single = BatchScheduler(SchedulerConfig()).schedule_names(snap, batch)
    sharded = MeshBatchScheduler(mesh).schedule_names(snap, batch)
    assert sharded == single


def test_mesh_matches_oracle(mesh):
    rng = random.Random(7)
    state, pending = random_scenario(rng, n_nodes=16, n_existing=8, n_pending=12)
    oracle_result, _ = run_both(state, pending)
    snap, batch = SnapshotEncoder(state, pending).encode()
    sharded = MeshBatchScheduler(mesh).schedule_names(snap, batch)
    assert sharded == oracle_result


@pytest.mark.parametrize("seed", [2, 9])
def test_mesh_interpod_affinity_matches_oracle(mesh, seed):
    """The mesh interpod path (dynamic_slice domain queries, all_gather
    min/max normalization, replicated table commits, ip_topo_dom padding
    on a non-divisible node count) must match the serial oracle."""
    rng = random.Random(seed)
    state, pending = random_scenario(
        rng, n_nodes=13, n_existing=10, n_pending=14, interpod_p=0.7
    )
    oracle_result, single = run_both(state, pending)
    assert single == oracle_result  # precondition: single-chip conformance
    snap, batch = SnapshotEncoder(state, pending).encode()
    sharded = MeshBatchScheduler(mesh).schedule_names(snap, batch)
    assert sharded == oracle_result


@pytest.mark.parametrize("seed", [3])
def test_mesh_volumes_match_oracle(mesh, seed):
    """Mesh path with volume predicates active: the sharded volume-mask
    commit (shard-local indexing) must thread identically to the serial
    oracle."""
    rng = random.Random(seed)
    state, pending = random_scenario(
        rng, n_nodes=13, n_existing=12, n_pending=14, volumes_p=0.7
    )
    oracle_result, single = run_both(state, pending)
    assert single == oracle_result
    snap, batch = SnapshotEncoder(state, pending).encode()
    sharded = MeshBatchScheduler(mesh).schedule_names(snap, batch)
    assert sharded == oracle_result


def test_mesh_service_affinity_matches_oracle(mesh):
    """ServiceAffinity on the mesh: replicated svc tables, global-axis
    evaluation sliced per shard, identical commits on every shard —
    bit-identical to the serial oracle (incl. 9->16 node padding)."""
    from kubernetes_tpu.oracle import ClusterState
    from tests.test_conformance import (
        _run_both_svc,
        _svc_affinity_cluster,
        _svc_pod,
    )

    nodes, services = _svc_affinity_cluster()
    state = ClusterState.build(
        nodes,
        services=services,
        assigned_pods=[_svc_pod("web-0", {"app": "web"}, node="node-0")],
    )
    pending = [
        _svc_pod("web-1", {"app": "web"}),
        _svc_pod("db-1", {"app": "db"}),
        _svc_pod("web-2", {"app": "web"}),
        _svc_pod("lone", {"app": "none"}),
        _svc_pod("db-2", {"app": "db"}),
    ]
    oracle_result, single = _run_both_svc(state, pending)
    assert single == oracle_result  # precondition: single-chip conformance

    cfg = SchedulerConfig(
        predicates=("GeneralPredicates", ("ServiceAffinity", ("region",))),
        priorities=(("LeastRequestedPriority", 1),),
    )
    snap, batch = SnapshotEncoder(state, pending, config=cfg).encode()
    sharded = MeshBatchScheduler(mesh, config=cfg).schedule_names(snap, batch)
    assert sharded == oracle_result


def test_mesh_service_anti_affinity_matches_oracle(mesh):
    """ServiceAntiAffinity spreading on the mesh: the per-value peer
    normalizer counts over the globally gathered fit mask."""
    from kubernetes_tpu.oracle import ClusterState
    from tests.test_conformance import (
        _run_both_svc,
        _svc_affinity_cluster,
        _svc_pod,
    )

    nodes, services = _svc_affinity_cluster()
    state = ClusterState.build(
        nodes,
        services=services,
        assigned_pods=[
            _svc_pod("web-0", {"app": "web"}, node="node-0"),
            _svc_pod("web-1", {"app": "web"}, node="node-1"),
        ],
    )
    pending = [
        _svc_pod(f"web-{i}", {"app": "web"}) for i in range(2, 8)
    ] + [_svc_pod("db-1", {"app": "db"})]
    oracle_result, single = _run_both_svc(
        state, pending, labels=("region",), anti_label="rack"
    )
    assert single == oracle_result

    cfg = SchedulerConfig(
        predicates=("GeneralPredicates", ("ServiceAffinity", ("region",))),
        priorities=(("LeastRequestedPriority", 1),
                    (("ServiceAntiAffinity", "rack"), 2)),
    )
    snap, batch = SnapshotEncoder(state, pending, config=cfg).encode()
    sharded = MeshBatchScheduler(mesh, config=cfg).schedule_names(snap, batch)
    assert sharded == oracle_result
