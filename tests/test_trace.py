"""The tracing + device-profiling layer (kubernetes_tpu/trace).

Covers: span nesting and context propagation, trace-id continuity
across the TLV wire (apiserver process -> scheduler process as ONE
trace), the per-phase histograms, the /debug/traces and scheduler
/metrics endpoints, SLO-breach Event emission, and the two
storage/replicated.py regressions that rode this PR (stale ack after a
follower reconnect; stalled-follower drop closes the socket).
"""

import json
import io
import socket
import threading
import time
import urllib.request

import pytest

import kubernetes_tpu.trace as trace
from kubernetes_tpu.api.types import (
    Container,
    Node,
    NodeCondition,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from kubernetes_tpu.trace import profile as trace_profile
from kubernetes_tpu.trace.spans import TraceBuffer

from conftest import wait_until  # noqa: E402


def _node(name="n1"):
    return Node(
        metadata=ObjectMeta(name=name),
        status=NodeStatus(
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )


def _pod(name="p1"):
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(containers=[Container(requests={"cpu": "100m"})]),
    )


# -- span API -----------------------------------------------------------------


def test_span_nesting_and_propagation():
    with trace.span("outer", kind="test") as outer:
        assert outer.parent_id is None
        with trace.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
        # sibling after the inner closed: parent is outer again
        with trace.span("sibling") as sib:
            assert sib.parent_id == outer.span_id
    spans = trace.BUFFER.snapshot(trace_id=outer.trace_id)
    # newest first: outer closed last
    assert [s["name"] for s in spans] == ["outer", "sibling", "inner"]
    by_name = {s["name"]: s for s in spans}
    assert by_name["outer"]["attrs"] == {"kind": "test"}
    assert by_name["inner"]["parent_id"] == outer.span_id
    assert all(s["duration"] >= 0 for s in spans)


def test_span_threads_do_not_share_context():
    seen = {}

    def worker():
        with trace.span("thread-root") as s:
            seen["tid"] = s.trace_id
            seen["parent"] = s.parent_id

    with trace.span("main-root") as root:
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    # a fresh thread has no inherited context: it starts its own trace
    assert seen["parent"] is None
    assert seen["tid"] != root.trace_id


def test_trace_context_adopts_remote_trace():
    tid = trace.new_trace_id()
    with trace.trace_context(tid):
        with trace.span("adopted") as s:
            assert s.trace_id == tid
    assert trace.current_trace_id() is None


def test_buffer_ring_limit_and_jsonl_export():
    buf = TraceBuffer(capacity=4)
    for i in range(10):
        buf.record({"trace_id": "t", "span_id": str(i), "name": "s",
                    "start": 0.0, "duration": 0.0})
    assert buf.total_recorded == 10
    snap = buf.snapshot(limit=100)
    assert len(snap) == 4  # ring evicted the oldest
    assert [s["span_id"] for s in snap] == ["9", "8", "7", "6"]
    out = io.StringIO()
    assert buf.export_jsonl(out) == 4
    lines = [json.loads(l) for l in out.getvalue().splitlines()]
    assert [l["span_id"] for l in lines] == ["6", "7", "8", "9"]


def test_disabled_tracing_records_nothing():
    trace.set_enabled(False)
    try:
        before = trace.BUFFER.total_recorded
        with trace.span("never"):
            pass
        with trace_profile.phase_timer("probe"):
            pass
        trace.record_span("never", "sometrace", 0.0, 1.0)
        assert trace.BUFFER.total_recorded == before
        assert trace.inject(_pod()) is None
    finally:
        trace.set_enabled(True)


def test_inject_extract_rides_the_tlv_wire():
    from kubernetes_tpu.runtime import tlv

    pod = _pod()
    tid = trace.inject(pod)
    assert tid and trace.extract(pod) == tid
    # the annotation is ordinary ObjectMeta data: a TLV round trip (the
    # cross-process wire) preserves it bit-for-bit
    decoded = tlv.loads(tlv.dumps(pod))
    assert trace.extract(decoded) == tid
    # injecting under an open span reuses that span's trace
    with trace.span("creator") as s:
        p2 = _pod("p2")
        assert trace.inject(p2) == s.trace_id


# -- phase histograms ---------------------------------------------------------


def test_phase_timer_buckets_and_totals():
    from kubernetes_tpu.metrics import scheduler_wave_phase_seconds

    before = trace_profile.phase_totals()
    assert set(before) == set(trace_profile.PHASES)
    hist = scheduler_wave_phase_seconds.labels("encode")
    count_before = hist.count
    with trace_profile.phase_timer("encode"):
        time.sleep(0.01)
    assert hist.count == count_before + 1
    after = trace_profile.phase_totals()
    delta = after["encode"] - before["encode"]
    assert 0.005 < delta < 5.0
    # rendering carries the phase label on every sample line
    text = scheduler_wave_phase_seconds.render()
    assert 'scheduler_wave_phase_seconds_bucket{phase="encode",le="' in text
    assert 'scheduler_wave_phase_seconds_sum{phase="encode"}' in text


def test_exclusive_accountant_partitions_overlapping_phases():
    """Concurrent phase occurrences must not double-count: two phases
    held open simultaneously on different threads split the elapsed
    window between them (sum <= wall), with the higher-priority phase
    (earlier in PHASES) earning the overlap."""
    from kubernetes_tpu.trace.profile import _ExclusiveAccountant

    acct = _ExclusiveAccountant()
    t0 = time.perf_counter()
    acct.enter("bind")
    time.sleep(0.05)
    acct.enter("encode")  # higher priority: preempts bind's lane
    time.sleep(0.05)
    acct.exit("encode")
    time.sleep(0.05)
    acct.exit("bind")
    wall = time.perf_counter() - t0
    totals = acct.snapshot()
    assert totals["encode"] >= 0.04
    assert totals["bind"] >= 0.08  # the two bind-only stretches
    assert sum(totals.values()) <= wall + 1e-6
    # and close to wall: a phase was active the whole time
    assert sum(totals.values()) >= 0.9 * wall


def test_wave_schedule_populates_phase_histograms():
    """A raw tensor-path backlog leaves encode/score (or probe/replay)
    time in the histograms — the bench breakdown's data source."""
    from kubernetes_tpu.oracle import ClusterState
    from kubernetes_tpu.scheduler.tpu_algorithm import TPUScheduleAlgorithm

    before = trace_profile.phase_totals()
    state = ClusterState.build([_node(f"n{i}") for i in range(8)])
    pods = [_pod(f"w{i}") for i in range(32)]
    algo = TPUScheduleAlgorithm()
    hosts = algo.schedule_backlog(pods, state)
    assert all(h is not None for h in hosts)
    after = trace_profile.phase_totals()
    assert after["encode"] > before["encode"]
    device_work = sum(
        after[p] - before[p] for p in ("probe", "score", "replay")
    )
    assert device_work > 0
    assert after["transfer"] > before["transfer"]


# -- SLO watchdog -------------------------------------------------------------


def test_slo_watchdog_emits_breach_event():
    from kubernetes_tpu.client.record import FakeRecorder
    from kubernetes_tpu.metrics import Histogram
    from kubernetes_tpu.trace.slo import SLOWatchdog

    hist = Histogram("test_slo_hist", "")
    rec = FakeRecorder()
    dog = SLOWatchdog(rec, objective_seconds=0.5, histogram=hist)
    # no new observations: never fires
    assert dog.check_once() is False
    # fast observations under the objective: no breach
    hist.observe(1000.0)  # 1ms in microseconds
    assert dog.check_once() is False
    # a slow one breaches (histogram is microsecond-unit)
    for _ in range(100):
        hist.observe(2_000_000.0)  # 2s
    assert dog.check_once() is True
    assert dog.breaches == 1
    assert any("SchedulingSLOBreach" in e for e in rec.events), rec.events
    # no NEW observations since: re-checking must not re-alert
    assert dog.check_once() is False
    # alert-storm regression: the quantile is over the WINDOW delta, so
    # a recovered scheduler (new fast observations) must not keep
    # re-firing off the historical slow tail in the cumulative buckets
    for _ in range(10):
        hist.observe(1000.0)
    assert dog.check_once() is False
    assert dog.breaches == 1


def test_slo_watchdog_event_reaches_apiserver():
    """Daemon wiring: a breach flows recorder -> broadcaster -> sink ->
    a Warning Event on the apiserver, kind Scheduler."""
    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.client.record import EventBroadcaster, EventSink
    from kubernetes_tpu.client.rest import RESTClient
    from kubernetes_tpu.client.transport import LocalTransport
    from kubernetes_tpu.metrics import Histogram
    from kubernetes_tpu.trace.slo import SLOWatchdog

    server = APIServer()
    client = RESTClient(LocalTransport(server))
    broadcaster = EventBroadcaster()
    broadcaster.start_recording_to_sink(EventSink(client))
    hist = Histogram("test_slo_hist2", "")
    dog = SLOWatchdog(
        broadcaster.new_recorder("scheduler"), 0.01, histogram=hist
    )
    for _ in range(50):
        hist.observe(5_000_000.0)
    assert dog.check_once() is True

    def breach_event():
        evs, _ = client.events().in_namespace("kube-system").list()
        return [e for e in evs if e.reason == "SchedulingSLOBreach"]

    assert wait_until(lambda: breach_event(), timeout=10)
    ev = breach_event()[0]
    assert ev.type == "Warning"
    assert ev.involved_object.kind == "Scheduler"
    broadcaster.shutdown()


# -- endpoints ----------------------------------------------------------------


def test_component_server_endpoints():
    from kubernetes_tpu.trace.httpd import start_component_server

    srv, port = start_component_server(name="test")
    try:
        base = f"http://127.0.0.1:{port}"
        assert urllib.request.urlopen(f"{base}/healthz").read() == b"ok"
        metrics = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert "scheduler_e2e_scheduling_latency" in metrics
        assert "scheduler_xla_compile_seconds" in metrics
        assert "scheduler_wave_phase_seconds" in metrics
        with trace.span("endpoint-span"):
            pass
        traces = json.loads(
            urllib.request.urlopen(f"{base}/debug/traces?limit=5").read()
        )
        assert traces["kind"] == "TraceList" and traces["enabled"]
        assert 0 < len(traces["items"]) <= 5
        assert "endpoint-span" in {s["name"] for s in traces["items"]}
        # 404 for unknown paths
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope")
    finally:
        srv.shutdown()
        srv.server_close()


def test_apiserver_debug_traces_route():
    from kubernetes_tpu.apiserver.server import APIServer

    with trace.span("api-route-span"):
        pass
    code, payload = APIServer().handle("GET", "/debug/traces",
                                       {"limit": "10"}, None)
    assert code == 200 and payload["kind"] == "TraceList"
    assert len(payload["items"]) <= 10


def test_kubelet_serves_metrics_and_traces():
    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.client.rest import RESTClient
    from kubernetes_tpu.client.transport import LocalTransport
    from kubernetes_tpu.kubelet.kubelet import Kubelet, KubeletConfig
    from kubernetes_tpu.kubelet.runtime import FakeRuntime
    from kubernetes_tpu.kubelet.server import KubeletServer

    client = RESTClient(LocalTransport(APIServer()))
    kl = Kubelet(client, KubeletConfig(node_name="kn1"), FakeRuntime())
    srv = KubeletServer(kl)
    host, port = srv.serve()
    try:
        base = f"http://{host}:{port}"
        metrics = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert "scheduler_wave_phase_seconds" in metrics
        traces = json.loads(
            urllib.request.urlopen(f"{base}/debug/traces").read()
        )
        assert traces["kind"] == "TraceList"
    finally:
        srv.shutdown()


# -- end-to-end trace continuity ---------------------------------------------


def test_scheduler_daemon_trace_and_metrics_endpoints():
    """In-process control plane: one annotated pod scheduled through
    the daemon yields apiserver.create + scheduler.schedule +
    scheduler.bind on ONE trace id, and the scheduler's own mux serves
    /metrics with the e2e + compile histograms."""
    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.client.rest import RESTClient
    from kubernetes_tpu.client.transport import LocalTransport
    from kubernetes_tpu.scheduler.server import (
        SchedulerServer,
        SchedulerServerOptions,
    )

    server = APIServer()
    client = RESTClient(LocalTransport(server))
    client.nodes().create(_node())
    sched = SchedulerServer(client, SchedulerServerOptions()).start()
    try:
        assert sched.ready.wait(120), "scheduler never became ready"
        pod = _pod()
        tid = trace.inject(pod)
        client.pods().create(pod)
        assert wait_until(
            lambda: client.pods().get("p1").spec.node_name, timeout=60
        )
        host, port = sched.health_address
        base = f"http://{host}:{port}"
        metrics = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert "scheduler_e2e_scheduling_latency" in metrics
        assert "scheduler_xla_compile_seconds" in metrics

        def span_names():
            payload = json.loads(urllib.request.urlopen(
                f"{base}/debug/traces?limit=1000&trace={tid}"
            ).read())
            return {s["name"] for s in payload["items"]}

        # bind spans land asynchronously (bind pool)
        assert wait_until(
            lambda: {"apiserver.create", "scheduler.schedule",
                     "scheduler.bind"} <= span_names(),
            timeout=30,
        ), span_names()
    finally:
        sched.stop()


def test_trace_id_crosses_the_tlv_wire_between_processes():
    """The acceptance shape: apiserver in its OWN process on the TLV
    binary wire, scheduler here; the pod's trace id is preserved across
    the process boundary and each process's /debug/traces shows its leg
    of the same trace."""
    import subprocess
    import sys

    from kubernetes_tpu.client.rest import RESTClient
    from kubernetes_tpu.client.transport import HTTPTransport
    from kubernetes_tpu.scheduler.server import (
        SchedulerServer,
        SchedulerServerOptions,
    )

    api_proc = subprocess.Popen(
        [sys.executable, "-m", "kubernetes_tpu.hyperkube", "apiserver",
         "--port", "0", "--enable-binary-wire"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    sched = None
    try:
        url = api_proc.stdout.readline().strip().rsplit(" ", 1)[-1]
        client = RESTClient(HTTPTransport(url, binary=True))
        assert wait_until(client.healthz, timeout=15)
        client.nodes().create(_node())
        sched = SchedulerServer(client, SchedulerServerOptions()).start()
        assert sched.ready.wait(120)
        pod = _pod()
        tid = trace.inject(pod)
        client.pods().create(pod)
        assert wait_until(
            lambda: client.pods().get("p1").spec.node_name, timeout=60
        )
        # the apiserver process recorded its leg (queried over HTTP)
        api_payload = json.loads(urllib.request.urlopen(
            f"{url}/debug/traces?trace={tid}"
        ).read())
        api_names = {s["name"] for s in api_payload["items"]}
        assert "apiserver.create" in api_names
        assert all(s["trace_id"] == tid for s in api_payload["items"])

        # the scheduler process recorded its legs on the SAME trace id
        def sched_names():
            return {
                s["name"]
                for s in trace.BUFFER.snapshot(limit=4096, trace_id=tid)
            }

        assert wait_until(
            lambda: {"scheduler.schedule", "scheduler.bind"}
            <= sched_names(),
            timeout=30,
        ), sched_names()
    finally:
        if sched is not None:
            sched.stop()
        api_proc.terminate()
        api_proc.wait(timeout=10)


# -- replicated.py regressions (satellites) -----------------------------------


def _attach_raw_follower(store, timeout=5.0):
    """Handshake as a follower and read the initial snapshot, acking
    nothing: the stalled-peer simulation."""
    from kubernetes_tpu.storage import replicated as R

    conn = socket.create_connection(store.repl_address, timeout=timeout)
    conn.sendall(R._MAGIC)
    R._read_frame(conn)  # the snapshot
    return conn


def test_stale_ack_from_replaced_follower_is_ignored(tmp_path):
    """ADVICE r5: an ack arriving through a connection that is no
    longer the current follower must not advance _acked — it counts the
    OLD stream's byte offsets and would void the synchronous-commit
    guarantee for the new follower."""
    from kubernetes_tpu.storage import replicated as R
    from kubernetes_tpu.storage.replicated import ReplicatedStore

    store = ReplicatedStore(str(tmp_path / "p"), sync_timeout=2.0)
    try:
        current = _attach_raw_follower(store)
        assert wait_until(lambda: store._follower is not None)
        # a REPLACED connection: hand its server side to an ack loop
        # directly (deterministic stand-in for the raced real thread)
        old_srv, old_peer = socket.socketpair()
        t = threading.Thread(
            target=store._ack_loop, args=(old_srv,), daemon=True
        )
        t.start()
        old_peer.sendall(R._ACK.pack(10**9))  # a huge stale ack
        old_peer.close()
        t.join(timeout=5)
        assert not t.is_alive()
        # the guard: _acked untouched by the stale stream's ack
        assert store._acked == 0
        # and the CURRENT follower was not dropped by the stale loop
        assert store._follower is not None
        current.close()
    finally:
        store.close()


def test_stalled_follower_drop_closes_socket_and_allows_reattach(tmp_path):
    """ADVICE r5: the sync-timeout path must CLOSE the stalled
    follower's socket (not just clear the slot) so the peer observes
    the break and re-attaches instead of serving stale reads forever."""
    from kubernetes_tpu.storage.durable import FileStore
    from kubernetes_tpu.storage.replicated import (
        FollowerStore,
        ReplicatedStore,
    )

    store = ReplicatedStore(str(tmp_path / "p"), sync_timeout=0.3)
    follower = None
    try:
        stalled = _attach_raw_follower(store)
        assert wait_until(lambda: store._follower is not None)
        # a write times out against the silent peer and degrades
        t0 = time.monotonic()
        store.create("/pods/default/a", {"n": 1})
        assert time.monotonic() - t0 >= 0.25
        assert store._follower is None
        # the stalled peer OBSERVES the break: EOF once the buffered
        # frames drain (pre-fix the socket stayed open and this timed
        # out still connected)
        stalled.settimeout(5.0)
        saw_eof = False
        for _ in range(100):
            try:
                if stalled.recv(65536) == b"":
                    saw_eof = True
                    break
            except OSError:
                saw_eof = True  # reset also observes the break
                break
        assert saw_eof, "stalled follower never saw the socket close"
        stalled.close()
        # a fresh follower can attach and replication resumes
        follower = FollowerStore(
            str(tmp_path / "f"), store.repl_address
        )
        assert follower.synced(10)
        store.create("/pods/default/b", {"n": 2})
        assert wait_until(
            lambda: "/pods/default/b" in follower._data, timeout=10
        )
    finally:
        if follower is not None:
            follower.close()
        store.close()


def test_update_batch_isolates_arbitrary_exceptions():
    """ADVICE r5 (store.py): one raising mutation in a bulk bind stays
    with its item instead of 500ing the whole BindingList."""
    from kubernetes_tpu.storage import MemoryStore

    store = MemoryStore()
    store.create("/pods/default/a", {"v": 1})
    store.create("/pods/default/b", {"v": 1})

    def boom(cur):
        raise TypeError("bad mutation")

    def ok(cur):
        cur["v"] = 2
        return cur

    res = store.update_batch([
        ("/pods/default/a", boom),
        ("/pods/default/b", ok),
        ("/pods/default/missing", ok),
    ])
    assert isinstance(res[0], TypeError)
    assert res[1] is None
    assert isinstance(res[2], Exception)
    assert store.get("/pods/default/b")[0]["v"] == 2
    # the poisoned item really did not commit
    assert store.get("/pods/default/a")[0]["v"] == 1


def test_transport_ssl_context_for_any_https_member():
    """ADVICE r5 (transport.py): a mixed endpoint list builds the SSL
    context even when the FIRST member is plain http, and rotation is
    lock-guarded."""
    from kubernetes_tpu.client.transport import HTTPTransport

    t = HTTPTransport("http://a:1,https://b:2")
    assert t._ssl_ctx is not None
    t2 = HTTPTransport("http://a:1,http://b:2")
    assert t2._ssl_ctx is None
    # rotation under concurrent hammering stays in range and makes
    # progress (the lock prevents torn read-modify-writes)
    threads = [
        threading.Thread(
            target=lambda: [t._rotate() for _ in range(500)]
        )
        for _ in range(8)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert t._active in (0, 1)
