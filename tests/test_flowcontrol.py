"""API priority-and-fairness unit suite (apiserver/flowcontrol.py).

Covers the ISSUE-12 contract: classification by identity, exempt-level
bypass, seat accounting under concurrency, shuffle-shard fairness (one
hot flow cannot occupy all queues), queue-full shed with Retry-After
(both in-process and through the HTTP door), the client transport's
429 backoff, and the queue/dispatch machinery under the ARMED race
witness + lock-order sanitizer.
"""

import threading
import time

import pytest

from kubernetes_tpu.analysis import locks, races
from kubernetes_tpu.apiserver.flowcontrol import (
    APFController,
    FlowSchema,
    PriorityLevel,
    Rejected,
    default_levels,
    default_schemas,
    is_exempt_identity,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.transport import HTTPTransport, LocalTransport

from conftest import wait_until


def _tiny_controller(seats=1, queues=8, queue_length=2, hand_size=2,
                     queue_wait=0.4):
    levels = {
        "exempt": PriorityLevel("exempt", seats=1, exempt=True),
        "workload-high": PriorityLevel(
            "workload-high", seats=seats, queues=queues,
            queue_length=queue_length, hand_size=hand_size,
            queue_wait=queue_wait),
        "workload-low": PriorityLevel("workload-low", seats=seats),
        "catch-all": PriorityLevel("catch-all", seats=seats),
    }
    return APFController(levels=levels)


# -- classification -----------------------------------------------------------


def test_classification_table():
    c = APFController()
    for user, groups, want in [
        ("system:kube-scheduler", (), "exempt"),
        ("system:kube-controller-manager", (), "exempt"),
        ("system:node:hollow-0001", (), "exempt"),
        ("system:unsecured", (), "exempt"),
        ("anybody", ("system:masters",), "exempt"),
        ("batch-bot", ("workload:low",), "workload-low"),
        ("tenant-a", (), "workload-high"),
        ("", (), "catch-all"),
    ]:
        _s, level, _f = c.classify(user, groups, "GET", "/api/v1/pods")
        assert level.name == want, (user, groups, level.name)


def test_flow_keys_are_per_user():
    c = APFController()
    _, _, fa = c.classify("tenant-a", (), "GET", "/api/v1/pods")
    _, _, fb = c.classify("tenant-b", (), "GET", "/api/v1/pods")
    assert fa != fb
    # anonymous traffic collapses into one catch-all flow
    _, _, f1 = c.classify("", (), "GET", "/api/v1/pods")
    _, _, f2 = c.classify("", (), "POST", "/api/v1/pods")
    assert f1 == f2


def test_exempt_identity_helper():
    assert is_exempt_identity("system:kube-proxy", ())
    assert is_exempt_identity("system:node:n1", ())
    assert is_exempt_identity("x", ("system:nodes",))
    assert not is_exempt_identity("system:anonymous", ())
    assert not is_exempt_identity("tenant", ("workload:high",))


def test_custom_schema_table_validates_levels():
    with pytest.raises(ValueError):
        APFController(schemas=[FlowSchema(
            "x", "no-such-level", match=lambda u, g, v, p: True)])


# -- seats + queues ------------------------------------------------------------


def test_exempt_level_never_queues():
    """Saturate every shared level; the exempt level must still admit
    immediately with zero recorded wait — the control-plane contract."""
    c = _tiny_controller(seats=1)
    holders = [c.admit("tenant-a", (), "GET", "/api/v1/pods")]
    t0 = time.monotonic()
    tk = c.admit("system:kube-scheduler", (), "POST", "/api/v1/batch")
    assert time.monotonic() - t0 < 0.2
    assert tk.level.name == "exempt" and tk.waited == 0.0
    tk.__exit__()
    for h in holders:
        h.__exit__()


def test_seat_accounting_bounds_concurrency():
    lvl = PriorityLevel("acct", seats=3, queues=8, queue_length=64,
                        hand_size=4, queue_wait=5.0)
    in_flight = []
    peak = [0]
    mu = threading.Lock()

    def worker(i):
        lvl.acquire(f"flow-{i % 5}")
        with mu:
            in_flight.append(i)
            peak[0] = max(peak[0], len(in_flight))
        time.sleep(0.01)
        with mu:
            in_flight.remove(i)
        lvl.release()

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(24)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert peak[0] <= 3, f"seat limit violated: {peak[0]} in flight"
    st = lvl.state()
    assert st["seats_in_use"] == 0 and st["waiting"] == 0
    assert st["dispatched"] >= 24


def test_queue_full_sheds_with_retry_after():
    lvl = PriorityLevel("shed", seats=1, queues=8, queue_length=1,
                        hand_size=1, queue_wait=5.0)
    lvl.acquire("hot")  # take the only seat
    # hand_size=1 x queue_length=1: exactly one waiter fits
    waiter = threading.Thread(
        target=lambda: (lvl.acquire("hot"), lvl.release()))
    waiter.start()
    assert wait_until(lambda: lvl.state()["waiting"] == 1, 2.0)
    with pytest.raises(Rejected) as exc:
        lvl.acquire("hot")
    assert exc.value.reason == "queue-full"
    assert exc.value.retry_after >= 1
    lvl.release()  # dispatches the queued waiter
    waiter.join(timeout=2)
    assert lvl.state()["rejected_queue_full"] >= 1
    lvl.release()


def test_queue_wait_timeout_sheds():
    lvl = PriorityLevel("timeout", seats=1, queues=4, queue_length=8,
                        hand_size=2, queue_wait=0.15)
    lvl.acquire("holder")
    t0 = time.monotonic()
    with pytest.raises(Rejected) as exc:
        lvl.acquire("victim")
    assert exc.value.reason == "time-out"
    assert 0.1 <= time.monotonic() - t0 < 2.0
    st = lvl.state()
    assert st["waiting"] == 0, "timed-out waiter must leave the queue"
    lvl.release()


def test_shuffle_shard_hot_flow_cannot_occupy_all_queues():
    """The fairness core: a hot flow only ever reaches its own hand of
    queues, so some queue always stays free for other flows."""
    lvl = PriorityLevel("shard", seats=1, queues=16, queue_length=4,
                        hand_size=4, queue_wait=3.0)
    hand = lvl.hand_for("hot")
    assert len(set(hand)) == 4
    lvl.acquire("seat-holder")  # saturate the seat
    # flood the hot flow until it sheds: its queues are full
    flooded = []

    def hot_waiter():
        try:
            lvl.acquire("hot")
            lvl.release()
        except Rejected:
            pass

    for _ in range(4 * 4):  # exactly fills the hand
        th = threading.Thread(target=hot_waiter)
        th.start()
        flooded.append(th)
    assert wait_until(lambda: lvl.state()["waiting"] == 16, 3.0), \
        lvl.state()
    with pytest.raises(Rejected):
        lvl.acquire("hot")
    # only the hot flow's hand is occupied...
    st = lvl.state()
    occupied = {int(i) for i in st["nonempty_queues"]}
    assert occupied == set(hand)
    assert len(occupied) < 16, "hot flow occupied every queue"
    # ...so a well-behaved flow whose hand differs still enqueues
    other = next(f"flow-{i}" for i in range(100)
                 if set(lvl.hand_for(f"flow-{i}")) != set(hand))
    ok = []

    def good_waiter():
        lvl.acquire(other)
        ok.append(True)
        lvl.release()

    th = threading.Thread(target=good_waiter)
    th.start()
    assert wait_until(lambda: lvl.state()["waiting"] == 17, 2.0)
    lvl.release()  # free the seat: round-robin dispatch drains
    th.join(timeout=5)
    for f in flooded:
        f.join(timeout=5)
    assert ok, "well-behaved flow starved behind the hot flow"
    # drain bookkeeping: every dispatched waiter released its seat
    assert wait_until(
        lambda: lvl.state()["seats_in_use"] == 0
        and lvl.state()["waiting"] == 0, 5.0), lvl.state()


def test_round_robin_dispatch_is_fair_across_flows():
    """10 queued requests from the hot flow, 1 from another flow: the
    other flow's request must dispatch within the first two seat
    grants, not after the hot backlog drains."""
    lvl = PriorityLevel("rr", seats=1, queues=16, queue_length=16,
                        hand_size=2, queue_wait=10.0)
    lvl.acquire("holder")
    order = []
    mu = threading.Lock()

    def waiter(flow):
        lvl.acquire(flow)
        with mu:
            order.append(flow)
        lvl.release()

    hot = [threading.Thread(target=waiter, args=("hot",))
           for _ in range(10)]
    for th in hot:
        th.start()
    assert wait_until(lambda: lvl.state()["waiting"] == 10, 3.0)
    good = threading.Thread(target=waiter, args=("good",))
    good.start()
    assert wait_until(lambda: lvl.state()["waiting"] == 11, 3.0)
    lvl.release()  # seats free one by one as each waiter releases
    good.join(timeout=5)
    for th in hot:
        th.join(timeout=5)
    assert "good" in order[:2], order


# -- per-request seat width (round 13) ----------------------------------------


def test_request_width_classification():
    """Cost classification at classify time: selector LISTs and bulk
    batch bodies occupy more than one seat; everything else is 1."""
    from kubernetes_tpu.apiserver.flowcontrol import (
        WIDTH_MAX,
        request_width,
    )

    assert request_width("GET", "/api/v1/pods") == 1
    assert request_width(
        "GET", "/api/v1/pods", {"labelSelector": "a=b"}) == 2
    assert request_width(
        "GET", "/api/v1/pods", {"fieldSelector": "spec.nodeName=n1"}
    ) == 2
    # a WATCH with a selector holds a connection, not a seat-width
    assert request_width(
        "GET", "/api/v1/pods",
        {"labelSelector": "a=b", "watch": "true"}) == 1
    assert request_width("POST", "/api/v1/pods",
                         None, {"kind": "Pod"}) == 1
    assert request_width("POST", "/api/v1/batch", None,
                         {"items": [0] * 250}) == 2
    assert request_width("POST", "/api/v1/batch", None,
                         {"items": [0] * 10_000}) == WIDTH_MAX


def test_wide_request_occupies_multiple_seats():
    """One heavy request cannot masquerade as a singleton: a width-3
    request in a 4-seat level leaves room for only ONE more singleton;
    the next narrow request queues until the wide one releases."""
    lvl = PriorityLevel("wide", seats=4, queues=8, queue_length=8,
                        hand_size=2, queue_wait=5.0)
    lvl.acquire("heavy", width=3)
    lvl.acquire("light-a", width=1)  # the last free seat
    got = []

    def second():
        lvl.acquire("light-b", width=1)
        got.append(time.monotonic())

    th = threading.Thread(target=second, daemon=True)
    th.start()
    time.sleep(0.15)
    assert not got, "a narrow request dispatched past a full level"
    lvl.release(3)  # the wide request leaves; the waiter dispatches
    th.join(timeout=5)
    assert got, "the queued request never dispatched after release"
    lvl.release(1)
    lvl.release(1)


def test_wide_head_of_queue_accumulates_seats():
    """A wide queued request HOLDS the dispatcher until enough seats
    free (no skip — narrow traffic cannot starve it)."""
    lvl = PriorityLevel("hol", seats=4, queues=4, queue_length=8,
                        hand_size=2, queue_wait=5.0)
    for _ in range(4):
        lvl.acquire("filler", width=1)
    done = []

    def wide():
        lvl.acquire("big", width=3)
        done.append("wide")

    th = threading.Thread(target=wide, daemon=True)
    th.start()
    time.sleep(0.1)
    lvl.release(1)  # 1 free < 3: the wide head keeps waiting
    time.sleep(0.1)
    assert not done
    lvl.release(1)
    lvl.release(1)  # 3 free: dispatches
    th.join(timeout=5)
    assert done == ["wide"]
    lvl.release(3)
    lvl.release(1)


def test_wide_head_timeout_releases_dispatcher():
    """A wide head-of-queue waiter that TIMES OUT must re-run the
    dispatcher on its way out: it was holding seats hostage for
    itself, and the narrow waiters behind it are dispatchable the
    moment it withdraws (review-found stall: 2 seats free, narrow
    waiter spuriously 429'd)."""
    lvl = PriorityLevel("wto", seats=4, queues=4, queue_length=8,
                        hand_size=2, queue_wait=5.0)
    for _ in range(4):
        lvl.acquire("filler", width=1)
    wide_rejected = []
    narrow_got = []

    def wide():
        try:
            lvl.acquire("big", width=3)
        except Rejected:
            wide_rejected.append(True)

    def narrow():
        lvl.acquire("small", width=1)
        narrow_got.append(True)

    # the wide request queues with a SHORT timeout; the narrow one
    # queues behind it with a long one (set_queue_wait: the budget is
    # read under the level lock at enqueue, so flipping it between
    # starts is race-free)
    lvl.set_queue_wait(0.3)
    tw = threading.Thread(target=wide, daemon=True)
    tw.start()
    time.sleep(0.05)
    lvl.set_queue_wait(5.0)
    tn = threading.Thread(target=narrow, daemon=True)
    tn.start()
    time.sleep(0.05)
    # free 2 seats: not enough for the wide head, which holds them
    lvl.release(1)
    lvl.release(1)
    tw.join(timeout=5)
    assert wide_rejected, "the wide waiter never timed out"
    # its withdrawal must hand the accumulated seats to the narrow one
    tn.join(timeout=5)
    assert narrow_got, ("narrow waiter stalled with free seats after "
                        "the wide head timed out")
    lvl.release(1)
    for _ in range(2):
        lvl.release(1)


def test_width_capped_at_level_seats():
    """A request wider than the whole level is capped so it can still
    dispatch (otherwise it could never be admitted at all)."""
    c = _tiny_controller(seats=1)
    tk = c.admit("tenant-a", (), "POST", "/api/v1/batch", width=64)
    assert tk.width == 1
    tk.__exit__()


def test_wide_requests_through_the_apf_door():
    """End-to-end: bulk batch bodies through server.handle() are
    charged their width — two 2-wide requests cannot run concurrently
    in a 3-seat level (the second queues), while singles still fit."""
    levels = {
        "exempt": PriorityLevel("exempt", seats=1, exempt=True),
        "workload-high": PriorityLevel(
            "workload-high", seats=3, queues=8, queue_length=8,
            hand_size=2, queue_wait=2.0),
        "workload-low": PriorityLevel("workload-low", seats=1),
        "catch-all": PriorityLevel("catch-all", seats=1),
    }
    c = APFController(levels=levels)
    t1 = c.admit("tenant-a", (), "POST", "/api/v1/batch", width=2)
    assert t1.width == 2
    lvl = levels["workload-high"]
    with lvl._mu:
        assert lvl._seats_in_use == 2
    # one singleton still fits...
    t2 = c.admit("tenant-b", (), "GET", "/api/v1/pods", width=1)
    with lvl._mu:
        assert lvl._seats_in_use == 3
    # ...but another wide request must wait for the first to leave
    woke = []

    def wide2():
        tk = c.admit("tenant-c", (), "POST", "/api/v1/batch", width=2)
        woke.append(tk)

    th = threading.Thread(target=wide2, daemon=True)
    th.start()
    time.sleep(0.15)
    assert not woke
    t1.__exit__()
    th.join(timeout=5)
    assert woke and woke[0].width == 2
    woke[0].__exit__()
    t2.__exit__()


# -- the apiserver doors -------------------------------------------------------


def test_http_door_sheds_with_retry_after_header():
    api = APIServer(flowcontrol=_tiny_controller())
    _h, _p = api.serve_http()
    url = f"http://{_h}:{_p}"
    try:
        holder = api.flowcontrol.admit("tenant-x", (), "GET",
                                       "/api/v1/pods")
        # one waiter fills hand(1-2 queues x len 2)... flood until shed
        tr = HTTPTransport(url, user="tenant-x", retry_429=0)
        results = []

        def req():
            results.append(tr.request(
                "GET", "/api/v1/namespaces/default/pods"))

        threads = [threading.Thread(target=req) for _ in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=10)
        holder.__exit__()
        codes = sorted(c for c, _ in results)
        assert 429 in codes, codes
        shed = next(p for c, p in results if c == 429)
        assert shed["reason"] == "TooManyRequests"
        assert shed["details"]["retryAfterSeconds"] >= 1
        # the real header rides the wire too
        holder2 = api.flowcontrol.admit("tenant-x", (), "GET",
                                        "/api/v1/pods")
        import http.client as hc

        conn = hc.HTTPConnection(_h, _p, timeout=10)
        waiters = [threading.Thread(target=req) for _ in range(6)]
        for th in waiters:
            th.start()
        deadline = time.time() + 5
        retry_after = None
        while time.time() < deadline and retry_after is None:
            conn.request("GET", "/api/v1/namespaces/default/pods",
                         headers={"X-Remote-User": "tenant-x"})
            resp = conn.getresponse()
            resp.read()
            if resp.status == 429:
                retry_after = resp.headers.get("Retry-After")
        holder2.__exit__()
        for th in waiters:
            th.join(timeout=10)
        conn.close()
        assert retry_after is not None and int(retry_after) >= 1
        tr.close()
    finally:
        api.shutdown_http()


def test_http_door_identity_headers_classify_and_audit():
    api = APIServer(flowcontrol=APFController())
    _h, _p = api.serve_http()
    try:
        from kubernetes_tpu.metrics import (
            apiserver_flowcontrol_dispatched_requests_total as disp,
        )

        base_wh = disp.get(priority_level="workload-high")
        base_ex = disp.get(priority_level="exempt")
        tr = HTTPTransport(f"http://{_h}:{_p}", user="tenant-z")
        assert tr.request("GET", "/api/v1/nodes")[0] == 200
        assert disp.get(priority_level="workload-high") == base_wh + 1
        trs = HTTPTransport(f"http://{_h}:{_p}",
                            user="system:kube-scheduler")
        assert trs.request("GET", "/api/v1/nodes")[0] == 200
        assert disp.get(priority_level="exempt") == base_ex + 1
        # the audit trail sees the declared caller, not anonymous
        code, audit = tr.request("GET", "/debug/audit",
                                 query={"user": "tenant-z"})
        assert code == 200 and audit["items"], audit
        tr.close()
        trs.close()
    finally:
        api.shutdown_http()


def test_local_transport_deposits_identity():
    api = APIServer(flowcontrol=APFController())
    from kubernetes_tpu.metrics import (
        apiserver_flowcontrol_dispatched_requests_total as disp,
    )

    base_ex = disp.get(priority_level="exempt")
    base_wl = disp.get(priority_level="workload-low")
    lt = LocalTransport(api)  # unnamed in-process caller -> unsecured
    assert lt.request("GET", "/api/v1/nodes")[0] == 200
    assert disp.get(priority_level="exempt") == base_ex + 1
    lt2 = LocalTransport(api, user="batcher", groups=("workload:low",))
    assert lt2.request("GET", "/api/v1/nodes")[0] == 200
    assert disp.get(priority_level="workload-low") == base_wl + 1


def test_local_transport_identity_does_not_leak_to_direct_callers():
    """After a LocalTransport(user=tenant) request, a DIRECT handle()
    call on the same thread must classify as loopback/unsecured again
    — a stale tenant identity would queue (or shed) exempt work."""
    api = APIServer(flowcontrol=APFController())
    from kubernetes_tpu.metrics import (
        apiserver_flowcontrol_dispatched_requests_total as disp,
    )

    lt = LocalTransport(api, user="tenant-sticky")
    assert lt.request("GET", "/api/v1/nodes")[0] == 200
    base_ex = disp.get(priority_level="exempt")
    base_wh = disp.get(priority_level="workload-high")
    assert api.handle("GET", "/api/v1/nodes", {}, None)[0] == 200
    assert disp.get(priority_level="exempt") == base_ex + 1
    assert disp.get(priority_level="workload-high") == base_wh


def test_hand_memo_is_bounded():
    """Flow keys derive from caller-controlled identity: the per-flow
    hand memo must cap, not grow one entry per spoofed user."""
    lvl = PriorityLevel("memo", seats=1, queues=8, queue_length=4,
                        hand_size=2, queue_wait=0.05)
    lvl.HAND_MEMO_MAX = 16
    lvl.acquire("holder")  # force every later acquire onto queues
    for i in range(64):
        try:
            lvl.acquire(f"spoofed-{i}")
        except Rejected:
            pass
    assert len(lvl._hands) <= 16
    lvl.release()


def test_fleet_fail_nodes_zero_is_a_noop():
    from kubernetes_tpu.kubemark.fleet import HollowFleet

    fleet = HollowFleet.__new__(HollowFleet)
    fleet.node_names = [f"n{i}" for i in range(5)]
    import threading as _t

    fleet._lock = _t.Lock()
    fleet._dead = set()
    assert fleet.fail_nodes(0) == []
    assert not fleet._dead
    assert fleet.fail_nodes(2) == ["n3", "n4"]


def test_debug_flowcontrol_endpoint_and_kill_switch(monkeypatch):
    api = APIServer(flowcontrol=APFController())
    code, state = api.handle("GET", "/debug/flowcontrol", {}, None)
    assert code == 200 and state["enabled"]
    assert set(state["priority_levels"]) == {
        "exempt", "workload-high", "workload-low", "catch-all"}
    assert [s["name"] for s in state["flow_schemas"]] == [
        "system", "workload-low", "workload-high", "catch-all"]
    # the kill switch: KUBERNETES_TPU_APF=0 disables classification
    monkeypatch.setenv("KUBERNETES_TPU_APF", "0")
    off = APIServer()
    assert off.flowcontrol is None
    code, state = off.handle("GET", "/debug/flowcontrol", {}, None)
    assert code == 200 and state == {"enabled": False}
    monkeypatch.delenv("KUBERNETES_TPU_APF")
    on = APIServer()
    assert on.flowcontrol is not None


def test_default_levels_share_seats():
    levels = default_levels(total_seats=32)
    assert levels["exempt"].exempt
    shared = [levels[n].seats for n in
              ("workload-high", "workload-low", "catch-all")]
    assert shared[0] > shared[1] > shared[2] >= 1
    assert sum(shared) <= 34  # rounding slack over 32


def test_default_schemas_order_is_first_match_wins():
    c = APFController()
    # a system user in workload:low still lands exempt (schema order)
    _s, level, _f = c.classify(
        "system:kube-scheduler", ("workload:low",), "GET", "/x")
    assert level.name == "exempt"
    assert [s.name for s in default_schemas()] == [
        "system", "workload-low", "workload-high", "catch-all"]


# -- client transport 429 resilience ------------------------------------------


class _FakeResp:
    def __init__(self, status, retry_after=None):
        self.status = status
        self.headers = (
            {"Retry-After": str(retry_after)} if retry_after else {})


def test_transport_retries_429_honoring_retry_after(monkeypatch):
    tr = HTTPTransport("http://127.0.0.1:1", retry_429=3)
    responses = [_FakeResp(429, retry_after=2), _FakeResp(429),
                 _FakeResp(200)]
    calls = []

    def fake_once(method, target, data, headers):
        calls.append(method)
        return responses[len(calls) - 1], {"n": len(calls)}

    sleeps = []
    monkeypatch.setattr(tr, "_request_once", fake_once)
    monkeypatch.setattr(
        "kubernetes_tpu.client.transport._time",
        type("T", (), {"sleep": staticmethod(sleeps.append)}),
    )
    code, payload = tr.request("GET", "/api/v1/pods")
    assert code == 200 and payload == {"n": 3}
    assert len(calls) == 3
    assert tr.stats == {"sheds_429": 2, "retries_429": 2,
                        "giveups_429": 0, "failovers_503": 0,
                        "retries_503": 0}
    # first sleep honors (jittered) Retry-After: in [1, 2]s
    assert 1.0 <= sleeps[0] <= 2.0, sleeps
    # second has no hint: capped exponential backoff, well under cap
    assert 0.0 < sleeps[1] <= tr.BACKOFF_429_CAP


def test_transport_gives_up_after_retry_budget(monkeypatch):
    tr = HTTPTransport("http://127.0.0.1:1", retry_429=2)
    calls = []

    def fake_once(method, target, data, headers):
        calls.append(1)
        return _FakeResp(429, retry_after=1), {"code": 429}

    monkeypatch.setattr(tr, "_request_once", fake_once)
    monkeypatch.setattr(
        "kubernetes_tpu.client.transport._time",
        type("T", (), {"sleep": staticmethod(lambda s: None)}),
    )
    code, _ = tr.request("POST", "/api/v1/pods")
    assert code == 429
    assert len(calls) == 3  # initial + 2 retries
    assert tr.stats["giveups_429"] == 1


def test_transport_retry_disabled(monkeypatch):
    tr = HTTPTransport("http://127.0.0.1:1", retry_429=0)
    monkeypatch.setattr(
        tr, "_request_once",
        lambda *a: (_FakeResp(429), {"code": 429}))
    code, _ = tr.request("GET", "/x")
    assert code == 429
    assert tr.stats == {"sheds_429": 1, "retries_429": 0,
                        "giveups_429": 1, "failovers_503": 0,
                        "retries_503": 0}


def test_identity_headers_on_the_wire():
    tr = HTTPTransport("http://127.0.0.1:1", user="tenant-q",
                       groups=("workload:low", "g2"))
    h = tr._headers(False)
    assert h["X-Remote-User"] == "tenant-q"
    assert h["X-Remote-Group"] == "workload:low,g2"
    anon = HTTPTransport("http://127.0.0.1:1")
    assert "X-Remote-User" not in anon._headers(False)


def test_creator_shed_classification():
    """The Poisson creator must count a post-retry 429 as a shed (and
    keep going), not die: the classification rides APIStatusError."""
    from kubernetes_tpu.client.rest import APIStatusError

    shed = APIStatusError(429, {"reason": "TooManyRequests"})
    other = APIStatusError(500, {"reason": "InternalError"})
    assert shed.code == 429 and other.code != 429


# -- the armed witnesses over the queue/dispatch machinery ---------------------


def test_concurrent_dispatch_under_armed_race_witness():
    """Hammer admit/release from many threads with the data-race
    detector ARMED over the controller and its levels (track() runs in
    their constructors, so building them inside the armed window
    instruments every queue/seat attribute access)."""
    with races.instrumented(reset=True):
        c = _tiny_controller(seats=2, queues=8, queue_length=8,
                             hand_size=2, queue_wait=1.0)
        stats = {"ok": 0, "shed": 0}
        mu = threading.Lock()

        def worker(i):
            for j in range(20):
                user = ("system:kube-scheduler" if i % 4 == 0
                        else f"tenant-{i % 3}")
                try:
                    with c.admit(user, (), "GET", "/api/v1/pods"):
                        time.sleep(0.0005)
                    with mu:
                        stats["ok"] += 1
                except Rejected:
                    with mu:
                        stats["shed"] += 1

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert stats["ok"] > 0
        for lvl in c.levels.values():
            st = lvl.state()
            assert st["seats_in_use"] == 0 and st["waiting"] == 0
    races.assert_no_races("(flowcontrol)")


def test_lock_order_sanitizer_green_over_apf_doors():
    """Drive the full handle() path (APF + audit + store + cacher
    locks) under the lock-ORDER sanitizer; any inconsistent acquisition
    order across those subsystems fails here."""
    with locks.instrumented():
        api = APIServer(flowcontrol=_tiny_controller(
            seats=2, queue_wait=0.5))
        lt = LocalTransport(api, user="tenant-lock")

        def worker():
            for _ in range(10):
                lt.request("GET", "/api/v1/nodes")
                lt.request(
                    "POST", "/api/v1/namespaces/default/pods",
                    body={"kind": "Pod", "apiVersion": "v1",
                          "metadata": {"generateName": "fc-"},
                          "spec": {"containers": [{"name": "c"}]}})

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        api.close_cachers()
    locks.assert_no_cycles("(flowcontrol doors)")


# -- seat borrowing between levels (lendable concurrency limits) --------------


def test_saturated_level_borrows_from_idle_sibling():
    """workload-high at capacity + workload-low idle: the next
    workload-high request dispatches on a BORROWED seat (no queueing,
    zero wait) and /debug state shows the lease on both sides."""
    c = _tiny_controller(seats=2, queue_wait=2.0)
    wh, wl = c.levels["workload-high"], c.levels["workload-low"]
    holders = [c.admit("tenant-a", (), "GET", "/api/v1/pods")
               for _ in range(2)]  # wh nominal seats exhausted
    # occupy catch-all so workload-low is the only idle lender
    holders += [c.admit("", (), "GET", "/api/v1/pods")
                for _ in range(2)]
    t0 = time.monotonic()
    extra = c.admit("tenant-a", (), "GET", "/api/v1/pods")
    assert time.monotonic() - t0 < 0.2
    assert extra.waited == 0.0
    assert wh.state()["borrowed_in"] == 1
    assert wl.state()["lent_out"] == 1
    extra.__exit__()
    # the lease returns on release
    assert wh.state()["borrowed_in"] == 0
    assert wl.state()["lent_out"] == 0
    for h in holders:
        h.__exit__()


def test_lender_under_contention_gets_seats_back():
    """A lender that saturates while its seat is lent out recovers it
    the moment the borrower releases: the lender's queued waiter
    dispatches off the give-back, not off a timeout."""
    c = _tiny_controller(seats=2, queue_wait=5.0)
    wh, wl = c.levels["workload-high"], c.levels["workload-low"]
    hold_wh = [c.admit("tenant-a", (), "GET", "/api/v1/pods")
               for _ in range(2)]
    hold_ca = [c.admit("", (), "GET", "/api/v1/pods")
               for _ in range(2)]  # catch-all busy
    borrowed = c.admit("tenant-a", (), "GET", "/api/v1/pods")
    assert wl.state()["lent_out"] == 1  # wl lent its lendable seat
    # wl now becomes contended: one caller takes its remaining seat,
    # the next must queue behind the lease
    hold_wl = c.admit("batch-bot", ("workload:low",), "GET",
                      "/api/v1/pods")
    got = []

    def low_caller():
        tk = c.admit("batch-bot", ("workload:low",), "GET",
                     "/api/v1/pods")
        got.append(time.monotonic())
        tk.__exit__()

    t = threading.Thread(target=low_caller)
    t.start()
    wait_until(lambda: wl.state()["waiting"] == 1, timeout=2.0)
    # borrower completes -> seat returns -> wl waiter dispatches
    t0 = time.monotonic()
    borrowed.__exit__()
    t.join(timeout=2.0)
    assert got, "lender's waiter never dispatched after give-back"
    assert got[0] - t0 < 1.0
    assert wl.state()["lent_out"] == 0
    hold_wl.__exit__()
    for h in hold_wh + hold_ca:
        h.__exit__()


def test_borrowing_is_bounded_and_idle_only():
    """A lender with waiters lends nothing, and a borrower can never
    exceed its borrow limit (2x nominal): with every sibling
    saturated, workload-high requests queue/shed exactly as before
    borrowing existed."""
    c = _tiny_controller(seats=1, queue_length=1, queue_wait=0.3)
    # saturate EVERY shared level so no seats are lendable
    holders = [
        c.admit("tenant-a", (), "GET", "/api/v1/pods"),
        c.admit("batch-bot", ("workload:low",), "GET", "/api/v1/pods"),
        c.admit("", (), "GET", "/api/v1/pods"),
    ]
    wh = c.levels["workload-high"]
    # one more wh request: borrow limit is 1 (seats=1) -> one borrowed
    # seat max; but no sibling is idle, so it must time out in queue
    with pytest.raises(Rejected):
        c.admit("tenant-a", (), "GET", "/api/v1/pods")
    assert wh.state()["borrowed_in"] == 0
    for h in holders:
        h.__exit__()
