"""Multi-process control plane (round 13): apiserver replicas as
separate OS processes over one quorum behind the multi-endpoint
spread/failover transport, scheduler HA through leader election, and
the 503/refused-connect failover contract.

The tier-1 smoke runs a SHORT 2-apiserver-process soak end-to-end
(hollow fleet -> spread transport -> replica processes -> quorum ->
scheduler -> batched binds -> fleet acks) with every PR-8 integrity
gate armed plus the structural lease gate; the process-kill chaos form
(kill -9 leader / follower / active scheduler mid-soak) is the
slow-marked ``--wire-soak-scenario process-kill`` protocol in bench.py.
"""

import time

import pytest

from conftest import wait_until  # noqa: E402

from kubernetes_tpu.api import types as t
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import HTTPTransport, LocalTransport
from kubernetes_tpu.harness.procs import ApiserverFleet


def _pod(name: str) -> t.Pod:
    return t.Pod(
        metadata=t.ObjectMeta(name=name),
        spec=t.PodSpec(containers=[t.Container(
            requests={"cpu": "100m", "memory": "100Mi"})]),
    )


def _node(name: str) -> t.Node:
    return t.Node(
        metadata=t.ObjectMeta(name=name),
        status=t.NodeStatus(
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[t.NodeCondition("Ready", "True")],
        ),
    )


@pytest.fixture
def fleet3(tmp_path):
    """Three apiserver replica PROCESSES over one quorum."""
    fleet = ApiserverFleet(3, str(tmp_path / "procs"),
                           election_timeout=0.3).start()
    try:
        yield fleet
    finally:
        fleet.stop()


class TestReplicaProcesses:
    def test_replicas_share_one_quorum_and_serve_reads(self, fleet3):
        """Every replica answers /healthz with its member identity;
        a write through ANY endpoint is readable through every other
        (one quorum behind N frontends)."""
        ids = set()
        for r in fleet3.replicas:
            q = r.quorum_status()
            assert q is not None, r.node_id
            ids.add(q["node"])
            assert set(q["members"]) == {"q0", "q1", "q2"}
        assert ids == {"q0", "q1", "q2"}
        lead = fleet3.leader()
        follower = next(r for r in fleet3.replicas if r is not lead)
        # write through a FOLLOWER frontend (forwarded to the leader)
        wtr = HTTPTransport(follower.url, binary=True, timeout=30.0,
                            user="system:admin",
                            groups=("system:masters",))
        RESTClient(wtr).pods().create(_pod("via-follower"))
        # readable through every replica (linearizable barrier reads)
        for r in fleet3.replicas:
            rtr = HTTPTransport(r.url, binary=True, timeout=30.0,
                                user="system:admin",
                                groups=("system:masters",))
            got = RESTClient(rtr).pods().get("via-follower")
            assert got.metadata.name == "via-follower", r.node_id
            rtr.close()
        wtr.close()

    def test_failover_on_killed_replica(self, fleet3):
        """The killed-member regression for the multi-endpoint
        transport: a dead replica's refused connects and the
        survivors' 503s both rotate the endpoint (counted in
        transport.stats) and the caller's writes keep committing."""
        tr = HTTPTransport(fleet3.urls(), binary=True, timeout=30.0,
                           user="system:admin",
                           groups=("system:masters",), spread=True)
        client = RESTClient(tr)
        pods = client.pods()
        for i in range(4):
            pods.create(_pod(f"pre-{i}"))
        lead = fleet3.leader()
        lead.kill()
        # writes recover through rotation within the failover SLO
        t0 = time.monotonic()
        recovered = False
        while time.monotonic() - t0 < 20:
            try:
                pods.create(_pod(f"post-{int((time.monotonic()-t0)*1e3)}"))
                recovered = True
                break
            except Exception:
                time.sleep(0.2)
        assert recovered, "writes never recovered after the leader kill"
        # the rotation was COUNTED (the regression: only connect
        # errors used to rotate; refused/503 now do too)
        assert tr.stats["failovers_503"] >= 1, tr.stats
        # no acked write lost: everything created pre-kill still lists
        objs, _rv = pods.list()
        names = {p.metadata.name for p in objs}
        assert {f"pre-{i}" for i in range(4)} <= names
        tr.close()

    def test_lease_reads_flat_readindex_rounds(self, fleet3):
        """Structural lease gate at the process level: hammering
        linearizable reads against the replicas grows
        quorum_lease_reads_total while quorum_readindex_rounds_total
        stays flat (scraped from the replicas' /metrics)."""
        lead = fleet3.leader()
        tr = HTTPTransport(lead.url, binary=True, timeout=30.0,
                           user="system:admin",
                           groups=("system:masters",))
        client = RESTClient(tr)
        client.pods().create(_pod("lease-probe"))
        time.sleep(0.7)  # a full heartbeat round so the lease is live
        base = fleet3.scrape()
        # uncached reads: guaranteed_update runs a read_index per CAS
        for i in range(20):
            client.pods().patch("lease-probe",
                                {"metadata": {"labels": {"i": str(i)}}})
        end = fleet3.scrape()
        lease_reads = (end.get("quorum_lease_reads_total", 0)
                       - base.get("quorum_lease_reads_total", 0))
        rounds = (end.get("quorum_readindex_rounds_total", 0)
                  - base.get("quorum_readindex_rounds_total", 0))
        assert lease_reads >= 10, (lease_reads, rounds)
        assert rounds == 0, (lease_reads, rounds)
        tr.close()


class TestMultiProcessSoakSmoke:
    @pytest.mark.slow
    def test_two_process_soak_end_to_end(self):
        """The multi-process soak: 2 apiserver replica processes over
        one quorum, hollow fleet + Poisson arrivals through the spread
        transport, every integrity gate armed (p99, zero recompiles,
        flat RSS per process, zero drops) plus the structural lease
        gate and zero leader churn.

        Slow-marked (round 14 tier-1 budget reclaim): the ~46s soak
        rides the slow lane; tier-1 keeps the replica/failover/lease
        tests above for the multi-process machinery."""
        from kubernetes_tpu.harness.soak import SoakConfig, run_wire_soak

        rec = run_wire_soak(SoakConfig(
            seconds=30, num_nodes=64, rate=20.0, slo=5.0, procs=2,
            params={"churn_floor": 256,
                    "quorum_election_timeout": 0.4},
        ))
        assert rec["ok"], rec["gates"]
        assert rec["apiserver_processes"] == 2
        # the lease economics held: steady reads rode the lease,
        # zero read-index heartbeat rounds
        assert rec["gates"]["lease_reads_no_readindex_rounds"]
        qa = rec["quorum_accounting"]
        assert qa["steady_lease_reads"] > 0
        assert qa["steady_readindex_rounds"] == 0
        assert qa["steady_leader_changes"] == 0
        # per-process accounting made it into the record
        assert len(rec["apiserver_process_accounting"]) == 2
        for row in rec["apiserver_process_accounting"]:
            assert row["cpu_seconds"] > 0.0


@pytest.mark.slow
class TestProcessKillScenario:
    """The kill -9 chaos protocol (slow: ~2-5 min each; the tier-1
    budget carries the plain 2-process soak above instead — these are
    the `--wire-soak-scenario process-kill` forms CI runs separately,
    and this session's runs are recorded in BENCH_r09.json)."""

    def test_smoke(self):
        from kubernetes_tpu.harness.soak import (
            run_wire_soak,
            scenario_config,
        )

        rec = run_wire_soak(scenario_config("process-kill", 70,
                                            smoke=True))
        assert rec["ok"], rec["gates"]
        acct = rec["scenario_accounting"]
        assert acct["lost_acked_writes"] == 0
        assert all(len(v) <= 1
                   for v in acct["terms_observed"].values())

    def test_full_with_scheduler_ha(self):
        from kubernetes_tpu.harness.soak import (
            run_wire_soak,
            scenario_config,
        )

        rec = run_wire_soak(scenario_config(
            "process-kill", 180, smoke=False,
            num_nodes=256, rate=60.0))
        assert rec["ok"], rec["gates"]
        acct = rec["scenario_accounting"]
        assert acct["scheduler_failover_seconds"] is not None
        assert acct["lost_acked_writes"] == 0


class TestSchedulerHA:
    def test_standby_takes_over_when_holder_dies(self):
        """Scheduler HA through client/leaderelection: two scheduler
        servers share the lease; when the holder CRASHES (no lease
        release — the kill -9 shape), the standby acquires after the
        lease window and schedules new pods inside the SLO."""
        from kubernetes_tpu.scheduler.server import (
            SchedulerServer,
            SchedulerServerOptions,
        )

        server = APIServer()
        client = RESTClient(LocalTransport(server))
        client.nodes().create(_node("n0"))

        def opts(ident):
            return SchedulerServerOptions(
                leader_elect=True,
                leader_elect_identity=ident,
                leader_elect_lease_duration=1.2,
                leader_elect_renew_deadline=0.8,
                leader_elect_retry_period=0.3,
                serve_port=None,
            )

        s1 = SchedulerServer(
            RESTClient(LocalTransport(server)), opts("sched-1")
        ).start()
        s2 = None
        try:
            assert wait_until(lambda: s1._elector.is_leader(),
                              timeout=20)
            s2 = SchedulerServer(
                RESTClient(LocalTransport(server)), opts("sched-2")
            ).start()
            # the holder schedules; the standby must NOT
            client.pods().create(_pod("held"))
            assert wait_until(
                lambda: client.pods().get("held").spec.node_name,
                timeout=40)
            time.sleep(0.5)
            assert not s2._elector.is_leader()
            # CRASH the holder: stop its elector WITHOUT releasing the
            # lease (kill -9 never says goodbye), stop its loop
            t0 = time.monotonic()
            s1._elector._stop.set()
            s1.scheduler.stop()
            # the standby acquires after lease expiry and schedules
            assert wait_until(lambda: s2._elector.is_leader(),
                              timeout=20)
            client.pods().create(_pod("after-failover"))
            assert wait_until(
                lambda: client.pods().get(
                    "after-failover").spec.node_name,
                timeout=40)
            took = time.monotonic() - t0
            # lease 1.2s + acquire retries + one scheduling pass; the
            # SLO is generous for a loaded 1-core CI box
            assert took <= 45.0, took
        finally:
            s1.stop()
            if s2 is not None:
                s2.stop()
