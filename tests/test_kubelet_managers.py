"""Kubelet image + volume managers (VERDICT r2 #8 / missing #3).

Reference: pkg/kubelet/image_manager.go (pull tracking + LRU GC),
pkg/kubelet/volume_manager.go (mount lifecycle + reconciler), and the
end-to-end loop the round-2 VERDICT demanded: image state reported by a
kubelet changes a scheduling decision (ImageLocality,
priorities.go:149), proven on hollow nodes.
"""

import json
import time

from kubernetes_tpu.api import types as t
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.kubelet import FakeRuntime, Kubelet, KubeletConfig
from kubernetes_tpu.kubelet.images import ImageManager
from kubernetes_tpu.kubelet.volumes import VolumeManager


from conftest import wait_until  # noqa: E402


class TestImageManager:
    def test_pull_once_then_cache(self):
        m = ImageManager(size_of=lambda img: 100)
        assert m.ensure("nginx:1.9") is True
        assert m.ensure("nginx:1.9") is False  # present: no second pull
        assert m.pulls == 1
        assert m.usage_bytes() == 100
        lst = m.image_list()
        assert lst[0].names == ("nginx:1.9",) and lst[0].size_bytes == 100

    def test_lru_gc_respects_in_use(self):
        m = ImageManager(capacity_bytes=1000, high_threshold_pct=90,
                         low_threshold_pct=50, size_of=lambda img: 300)
        m.ensure("old")
        time.sleep(0.01)
        m.ensure("mid")
        time.sleep(0.01)
        m.ensure("new")
        m.ensure("old")  # refresh: "old" is now most recently used
        # 900/1000 == 90%: at the threshold, not over it
        assert m.garbage_collect() == 0
        m.ensure("extra")  # 1200 > 90%: GC down to <= 500
        freed = m.garbage_collect(in_use={"mid"})
        names = {i.names[0] for i in m.image_list()}
        assert "mid" in names  # in-use is never collected
        assert "new" not in names  # LRU victim
        assert freed >= 600

    def test_gc_noop_under_threshold(self):
        m = ImageManager(capacity_bytes=10**9, size_of=lambda img: 10)
        m.ensure("a")
        assert m.garbage_collect() == 0


class TestVolumeManager:
    def _pod(self, uid, vols):
        return t.Pod(
            metadata=t.ObjectMeta(name=uid, uid=uid),
            spec=t.PodSpec(
                containers=[t.Container(name="c")],
                volumes=vols,
            ),
        )

    def test_mount_unmount_lifecycle(self):
        vm = VolumeManager(node_name="n1")
        pod = self._pod("u1", [
            t.Volume(name="scratch"),  # sourceless inline == emptyDir
            t.Volume(name="host", host_path=t.HostPathVolumeSource(
                path="/data")),
        ])
        paths = vm.mount_pod_volumes(pod)
        assert set(paths) == {"scratch", "host"}
        for p in paths.values():
            assert vm.mounter.is_mounted(p)
        # idempotent remount returns the same paths
        assert vm.mount_pod_volumes(pod) == paths
        assert vm.mounted_for("u1") == ["host", "scratch"]
        n = vm.unmount_pod_volumes("u1")
        assert n == 2
        for p in paths.values():
            assert not vm.mounter.is_mounted(p)

    def test_reconciler_sweeps_orphans(self):
        vm = VolumeManager(node_name="n1")
        p1 = self._pod("u1", [t.Volume(name="v")])
        p2 = self._pod("u2", [t.Volume(name="v")])
        vm.mount_pod_volumes(p1)
        vm.mount_pod_volumes(p2)
        assert vm.reconcile(active_uids={"u2"}) == 1
        assert vm.mounted_for("u1") == []
        assert vm.mounted_for("u2") == ["v"]


def test_image_state_changes_scheduling_decision(tmp_path):
    """The full loop: a pod pinned to node A pulls a big image; A's
    kubelet reports it on node status; the scheduler (ImageLocality in
    the policy) then prefers A for a new pod using that image, and
    prefers B when the image only exists on B."""
    from kubernetes_tpu.scheduler.server import (
        SchedulerServer,
        SchedulerServerOptions,
    )

    server = APIServer()
    client = RESTClient(LocalTransport(server))
    big = 700 * 1024 * 1024  # top scoring bucket (priorities.go:138-142)
    kubelets = {}
    for name in ("node-a", "node-b"):
        rt = FakeRuntime()
        rt.image_sizes["registry/heavy:v1"] = big
        kubelets[name] = Kubelet(client, KubeletConfig(
            node_name=name,
            pleg_relist_period=0.05, status_sync_period=0.05,
            node_status_update_frequency=0.05,
        ), rt).run()
    policy = tmp_path / "policy.json"
    policy.write_text(json.dumps({
        "kind": "Policy",
        "predicates": [{"name": "PodFitsResources"}],
        "priorities": [{"name": "ImageLocalityPriority", "weight": 1}],
    }))
    sched = SchedulerServer(client, SchedulerServerOptions(
        policy_config_file=str(policy),
    )).start()
    try:
        assert wait_until(lambda: all(
            any(c.type == "Ready" and c.status == "True"
                for c in client.nodes().get(n).status.conditions)
            for n in kubelets
        ))
        # seed the image onto node-a by PINNING a pod there
        client.pods().create(t.Pod(
            metadata=t.ObjectMeta(name="seed-a"),
            spec=t.PodSpec(node_name="node-a", containers=[
                t.Container(name="c", image="registry/heavy:v1")]),
        ))
        assert wait_until(lambda: any(
            "registry/heavy:v1" in i.names
            for i in client.nodes().get("node-a").status.images
        ))
        assert not any(
            "registry/heavy:v1" in i.names
            for i in client.nodes().get("node-b").status.images
        )
        # an unpinned pod wanting that image must land on node-a
        client.pods().create(t.Pod(
            metadata=t.ObjectMeta(name="wants-image"),
            spec=t.PodSpec(containers=[
                t.Container(name="c", image="registry/heavy:v1")]),
        ))
        assert wait_until(
            lambda: client.pods().get("wants-image").spec.node_name
        )
        assert client.pods().get("wants-image").spec.node_name == "node-a"
        # …and the decision flips with the image's location: seed a
        # DIFFERENT image onto node-b only
        client.pods().create(t.Pod(
            metadata=t.ObjectMeta(name="seed-b"),
            spec=t.PodSpec(node_name="node-b", containers=[
                t.Container(name="c", image="registry/other:v2")]),
        ))
        for rt in (kubelets["node-b"].runtime,):
            rt.image_sizes["registry/other:v2"] = big
        assert wait_until(lambda: any(
            "registry/other:v2" in i.names
            for i in client.nodes().get("node-b").status.images
        ))
        client.pods().create(t.Pod(
            metadata=t.ObjectMeta(name="wants-other"),
            spec=t.PodSpec(containers=[
                t.Container(name="c", image="registry/other:v2")]),
        ))
        assert wait_until(
            lambda: client.pods().get("wants-other").spec.node_name
        )
        assert client.pods().get("wants-other").spec.node_name == "node-b"
    finally:
        sched.stop()
        for kl in kubelets.values():
            kl.stop()
