"""Static lint over the metrics registry: every registered metric must
carry HELP text, a snake_case name with the conventional type/unit
suffix, and no name may be registered twice. Keeps the /metrics surface
scrapeable and greppable as it grows (prometheus naming conventions;
the reference gates metrics the same way in its metrics linter)."""

import re

import pytest

from kubernetes_tpu.metrics.metrics import (
    Counter,
    Gauge,
    GaugeVec,
    Histogram,
    HistogramVec,
    Registry,
    registry,
)

# importing the daemons pulls in any metrics they register lazily, so
# the walk below sees the full production registry
import kubernetes_tpu.trace  # noqa: F401

_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")
#  _objects: dimensionless count distributions (batch commit sizes)
_UNIT_SUFFIXES = ("_seconds", "_microseconds", "_milliseconds", "_bytes",
                  "_objects")


def _registered():
    ms = registry.metrics()
    assert ms, "registry is empty - nothing imported the metric modules?"
    return ms


def test_every_metric_has_help_text():
    for m in _registered():
        assert m.help and m.help.strip(), (
            f"metric {m.name!r} registered without HELP text"
        )


def test_names_are_snake_case():
    for m in _registered():
        assert _SNAKE.match(m.name), (
            f"metric {m.name!r} is not snake_case"
        )


def test_counters_end_in_total():
    for m in _registered():
        if isinstance(m, Counter):
            assert m.name.endswith("_total"), (
                f"counter {m.name!r} must end in _total"
            )


def test_histograms_carry_a_unit_suffix():
    for m in _registered():
        if isinstance(m, (Histogram, HistogramVec)):
            assert m.name.endswith(_UNIT_SUFFIXES), (
                f"histogram {m.name!r} must end in one of "
                f"{_UNIT_SUFFIXES}"
            )


def test_no_duplicate_registration():
    names = [m.name for m in _registered()]
    dupes = {n for n in names if names.count(n) > 1}
    assert not dupes, f"duplicate metric registrations: {sorted(dupes)}"


def test_registry_rejects_duplicate_register():
    r = Registry()
    r.register(Counter("probe_dup_total", "probe"))
    with pytest.raises(ValueError):
        r.register(Gauge("probe_dup_total", "same name, other type"))


def test_gauges_lint_clean_too():
    # gauges are exempt from the unit-suffix rule (depth is a count of
    # items) but must still be snake_case with help
    for m in _registered():
        if isinstance(m, (Gauge, GaugeVec)):
            assert _SNAKE.match(m.name) and m.help.strip()


def test_rendered_exposition_parses():
    # every line of the text exposition is either a comment or
    # `name{labels} value` — a malformed render corrupts whole scrapes
    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE.+-]+(inf)?$"
    )
    for line in registry.render().splitlines():
        if not line or line.startswith("# "):
            continue
        assert sample.match(line), f"unparseable exposition line: {line!r}"


# -- label-cardinality bounds (round 17) --------------------------------------
#
# A metric whose label VALUES come from runtime data (flow keys, node
# ids, workqueue names) can mint unbounded series — each one a ring
# buffer in the telemetry TSDB and a dict entry in the registry
# forever. The rule: every call site that passes a non-literal label
# value forces that metric to declare `label_bound=N` at registration;
# the TSDB enforces the same bound at scrape time
# (telemetry_series_dropped_total counts the overflow).

_METRIC_MODULES = ("kubernetes_tpu.metrics", "kubernetes_tpu.metrics.metrics")
_DYNAMIC_CALL_ATTRS = ("inc", "child", "labels")


def _dynamic_label_call_sites():
    """AST-walk the package for metric calls whose label values are
    not literals: `m.inc(k=expr)`, `m.child(k=expr)`, `m.labels(expr)`
    — resolving both `from kubernetes_tpu.metrics import x [as y]`
    aliases and `metrics.x` / `_m.x` module-attribute access."""
    import ast
    import os

    import kubernetes_tpu

    pkg_root = os.path.dirname(kubernetes_tpu.__file__)
    hits = {}  # metric variable name -> ["path:line", ...]
    for root, dirs, files in os.walk(pkg_root):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            with open(path) as f:
                tree = ast.parse(f.read())
            aliases = {}       # local name -> metric variable name
            mod_aliases = set()  # local names bound to a metrics module
            for node in ast.walk(tree):
                if isinstance(node, ast.ImportFrom):
                    if node.module in _METRIC_MODULES:
                        for a in node.names:
                            aliases[a.asname or a.name] = a.name
                    elif node.module == "kubernetes_tpu":
                        for a in node.names:
                            if a.name == "metrics":
                                mod_aliases.add(a.asname or a.name)
                elif isinstance(node, ast.Import):
                    for a in node.names:
                        if a.name in _METRIC_MODULES:
                            mod_aliases.add(
                                a.asname or a.name.split(".")[0])
            if not aliases and not mod_aliases:
                continue
            rel = os.path.relpath(path, os.path.dirname(pkg_root))
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fnode = node.func
                if not isinstance(fnode, ast.Attribute) or \
                        fnode.attr not in _DYNAMIC_CALL_ATTRS:
                    continue
                base = fnode.value
                metric = None
                if isinstance(base, ast.Name) and base.id in aliases:
                    metric = aliases[base.id]
                elif (isinstance(base, ast.Attribute)
                      and isinstance(base.value, ast.Name)
                      and base.value.id in mod_aliases):
                    metric = base.attr
                if metric is None:
                    continue
                if fnode.attr in ("inc", "child"):
                    dynamic = any(
                        not isinstance(kw.value, ast.Constant)
                        for kw in node.keywords if kw.arg)
                else:  # labels(x)
                    dynamic = bool(node.args) and not isinstance(
                        node.args[0], ast.Constant)
                if dynamic:
                    hits.setdefault(metric, []).append(
                        f"{rel}:{node.lineno}")
    return hits


def test_caller_controlled_labels_declare_bounds():
    import kubernetes_tpu.metrics.metrics as mm

    hits = _dynamic_label_call_sites()
    assert hits, "the call-site scan found nothing — scanner broken?"
    missing = {}
    for varname, sites in sorted(hits.items()):
        metric = getattr(mm, varname, None)
        if metric is None:
            # a local alias the scan could not resolve to a registered
            # metric (e.g. a test fixture); name-level rules above
            # cover those
            continue
        if getattr(metric, "label_bound", None) is None:
            missing[varname] = sites
    assert not missing, (
        "metrics take caller-controlled label values but declare no "
        f"label_bound: {missing}"
    )


def test_label_bounds_are_positive_ints():
    for m in _registered():
        bound = getattr(m, "label_bound", None)
        if bound is not None:
            assert isinstance(bound, int) and bound > 0, (
                f"metric {m.name!r} label_bound must be a positive "
                f"int, got {bound!r}"
            )
