"""Static lint over the metrics registry: every registered metric must
carry HELP text, a snake_case name with the conventional type/unit
suffix, and no name may be registered twice. Keeps the /metrics surface
scrapeable and greppable as it grows (prometheus naming conventions;
the reference gates metrics the same way in its metrics linter)."""

import re

import pytest

from kubernetes_tpu.metrics.metrics import (
    Counter,
    Gauge,
    GaugeVec,
    Histogram,
    HistogramVec,
    Registry,
    registry,
)

# importing the daemons pulls in any metrics they register lazily, so
# the walk below sees the full production registry
import kubernetes_tpu.trace  # noqa: F401

_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")
#  _objects: dimensionless count distributions (batch commit sizes)
_UNIT_SUFFIXES = ("_seconds", "_microseconds", "_milliseconds", "_bytes",
                  "_objects")


def _registered():
    ms = registry.metrics()
    assert ms, "registry is empty - nothing imported the metric modules?"
    return ms


def test_every_metric_has_help_text():
    for m in _registered():
        assert m.help and m.help.strip(), (
            f"metric {m.name!r} registered without HELP text"
        )


def test_names_are_snake_case():
    for m in _registered():
        assert _SNAKE.match(m.name), (
            f"metric {m.name!r} is not snake_case"
        )


def test_counters_end_in_total():
    for m in _registered():
        if isinstance(m, Counter):
            assert m.name.endswith("_total"), (
                f"counter {m.name!r} must end in _total"
            )


def test_histograms_carry_a_unit_suffix():
    for m in _registered():
        if isinstance(m, (Histogram, HistogramVec)):
            assert m.name.endswith(_UNIT_SUFFIXES), (
                f"histogram {m.name!r} must end in one of "
                f"{_UNIT_SUFFIXES}"
            )


def test_no_duplicate_registration():
    names = [m.name for m in _registered()]
    dupes = {n for n in names if names.count(n) > 1}
    assert not dupes, f"duplicate metric registrations: {sorted(dupes)}"


def test_registry_rejects_duplicate_register():
    r = Registry()
    r.register(Counter("probe_dup_total", "probe"))
    with pytest.raises(ValueError):
        r.register(Gauge("probe_dup_total", "same name, other type"))


def test_gauges_lint_clean_too():
    # gauges are exempt from the unit-suffix rule (depth is a count of
    # items) but must still be snake_case with help
    for m in _registered():
        if isinstance(m, (Gauge, GaugeVec)):
            assert _SNAKE.match(m.name) and m.help.strip()


def test_rendered_exposition_parses():
    # every line of the text exposition is either a comment or
    # `name{labels} value` — a malformed render corrupts whole scrapes
    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE.+-]+(inf)?$"
    )
    for line in registry.render().splitlines():
        if not line or line.startswith("# "):
            continue
        assert sample.match(line), f"unparseable exposition line: {line!r}"
