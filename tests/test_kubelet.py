"""Kubelet against the in-process control plane with the fake runtime —
the hollow-node configuration (kubemark, hollow-node.go:102-120): real
kubelet logic, instant containers."""

import time

import pytest

from kubernetes_tpu.api.types import (
    Container,
    ObjectMeta,
    Pod,
    PodSpec,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.kubelet import FakeRuntime, Kubelet, KubeletConfig


from conftest import wait_until  # noqa: E402


@pytest.fixture()
def plane():
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    kubelets = []

    def start_kubelet(node_name, **kw):
        cfg = KubeletConfig(
            node_name=node_name,
            pleg_relist_period=0.05,
            status_sync_period=0.05,
            housekeeping_interval=0.2,
            node_status_update_frequency=0.2,
            **kw,
        )
        runtime = FakeRuntime()
        kl = Kubelet(client, cfg, runtime).run()
        kubelets.append(kl)
        return kl, runtime

    yield server, client, start_kubelet
    for kl in kubelets:
        kl.stop()


def bound_pod(name, node, restart_policy="Always"):
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(
            node_name=node,
            restart_policy=restart_policy,
            containers=[Container(name="main", requests={"cpu": "100m"})],
        ),
    )


def test_kubelet_registers_and_heartbeats(plane):
    server, client, start_kubelet = plane
    start_kubelet("n1")
    assert wait_until(lambda: _node_exists(client, "n1"))
    node = client.nodes().get("n1")
    ready = node.status.conditions[0]
    assert ready.type == "Ready" and ready.status == "True"
    first = ready.last_heartbeat_time
    assert wait_until(
        lambda: client.nodes().get("n1").status.conditions[0].last_heartbeat_time
        is not None
    )


def _node_exists(client, name):
    try:
        client.nodes().get(name)
        return True
    except Exception:
        return False


def test_bound_pod_runs(plane):
    server, client, start_kubelet = plane
    kl, runtime = start_kubelet("n1")
    assert wait_until(lambda: _node_exists(client, "n1"))
    client.pods().create(bound_pod("p1", "n1"))

    def phase():
        return client.pods().get("p1").status.phase

    assert wait_until(lambda: phase() == "Running")
    pod = client.pods().get("p1")
    assert pod.status.pod_ip.startswith("10.")
    assert any(c.type == "Ready" and c.status == "True" for c in pod.status.conditions)
    assert pod.status.container_statuses[0].state == "running"
    # runtime actually holds the pod
    assert any(rp.name == "p1" for rp in runtime.list_pods())


def test_container_death_via_pleg(plane):
    server, client, start_kubelet = plane
    kl, runtime = start_kubelet("n1")
    assert wait_until(lambda: _node_exists(client, "n1"))
    client.pods().create(bound_pod("crasher", "n1", restart_policy="Never"))
    assert wait_until(
        lambda: client.pods().get("crasher").status.phase == "Running"
    )
    uid = client.pods().get("crasher").metadata.uid
    runtime.exits["main"] = 1  # future syncs see the crash
    runtime.exit_container(uid, "main", code=1)
    assert wait_until(
        lambda: client.pods().get("crasher").status.phase == "Failed"
    )


def test_successful_completion(plane):
    server, client, start_kubelet = plane
    kl, runtime = start_kubelet("n1")
    assert wait_until(lambda: _node_exists(client, "n1"))
    client.pods().create(bound_pod("oneshot", "n1", restart_policy="Never"))
    assert wait_until(
        lambda: client.pods().get("oneshot").status.phase == "Running"
    )
    uid = client.pods().get("oneshot").metadata.uid
    runtime.exits["main"] = 0
    runtime.exit_container(uid, "main", code=0)
    assert wait_until(
        lambda: client.pods().get("oneshot").status.phase == "Succeeded"
    )


def test_pod_delete_kills_runtime(plane):
    server, client, start_kubelet = plane
    kl, runtime = start_kubelet("n1")
    assert wait_until(lambda: _node_exists(client, "n1"))
    client.pods().create(bound_pod("doomed", "n1"))
    assert wait_until(lambda: any(rp.name == "doomed" for rp in runtime.list_pods()))
    client.pods().delete("doomed")
    assert wait_until(
        lambda: not any(rp.name == "doomed" for rp in runtime.list_pods())
    )


def test_scheduler_to_kubelet_end_to_end(plane):
    """The full loop the reference demonstrates in its integration tier:
    unbound pod -> scheduler binds -> kubelet (watching its node) runs it."""
    from kubernetes_tpu.scheduler.server import SchedulerServer, SchedulerServerOptions

    server, client, start_kubelet = plane
    for i in range(2):
        start_kubelet(f"n{i}")
    assert wait_until(lambda: _node_exists(client, "n0") and _node_exists(client, "n1"))
    sched = SchedulerServer(client, SchedulerServerOptions()).start()
    try:
        client.pods().create(
            Pod(
                metadata=ObjectMeta(name="workload"),
                spec=PodSpec(containers=[Container(name="main", requests={"cpu": "100m"})]),
            )
        )
        assert wait_until(
            lambda: client.pods().get("workload").status.phase == "Running", 15
        )
        assert client.pods().get("workload").spec.node_name in ("n0", "n1")
    finally:
        sched.stop()


def test_pod_ips_unique_across_nodes(plane):
    """Review regression: each kubelet draws pod IPs from its own range
    (per-node CIDR), so pods on different nodes never share an IP."""
    server, client, start_kubelet = plane
    start_kubelet("node-a")
    start_kubelet("node-b")
    assert wait_until(lambda: _node_exists(client, "node-a") and _node_exists(client, "node-b"))
    client.pods().create(bound_pod("pa", "node-a"))
    client.pods().create(bound_pod("pb", "node-b"))
    assert wait_until(
        lambda: client.pods().get("pa").status.pod_ip
        and client.pods().get("pb").status.pod_ip
    )
    assert client.pods().get("pa").status.pod_ip != client.pods().get("pb").status.pod_ip


def test_status_writes_settle(plane):
    """Review regression: a steady-state running pod must stop generating
    status writes (no start_time churn / self-sustaining update loop)."""
    server, client, start_kubelet = plane
    start_kubelet("n1")
    assert wait_until(lambda: _node_exists(client, "n1"))
    client.pods().create(bound_pod("steady", "n1"))
    assert wait_until(lambda: client.pods().get("steady").status.phase == "Running")
    rv1 = client.pods().get("steady").metadata.resource_version
    time.sleep(1.0)  # many sync periods
    rv2 = client.pods().get("steady").metadata.resource_version
    assert rv1 == rv2, "pod status kept churning at steady state"


# --- probes (pkg/kubelet/prober) --------------------------------------------


def probed_pod(name, node, kind, restart_policy="Always", period=0.05):
    from kubernetes_tpu.api.types import Probe

    probe = Probe(period_seconds=period, failure_threshold=2,
                  success_threshold=1)
    kw = {"liveness_probe" if kind == "liveness" else "readiness_probe": probe}
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(
            node_name=node,
            restart_policy=restart_policy,
            containers=[Container(name="main", requests={"cpu": "100m"},
                                  **kw)],
        ),
    )


def _probe_plane(node_name="n1", **kubelet_kw):
    from kubernetes_tpu.kubelet.prober import FakeProber

    server = APIServer()
    client = RESTClient(LocalTransport(server))
    prober = FakeProber()
    cfg = KubeletConfig(
        node_name=node_name,
        pleg_relist_period=0.05,
        status_sync_period=0.05,
        housekeeping_interval=0.2,
        node_status_update_frequency=0.1,
    )
    runtime = FakeRuntime()
    kl = Kubelet(client, cfg, runtime, prober=prober, **kubelet_kw).run()
    return server, client, kl, runtime, prober


def test_failing_liveness_probe_restarts_container():
    """prober/worker.go: failureThreshold consecutive liveness failures
    kill the container; the pod worker restarts it (restartPolicy Always)
    and restartCount climbs while the pod returns to Running."""
    server, client, kl, runtime, prober = _probe_plane()
    try:
        client.pods().create(probed_pod("sick", "n1", "liveness"))
        assert wait_until(
            lambda: client.pods().get("sick").status.phase == "Running"
        )
        prober.set_result("sick", "main", "liveness", False)

        def restarted():
            st = client.pods().get("sick").status
            return any(cs.restart_count >= 1 for cs in st.container_statuses)

        assert wait_until(restarted)
        # back to Running after the restart (fresh probe history)
        prober.set_result("sick", "main", "liveness", True)
        assert wait_until(
            lambda: client.pods().get("sick").status.phase == "Running"
            and all(cs.state == "running"
                    for cs in client.pods().get("sick").status.container_statuses)
        )
    finally:
        kl.stop()


def test_liveness_failure_with_restart_never_fails_pod():
    server, client, kl, runtime, prober = _probe_plane()
    try:
        client.pods().create(
            probed_pod("doomed", "n1", "liveness", restart_policy="Never")
        )
        assert wait_until(
            lambda: client.pods().get("doomed").status.phase == "Running"
        )
        prober.set_result("doomed", "main", "liveness", False)
        assert wait_until(
            lambda: client.pods().get("doomed").status.phase == "Failed"
        )
        st = client.pods().get("doomed").status
        assert all(cs.restart_count == 0 for cs in st.container_statuses)
    finally:
        kl.stop()


def test_readiness_starts_false_during_initial_delay():
    """A probed container must report unready from the moment the
    worker exists — not default-Ready during initialDelaySeconds
    (worker.go:88,170; ADVICE r2 medium)."""
    import time as _time

    from kubernetes_tpu.api.types import Probe
    from kubernetes_tpu.kubelet.prober import ProbeManager

    mgr = ProbeManager(runner=lambda pod, container, probe: True)
    pod = Pod(
        metadata=ObjectMeta(name="slow", uid="u-slow"),
        spec=PodSpec(containers=[Container(
            name="main",
            readiness_probe=Probe(initial_delay_seconds=1,
                                  period_seconds=1),
        )]),
    )
    mgr.add_pod(pod)
    try:
        assert wait_until(
            lambda: mgr.is_ready("u-slow", "main") is False, timeout=2
        )
        # still within the initial delay: must remain unready
        assert mgr.is_ready("u-slow", "main") is False
        # after the delay, the succeeding probe flips it ready
        assert wait_until(lambda: mgr.is_ready("u-slow", "main"))
    finally:
        mgr.remove_pod("u-slow")


def test_readiness_probe_gates_pod_ready_condition():
    """A failing readiness probe keeps phase Running but flips the pod
    Ready condition False (endpoints drop it; status stays Running)."""
    server, client, kl, runtime, prober = _probe_plane()
    try:
        prober.set_result("web", "main", "readiness", True)
        client.pods().create(probed_pod("web", "n1", "readiness"))

        def ready_is(v):
            st = client.pods().get("web").status
            return st.phase == "Running" and any(
                c.type == "Ready" and c.status == v for c in st.conditions
            )

        assert wait_until(lambda: ready_is("True"))
        prober.set_result("web", "main", "readiness", False)
        assert wait_until(lambda: ready_is("False"))
        assert client.pods().get("web").status.phase == "Running"
        prober.set_result("web", "main", "readiness", True)
        assert wait_until(lambda: ready_is("True"))
    finally:
        kl.stop()


# --- eviction (pkg/kubelet/eviction) ----------------------------------------


def qos_pod(name, node, qos):
    if qos == "BestEffort":
        containers = [Container(name="main")]
    elif qos == "Guaranteed":
        containers = [Container(name="main",
                                requests={"cpu": "100m", "memory": "100Mi"},
                                limits={"cpu": "100m", "memory": "100Mi"})]
    else:
        containers = [Container(name="main", requests={"cpu": "100m"})]
    return Pod(metadata=ObjectMeta(name=name),
               spec=PodSpec(node_name=node, containers=containers))


def test_memory_pressure_evicts_best_effort_first():
    """eviction/helpers.go rankMemoryPressure: under pressure the node
    reports MemoryPressure (feeding CheckNodeMemoryPressure) and evicts
    BestEffort before Burstable before Guaranteed."""
    available = [8 << 30]
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    cfg = KubeletConfig(
        node_name="n2",
        pleg_relist_period=0.05,
        status_sync_period=0.05,
        node_status_update_frequency=0.05,
        eviction_memory_threshold=1 << 30,
        eviction_sync_period=0.1,
        eviction_pressure_transition_period=0.5,
    )
    runtime2 = FakeRuntime()
    kl2 = Kubelet(client, cfg, runtime2,
                  memory_available_fn=lambda: available[0]).run()
    try:
        for qos in ("Guaranteed", "BestEffort", "Burstable"):
            client.pods().create(qos_pod(f"p-{qos.lower()}", "n2", qos))
        assert wait_until(lambda: all(
            client.pods().get(f"p-{q.lower()}").status.phase == "Running"
            for q in ("Guaranteed", "BestEffort", "Burstable")
        ))
        available[0] = 256 << 20  # under the 1Gi threshold

        def phase(name):
            return client.pods().get(name).status.phase

        assert wait_until(lambda: phase("p-besteffort") == "Failed")
        assert client.pods().get("p-besteffort").status.reason == "Evicted"
        # the node now advertises MemoryPressure for the scheduler
        def mem_pressure():
            n = client.nodes().get("n2")
            return any(c.type == "MemoryPressure" and c.status == "True"
                       for c in n.status.conditions)

        assert wait_until(mem_pressure)
        # CheckNodeMemoryPressure end-to-end: a BestEffort pod no longer
        # fits this node while a Burstable one still does
        from kubernetes_tpu.oracle import ClusterState
        from kubernetes_tpu.oracle import predicates as opreds

        state = ClusterState.build([client.nodes().get("n2")])
        info = state.node_infos["n2"]
        fit, reason = opreds.check_node_memory_pressure(
            qos_pod("probe-be", "", "BestEffort"), info, state)
        assert not fit and reason == "NodeUnderMemoryPressure"
        fit, _ = opreds.check_node_memory_pressure(
            qos_pod("probe-bu", "", "Burstable"), info, state)
        assert fit
        # next ranked eviction: Burstable before Guaranteed
        assert wait_until(lambda: phase("p-burstable") == "Failed")
        assert phase("p-guaranteed") != "Failed"
        # pressure clears after the transition period
        available[0] = 8 << 30
        def mem_clear():
            n = client.nodes().get("n2")
            return any(c.type == "MemoryPressure" and c.status == "False"
                       for c in n.status.conditions)

        assert wait_until(mem_clear)
    finally:
        kl2.stop()
