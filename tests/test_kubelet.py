"""Kubelet against the in-process control plane with the fake runtime —
the hollow-node configuration (kubemark, hollow-node.go:102-120): real
kubelet logic, instant containers."""

import time

import pytest

from kubernetes_tpu.api.types import (
    Container,
    ObjectMeta,
    Pod,
    PodSpec,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.kubelet import FakeRuntime, Kubelet, KubeletConfig


def wait_until(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture()
def plane():
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    kubelets = []

    def start_kubelet(node_name, **kw):
        cfg = KubeletConfig(
            node_name=node_name,
            pleg_relist_period=0.05,
            status_sync_period=0.05,
            housekeeping_interval=0.2,
            node_status_update_frequency=0.2,
            **kw,
        )
        runtime = FakeRuntime()
        kl = Kubelet(client, cfg, runtime).run()
        kubelets.append(kl)
        return kl, runtime

    yield server, client, start_kubelet
    for kl in kubelets:
        kl.stop()


def bound_pod(name, node, restart_policy="Always"):
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(
            node_name=node,
            restart_policy=restart_policy,
            containers=[Container(name="main", requests={"cpu": "100m"})],
        ),
    )


def test_kubelet_registers_and_heartbeats(plane):
    server, client, start_kubelet = plane
    start_kubelet("n1")
    assert wait_until(lambda: _node_exists(client, "n1"))
    node = client.nodes().get("n1")
    ready = node.status.conditions[0]
    assert ready.type == "Ready" and ready.status == "True"
    first = ready.last_heartbeat_time
    assert wait_until(
        lambda: client.nodes().get("n1").status.conditions[0].last_heartbeat_time
        is not None
    )


def _node_exists(client, name):
    try:
        client.nodes().get(name)
        return True
    except Exception:
        return False


def test_bound_pod_runs(plane):
    server, client, start_kubelet = plane
    kl, runtime = start_kubelet("n1")
    assert wait_until(lambda: _node_exists(client, "n1"))
    client.pods().create(bound_pod("p1", "n1"))

    def phase():
        return client.pods().get("p1").status.phase

    assert wait_until(lambda: phase() == "Running")
    pod = client.pods().get("p1")
    assert pod.status.pod_ip.startswith("10.")
    assert any(c.type == "Ready" and c.status == "True" for c in pod.status.conditions)
    assert pod.status.container_statuses[0].state == "running"
    # runtime actually holds the pod
    assert any(rp.name == "p1" for rp in runtime.list_pods())


def test_container_death_via_pleg(plane):
    server, client, start_kubelet = plane
    kl, runtime = start_kubelet("n1")
    assert wait_until(lambda: _node_exists(client, "n1"))
    client.pods().create(bound_pod("crasher", "n1", restart_policy="Never"))
    assert wait_until(
        lambda: client.pods().get("crasher").status.phase == "Running"
    )
    uid = client.pods().get("crasher").metadata.uid
    runtime.exits["main"] = 1  # future syncs see the crash
    runtime.exit_container(uid, "main", code=1)
    assert wait_until(
        lambda: client.pods().get("crasher").status.phase == "Failed"
    )


def test_successful_completion(plane):
    server, client, start_kubelet = plane
    kl, runtime = start_kubelet("n1")
    assert wait_until(lambda: _node_exists(client, "n1"))
    client.pods().create(bound_pod("oneshot", "n1", restart_policy="Never"))
    assert wait_until(
        lambda: client.pods().get("oneshot").status.phase == "Running"
    )
    uid = client.pods().get("oneshot").metadata.uid
    runtime.exits["main"] = 0
    runtime.exit_container(uid, "main", code=0)
    assert wait_until(
        lambda: client.pods().get("oneshot").status.phase == "Succeeded"
    )


def test_pod_delete_kills_runtime(plane):
    server, client, start_kubelet = plane
    kl, runtime = start_kubelet("n1")
    assert wait_until(lambda: _node_exists(client, "n1"))
    client.pods().create(bound_pod("doomed", "n1"))
    assert wait_until(lambda: any(rp.name == "doomed" for rp in runtime.list_pods()))
    client.pods().delete("doomed")
    assert wait_until(
        lambda: not any(rp.name == "doomed" for rp in runtime.list_pods())
    )


def test_scheduler_to_kubelet_end_to_end(plane):
    """The full loop the reference demonstrates in its integration tier:
    unbound pod -> scheduler binds -> kubelet (watching its node) runs it."""
    from kubernetes_tpu.scheduler.server import SchedulerServer, SchedulerServerOptions

    server, client, start_kubelet = plane
    for i in range(2):
        start_kubelet(f"n{i}")
    assert wait_until(lambda: _node_exists(client, "n0") and _node_exists(client, "n1"))
    sched = SchedulerServer(client, SchedulerServerOptions()).start()
    try:
        client.pods().create(
            Pod(
                metadata=ObjectMeta(name="workload"),
                spec=PodSpec(containers=[Container(name="main", requests={"cpu": "100m"})]),
            )
        )
        assert wait_until(
            lambda: client.pods().get("workload").status.phase == "Running", 15
        )
        assert client.pods().get("workload").spec.node_name in ("n0", "n1")
    finally:
        sched.stop()


def test_pod_ips_unique_across_nodes(plane):
    """Review regression: each kubelet draws pod IPs from its own range
    (per-node CIDR), so pods on different nodes never share an IP."""
    server, client, start_kubelet = plane
    start_kubelet("node-a")
    start_kubelet("node-b")
    assert wait_until(lambda: _node_exists(client, "node-a") and _node_exists(client, "node-b"))
    client.pods().create(bound_pod("pa", "node-a"))
    client.pods().create(bound_pod("pb", "node-b"))
    assert wait_until(
        lambda: client.pods().get("pa").status.pod_ip
        and client.pods().get("pb").status.pod_ip
    )
    assert client.pods().get("pa").status.pod_ip != client.pods().get("pb").status.pod_ip


def test_status_writes_settle(plane):
    """Review regression: a steady-state running pod must stop generating
    status writes (no start_time churn / self-sustaining update loop)."""
    server, client, start_kubelet = plane
    start_kubelet("n1")
    assert wait_until(lambda: _node_exists(client, "n1"))
    client.pods().create(bound_pod("steady", "n1"))
    assert wait_until(lambda: client.pods().get("steady").status.phase == "Running")
    rv1 = client.pods().get("steady").metadata.resource_version
    time.sleep(1.0)  # many sync periods
    rv2 = client.pods().get("steady").metadata.resource_version
    assert rv1 == rv2, "pod status kept churning at steady state"
