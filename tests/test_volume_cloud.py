"""Volume plugin registry, cloud provider fake, and the PV claim binder
(pkg/volume, pkg/cloudprovider, pkg/controller/persistentvolume)."""

import time

import pytest

from kubernetes_tpu.api.types import (
    AWSElasticBlockStore,
    GCEPersistentDisk,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    RBDVolume,
    Volume,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.cloudprovider import FakeCloud, get_cloud_provider
from kubernetes_tpu.controller.framework import SharedInformerFactory
from kubernetes_tpu.controller.pv_binder import PersistentVolumeClaimBinder
from kubernetes_tpu.volume import FakeMounter, default_plugin_mgr
from kubernetes_tpu.volume.plugins import VolumeSpec


from conftest import wait_until  # noqa: E402


def test_plugin_resolution_and_mount_cycle():
    mgr = default_plugin_mgr()
    mounter = FakeMounter()
    specs = {
        "kubernetes.io/gce-pd": VolumeSpec(
            volume=Volume(name="d", gce_persistent_disk=GCEPersistentDisk(pd_name="pd1"))
        ),
        "kubernetes.io/aws-ebs": VolumeSpec(
            volume=Volume(name="e", aws_elastic_block_store=AWSElasticBlockStore(volume_id="v1"))
        ),
        "kubernetes.io/rbd": VolumeSpec(
            volume=Volume(name="r", rbd=RBDVolume(monitors=("m",), pool="p", image="i"))
        ),
        "kubernetes.io/empty-dir": VolumeSpec(volume=Volume(name="scratch")),
    }
    for want, spec in specs.items():
        plugin = mgr.find_plugin_by_spec(spec)
        assert plugin.name == want
        path = plugin.setup(mounter, spec, pod_uid="u1")
        assert mounter.is_mounted(path)
        plugin.teardown(mounter, spec, pod_uid="u1")
        assert not mounter.is_mounted(path)
    # PV-backed spec resolves too
    pv_spec = VolumeSpec(
        pv=PersistentVolume(
            metadata=ObjectMeta(name="pv1"),
            gce_persistent_disk=GCEPersistentDisk(pd_name="pd9"),
        )
    )
    assert mgr.find_plugin_by_spec(pv_spec).name == "kubernetes.io/gce-pd"
    assert mgr.find_plugin_by_name("kubernetes.io/aws-ebs").attachable


def test_fake_cloud_provider():
    cloud = get_cloud_provider("fake")
    assert isinstance(cloud, FakeCloud)
    cloud.instances = ["n1", "n2"]
    assert cloud.external_id("n1") == "ext-n1"
    assert cloud.list_instances() == ["n1", "n2"]
    assert cloud.get_zone().region == "us-central1"
    lb = cloud.ensure_tcp_load_balancer("svc", "r1", (80,), ("n1",))
    assert cloud.get_tcp_load_balancer("svc", "r1") == lb
    cloud.ensure_tcp_load_balancer_deleted("svc", "r1")
    assert cloud.get_tcp_load_balancer("svc", "r1") is None
    assert "ensure-lb" in cloud.calls


def test_pv_claim_binder():
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    informers = SharedInformerFactory(client)
    binder = PersistentVolumeClaimBinder(client, informers)
    pv_client = client.resource("persistentvolumes")
    pvc_client = client.resource("persistentvolumeclaims", "default")
    pv_client.create(PersistentVolume(
        metadata=ObjectMeta(name="small"), capacity={"storage": "1Gi"}))
    pv_client.create(PersistentVolume(
        metadata=ObjectMeta(name="big"), capacity={"storage": "100Gi"}))
    pvc_client.create(PersistentVolumeClaim(
        metadata=ObjectMeta(name="claim"), requests={"storage": "500Mi"}))
    informers.start()
    informers.wait_for_sync()
    assert wait_until(lambda: len(informers.informer("persistentvolumes").store.list()) == 2)
    assert binder.sync_once() == 1
    # smallest fitting PV wins; two-way binding recorded
    assert pvc_client.get("claim").volume_name == "small"
    assert pv_client.get("small").claim_ref == "default/claim"
    assert pv_client.get("big").claim_ref == ""
    # claim deleted -> PV released
    pvc_client.delete("claim")
    assert wait_until(
        lambda: len(informers.informer("persistentvolumeclaims").store.list()) == 0
    )
    binder.sync_once()
    assert pv_client.get("small").claim_ref == ""
    informers.stop()


def test_pv_binder_no_double_bind():
    """Review regression: two unbound PVCs and one PV must result in
    exactly one binding, not both claims sharing the volume."""
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    informers = SharedInformerFactory(client)
    binder = PersistentVolumeClaimBinder(client, informers)
    client.resource("persistentvolumes").create(PersistentVolume(
        metadata=ObjectMeta(name="only"), capacity={"storage": "10Gi"}))
    pvc_client = client.resource("persistentvolumeclaims", "default")
    pvc_client.create(PersistentVolumeClaim(
        metadata=ObjectMeta(name="a"), requests={"storage": "1Gi"}))
    pvc_client.create(PersistentVolumeClaim(
        metadata=ObjectMeta(name="b"), requests={"storage": "1Gi"}))
    informers.start()
    informers.wait_for_sync()
    assert wait_until(
        lambda: len(informers.informer("persistentvolumeclaims").store.list()) == 2
    )
    assert binder.sync_once() == 1
    bound = [pvc_client.get(n).volume_name for n in ("a", "b")]
    assert sorted(bound) == ["", "only"]
    informers.stop()


class TestVolumePluginBreadth:
    """Every reference volume family routes to exactly one plugin
    (pkg/volume/plugins.go FindPluginBySpec)."""

    def test_all_sources_route(self):
        from kubernetes_tpu.api import types as t
        from kubernetes_tpu.volume.plugins import (
            VolumeSpec,
            default_plugin_mgr,
        )

        mgr = default_plugin_mgr()
        cases = [
            (t.Volume(name="v", gce_persistent_disk=t.GCEPersistentDisk(
                pd_name="d")), "kubernetes.io/gce-pd", "gce-pd/d"),
            (t.Volume(name="v", aws_elastic_block_store=t.AWSElasticBlockStore(
                volume_id="i")), "kubernetes.io/aws-ebs", "aws-ebs/i"),
            (t.Volume(name="v", rbd=t.RBDVolume(pool="p", image="im")),
             "kubernetes.io/rbd", "rbd/p/im"),
            (t.Volume(name="v", host_path=t.HostPathVolumeSource(path="/x")),
             "kubernetes.io/host-path", "/x"),
            (t.Volume(name="v"), "kubernetes.io/empty-dir", "tmpfs"),
            (t.Volume(name="v", nfs=t.NFSVolumeSource(server="s",
                                                      path="/e")),
             "kubernetes.io/nfs", "nfs/s/e"),
            (t.Volume(name="v", iscsi=t.ISCSIVolumeSource(
                target_portal="tp", iqn="iqn.x", lun=2)),
             "kubernetes.io/iscsi", "iscsi/tp/iqn.x/lun-2"),
            (t.Volume(name="v", glusterfs=t.GlusterfsVolumeSource(
                endpoints_name="ep", path="vol")),
             "kubernetes.io/glusterfs", "glusterfs/ep/vol"),
            (t.Volume(name="v", cephfs=t.CephFSVolumeSource(
                monitors=("m1", "m2"))), "kubernetes.io/cephfs",
             "cephfs/m1,m2/"),
            (t.Volume(name="v", cinder=t.CinderVolumeSource(
                volume_id="c1")), "kubernetes.io/cinder", "cinder/c1"),
            (t.Volume(name="v", fc=t.FCVolumeSource(
                target_wwns=("w1",), lun=1)), "kubernetes.io/fc",
             "fc/w1/lun-1"),
            (t.Volume(name="v", azure_file=t.AzureFileVolumeSource(
                share_name="sh")), "kubernetes.io/azure-file",
             "azure-file/sh"),
            (t.Volume(name="v", flocker=t.FlockerVolumeSource(
                dataset_name="ds")), "kubernetes.io/flocker", "flocker/ds"),
            (t.Volume(name="v", vsphere_volume=(
                t.VsphereVirtualDiskVolumeSource(volume_path="[ds] x"))),
             "kubernetes.io/vsphere-volume", "vsphere/[ds] x"),
            (t.Volume(name="v", secret=t.SecretVolumeSource(
                secret_name="tok")), "kubernetes.io/secret", "secret/tok"),
            (t.Volume(name="v", config_map=t.ConfigMapVolumeSource(
                name="cm")), "kubernetes.io/configmap", "configmap/cm"),
            (t.Volume(name="v", downward_api=t.DownwardAPIVolumeSource()),
             "kubernetes.io/downward-api", "downward-api"),
            (t.Volume(name="v", git_repo=t.GitRepoVolumeSource(
                repository="r")), "kubernetes.io/git-repo", "git/r@HEAD"),
        ]
        for vol, plugin_name, device in cases:
            spec = VolumeSpec(volume=vol)
            p = mgr.find_plugin_by_spec(spec)
            assert p.name == plugin_name, (vol, p.name)
            assert p.device_of(spec) == device

    def test_pv_sources_route(self):
        from kubernetes_tpu.api import types as t
        from kubernetes_tpu.volume.plugins import (
            VolumeSpec,
            default_plugin_mgr,
        )

        mgr = default_plugin_mgr()
        pv = t.PersistentVolume(
            metadata=t.ObjectMeta(name="pv1"),
            nfs=t.NFSVolumeSource(server="s", path="/e"),
        )
        p = mgr.find_plugin_by_spec(VolumeSpec(pv=pv))
        assert p.name == "kubernetes.io/nfs"


class TestAttachDetachController:
    def test_attach_then_detach_follows_pods(self):
        import time

        from kubernetes_tpu.api import types as t
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import LocalTransport
        from kubernetes_tpu.controller.attach_detach import (
            AttachDetachController,
        )
        from kubernetes_tpu.controller.framework import SharedInformerFactory

        server = APIServer()
        client = RESTClient(LocalTransport(server))
        client.nodes().create(t.Node(metadata=t.ObjectMeta(name="n1")))
        informers = SharedInformerFactory(client)
        ctrl = AttachDetachController(client, informers)
        informers.start()
        informers.wait_for_sync()
        # a scheduled pod with an attachable inline volume
        client.pods().create(t.Pod(
            metadata=t.ObjectMeta(name="p1"),
            spec=t.PodSpec(node_name="n1", containers=[
                t.Container(name="c")],
                volumes=[t.Volume(name="disk",
                                  gce_persistent_disk=t.GCEPersistentDisk(
                                      pd_name="data-1"))]),
        ))
        # and one with a PVC -> bound PV (attachable)
        client.resource("persistentvolumes", "").create(t.PersistentVolume(
            metadata=t.ObjectMeta(name="pv9", namespace=""),
            cinder=t.CinderVolumeSource(volume_id="vol-9"),
        ))
        client.resource("persistentvolumeclaims", "default").create(
            t.PersistentVolumeClaim(
                metadata=t.ObjectMeta(name="claim9"),
                volume_name="pv9",
            )
        )
        client.pods().create(t.Pod(
            metadata=t.ObjectMeta(name="p2"),
            spec=t.PodSpec(node_name="n1", containers=[
                t.Container(name="c")],
                volumes=[t.Volume(
                    name="pvc",
                    persistent_volume_claim=t.PersistentVolumeClaimSource(
                        claim_name="claim9"))]),
        ))

        def attached():
            return {v.name
                    for v in client.nodes().get("n1").status.volumes_attached}

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            ctrl.sync_once()
            if attached() == {"gce-pd/data-1", "cinder/vol-9"}:
                break
            time.sleep(0.05)
        assert attached() == {"gce-pd/data-1", "cinder/vol-9"}
        # delete p1: its disk detaches, the PVC-backed one stays
        client.pods().delete("p1")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            ctrl.sync_once()
            if attached() == {"cinder/vol-9"}:
                break
            time.sleep(0.05)
        assert attached() == {"cinder/vol-9"}


# -- the local cloud provider: a load balancer that forwards bytes -----------
# (providers/gce/gce.go capability, realized in-process: ServiceController
#  -> LocalCloud LB -> userspace proxy -> pod backend)


class TestLocalCloudLoadBalancer:
    def _echo_backend(self):
        import socketserver
        import threading

        class H(socketserver.BaseRequestHandler):
            def handle(self):
                data = self.request.recv(4096)
                if data:
                    self.request.sendall(b"pod:" + data)

        srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), H)
        srv.daemon_threads = True
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv

    def test_servicecontroller_provisions_working_lb(self):
        import socket

        from kubernetes_tpu.api.types import (
            EndpointAddress,
            EndpointPort,
            Endpoints,
            EndpointSubset,
            Node,
            NodeStatus,
            Service,
            ServicePort,
            ServiceSpec,
        )
        from kubernetes_tpu.cloudprovider import LocalCloud
        from kubernetes_tpu.controller.cloud import ServiceController
        from kubernetes_tpu.proxy.userspace import UserspaceProxier

        server = APIServer()
        client = RESTClient(LocalTransport(server))
        backend = self._echo_backend()
        proxier = UserspaceProxier(client, node_name="n1").run()
        cloud = LocalCloud()
        cloud.register_node("n1", proxier)
        client.resource("nodes").create(
            Node(metadata=ObjectMeta(name="n1"), status=NodeStatus())
        )
        # a LoadBalancer service + endpoints at the live backend
        sport = 18080
        client.resource("services", "default").create(Service(
            metadata=ObjectMeta(name="web", uid="uid-web-1"),
            spec=ServiceSpec(
                type="LoadBalancer",
                cluster_ip="10.0.0.20",
                ports=[ServicePort(name="http", port=sport)],
            ),
        ))
        client.resource("endpoints", "default").create(Endpoints(
            metadata=ObjectMeta(name="web"),
            subsets=[EndpointSubset(
                addresses=[EndpointAddress(ip="127.0.0.1")],
                ports=[EndpointPort(
                    name="http", port=backend.server_address[1]
                )],
            )],
        ))
        informers = SharedInformerFactory(client)
        ctrl = ServiceController(client, informers, cloud)
        informers.start()
        informers.wait_for_sync()
        # proxier must have its listener before the LB forwards
        assert wait_until(
            lambda: proxier.addr_for_port(sport) is not None
        )
        ctrl.sync_once()
        svc = client.resource("services", "default").get("web")
        # LB provisioned + address persisted in service status; node
        # ports were allocated by the apiserver (30000-32767)
        assert svc.status.load_balancer.ingress
        ingress_ip = svc.status.load_balancer.ingress[0].ip
        assert ingress_ip.startswith("127.200.")
        assert 30000 <= svc.spec.ports[0].node_port <= 32767
        # real-k8s dial semantics: ingress ip + the service's own port
        lb_addr = (ingress_ip, sport)
        assert cloud.lb_addr(ctrl._lb_name(svc), "local", sport) == lb_addr
        # real bytes: client -> cloud LB -> node proxy -> pod backend
        with socket.create_connection(lb_addr, timeout=5) as s:
            s.sendall(b"ping")
            assert s.recv(4096) == b"pod:ping"
        # service deleted -> balancer torn down
        client.resource("services", "default").delete("web")
        assert wait_until(lambda: not any(
            s.metadata.name == "web"
            for s in informers.informer("services").store.list()
        ))
        ctrl.sync_once()
        assert cloud.lb_addr(ctrl._lb_name(svc), "local", sport) is None

        def refused():
            try:
                socket.create_connection(lb_addr, timeout=1).close()
                return False
            except OSError:
                return True

        assert wait_until(refused)  # listener torn down
        proxier.stop()
        informers.stop()
        backend.shutdown()
        backend.server_close()


class TestCloudDiskAttachers:
    """The real attach state machines (gce_pd/attacher.go,
    aws_ebs/attacher.go) against the fake cloud — VERDICT r3 #9."""

    def _plane(self):
        from kubernetes_tpu.cloudprovider import FakeCloud
        from kubernetes_tpu.controller.attach_detach import (
            AttachDetachController,
        )

        server = APIServer()
        client = RESTClient(LocalTransport(server))
        informers = SharedInformerFactory(client)
        cloud = FakeCloud(instances=["n1", "n2"])
        ctrl = AttachDetachController(client, informers, cloud=cloud)
        return server, client, informers, cloud, ctrl

    @staticmethod
    def _pd_pod(name, node, pd="data-disk", read_only=False):
        from kubernetes_tpu.api.types import (
            Container,
            GCEPersistentDisk,
            Pod,
            PodSpec,
            Volume,
        )

        return Pod(
            metadata=ObjectMeta(name=name),
            spec=PodSpec(
                node_name=node,
                containers=[Container(name="c")],
                volumes=[Volume(
                    name="v",
                    gce_persistent_disk=GCEPersistentDisk(
                        pd_name=pd, read_only=read_only),
                )],
            ),
        )

    def test_attach_goes_through_the_cloud(self):
        from kubernetes_tpu.api.types import Node

        server, client, informers, cloud, ctrl = self._plane()
        client.resource("nodes").create(Node(
            metadata=ObjectMeta(name="n1", namespace="")))
        client.pods().create(self._pd_pod("p1", "n1"))
        informers.start()
        informers.wait_for_sync()
        wait_until(lambda: len(informers.pods().store.list()) == 1)
        ctrl.sync_once()
        # the cloud's attachment table is authoritative
        assert cloud.disk_is_attached("gce-pd/data-disk", "n1")
        node = client.resource("nodes").get("n1")
        assert [v.name for v in node.status.volumes_attached] == [
            "gce-pd/data-disk"]
        # pod gone -> cloud detach
        client.pods().delete("p1")
        wait_until(lambda: not informers.pods().store.list())
        ctrl.sync_once()
        assert not cloud.disk_is_attached("gce-pd/data-disk", "n1")
        informers.stop()

    def test_rw_disk_attaches_to_one_node_only(self):
        from kubernetes_tpu.api.types import Node

        server, client, informers, cloud, ctrl = self._plane()
        for n in ("n1", "n2"):
            client.resource("nodes").create(Node(
                metadata=ObjectMeta(name=n, namespace="")))
        client.pods().create(self._pd_pod("p1", "n1"))
        client.pods().create(self._pd_pod("p2", "n2"))
        informers.start()
        informers.wait_for_sync()
        wait_until(lambda: len(informers.pods().store.list()) == 2)
        ctrl.sync_once()
        # exactly one node holds the RW disk; the other is refused
        holders = [n for n in ("n1", "n2")
                   if cloud.disk_is_attached("gce-pd/data-disk", n)]
        assert len(holders) == 1
        assert ctrl.conflicts >= 1
        # the holder's pod leaves -> next syncs flip the attachment
        holder = holders[0]
        client.pods().delete("p1" if holder == "n1" else "p2")
        wait_until(lambda: len(informers.pods().store.list()) == 1)
        ctrl.sync_once()  # detaches from the old holder
        ctrl.sync_once()  # attaches to the waiting node
        other = "n2" if holder == "n1" else "n1"
        assert cloud.disk_is_attached("gce-pd/data-disk", other)
        assert not cloud.disk_is_attached("gce-pd/data-disk", holder)
        informers.stop()

    def test_wait_for_attach_polls_the_cloud(self):
        from kubernetes_tpu.cloudprovider import FakeCloud
        from kubernetes_tpu.volume.attachers import CloudDiskAttacher
        from kubernetes_tpu.volume.plugins import (
            VolumeSpec,
            default_plugin_mgr,
        )
        from kubernetes_tpu.api.types import GCEPersistentDisk, Volume

        cloud = FakeCloud(instances=["n1"])
        spec = VolumeSpec(volume=Volume(
            name="v", gce_persistent_disk=GCEPersistentDisk(pd_name="d")))
        plugin = default_plugin_mgr().find_plugin_by_spec(spec)
        att = CloudDiskAttacher(plugin, cloud)
        assert att.wait_for_attach(spec, "n1", timeout=0.2) is None
        path = att.attach(spec, "n1")
        assert path == "/dev/disk/by-id/gce-pd/d"
        assert att.wait_for_attach(spec, "n1", timeout=1.0) == path
        # detach is idempotent
        att.detach("gce-pd/d", "n1")
        att.detach("gce-pd/d", "n1")


def test_localcloud_implements_disk_ops():
    """local-up wires cloud=LocalCloud into the attach/detach
    controller; the local provider must carry the same disk semantics
    as the fake (regression: it once inherited NotImplementedError)."""
    from kubernetes_tpu.cloudprovider import LocalCloud
    from kubernetes_tpu.cloudprovider.cloud import DiskConflict

    lc = LocalCloud()
    assert lc.attach_disk("d1", "n1") == "/dev/disk/by-id/d1"
    with pytest.raises(DiskConflict):
        lc.attach_disk("d1", "n2")
    assert lc.disks_attached_to("n1") == ["d1"]
    lc.detach_disk("d1", "n1")
    assert lc.all_disk_attachments() == {}


def test_startup_sweep_releases_holds_of_deleted_nodes():
    """A node deleted while the controller was DOWN must not leak its
    cloud holds: the first sync lists the cloud's attachment table and
    sweeps (reconciler.go actual-state at startup)."""
    from kubernetes_tpu.api.types import Node
    from kubernetes_tpu.cloudprovider import FakeCloud
    from kubernetes_tpu.controller.attach_detach import (
        AttachDetachController,
    )

    server = APIServer()
    client = RESTClient(LocalTransport(server))
    informers = SharedInformerFactory(client)
    cloud = FakeCloud(instances=["n1"])
    # a hold left by a previous controller process on a node that no
    # longer exists
    cloud.attach_disk("gce-pd/orphan", "dead-node")
    client.resource("nodes").create(Node(
        metadata=ObjectMeta(name="n1", namespace="")))
    ctrl = AttachDetachController(client, informers, cloud=cloud)
    informers.start()
    informers.wait_for_sync()
    ctrl.sync_once()
    assert not cloud.disk_is_attached("gce-pd/orphan", "dead-node")
    informers.stop()


# -- the multizone cloud provider: regional semantics behind the same --------
# interface (providers/aws + providers/gce registry breadth)


class TestMultiZoneCloud:
    def test_instances_and_zones(self):
        from kubernetes_tpu.cloudprovider import MultiZoneCloud, get_cloud_provider
        from kubernetes_tpu.cloudprovider.cloud import InstanceNotFound

        assert isinstance(get_cloud_provider("multizone"), MultiZoneCloud)
        cloud = MultiZoneCloud()
        zones = {cloud.add_instance(f"n{i}") for i in range(6)}
        assert zones == set(cloud.zones)  # round-robin covers all zones
        assert cloud.instance_zone("n0").region == "us-sim1"
        with pytest.raises(InstanceNotFound):
            cloud.instance_zone("ghost")
        assert cloud.external_id("n1").startswith("mz-us-sim1-")

    def test_zonal_disk_placement_rule(self):
        from kubernetes_tpu.cloudprovider import MultiZoneCloud
        from kubernetes_tpu.cloudprovider.cloud import DiskConflict

        cloud = MultiZoneCloud()
        cloud.add_instance("a1", "us-sim1-a")
        cloud.add_instance("b1", "us-sim1-b")
        cloud.create_disk("pd-a", "us-sim1-a")
        # attach in-zone OK; cross-zone is the GCE/EBS placement error
        cloud.attach_disk("pd-a", "a1")
        assert cloud.disk_is_attached("pd-a", "a1")
        with pytest.raises(DiskConflict):
            cloud.attach_disk("pd-a", "b1")
        # rw-exclusivity still holds within the zone
        cloud.add_instance("a2", "us-sim1-a")
        with pytest.raises(DiskConflict):
            cloud.attach_disk("pd-a", "a2")
        cloud.detach_disk("pd-a", "a1")
        assert not cloud.disk_is_attached("pd-a", "a1")

    def test_async_attach_passes_through_attaching(self):
        import threading

        from kubernetes_tpu.cloudprovider import MultiZoneCloud

        cloud = MultiZoneCloud(attach_latency=0.3)
        cloud.add_instance("n1", "us-sim1-a")
        done = threading.Event()

        def do():
            cloud.attach_disk("slow-pd", "n1")
            done.set()

        threading.Thread(target=do, daemon=True).start()
        # mid-flight: the cloud reports NOT attached yet
        time.sleep(0.1)
        assert not cloud.disk_is_attached("slow-pd", "n1")
        assert done.wait(5)
        assert cloud.disk_is_attached("slow-pd", "n1")

    def test_attach_detach_controller_against_multizone(self):
        """The SAME attach/detach controller drives the multizone cloud:
        async latency + zonal placement behind the shared interface."""
        from kubernetes_tpu.api.types import Node
        from kubernetes_tpu.cloudprovider import MultiZoneCloud
        from kubernetes_tpu.controller.attach_detach import (
            AttachDetachController,
        )
        from kubernetes_tpu.controller.framework import SharedInformerFactory
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import LocalTransport

        server = APIServer()
        client = RESTClient(LocalTransport(server))
        cloud = MultiZoneCloud(attach_latency=0.05, detach_latency=0.05)
        cloud.add_instance("n1", "us-sim1-a")
        informers = SharedInformerFactory(client)
        ctrl = AttachDetachController(client, informers, cloud=cloud)
        informers.start()
        client.resource("nodes").create(Node(metadata=ObjectMeta(name="n1")))
        client.pods().create(TestCloudDiskAttachers._pd_pod("p1", "n1", pd="mz-pd"))
        informers.wait_for_sync()

        def attached():
            n = client.nodes().get("n1")
            return {v.name for v in n.status.volumes_attached}

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            ctrl.sync_once()
            if attached() == {"gce-pd/mz-pd"}:
                break
            time.sleep(0.05)
        assert attached() == {"gce-pd/mz-pd"}
        assert cloud.disk_is_attached("gce-pd/mz-pd", "n1")
        client.pods().delete("p1")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            ctrl.sync_once()
            if not attached() and not cloud.disk_is_attached(
                    "gce-pd/mz-pd", "n1"):
                break
            time.sleep(0.05)
        assert not cloud.disk_is_attached("gce-pd/mz-pd", "n1")

    def test_service_controller_regional_lb(self):
        """ServiceController provisions a REGIONAL LB with hosts across
        zones through the same interface the local provider serves."""
        from kubernetes_tpu.api.types import (
            Node, NodeCondition, NodeStatus, Service, ServicePort,
            ServiceSpec,
        )
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import LocalTransport
        from kubernetes_tpu.cloudprovider import MultiZoneCloud
        from kubernetes_tpu.controller.cloud import ServiceController
        from kubernetes_tpu.controller.framework import SharedInformerFactory

        server = APIServer()
        client = RESTClient(LocalTransport(server))
        cloud = MultiZoneCloud()
        for i in range(3):
            cloud.add_instance(f"n{i}")
            client.nodes().create(Node(
                metadata=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(conditions=[NodeCondition("Ready", "True")]),
            ))
        informers = SharedInformerFactory(client)
        ctrl = ServiceController(client, informers, cloud)
        informers.start()
        informers.wait_for_sync()
        client.resource("services", "default").create(Service(
            metadata=ObjectMeta(name="web"),
            spec=ServiceSpec(
                type="LoadBalancer", selector={"run": "web"},
                ports=[ServicePort(port=80)],
            ),
        ))
        deadline = time.monotonic() + 10
        ingress = None
        while time.monotonic() < deadline:
            ctrl.sync_once()
            svc = client.resource("services", "default").get("web")
            ing = svc.status.load_balancer.ingress
            if ing:
                ingress = ing[0].ip
                break
            time.sleep(0.05)
        assert ingress and ingress.startswith("203.0."), ingress
        lb = cloud.get_tcp_load_balancer(
            ctrl._lb_name(svc), cloud.region
        )
        assert lb is not None and set(lb.hosts) == {"n0", "n1", "n2"}
        assert lb.ports == (80,)
        # deleting the service tears the LB down
        client.resource("services", "default").delete("web")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            ctrl.sync_once()
            if cloud.get_tcp_load_balancer("web", cloud.region) is None:
                break
            time.sleep(0.05)
