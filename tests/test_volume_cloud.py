"""Volume plugin registry, cloud provider fake, and the PV claim binder
(pkg/volume, pkg/cloudprovider, pkg/controller/persistentvolume)."""

import time

import pytest

from kubernetes_tpu.api.types import (
    AWSElasticBlockStore,
    GCEPersistentDisk,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    RBDVolume,
    Volume,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.cloudprovider import FakeCloud, get_cloud_provider
from kubernetes_tpu.controller.framework import SharedInformerFactory
from kubernetes_tpu.controller.pv_binder import PersistentVolumeClaimBinder
from kubernetes_tpu.volume import FakeMounter, default_plugin_mgr
from kubernetes_tpu.volume.plugins import VolumeSpec


def wait_until(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def test_plugin_resolution_and_mount_cycle():
    mgr = default_plugin_mgr()
    mounter = FakeMounter()
    specs = {
        "kubernetes.io/gce-pd": VolumeSpec(
            volume=Volume(name="d", gce_persistent_disk=GCEPersistentDisk(pd_name="pd1"))
        ),
        "kubernetes.io/aws-ebs": VolumeSpec(
            volume=Volume(name="e", aws_elastic_block_store=AWSElasticBlockStore(volume_id="v1"))
        ),
        "kubernetes.io/rbd": VolumeSpec(
            volume=Volume(name="r", rbd=RBDVolume(monitors=("m",), pool="p", image="i"))
        ),
        "kubernetes.io/empty-dir": VolumeSpec(volume=Volume(name="scratch")),
    }
    for want, spec in specs.items():
        plugin = mgr.find_plugin_by_spec(spec)
        assert plugin.name == want
        path = plugin.setup(mounter, spec, pod_uid="u1")
        assert mounter.is_mounted(path)
        plugin.teardown(mounter, spec, pod_uid="u1")
        assert not mounter.is_mounted(path)
    # PV-backed spec resolves too
    pv_spec = VolumeSpec(
        pv=PersistentVolume(
            metadata=ObjectMeta(name="pv1"),
            gce_persistent_disk=GCEPersistentDisk(pd_name="pd9"),
        )
    )
    assert mgr.find_plugin_by_spec(pv_spec).name == "kubernetes.io/gce-pd"
    assert mgr.find_plugin_by_name("kubernetes.io/aws-ebs").attachable


def test_fake_cloud_provider():
    cloud = get_cloud_provider("fake")
    assert isinstance(cloud, FakeCloud)
    cloud.instances = ["n1", "n2"]
    assert cloud.external_id("n1") == "ext-n1"
    assert cloud.list_instances() == ["n1", "n2"]
    assert cloud.get_zone().region == "us-central1"
    lb = cloud.ensure_tcp_load_balancer("svc", "r1", (80,), ("n1",))
    assert cloud.get_tcp_load_balancer("svc", "r1") == lb
    cloud.ensure_tcp_load_balancer_deleted("svc", "r1")
    assert cloud.get_tcp_load_balancer("svc", "r1") is None
    assert "ensure-lb" in cloud.calls


def test_pv_claim_binder():
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    informers = SharedInformerFactory(client)
    binder = PersistentVolumeClaimBinder(client, informers)
    pv_client = client.resource("persistentvolumes")
    pvc_client = client.resource("persistentvolumeclaims", "default")
    pv_client.create(PersistentVolume(
        metadata=ObjectMeta(name="small"), capacity={"storage": "1Gi"}))
    pv_client.create(PersistentVolume(
        metadata=ObjectMeta(name="big"), capacity={"storage": "100Gi"}))
    pvc_client.create(PersistentVolumeClaim(
        metadata=ObjectMeta(name="claim"), requests={"storage": "500Mi"}))
    informers.start()
    informers.wait_for_sync()
    assert wait_until(lambda: len(informers.informer("persistentvolumes").store.list()) == 2)
    assert binder.sync_once() == 1
    # smallest fitting PV wins; two-way binding recorded
    assert pvc_client.get("claim").volume_name == "small"
    assert pv_client.get("small").claim_ref == "default/claim"
    assert pv_client.get("big").claim_ref == ""
    # claim deleted -> PV released
    pvc_client.delete("claim")
    assert wait_until(
        lambda: len(informers.informer("persistentvolumeclaims").store.list()) == 0
    )
    binder.sync_once()
    assert pv_client.get("small").claim_ref == ""
    informers.stop()


def test_pv_binder_no_double_bind():
    """Review regression: two unbound PVCs and one PV must result in
    exactly one binding, not both claims sharing the volume."""
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    informers = SharedInformerFactory(client)
    binder = PersistentVolumeClaimBinder(client, informers)
    client.resource("persistentvolumes").create(PersistentVolume(
        metadata=ObjectMeta(name="only"), capacity={"storage": "10Gi"}))
    pvc_client = client.resource("persistentvolumeclaims", "default")
    pvc_client.create(PersistentVolumeClaim(
        metadata=ObjectMeta(name="a"), requests={"storage": "1Gi"}))
    pvc_client.create(PersistentVolumeClaim(
        metadata=ObjectMeta(name="b"), requests={"storage": "1Gi"}))
    informers.start()
    informers.wait_for_sync()
    assert wait_until(
        lambda: len(informers.informer("persistentvolumeclaims").store.list()) == 2
    )
    assert binder.sync_once() == 1
    bound = [pvc_client.get(n).volume_name for n in ("a", "b")]
    assert sorted(bound) == ["", "only"]
    informers.stop()
