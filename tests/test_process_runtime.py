"""ProcessRuntime: the kubelet driving real local processes as
containers (the docker_manager.go capability on a sandbox substrate).

What must hold: a bound pod's container is a LIVE process; PLEG notices
real process death; logs are what the process actually wrote; exec runs
real commands; probes and eviction act on the live substrate; /proc
feeds stats."""

import os
import signal
import time

import pytest

from kubernetes_tpu.api.types import (
    Container,
    ObjectMeta,
    Pod,
    PodSpec,
    Probe,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.kubelet import Kubelet, KubeletConfig, ProcessRuntime
from kubernetes_tpu.kubelet.process_runtime import ensure_pause


from conftest import wait_until  # noqa: E402


def _probe_proc() -> bool:
    """Restricted sandboxes mount /proc with hidepid (or without per-pid
    stat files); tests asserting on /proc contents then skip with a
    reason instead of failing on an environment gap."""
    import subprocess

    try:
        p = subprocess.Popen(["/bin/sh", "-c", "sleep 2"])
    except OSError:
        return False
    try:
        time.sleep(0.05)
        with open(f"/proc/{p.pid}/cmdline") as f:
            if not f.read():
                return False
        with open(f"/proc/{p.pid}/statm") as f:
            if int(f.read().split()[1]) <= 0:
                return False
        return True
    except (OSError, ValueError, IndexError):
        return False
    finally:
        p.kill()
        p.wait()


_PROC_OK = _probe_proc()
requires_proc = pytest.mark.skipif(
    not _PROC_OK,
    reason="restricted /proc: per-pid stat files unreadable here",
)


@pytest.fixture()
def plane(tmp_path):
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    runtime = ProcessRuntime(root_dir=str(tmp_path / "proc-root"))
    cfg = KubeletConfig(
        node_name="pnode",
        pleg_relist_period=0.05,
        status_sync_period=0.05,
        housekeeping_interval=0.2,
        node_status_update_frequency=0.2,
    )
    kl = Kubelet(client, cfg, runtime).run()
    yield server, client, kl, runtime
    kl.stop()
    runtime.close()


def bound_pod(name, command=None, restart_policy="Always", probe=None):
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(
            node_name="pnode",
            restart_policy=restart_policy,
            containers=[Container(
                name="main",
                image="kubernetes/pause:go",
                command=command or [],
                requests={"cpu": "100m"},
                liveness_probe=probe,
            )],
        ),
    )


def _runtime_pid(runtime, uid, name="main"):
    with runtime._lock:
        pp = runtime._pods.get(uid)
        c = pp.containers.get(name) if pp else None
        return c.proc.pid if c and c.exit_code is None else None


class TestProcessLifecycle:
    @requires_proc
    def test_pause_container_is_a_live_process(self, plane):
        server, client, kl, runtime = plane
        assert ensure_pause() is not None  # cc exists in this image
        client.pods().create(bound_pod("p1"))
        assert wait_until(
            lambda: client.pods().get("p1").status.phase == "Running"
        )
        uid = client.pods().get("p1").metadata.uid
        pid = _runtime_pid(runtime, uid)
        assert pid is not None
        # genuinely alive: /proc agrees and the binary is pause
        assert os.path.exists(f"/proc/{pid}")
        with open(f"/proc/{pid}/cmdline") as f:
            assert "pause" in f.read()

    def test_pleg_notices_real_process_death(self, plane):
        server, client, kl, runtime = plane
        # a short-lived real command: runs, exits 0
        client.pods().create(bound_pod(
            "p2", command=["/bin/sh", "-c", "sleep 30"]))
        assert wait_until(
            lambda: client.pods().get("p2").status.phase == "Running"
        )
        uid = client.pods().get("p2").metadata.uid
        pid = _runtime_pid(runtime, uid)
        os.kill(pid, signal.SIGKILL)  # the process dies OUTSIDE the kubelet
        # PLEG relist sees the death; restartPolicy Always restarts it
        assert wait_until(lambda: (
            _runtime_pid(runtime, uid) is not None
            and _runtime_pid(runtime, uid) != pid
        ))

    def test_run_to_completion_phase_succeeded(self, plane):
        server, client, kl, runtime = plane
        client.pods().create(bound_pod(
            "p3", command=["/bin/sh", "-c", "exit 0"],
            restart_policy="Never"))
        assert wait_until(
            lambda: client.pods().get("p3").status.phase == "Succeeded"
        )

    def test_failure_phase_failed(self, plane):
        server, client, kl, runtime = plane
        client.pods().create(bound_pod(
            "p4", command=["/bin/sh", "-c", "exit 3"],
            restart_policy="Never"))
        assert wait_until(
            lambda: client.pods().get("p4").status.phase == "Failed"
        )

    def test_logs_are_what_the_process_wrote(self, plane):
        server, client, kl, runtime = plane
        client.pods().create(bound_pod(
            "p5", command=["/bin/sh", "-c",
                           "echo hello-from-pod; sleep 30"]))
        assert wait_until(
            lambda: client.pods().get("p5").status.phase == "Running"
        )
        uid = client.pods().get("p5").metadata.uid
        assert wait_until(
            lambda: any("hello-from-pod" in l
                        for l in runtime.get_logs(uid, "main"))
        )

    def test_exec_runs_a_real_command(self, plane):
        server, client, kl, runtime = plane
        client.pods().create(bound_pod("p6"))
        assert wait_until(
            lambda: client.pods().get("p6").status.phase == "Running"
        )
        uid = client.pods().get("p6").metadata.uid
        out = runtime.exec_in(uid, "main", ["/bin/echo", "live-exec"])
        assert out.strip() == "live-exec"

    @requires_proc
    def test_pod_delete_reaps_the_process(self, plane):
        server, client, kl, runtime = plane
        client.pods().create(bound_pod("p7"))
        assert wait_until(
            lambda: client.pods().get("p7").status.phase == "Running"
        )
        uid = client.pods().get("p7").metadata.uid
        pid = _runtime_pid(runtime, uid)
        client.pods().delete("p7")
        assert wait_until(lambda: not os.path.exists(f"/proc/{pid}")
                          or open(f"/proc/{pid}/stat").read().split()[2] == "Z")

    @requires_proc
    def test_proc_stats(self, plane):
        server, client, kl, runtime = plane
        client.pods().create(bound_pod("p8"))
        assert wait_until(
            lambda: client.pods().get("p8").status.phase == "Running"
        )
        uid = client.pods().get("p8").metadata.uid
        stats = runtime.pod_stats(uid)
        assert "main" in stats
        assert stats["main"]["memory_rss_bytes"] > 0
        assert runtime.machine_memory_available() > 0


class TestLivenessOnLiveProcesses:
    def test_liveness_kill_restarts_real_process(self, plane):
        server, client, kl, runtime = plane
        probe = Probe(handler="exec",
                      exec_command=["/bin/sh", "-c", "exit 1"],
                      period_seconds=0.1, failure_threshold=2,
                      initial_delay_seconds=0)
        client.pods().create(bound_pod("p9", probe=probe))
        assert wait_until(
            lambda: client.pods().get("p9").status.phase == "Running"
        )
        uid = client.pods().get("p9").metadata.uid
        first = _runtime_pid(runtime, uid)
        # failing liveness: the kubelet kills and restarts -> new pid
        assert wait_until(lambda: (
            (p := _runtime_pid(runtime, uid)) is not None and p != first
        ))


class TestHardenedNodeAPI:
    """The node API gate (server.go TLS + authn): with a live-process
    runtime, an open /exec is remote code execution — serve HTTPS and
    demand the bearer token."""

    def test_tls_and_token_gate_logs_and_exec(self, tmp_path):
        import subprocess
        import urllib.error
        import urllib.request

        from kubernetes_tpu.kubectl.cmd import Kubectl

        cert, key = tmp_path / "tls.crt", tmp_path / "tls.key"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(cert), "-days", "1",
             "-subj", "/CN=127.0.0.1",
             "-addext", "subjectAltName=IP:127.0.0.1"],
            check=True, capture_output=True,
        )
        server = APIServer()
        client = RESTClient(LocalTransport(server))
        runtime = ProcessRuntime(root_dir=str(tmp_path / "rt"))
        kl = Kubelet(client, KubeletConfig(
            node_name="pnode",
            pleg_relist_period=0.05,
            status_sync_period=0.05,
            serve_api=True,
            api_tls_cert=str(cert),
            api_tls_key=str(key),
            api_auth_token="s3cret",
        ), runtime).run()
        try:
            client.pods().create(bound_pod(
                "sec", command=["/bin/sh", "-c",
                                "echo from-secure-pod; sleep 30"]))
            assert wait_until(
                lambda: client.pods().get("sec").status.phase == "Running"
            )
            node = client.nodes().get("pnode")
            assert node.status.kubelet_https
            base = f"https://127.0.0.1:{node.status.kubelet_port}"
            import ssl
            ctx = ssl.create_default_context(cafile=str(cert))
            # no token -> 401 (and the 401 arrives over TLS)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{base}/pods", timeout=5, context=ctx)
            assert ei.value.code == 401
            # plain http is refused outright
            with pytest.raises(OSError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{node.status.kubelet_port}/pods",
                    timeout=5)
            # kubectl with credentials: logs + exec reach the live pod
            kc = Kubectl(client, node_token="s3cret",
                         node_tls_ca=str(cert))
            assert wait_until(
                lambda: "from-secure-pod" in kc.logs("sec"))
            assert kc.exec("sec", ["/bin/echo", "exec-ok"]).strip() == \
                "exec-ok"
            # wrong token -> 401 through kubectl too
            bad = Kubectl(client, node_token="wrong",
                          node_tls_ca=str(cert))
            with pytest.raises(urllib.error.HTTPError) as ei:
                bad.logs("sec")
            assert ei.value.code == 401
        finally:
            kl.stop()
            runtime.close()


def test_ensure_pause_rejects_binary_that_cannot_run_here(
        tmp_path, monkeypatch):
    """Regression: a cached/checked-in pause built on a different image
    exec()s but dies in the dynamic loader (GLIBC skew), leaving every
    'running' pod a restart-flapping corpse. ensure_pause must validate
    the cached binary by RUNNING it and rebuild when it doesn't."""
    import shutil

    import kubernetes_tpu.kubelet.process_runtime as pr

    if not (shutil.which("cc") or shutil.which("gcc")):
        pytest.skip("no C compiler in this image")
    src = tmp_path / "pause.c"
    shutil.copy(pr._PAUSE_SRC, src)
    stale = tmp_path / "pause"
    # a stand-in for the loader-failure binary: exec succeeds, process
    # exits immediately — exactly what the GLIBC mismatch looks like
    stale.write_text("#!/bin/sh\nexit 127\n")
    stale.chmod(0o755)
    monkeypatch.setattr(pr, "_PAUSE_SRC", str(src))
    monkeypatch.setattr(pr, "_PAUSE_BIN", str(stale))
    pr._pause_validated.clear()
    try:
        out = pr.ensure_pause()
        assert out == str(stale)
        # the stale script was REPLACED by a real compiled pause...
        with open(out, "rb") as f:
            assert f.read(2) != b"#!", "stale binary was not rebuilt"
        # ...which actually survives the loader on this image
        assert pr._pause_runs_here(out)
    finally:
        pr._pause_validated.clear()
