"""Test harness configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding
(kubernetes_tpu.parallel) is exercised without TPU hardware, per the
kubemark idea in the reference (hollow nodes: real scheduler, fake
everything else — SURVEY.md §4).

NOTE: the jaxtyping pytest plugin imports jax before this conftest runs,
so env vars alone are too late — jax.config.update still works as long as
no backend has been initialized yet.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses
# The 8-device CPU mesh below would flip EVERY TPUProvider daemon test
# onto the mesh path via KUBERNETES_TPU_MESH=auto, silently dropping
# coverage of the single-chip daemon path (the production path on any
# 1-device host). Tests that want the mesh daemon opt in with
# monkeypatch.setenv("KUBERNETES_TPU_MESH", "force").
os.environ.setdefault("KUBERNETES_TPU_MESH", "off")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.5) has no jax_num_cpu_devices option; the
    # --xla_force_host_platform_device_count XLA flag above is the
    # equivalent and is honored by every version in use here
    pass

# Build the native engines up front (cached by mtime) so the C-replay
# differential fuzz tests exercise replay.c instead of silently skipping
# (the round-2 failure: the driver's test run never executed the C path).
from kubernetes_tpu.native.build import ensure_all

ensure_all()


# -- optional-dependency auto-skip --------------------------------------------
#
# The image lacks `cryptography` (service-account JWT signing) and this
# jax build predates `jax.shard_map` (the mesh scheduler's entry point).
# Tests needing either are environment gaps, not regressions — report
# them as SKIPPED instead of collection errors / failures so tier-1
# output only goes red for real breakage. Both conversions are gated on
# the dependency actually being absent: with the dep installed, a
# matching error is a genuine failure and stays one.

import importlib

import pytest


def _have_module(name):
    try:
        importlib.import_module(name)
        return True
    except ImportError:
        return False


_MISSING_DEPS = []
if not _have_module("cryptography"):
    _MISSING_DEPS.append("cryptography")
# parallel/compat.py bridges `jax.shard_map` to the 0.4.x experimental
# spelling, so the mesh path only goes missing when NEITHER exists
from kubernetes_tpu.parallel.compat import have_shard_map

if not have_shard_map():
    _MISSING_DEPS.append("shard_map")


def _missing_dep_in(exc) -> str:
    if not isinstance(exc, (ImportError, AttributeError)):
        return ""
    text = str(exc)
    for dep in _MISSING_DEPS:
        if dep in text:
            return dep
    return ""


def pytest_pycollect_makemodule(module_path, parent):
    """Collect test modules through a guard that turns an ImportError
    caused by a known-missing optional dependency into a module-level
    skip (the importorskip outcome, without editing every test file)."""

    class GuardedModule(pytest.Module):
        def _getobj(self):
            try:
                return super()._getobj()
            except self.CollectError as e:
                # pytest wraps the module's ImportError into CollectError
                # (with the traceback text) before it reaches us
                text = str(e)
                for dep in _MISSING_DEPS:
                    if dep in text:
                        raise pytest.skip.Exception(
                            f"optional dependency {dep!r} not in this image",
                            allow_module_level=True,
                        ) from e
                raise
            except ImportError as e:
                dep = _missing_dep_in(e)
                if dep:
                    raise pytest.skip.Exception(
                        f"optional dependency {dep!r} not in this image: {e}",
                        allow_module_level=True,
                    ) from e
                raise

    return GuardedModule.from_parent(parent, path=module_path)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """Lazily-imported optional deps fail inside the test call (the
    mesh path resolves shard_map through kubernetes_tpu.parallel.compat
    at dispatch time); remap those failures to skips the same way."""
    outcome = yield
    rep = outcome.get_result()
    if rep.when in ("setup", "call") and rep.failed and call.excinfo is not None:
        dep = _missing_dep_in(call.excinfo.value)
        if dep:
            rep.outcome = "skipped"
            rep.longrepr = (
                str(item.path),
                item.location[1],
                f"Skipped: optional dependency {dep!r} not in this image",
            )


if os.environ.get("KUBERNETES_TPU_LOCK_SANITIZER"):
    # opt-in suite-wide arming of the lock-order sanitizer (the chaos
    # module arms it unconditionally): KUBERNETES_TPU_LOCK_SANITIZER=1
    # wraps EVERY test, so any suite doubles as an ordering witness
    from kubernetes_tpu.analysis import locks as _locks

    @pytest.fixture(autouse=True)
    def _global_lock_sanitizer():
        with _locks.instrumented():
            yield
        _locks.assert_no_cycles("(suite-wide)")


if os.environ.get("KUBERNETES_TPU_RACE_SANITIZER"):
    # opt-in suite-wide arming of the DATA-RACE sanitizer (lockset +
    # vector-clock happens-before, analysis/races), mirroring the lock
    # sanitizer: KUBERNETES_TPU_RACE_SANITIZER=1 wraps every test so
    # any suite doubles as a race witness. Findings accumulate into the
    # KUBERNETES_TPU_RACE_REPORT JSONL artifact (when set) that
    # `python -m kubernetes_tpu.analysis --race-report` merges back
    # into the CI gate; an unsuppressed race also fails the exposing
    # test directly. This is a SEPARATE CI invocation, not the default
    # tier-1 run — the detector's instrumentation overhead rides every
    # tracked attribute access (see README "Static analysis").
    from kubernetes_tpu.analysis import races as _races

    # truncate the artifact once per session: dump_jsonl appends per
    # test, and stale rows from a PREVIOUS run (races since fixed)
    # would keep failing the --race-report gate forever
    _report = os.environ.get("KUBERNETES_TPU_RACE_REPORT")
    if _report:
        open(_report, "w").close()

    @pytest.fixture(autouse=True)
    def _global_race_sanitizer():
        with _races.instrumented(reset=True):
            yield
        report = os.environ.get("KUBERNETES_TPU_RACE_REPORT")
        if report:
            _races.dump_jsonl(report)
        _races.assert_no_races("(suite-wide)")


def pytest_configure(config):
    # tier-1 runs with -m 'not slow'; the slow set is the hours-long
    # production-realism forms (full chaos scenarios, A/B soaks)
    config.addinivalue_line(
        "markers",
        "slow: production-realism long forms excluded from tier-1",
    )


def wait_until(cond, timeout=60.0, interval=0.01):
    """Poll `cond` until truthy or `timeout` elapses. The single shared
    copy (each test file used to carry its own, and the defaults
    drifted): a passing wait returns immediately, so the generous
    deadline only slows genuinely failing tests."""
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return bool(cond())
