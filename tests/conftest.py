"""Test harness configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding
(kubernetes_tpu.parallel) is exercised without TPU hardware, per the
kubemark idea in the reference (hollow nodes: real scheduler, fake
everything else — SURVEY.md §4).

NOTE: the jaxtyping pytest plugin imports jax before this conftest runs,
so env vars alone are too late — jax.config.update still works as long as
no backend has been initialized yet.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses
# The 8-device CPU mesh below would flip EVERY TPUProvider daemon test
# onto the mesh path via KUBERNETES_TPU_MESH=auto, silently dropping
# coverage of the single-chip daemon path (the production path on any
# 1-device host). Tests that want the mesh daemon opt in with
# monkeypatch.setenv("KUBERNETES_TPU_MESH", "force").
os.environ.setdefault("KUBERNETES_TPU_MESH", "off")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.5) has no jax_num_cpu_devices option; the
    # --xla_force_host_platform_device_count XLA flag above is the
    # equivalent and is honored by every version in use here
    pass

# Build the native engines up front (cached by mtime) so the C-replay
# differential fuzz tests exercise replay.c instead of silently skipping
# (the round-2 failure: the driver's test run never executed the C path).
from kubernetes_tpu.native.build import ensure_all

ensure_all()


def wait_until(cond, timeout=60.0, interval=0.01):
    """Poll `cond` until truthy or `timeout` elapses. The single shared
    copy (each test file used to carry its own, and the defaults
    drifted): a passing wait returns immediately, so the generous
    deadline only slows genuinely failing tests."""
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return bool(cond())
