"""Test harness configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding
(kubernetes_tpu.parallel) is exercised without TPU hardware, per the
kubemark idea in the reference (hollow nodes: real scheduler, fake
everything else — SURVEY.md §4).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env may point at TPU
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
