"""Round-19 kernel-path contracts: the Pallas probe build must be
bit-identical to the lax build, quantized table placement must be
lossless (including the int8 -> int16 boundary rebuild), the
double-buffered pipeline must reproduce the serial loop's decisions
exactly, the bf16 profile must ride the ShadowGate, and the trace
accountant must attribute staged encode seconds as probe overlap.

Every identity here is exact array/decision equality — the kernel
path's whole contract is that raw speed changes NOTHING observable."""

import json
import random
import types

import numpy as np
import pytest

from kubernetes_tpu.api.types import (
    Container,
    Node,
    NodeCondition,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    Service,
    ServiceSpec,
)
from kubernetes_tpu.models.batch import BatchScheduler, SchedulerConfig
from kubernetes_tpu.models.probe import WaveProbe
from kubernetes_tpu.models.wave import WaveScheduler
from kubernetes_tpu.oracle import ClusterState
from kubernetes_tpu.parallel import quant
from kubernetes_tpu.scheduler.tpu_algorithm import TPUScheduleAlgorithm
from kubernetes_tpu.snapshot.encode import SnapshotEncoder

from tests.test_conformance import random_scenario
from tests.test_wave import oracle_backlog


# -- parallel/quant units ------------------------------------------------------


def test_narrow_dtype_boundaries():
    def dt(vals, dtype=np.int32, name="zone_id"):
        return quant.narrow_dtype(name, np.asarray(vals, dtype))

    assert dt([0, 127]) == np.int8
    assert dt([0, 128]) == np.int16
    assert dt([-128, 0]) == np.int8
    assert dt([-129, 0]) == np.int16
    assert dt([0, 32767]) == np.int16
    # past int16: keep the original width (no int32 "narrowing" step)
    assert dt([0, 32768]) == np.int32
    assert dt([0, 32768], np.int64) == np.int64
    # empty tables place at the narrowest width and rebuild on growth
    assert dt([]) == np.int8


def test_narrow_dtype_scope():
    # only the declared-narrowable names shrink; bitsets/floats/bytes
    # pass through untouched
    big = np.arange(4, dtype=np.int64)
    assert quant.narrow_dtype("alloc_cpu", big) == np.int64
    assert quant.narrow_dtype("label_kv", np.zeros(4, np.uint32)) \
        == np.uint32
    assert quant.narrow_dtype("zone_id", np.zeros(4, np.float32)) \
        == np.float32
    assert quant.narrow_dtype("zone_id", np.zeros(4, np.int16)) \
        == np.int16  # already narrow: no re-audit churn


def test_narrow_eq_out_of_range_guard():
    import jax.numpy as jnp

    table = jnp.asarray(np.array([1, 2, 3, 127], np.int8))
    # in-range compare matches the wide compare exactly
    assert np.array_equal(
        np.asarray(quant.narrow_eq(table, jnp.asarray(3))),
        np.array([False, False, True, False]))
    # an out-of-vocab wide comparand must NOT alias into the narrow
    # range (300 % 256 = 44 would otherwise be a valid int8)
    assert not np.asarray(
        quant.narrow_eq(table, jnp.asarray(300))).any()
    assert not np.asarray(
        quant.narrow_eq(table, jnp.asarray(-300))).any()


def test_narrow_matvec_matches_wide():
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    table = rng.integers(0, 100, (32, 8)).astype(np.int8)
    vec = rng.integers(0, 2, 8).astype(np.int32)  # 0/1 indicator
    got = np.asarray(quant.narrow_matvec(
        jnp.asarray(table), jnp.asarray(vec), np.int32))
    want = table.astype(np.int32) @ vec
    assert got.dtype == np.int32 and np.array_equal(got, want)


def test_shadow_gate_stride_and_fallback():
    g = quant.ShadowGate(stride=4)
    checks = [g.should_check() for _ in range(9)]
    assert checks == [True, False, False, False, True,
                      False, False, False, True]
    g.record(True)
    assert not g.fallen_back and g.divergence == 0
    g.record(False)
    assert g.fallen_back and g.divergence == 1
    # fallen back: no further waves sample
    assert not g.should_check()
    assert quant.ShadowGate(stride=0).should_check() is False


# -- quantized placement: device dtype + boundary rebuild ----------------------


def test_to_dev_many_narrow_placement_and_boundary_rebuild():
    ws = WaveScheduler(quant_mode="int")
    zid = (np.arange(24) % 3).astype(np.int32)
    snap = types.SimpleNamespace(zone_id=zid)
    out = ws._to_dev_many(snap, ["zone_id"], keep=frozenset())
    assert out["zone_id"].dtype == np.int8  # placed narrow
    assert ws._dev["zone_id"][3].dtype == np.int32  # mirror full width
    ships0 = ws.stats["table_ships"]

    # unchanged content: reuse, no bytes
    out = ws._to_dev_many(snap, ["zone_id"], keep=frozenset())
    assert out["zone_id"].dtype == np.int8
    assert ws.stats["table_ships"] == ships0
    assert ws.stats["table_bytes_reused"] > 0

    # vocab growth past int8: the placement dtype is part of the cache
    # key, so the first sync after an out-of-range value rebuilds wider
    snap.zone_id = zid.copy()
    snap.zone_id[5] = 200
    out = ws._to_dev_many(snap, ["zone_id"], keep=frozenset())
    assert out["zone_id"].dtype == np.int16
    assert ws.stats["table_ships"] == ships0 + 1

    # and past int16 -> full width
    snap.zone_id = zid.copy()
    snap.zone_id[5] = 40000
    out = ws._to_dev_many(snap, ["zone_id"], keep=frozenset())
    assert out["zone_id"].dtype == np.int32


def test_to_dev_many_wide_mode_off():
    ws = WaveScheduler(quant_mode="off")
    snap = types.SimpleNamespace(zone_id=(np.arange(8) % 3)
                                 .astype(np.int32))
    out = ws._to_dev_many(snap, ["zone_id"], keep=frozenset())
    assert out["zone_id"].dtype == np.int32


# -- probe builds: pallas == lax, bf16 == i64 on the audit scenario ------------


def _probe_inputs(J=64):
    import jax.numpy as jnp

    from kubernetes_tpu.analysis.programs import _scenario

    config = SchedulerConfig()
    snap, batch = _scenario()
    num_zones = max(int(snap.zone_id.max()) + 1, 1)
    num_values = int(snap.svc_num_values)
    sched = BatchScheduler(config)
    static = {f: jnp.asarray(getattr(snap, f))
              for f in BatchScheduler.STATIC_FIELDS}
    static.update(BatchScheduler.config_static(config, snap))
    carry = sched.initial_carry(snap)
    pod = {f: jnp.asarray(np.asarray(getattr(batch, f))[0])
           for f in BatchScheduler.POD_FIELDS}
    return config, num_zones, num_values, J, static, carry, pod


def test_pallas_probe_bit_identical_to_lax():
    config, nz, nv, J, static, carry, pod = _probe_inputs()
    lax_out = WaveProbe(config, kernel="lax")._compiled(
        nz, nv, J)(static, carry, pod)
    pal_out = WaveProbe(config, kernel="pallas")._compiled(
        nz, nv, J)(static, carry, pod)
    a = np.asarray(lax_out["packed"])
    b = np.asarray(pal_out["packed"])
    assert a.dtype == b.dtype and a.shape == b.shape
    assert np.array_equal(a, b)


def test_bf16_probe_matches_i64_on_default_profile():
    # the default profile's summed |weight|*10 bound fits bf16's exact
    # integer range, so the bf16 accumulator is bit-identical here
    config, nz, nv, J, static, carry, pod = _probe_inputs()
    i64 = WaveProbe(config, score_mode="i64")._compiled(
        nz, nv, J)(static, carry, pod)
    b16 = WaveProbe(config, score_mode="bf16")._compiled(
        nz, nv, J)(static, carry, pod)
    assert np.array_equal(np.asarray(i64["packed"]),
                          np.asarray(b16["packed"]))


def test_probe_kernel_env_selection(monkeypatch):
    monkeypatch.delenv("KUBERNETES_TPU_KERNEL", raising=False)
    assert WaveProbe(SchedulerConfig()).kernel == "lax"
    monkeypatch.setenv("KUBERNETES_TPU_KERNEL", "pallas")
    assert WaveProbe(SchedulerConfig()).kernel == "pallas"
    # explicit ctor arg beats the env (the shadow-driver seam)
    assert WaveProbe(SchedulerConfig(), kernel="lax").kernel == "lax"


# -- end-to-end bit-identity: quant / pipeline / full stack --------------------


def _staged_backlog(num_nodes=16, num_pods=120, templates=3, block=10):
    """Blocks of impure runs (soft anti-affinity against the NEXT
    group) — the shape where the pipeline actually stages; mirrors
    bench.build_multi at test scale."""
    nodes = [
        Node(
            metadata=ObjectMeta(
                name=f"kn-{i:03d}",
                labels={"kubernetes.io/hostname": f"kn-{i:03d}"},
            ),
            status=NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[NodeCondition("Ready", "True")],
            ),
        )
        for i in range(num_nodes)
    ]
    pods = []
    for i in range(num_pods):
        t = (i // block) % templates
        p = Pod(
            metadata=ObjectMeta(name=f"kp-{i:04d}",
                                labels={"group": f"g{t}"}),
            spec=PodSpec(containers=[Container(
                requests={"cpu": "100m", "memory": "200Mi"})]),
        )
        p.metadata.annotations = {
            "scheduler.alpha.kubernetes.io/affinity": json.dumps({
                "podAntiAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [{
                        "weight": 1,
                        "podAffinityTerm": {
                            "labelSelector": {"matchLabels": {
                                "group": f"g{(t + 1) % templates}"}},
                            "topologyKey": "kubernetes.io/hostname",
                            "namespaces": [],
                        },
                    }],
                },
            })
        }
        pods.append(p)
    services = [
        Service(metadata=ObjectMeta(name=f"ksvc-{t}"),
                spec=ServiceSpec(selector={"group": f"g{t}"}))
        for t in range(templates)
    ]
    return ClusterState.build(nodes, services=services), pods


def test_pipeline_decisions_identical_to_serial():
    from kubernetes_tpu.parallel.mesh import _pad_snapshot
    from kubernetes_tpu.snapshot.encode import pod_feature_key
    from kubernetes_tpu.snapshot.pad import next_pow2

    state, pods = _staged_backlog()
    uniq, rep_of, rep_list = [], {}, []
    for p in pods:
        k = pod_feature_key(p)
        if k not in rep_of:
            rep_of[k] = len(uniq)
            uniq.append(p)
        rep_list.append(rep_of[k])
    enc = SnapshotEncoder(state, uniq)
    snap = enc.encode_nodes()
    batch = enc.encode_pods()
    snap = _pad_snapshot(snap, next_pow2(snap.num_nodes, 4))
    rep_idx = np.asarray(rep_list, np.int64)

    serial = WaveScheduler(min_run=1, pipeline=False)
    piped = WaveScheduler(min_run=1, pipeline=True)
    s_chosen, s_carry, s_last = serial.schedule_backlog(
        snap, batch, rep_idx)
    p_chosen, p_carry, p_last = piped.schedule_backlog(
        snap, batch, rep_idx)
    assert np.array_equal(s_chosen, p_chosen)
    assert s_last == p_last
    # the pipelined driver actually staged (the wave kept per-wave
    # dispatch tallies; staging shows up as its own count)
    assert piped.dispatches.get("stage", 0) > 0
    assert serial.dispatches.get("stage", 0) == 0


def test_pipeline_env_gate(monkeypatch):
    monkeypatch.delenv("KUBERNETES_TPU_PIPELINE", raising=False)
    assert WaveScheduler().pipeline is False
    monkeypatch.setenv("KUBERNETES_TPU_PIPELINE", "1")
    assert WaveScheduler().pipeline is True
    assert WaveScheduler(pipeline=False).pipeline is False


def test_full_stack_matches_oracle_end_to_end(monkeypatch):
    # quant int + pipeline on, against the oracle: the whole round-19
    # stack must change nothing observable
    state, pods = _staged_backlog(num_nodes=12, num_pods=90,
                                  templates=3, block=10)
    want = oracle_backlog(state, pods)
    monkeypatch.setenv("KUBERNETES_TPU_QUANT", "int")
    monkeypatch.setenv("KUBERNETES_TPU_PIPELINE", "1")
    got = TPUScheduleAlgorithm().schedule_backlog(pods, state)
    assert got == want


@pytest.mark.parametrize("seed", [11, 23])
def test_quant_decision_identity_fuzz(monkeypatch, seed):
    rng = random.Random(seed)
    state, pending = random_scenario(
        rng, n_nodes=10, n_existing=12, n_pending=30,
        interpod_p=0.2, volumes_p=0.3)
    monkeypatch.setenv("KUBERNETES_TPU_QUANT", "off")
    wide = TPUScheduleAlgorithm().schedule_backlog(pending,
                                                   state.clone())
    monkeypatch.setenv("KUBERNETES_TPU_QUANT", "int")
    narrow = TPUScheduleAlgorithm().schedule_backlog(pending,
                                                     state.clone())
    assert narrow == wide


@pytest.mark.slow
@pytest.mark.parametrize("seed", [3, 5, 17, 29])
def test_quant_pipeline_identity_fuzz_slow(monkeypatch, seed):
    rng = random.Random(seed)
    state, pending = random_scenario(
        rng, n_nodes=14, n_existing=20, n_pending=60,
        interpod_p=0.3, volumes_p=0.3)
    monkeypatch.delenv("KUBERNETES_TPU_QUANT", raising=False)
    monkeypatch.delenv("KUBERNETES_TPU_PIPELINE", raising=False)
    base = TPUScheduleAlgorithm().schedule_backlog(pending,
                                                   state.clone())
    monkeypatch.setenv("KUBERNETES_TPU_QUANT", "int")
    monkeypatch.setenv("KUBERNETES_TPU_PIPELINE", "1")
    full = TPUScheduleAlgorithm().schedule_backlog(pending,
                                                   state.clone())
    assert full == base


# -- bf16 ShadowGate wiring ----------------------------------------------------


def test_bf16_profile_builds_shadow_and_matches(monkeypatch):
    monkeypatch.setenv("KUBERNETES_TPU_QUANT", "bf16")
    monkeypatch.setenv("KUBERNETES_TPU_QUANT_SHADOW", "1")
    state, pods = _staged_backlog(num_nodes=10, num_pods=60,
                                  templates=2, block=10)
    algo = TPUScheduleAlgorithm()
    assert algo._shadow_gate is not None
    assert algo._shadow_wave is not None
    got = algo.schedule_backlog(pods, state.clone())
    assert algo._shadow_gate.checked >= 1
    assert algo._shadow_gate.divergence == 0
    monkeypatch.setenv("KUBERNETES_TPU_QUANT", "off")
    wide = TPUScheduleAlgorithm().schedule_backlog(pods, state.clone())
    assert got == wide


def test_bf16_shadow_divergence_falls_back(monkeypatch):
    from kubernetes_tpu.metrics import (
        scheduler_quant_shadow_divergence_total,
    )

    monkeypatch.setenv("KUBERNETES_TPU_QUANT", "bf16")
    monkeypatch.setenv("KUBERNETES_TPU_QUANT_SHADOW", "1")
    state, pods = _staged_backlog(num_nodes=8, num_pods=40,
                                  templates=2, block=10)
    algo = TPUScheduleAlgorithm()
    shadow = algo._shadow_wave
    real_fn = shadow.schedule_backlog

    def lying_shadow(*a, **kw):
        chosen, carry, last = real_fn(*a, **kw)
        bad = np.asarray(chosen).copy()
        bad[0] = -1 if bad[0] != -1 else 0
        return bad, carry, last

    shadow.schedule_backlog = lying_shadow
    before = scheduler_quant_shadow_divergence_total.get()
    algo.schedule_backlog(pods, state.clone())
    assert scheduler_quant_shadow_divergence_total.get() == before + 1
    assert algo._shadow_gate.fallen_back
    # after the trip the shadow (full-width) wave IS the driver; undo
    # the lie and confirm the next backlog schedules sanely through it
    shadow.schedule_backlog = real_fn
    got = algo.schedule_backlog(pods, state.clone())
    assert sum(1 for h in got if h is not None) > 0


# -- trace accountant: overlap attribution -------------------------------------


def test_overlap_totals_attributes_nested_encode():
    import time as _time

    from kubernetes_tpu.trace import profile as tp
    from kubernetes_tpu.trace import spans as trace_span

    if not trace_span.enabled():
        pytest.skip("tracing force-disabled in this environment")
    pt0, ov0 = tp.phase_totals(), tp.overlap_totals()
    with tp.phase_timer("probe"):
        with tp.phase_timer("encode"):  # staged pack inside the window
            _time.sleep(0.03)
        _time.sleep(0.01)
    pt1, ov1 = tp.phase_totals(), tp.overlap_totals()
    # encode (rank 0) steals the exclusive timeline from probe, so the
    # nested 30ms shows up as probe OVERLAP — hidden staging seconds
    assert pt1["probe"] - pt0["probe"] >= 0.035
    assert ov1["probe"] - ov0["probe"] >= 0.02
    assert ov1["encode"] - ov0["encode"] <= 0.005


# -- dtype contract (analysis gate) --------------------------------------------


def _audit_dtype(fn, args, narrow_dtypes):
    import jax

    from kubernetes_tpu.analysis.jaxpr_audit import _dtype_findings
    from kubernetes_tpu.analysis.programs import ProgramSpec

    spec = ProgramSpec(name="t", fn=fn, args=args,
                       narrow_dtypes=narrow_dtypes)
    return _dtype_findings(spec, jax.make_jaxpr(fn)(*args))


def test_dtype_contract_flags_widening():
    import jax.numpy as jnp

    def widens(static, x):
        # terminal use is a reduction, not a gather index — the widened
        # full-width table is genuinely materialized and consumed
        return jnp.sum(static["zone_id"].astype(jnp.int32) * x)

    args = ({"zone_id": jnp.zeros(16, jnp.int8)},
            jnp.ones(16, jnp.int32))
    found = _audit_dtype(widens, args, (("zone_id", "|i1"),))
    assert len(found) == 1 and "widening" in found[0].message


def test_dtype_contract_exempts_index_feeds():
    import jax.numpy as jnp

    def gathers(static, w):
        idx = static["zone_id"]  # narrow ids used ONLY as indices
        return w.at[idx].add(1), w[idx]

    args = ({"zone_id": jnp.zeros(16, jnp.int8)},
            jnp.ones(8, jnp.int64))
    assert _audit_dtype(gathers, args, (("zone_id", "|i1"),)) == []


def test_dtype_contract_flags_wide_arrival():
    import jax.numpy as jnp

    def f(static):
        return static["zone_id"] + 0

    args = ({"zone_id": jnp.zeros(16, jnp.int32)},)
    found = _audit_dtype(f, args, (("zone_id", "|i1"),))
    assert len(found) == 1 and "arrives" in found[0].message


def test_registered_quant_programs_clean():
    # the registry's probe_quant_* specs carry the contract; they must
    # trace clean end to end (the CI gate runs audit_all; this is the
    # fast in-suite slice for the two quant builds + pallas)
    from kubernetes_tpu.analysis.jaxpr_audit import audit_program
    from kubernetes_tpu.analysis.programs import build_programs

    specs = {s.name: s for s in build_programs(include_mesh=False)}
    for name in ("probe_quant_int8", "probe_quant_int16"):
        assert name in specs
        assert specs[name].narrow_dtypes
        assert audit_program(specs[name]) == []
