"""ServiceAccount JWT tokens + webhook authn/authz (VERDICT r2 #10).

Reference: pkg/serviceaccount/jwt.go (RS256 token mint/verify),
pkg/serviceaccount/{serviceaccounts,tokens}_controller.go (default SA +
token secrets), plugin/pkg/auth/authenticator/token/webhook +
plugin/pkg/auth/authorizer/webhook (TokenReview / SubjectAccessReview
over HTTP, cached, authz failing closed).
"""

import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.auth.authn import (
    AuthenticationError,
    TokenAuthenticator,
    UnionAuthenticator,
    UserInfo,
)
from kubernetes_tpu.auth.authz import ABACAuthorizer, Attributes
from kubernetes_tpu.auth.serviceaccount import (
    JWTTokenAuthenticator,
    TokenGenerator,
    generate_key,
)
from kubernetes_tpu.auth.webhook import (
    WebhookAuthorizer,
    WebhookTokenAuthenticator,
)
from kubernetes_tpu.client.rest import APIStatusError, RESTClient
from kubernetes_tpu.client.transport import HTTPTransport, LocalTransport
from kubernetes_tpu.controller.manager import (
    ControllerManager,
    ControllerManagerOptions,
)
from kubernetes_tpu.controller.serviceaccount import make_token_lookup


from conftest import wait_until  # noqa: E402


KEY = generate_key()  # RSA keygen is slow; share across tests


class TestJWT:
    def test_mint_and_verify(self):
        gen = TokenGenerator(KEY)
        token = gen.generate("team-a", "builder", "uid-1", "builder-token")
        authn = JWTTokenAuthenticator(KEY.public_key())
        user = authn.authenticate({"Authorization": f"Bearer {token}"})
        assert user.name == "system:serviceaccount:team-a:builder"
        assert user.uid == "uid-1"
        assert set(user.groups) == {
            "system:serviceaccounts", "system:serviceaccounts:team-a"
        }

    def test_tampered_and_foreign_tokens_rejected(self):
        gen = TokenGenerator(KEY)
        token = gen.generate("ns", "sa", "u", "s")
        authn = JWTTokenAuthenticator(KEY.public_key())
        head, payload, sig = token.split(".")
        # swap the namespace claim: signature check must fail -> no
        # opinion (falls through the union, ends 401 with nothing else)
        claims = json.loads(
            base64.urlsafe_b64decode(payload + "=" * (-len(payload) % 4))
        )
        claims["kubernetes.io/serviceaccount/namespace"] = "kube-system"
        forged = base64.urlsafe_b64encode(
            json.dumps(claims).encode()
        ).rstrip(b"=").decode()
        assert authn.authenticate(
            {"Authorization": f"Bearer {head}.{forged}.{sig}"}
        ) is None
        # token signed by a different key
        other = TokenGenerator(generate_key()).generate("ns", "sa", "u", "s")
        assert authn.authenticate(
            {"Authorization": f"Bearer {other}"}
        ) is None
        # non-JWT bearer tokens are not our business
        assert authn.authenticate(
            {"Authorization": "Bearer plain-old-token"}
        ) is None

    def test_lookup_rejects_deleted_account(self):
        gen = TokenGenerator(KEY)
        token = gen.generate("ns", "gone", "u", "gone-token")
        authn = JWTTokenAuthenticator(
            KEY.public_key(), lookup=lambda ns, name, secret: False
        )
        with pytest.raises(AuthenticationError):
            authn.authenticate({"Authorization": f"Bearer {token}"})


class TestControllersEndToEnd:
    def test_default_sa_token_and_tls_frontend_auth(self, tmp_path):
        """The controllers mint default/default's token; a client using
        it against the HTTPS frontend authenticates as the SA and ABAC
        authorizes it; deleting the SA kills the token (lookup)."""
        server = APIServer()
        local = RESTClient(LocalTransport(server))
        cm = ControllerManager(
            local,
            ControllerManagerOptions(service_account_private_key=KEY),
        ).start()
        try:
            # namespace exists (auto-provisioned on first write)
            local.pods().create(t.Pod(
                metadata=t.ObjectMeta(name="seed"),
                spec=t.PodSpec(containers=[t.Container(name="c")]),
            ))
            assert wait_until(lambda: _token(local) is not None)
            token = _token(local)

            # lock the frontend down: SA JWTs + ABAC for the SA user
            server.authenticator = UnionAuthenticator([
                JWTTokenAuthenticator(
                    KEY.public_key(), lookup=make_token_lookup(local)
                ),
            ])
            server.authorizer = ABACAuthorizer.from_jsonl(json.dumps({
                "user": "system:serviceaccount:default:default",
                "resource": "pods", "namespace": "default",
                "readonly": True,
            }))
            host, port = server.serve_http(port=0)
            authed = RESTClient(HTTPTransport(
                f"http://{host}:{port}",
                bearer_token=token,
            ))
            pods, _rv = authed.pods().list()
            assert [p.metadata.name for p in pods] == ["seed"]
            # ABAC: readonly only — a write is 403
            with pytest.raises(APIStatusError) as ei:
                authed.pods().create(t.Pod(
                    metadata=t.ObjectMeta(name="nope"),
                    spec=t.PodSpec(containers=[t.Container(name="c")]),
                ))
            assert ei.value.code == 403
            # no token at all: 401
            anon = RESTClient(HTTPTransport(f"http://{host}:{port}"))
            with pytest.raises(APIStatusError) as ei:
                anon.pods().list()
            assert ei.value.code == 401
            # rotation: deleting the token secret revokes the OLD token
            # (unique secret names — the re-mint can never resurrect it)
            sa = local.resource("serviceaccounts", "default").get("default")
            old_secret = sa.secrets[0]
            local.resource("secrets", "default").delete(old_secret)
            sa.secrets = []
            local.resource("serviceaccounts", "default").update(sa)
            assert wait_until(
                lambda: (_token(local) or "") not in ("", token)
            )
            with pytest.raises(APIStatusError) as ei:
                authed.pods().list()  # old token: dead
            assert ei.value.code == 401
            rotated = RESTClient(HTTPTransport(
                f"http://{host}:{port}", bearer_token=_token(local)
            ))
            assert rotated.pods().list()[0]  # new token: live
            # delete the SA: its token dies with it and the orphaned
            # secret is reaped
            local.resource("serviceaccounts", "default").delete("default")
            with pytest.raises(APIStatusError) as ei:
                rotated.pods().list()
            assert ei.value.code == 401
            def _reaped():
                names = [
                    s.metadata.name
                    for s in local.resource("secrets", "default").list()[0]
                    if s.type == "kubernetes.io/service-account-token"
                ]
                return not names
            # (the SA controller recreates default/default, which mints
            # a fresh secret; the ORPHANED one must be gone)
            assert wait_until(lambda: (_token(local) is not None) or _reaped())
        finally:
            server.shutdown_http()
            cm.stop()


def _token(client):
    try:
        sa = client.resource("serviceaccounts", "default").get("default")
    except APIStatusError:
        return None
    for name in sa.secrets:
        try:
            sec = client.resource("secrets", "default").get(name)
        except APIStatusError:
            continue
        if sec.type == "kubernetes.io/service-account-token":
            return base64.b64decode(sec.data["token"]).decode()
    return None


class _Webhook(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


def _webhook(respond):
    """A fake TokenReview/SubjectAccessReview endpoint."""
    calls = []

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n))
            calls.append(body)
            data = json.dumps(respond(body)).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    srv = _Webhook(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}", calls


class TestWebhooks:
    def test_token_review(self):
        def respond(body):
            ok = body["spec"]["token"] == "good"
            status = {"authenticated": ok}
            if ok:
                status["user"] = {"username": "alice", "uid": "a1",
                                  "groups": ["dev"]}
            return {"kind": "TokenReview", "status": status}

        srv, url, calls = _webhook(respond)
        try:
            authn = WebhookTokenAuthenticator(url, cache_ttl=60)
            user = authn.authenticate({"Authorization": "Bearer good"})
            assert user == UserInfo(name="alice", uid="a1", groups=("dev",))
            assert authn.authenticate(
                {"Authorization": "Bearer bad"}
            ) is None
            # verdicts (accept AND reject) are cached
            n = len(calls)
            authn.authenticate({"Authorization": "Bearer good"})
            authn.authenticate({"Authorization": "Bearer bad"})
            assert len(calls) == n
        finally:
            srv.shutdown()
            srv.server_close()

    def test_token_review_webhook_down_is_no_opinion(self):
        authn = WebhookTokenAuthenticator(
            "http://127.0.0.1:1", timeout=0.2
        )
        union = UnionAuthenticator([
            authn,
            TokenAuthenticator({"fallback": UserInfo(name="bob")}),
        ])
        # webhook unreachable: union continues to the static tokens
        assert union.authenticate(
            {"Authorization": "Bearer fallback"}
        ).name == "bob"

    def test_subject_access_review_and_fail_closed(self):
        def respond(body):
            spec = body["spec"]
            # the client ships mapped API verbs (a GET on a
            # collection reviews as "list"), like upstream
            allowed = (
                spec["user"] == "alice"
                and spec["resourceAttributes"]["verb"] == "list"
            )
            return {"kind": "SubjectAccessReview",
                    "status": {"allowed": allowed}}

        srv, url, calls = _webhook(respond)
        alice = UserInfo(name="alice")
        attrs_get = Attributes(user=alice, verb="GET", resource="pods",
                               namespace="default")
        attrs_post = Attributes(user=alice, verb="POST", resource="pods",
                                namespace="default")
        try:
            authz = WebhookAuthorizer(url, cache_ttl=60)
            assert authz.authorize(attrs_get) is True
            assert authz.authorize(attrs_post) is False
            n = len(calls)
            assert authz.authorize(attrs_get) is True  # cached
            assert len(calls) == n
        finally:
            srv.shutdown()
            srv.server_close()
        # unreachable authorizer must DENY, not allow
        dead = WebhookAuthorizer("http://127.0.0.1:1", timeout=0.2)
        assert dead.authorize(attrs_get) is False


# -- RBAC (pkg/apis/rbac + the rbac authorizer) ------------------------------


class TestRBAC:
    def _plane(self):
        import subprocess

        from kubernetes_tpu.api import types as t
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.auth.authn import (
            TokenAuthenticator,
            UserInfo,
        )
        from kubernetes_tpu.auth.rbac import RBACAuthorizer
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import LocalTransport

        api = APIServer(
            authenticator=TokenAuthenticator({
                "alice-token": UserInfo(name="alice", groups=("devs",)),
                "bob-token": UserInfo(name="bob", groups=("ops",)),
            }),
        )
        api.authorizer = RBACAuthorizer(api)
        admin = RESTClient(LocalTransport(api))  # bypasses HTTP auth
        return api, admin, t

    @staticmethod
    def _grant(admin, t, name, ns, rules, subjects, cluster=False):
        if cluster:
            admin.resource("clusterroles").create(
                t.ClusterRole(metadata=t.ObjectMeta(name=name, namespace=""),
                              rules=rules))
            admin.resource("clusterrolebindings").create(
                t.ClusterRoleBinding(
                    metadata=t.ObjectMeta(name=f"{name}-b", namespace=""),
                    subjects=subjects,
                    role_ref=t.RoleRef(kind="ClusterRole", name=name)))
        else:
            admin.resource("roles", ns).create(
                t.Role(metadata=t.ObjectMeta(name=name, namespace=ns),
                       rules=rules))
            admin.resource("rolebindings", ns).create(
                t.RoleBinding(
                    metadata=t.ObjectMeta(name=f"{name}-b", namespace=ns),
                    subjects=subjects,
                    role_ref=t.RoleRef(kind="Role", name=name)))

    def test_namespace_scoping_and_verbs(self):
        import urllib.request
        import urllib.error

        api, admin, t = self._plane()
        self._grant(
            admin, t, "pod-reader", "default",
            rules=[t.PolicyRule(verbs=["get", "list"],
                                resources=["pods"])],
            subjects=[t.RBACSubject(kind="User", name="alice")],
        )
        host, port = api.serve_http()
        base = f"http://{host}:{port}"

        def req(path, token, method="GET", data=None):
            r = urllib.request.Request(
                f"{base}{path}", method=method, data=data,
                headers={"Authorization": f"Bearer {token}",
                         **({"Content-Type": "application/json"}
                            if data else {})},
            )
            return urllib.request.urlopen(r, timeout=10).status

        # alice reads pods in default
        assert req("/api/v1/namespaces/default/pods", "alice-token") == 200
        # ...but cannot write them (verb not granted)
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("/api/v1/namespaces/default/pods", "alice-token",
                method="POST",
                data=b'{"kind":"Pod","metadata":{"name":"x"},'
                     b'"spec":{"containers":[{"name":"c"}]}}')
        assert ei.value.code == 403
        # ...and not in another namespace (RoleBinding is namespaced)
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("/api/v1/namespaces/other/pods", "alice-token")
        assert ei.value.code == 403
        # bob has no grants at all: deny-by-default
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("/api/v1/namespaces/default/pods", "bob-token")
        assert ei.value.code == 403

    def test_group_subject_and_cluster_wildcards(self):
        import urllib.request
        import urllib.error

        api, admin, t = self._plane()
        # ops group gets cluster-admin-ish wildcard rules
        self._grant(
            admin, t, "admin",  "",
            rules=[t.PolicyRule(verbs=["*"], api_groups=["*"],
                                resources=["*"])],
            subjects=[t.RBACSubject(kind="Group", name="ops")],
            cluster=True,
        )
        host, port = api.serve_http()
        base = f"http://{host}:{port}"

        def req(path, token, method="GET"):
            r = urllib.request.Request(
                f"{base}{path}", method=method,
                headers={"Authorization": f"Bearer {token}"})
            return urllib.request.urlopen(r, timeout=10).status

        # bob (group ops) can read anything, any namespace, any group
        assert req("/api/v1/namespaces/x/pods", "bob-token") == 200
        assert req("/apis/extensions/v1beta1/namespaces/x/replicasets",
                   "bob-token") == 200
        assert req("/api/v1/nodes", "bob-token") == 200
        # alice is not in ops
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("/api/v1/nodes", "alice-token")
        assert ei.value.code == 403

    def test_resource_names_and_api_groups(self):
        from kubernetes_tpu.auth.authz import Attributes
        from kubernetes_tpu.auth.authn import UserInfo
        from kubernetes_tpu.auth.rbac import RBACAuthorizer

        api, admin, t = self._plane()
        self._grant(
            admin, t, "one-cm", "default",
            rules=[t.PolicyRule(verbs=["get"], resources=["configmaps"],
                                resource_names=["the-one"])],
            subjects=[t.RBACSubject(kind="User", name="alice")],
        )
        rbac = api.authorizer
        alice = UserInfo(name="alice", groups=("devs",))

        def attrs(**kw):
            return Attributes(user=alice, verb="GET",
                              resource="configmaps",
                              namespace="default", **kw)

        assert rbac.authorize(attrs(name="the-one"))
        assert not rbac.authorize(attrs(name="another"))
        assert not rbac.authorize(attrs())  # list needs no-name grant
        # core-group rule does not bleed into named groups
        ext = Attributes(user=alice, verb="GET", resource="configmaps",
                         namespace="default", name="the-one",
                         api_group="extensions")
        assert not rbac.authorize(ext)

    def test_subresource_watch_and_nonresource_semantics(self):
        from kubernetes_tpu.auth.authn import UserInfo
        from kubernetes_tpu.auth.authz import Attributes

        api, admin, t = self._plane()
        self._grant(
            admin, t, "narrow", "default",
            rules=[
                t.PolicyRule(verbs=["update"], resources=["pods/status"]),
                t.PolicyRule(verbs=["watch"], resources=["pods"]),
                t.PolicyRule(verbs=["get"],
                             non_resource_urls=["/healthz", "/debug/*"]),
            ],
            subjects=[t.RBACSubject(kind="User", name="alice")],
        )
        rbac = api.authorizer
        alice = UserInfo(name="alice", groups=())

        def attrs(**kw):
            base = dict(user=alice, verb="GET", resource="pods",
                        namespace="default")
            base.update(kw)
            return Attributes(**base)

        # pods/status grant covers ONLY the status subresource
        assert rbac.authorize(attrs(verb="PUT", name="p",
                                    subresource="status"))
        assert not rbac.authorize(attrs(verb="PUT", name="p"))
        # watch is its own verb: granted explicitly, not via list
        assert rbac.authorize(attrs(query_watch=True))
        assert not rbac.authorize(attrs())  # plain list not granted
        # nonResourceURLs: exact + trailing-star prefix
        assert rbac.authorize(attrs(resource="", path="/healthz"))
        assert rbac.authorize(attrs(resource="",
                                    path="/debug/pprof/goroutine"))
        assert not rbac.authorize(attrs(resource="", path="/metrics"))

    def test_rbac_objects_ride_the_json_wire(self):
        """Role round-trips through the plain-JSON HTTP transport (the
        kind registry regression: object-protocol tests can't catch a
        missing scheme registration)."""
        from kubernetes_tpu.api import types as t
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import HTTPTransport

        api = APIServer()
        host, port = api.serve_http()
        client = RESTClient(HTTPTransport(f"http://{host}:{port}"))
        role = t.Role(
            metadata=t.ObjectMeta(name="reader"),
            rules=[t.PolicyRule(verbs=["get"], resources=["pods"])],
        )
        created = client.resource("roles", "default").create(role)
        assert type(created) is t.Role
        got = client.resource("roles", "default").get("reader")
        assert got.rules[0].verbs == ["get"]
        crb = t.ClusterRoleBinding(
            metadata=t.ObjectMeta(name="b", namespace=""),
            subjects=[t.RBACSubject(kind="Group", name="ops")],
            role_ref=t.RoleRef(kind="ClusterRole", name="admin"),
        )
        client.resource("clusterrolebindings").create(crb)
        items, _ = client.resource("clusterrolebindings").list()
        assert items[0].subjects[0].name == "ops"


class TestReviewEndpoints:
    """The SERVER side of the webhook wire: this apiserver answers
    TokenReview and SubjectAccessReview, so the existing webhook
    CLIENTS can point one apiserver's authn/authz at another's."""

    def _api(self):
        from kubernetes_tpu.api import types as t
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.auth.authn import TokenAuthenticator, UserInfo
        from kubernetes_tpu.auth.rbac import RBACAuthorizer
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import LocalTransport

        api = APIServer(authenticator=TokenAuthenticator({
            "good-token": UserInfo(name="carol", uid="u1",
                                   groups=("qa",)),
        }))
        api.authorizer = RBACAuthorizer(api)
        admin = RESTClient(LocalTransport(api))
        admin.resource("clusterroles").create(t.ClusterRole(
            metadata=t.ObjectMeta(name="viewer", namespace=""),
            rules=[t.PolicyRule(verbs=["get", "list"],
                                resources=["pods"])]))
        admin.resource("clusterrolebindings").create(t.ClusterRoleBinding(
            metadata=t.ObjectMeta(name="viewer-b", namespace=""),
            subjects=[t.RBACSubject(kind="Group", name="qa")],
            role_ref=t.RoleRef(kind="ClusterRole", name="viewer")))
        return api

    def test_tokenreview_round_trip(self):
        api = self._api()
        code, out = api.handle(
            "POST",
            "/apis/authentication.k8s.io/v1beta1/tokenreviews",
            body={"kind": "TokenReview",
                  "spec": {"token": "good-token"}},
        )
        assert code == 201
        assert out["status"]["authenticated"] is True
        assert out["status"]["user"]["username"] == "carol"
        assert out["status"]["user"]["groups"] == ["qa"]
        code, out = api.handle(
            "POST",
            "/apis/authentication.k8s.io/v1beta1/tokenreviews",
            body={"kind": "TokenReview", "spec": {"token": "bogus"}},
        )
        assert out["status"]["authenticated"] is False

    def test_subjectaccessreview_round_trip(self):
        api = self._api()

        def sar(spec):
            code, out = api.handle(
                "POST",
                "/apis/authorization.k8s.io/v1beta1/subjectaccessreviews",
                body={"kind": "SubjectAccessReview", "spec": spec},
            )
            assert code == 201
            return out["status"]["allowed"]

        assert sar({"user": "carol", "groups": ["qa"],
                    "resourceAttributes": {"verb": "get",
                                           "resource": "pods",
                                           "name": "p1",
                                           "namespace": "x"}})
        assert not sar({"user": "carol", "groups": ["qa"],
                        "resourceAttributes": {"verb": "create",
                                               "resource": "pods",
                                               "namespace": "x"}})
        assert not sar({"user": "mallory", "groups": [],
                        "resourceAttributes": {"verb": "get",
                                               "resource": "pods"}})

    def test_webhook_clients_point_at_this_server(self):
        """The loop closes: WebhookTokenAuthenticator /
        WebhookAuthorizer against OUR endpoints."""
        from kubernetes_tpu.auth.authn import UserInfo
        from kubernetes_tpu.auth.authz import Attributes
        from kubernetes_tpu.auth.webhook import (
            WebhookAuthorizer,
            WebhookTokenAuthenticator,
        )

        from kubernetes_tpu.api import types as t
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import LocalTransport

        api = self._api()
        # the caller of a review endpoint authenticates and needs the
        # auth-delegator grants (create tokenreviews/SARs)
        admin = RESTClient(LocalTransport(api))
        admin.resource("clusterroles").create(t.ClusterRole(
            metadata=t.ObjectMeta(name="auth-delegator", namespace=""),
            rules=[t.PolicyRule(
                verbs=["create"],
                api_groups=["*"],
                resources=["tokenreviews", "subjectaccessreviews"])]))
        admin.resource("clusterrolebindings").create(t.ClusterRoleBinding(
            metadata=t.ObjectMeta(name="auth-delegator-b", namespace=""),
            subjects=[t.RBACSubject(kind="User", name="carol")],
            role_ref=t.RoleRef(kind="ClusterRole",
                               name="auth-delegator")))
        host, port = api.serve_http()
        base = f"http://{host}:{port}"
        wa = WebhookTokenAuthenticator(
            f"{base}/apis/authentication.k8s.io/v1beta1/tokenreviews",
            bearer_token="good-token")
        user = wa.authenticate(
            {"Authorization": "Bearer good-token"})
        assert user is not None and user.name == "carol"
        assert wa.authenticate({"Authorization": "Bearer nope"}) is None
        wz = WebhookAuthorizer(
            f"{base}/apis/authorization.k8s.io/v1beta1/"
            "subjectaccessreviews", bearer_token="good-token")
        carol = UserInfo(name="carol", groups=("qa",))
        assert wz.authorize(Attributes(
            user=carol, verb="get", resource="pods", namespace="x",
            name="p1"))
        assert not wz.authorize(Attributes(
            user=carol, verb="create", resource="pods", namespace="x"))

    def test_sar_cache_keys_on_the_full_request(self):
        """A cached named-get verdict must not answer a collection
        list (the cache-key collision would be privilege escalation
        under resourceNames grants)."""
        from kubernetes_tpu.auth.authn import UserInfo
        from kubernetes_tpu.auth.authz import Attributes
        from kubernetes_tpu.auth.webhook import WebhookAuthorizer

        api = self._api()
        admin_calls = []
        # grant carol get on pod p1 ONLY (resourceNames)
        from kubernetes_tpu.api import types as t
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.client.transport import LocalTransport

        admin = RESTClient(LocalTransport(api))
        admin.resource("clusterroles").create(t.ClusterRole(
            metadata=t.ObjectMeta(name="p1-only", namespace=""),
            rules=[t.PolicyRule(verbs=["get"], resources=["secrets"],
                                resource_names=["p1"])]))
        admin.resource("clusterrolebindings").create(t.ClusterRoleBinding(
            metadata=t.ObjectMeta(name="p1-only-b", namespace=""),
            subjects=[t.RBACSubject(kind="User", name="carol")],
            role_ref=t.RoleRef(kind="ClusterRole", name="p1-only")))
        admin.resource("clusterroles").create(t.ClusterRole(
            metadata=t.ObjectMeta(name="delegate", namespace=""),
            rules=[t.PolicyRule(verbs=["create"], api_groups=["*"],
                                resources=["subjectaccessreviews"])]))
        admin.resource("clusterrolebindings").create(t.ClusterRoleBinding(
            metadata=t.ObjectMeta(name="delegate-b", namespace=""),
            subjects=[t.RBACSubject(kind="User", name="carol")],
            role_ref=t.RoleRef(kind="ClusterRole", name="delegate")))
        host, port = api.serve_http()
        wz = WebhookAuthorizer(
            f"http://{host}:{port}/apis/authorization.k8s.io/v1beta1/"
            "subjectaccessreviews", bearer_token="good-token",
            cache_ttl=60)
        carol = UserInfo(name="carol", groups=("qa",))
        named = Attributes(user=carol, verb="GET", resource="secrets",
                           namespace="x", name="p1")
        listing = Attributes(user=carol, verb="GET", resource="secrets",
                             namespace="x")
        assert wz.authorize(named) is True
        # the cached named-get verdict must NOT leak onto the list
        assert wz.authorize(listing) is False
