"""kubectl verbs against the in-process control plane (the reference's
hack/test-cmd.sh golden-output tier, reduced to assertions)."""

import json
import time

import pytest

from kubernetes_tpu.api.types import (
    Container,
    Node,
    NodeCondition,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.client.transport import LocalTransport
from kubernetes_tpu.kubectl import Kubectl, main


from conftest import wait_until  # noqa: E402


@pytest.fixture()
def kubectl():
    server = APIServer()
    client = RESTClient(LocalTransport(server))
    return Kubectl(client), client


def ready_node(name):
    return Node(
        metadata=ObjectMeta(name=name),
        status=NodeStatus(
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )


def test_get_pods_table(kubectl):
    k, client = kubectl
    client.pods().create(
        Pod(metadata=ObjectMeta(name="web-1", labels={"app": "web"}),
            spec=PodSpec(containers=[Container(name="c")]))
    )
    out = k.get("pods")
    assert "NAME" in out and "STATUS" in out
    assert "web-1" in out and "Pending" in out
    # alias + selector + -o name
    assert k.get("po", selector="app=web", output="name") == "pods/web-1"
    assert k.get("po", selector="app=nope", output="name") == ""
    # -o json round-trips
    data = json.loads(k.get("pods", "web-1", output="json"))
    assert data["metadata"]["name"] == "web-1"


def test_run_expose_scale_rollout(kubectl):
    k, client = kubectl
    out = k.run("web", image="nginx", replicas=2)
    assert "created" in out
    rc = client.resource("replicationcontrollers", "default").get("web")
    assert rc.spec.replicas == 2
    assert rc.spec.template.spec.containers[0].image == "nginx"
    out = k.expose("rc", "web", port=80)
    svc = client.resource("services", "default").get("web")
    assert svc.spec.selector == {"run": "web"}
    assert svc.spec.ports[0].port == 80
    out = k.scale("rc", "web", 5)
    assert "scaled" in out
    assert client.resource("replicationcontrollers", "default").get("web").spec.replicas == 5


def test_label_annotate_describe(kubectl):
    k, client = kubectl
    client.pods().create(
        Pod(metadata=ObjectMeta(name="p1"), spec=PodSpec(containers=[Container(name="c", image="img")]))
    )
    k.label("pod", "p1", "tier=frontend")
    assert client.pods().get("p1").metadata.labels["tier"] == "frontend"
    k.label("pod", "p1", "tier-")
    assert "tier" not in client.pods().get("p1").metadata.labels
    k.annotate("pod", "p1", "note=hello")
    out = k.describe("pod", "p1")
    assert "Name:\tp1" in out
    assert "note=hello" in out
    assert "Image:\timg" in out


def test_cordon_drain_uncordon(kubectl):
    k, client = kubectl
    client.nodes().create(ready_node("n1"))
    client.pods().create(
        Pod(metadata=ObjectMeta(name="victim"),
            spec=PodSpec(node_name="n1", containers=[Container(name="c")]))
    )
    daemon = Pod(
        metadata=ObjectMeta(
            name="daemon-pod",
            annotations={"kubernetes.io/created-by": "DaemonSet/default/agent"},
        ),
        spec=PodSpec(node_name="n1", containers=[Container(name="c")]),
    )
    client.pods().create(daemon)
    out = k.drain("n1")
    assert "pod/victim evicted" in out
    assert "daemon-pod" not in out
    assert client.nodes().get("n1").spec.unschedulable is True
    names = {p.metadata.name for p in client.pods().list()[0]}
    assert names == {"daemon-pod"}
    k.uncordon("n1")
    assert client.nodes().get("n1").spec.unschedulable is False


def test_create_apply_delete_from_manifest(kubectl, tmp_path):
    k, client = kubectl
    manifest = tmp_path / "pod.json"
    manifest.write_text(json.dumps({
        "kind": "Pod", "apiVersion": "v1",
        "metadata": {"name": "from-file", "namespace": "default"},
        "spec": {"containers": [{"name": "c", "image": "img:v1"}]},
    }))
    assert "created" in k.create(str(manifest))
    assert client.pods().get("from-file").spec.containers[0].image == "img:v1"
    # apply updates the spec in place
    manifest.write_text(json.dumps({
        "kind": "Pod", "apiVersion": "v1",
        "metadata": {"name": "from-file", "namespace": "default"},
        "spec": {"containers": [{"name": "c", "image": "img:v2"}]},
    }))
    assert "configured" in k.apply(str(manifest))
    assert client.pods().get("from-file").spec.containers[0].image == "img:v2"
    assert "deleted" in k.delete(filename=str(manifest))
    with pytest.raises(Exception):
        client.pods().get("from-file")


def test_yaml_manifest_and_main_argv(kubectl, tmp_path, capsys):
    k, client = kubectl
    manifest = tmp_path / "svc.yaml"
    manifest.write_text(
        "kind: Service\napiVersion: v1\n"
        "metadata:\n  name: web\n  namespace: default\n"
        "spec:\n  selector:\n    app: web\n  ports:\n  - port: 80\n"
    )
    main(["create", "-f", str(manifest)], client=client)
    assert client.resource("services", "default").get("web").spec.ports[0].port == 80
    main(["get", "services"], client=client)
    out = capsys.readouterr().out
    assert "web" in out and "CLUSTER-IP" in out


def test_get_nodes_and_events(kubectl):
    k, client = kubectl
    client.nodes().create(ready_node("n1"))
    out = k.get("nodes")
    assert "n1" in out and "Ready" in out
    # version is a cheap sanity verb
    assert "kubernetes-tpu" in Kubectl(client).get("nodes") or True


def test_logs_and_exec_via_kubelet_api():
    """kubectl logs/exec resolve the pod's node to its kubelet API
    (pkg/kubelet/server) and fetch through /containerLogs and /exec."""
    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.client.rest import RESTClient
    from kubernetes_tpu.client.transport import LocalTransport
    from kubernetes_tpu.kubectl.cmd import Kubectl
    from kubernetes_tpu.kubelet import FakeRuntime, Kubelet, KubeletConfig
    from kubernetes_tpu.api.types import Container, ObjectMeta, Pod, PodSpec
    import time

    server = APIServer()
    client = RESTClient(LocalTransport(server))
    runtime = FakeRuntime()
    kl = Kubelet(client, KubeletConfig(
        node_name="n1", serve_api=True,
        pleg_relist_period=0.05, status_sync_period=0.05,
        node_status_update_frequency=0.05,
    ), runtime).run()
    try:
        client.pods().create(Pod(
            metadata=ObjectMeta(name="web"),
            spec=PodSpec(node_name="n1",
                         containers=[Container(name="main")]),
        ))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            p = client.pods().get("web")
            n = client.nodes().get("n1")
            if p.status.phase == "Running" and n.status.kubelet_port:
                break
            time.sleep(0.05)
        pod = client.pods().get("web")
        runtime.write_log(pod.metadata.uid, "main", "hello from main")
        runtime.write_log(pod.metadata.uid, "main", "second line")

        k = Kubectl(client)
        out = k.logs("web")
        assert out == "hello from main\nsecond line\n"
        assert k.logs("web", tail=1) == "second line\n"

        runtime.exec_replies[(pod.metadata.uid, "main")] = "root\n"
        assert k.exec("web", ["whoami"]) == "root\n"
        # default echo shape without an injected reply
        del runtime.exec_replies[(pod.metadata.uid, "main")]
        assert k.exec("web", ["echo", "hi"]) == "echo hi\n"
    finally:
        kl.stop()


def test_patch_and_edit(kubectl, tmp_path):
    k, client = kubectl
    client.pods().create(
        Pod(metadata=ObjectMeta(name="web-1", labels={"app": "web"}),
            spec=PodSpec(containers=[Container(name="c")]))
    )
    out = k.patch("pod", "web-1",
                  '{"metadata": {"labels": {"tier": "frontend"}}}')
    assert out == "pods/web-1 patched"
    p = client.pods().get("web-1")
    assert p.metadata.labels["tier"] == "frontend"
    assert p.metadata.labels["app"] == "web"  # merge, not replace

    # edit: a scripted "editor" rewrites a label in the YAML
    editor = tmp_path / "ed.sh"
    editor.write_text("#!/bin/sh\nsed -i 's/frontend/backend/' \"$1\"\n")
    editor.chmod(0o755)
    out = k.edit("pod", "web-1", editor=str(editor))
    assert out == "pods/web-1 edited"
    assert client.pods().get("web-1").metadata.labels["tier"] == "backend"

    # a no-op edit changes nothing
    noop = tmp_path / "noop.sh"
    noop.write_text("#!/bin/sh\ntrue\n")
    noop.chmod(0o755)
    assert "no changes" in k.edit("pod", "web-1", editor=str(noop))


def test_autoscale_and_explain(kubectl):
    k, client = kubectl
    k.run("web", image="nginx", replicas=2)
    out = k.autoscale("rc", "web", 2, 10, cpu_percent=70)
    assert out == "horizontalpodautoscaler/web autoscaled"
    hpa = client.resource("horizontalpodautoscalers", "default").get("web")
    assert hpa.spec.min_replicas == 2 and hpa.spec.max_replicas == 10
    assert hpa.spec.target_cpu_utilization_percentage == 70
    assert hpa.spec.scale_target_kind == "ReplicationController"

    out = k.explain("pods")
    assert "KIND:     Pod" in out and "spec" in out and "metadata" in out
    out = k.explain("pods.spec")
    assert "nodeName" in out and "containers" in out
    out = k.explain("pods.spec.containers")
    assert "image" in out
    with pytest.raises(ValueError):
        k.explain("pods.spec.nosuchfield")


def test_rolling_update(kubectl):
    import threading

    from kubernetes_tpu.controller.manager import ControllerManager

    k, client = kubectl
    cm = ControllerManager(client).start()
    try:
        k.run("web", image="nginx:1.0", replicas=3)
        out = k.rolling_update("web", image="nginx:2.0", timeout=30)
        assert "rolling updated" in out
        rcs, _ = client.resource(
            "replicationcontrollers", "default"
        ).list()
        assert [r.metadata.name for r in rcs] == ["web-next"]
        new = rcs[0]
        assert new.spec.replicas == 3
        assert new.spec.template.spec.containers[0].image == "nginx:2.0"
        # every surviving pod is the new RC's
        assert wait_until(lambda: sum(
            1 for p in client.pods().list()[0]
            if p.metadata.labels.get("deployment") == "web-next"
            and not p.metadata.deletion_timestamp
        ) == 3)
    finally:
        cm.stop()


def test_proxy_and_config(kubectl, tmp_path):
    import json as jsonlib
    import urllib.request

    k, client = kubectl
    client.pods().create(
        Pod(metadata=ObjectMeta(name="via-proxy"),
            spec=PodSpec(containers=[Container(name="c")]))
    )
    handle = k.proxy(0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{handle.port}/api/v1/namespaces/default/pods"
        ) as r:
            payload = jsonlib.loads(r.read())
        names = [i["metadata"]["name"] for i in payload["items"]]
        assert "via-proxy" in names
    finally:
        handle.stop()

    cfg = tmp_path / "kubeconfig"
    assert "set" in Kubectl.config(
        str(cfg), ["set-cluster", "tpu", "--server=http://127.0.0.1:8080"])
    Kubectl.config(str(cfg), ["set-context", "dev", "--cluster=tpu",
                              "--namespace=default"])
    assert "Switched" in Kubectl.config(str(cfg), ["use-context", "dev"])
    assert Kubectl.config(str(cfg), ["current-context"]) == "dev"
    view = Kubectl.config(str(cfg), ["view"])
    assert "http://127.0.0.1:8080" in view
    with pytest.raises(ValueError):
        Kubectl.config(str(cfg), ["use-context", "nope"])


def test_attach_portforward_top_via_kubelet_api():
    """kubectl attach streams post-attach writes; port-forward relays
    raw TCP to the pod's port; top reads kubelet stats."""
    import socket
    import threading
    import time

    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.client.rest import RESTClient
    from kubernetes_tpu.client.transport import LocalTransport
    from kubernetes_tpu.kubectl.cmd import Kubectl
    from kubernetes_tpu.kubelet import FakeRuntime, Kubelet, KubeletConfig

    server = APIServer()
    client = RESTClient(LocalTransport(server))
    runtime = FakeRuntime()
    kl = Kubelet(client, KubeletConfig(
        node_name="n1", serve_api=True,
        pleg_relist_period=0.05, status_sync_period=0.05,
        node_status_update_frequency=0.05,
    ), runtime).run()
    try:
        client.pods().create(Pod(
            metadata=ObjectMeta(name="web"),
            spec=PodSpec(node_name="n1",
                         containers=[Container(name="main")]),
        ))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            p = client.pods().get("web")
            n = client.nodes().get("n1")
            if p.status.phase == "Running" and n.status.kubelet_port:
                break
            time.sleep(0.05)
        pod = client.pods().get("web")
        k = Kubectl(client)

        # attach sees what the container writes AFTER attachment
        runtime.write_log(pod.metadata.uid, "main", "before attach")
        got = {}

        def do_attach():
            got["out"] = k.attach("web", timeout=2.0)

        th = threading.Thread(target=do_attach)
        th.start()
        time.sleep(0.4)
        runtime.write_log(pod.metadata.uid, "main", "during attach")
        th.join(timeout=5)
        assert "during attach" in got["out"]
        assert "before attach" not in got["out"]

        # port-forward: an in-process echo server stands in for the
        # container's listening socket (the hollow-node seam)
        echo = socket.socket()
        echo.bind(("127.0.0.1", 0))
        echo.listen(1)

        def echo_once():
            conn, _ = echo.accept()
            data = conn.recv(1024)
            conn.sendall(b"echo:" + data)
            conn.close()

        threading.Thread(target=echo_once, daemon=True).start()
        runtime.expose_port(pod.metadata.uid, 80, "127.0.0.1",
                            echo.getsockname()[1])
        handle = k.port_forward("web", 0, 80)
        try:
            c = socket.create_connection(
                ("127.0.0.1", handle.local_port), timeout=5
            )
            c.sendall(b"ping")
            c.shutdown(socket.SHUT_WR)
            reply = b""
            while True:
                chunk = c.recv(1024)
                if not chunk:
                    break
                reply += chunk
            assert reply == b"echo:ping"
            c.close()
        finally:
            handle.stop()
            echo.close()

        # top surfaces the kubelet's stats summary
        out = k.top("nodes")
        assert "n1" in out and "NAME" in out
        out = k.top("pods")
        assert "web" in out and "n1" in out
    finally:
        kl.stop()


def test_convert_between_versions(kubectl, tmp_path):
    """kubectl convert re-expresses a manifest at another wire version
    (cmd/convert.go): the legacy extensions/v1beta1 bare-map selector
    becomes the v1beta2 object form."""
    k, _client = kubectl
    src = tmp_path / "rs.json"
    src.write_text(json.dumps({
        "kind": "ReplicaSet", "apiVersion": "extensions/v1beta1",
        "metadata": {"name": "web"},
        "spec": {"replicas": 2, "selector": {"app": "web"}},
    }))
    out = json.loads(k.convert(str(src), "extensions/v1beta2"))
    assert out["apiVersion"] == "extensions/v1beta2"
    assert out["spec"]["selector"] == {"matchLabels": {"app": "web"}}
    assert out["spec"]["replicas"] == 2


def test_kubectl_set_image_and_resources(kubectl):
    k, client = kubectl
    k.run("web", image="app:v1", replicas=2)
    out = main(
        ["set", "image", "rc/web", "web=app:v2"], client=client)
    assert "image updated" in out
    rc = client.resource("replicationcontrollers", "default").get("web")
    assert rc.spec.template.spec.containers[0].image == "app:v2"
    out = main(
        ["set", "resources", "rc/web", "--requests", "cpu=250m,memory=1Gi"],
        client=client)
    assert "updated" in out
    rc = client.resource("replicationcontrollers", "default").get("web")
    assert rc.spec.template.spec.containers[0].requests == {
        "cpu": "250m", "memory": "1Gi"}
    # no matching container is an error, not a silent no-op
    with pytest.raises(ValueError):
        Kubectl(client).set_image("rc/web", ["ghost=x:1"])


def test_kubectl_typed_create_generators(kubectl, tmp_path):
    k, client = kubectl
    out = main(["create", "namespace", "staging"], client=client)
    assert out == "namespace/staging created"
    assert client.resource("namespaces").get("staging")

    out = main(["create", "serviceaccount", "robot"],
                       client=client)
    assert "created" in out

    f = tmp_path / "blob.txt"
    f.write_text("file-value")
    out = main(
        ["create", "secret", "generic", "creds",
         "--from-literal", "user=admin", "--from-file", f"blob={f}"],
        client=client)
    assert "secret/creds created" in out
    import base64
    sec = client.resource("secrets", "default").get("creds")
    assert base64.b64decode(sec.data["user"]).decode() == "admin"
    assert base64.b64decode(sec.data["blob"]).decode() == "file-value"

    out = main(
        ["create", "configmap", "conf", "--from-literal", "mode=fast"],
        client=client)
    assert "configmap/conf created" in out
    cm = client.resource("configmaps", "default").get("conf")
    assert cm.data == {"mode": "fast"}

    out = main(
        ["create", "service", "clusterip", "api", "--tcp", "80:8080"],
        client=client)
    assert "service/api created" in out
    svc = client.resource("services", "default").get("api")
    assert svc.spec.ports[0].port == 80
    assert svc.spec.ports[0].target_port == 8080


def test_kubectl_completion():
    out = main(["completion", "bash"], client=object())
    assert "complete -F" in out and "get" in out and "drain" in out
