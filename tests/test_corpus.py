"""Conformance against the reference's own test tables (ported corpus).

The JSON fixtures under tests/corpus/ are mechanical transcriptions of the
scenario tables in the reference's unit tests
(plugin/pkg/scheduler/algorithm/predicates/predicates_test.go,
priorities/*_test.go, generic_scheduler_test.go) — see
tests/corpus/builders/. Two independent checks run per suite:

1. oracle == Kubernetes: the host oracle predicate/priority evaluated on
   the exact scenario must reproduce the Go table's expected fit/score and
   failure reason.
2. tensor == oracle: the device path (BatchScheduler.debug_evaluate with a
   config isolating the suite's predicate/priority) must agree on the same
   scenario. Where the suite's predicate is only expressible inside
   GeneralPredicates on the device, unrelated resource limits are padded so
   the other components of GeneralPredicates pass trivially.
"""

import json
import os

import pytest

from kubernetes_tpu.api.types import (
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    Service,
)
from kubernetes_tpu.models.batch import (
    BatchScheduler,
    CHECK_NODE_MEMORY_PRESSURE,
    EQUAL,
    GENERAL_PREDICATES,
    MATCH_INTER_POD_AFFINITY,
    MAX_EBS_VOLUME_COUNT,
    NODE_LABEL_PREDICATE,
    NO_DISK_CONFLICT,
    POD_TOLERATES_NODE_TAINTS,
    SERVICE_AFFINITY,
    SchedulerConfig,
)
from kubernetes_tpu.oracle import ClusterState
from kubernetes_tpu.oracle import predicates as opreds
from kubernetes_tpu.runtime.scheme import scheme
from kubernetes_tpu.snapshot.encode import SnapshotEncoder

CORPUS = os.path.join(os.path.dirname(__file__), "corpus")

GENEROUS = {"cpu": "1000", "memory": 10**15, "pods": 1000,
            "alpha.kubernetes.io/nvidia-gpu": 1000}


def load(name):
    with open(os.path.join(CORPUS, name + ".json")) as f:
        return json.load(f)


def dec_pod(d):
    return scheme.decode(d, Pod)


def dec_node(d):
    return scheme.decode(d, Node)


def reason_str(reason):
    """Fixture reason → the oracle's reason string (error.go semantics)."""
    if reason is None:
        return None
    if reason["kind"] == "insufficient":
        return opreds.insufficient_resource_error(
            reason["resource"], reason["requested"], reason["used"],
            reason["capacity"])
    return reason["name"]


def single_node_state(case, patch_resources=False):
    """Build ClusterState for a single-node predicate case: the node plus
    its 'existing' pods (NewNodeInfo(pods...) in the Go tables)."""
    node = dec_node(case["node"])
    if not node.metadata.name:
        node.metadata.name = "node-unnamed"  # keyable; semantics unchanged
    if patch_resources and "pods" not in node.status.allocatable:
        node.status.allocatable = dict(GENEROUS)
    state = ClusterState.build([node])
    info = state.node_infos[node.metadata.name]
    for pd in case.get("existing", []):
        ep = dec_pod(pd)
        ep.spec.node_name = node.metadata.name
        info.add_pod(ep)
    return state, node, info


def tensor_fits(state, pod, config):
    """Device fit vector for one pending pod: {node_name: bool}."""
    snap, batch = SnapshotEncoder(state, [pod], config=config).encode()
    fit, _ = BatchScheduler(config).debug_evaluate(snap, batch)
    return dict(zip(snap.node_names, fit[0].tolist()))


def check_single_node_suite(fixture, oracle_fn, config_builder,
                            patch_resources=False, state_builder=None):
    doc = load(fixture)
    for case in doc["cases"]:
        if state_builder is not None:
            state, node, info = state_builder(case)
        else:
            state, node, info = single_node_state(case, patch_resources)
        pod = dec_pod(case["pod"])
        fit, reason = oracle_fn(case)(pod, info, state)
        assert fit == case["fits"], f"oracle fit: {case['test']}"
        if not case["fits"] and case["reason"] is not None:
            assert reason == reason_str(case["reason"]), \
                f"oracle reason: {case['test']}"
        # device agreement on the same scenario
        config = config_builder(case)
        fits = tensor_fits(state, pod, config)
        assert fits[node.metadata.name] == case["fits"], \
            f"tensor fit: {case['test']}"


def test_pod_fits_resources_table():
    check_single_node_suite(
        "pod_fits_resources",
        lambda case: opreds.pod_fits_resources,
        lambda case: SchedulerConfig(predicates=(GENERAL_PREDICATES,),
                                     priorities=((EQUAL, 1),)),
    )


def test_pod_fits_host_table():
    check_single_node_suite(
        "pod_fits_host",
        lambda case: opreds.pod_fits_host,
        lambda case: SchedulerConfig(predicates=(GENERAL_PREDICATES,),
                                     priorities=((EQUAL, 1),)),
        patch_resources=True,
    )


def test_pod_fits_host_ports_table():
    check_single_node_suite(
        "pod_fits_host_ports",
        lambda case: opreds.pod_fits_host_ports,
        lambda case: SchedulerConfig(predicates=(GENERAL_PREDICATES,),
                                     priorities=((EQUAL, 1),)),
        patch_resources=True,
    )


def test_no_disk_conflict_table():
    check_single_node_suite(
        "no_disk_conflict",
        lambda case: opreds.no_disk_conflict,
        lambda case: SchedulerConfig(predicates=(NO_DISK_CONFLICT,),
                                     priorities=((EQUAL, 1),)),
    )


def test_pod_fits_selector_table():
    check_single_node_suite(
        "pod_fits_selector",
        lambda case: opreds.pod_selector_matches,
        lambda case: SchedulerConfig(predicates=(GENERAL_PREDICATES,),
                                     priorities=((EQUAL, 1),)),
        patch_resources=True,
    )


def test_node_label_presence_table():
    check_single_node_suite(
        "node_label_presence",
        lambda case: opreds.node_label_predicate(case["labels"],
                                                 case["presence"]),
        lambda case: SchedulerConfig(
            predicates=((NODE_LABEL_PREDICATE, tuple(case["labels"]),
                         case["presence"]),),
            priorities=((EQUAL, 1),)),
    )


def test_pod_tolerates_taints_table():
    check_single_node_suite(
        "pod_tolerates_taints",
        lambda case: opreds.pod_tolerates_node_taints,
        lambda case: SchedulerConfig(predicates=(POD_TOLERATES_NODE_TAINTS,),
                                     priorities=((EQUAL, 1),)),
    )


def test_memory_pressure_table():
    check_single_node_suite(
        "memory_pressure",
        lambda case: opreds.check_node_memory_pressure,
        lambda case: SchedulerConfig(predicates=(CHECK_NODE_MEMORY_PRESSURE,),
                                     priorities=((EQUAL, 1),)),
    )


def test_general_predicates_table():
    check_single_node_suite(
        "general_predicates",
        lambda case: opreds.general_predicates,
        lambda case: SchedulerConfig(predicates=(GENERAL_PREDICATES,),
                                     priorities=((EQUAL, 1),)),
    )


def test_max_pd_volume_count_table():
    def state_builder(case):
        state, node, info = single_node_state(case)
        for pd in case["pvs"]:
            pv = scheme.decode(pd, PersistentVolume)
            state.pvs[pv.metadata.name] = pv
        for pd in case["pvcs"]:
            pvc = scheme.decode(pd, PersistentVolumeClaim)
            state.pvcs[(pvc.metadata.namespace, pvc.metadata.name)] = pvc
        return state, node, info

    check_single_node_suite(
        "max_pd_volume_count",
        lambda case: opreds.max_pd_volume_count(case["filter"],
                                                case["max_vols"]),
        lambda case: SchedulerConfig(predicates=(MAX_EBS_VOLUME_COUNT,),
                                     priorities=((EQUAL, 1),),
                                     max_ebs_volumes=case["max_vols"]),
        state_builder=state_builder,
    )


def test_service_affinity_table():
    doc = load("service_affinity")
    for case in doc["cases"]:
        nodes = [dec_node(d) for d in case["nodes"]]
        services = [scheme.decode(d, Service) for d in case["services"]]
        pods = [dec_pod(d) for d in case["pods"]]
        state = ClusterState.build(nodes, assigned_pods=pods,
                                   services=services)
        pod = dec_pod(case["pod"])
        pred = opreds.service_affinity_predicate(case["labels"])
        info = state.node_infos[case["node"]]
        fit, reason = pred(pod, info, state)
        assert fit == case["fits"], f"oracle fit: {case['test']}"
        if not fit:
            assert reason == reason_str(case["reason"]), \
                f"oracle reason: {case['test']}"
        config = SchedulerConfig(
            predicates=((SERVICE_AFFINITY, tuple(case["labels"])),),
            priorities=((EQUAL, 1),))
        fits = tensor_fits(state, pod, config)
        assert fits[case["node"]] == case["fits"], f"tensor: {case['test']}"


@pytest.mark.parametrize("fixture", ["interpod_affinity",
                                     "interpod_affinity_multi"])
def test_interpod_affinity_tables(fixture):
    doc = load(fixture)
    for case in doc["cases"]:
        nodes = [dec_node(d) for d in case["nodes"]]
        known = {n.metadata.name for n in nodes}
        pods = [dec_pod(d) for d in case["pods"]]
        state = ClusterState.build(nodes)
        for ep in pods:
            # pods on nodes absent from the scenario's node list cannot
            # contribute topology matches (their node resolves to nothing)
            state.assign(ep)
        pod = dec_pod(case["pod"])
        also_selector = case.get("also_node_selector", False)
        for name, exp in case["expect"].items():
            info = state.node_infos[name]
            fit, reason = opreds.inter_pod_affinity_matches(pod, info, state)
            if also_selector:
                # predicates_test.go:2341-2353 ANDs PodSelectorMatches when
                # the pod carries a node affinity annotation
                fit2, _ = opreds.pod_selector_matches(pod, info, state)
                fit = fit and fit2
            assert fit == exp["fits"], f"oracle {name}: {case['test']}"
            if not exp["fits"] and exp["reason"] is not None and not also_selector:
                assert reason == reason_str(exp["reason"]), \
                    f"oracle reason {name}: {case['test']}"
        # device agreement (drop pods on unknown nodes for the encoder)
        tensor_state = ClusterState.build(nodes)
        for ep in pods:
            if ep.spec.node_name in known:
                tensor_state.assign(ep)
        preds = (GENERAL_PREDICATES, MATCH_INTER_POD_AFFINITY) if also_selector \
            else (MATCH_INTER_POD_AFFINITY,)
        for n in nodes:
            if "pods" not in n.status.allocatable:
                n.status.allocatable = dict(GENEROUS)
        config = SchedulerConfig(predicates=preds, priorities=((EQUAL, 1),))
        fits = tensor_fits(tensor_state, pod, config)
        for name, exp in case["expect"].items():
            assert fits[name] == exp["fits"], f"tensor {name}: {case['test']}"


# ===========================================================================
# Priority tables (priorities_test.go, selector_spreading_test.go,
# node_affinity_test.go, taint_toleration_test.go, interpod_affinity_test.go)
# ===========================================================================

from kubernetes_tpu.api.types import ReplicaSet, ReplicationController  # noqa: E402
from kubernetes_tpu.models.batch import (  # noqa: E402
    BALANCED_ALLOCATION,
    IMAGE_LOCALITY,
    INTER_POD_AFFINITY,
    LEAST_REQUESTED,
    NODE_AFFINITY,
    NODE_LABEL_PRIORITY,
    SELECTOR_SPREAD,
    SERVICE_ANTI_AFFINITY,
    TAINT_TOLERATION,
)
from kubernetes_tpu.oracle import priorities as oprios  # noqa: E402


def priority_state(case):
    nodes = [dec_node(d) for d in case["nodes"]]
    pods = [dec_pod(d) for d in case["pods"]]
    services = [scheme.decode(d, Service) for d in case.get("services", [])]
    rcs = [scheme.decode(d, ReplicationController) for d in case.get("rcs", [])]
    rss = [scheme.decode(d, ReplicaSet) for d in case.get("rss", [])]
    state = ClusterState.build(nodes, assigned_pods=pods, services=services,
                               controllers=rcs, replica_sets=rss)
    return state, dec_pod(case["pod"])


def tensor_scores(state, pod, priorities, hard_weight=1):
    """Device per-node score vector for one pod (no predicates)."""
    config = SchedulerConfig(predicates=(), priorities=tuple(priorities),
                             hard_pod_affinity_weight=hard_weight)
    snap, batch = SnapshotEncoder(state, [pod], config=config).encode()
    _, score = BatchScheduler(config).debug_evaluate(snap, batch)
    return dict(zip(snap.node_names, score[0].tolist()))


def check_priority_suite(fixture, oracle_fn, tensor_priority):
    doc = load(fixture)
    for case in doc["cases"]:
        state, pod = priority_state(case)
        got = oracle_fn(case)(pod, state)
        assert got == case["expected"], f"oracle: {case['test']}: {got}"
        scores = tensor_scores(state, pod, [(tensor_priority(case), 1)])
        assert scores == case["expected"], f"tensor: {case['test']}: {scores}"


def test_least_requested_table():
    check_priority_suite(
        "least_requested",
        lambda case: oprios.least_requested_priority,
        lambda case: LEAST_REQUESTED,
    )


def test_balanced_allocation_table():
    check_priority_suite(
        "balanced_allocation",
        lambda case: oprios.balanced_resource_allocation,
        lambda case: BALANCED_ALLOCATION,
    )


def test_node_label_priority_table():
    check_priority_suite(
        "node_label_priority",
        lambda case: oprios.node_label_priority(case["label"], case["presence"]),
        lambda case: (NODE_LABEL_PRIORITY, case["label"], case["presence"]),
    )


def test_image_locality_table():
    check_priority_suite(
        "image_locality",
        lambda case: oprios.image_locality_priority,
        lambda case: IMAGE_LOCALITY,
    )


@pytest.mark.parametrize("fixture", ["selector_spread", "zone_selector_spread"])
def test_selector_spread_tables(fixture):
    check_priority_suite(
        fixture,
        lambda case: oprios.selector_spread_priority,
        lambda case: SELECTOR_SPREAD,
    )


def test_zone_spread_table():
    check_priority_suite(
        "zone_spread",
        lambda case: oprios.service_anti_affinity_priority(case["label"]),
        lambda case: (SERVICE_ANTI_AFFINITY, case["label"]),
    )


def test_node_affinity_priority_table():
    check_priority_suite(
        "node_affinity_priority",
        lambda case: oprios.node_affinity_priority,
        lambda case: NODE_AFFINITY,
    )


def test_taint_toleration_priority_table():
    check_priority_suite(
        "taint_toleration_priority",
        lambda case: oprios.taint_toleration_priority,
        lambda case: TAINT_TOLERATION,
    )


@pytest.mark.parametrize("fixture", ["interpod_priority",
                                     "hard_pod_affinity_weight",
                                     "soft_anti_affinity_failure_domains"])
def test_interpod_priority_tables(fixture):
    doc = load(fixture)
    for case in doc["cases"]:
        state, pod = priority_state(case)
        weight = case.get("hard_pod_affinity_weight", 1)
        fd = None
        if case.get("failure_domains") == "none":
            fd = ()
        got = oprios.inter_pod_affinity_priority(
            pod, state, hard_pod_affinity_weight=weight, failure_domains=fd)
        assert got == case["expected"], f"oracle: {case['test']}: {got}"
        if case.get("oracle_only"):
            continue
        scores = tensor_scores(state, pod, [(INTER_POD_AFFINITY, 1)],
                               hard_weight=weight)
        assert scores == case["expected"], f"tensor: {case['test']}: {scores}"


def test_zero_request_table():
    """priorities_test.go:53 TestZeroRequest — the default-provider triple
    (LeastRequested + Balanced + SelectorSpread) must blend nonzero-request
    defaults so zero-request pods score like default-request pods."""
    doc = load("zero_request")
    triple = [(LEAST_REQUESTED, 1), (BALANCED_ALLOCATION, 1),
              (SELECTOR_SPREAD, 1)]
    for case in doc["cases"]:
        state, pod = priority_state(case)
        totals = {}
        for fn in (oprios.least_requested_priority,
                   oprios.balanced_resource_allocation,
                   oprios.selector_spread_priority):
            for host, score in fn(pod, state).items():
                totals[host] = totals.get(host, 0) + score
        scores = tensor_scores(state, pod, triple)
        for host in totals:
            if "expect_all" in case:
                assert totals[host] == case["expect_all"], \
                    f"oracle: {case['test']}: {totals}"
                assert scores[host] == case["expect_all"], \
                    f"tensor: {case['test']}: {scores}"
            else:
                assert totals[host] != case["expect_all_not"], \
                    f"oracle: {case['test']}: {totals}"
                assert scores[host] != case["expect_all_not"], \
                    f"tensor: {case['test']}: {scores}"
        assert totals == scores, f"tensor!=oracle: {case['test']}"


# ===========================================================================
# generic_scheduler_test.go tables (selectHost + Schedule + findNodesThatFit)
# ===========================================================================

from kubernetes_tpu.oracle.scheduler import (  # noqa: E402
    FitError,
    GenericScheduler,
    PriorityConfig,
    select_host,
)


def _fake_predicates(names):
    """generic_scheduler_test.go:37-61 fake predicates."""
    impls = {
        "false": lambda pod, info, state: (False, "FakePredicateError"),
        "true": lambda pod, info, state: (True, None),
        "matches": lambda pod, info, state: (
            (True, None) if info.node is not None
            and pod.metadata.name == info.node.metadata.name
            else (False, "FakePredicateError")),
        "nopods": lambda pod, info, state: (
            (True, None) if len(info.pods) == 0
            else (False, "FakePredicateError")),
    }
    return [(n, impls[n]) for n in names]


def _fake_priorities(entries):
    """generic_scheduler_test.go:63-104 numeric/reverseNumeric + Equal."""
    def numeric(pod, state):
        return {name: int(name) for name in state.node_infos}

    def reverse_numeric(pod, state):
        scores = numeric(pod, state)
        hi, lo = max(scores.values()), min(scores.values())
        return {name: int(hi + lo - s) for name, s in scores.items()}

    from kubernetes_tpu.oracle import priorities as _op
    impls = {"equal": _op.equal_priority, "numeric": numeric,
             "reverseNumeric": reverse_numeric}
    return [PriorityConfig(impls[n], w, n) for n, w in entries]


def test_select_host_table():
    doc = load("select_host")
    for case in doc["cases"]:
        plist = [(h, s) for h, s in case["list"]]
        if case["expects_err"]:
            with pytest.raises(ValueError):
                select_host(plist, 0)
            continue
        # "increase the randomness" loop: every round-robin offset must
        # stay inside the max-score tie set
        for i in range(10):
            assert select_host(plist, i) in set(case["possible"]), \
                f"offset {i}: {case}"


def test_generic_scheduler_table():
    doc = load("generic_scheduler")
    for case in doc["cases"]:
        nodes = [Node(metadata=type(Node().metadata)(name=n))
                 for n in case["nodes"]]
        state = ClusterState.build(nodes,
                                   assigned_pods=[dec_pod(d) for d in case["pods"]])
        sched = GenericScheduler(
            predicates=_fake_predicates(case["predicates"]),
            priorities=_fake_priorities(case["priorities"]))
        pod = dec_pod(case["pod"])
        if case["expects_err"]:
            with pytest.raises(FitError):
                sched.schedule(pod, state)
        else:
            assert sched.schedule(pod, state) in set(case["expected"]), \
                case["name"]
    for case in doc["find_fit"]:
        nodes = [Node(metadata=type(Node().metadata)(name=n))
                 for n in case["nodes"]]
        state = ClusterState.build(nodes,
                                   assigned_pods=[dec_pod(d) for d in case["pods"]])
        sched = GenericScheduler(
            predicates=_fake_predicates(case["predicates"]),
            priorities=_fake_priorities([["numeric", 1]]))
        pod = dec_pod(case["pod"])
        _, failed = sched.find_nodes_that_fit(pod, state)
        assert failed == case["expect_failed"], case["name"]
